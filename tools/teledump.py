#!/usr/bin/env python
"""teledump — pull a telemetry snapshot from a live server over the wire.

The `MSG_STATS` verb ships the serving backend's counter snapshot with
the process-wide telemetry registry riding under the `telemetry` key
(`runtime/net.py`); this CLI is the operator's one-shot pull: no second
port, no agent, just the op channel a monitoring client already speaks.

    python tools/teledump.py HOST PORT                 # JSON to stdout
    python tools/teledump.py HOST PORT --format prom   # Prometheus text
    python tools/teledump.py HOST PORT --out snap.json # for check_teledump
    python tools/teledump.py --local                   # this process's registry

Schema: `tools/check_teledump.py` validates the pulled document (the
`pmdfc-telemetry-v2` contract — windowed series, workload sketches,
miss-cause sums — the CI telemetry_smoke step diffs against; v1
documents from older servers still parse).
"""

from __future__ import annotations

import argparse
import json
import sys


def pull(host: str, port: int, page_words: int,
         timeout_s: float = 10.0) -> dict:
    from pmdfc_tpu.runtime.net import TcpBackend

    with TcpBackend(host, port, page_words=page_words,
                    keepalive_s=None, op_timeout_s=timeout_s) as be:
        return be.server_stats()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("host", nargs="?", default="127.0.0.1")
    p.add_argument("port", nargs="?", type=int)
    p.add_argument("--page-words", type=int, default=1024,
                   help="must match the server (HOLA negotiation)")
    p.add_argument("--format", choices=("json", "prom"), default="json")
    p.add_argument("--out", default=None, help="write the document here "
                   "instead of stdout (JSON regardless of --format)")
    p.add_argument("--local", action="store_true",
                   help="dump THIS process's registry (no wire pull)")
    p.add_argument("--timeout-s", type=float, default=10.0)
    args = p.parse_args(argv)

    from pmdfc_tpu.runtime import telemetry

    if args.local:
        doc = {"telemetry": telemetry.snapshot()}
    else:
        if args.port is None:
            p.error("PORT is required unless --local")
        doc = pull(args.host, args.port, args.page_words, args.timeout_s)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"[teledump] wrote {args.out}", file=sys.stderr)
        return 0
    if args.format == "prom":
        snap = doc.get("telemetry")
        if snap is None:
            print("[teledump] server reported no telemetry section "
                  "(PMDFC_TELEMETRY=off on the server?)", file=sys.stderr)
            return 2
        sys.stdout.write(telemetry.render_snapshot(snap))
        return 0
    json.dump(doc, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    import os

    # runnable as `python tools/teledump.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
