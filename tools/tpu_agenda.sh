#!/bin/bash
# On-chip measurement agenda — fired by tools/tpu_poll.sh whenever the axon
# tunnel is up and work remains. Ordered by VERDICT priority so a tunnel
# that dies mid-run still leaves the most important evidence behind.
#
# 10-MINUTE WORST-CASE WINDOW BUDGET (VERDICT r5 §1: a short flap must
# still decide the round). If the tunnel holds for only ~600 s, the steps
# below run in this order and roughly this cost; everything after the
# budget line is bonus — the resumable markers carry it to the next
# window:
#   1. family3_path      ~150 s  (the decisive after-row: keep/revert v2)
#   2. family3_cuckoo    ~150 s  (compacted-kick after-row)
#   3. family3_level     ~150 s  (third rewritten family)
#   4. linear8m_control  ~120 s  (the "7x collapse" control point)
#   ---------------- ~570 s: budget exhausted ----------------
#   5. cert3 refresh    ~600+ s  (needs its own window)
#   6. replica_avail     ~120 s  (availability smoke: breaker/hedge/
#                                 repair machinery alive on the host)
#   7. macro sims       ~1800 s  (swap/paging/replay/soak rows)
# Steps 1-4 are >80% of the round's decision value (the three round-5
# rewrites are unverified on hardware and the control kills a misread);
# they are hoisted to the front of the body below as family3_*/
# linear8m_control, ahead of every macro sim.
#
# RESUMABLE: each step records a .tpu_agenda_step.<name>.done marker on
# success and is skipped on re-entry, so a window that dies at step 4 makes
# the next window start there, not at step 1. Every test_kv invocation
# appends its on-chip record to BENCH_HISTORY.jsonl itself; step 1
# (bench.py) additionally writes BENCH_TPU_CERT.json — the round-end
# fallback artifact. Everything logs to .tpu_agenda.log.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
LOG="$REPO/.tpu_agenda.log"
HIST="$REPO/BENCH_HISTORY.jsonl"
say() { echo "[agenda $(date -u +%T)] $*" >> "$LOG"; }

# step <name> <timeout> <cmd...>: run once, marker on rc=0. Every step
# registers itself in STEPS so the completion check below can never drift
# from the steps that actually exist (review finding: a hand-kept list
# would silently disable the poller for a forgotten new step).
STEPS=()
step() {
  local name="$1" tmo="$2"; shift 2
  STEPS+=("$name")
  local mark="$REPO/.tpu_agenda_step.$name.done"
  if [ -f "$mark" ]; then say "step $name: already done, skip"; return 0; fi
  say "step $name: start"
  timeout "$tmo" "$@" >> "$LOG" 2>&1
  local rc=$?
  say "step $name rc=$rc"
  if [ "$rc" -eq 0 ]; then touch "$mark"; fi
  return $rc
}

# cert_step <name>: run bench.py and mark done ONLY if this invocation
# wrote a device=tpu certification artifact (bench.py exits 0 even on CPU
# fallback, so rc alone can't gate; the mtime stamp rejects an inherited
# cert from an earlier run).
cert_step() {
  local name="$1"
  STEPS+=("$name")
  if [ -f "$REPO/.tpu_agenda_step.$name.done" ]; then
    say "step $name: already done, skip"; return 0
  fi
  say "step $name: bench.py"
  local stamp="$REPO/.tpu_agenda.$name.stamp"
  touch "$stamp"
  timeout 2400 python bench.py >> "$LOG" 2>&1
  local rc=$?
  say "step $name rc=$rc"
  if [ "$rc" -eq 0 ] && [ "$REPO/BENCH_TPU_CERT.json" -nt "$stamp" ] && \
     grep -q '"device": "tpu"' "$REPO/BENCH_TPU_CERT.json"; then
    touch "$REPO/.tpu_agenda_step.$name.done"
  fi
  rm -f "$stamp"
}

say "=== agenda start (resumable) ==="

# 0. THE 10-MINUTE BUDGET STEPS (see header): the three rewritten-family
# after-rows and the control point run before anything else — a window
# that dies after ~570 s has still decided the round.
# 0a. Insert-laggard re-runs AFTER the straggler-compaction rewrites
#     (VERDICT-r4 item 2): cuckoo's narrow kick loop and path's fused-row
#     v2 + staged claim rounds. Before-rows on-chip: cuckoo insert 0.635,
#     path insert 0.411 / GET 6.4 (BENCH_HISTORY 2026-07-31T04:17/04:24).
for idx in path cuckoo level; do
  step "family3_$idx" 1200 python -m pmdfc_tpu.bench.test_kv --index=$idx \
    --n=4194304 --batch=4194304 --capacity=8388608 --no-engine \
    --history="$HIST"
done

# 0b. Default-path control at the exact shape the round-4 judge read as a
#     "7x collapse" (it was the PMDFC_INSERT_PATH=row A/B arm; records now
#     stamp insert_path): linear, element path, n=8M. Expected ~6-7 Mops/s.
step linear8m_control 1200 python -m pmdfc_tpu.bench.test_kv \
  --n=8388608 --batch=4194304 --capacity=16777216 --no-engine \
  --history="$HIST"

# 0c. Cert refresh with the round-5 code (deep-client serving point rides
#     the bench.py defaults; artifact now reports the reference per-op p99
#     alongside).
cert_step cert3

# 0d0. Concurrency & JAX-discipline gate (ISSUE 6): the static pass must
#      be CLEAN (zero findings, zero stale allowlist entries) before any
#      measured run — a lock-order cycle or unguarded write invalidates
#      every number the window produces. Cheap (~seconds, pure ast).
step analyze 300 python -m tools.analyze

# 0d. Replica-group availability smoke (ISSUE 3): rolling kill/restore
#     over 3 in-process servers — proves breaker/hedge/anti-entropy
#     machinery is alive on this host (exits nonzero on any invariant
#     violation; not a perf row).
step replica_avail 900 python -m pmdfc_tpu.bench.replica_soak --smoke

# 1. North-star certification: the supervised headline bench (linear).
cert_step cert

# 2. The baseline's own algorithm on TPU: cceh.
step cceh 1200 python -m pmdfc_tpu.bench.test_kv --index=cceh \
  --n=8388608 --batch=4194304 --capacity=16777216 --no-engine \
  --history="$HIST"

# 3. Engine serving path + throughput-vs-p99 sweep (uses the fixed path).
step engine_sweep 1800 python -m pmdfc_tpu.bench.test_kv --n=4194304 \
  --batch=4194304 --capacity=8388608 --sweep --engine-secs=5 \
  --history="$HIST"

# 3b. Deep-client engine point: the chip's ~17 ms dispatch floor needs
# outstanding work ~ flush-size deep to amortize (CPU defaults are shallow).
step engine_deep 1200 python -m pmdfc_tpu.bench.test_kv --n=4194304 \
  --batch=4194304 --capacity=8388608 --engine-secs=8 \
  --engine-threads=8 --engine-client-batch=16384 --engine-inflight=4 \
  --engine-batch=131072 --engine-timeout-us=2000 \
  --history="$HIST"

# 3c. Tier smoke + sweep: the hot/cold page-store trajectory row (ISSUE 2).
# Smoke first (fails fast if migration machinery regressed), then the
# measured sweep whose rows land in BENCH_HISTORY via --history.
step tier_smoke 600 python -m pmdfc_tpu.bench.tier_sweep --smoke
step tier_sweep 1800 python -m pmdfc_tpu.bench.tier_sweep \
  --device tpu --zipfs 0.6,0.99,1.2 --gets 65536 --capacity 65536 \
  --out "$REPO/BENCH_tier.json" --history="$HIST"

# 3d. Coalesced TCP serving tier (ISSUE 4): connections × window × verb
# grid, lockstep baseline vs cross-connection coalescer, on-host through
# the real wire. On a TPU host the fused flushes amortize the ~17 ms
# dispatch floor, so the 8-conn ratio here is the tier's headline row
# (CPU acceptance floor was ≥3x; rows stamp transport=tcp_coalesced).
step net_smoke 600 python -m pmdfc_tpu.bench.net_sweep --smoke
step net_sweep 1800 python -m pmdfc_tpu.bench.net_sweep --device tpu \
  --out "$REPO/BENCH_net.json" --history="$HIST"

# 3e. Unified telemetry (ISSUE 5): run the net-smoke serving shape with
# telemetry on vs off (paired, live kill-switch flips) and gate the
# overhead at 3%; then validate the wire-pulled teledump snapshot
# against the pmdfc-telemetry-v1 schema — the artifact a monitoring
# consumer would scrape. History rows land with telemetry=on|off lanes.
step telemetry_smoke 900 bash -c "PMDFC_TELEMETRY=on python -m \
  pmdfc_tpu.bench.telemetry_overhead --smoke \
  --teledump '$REPO/.teledump_smoke.json' --history='$HIST' \
  && python '$REPO/tools/check_teledump.py' '$REPO/.teledump_smoke.json'"

# 3e'. Workload X-ray console (ISSUE 10): teletop's hermetic self-drill —
# spin a coalesced server, drive traffic, run the exact `--once --json`
# poll path against it, and schema-check the emitted document (miss-cause
# sums, windowed rates, working-set bounds). The fleet console's wire
# contract must hold before any operator trusts it mid-incident.
step teletop_smoke 600 env PMDFC_TELEMETRY=on \
  python "$REPO/tools/teletop.py" --smoke

# 3f. Mesh-sharded serving plane (ISSUE 7): partitioned KV behind the
# coalesced NetServer at 1/2/4/8 shards vs the PMDFC_MESH=off path.
# On a TPU host the shard grid is real chips and the scaling ratios are
# the headline; on CPU the forced host devices execute sequentially and
# the honest row is ratio_plane_vs_off (read-only GET phases skip the
# per-flush table materialization). Rows stamp
# transport=tcp_coalesced_mesh.
step mesh_smoke 900 python -m pmdfc_tpu.bench.mesh_sweep --smoke
step mesh_sweep 1800 python -m pmdfc_tpu.bench.mesh_sweep \
  --device tpu --out "$REPO/BENCH_mesh.json" --history="$HIST"

# 3e2. 2-D mesh (ISSUE 13): replication fused into the serving plane as
# device-side replica collectives. The smoke prices replicated PUTs
# both ways at equal device budget (fused (kv,replica) plane vs host
# ReplicaGroup rf fan-out) and the pytest leg pins PMDFC_MESH2D=off
# conformance plus the corrupt-lane wire drill, whose MSG_STATS pull is
# schema-checked (tools/check_teledump.check with the replica block
# aboard). On THIS host the replica lanes are the real second mesh
# axis, so the full mesh_sweep --replica run is the owed on-chip curve
# over BOTH axes at once (rows stamp transport=tcp_coalesced_mesh2d).
step mesh2d_smoke 1200 bash -c "env PMDFC_TELEMETRY=on python -m \
  pmdfc_tpu.bench.mesh_sweep --smoke --replica 2 --history='$HIST' && \
  env PMDFC_TELEMETRY=on JAX_PLATFORMS=cpu python -m pytest \
  tests/test_mesh2d.py::test_mesh2d_off_kill_switch_is_conformant \
  tests/test_mesh2d.py::test_mesh2d_wire_soak_corrupt_lane_mid_flight \
  -q -p no:cacheprovider -p no:randomly"
step mesh2d_sweep 1800 python -m pmdfc_tpu.bench.mesh_sweep \
  --device tpu --replica 2,4 --out "$REPO/BENCH_mesh2d.json" \
  --history="$HIST"

# 3f2. One-sided fast path (ISSUE 11): directory-mirrored direct row
# reads vs the verb path, same live KV behind one coalesced server. The
# smoke asserts machinery + a schema-checked teledump (incl. the
# hits+stale==reads pin); the full run appends transport=tcp_fastpath /
# tcp_verb p50 lanes (unit us => lower-better) under the bench_gate.
step fastpath_smoke 600 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.fastpath_sweep --smoke
step fastpath_sweep 1800 python -m pmdfc_tpu.bench.fastpath_sweep \
  --device tpu --out "$REPO/BENCH_fastpath.json" --history="$HIST"

# 3f3. Elastic membership (ISSUE 12): scale the fleet 3->5->2 mid
# zipf-storm over real servers. The consistent-hash ring moves only the
# owed ~rf/N key ranges (counted against moved_mask, not assumed), live
# migration streams them digest-verified through the repair path, and
# the dual-read window bounds the hit-rate dip. The smoke asserts the
# invariants (zero wrong bytes, owed_frac within vnode variance of the
# consistent-hashing expectation) and schema-checks the pulled teledump
# including the migration-counter pins; rows land as a
# transport=tcp_elastic lane under the bench_gate.
step elastic_smoke 900 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.elastic_sweep --smoke --history="$HIST"

# 3f4. Closed-loop controller (ISSUE 14): hand-tuned defaults vs the
# autotune controller on the phase-shifting zipf soak (light phase ->
# shifted working set under fan-in). The smoke asserts the machinery —
# the controller decided, walked the flush dwell down inside its
# declared envelope, the live teledump passes check_teledump including
# the check_autotune pins, and the static run carries no ctl scope —
# and appends the paired transport=tcp_autotune/tcp_static lanes the
# bench_gate then watches.
step autotune_smoke 900 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.autotune_sweep --smoke --history="$HIST"

# 3f4b. Multi-tenant QoS plane (ISSUE 17): antagonist tenant vs
# compliant tenant, paired with/without the plane. The smoke asserts
# the machinery — the antagonist was edge-shed with every shed
# attributed to miss_shed (misses == sum of causes on the wire doc),
# the compliant lane shed nothing, the live teledump passes
# check_teledump including the check_qos lane pins, and the no-QoS arm
# carries no tenant scope — and appends the paired
# transport=tcp_qos/tcp_noqos lanes the bench_gate then watches.
step qos_smoke 900 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.qos_soak --smoke --history="$HIST"

# 3f5. Scan-resistant admission gate (ISSUE 15): the scan-antagonist
# scenario — a zipf tenant vs a concurrent cyclic sequential scanner
# under periodic memory-pressure pulses — run PAIRED (admit_on /
# admit_off on identical seeds). The smoke asserts the machinery (the
# gate denied scan candidates, demotion churn suppressed, the zipf
# tenant's hit-rate did not lose to admission-off, zero wrong bytes)
# and appends the paging_scanmix_hit_rate / _get_p99 /
# _pure_zipf_rate lane pairs the bench_gate then watches.
step paging_smoke 900 python -m pmdfc_tpu.bench.paging_sim \
  --job scan_mix --smoke --history="$HIST"

# 3f2. Bounded-RPO durability smoke (ISSUE 16): a real NetServer child
# is SIGKILLed between two acked puts, then warm restart (snapshot
# chain + journal-tail replay) races a cold rejoin over the identical
# seeded storm. Asserts pages-lost <= the JournalConfig RPO bound,
# zero wrong bytes through crash+recovery, miss_recovering keeping
# misses == Σ causes, and warm strictly beating cold — and appends the
# paired recovery_soak mode=warm/mode=cold lanes the bench_gate
# then watches.
step recovery_smoke 900 python -m pmdfc_tpu.bench.recovery_soak \
  --smoke --history="$HIST"

# 3f6. Blast-radius containment (ISSUE 18): poison-op storm, shard-kill
# quarantine, and deadline-shed drills over real coalesced servers. The
# smoke asserts the machinery — one poisoned op isolated in <= ceil(log2 b)
# bisection launches with every healthy sibling answered and every conn
# alive, resubmits refused at staging without re-isolation, a killed
# shard tripping to miss_quarantined (misses == Σ causes) then
# re-admitted through the half-open probe, and the deadline proof arm
# (whole pool poisoned: poison_ops == 0 means expired ops never reached
# the device) — and appends the containment_* lanes the bench_gate
# then watches.
step containment_smoke 900 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.containment_soak --smoke --history="$HIST"

# 3f3b. Tier-1 overflow (PR 16 rebudget): the tier-1 suite outgrew its
# 870 s window on the 1-cpu harness host, so the heaviest soak/chaos
# drills moved to the slow tier (per the PR 13 budget note) and run
# here instead — same tests, same assertions, different envelope.
step tier1_overflow 1200 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_elastic.py::test_elastic_chaos_scale_3_5_2_mid_soak \
  tests/test_replica.py::test_rolling_kill_restore_drill \
  tests/test_replica.py::test_hedged_get_fires_on_slow_primary \
  tests/test_xray.py::test_xray_acceptance_soak_and_teletop \
  'tests/test_mesh.py::test_reshard_restore_loses_nothing[2-3]' \
  'tests/test_mesh.py::test_reshard_restore_loses_nothing[8-4]' \
  tests/test_qos.py::test_wire_shed_drill_end_to_end \
  tests/test_qos.py::test_qos_off_is_single_tenant_fifo \
  tests/test_containment.py::test_nack_negotiation_and_kill_switch \
  tests/test_containment.py::test_poison_bisection_isolates_culprit \
  tests/test_containment.py::test_poison_fingerprint_is_verb_seeded \
  tests/test_containment.py::test_unnegotiated_peer_keeps_conn_drop_semantics \
  tests/test_containment.py::test_deadline_shed_lands_in_miss_deadline \
  tests/test_containment.py::test_deadline_zero_means_none \
  tests/test_containment.py::test_plane_shard_quarantine_and_readmission \
  tests/test_containment.py::test_plane_containment_off_is_conformant \
  tests/test_chaos.py::test_reconnect_storm_after_phase_failures_is_backoff_bounded \
  tests/test_chaos.py::test_nacked_ops_close_spans_as_failed_v2_records \
  tests/test_profiler.py::test_msg_profile_capture_cooldown_and_old_peer \
  tests/test_profiler.py::test_msg_profile_refused_without_dump_dir \
  -q -p no:cacheprovider -p no:randomly

# 3f4. Device-fused GET smoke (ISSUE 19): tiny shapes, EVERY batch
# parity-checked fused-vs-composed ON CHIP — the first place a
# Mosaic-lowered kernel can diverge from the interpret-mode trace CI
# pinned. Appends the paired kernel=pallas_fused/xla_composed lanes the
# bench_gate then watches — and, since ISSUE 20, the matching
# `device_us` lanes: the profiler's timed-fetch split of each wall row,
# gated lower-is-better by the same bench_gate.
step fused_smoke 600 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.fused_get --smoke --device tpu \
  --history="$HIST"

# 3f5. Device-time X-ray smoke (ISSUE 20): the profiler suite run on
# the chip host — timed-fetch attribution through the real launch
# seams, per-shard lanes reconciling bit-exactly with
# mesh.shard{i}_ops, MSG_PROFILE capture lifecycle + old-peer
# fallback, proftool breakdown/Perfetto schema on a real dump, and the
# PMDFC_PROF=off v2-conformance pin. Forced-CPU like tier1_overflow
# (the suite pins exact snapshots and virtual-device meshes); the chip
# evidence is the device_us lanes fused_smoke/fused_sweep append.
step prof_smoke 600 env JAX_PLATFORMS=cpu PMDFC_TELEMETRY=on \
  python -m pytest tests/test_profiler.py -q \
  -p no:cacheprovider -p no:randomly

# 3g. Bench regression gate (ISSUE 9): each fresh BENCH_HISTORY lane the
# smoke steps above just appended is compared against that lane's
# previous row with a 15% tolerance band — a silent smoke-bench
# regression fails the window HERE, before the long measured runs spend
# it. Only lanes refreshed in the last day gate (an old lane that simply
# didn't re-run is not a regression).
step bench_gate 300 python "$REPO/tools/check_bench.py" "$HIST" \
  --max-age-h 24

# 4. Insert row-scatter experiment (flip decision data).
step insert_ab 1200 python -m pmdfc_tpu.bench.insert_rowscatter \
  --device tpu --n 1048576 --capacity 2097152 --skip-check

# 4a. Device-fused GET full sweep (ISSUE 19): the serving shapes
# (batch x zipf x family) priced fused-vs-composed on chip; whether the
# whole-verb fusion beats XLA's composed chain is SETTLED HERE — the
# paired lanes are the record either way (pallas_gather's retired
# verdict bounds the pure-gather half of the claim). With the tracing
# tier on (ISSUE 20) every combo also appends the paired
# kernel=pallas_fused|xla_composed `device_us` lanes — the profiler's
# on-chip split of each wall row, so the sweep's verdict carries
# device time, not wall-only numbers.
step fused_sweep 1800 env PMDFC_TELEMETRY=on \
  python -m pmdfc_tpu.bench.fused_get \
  --device tpu --history="$HIST" \
  --out "$REPO/BENCH_fused.json"

# 4b. Row path through the FULL insert program (facade + BF + stats fused):
# if this beats step 1's insert_mops, flip the default in models/linear.py.
step insert_row_full 1200 env PMDFC_INSERT_PATH=row \
  python -m pmdfc_tpu.bench.test_kv \
  --n=8388608 --batch=4194304 --capacity=16777216 --no-engine \
  --history="$HIST"

# 5. Nine-family lean-GET sweep at one fixed shape (N=4M).
for idx in linear cceh cuckoo ccp level path extendible static hotring; do
  step "family_$idx" 900 python -m pmdfc_tpu.bench.test_kv --index=$idx \
    --n=4194304 --batch=4194304 --capacity=8388608 --no-engine \
    --history="$HIST"
done

# (the former section 8 — family3_*, linear8m_control, cert3 — moved to
# section 0 at the top: the 10-minute window budget runs them first)

# 6. Paging workloads (the juleeswap fio-4K-randread analog + fio-style).
step swap_sim 1800 python -m pmdfc_tpu.bench.swap_sim --device tpu \
  --ops 64000 --working-pages 262144 --ram-pages 32768 \
  --capacity 524288 --jobs 8 --iodepth 16 --history="$HIST"
step paging_sim 1800 python -m pmdfc_tpu.bench.paging_sim --device tpu \
  --job rand_read --file-pages 262144 --ram-pages 32768 --ops 64000 \
  --capacity 524288 --iodepth 16 --history="$HIST"

# 6c/6d. Same workloads THROUGH the native engine transport (VERDICT-r3
# item 4: the measured path must include the transport, not just the
# in-process KV). Smaller op counts: the engine path adds per-verb cost.
step swap_sim_engine 1800 python -m pmdfc_tpu.bench.swap_sim \
  --device tpu --backend engine --ops 48000 --working-pages 262144 \
  --ram-pages 32768 --capacity 524288 --jobs 8 --iodepth 16 \
  --history="$HIST"
step paging_sim_engine 1800 python -m pmdfc_tpu.bench.paging_sim \
  --device tpu --backend engine --job rand_read --file-pages 262144 \
  --ram-pages 32768 --ops 48000 --capacity 524288 --iodepth 16 \
  --history="$HIST"

# 7. Round-4 follow-ups (added after the first window of 2026-07-31):
# 7a. Cert refresh: bench.py again with the deep-client engine default
#     and the shrunk insert sort — same artifact discipline as step 1.
#     Runs BEFORE the lower-priority follow-ups: it refreshes the
#     round-end artifact.
cert_step cert2

# 7b. Insert phase profile on-chip — which piece owns the ~145 ns/key
#     (bench/insert_profile.py; the 3-operand plan sort landed after the
#     first window's bench runs).
step insert_profile 1200 python -m pmdfc_tpu.bench.insert_profile \
  --n 4194304 --capacity 8388608 --history="$HIST"

# 7c. Path family re-run: the roofline stamp (2*LEVELS cells vs a 1-slot
#     wall) replaced the null frac after family_path already ran.
step path_roofline 900 python -m pmdfc_tpu.bench.test_kv --index=path \
  --n=4194304 --batch=4194304 --capacity=8388608 --no-engine \
  --history="$HIST"

# 7d. Family re-runs after the eviction-skip insert fixes (CPU gains:
#     hotring +31%, level +23%, cuckoo +25%, cceh +76% — extendible
#     shares cceh's module — ccp +13%; the family_* rows in
#     BENCH_HISTORY predate them — these record the improved on-chip
#     insert rates).
for idx in hotring level cuckoo cceh extendible ccp; do
  step "family2_$idx" 900 python -m pmdfc_tpu.bench.test_kv --index=$idx \
    --n=4194304 --batch=4194304 --capacity=8388608 --no-engine \
    --history="$HIST"
done

# 7e. Trace replay on-chip (replay_KV analog): the bundled fileserver
#     trace plus a 1M-event synthetic mix, recorded to history.
step replay_trace 1500 python -m pmdfc_tpu.bench.replay \
  --trace tests/data/fileserver.trace --capacity 65536 --batch 4096 \
  --history="$HIST"
step replay_synth 1800 python -m pmdfc_tpu.bench.replay \
  --synthetic 1000000 --capacity 4194304 --batch 65536 \
  --history="$HIST"

# 7f. Serving-path soak on-chip: 3 minutes of mixed put/delete/get with
#     content verification (bench/soak.py exits 3 off-chip, 2 on any
#     data-loss/protocol violation, so the marker stays honest).
step soak 1200 python -m pmdfc_tpu.bench.soak --minutes 3 --threads 6 \
  --verb 512 --history="$HIST"

# 7g. Sanitizer-enabled soak variants (ISSUE 6): the chaos/net/replica
#     serving shapes re-run with every lock instrumented
#     (PMDFC_SAN=strict — a single order inversion or flush-loop long
#     hold exits 70 and fails the step). Shorter/smaller than the
#     measured runs: these are correctness drills, not perf rows.
step net_smoke_san 900 env PMDFC_SAN=strict \
  python -m pmdfc_tpu.bench.net_sweep --smoke
step replica_avail_san 900 env PMDFC_SAN=strict \
  python -m pmdfc_tpu.bench.replica_soak --smoke
step soak_san 900 env PMDFC_SAN=strict \
  python -m pmdfc_tpu.bench.soak --minutes 1 --threads 4 --verb 256
step elastic_soak_san 900 env PMDFC_SAN=strict \
  python -m pmdfc_tpu.bench.elastic_sweep --smoke

# all steps done? (STEPS self-registers at each step() call, so this list
# cannot drift from the agenda body) — write the terminal marker so the
# poller stands down
missing=0
for m in "${STEPS[@]}"; do
  [ -f "$REPO/.tpu_agenda_step.$m.done" ] || missing=$((missing + 1))
done
if [ "$missing" -eq 0 ]; then
  touch "$REPO/.tpu_agenda.all.done"
  say "=== agenda COMPLETE (all steps done) ==="
else
  say "=== agenda pass ended, $missing steps remain (will resume) ==="
fi
