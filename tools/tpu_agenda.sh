#!/bin/bash
# On-chip measurement agenda — run automatically the moment the axon tunnel
# comes back. Ordered by VERDICT-r2 priority so a tunnel that dies mid-run
# still leaves the most important evidence behind. Every test_kv invocation
# appends its on-chip record to BENCH_HISTORY.jsonl itself; everything logs
# to .tpu_agenda.log.
set -u
cd /root/repo
LOG=/root/repo/.tpu_agenda.log
HIST=/root/repo/BENCH_HISTORY.jsonl
say() { echo "[agenda $(date -u +%T)] $*" >> "$LOG"; }

say "=== agenda start ==="

# 1. North-star certification: the supervised headline bench (linear).
say "step 1: bench.py (north star)"
timeout 1800 python bench.py >> "$LOG" 2>&1
say "step 1 rc=$?"

# 2. The baseline's own algorithm on TPU: cceh.
say "step 2: cceh run"
timeout 1200 python -m pmdfc_tpu.bench.test_kv --index=cceh \
  --n=8388608 --batch=4194304 --capacity=16777216 --no-engine \
  --history="$HIST" >> "$LOG" 2>&1
say "step 2 rc=$?"

# 3. Engine serving path + throughput-vs-p99 sweep (uses the fixed path).
say "step 3: engine sweep"
timeout 1800 python -m pmdfc_tpu.bench.test_kv --n=4194304 \
  --batch=4194304 --capacity=8388608 --sweep --engine-secs=5 \
  --history="$HIST" >> "$LOG" 2>&1
say "step 3 rc=$?"

# 3b. Deep-client engine point: the chip's ~17 ms dispatch floor needs
# outstanding work ~ flush-size deep to amortize (CPU defaults are shallow).
say "step 3b: engine deep clients"
timeout 1200 python -m pmdfc_tpu.bench.test_kv --n=4194304 \
  --batch=4194304 --capacity=8388608 --engine-secs=8 \
  --engine-threads=8 --engine-client-batch=16384 --engine-inflight=4 \
  --engine-batch=131072 --engine-timeout-us=2000 \
  --history="$HIST" >> "$LOG" 2>&1
say "step 3b rc=$?"

# 4. Insert row-scatter experiment (flip decision data).
say "step 4: insert_rowscatter"
timeout 1200 python -m pmdfc_tpu.bench.insert_rowscatter --device tpu \
  --n 1048576 --capacity 2097152 --skip-check >> "$LOG" 2>&1
say "step 4 rc=$?"

# 4b. Row path through the FULL insert program (facade + BF + stats fused):
# if this beats step 1's insert_mops, flip the default in models/linear.py.
say "step 4b: full bench with PMDFC_INSERT_PATH=row"
timeout 1200 env PMDFC_INSERT_PATH=row python -m pmdfc_tpu.bench.test_kv \
  --n=8388608 --batch=4194304 --capacity=16777216 --no-engine \
  --history="$HIST" >> "$LOG" 2>&1
say "step 4b rc=$?"

# 5. Nine-family lean-GET sweep at one fixed shape (N=4M).
for idx in linear cceh cuckoo ccp level path extendible static hotring; do
  say "step 5: family $idx"
  timeout 900 python -m pmdfc_tpu.bench.test_kv --index=$idx \
    --n=4194304 --batch=4194304 --capacity=8388608 --no-engine \
    --history="$HIST" >> "$LOG" 2>&1
  say "step 5 $idx rc=$?"
done

# 6. Paging workloads (the juleeswap fio-4K-randread analog + fio-style).
say "step 6: swap_sim"
timeout 1800 python -m pmdfc_tpu.bench.swap_sim --device tpu \
  --ops 400000 --working-pages 262144 --ram-pages 32768 \
  --capacity 524288 --jobs 8 --iodepth 16 >> "$LOG" 2>&1
say "step 6 rc=$?"
say "step 6b: paging_sim rand_read"
timeout 1800 python -m pmdfc_tpu.bench.paging_sim --device tpu \
  --job rand_read --file-pages 262144 --ram-pages 32768 --ops 400000 \
  --capacity 524288 --iodepth 16 >> "$LOG" 2>&1
say "step 6b rc=$?"

say "=== agenda done ==="
