#!/usr/bin/env python
"""check_bench — bench-lane regression gate over BENCH_HISTORY.jsonl.

Every bench main appends evidence rows through the one shared logger
(`bench/common.append_history`). This tool groups those rows into LANES
— all identity fields equal: metric, transport, index, verb/shape
knobs, device, telemetry on/off, ... everything except the measured
value and the timestamp — and compares each lane's FRESHEST row against
the previous row of the same lane with a tolerance band. A throughput
lane (Mpages/s, Mops/s, ...) regresses when the fresh value drops below
`prev * (1 - tolerance)`; a latency lane (us/ms/s units) regresses when
it rises above `prev * (1 + tolerance)`. Exit 1 on any regression, so
the agenda can gate on it right after the smoke benches (step
`bench_gate`).

    python tools/check_bench.py BENCH_HISTORY.jsonl [--tolerance 0.15]
        [--metric telemetry_overhead] [--max-age-h 48]

`--max-age-h` only checks lanes whose freshest row is recent (default:
all) — an old lane that simply wasn't re-run is not a regression.

Importable: `lane_key(row)`, `check_history(rows, tolerance) ->
regressions` — tests/test_tracing.py pins the comparison semantics.
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys

# explicitly measured outputs, never lane identity
VALUE_KEYS = frozenset({"ts", "value", "wall_s", "overhead_ratio"})
# int-typed fields that are nevertheless RESULTS (the int/float type
# split below is the main classifier; these are its exceptions).
# `hits` is the fused_get sweep's workload outcome — deterministic per
# seed today, but an eviction-policy change must not silently fork the
# lane. `kernel` (pallas_fused | xla_composed) and `tile` ARE identity:
# the paired fused-vs-composed rows may never collapse into one lane,
# or the gate would read the slower kernel as a regression of the
# faster one.
MEASURED_INT_KEYS = frozenset({"failed_search", "gather_bytes_per_s",
                               "spans_recorded", "hits"})
# float-typed fields that are KNOBS (zipf exponents and the like)
FLOAT_KNOB_KEYS = frozenset({"zipf", "theta", "alpha", "hedge_ms"})
# units where smaller is better; anything else is treated as throughput
# (`device_us` is the profiler's blocked-fetch device-time lane — wall
# microseconds on the chip, so lower is better like any latency)
LATENCY_UNITS = frozenset({"ns", "us", "ms", "s", "device_us"})


def lane_key(row: dict) -> str:
    """Lane identity = the row's qualitative stamps and shape knobs.

    History rows interleave knobs with SECONDARY measured outputs
    (best_wall_s, link_h2d_mbs, p99_batch_ms, ...) that differ every
    run — treating those as identity would make every row a singleton
    lane and the gate vacuous. The type split matches how the benches
    actually write rows: strings/bools/ints are stamps and knobs
    (minus the known measured-int exceptions), floats are measurements
    (minus the known float knobs), None/lists are never identity."""
    ident = {}
    for k, v in row.items():
        if k in VALUE_KEYS or k in MEASURED_INT_KEYS:
            continue
        if isinstance(v, (str, bool)) or isinstance(v, int):
            ident[k] = v
        elif isinstance(v, float) and k in FLOAT_KNOB_KEYS:
            ident[k] = v
    return json.dumps(ident, sort_keys=True)


def _parse_ts(row: dict):
    try:
        return datetime.datetime.fromisoformat(row["ts"])
    except (KeyError, ValueError):
        return None


def check_history(rows: list[dict], tolerance: float = 0.15,
                  metric: str | None = None,
                  max_age_h: float | None = None) -> list[dict]:
    """Regressions across all lanes with >= 2 rows (file order = time
    order within a lane; append_history only ever appends)."""
    lanes: dict[str, list[dict]] = {}
    for row in rows:
        if "value" not in row or "metric" not in row:
            continue
        if metric is not None and row["metric"] != metric:
            continue
        lanes.setdefault(lane_key(row), []).append(row)
    now = datetime.datetime.now(datetime.timezone.utc)
    out = []
    for key, rs in lanes.items():
        if len(rs) < 2:
            continue
        prev, cur = rs[-2], rs[-1]
        if max_age_h is not None:
            ts = _parse_ts(cur)
            if ts is None or (now - ts).total_seconds() > max_age_h * 3600:
                continue
        try:
            pv, cv = float(prev["value"]), float(cur["value"])
        except (TypeError, ValueError):
            continue
        if pv <= 0:
            continue  # no meaningful band around a zero baseline
        lower_better = str(cur.get("unit", "")).strip() in LATENCY_UNITS
        ratio = cv / pv
        bad = (ratio > 1 + tolerance) if lower_better \
            else (ratio < 1 - tolerance)
        if bad:
            out.append({
                "metric": cur.get("metric"),
                "unit": cur.get("unit"),
                "prev": pv, "cur": cv, "ratio": round(ratio, 4),
                "direction": "lower-better" if lower_better
                             else "higher-better",
                "tolerance": tolerance,
                "lane": key,
                "prev_ts": prev.get("ts"), "cur_ts": cur.get("ts"),
            })
    return out


def load_history(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                print(f"[check_bench] skipping unparseable line: "
                      f"{line[:80]}", file=sys.stderr)
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("history", help="BENCH_HISTORY.jsonl path")
    p.add_argument("--tolerance", type=float, default=0.15,
                   help="allowed fractional drift (default 0.15)")
    p.add_argument("--metric", default=None,
                   help="restrict to one metric name")
    p.add_argument("--max-age-h", type=float, default=None,
                   help="only gate lanes whose fresh row is younger "
                        "than this many hours")
    args = p.parse_args(argv)

    rows = load_history(args.history)
    lanes = {lane_key(r) for r in rows if "value" in r}
    regs = check_history(rows, tolerance=args.tolerance,
                         metric=args.metric, max_age_h=args.max_age_h)
    if regs:
        for r in regs:
            print(f"[check_bench] REGRESSION {r['metric']} "
                  f"({r['direction']}, unit={r['unit']}): "
                  f"{r['prev']} -> {r['cur']} (x{r['ratio']}, "
                  f"tolerance {r['tolerance']})\n"
                  f"  lane: {r['lane']}", file=sys.stderr)
        print(f"[check_bench] FAIL: {len(regs)} regressed lane(s) of "
              f"{len(lanes)}", file=sys.stderr)
        return 1
    print(f"[check_bench] OK: {len(lanes)} lanes, none regressed "
          f"beyond {args.tolerance:.0%}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
