# repo tooling package (makes `python -m tools.analyze` runnable)
