#!/usr/bin/env python
"""tracetool — merge flight dumps into a causal timeline.

Reads one or more flight-recorder dumps (`pmdfc-flight-v2` JSON, from
rung firings, the SLO watchdog, or `telemetry.dump_now()`) — typically
one from the CLIENT process and one from the SERVER — and reconstructs
each traced op's walk through the serving plane as a nested tree:

    group get
    └─ attempt (endpoint 0, hedge=False)
       └─ get (client wire span)
          └─ get (server op span)          <- linked by the 32-bit trace id
             ├─ queue_wait                 <- staging -> flush pickup
             └─ phase                      <- the op's slice of the flush
                └─ flush:get               <- linked by flush seq
                   ├─ shard_program s0     <- per-shard program windows
                   └─ shard_program s3

Clock domains: server spans carry the SERVER's monotonic_ns. The client
records a `clock` event per connection during the HOLA exchange (the
server stamps its HOLASI; the midpoint of the client's send/recv
brackets it, so the offset error is bounded by rtt/2). Server-side span
times are shifted by that offset onto the client timeline — per conn
when a matching clock record exists, the median offset otherwise, zero
(with a warning) when no clock record was captured at all.

Outputs:
- `--out trace.json`: Chrome-trace / Perfetto JSON (`chrome://tracing`,
  https://ui.perfetto.dev — "X" complete events, µs timestamps).
- `--table` (default when no --out): per-stage latency breakdown
  (count / p50 / p95 / max / total µs per stage).
- `--trace ID` restricts both to one traced op.

Importable: `load_dumps`, `build_tree` (returns `Node`s with resolved
children across the process boundary), `chrome_trace`, `breakdown` —
`tests/test_tracing.py` pins the nesting contract through them.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys

# ops that name one wire verb (client+server op spans cross-link on the
# trace id at THIS level; everything else links by parent id or flush)
_VERB_OPS = ("put", "get", "invalidate", "keepalive", "bfpull",
             "ins_ext", "get_ext", "stats")


class Node:
    """One completed span as a tree node (children resolved across the
    in-process parent ids AND the cross-process/cross-flush links)."""

    __slots__ = ("pid", "rec", "children", "linked")

    def __init__(self, pid: int, rec: dict):
        self.pid = pid
        self.rec = rec
        self.children: list = []    # via in-process parent ids
        self.linked: list = []      # via trace-id / flush-seq joins

    @property
    def sid(self):
        return self.rec.get("span", 0)

    @property
    def op(self):
        return self.rec.get("op", "?")

    def all_children(self) -> list:
        return self.children + self.linked

    def depth(self) -> int:
        """Longest nesting chain rooted here (this node counts as 1)."""
        kids = self.all_children()
        return 1 + (max((k.depth() for k in kids), default=0))


def load_dumps(paths) -> list:
    """[(pid, record)] across dumps; pid = dump index (span ids are
    process-local, so records never join by id across dumps)."""
    out = []
    for pid, path in enumerate(paths):
        with open(path) as f:
            doc = json.load(f)
        for rec in doc.get("records", []):
            out.append((pid, rec))
    return out


def clock_offsets(records) -> tuple[dict, int]:
    """({conn: offset_ns}, fallback offset). The fallback is the median
    captured offset (0 when none were captured)."""
    per_conn: dict = {}
    all_offsets = []
    for _pid, rec in records:
        if rec.get("kind") != "clock":
            continue
        off = int(rec.get("offset_ns", 0))
        per_conn[rec.get("conn")] = off
        all_offsets.append(off)
    fallback = int(statistics.median(all_offsets)) if all_offsets else 0
    return per_conn, fallback


def _adjusted(rec: dict, offsets: dict, fallback: int) -> dict:
    """Server-side spans shifted onto the client clock (peer_t - offset
    = local_t). Client/group spans pass through untouched."""
    if rec.get("src") != "server" or "t0_ns" not in rec:
        return rec
    off = offsets.get(rec.get("conn"), fallback)
    if not off:
        return rec
    rec = dict(rec)
    rec["t0_ns"] -= off
    rec["t1_ns"] -= off
    return rec


def build_tree(records) -> dict:
    """{(pid, span_id): Node} with children resolved three ways:

    1. in-process parent ids (same dump);
    2. trace-id joins: a server VERB span with trace T becomes a child
       of the client verb span carrying the same T (the wire hop);
    3. flush joins: the per-op `phase` span adopts the `flush:<ph>`
       span with the same flush seq (and through it the shard_program
       children) — the op's view into the shared fused flush.

    Roots are the nodes with no resolved parent (`roots` key holds
    them under the synthetic key (-1, 0))."""
    per_conn, fallback = clock_offsets(records)
    nodes: dict = {}
    by_trace_client: dict = {}
    by_flush: dict = {}
    for pid, rec in records:
        if rec.get("kind") != "span" or not rec.get("span"):
            continue
        rec = _adjusted(rec, per_conn, fallback)
        n = Node(pid, rec)
        nodes[(pid, n.sid)] = n
        if (rec.get("src") == "client" and rec.get("trace")
                and rec.get("op") in _VERB_OPS):
            # hedged ops share one trace across two wire verbs: prefer
            # the exact (trace, conn) pairing, keep a bare-trace fallback
            by_trace_client.setdefault((rec["trace"], rec.get("conn")), n)
            by_trace_client.setdefault(rec["trace"], n)
        if rec.get("op", "").startswith("flush:"):
            by_flush[(pid, rec.get("flush"), rec.get("phase"))] = n

    roots = []
    for (pid, _sid), n in nodes.items():
        rec = n.rec
        parent = nodes.get((pid, rec.get("parent", 0)))
        if parent is not None:
            parent.children.append(n)
            continue
        # cross-process wire hop: server verb span -> client verb span
        if (rec.get("src") == "server" and rec.get("trace")
                and rec.get("op") in _VERB_OPS):
            cl = (by_trace_client.get((rec["trace"], rec.get("conn")))
                  or by_trace_client.get(rec["trace"]))
            if cl is not None and cl is not n:
                cl.linked.append(n)
                continue
        roots.append(n)
    # flush joins: the op's phase slice adopts the shared flush span
    for n in nodes.values():
        if n.op == "phase" and n.rec.get("flush") is not None:
            for pid in {p for p, _ in nodes}:
                fl = by_flush.get((pid, n.rec["flush"], n.rec.get("phase")))
                if fl is not None:
                    n.linked.append(fl)
    nodes[(-1, 0)] = rootholder = Node(-1, {"op": "<roots>"})
    rootholder.children = roots
    return nodes


def trace_tree(nodes: dict, trace: int) -> list:
    """The root nodes whose subtree carries `trace` (group/client op
    spans for that traced verb)."""
    def carries(n: Node) -> bool:
        if n.rec.get("trace") == trace:
            return True
        return any(carries(k) for k in n.all_children())

    return [n for n in nodes[(-1, 0)].children if carries(n)]


def chrome_trace(records, trace: int | None = None) -> dict:
    """Chrome-trace JSON (Perfetto-compatible 'X' complete events)."""
    per_conn, fallback = clock_offsets(records)
    spans = []
    for pid, rec in records:
        if rec.get("kind") != "span" or "t0_ns" not in rec:
            continue
        if trace is not None and rec.get("trace") != trace:
            continue
        spans.append((pid, _adjusted(rec, per_conn, fallback)))
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(rec["t0_ns"] for _pid, rec in spans)
    events = []
    for pid, rec in spans:
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "t", "t0_ns", "t1_ns", "dur_us",
                             "op", "src")}
        events.append({
            "name": rec.get("op", "?"),
            "cat": rec.get("src", "?"),
            "ph": "X",
            "ts": (rec["t0_ns"] - t_base) / 1e3,
            "dur": max((rec["t1_ns"] - rec["t0_ns"]) / 1e3, 0.001),
            "pid": pid,
            "tid": rec.get("conn", rec.get("src", 0)),
            "args": args,
        })
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _stage_name(rec: dict) -> str:
    op = rec.get("op", "?")
    if op == "phase":
        # one op's view of the shared flush window — kept as its own
        # row so the shared flush:<ph> span's wall isn't multiplied by
        # the op count in the table
        return f"op_phase:{rec.get('phase', '?')}"
    if op.startswith("flush:"):
        return f"flush:{rec.get('phase', op.split(':', 1)[-1])}"
    if op == "shard_program":
        return f"shard:{rec.get('phase', '?')}"
    if op == "attempt":
        return "hedge" if rec.get("hedge") else "attempt"
    return f"{rec.get('src', '?')}:{op}"


def breakdown(records) -> list[dict]:
    """Per-stage latency rows: [{stage, count, p50_us, p95_us, max_us,
    total_us}] sorted by total, the tuning table the per-stage
    visibility argument (RDMAbox) asks for."""
    durs: dict[str, list] = {}
    for _pid, rec in records:
        if rec.get("kind") != "span" or rec.get("dur_us") is None:
            continue
        durs.setdefault(_stage_name(rec), []).append(rec["dur_us"])
    rows = []
    for stage, vs in durs.items():
        vs.sort()
        rows.append({
            "stage": stage,
            "count": len(vs),
            "p50_us": round(vs[len(vs) // 2], 1),
            "p95_us": round(vs[min(len(vs) - 1, int(0.95 * len(vs)))], 1),
            "max_us": round(vs[-1], 1),
            "total_us": round(sum(vs), 1),
        })
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def render_table(rows: list[dict]) -> str:
    cols = ("stage", "count", "p50_us", "p95_us", "max_us", "total_us")
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows)) if rows
              else len(c) for c in cols}
    lines = ["  ".join(c.ljust(widths[c]) for c in cols),
             "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        lines.append("  ".join(str(r[c]).ljust(widths[c]) for c in cols))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("dumps", nargs="+",
                   help="flight dump JSON files (client and/or server)")
    p.add_argument("--out", default=None,
                   help="write Chrome-trace/Perfetto JSON here")
    p.add_argument("--trace", type=lambda s: int(s, 0), default=None,
                   help="restrict to one 32-bit trace id")
    p.add_argument("--table", action="store_true",
                   help="print the per-stage latency breakdown")
    args = p.parse_args(argv)

    records = load_dumps(args.dumps)
    spans = [r for _p, r in records if r.get("kind") == "span"]
    if not spans:
        print("[tracetool] no span records in the given dumps "
              "(telemetry off, or ring rolled over?)", file=sys.stderr)
        return 1
    _per_conn, fallback = clock_offsets(records)
    if len(args.dumps) > 1 and not _per_conn:
        print("[tracetool] WARNING: multiple dumps but no clock records "
              "— server spans placed with zero offset", file=sys.stderr)

    if args.out:
        doc = chrome_trace(records, trace=args.trace)
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"[tracetool] {len(doc['traceEvents'])} events -> "
              f"{args.out} (open in chrome://tracing or ui.perfetto.dev)")
    if args.table or not args.out:
        sel = records
        if args.trace is not None:
            sel = [(p_, r) for p_, r in records
                   if r.get("trace") == args.trace]
        print(render_table(breakdown(sel)))
    if args.trace is not None:
        nodes = build_tree(records)
        roots = trace_tree(nodes, args.trace)
        depth = max((n.depth() for n in roots), default=0)
        print(f"[tracetool] trace {args.trace:#010x}: "
              f"{len(roots)} root(s), max nesting depth {depth}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
