"""proftool — roll profiler teledumps into device-time breakdowns.

Consumes any document that embeds a telemetry snapshot with the
profiler's `profile` block (schema `pmdfc-telemetry-v3`):

- flight-recorder dumps (`flight_*.json`, the `telemetry` key),
- `tools/teledump.py` pulls / raw `MSG_STATS` replies (same shape),
- bare `Registry.snapshot()` documents.

Surfaces:

    python -m tools.proftool dump.json --table
        The phase x program x shard device-time breakdown (ops,
        device_us, share of the shard axis), followed by the per-shard
        lane totals RECONCILED against the `mesh.shard{i}_ops` span
        counters — the cross-check that the profiler's proportional
        split and the plane's routed-op accounting agree — plus the
        windowed imbalance gauge and any captured `cost.*` roofline
        context (FLOPs / bytes per program signature).

    python -m tools.proftool dump.json --json
        The same aggregation as a machine-readable document.

    python -m tools.proftool dump*.json --perfetto trace.json
        tracetool's Chrome-trace export with the profiler's `device`
        span records lifted onto their own per-program lanes
        (`tid = "device:<program>"`), so the blocked-fetch windows
        read as a device-occupancy track under the host span tree.

Aggregation is additive across input documents (counters and the
attribution table are cumulative), so feeding several dumps from ONE
process yields the latest totals via max-merge, while dumps from
DIFFERENT processes simply sum.
"""
from __future__ import annotations

import argparse
import json
import sys

from tools import tracetool

_ROW_COLS = ("phase", "program", "shard", "ops", "device_us", "share")
_SHARD_COLS = ("shard", "device_us", "prof_ops", "mesh_ops", "match")


def load_docs(paths) -> list[dict]:
    """Each input file -> the embedded telemetry snapshot (flight dumps
    and stats replies nest it under `telemetry`; bare snapshots pass
    through). Files without one are kept (they may still carry ring
    `records` for --perfetto) but contribute no profile rows."""
    docs = []
    for path in paths:
        with open(path) as f:
            raw = json.load(f)
        snap = raw.get("telemetry", raw)
        docs.append({"path": path, "raw": raw, "snap": snap,
                     "profile": snap.get("profile")})
    return docs


def _merge(docs: list[dict]) -> dict:
    """Aggregate profile blocks + mesh counters across documents.

    Same-process dumps carry cumulative state, so identical row keys
    max-merge (the later dump supersedes); distinct processes occupy
    distinct keys only by luck, so cross-process feeds should pass one
    dump per process — the common workflows (one teledump, or a rolling
    window from one server) are both exact."""
    table: dict = {}
    shard_us: list[float] = []
    shard_ops: list[int] = []
    mesh_ops: dict[int, int] = {}
    cost: dict = {}
    launches = 0
    dropped = 0
    imbalance = 0.0
    n_docs = 0
    for d in docs:
        prof = d["profile"]
        if not prof:
            continue
        n_docs += 1
        launches = max(launches, int(prof.get("launches", 0)))
        dropped = max(dropped, int(prof.get("rows_dropped", 0)))
        imbalance = prof.get("imbalance", imbalance) or imbalance
        for r in prof.get("rows", ()):
            key = (r.get("phase", "?"), r.get("program", "?"),
                   int(r.get("shard", -1)))
            row = table.setdefault(key, [0, 0.0])
            row[0] = max(row[0], int(r.get("ops", 0)))
            row[1] = max(row[1], float(r.get("device_us", 0.0)))
        us = prof.get("shard_device_us", ())
        ops = prof.get("shard_ops", ())
        while len(shard_us) < len(us):
            shard_us.append(0.0)
            shard_ops.append(0)
        for i, v in enumerate(us):
            shard_us[i] = max(shard_us[i], float(v))
        for i, v in enumerate(ops):
            shard_ops[i] = max(shard_ops[i], int(v))
        for prog, c in prof.get("cost", {}).items():
            cost[prog] = dict(c)
        for name, v in d["snap"].get("counters", {}).items():
            if name.startswith("mesh.shard") and name.endswith("_ops"):
                try:
                    i = int(name[len("mesh.shard"):-len("_ops")])
                except ValueError:
                    continue
                mesh_ops[i] = max(mesh_ops.get(i, 0), int(v))
    return {"table": table, "shard_us": shard_us, "shard_ops": shard_ops,
            "mesh_ops": mesh_ops, "cost": cost, "launches": launches,
            "rows_dropped": dropped, "imbalance": imbalance,
            "docs_with_profile": n_docs}


def breakdown(agg: dict) -> dict:
    """The merged state as the report document (--json payload)."""
    total_us = sum(us for _ops, us in agg["table"].values()) or 1.0
    rows = [
        {"phase": ph, "program": pr, "shard": s, "ops": ops,
         "device_us": round(us, 1), "share": round(us / total_us, 4)}
        for (ph, pr, s), (ops, us) in sorted(
            agg["table"].items(), key=lambda kv: -kv[1][1])
    ]
    shards = []
    for i, us in enumerate(agg["shard_us"]):
        mesh = agg["mesh_ops"].get(i)
        prof = agg["shard_ops"][i]
        shards.append({
            "shard": i, "device_us": round(us, 1), "prof_ops": prof,
            "mesh_ops": mesh,
            # mesh counters cover EVERY routed launch since process
            # start; the profiler only attributes while attached AND
            # tracing — equality holds on the from-boot workflows the
            # acceptance drill runs, subset otherwise
            "match": (mesh is None and "n/a")
                     or ("yes" if prof == mesh else "no"),
        })
    return {
        "schema": "pmdfc-proftable-v1",
        "launches": agg["launches"],
        "rows_dropped": agg["rows_dropped"],
        "imbalance": agg["imbalance"],
        "rows": rows,
        "shards": shards,
        "cost": agg["cost"],
    }


def _render(rows: list[dict], cols: tuple) -> str:
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              if rows else len(c) for c in cols}
    lines = ["  ".join(c.ljust(widths[c]) for c in cols),
             "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        lines.append("  ".join(
            str(r.get(c, "")).ljust(widths[c]) for c in cols))
    return "\n".join(lines)


def render_report(rep: dict) -> str:
    out = [_render(rep["rows"], _ROW_COLS)]
    if rep["shards"]:
        out.append("")
        out.append("per-shard lanes (vs mesh.shard{i}_ops):")
        out.append(_render(rep["shards"], _SHARD_COLS))
    out.append("")
    out.append(f"launches={rep['launches']} "
               f"rows_dropped={rep['rows_dropped']} "
               f"imbalance={rep['imbalance']}")
    if rep["cost"]:
        out.append("")
        out.append("static cost (lowered.cost_analysis):")
        out.append(_render(
            [{"program": k, "flops": v.get("flops", 0.0),
              "bytes": v.get("bytes", 0.0)}
             for k, v in sorted(rep["cost"].items())],
            ("program", "flops", "bytes")))
    return "\n".join(out)


def device_lane_trace(paths) -> dict:
    """tracetool's Chrome-trace export with src=prof `device` spans
    re-homed onto per-program lanes. Host spans keep their conn tids;
    every profiler window lands on `device:<program>` so Perfetto draws
    a device-occupancy track."""
    records = tracetool.load_dumps(paths)
    doc = tracetool.chrome_trace(records)
    for ev in doc["traceEvents"]:
        if ev.get("cat") == "prof" and ev.get("name") == "device":
            prog = ev.get("args", {}).get("program", "?")
            ev["tid"] = f"device:{prog}"
            ev["name"] = prog
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("dumps", nargs="+",
                   help="flight dumps / teledump pulls / snapshots")
    p.add_argument("--table", action="store_true",
                   help="print the phase x program x shard breakdown")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the breakdown as JSON")
    p.add_argument("--perfetto", default=None, metavar="OUT",
                   help="write a Chrome-trace with device lanes merged")
    args = p.parse_args(argv)

    docs = load_docs(args.dumps)
    agg = _merge(docs)
    if args.perfetto:
        doc = device_lane_trace(args.dumps)
        with open(args.perfetto, "w") as f:
            json.dump(doc, f)
        n_dev = sum(1 for e in doc["traceEvents"]
                    if str(e.get("tid", "")).startswith("device:"))
        print(f"[proftool] {len(doc['traceEvents'])} events "
              f"({n_dev} device-lane) -> {args.perfetto}")
    if not agg["docs_with_profile"]:
        if args.perfetto:
            return 0
        print("[proftool] no `profile` block in the given documents "
              "(profiler not attached? PMDFC_PROF=off?)", file=sys.stderr)
        return 1
    rep = breakdown(agg)
    if args.as_json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    elif args.table or not args.perfetto:
        print(render_report(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
