#!/usr/bin/env python
"""teletop — "top for the fleet": a curses-free live console over MSG_STATS.

Fans out to N serving endpoints (a `ReplicaGroup`'s endpoint list, or any
`host:port` set), pulls each server's `pmdfc-telemetry-v2` snapshot over
the existing op channel (`tools/teledump.py`'s verb — no second port, no
agent), and renders per-server / per-shard:

- op RATES from the server-side windowed series (`runtime/timeseries.py`
  — a single `--once` poll still yields rates, no second sample needed),
- p95/p99 of the GET flush phase (per-shard `phase_get_us_s{i}` families
  when the mesh plane is up),
- hit-rate and the MISS-CAUSE breakdown (`miss_cold/evicted/parked/
  stale/digest/routed` — the taxonomy whose sums reconcile with `misses`
  on every surface),
- working-set estimate vs table capacity and keyspace heat skew
  (`runtime/workload.py` sketches),
- shard balance (max/mean routed gets across the shard_report),
- the tiered store's placement counters, with the TinyLFU admission
  block (denied/override rates, sketch age, live threshold) when the
  gate is on,
- the GET kernel-path indicator (fused Pallas vs composed XLA, from
  the `serving.fused_get` gauge) and — when a profiler is attached
  (v3 snapshots) — the DEVICE-TIME lanes: per-shard blocked-fetch
  p95s and the windowed shard-imbalance gauge.

Plain ANSI repaint, poll-based (`--interval`), and a `--once --json`
mode that emits one machine-readable document for scripts — the form
`tools/check_teledump.py`-style gates and the agenda's `teletop_smoke`
step consume.

    python tools/teletop.py HOST:PORT [HOST:PORT ...]
    python tools/teletop.py HOST:PORT --once --json
    python tools/teletop.py --smoke          # hermetic self-drill (CI)
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from concurrent.futures import ThreadPoolExecutor

_SHARD_HIST = re.compile(r"\.phase_get_us_s(\d+)$")
# the profiler's per-shard device-time lanes (`runtime/profiler.py`
# hist family on the shared `prof` scope — present IFF a profiler is
# attached, the v3 teledump pin)
_PROF_SHARD_HIST = re.compile(r"^prof\.device_us_s(\d+)$")
# per-tenant QoS lanes (`runtime/qos.py` scope families): the lane
# counters and the declared-policy gauges share one `.qos.t<tid>.`
# namespace under the server's stats prefix
_QOS_CTR = re.compile(
    r"\.qos\.t(\d+)\.(ops|staged|shed_edge|shed_ladder"
    r"|shed_gets|shed_puts)$")
_QOS_GAUGE = re.compile(r"\.qos\.t(\d+)\.(weight|rate|priority)$")


def pull(endpoint: str, page_words: int, timeout_s: float) -> dict:
    """One MSG_STATS snapshot from `host:port` ({"error": ...} on any
    transport failure — a dead server must not kill the console)."""
    from pmdfc_tpu.runtime.net import TcpBackend

    host, port = endpoint.rsplit(":", 1)
    try:
        with TcpBackend(host, int(port), page_words=page_words,
                        keepalive_s=None, op_timeout_s=timeout_s) as be:
            return be.server_stats()
    except Exception as e:  # noqa: BLE001 — console, not serving path
        return {"error": f"{type(e).__name__}: {e}"}


def _series_rate(doc: dict, suffix: str) -> float | None:
    """Per-second rate of every counter ending `suffix`, from the last
    closed series window (None when the server ships no series)."""
    windows = ((doc.get("telemetry") or {}).get("series")
               or {}).get("windows") or []
    if not windows:
        return None
    w = windows[-1]
    dt = w.get("dt_s") or 0
    if dt <= 0:
        return None
    total = sum(v for k, v in (w.get("counters") or {}).items()
                if k.endswith(suffix))
    return total / dt


def _hist(doc: dict, suffix: str) -> dict | None:
    """The busiest histogram whose full name ends `suffix`."""
    hists = (doc.get("telemetry") or {}).get("histograms") or {}
    best = None
    for name, h in hists.items():
        if name.endswith(suffix):
            if best is None or h.get("count", 0) > best.get("count", 0):
                best = h
    return best


def miss_causes(stats: dict) -> dict:
    from pmdfc_tpu.kv import MISS_CAUSE_NAMES

    return {k: int(stats.get(k, 0)) for k in MISS_CAUSE_NAMES}


def summarize(endpoint: str, doc: dict) -> dict:
    """One server's console row from its MSG_STATS document."""
    if "error" in doc:
        return {"endpoint": endpoint, "ok": False, "error": doc["error"]}
    gets = int(doc.get("gets", 0))
    hits = int(doc.get("hits", 0))
    tele_snap = doc.get("telemetry") or {}
    get_hist = _hist(doc, ".phase_get_us")
    wl = doc.get("workload") or {}
    win = wl.get("window") or {}
    row = {
        "endpoint": endpoint,
        "ok": True,
        "gets": gets,
        "hits": hits,
        "misses": int(doc.get("misses", 0)),
        "hit_rate": round(hits / gets, 4) if gets else None,
        "ops_rate": _series_rate(doc, ".ops"),
        "get_rate": _series_rate(doc, ".coalesced_ops"),
        "p95_us": get_hist.get("p95") if get_hist else None,
        "p99_us": get_hist.get("p99") if get_hist else None,
        "miss_causes": miss_causes(doc),
        "capacity": doc.get("capacity"),
        "working_set": wl.get("working_set"),
        "window_working_set": win.get("working_set"),
        "heat_skew": (wl.get("heat") or {}).get("skew"),
        "telemetry_schema": tele_snap.get("schema"),
    }
    # kernel-path indicator: which GET program this server actually
    # runs (`ops/fused.py resolve()` publishes its construction-time
    # decision as the serving.fused_get gauge; absent = pre-gauge
    # server, unknown)
    fg = (tele_snap.get("gauges") or {}).get("serving.fused_get")
    row["kernel"] = (None if fg is None
                     else ("pallas_fused" if fg else "xla_composed"))
    # device-time lanes (profiler attached ⇒ v3 snapshot): per-shard
    # blocked-fetch p95s + the windowed imbalance gauge — the on-chip
    # complement to the host-side phase histograms above
    prof_p95 = {}
    for name, h in (tele_snap.get("histograms") or {}).items():
        m = _PROF_SHARD_HIST.match(name)
        if m:
            prof_p95[int(m.group(1))] = h.get("p95")
    if prof_p95 or (tele_snap.get("profile") is not None):
        row["device"] = {
            "imbalance": (tele_snap.get("gauges") or {}).get(
                "prof.shard_imbalance"),
            "shard_p95_us": [prof_p95.get(i)
                             for i in range(max(prof_p95, default=-1)
                                            + 1)],
            "launches": (tele_snap.get("profile") or {}).get("launches"),
        }
    # one-sided fast lane: share of served reads that bypassed the
    # dispatch path entirely (reads land in the net scope counters, not
    # the KV stats vector — zero device work by construction)
    ctr = tele_snap.get("counters") or {}
    fp_hits = sum(v for k, v in ctr.items()
                  if k.endswith(".fastpath_hits"))
    fp_stale = sum(v for k, v in ctr.items()
                   if k.endswith(".fastpath_stale"))
    row["fastpath"] = {
        # reads are DERIVED (hits + stale): the server stores only the
        # two exclusive lanes, so the sum can never drift mid-pull
        "reads": int(fp_hits + fp_stale), "hits": int(fp_hits),
        "stale": int(fp_stale),
        # fast-lane hit share of ALL served read lanes (fast + verb)
        "share": (round(fp_hits / (fp_hits + gets), 4)
                  if fp_hits + gets else None),
    }
    # tiered store: hot/cold placement counters, and the TinyLFU
    # admission block when the gate is on (denied/override RATES are
    # normalized against the decisions that could have gone the other
    # way — denied vs granted promotions, overrides vs ghost
    # readmissions — so a long-lived server's rates stay readable)
    if "hot_hits" in doc:
        tier = {k: int(doc.get(k, 0))
                for k in ("hot_hits", "cold_hits", "promotions",
                          "demotions", "ghost_readmits")}
        if "admit_denied" in doc:
            denied = int(doc.get("admit_denied", 0))
            granted = int(doc.get("promotions", 0))
            override = int(doc.get("admit_ghost_override", 0))
            readmits = int(doc.get("ghost_readmits", 0))
            tier["admit"] = {
                "denied": denied,
                "victim_kept": int(doc.get("admit_victim_kept", 0)),
                "ghost_override": override,
                "age_epochs": int(doc.get("admit_age_epochs", 0)),
                "threshold": int(doc.get("admit_threshold", 0)),
                "denied_rate": (round(denied / (denied + granted), 4)
                                if denied + granted else None),
                "override_rate": (round(override / readmits, 4)
                                  if readmits else None),
            }
        row["tier"] = tier
    # elastic membership: the last announced ring epoch (gauge) and how
    # many of this server's arrived pages were migration handoffs — a
    # transition mid-flight shows here before the hit-rate dip does
    gg = tele_snap.get("gauges") or {}
    row["ring"] = {
        "epoch": next((int(v) for k, v in gg.items()
                       if k.endswith(".ring_epoch") and v), None),
        "handoff_pages": int(sum(v for k, v in ctr.items()
                                 if k.endswith(".handoff_pages"))),
        "migration_lag": next((int(v) for k, v in gg.items()
                               if k.startswith("migration")
                               and k.endswith(".lag")), None),
    }
    # closed-loop controller (`runtime/autotune.py`): the live knob
    # vector + decision/revert counters, present only when a controller
    # is enabled in the serving process (the scope-iff-enabled pin)
    knobs = {k.split(".knob_", 1)[1]: v for k, v in gg.items()
             if ".knob_" in k and not k.endswith(("_lo", "_hi"))}
    if knobs:
        row["ctl"] = {
            "knobs": knobs,
            "decisions": int(sum(v for k, v in ctr.items()
                                 if k.endswith(".decisions"))),
            "reverts": int(sum(v for k, v in ctr.items()
                               if k.endswith(".reverts"))),
            "frozen": next((int(v) for k, v in gg.items()
                            if k.endswith(".frozen")), 0),
        }
    # multi-tenant QoS plane (`runtime/qos.py`): per-tenant lane
    # counters + declared weight/rate/priority gauges, present only
    # when the plane is on (the scope-iff-enabled pin). Keys are
    # stringified tids so the --json form round-trips unchanged.
    qos: dict[int, dict] = {}
    for k, v in ctr.items():
        m = _QOS_CTR.search(k)
        if m:
            qos.setdefault(int(m.group(1)), {})[m.group(2)] = int(v)
    for k, v in gg.items():
        m = _QOS_GAUGE.search(k)
        if m:
            qos.setdefault(int(m.group(1)), {})[m.group(2)] = v
    if qos:
        row["qos"] = {str(t): qos[t] for t in sorted(qos)}
    # blast-radius containment (`runtime/failure.py` + net NACKs): the
    # server's nack/bisect/deadline lanes ride the net scope counters;
    # the quarantine tier (when on) ships its own report block with the
    # live quarantined-shard list — a tripped shard shows here before
    # its hit-rate dip does
    cont = {k: int(sum(v for c, v in ctr.items()
                       if c.endswith("." + k)))
            for k in ("nacks_sent", "poison_refused", "poison_ops",
                      "bisect_failures", "deadline_shed")}
    q = doc.get("quarantine")
    if q:
        qs = q.get("stats") or {}
        cont["quarantined"] = [int(s) for s in q.get("quarantined", [])]
        cont["trips"] = int(qs.get("trips", 0))
        cont["readmits"] = int(qs.get("readmits", 0))
    if q or any(cont.values()):
        row["containment"] = cont
    rep = doc.get("shard_report")
    if rep:
        shards = []
        p99 = {}
        for name, h in (tele_snap.get("histograms") or {}).items():
            m = _SHARD_HIST.search(name)
            if m:
                p99[int(m.group(1))] = h.get("p99")
        st = rep.get("stats", {})
        n = int(rep.get("n_shards", 0))
        dev = (row.get("device") or {}).get("shard_p95_us") or []
        for i in range(n):
            shards.append({
                "shard": i,
                "gets": int(st.get("gets", [0] * n)[i]),
                "hits": int(st.get("hits", [0] * n)[i]),
                "misses": int(st.get("misses", [0] * n)[i]),
                "miss_causes": {k: int(st.get(k, [0] * n)[i])
                                for k in row["miss_causes"]},
                "utilization": rep.get("utilization", [None] * n)[i],
                "p99_us": p99.get(i),
                "device_p95_us": dev[i] if i < len(dev) else None,
            })
        sg = [s["gets"] for s in shards]
        mean = sum(sg) / len(sg) if sg else 0
        row["shards"] = shards
        row["shard_balance"] = (round(max(sg) / mean, 3)
                                if mean else None)
    return row


def poll(endpoints: list, page_words: int, timeout_s: float) -> list:
    with ThreadPoolExecutor(max_workers=max(1, len(endpoints))) as ex:
        docs = list(ex.map(
            lambda ep: pull(ep, page_words, timeout_s), endpoints))
    return [summarize(ep, doc) for ep, doc in zip(endpoints, docs)]


def _fmt(v, unit: str = "", nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{unit}"
    return f"{v}{unit}"


def render(rows: list) -> str:
    """The human console frame (plain text; the loop repaints it)."""
    out = [f"teletop — {len(rows)} server(s) @ "
           f"{time.strftime('%H:%M:%S')}"]
    hdr = (f"{'endpoint':<22} {'ops/s':>9} {'p95us':>8} {'p99us':>8} "
           f"{'hit%':>6} {'fast%':>6} {'wset':>8} {'cap':>8} {'bal':>5}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        if not r.get("ok"):
            out.append(f"{r['endpoint']:<22} DOWN  {r.get('error', '')}")
            continue
        hr = r.get("hit_rate")
        fp = (r.get("fastpath") or {}).get("share")
        out.append(
            f"{r['endpoint']:<22} {_fmt(r.get('ops_rate')):>9} "
            f"{_fmt(r.get('p95_us'), nd=0):>8} "
            f"{_fmt(r.get('p99_us'), nd=0):>8} "
            f"{_fmt(hr * 100 if hr is not None else None):>6} "
            f"{_fmt(fp * 100 if fp is not None else None):>6} "
            f"{_fmt(r.get('working_set'), nd=0):>8} "
            f"{_fmt(r.get('capacity')):>8} "
            f"{_fmt(r.get('shard_balance'), nd=2):>5}")
        mc = r.get("miss_causes") or {}
        live = {k.replace('miss_', ''): v for k, v in mc.items() if v}
        kern = {"pallas_fused": " kernel=fused",
                "xla_composed": " kernel=composed"}.get(
                    r.get("kernel"), "")
        out.append(f"    misses={r.get('misses')} causes={live or '{}'}"
                   f"{kern}")
        dev = r.get("device")
        if dev:
            lanes = " ".join(
                f"s{i}={_fmt(v, nd=0)}"
                for i, v in enumerate(dev.get("shard_p95_us") or []))
            out.append(
                f"    device: imbalance="
                f"{_fmt(dev.get('imbalance'), nd=2)}"
                f"{' p95us[' + lanes + ']' if lanes else ''}")
        tier = r.get("tier")
        if tier:
            line = (f"    tier: hot={tier['hot_hits']} "
                    f"cold={tier['cold_hits']} "
                    f"promo={tier['promotions']} "
                    f"demo={tier['demotions']}")
            adm = tier.get("admit")
            if adm:
                dr, orate = adm.get("denied_rate"), adm.get("override_rate")
                line += (f" | admit: thresh={adm['threshold']} "
                         f"denied={adm['denied']}"
                         f" ({_fmt(dr * 100 if dr is not None else None)}%)"
                         f" override={adm['ghost_override']}"
                         f" ({_fmt(orate * 100 if orate is not None else None)}%)"
                         f" age={adm['age_epochs']}")
            out.append(line)
        ctl = r.get("ctl")
        if ctl:
            ks = " ".join(f"{k}={_fmt(v, nd=0)}"
                          for k, v in sorted(ctl["knobs"].items()))
            out.append(
                f"    ctl: {ks} decisions={ctl['decisions']} "
                f"reverts={ctl['reverts']}"
                f"{' FROZEN' if ctl.get('frozen') else ''}")
        for t, d in (r.get("qos") or {}).items():
            shed = d.get("shed_edge", 0) + d.get("shed_ladder", 0)
            out.append(
                f"    qos t{t}: w={_fmt(d.get('weight'), nd=0)} "
                f"prio={_fmt(d.get('priority'), nd=0)} "
                f"rate={_fmt(d.get('rate'), nd=0)} "
                f"ops={d.get('ops', 0)} staged={d.get('staged', 0)} "
                f"shed={shed}")
        cont = r.get("containment")
        if cont:
            line = (f"    containment: nacks={cont.get('nacks_sent', 0)} "
                    f"refused={cont.get('poison_refused', 0)} "
                    f"poison={cont.get('poison_ops', 0)} "
                    f"bisects={cont.get('bisect_failures', 0)} "
                    f"deadline_shed={cont.get('deadline_shed', 0)}")
            if "quarantined" in cont:
                line += (f" | quarantined={cont['quarantined'] or '[]'} "
                         f"trips={cont.get('trips', 0)} "
                         f"readmits={cont.get('readmits', 0)}")
            out.append(line)
        for s in r.get("shards") or []:
            dp = s.get("device_p95_us")
            out.append(
                f"    shard{s['shard']}: gets={s['gets']} "
                f"hits={s['hits']} misses={s['misses']} "
                f"p99={_fmt(s.get('p99_us'), nd=0)}us "
                f"util={_fmt(s.get('utilization'), nd=3)}"
                + (f" dev_p95={_fmt(dp, nd=0)}us"
                   if dp is not None else ""))
    return "\n".join(out)


def run_loop(endpoints: list, page_words: int, interval_s: float,
             timeout_s: float) -> int:
    try:
        while True:
            rows = poll(endpoints, page_words, timeout_s)
            sys.stdout.write("\x1b[H\x1b[2J" + render(rows) + "\n")
            sys.stdout.flush()
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0


# -- hermetic self-drill (the agenda's teletop_smoke step) -----------------

_SMOKE_REQUIRED = ("endpoint", "ok", "gets", "hit_rate", "miss_causes",
                   "working_set", "capacity", "p99_us", "kernel")


def smoke() -> int:
    """Spin one coalesced NetServer over a real KV, drive traffic, run
    the exact `--once --json` path against it, and schema-check the
    emitted document. Exit 0 = the console's wire contract holds."""
    import io
    import numpy as np

    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.config import (IndexConfig, KVConfig, NetConfig,
                                  TelemetryConfig)
    from pmdfc_tpu.kv import KV, MISS_CAUSE_NAMES
    from pmdfc_tpu.runtime import telemetry, timeseries
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    telemetry.configure(TelemetryConfig(enabled=True))
    col = timeseries.ensure_collector(interval_s=0.2)
    kv = KV(KVConfig(index=IndexConfig(capacity=1 << 10), page_words=16))
    srv = NetServer(lambda: DirectBackend(kv),
                    net=NetConfig(flush_timeout_us=0, settle_us=0)).start()
    try:
        with TcpBackend("127.0.0.1", srv.port, page_words=16,
                        keepalive_s=None) as be:
            rng = np.random.default_rng(5)
            flat = rng.choice(1 << 12, 256, replace=False)
            keys = np.stack([flat >> 6, flat & 0x3F], -1).astype(np.uint32)
            pages = np.tile(np.arange(16, dtype=np.uint32), (256, 1))
            be.put(keys[:192], pages[:192])
            for _ in range(8):
                be.get(keys)  # 64 cold misses per round
        col.tick()  # close a series window deterministically
        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            rc = main([f"127.0.0.1:{srv.port}", "--once", "--json",
                       "--page-words", "16"])
        finally:
            sys.stdout = stdout
        if rc != 0:
            print(f"[teletop] FAIL: --once --json exited {rc}")
            return 1
        doc = json.loads(buf.getvalue())
        rows = doc.get("servers") or []
        errs = []
        if len(rows) != 1:
            errs.append(f"expected 1 server row, got {len(rows)}")
        row = rows[0] if rows else {}
        for k in _SMOKE_REQUIRED:
            if k not in row:
                errs.append(f"row lacks {k!r}")
        if row.get("ok") is not True:
            errs.append(f"row not ok: {row.get('error')}")
        mc = row.get("miss_causes") or {}
        if set(mc) != set(MISS_CAUSE_NAMES):
            errs.append(f"miss_causes keys {sorted(mc)}")
        if row.get("misses") != sum(mc.values()):
            errs.append(f"cause sum {sum(mc.values())} != "
                        f"misses {row.get('misses')}")
        if not row.get("gets"):
            errs.append("no gets observed")
        # the kernel-path indicator rides the serving.fused_get gauge
        # KV construction publishes; a CPU drill always runs composed
        if row.get("kernel") != "xla_composed":
            errs.append(f"kernel indicator {row.get('kernel')!r}, "
                        "expected 'xla_composed' on CPU")
        if row.get("ops_rate") is None:
            errs.append("no windowed ops rate (series missing?)")
        ws = row.get("working_set")
        if ws is None or not (0 < ws <= 4 * 256):
            errs.append(f"working_set {ws} out of bounds")
        # containment is activity-iff-present: a clean drill emits no
        # block (all nack/bisect lanes zero, no quarantine tier)
        if "containment" in row:
            errs.append(f"containment block on a clean run: "
                        f"{row['containment']}")
        if errs:
            for e in errs:
                print(f"[teletop] FAIL: {e}")
            return 1
        print(f"[teletop] OK: {json.dumps(row)[:200]}...")
        return 0
    finally:
        srv.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("endpoints", nargs="*", metavar="HOST:PORT")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll/repaint period (loop mode)")
    p.add_argument("--once", action="store_true",
                   help="one poll, print, exit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (with --once)")
    p.add_argument("--page-words", type=int, default=1024,
                   help="must match the servers (HOLA negotiation)")
    p.add_argument("--timeout-s", type=float, default=10.0)
    p.add_argument("--smoke", action="store_true",
                   help="hermetic self-drill against a local server")
    args = p.parse_args(argv)

    if args.smoke:
        return smoke()
    if not args.endpoints:
        p.error("need at least one HOST:PORT (or --smoke)")
    if not args.once:
        return run_loop(args.endpoints, args.page_words, args.interval,
                        args.timeout_s)
    rows = poll(args.endpoints, args.page_words, args.timeout_s)
    if args.json:
        json.dump({"ts": time.time(), "servers": rows}, sys.stdout,
                  indent=1)
        sys.stdout.write("\n")
    else:
        print(render(rows))
    return 0 if all(r.get("ok") for r in rows) else 3


if __name__ == "__main__":
    import os

    # runnable as `python tools/teletop.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
