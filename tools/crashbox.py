"""crashbox — real-process SIGKILL harness for durability drills.

The torn-tail story in `runtime/journal.py` is only honest if the
writer actually dies mid-write: in-process "crashes" (dropping a KV on
the floor) never tear a record, because CPython flushes the file object
on GC. This harness runs a real `NetServer` over a journal-attached KV
in a CHILD process (spawn context, so the child owns a fresh JAX
runtime and its own file descriptors) and lets the parent `kill -9` it
between two acked RPCs — the only way to manufacture a genuinely torn
journal tail or an un-fsynced pending window.

Parent-side surface:

    box = Crashbox(kv_cfg, journal_dir, journal_cfg)
    replay = box.start()              # {"port", "replay"} once serving
    ... drive TcpBackend("127.0.0.1", box.port) ...
    box.snapshot(path, delta=True)    # chain link cut in the child
    box.kill()                        # SIGKILL — no atexit, no flush
    # warm restart: a NEW Crashbox with chain_paths= replays the tail

The control pipe carries snapshot / stats / mark_recovered commands so
drills can cut chain links and read server-side counters mid-storm
without a second wire protocol. `kill()` bypasses the pipe entirely —
that is the point.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal


def _child_main(conn, kv_cfg, journal_cfg, journal_dir, chain_paths) -> None:
    """Child body: serve a journal-attached KV until killed.

    Runs in a spawned process — imports stay inside so the parent's
    module graph (and its JAX runtime) is never inherited.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.runtime.journal import Journal, warm_restart
    from pmdfc_tpu.runtime.net import NetServer

    if chain_paths:
        kv, replay = warm_restart(kv_cfg, list(chain_paths), journal_dir,
                                  journal_config=journal_cfg)
    else:
        from pmdfc_tpu.kv import KV

        kv = KV(kv_cfg, journal=Journal(journal_dir, journal_cfg))
        replay = {"records": 0, "pages": 0, "truncated_bytes": 0}
    srv = NetServer(lambda: DirectBackend(kv)).start()
    conn.send({"port": srv.port, "replay": replay})
    try:
        while True:
            try:
                cmd = conn.recv()
            except EOFError:
                break
            op = cmd[0]
            if op == "snapshot":
                conn.send(kv.snapshot(cmd[1], delta=bool(cmd[2])))
            elif op == "stats":
                conn.send(kv.stats())
            elif op == "recovery_info":
                conn.send(kv.recovery_info())
            elif op == "mark_recovered":
                conn.send(kv.mark_recovered())
            elif op == "stop":
                conn.send(True)
                break
            else:  # unknown command: fail loudly, not silently
                conn.send({"error": f"unknown crashbox op {cmd!r}"})
    finally:
        srv.stop()


class Crashbox:
    """One killable child serving a journal-attached KV over TCP."""

    def __init__(self, kv_cfg, journal_dir: str, journal_cfg=None,
                 chain_paths=(), start_timeout_s: float = 120.0):
        self._ctx = mp.get_context("spawn")
        self._parent, self._child = self._ctx.Pipe()
        self._proc = self._ctx.Process(
            target=_child_main,
            args=(self._child, kv_cfg, journal_cfg, str(journal_dir),
                  tuple(str(p) for p in chain_paths)),
            daemon=True)
        self._timeout = float(start_timeout_s)
        self.port: int | None = None
        self.replay: dict | None = None

    def start(self) -> dict:
        """Launch the child; blocks until it is serving. Returns the
        hello card: `{"port": int, "replay": warm-restart report}`."""
        self._proc.start()
        self._child.close()  # parent keeps only its end
        if not self._parent.poll(self._timeout):
            self.kill()
            raise TimeoutError(
                f"crashbox child not serving after {self._timeout:.0f}s")
        hello = self._parent.recv()
        self.port = hello["port"]
        self.replay = hello["replay"]
        return hello

    def _command(self, *cmd):
        self._parent.send(cmd)
        if not self._parent.poll(self._timeout):
            raise TimeoutError(f"crashbox child stuck on {cmd[0]!r}")
        out = self._parent.recv()
        if isinstance(out, dict) and "error" in out:
            raise RuntimeError(out["error"])
        return out

    def snapshot(self, path: str, delta: bool = False) -> dict:
        return self._command("snapshot", str(path), delta)

    def stats(self) -> dict:
        return self._command("stats")

    def recovery_info(self) -> dict:
        return self._command("recovery_info")

    def mark_recovered(self) -> bool:
        return self._command("mark_recovered")

    @property
    def pid(self) -> int | None:
        return self._proc.pid

    def alive(self) -> bool:
        return self._proc.is_alive()

    def kill(self) -> None:
        """SIGKILL — no flush, no atexit, no goodbye. The journal tail
        is whatever the kernel had; that is the drill."""
        if self._proc.pid is not None and self._proc.is_alive():
            os.kill(self._proc.pid, signal.SIGKILL)
        self._proc.join(timeout=30.0)
        self._parent.close()

    def stop(self) -> None:
        """Graceful shutdown (clean-exit control arm of the drill)."""
        if not self._proc.is_alive():
            self._parent.close()
            return
        try:
            self._command("stop")
        except (OSError, EOFError, TimeoutError):
            pass
        self._proc.join(timeout=30.0)
        if self._proc.is_alive():  # pragma: no cover — stuck child
            self.kill()
        else:
            self._parent.close()

    def __enter__(self) -> "Crashbox":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._proc.is_alive():
            self.kill()
