#!/bin/bash
# Poll the TPU tunnel; on first UP, fire the measurement agenda once.
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 240 python -c "import jax; d=jax.devices()[0]; print(d.platform)" 2>/dev/null)
  echo "$ts ${out:-DOWN}" >> /root/repo/.tpu_poll.log
  if [ "$out" = "tpu" ]; then
    if [ ! -f /root/repo/.tpu_agenda_started ]; then
      touch /root/repo/.tpu_agenda_started
      echo "$ts TPU UP - starting agenda" >> /root/repo/.tpu_poll.log
      /root/repo/.tpu_agenda.sh &
    fi
  fi
  sleep 120
done
