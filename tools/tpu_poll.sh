#!/bin/bash
# Poll the TPU tunnel; every time it is UP, (re)fire the measurement agenda
# until the agenda has completed end-to-end. Unlike the round-3 one-shot,
# this RE-ARMS: a tunnel window that dies mid-agenda leaves per-step markers
# behind (.tpu_agenda_step.*.done) and the next window resumes from the
# first incomplete step. The agenda's step 1 (bench.py) writes
# BENCH_TPU_CERT.json on a successful on-chip run — the certification
# artifact bench.py's round-end capture falls back to when the tunnel is
# down at that moment.
#
# Invokes the COMMITTED tools/tpu_agenda.sh next to this script (round-3
# advisor finding: the old poller launched an untracked dotfile that does
# not exist on a fresh checkout).
REPO="$(cd "$(dirname "$0")/.." && pwd)"
AGENDA="$REPO/tools/tpu_agenda.sh"
LOG="$REPO/.tpu_poll.log"
PIDFILE="$REPO/.tpu_agenda.pid"
DONE="$REPO/.tpu_agenda.all.done"

while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 240 python -c "import jax; d=jax.devices()[0]; print(d.platform)" 2>/dev/null)
  echo "$ts ${out:-DOWN}" >> "$LOG"
  if [ "$out" = "tpu" ] && [ ! -f "$DONE" ]; then
    # The pid must be alive AND actually be the agenda: a recycled pid
    # (observed round 5: the pidfile held a pid that a later poller
    # instance had been assigned) would otherwise block firing forever.
    apid=$(cat "$PIDFILE" 2>/dev/null)
    if [ -n "$apid" ] && kill -0 "$apid" 2>/dev/null && \
       grep -q tpu_agenda "/proc/$apid/cmdline" 2>/dev/null; then
      : # agenda already in progress
    else
      echo "$ts TPU UP - starting/resuming agenda" >> "$LOG"
      bash "$AGENDA" &
      echo $! > "$PIDFILE"
    fi
  fi
  sleep 120
done
