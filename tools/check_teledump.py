#!/usr/bin/env python
"""check_teledump — validate a teledump document against the telemetry
wire schema (`pmdfc-telemetry-v1`) or a flight-recorder dump against
the flight schema (`pmdfc-flight-v1`/`-v2`).

The CI `telemetry_smoke` step (tools/tpu_agenda.sh) runs the net smoke
with telemetry on, pulls a snapshot via `tools/teledump.py --out`, and
diffs it against this schema: counters are ints, gauges numeric,
histograms carry the full quantile block, and the sections a monitoring
consumer depends on are all present. Exit 0 = conformant.

Flight dumps dispatch automatically (a `rung` + flight `schema` key):
v2 additionally pins the SPAN TREE record shape — 32-bit span/parent
ids, monotonic-ns start<=end, bool ok — and the clock/recompile record
kinds tracetool and the SLO watchdog consume. Old v1 dumps (no tree
fields) still parse: the v2 requirements apply only to documents that
DECLARE v2.

    python tools/check_teledump.py snap.json
    python tools/check_teledump.py flight_get_00001.json
    python tools/check_teledump.py --live HOST PORT [--page-words N]

Importable: `check(doc)` / `check_flight(doc) -> list[str]` return the
violations (empty = conformant) — tests/test_telemetry.py and
tests/test_tracing.py pin the schemas through them.
"""

from __future__ import annotations

import argparse
import json
import numbers
import sys

_HIST_KEYS = ("count", "sum", "max", "p50", "p95", "p99")


def check(doc: dict) -> list[str]:
    """Schema violations in a teledump document (server_stats pull or a
    bare `{"telemetry": ...}` local dump)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    snap = doc.get("telemetry")
    if snap is None:
        return ["missing 'telemetry' section (server running with "
                "PMDFC_TELEMETRY=off?)"]
    if not isinstance(snap, dict):
        return ["'telemetry' is not an object"]
    if snap.get("schema") != "pmdfc-telemetry-v1":
        errs.append(f"schema is {snap.get('schema')!r}, expected "
                    "'pmdfc-telemetry-v1'")
    if not isinstance(snap.get("enabled"), bool):
        errs.append("'enabled' missing or not a bool")
    for section, want in (("counters", numbers.Integral),
                          ("gauges", numbers.Real)):
        block = snap.get(section)
        if not isinstance(block, dict):
            errs.append(f"'{section}' missing or not an object")
            continue
        for name, v in block.items():
            if not isinstance(name, str) or not name:
                errs.append(f"{section}: non-string metric name {name!r}")
            if not isinstance(v, want) or isinstance(v, bool):
                errs.append(f"{section}.{name}: {v!r} is not "
                            f"{want.__name__}")
    hists = snap.get("histograms")
    if not isinstance(hists, dict):
        errs.append("'histograms' missing or not an object")
    else:
        for name, h in hists.items():
            if not isinstance(h, dict):
                errs.append(f"histograms.{name}: not an object")
                continue
            for k in _HIST_KEYS:
                v = h.get(k)
                if not isinstance(v, numbers.Real) or isinstance(v, bool):
                    errs.append(f"histograms.{name}.{k}: {v!r} is not "
                                "numeric")
            c = h.get("count")
            if isinstance(c, numbers.Real) and c < 0:
                errs.append(f"histograms.{name}.count: negative")
    ring = snap.get("ring")
    if not isinstance(ring, dict) or not isinstance(
            ring.get("len"), numbers.Integral) or not isinstance(
            ring.get("capacity"), numbers.Integral):
        errs.append("'ring' missing or malformed (needs int len/capacity)")
    return errs


_FLIGHT_SCHEMAS = ("pmdfc-flight-v1", "pmdfc-flight-v2")


def _check_span_v2(i: int, rec: dict) -> list[str]:
    errs = []
    for k in ("span", "parent"):
        v = rec.get(k)
        if not isinstance(v, numbers.Integral) or isinstance(v, bool) \
                or not (0 <= v <= 0xFFFFFFFF):
            errs.append(f"records[{i}].{k}: {v!r} is not a 32-bit id")
    if not isinstance(rec.get("ok"), bool):
        errs.append(f"records[{i}].ok: missing or not a bool")
    t0, t1 = rec.get("t0_ns"), rec.get("t1_ns")
    if t0 is not None or t1 is not None:
        for k, v in (("t0_ns", t0), ("t1_ns", t1)):
            if not isinstance(v, numbers.Integral) or isinstance(v, bool):
                errs.append(f"records[{i}].{k}: {v!r} is not an int")
        if isinstance(t0, numbers.Integral) \
                and isinstance(t1, numbers.Integral) and t1 < t0:
            errs.append(f"records[{i}]: t1_ns < t0_ns")
    return errs


def check_flight(doc: dict) -> list[str]:
    """Schema violations in a flight-recorder dump. v1 documents are
    held only to the v1 shape (rung/detail/telemetry/records); the span
    tree + clock record requirements bind documents declaring v2."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    schema = doc.get("schema")
    if schema not in _FLIGHT_SCHEMAS:
        errs.append(f"schema is {schema!r}, expected one of "
                    f"{_FLIGHT_SCHEMAS}")
    if not isinstance(doc.get("rung"), str) or not doc.get("rung"):
        errs.append("'rung' missing or not a string")
    if not isinstance(doc.get("detail"), dict):
        errs.append("'detail' missing or not an object")
    errs.extend(check({"telemetry": doc.get("telemetry")}))
    records = doc.get("records")
    if not isinstance(records, list):
        return errs + ["'records' missing or not a list"]
    v2 = schema == "pmdfc-flight-v2"
    for i, rec in enumerate(records):
        if not isinstance(rec, dict) or not isinstance(
                rec.get("kind"), str):
            errs.append(f"records[{i}]: not an object with a 'kind'")
            continue
        if not v2:
            continue
        if rec["kind"] == "span" and "span" in rec:
            errs.extend(_check_span_v2(i, rec))
        elif rec["kind"] == "clock":
            for k in ("offset_ns", "rtt_ns"):
                if not isinstance(rec.get(k), numbers.Integral):
                    errs.append(f"records[{i}].{k}: missing or non-int")
        elif rec["kind"] == "recompile":
            if not isinstance(rec.get("program"), str):
                errs.append(f"records[{i}].program: missing or non-str")
    # the SLO watchdog's breach dumps must stay attributable
    if v2 and doc.get("rung") == "slo_breach":
        det = doc.get("detail") or {}
        for k in ("target", "stage", "metric", "threshold", "value"):
            if k not in det:
                errs.append(f"slo_breach detail lacks {k!r}")
    return errs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", nargs="?", help="teledump JSON file")
    p.add_argument("--live", nargs=2, metavar=("HOST", "PORT"),
                   help="pull from a live server instead of a file")
    p.add_argument("--page-words", type=int, default=1024)
    args = p.parse_args(argv)

    if args.live:
        from pmdfc_tpu.runtime.net import TcpBackend

        with TcpBackend(args.live[0], int(args.live[1]),
                        page_words=args.page_words,
                        keepalive_s=None) as be:
            doc = be.server_stats()
    elif args.path:
        with open(args.path) as f:
            doc = json.load(f)
    else:
        p.error("need a PATH or --live HOST PORT")

    is_flight = (isinstance(doc, dict) and "rung" in doc
                 and str(doc.get("schema", "")).startswith("pmdfc-flight"))
    errs = check_flight(doc) if is_flight else check(doc)
    if errs:
        for e in errs:
            print(f"[check_teledump] FAIL: {e}", file=sys.stderr)
        return 1
    snap = doc["telemetry"]
    kind = (f"flight dump ({doc['schema']}, rung {doc['rung']}, "
            f"{len(doc['records'])} records)" if is_flight
            else "telemetry snapshot")
    print(f"[check_teledump] OK: {kind} — {len(snap['counters'])} "
          f"counters, {len(snap['gauges'])} gauges, "
          f"{len(snap['histograms'])} histograms, "
          f"ring {snap['ring']['len']}/{snap['ring']['capacity']}")
    return 0


if __name__ == "__main__":
    import os

    # runnable as `python tools/check_teledump.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
