#!/usr/bin/env python
"""check_teledump — validate a teledump document against the telemetry
wire schema (`pmdfc-telemetry-v1`).

The CI `telemetry_smoke` step (tools/tpu_agenda.sh) runs the net smoke
with telemetry on, pulls a snapshot via `tools/teledump.py --out`, and
diffs it against this schema: counters are ints, gauges numeric,
histograms carry the full quantile block, and the sections a monitoring
consumer depends on are all present. Exit 0 = conformant.

    python tools/check_teledump.py snap.json
    python tools/check_teledump.py --live HOST PORT [--page-words N]

Importable: `check(doc) -> list[str]` returns the violations (empty =
conformant) — tests/test_telemetry.py pins the schema through it.
"""

from __future__ import annotations

import argparse
import json
import numbers
import sys

_HIST_KEYS = ("count", "sum", "max", "p50", "p95", "p99")


def check(doc: dict) -> list[str]:
    """Schema violations in a teledump document (server_stats pull or a
    bare `{"telemetry": ...}` local dump)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    snap = doc.get("telemetry")
    if snap is None:
        return ["missing 'telemetry' section (server running with "
                "PMDFC_TELEMETRY=off?)"]
    if not isinstance(snap, dict):
        return ["'telemetry' is not an object"]
    if snap.get("schema") != "pmdfc-telemetry-v1":
        errs.append(f"schema is {snap.get('schema')!r}, expected "
                    "'pmdfc-telemetry-v1'")
    if not isinstance(snap.get("enabled"), bool):
        errs.append("'enabled' missing or not a bool")
    for section, want in (("counters", numbers.Integral),
                          ("gauges", numbers.Real)):
        block = snap.get(section)
        if not isinstance(block, dict):
            errs.append(f"'{section}' missing or not an object")
            continue
        for name, v in block.items():
            if not isinstance(name, str) or not name:
                errs.append(f"{section}: non-string metric name {name!r}")
            if not isinstance(v, want) or isinstance(v, bool):
                errs.append(f"{section}.{name}: {v!r} is not "
                            f"{want.__name__}")
    hists = snap.get("histograms")
    if not isinstance(hists, dict):
        errs.append("'histograms' missing or not an object")
    else:
        for name, h in hists.items():
            if not isinstance(h, dict):
                errs.append(f"histograms.{name}: not an object")
                continue
            for k in _HIST_KEYS:
                v = h.get(k)
                if not isinstance(v, numbers.Real) or isinstance(v, bool):
                    errs.append(f"histograms.{name}.{k}: {v!r} is not "
                                "numeric")
            c = h.get("count")
            if isinstance(c, numbers.Real) and c < 0:
                errs.append(f"histograms.{name}.count: negative")
    ring = snap.get("ring")
    if not isinstance(ring, dict) or not isinstance(
            ring.get("len"), numbers.Integral) or not isinstance(
            ring.get("capacity"), numbers.Integral):
        errs.append("'ring' missing or malformed (needs int len/capacity)")
    return errs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", nargs="?", help="teledump JSON file")
    p.add_argument("--live", nargs=2, metavar=("HOST", "PORT"),
                   help="pull from a live server instead of a file")
    p.add_argument("--page-words", type=int, default=1024)
    args = p.parse_args(argv)

    if args.live:
        from pmdfc_tpu.runtime.net import TcpBackend

        with TcpBackend(args.live[0], int(args.live[1]),
                        page_words=args.page_words,
                        keepalive_s=None) as be:
            doc = be.server_stats()
    elif args.path:
        with open(args.path) as f:
            doc = json.load(f)
    else:
        p.error("need a PATH or --live HOST PORT")

    errs = check(doc)
    if errs:
        for e in errs:
            print(f"[check_teledump] FAIL: {e}", file=sys.stderr)
        return 1
    snap = doc["telemetry"]
    print(f"[check_teledump] OK: {len(snap['counters'])} counters, "
          f"{len(snap['gauges'])} gauges, "
          f"{len(snap['histograms'])} histograms, "
          f"ring {snap['ring']['len']}/{snap['ring']['capacity']}")
    return 0


if __name__ == "__main__":
    import os

    # runnable as `python tools/check_teledump.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
