#!/usr/bin/env python
"""check_teledump — validate a teledump document against the telemetry
wire schema (`pmdfc-telemetry-v1`/`-v2`/`-v3`) or a flight-recorder
dump against the flight schema (`pmdfc-flight-v1`/`-v2`).

The CI `telemetry_smoke` step (tools/tpu_agenda.sh) runs the net smoke
with telemetry on, pulls a snapshot via `tools/teledump.py --out`, and
diffs it against this schema: counters are ints, gauges numeric,
histograms carry the full quantile block, and the sections a monitoring
consumer depends on are all present. Exit 0 = conformant.

v2 documents additionally pin the workload-X-ray surfaces:

- the windowed SERIES block (`runtime/timeseries.py` window shape:
  per-window `t`/`dt_s` plus counter deltas, gauge samples, and
  histogram window quantiles),
- the WORKLOAD sketches (working-set KMV estimate bounds + count-min
  heat shape, `runtime/workload.py`),
- the MISS-CAUSE SUM invariant: wherever the document carries KV
  counters (top level, and per shard in `shard_report.stats`),
  `misses == Σ miss_*` must reconcile bit-exactly,
- the MIGRATION counters (elastic membership, `cluster/migrate.py`):
  `moved_pages == Σ per-transition-kind moves`, a sane lag gauge, and
  zero lag whenever no transition window is open,
- the ADMISSION counters (TinyLFU gate on the tiered store, `tier.py`):
  the four `admit_*` lanes travel together with the live threshold,
  `admit_ghost_override <= ghost_readmits`, and per-shard lanes sum
  exactly to the top-level fold.

Old v1 documents (no series/workload/causes) still parse: the v2
requirements bind only documents that declare v2 / carry the sections.

v3 documents additionally carry the device-time PROFILE block
(`runtime/profiler.py`): the phase x program x shard attribution
table, per-shard device-time lanes agreeing with `n_shards`, the
windowed imbalance gauge pinned to [1, n_shards] (or 0 before a
window completes), and the static `cost.*` captures. The block and
the v3 declaration travel together — additive over v2, so v2 docs
(profiler off) still parse unchanged.

Flight dumps dispatch automatically (a `rung` + flight `schema` key):
v2 additionally pins the SPAN TREE record shape — 32-bit span/parent
ids, monotonic-ns start<=end, bool ok — and the clock/recompile record
kinds tracetool and the SLO watchdog consume, plus the optional
windowed `series` tail.

    python tools/check_teledump.py snap.json
    python tools/check_teledump.py flight_get_00001.json
    python tools/check_teledump.py --live HOST PORT [--page-words N]

Importable: `check(doc)` / `check_flight(doc) -> list[str]` return the
violations (empty = conformant) — tests/test_telemetry.py,
tests/test_tracing.py, and tests/test_xray.py pin the schemas through
them.
"""

from __future__ import annotations

import argparse
import json
import numbers
import sys

_HIST_KEYS = ("count", "sum", "max", "p50", "p95", "p99")
_TELEMETRY_SCHEMAS = ("pmdfc-telemetry-v1", "pmdfc-telemetry-v2",
                      "pmdfc-telemetry-v3")
_MISS_CAUSES = ("miss_cold", "miss_evicted", "miss_parked",
                "miss_stale", "miss_digest", "miss_routed",
                "miss_recovering", "miss_shed", "miss_quarantined",
                "miss_deadline")


def _num(v) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def check_series(series) -> list[str]:
    """Violations in a `series` block (the windowed ring's wire form)."""
    errs: list[str] = []
    if not isinstance(series, dict):
        return ["'series' is not an object"]
    for k in ("interval_s", "capacity"):
        if not _num(series.get(k)):
            errs.append(f"series.{k}: missing or non-numeric")
    windows = series.get("windows")
    if not isinstance(windows, list):
        return errs + ["series.windows missing or not a list"]
    for i, w in enumerate(windows):
        if not isinstance(w, dict):
            errs.append(f"series.windows[{i}]: not an object")
            continue
        for k in ("t", "dt_s"):
            if not _num(w.get(k)):
                errs.append(f"series.windows[{i}].{k}: non-numeric")
        if _num(w.get("dt_s")) and w["dt_s"] < 0:
            errs.append(f"series.windows[{i}].dt_s: negative")
        for sec, want in (("counters", numbers.Integral),
                          ("gauges", numbers.Real)):
            blk = w.get(sec)
            if not isinstance(blk, dict):
                errs.append(f"series.windows[{i}].{sec}: missing")
                continue
            for name, v in blk.items():
                if not isinstance(v, want) or isinstance(v, bool):
                    errs.append(
                        f"series.windows[{i}].{sec}.{name}: {v!r}")
        hists = w.get("hists")
        if not isinstance(hists, dict):
            errs.append(f"series.windows[{i}].hists: missing")
            continue
        for name, h in hists.items():
            for k in ("count", "p50", "p95", "p99"):
                if not _num(h.get(k)):
                    errs.append(
                        f"series.windows[{i}].hists.{name}.{k}: "
                        f"{h.get(k)!r}")
    return errs


def check_workload(wl) -> list[str]:
    """Violations in a `workload` block (sketch shape + bounds)."""
    errs: list[str] = []
    if not isinstance(wl, dict):
        return ["'workload' is not an object"]
    ops = wl.get("ops")
    ws = wl.get("working_set")
    if not _num(ops) or ops < 0:
        errs.append(f"workload.ops: {ops!r}")
    if not _num(ws) or ws < 0:
        errs.append(f"workload.working_set: {ws!r}")
    # a KMV estimate can never exceed the ops that fed it (bounds gate)
    if _num(ops) and _num(ws) and ws > max(ops, 1) * 1.5:
        errs.append(f"workload.working_set {ws} exceeds ops {ops}")
    win = wl.get("window")
    if not isinstance(win, dict) or not _num(win.get("working_set")) \
            or not _num(win.get("dt_s")):
        errs.append("workload.window: missing or malformed")
    heat = wl.get("heat")
    if not isinstance(heat, dict):
        return errs + ["workload.heat: missing"]
    for k in ("depth", "width", "total"):
        if not isinstance(heat.get(k), numbers.Integral) \
                or heat.get(k) < 0:
            errs.append(f"workload.heat.{k}: {heat.get(k)!r}")
    skew = heat.get("skew")
    if not _num(skew) or not (0.0 <= skew <= 1.0):
        errs.append(f"workload.heat.skew: {skew!r} not in [0, 1]")
    top = heat.get("top")
    if not isinstance(top, list):
        errs.append("workload.heat.top: missing or not a list")
    else:
        for i, row in enumerate(top):
            if (not isinstance(row, list) or len(row) != 3
                    or not all(_num(x) for x in row)
                    or not (0.0 <= row[2] <= 1.0)):
                errs.append(f"workload.heat.top[{i}]: {row!r}")
    return errs


def check_causes(doc: dict) -> list[str]:
    """The miss-cause sum invariant, everywhere the document carries KV
    counters: top level and per shard in `shard_report.stats`."""
    errs: list[str] = []
    if all(k in doc for k in ("misses", *_MISS_CAUSES)):
        total = sum(int(doc[k]) for k in _MISS_CAUSES)
        if int(doc["misses"]) != total:
            errs.append(f"miss-cause drift: misses={doc['misses']} but "
                        f"Σ causes={total}")
    st = (doc.get("shard_report") or {}).get("stats") or {}
    if all(k in st for k in ("misses", *_MISS_CAUSES)):
        for i, m in enumerate(st["misses"]):
            total = sum(int(st[k][i]) for k in _MISS_CAUSES)
            if int(m) != total:
                errs.append(f"shard {i} miss-cause drift: misses={m} "
                            f"but Σ causes={total}")
    return errs


_ADMIT_LANES = ("admit_denied", "admit_victim_kept",
                "admit_ghost_override", "admit_age_epochs")


def check_admission(doc: dict) -> list[str]:
    """TinyLFU admission-gate pins, bound when the document carries the
    admission counters (a tiered server with the gate on — PMDFC_ADMIT
    =off ships no admission keys at all, which tests pin; this checker
    binds what is present): the four lanes travel together as
    non-negative integers alongside the live `admit_threshold`,
    `admit_ghost_override` never exceeds `ghost_readmits` (an override
    IS a ghost readmission the frequency evidence alone would have
    refused — a strict subset), and when a `shard_report` rides along
    its per-shard admission lanes sum exactly to the top-level counters
    (admission lanes live only in the device tier vector, so no host
    plane can fork the fold). The `misses == Σ causes` invariant is
    re-asserted by `check_causes` on every document, admission on or
    off."""
    errs: list[str] = []
    if "admit_denied" not in doc:
        return errs
    for k in _ADMIT_LANES:
        v = doc.get(k)
        if not isinstance(v, numbers.Integral) or isinstance(v, bool) \
                or v < 0:
            errs.append(f"{k}: {v!r} is not a non-negative integer "
                        "(admission lanes travel together)")
    th = doc.get("admit_threshold")
    if not isinstance(th, numbers.Integral) or isinstance(th, bool) \
            or th < 0:
        errs.append(f"admit_threshold: {th!r} missing or negative")
    gr = doc.get("ghost_readmits")
    ov = doc.get("admit_ghost_override")
    if isinstance(gr, numbers.Integral) and isinstance(ov, numbers.Integral) \
            and ov > gr:
        errs.append(f"admission drift: admit_ghost_override={ov} > "
                    f"ghost_readmits={gr} (overrides are a subset)")
    tier = (doc.get("shard_report") or {}).get("tier") or {}
    for k in _ADMIT_LANES:
        lanes = tier.get(k)
        if lanes is None:
            continue
        if not isinstance(lanes, list) or not all(
                isinstance(x, numbers.Integral) and not isinstance(x, bool)
                and x >= 0 for x in lanes):
            errs.append(f"shard_report.tier.{k}: {lanes!r}")
            continue
        if isinstance(doc.get(k), numbers.Integral) \
                and sum(lanes) != int(doc[k]):
            errs.append(f"admission drift: Σ shard {k}={sum(lanes)} != "
                        f"top-level {doc[k]}")
    return errs


def check_fastpath(snap: dict) -> list[str]:
    """One-sided fast-lane pins, bound wherever a scope reports the
    fast-path counters: every FASTREAD lane is exactly one of hit or
    stale, and total reads are DERIVED as `hits + stale` (a stored
    reads counter would race the two lanes under live pulls). The pin:
    both lanes travel together, the scope gauges its directory epoch,
    and any producer that DOES store a reads counter must agree with
    the lanes bit-exactly."""
    errs: list[str] = []
    ctr = snap.get("counters")
    gauges = snap.get("gauges")
    if not isinstance(ctr, dict) or not isinstance(gauges, dict):
        return errs  # the section checks in check() already flag this
    for name, hits in list(ctr.items()):
        if not name.endswith(".fastpath_hits"):
            continue
        scope = name[:-len("fastpath_hits")]
        stale = ctr.get(scope + "fastpath_stale")
        if stale is None:
            errs.append(f"{scope}: fastpath_hits without its stale lane")
            continue
        reads = ctr.get(scope + "fastpath_reads")
        if reads is not None and int(hits) + int(stale) != int(reads):
            errs.append(f"{scope}: fast-lane drift — hits={hits} + "
                        f"stale={stale} != reads={reads}")
        ep = gauges.get(scope + "dir_epoch")
        if not isinstance(ep, numbers.Real) or isinstance(ep, bool) \
                or ep < 0:
            errs.append(f"{scope}: dir_epoch gauge missing or negative "
                        f"({ep!r})")
    return errs


def check_migration(snap: dict) -> list[str]:
    """Elastic-membership pins, bound wherever a scope reports the
    live-migration counters (`cluster/migrate.py`): the total
    `moved_pages` must equal the sum of its per-transition-kind lanes
    (join/leave/replace — pages can only move inside a transition of
    exactly one kind), the `lag` gauge must be present and non-negative
    (the dual-read window's backlog), and a settled engine
    (`active == 0`) must report zero lag — a nonzero lag with no open
    window means the transition bookkeeping leaked."""
    errs: list[str] = []
    ctr = snap.get("counters")
    gauges = snap.get("gauges")
    if not isinstance(ctr, dict) or not isinstance(gauges, dict):
        return errs  # the section checks in check() already flag this
    for name, moved in list(ctr.items()):
        if not name.endswith(".moved_pages"):
            continue
        scope = name[:-len("moved_pages")]
        lanes = {k: ctr.get(f"{scope}moved_{k}")
                 for k in ("join", "leave", "replace")}
        missing = [k for k, v in lanes.items() if v is None]
        if missing:
            errs.append(f"{scope}: moved_pages without per-kind "
                        f"lane(s) {missing}")
            continue
        total = sum(int(v) for v in lanes.values())
        if int(moved) != total:
            errs.append(f"{scope}: migration drift — moved_pages="
                        f"{moved} != Σ per-transition moves={total}")
        lag = gauges.get(scope + "lag")
        if not _num(lag) or lag < 0:
            errs.append(f"{scope}: lag gauge missing or negative "
                        f"({lag!r})")
        active = gauges.get(scope + "active")
        if active not in (0, 1):
            errs.append(f"{scope}: active gauge {active!r} not in "
                        "{0, 1}")
        if active == 0 and _num(lag) and lag != 0:
            errs.append(f"{scope}: settled engine (active=0) reports "
                        f"lag={lag}")
    return errs


def check_autotune(snap: dict) -> list[str]:
    """Closed-loop controller pins (`runtime/autotune.py`), bound
    wherever a scope reports knob gauges (the scope exists IFF the
    controller is enabled — PMDFC_AUTOTUNE=off registers nothing, which
    tests pin; this checker binds what is present): every `knob_<name>`
    gauge ships its `_lo`/`_hi` envelope siblings and sits INSIDE them
    (a knob outside its declared bounds means the clamp was bypassed),
    the `decisions` counter dominates `reverts` (a revert IS knob
    moves), and the `frozen` gauge is a 0/1 flag."""
    errs: list[str] = []
    gauges = snap.get("gauges")
    ctr = snap.get("counters")
    if not isinstance(gauges, dict) or not isinstance(ctr, dict):
        return errs  # the section checks in check() already flag this
    scopes = set()
    for name, v in list(gauges.items()):
        if ".knob_" not in name or name.endswith(("_lo", "_hi")):
            continue
        # discovery keys on the VALUE gauge (teletop's filter), so a
        # knob shipped without an envelope sibling is an ERROR here —
        # keying on `_hi` made a missing `_hi` render the whole knob
        # invisible to every pin, the exact bypassed-clamp shape this
        # checker exists to catch
        scopes.add(name.split(".knob_", 1)[0])
        lo = gauges.get(name + "_lo")
        hi = gauges.get(name + "_hi")
        if lo is None or hi is None:
            errs.append(f"{name}: knob gauge missing its lo/hi "
                        "envelope siblings")
        elif not (lo <= v <= hi):
            errs.append(f"{name}: knob value {v} outside its declared "
                        f"envelope [{lo}, {hi}]")
    for name in list(gauges):
        # the symmetric orphan: an envelope gauge whose knob value
        # gauge is absent
        if ".knob_" in name and name.endswith(("_lo", "_hi")) \
                and gauges.get(name[:-3]) is None:
            errs.append(f"{name}: envelope gauge without its knob "
                        "value gauge")
    for s in sorted(scopes):
        d = ctr.get(f"{s}.decisions")
        r = ctr.get(f"{s}.reverts")
        if d is None or r is None:
            errs.append(f"{s}: knob gauges without decisions/reverts "
                        "counters")
        elif int(d) < int(r):
            errs.append(f"{s}: controller drift — decisions={d} < "
                        f"reverts={r}")
        fz = gauges.get(f"{s}.frozen")
        if fz not in (0, 1):
            errs.append(f"{s}: frozen gauge {fz!r} not in {{0, 1}}")
    return errs


_JOURNAL_COUNTERS = ("syncs", "rotations", "replayed_records",
                     "truncated_tails")
_JOURNAL_GAUGES = ("depth_ops", "depth_bytes", "fsync_lag_ms", "segments")


def check_durability(snap: dict) -> list[str]:
    """Write-ahead-journal and warm-restart pins, bound wherever the
    scopes report (`runtime/journal.py` registers a `journal<N>` scope
    per instance; `KV.begin_recovering` the shared `recovery` scope —
    a server without durability ships neither, which tests pin; this
    checker binds what is present): the journal lanes travel together,
    the pending-depth gauge never exceeds the cumulative appends (a
    deeper-than-appended queue means the fsync ledger raced the
    writer), and completed recoveries never exceed warm restarts (a
    completion IS a warm restart reaching caught-up)."""
    errs: list[str] = []
    ctr = snap.get("counters")
    gauges = snap.get("gauges")
    if not isinstance(ctr, dict) or not isinstance(gauges, dict):
        return errs  # the section checks in check() already flag this
    for name, appends in list(ctr.items()):
        if not name.endswith(".appends"):
            continue
        scope = name[:-len("appends")]
        if not scope.startswith("journal"):
            continue
        for k in _JOURNAL_COUNTERS:
            if ctr.get(scope + k) is None:
                errs.append(f"{scope}: appends without its {k} lane "
                            "(journal lanes travel together)")
        for k in _JOURNAL_GAUGES:
            v = gauges.get(scope + k)
            if not isinstance(v, numbers.Real) or isinstance(v, bool) \
                    or v < 0:
                errs.append(f"{scope}{k}: gauge missing or negative "
                            f"({v!r})")
        depth = gauges.get(scope + "depth_ops")
        if isinstance(depth, numbers.Real) and depth > int(appends):
            errs.append(f"{scope}: durability drift — pending depth_ops="
                        f"{depth} exceeds appends={appends}")
    wr = ctr.get("recovery.warm_restarts")
    done = ctr.get("recovery.completed")
    if wr is not None or done is not None:
        if wr is None or done is None:
            errs.append("recovery: warm_restarts/completed must travel "
                        "together")
        elif int(done) > int(wr):
            errs.append(f"recovery drift: completed={done} > "
                        f"warm_restarts={wr}")
        flag = gauges.get("recovery.recovering")
        if flag not in (0, 1):
            errs.append(f"recovery.recovering gauge {flag!r} not in "
                        "{0, 1}")
    return errs


def check_replica(doc: dict) -> list[str]:
    """Device-replica plane pins, bound when the document carries the
    `replica` block (a 2-D serving mesh behind the endpoint): the three
    per-lane attribution lists agree on the advertised lane count and
    every count is a non-negative integer — a negative lane would mean
    the host fold raced the device attribution."""
    errs: list[str] = []
    rep = doc.get("replica")
    if rep is None:
        return errs
    if not isinstance(rep, dict):
        return ["'replica' is not an object"]
    n = rep.get("n_replicas")
    if not isinstance(n, int) or isinstance(n, bool) or n < 2:
        return [f"replica.n_replicas={n!r}, expected int >= 2"]
    for k in ("served", "digest_refused", "repaired"):
        lanes = rep.get(k)
        if not isinstance(lanes, list) or len(lanes) != n:
            errs.append(f"replica.{k}: expected {n} lanes, got {lanes!r}")
            continue
        for i, x in enumerate(lanes):
            if not isinstance(x, numbers.Integral) \
                    or isinstance(x, bool) or x < 0:
                errs.append(f"replica.{k}[{i}]: {x!r} is not a "
                            "non-negative integer")
    return errs


_QOS_LANES = ("staged", "shed_edge", "shed_ladder",
              "shed_gets", "shed_puts")


def check_qos(snap: dict) -> list[str]:
    """Multi-tenant QoS pins (`runtime/qos.py`), bound wherever a
    tenant scope reports (scopes exist IFF the plane is on —
    PMDFC_QOS=off registers nothing, which tests pin; this checker
    binds what is present): the per-tenant lanes travel together as
    non-negative integers, every op the edge saw either staged or was
    edge-shed (`ops == staged + shed_edge` — conservation, nothing
    vanishes unattributed), the ladder can only shed what actually
    staged (`shed_ladder <= staged`, the shed ⊆ staged pin), the two
    shed sources decompose exactly into the per-verb shed lanes
    (`shed_edge + shed_ladder == shed_gets + shed_puts`), and the
    declared weight/rate gauges ride along (weight >= 1 — a zero-weight
    lane could never drain; rate >= 0, 0 = unlimited)."""
    errs: list[str] = []
    ctr = snap.get("counters")
    gauges = snap.get("gauges")
    if not isinstance(ctr, dict) or not isinstance(gauges, dict):
        return errs  # the section checks in check() already flag this
    for name, ops in list(ctr.items()):
        if ".qos.t" not in name or not name.endswith(".ops"):
            continue
        scope = name[:-len("ops")]
        lanes = {k: ctr.get(scope + k) for k in _QOS_LANES}
        missing = [k for k, v in lanes.items() if v is None]
        if missing:
            errs.append(f"{scope}: ops without lane(s) {missing} "
                        "(tenant lanes travel together)")
            continue
        bad = [k for k, v in lanes.items()
               if not isinstance(v, numbers.Integral)
               or isinstance(v, bool) or v < 0]
        if bad:
            errs.append(f"{scope}: non-integer/negative lane(s) {bad}")
            continue
        if int(lanes["staged"]) + int(lanes["shed_edge"]) != int(ops):
            errs.append(
                f"{scope}: qos drift — staged={lanes['staged']} + "
                f"shed_edge={lanes['shed_edge']} != ops={ops}")
        if int(lanes["shed_ladder"]) > int(lanes["staged"]):
            errs.append(
                f"{scope}: qos drift — shed_ladder={lanes['shed_ladder']}"
                f" exceeds staged={lanes['staged']} (shed ⊆ staged)")
        if int(lanes["shed_edge"]) + int(lanes["shed_ladder"]) \
                != int(lanes["shed_gets"]) + int(lanes["shed_puts"]):
            errs.append(
                f"{scope}: qos drift — shed_edge+shed_ladder="
                f"{int(lanes['shed_edge']) + int(lanes['shed_ladder'])} "
                f"!= shed_gets+shed_puts="
                f"{int(lanes['shed_gets']) + int(lanes['shed_puts'])}")
        w = gauges.get(scope + "weight")
        if not _num(w) or w < 1:
            errs.append(f"{scope}: weight gauge missing or < 1 ({w!r})")
        r = gauges.get(scope + "rate")
        if not _num(r) or r < 0:
            errs.append(f"{scope}: rate gauge missing or negative "
                        f"({r!r})")
    return errs


_CONTAIN_LANES = ("nacks_sent", "poison_refused", "poison_ops",
                  "bisect_launches", "bisect_failures", "deadline_shed")


def check_containment(snap: dict) -> list[str]:
    """Blast-radius containment pins (`runtime/net.py` NACK/bisection,
    `runtime/failure.py` ShardQuarantine), bound wherever the scopes
    report (PMDFC_CONTAINMENT=off still registers the net counters —
    they just never move): the six containment lanes travel together on
    every `net` scope as non-negative integers; each bisection split
    launches exactly its two halves (`bisect_launches == 2 *
    bisect_failures` — a drifted ratio means a relaunch escaped its
    bound accounting); a quarantine scope can only re-admit shards that
    tripped (`readmits <= trips`) and only replay invalidations that
    were journaled (`replayed_invals <= journaled_invals`)."""
    errs: list[str] = []
    ctr = snap.get("counters")
    if not isinstance(ctr, dict):
        return errs  # the section checks in check() already flag this
    for name in list(ctr):
        if name.endswith(".net.nacks_sent") or name == "net.nacks_sent":
            scope = name[:-len("nacks_sent")]
            lanes = {k: ctr.get(scope + k) for k in _CONTAIN_LANES}
            missing = [k for k, v in lanes.items() if v is None]
            if missing:
                errs.append(f"{scope}: containment lane(s) {missing} "
                            "missing (lanes travel together)")
                continue
            bad = [k for k, v in lanes.items()
                   if not isinstance(v, numbers.Integral)
                   or isinstance(v, bool) or v < 0]
            if bad:
                errs.append(f"{scope}: non-integer/negative "
                            f"containment lane(s) {bad}")
                continue
            if int(lanes["bisect_launches"]) \
                    != 2 * int(lanes["bisect_failures"]):
                errs.append(
                    f"{scope}: bisect drift — launches="
                    f"{lanes['bisect_launches']} != 2 x failures="
                    f"{lanes['bisect_failures']} (each split launches "
                    "exactly its two halves)")
        if name.endswith(".quarantine.trips") \
                or name == "quarantine.trips":
            scope = name[:-len("trips")]
            trips = ctr.get(scope + "trips", 0)
            readmits = ctr.get(scope + "readmits", 0)
            if isinstance(readmits, numbers.Integral) \
                    and isinstance(trips, numbers.Integral) \
                    and int(readmits) > int(trips):
                errs.append(f"{scope}: readmits={readmits} exceeds "
                            f"trips={trips}")
            j = ctr.get(scope + "journaled_invals", 0)
            r = ctr.get(scope + "replayed_invals", 0)
            if isinstance(j, numbers.Integral) \
                    and isinstance(r, numbers.Integral) and int(r) > int(j):
                errs.append(f"{scope}: replayed_invals={r} exceeds "
                            f"journaled_invals={j}")
    return errs


def check_profile(snap: dict) -> list[str]:
    """Device-time profiler pins (`runtime/profiler.py`), bound when
    the snapshot carries a `profile` block — which is ALSO the v3
    declaration gate: a profile block rides only on documents declaring
    `pmdfc-telemetry-v3`, and a v3 declaration without the block means
    the sink detached mid-snapshot. Inside the block: the attribution
    rows carry (phase, program, shard >= -1, non-negative ops /
    device_us), the per-shard lane vectors agree with the advertised
    `n_shards`, the windowed imbalance gauge is either 0 (no window
    completed yet) or inside its algebraic range [1, n_shards] —
    max/mean over n non-negative lanes can land nowhere else — and any
    captured `cost.*` entries ship numeric flops/bytes pairs."""
    errs: list[str] = []
    prof = snap.get("profile")
    declared_v3 = snap.get("schema") == "pmdfc-telemetry-v3"
    if prof is None:
        if declared_v3:
            errs.append("v3 snapshot lacks the 'profile' block")
        return errs
    if not declared_v3:
        errs.append(f"profile block on a {snap.get('schema')!r} snapshot "
                    "(v3 declares the profiler sink)")
    if not isinstance(prof, dict):
        return errs + ["'profile' is not an object"]
    if prof.get("schema") != "pmdfc-prof-v1":
        errs.append(f"profile.schema is {prof.get('schema')!r}, "
                    "expected 'pmdfc-prof-v1'")
    for k in ("launches", "rows_dropped", "n_shards"):
        v = prof.get(k)
        if not isinstance(v, numbers.Integral) or isinstance(v, bool) \
                or v < 0:
            errs.append(f"profile.{k}: {v!r} is not a non-negative int")
    n = prof.get("n_shards") if isinstance(
        prof.get("n_shards"), numbers.Integral) else 0
    rows = prof.get("rows")
    if not isinstance(rows, list):
        errs.append("profile.rows: missing or not a list")
    else:
        for i, r in enumerate(rows):
            if not isinstance(r, dict):
                errs.append(f"profile.rows[{i}]: not an object")
                continue
            for k in ("phase", "program"):
                if not isinstance(r.get(k), str) or not r.get(k):
                    errs.append(f"profile.rows[{i}].{k}: {r.get(k)!r}")
            s = r.get("shard")
            if not isinstance(s, numbers.Integral) or isinstance(s, bool) \
                    or s < -1 or (n and s >= n):
                errs.append(f"profile.rows[{i}].shard: {s!r} outside "
                            f"[-1, {n})")
            for k in ("ops", "device_us"):
                v = r.get(k)
                if not _num(v) or v < 0:
                    errs.append(f"profile.rows[{i}].{k}: {v!r}")
    for k, want in (("shard_device_us", numbers.Real),
                    ("shard_ops", numbers.Integral)):
        lanes = prof.get(k)
        if not isinstance(lanes, list) or len(lanes) != n:
            errs.append(f"profile.{k}: expected {n} lanes, got {lanes!r}")
            continue
        for i, x in enumerate(lanes):
            if not isinstance(x, want) or isinstance(x, bool) or x < 0:
                errs.append(f"profile.{k}[{i}]: {x!r}")
    imb = prof.get("imbalance")
    if not _num(imb) or not (imb == 0 or (1.0 <= imb <= max(n, 1))):
        errs.append(f"profile.imbalance: {imb!r} not 0 or in "
                    f"[1, {max(n, 1)}]")
    cost = prof.get("cost")
    if not isinstance(cost, dict):
        errs.append("profile.cost: missing or not an object")
    else:
        for prog, c in cost.items():
            if not isinstance(c, dict) or not _num(c.get("flops")) \
                    or not _num(c.get("bytes")) or c["flops"] < 0 \
                    or c["bytes"] < 0:
                errs.append(f"profile.cost.{prog}: {c!r}")
    return errs


def check(doc: dict) -> list[str]:
    """Schema violations in a teledump document (server_stats pull or a
    bare `{"telemetry": ...}` local dump)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    snap = doc.get("telemetry")
    if snap is None:
        return ["missing 'telemetry' section (server running with "
                "PMDFC_TELEMETRY=off?)"]
    if not isinstance(snap, dict):
        return ["'telemetry' is not an object"]
    if snap.get("schema") not in _TELEMETRY_SCHEMAS:
        errs.append(f"schema is {snap.get('schema')!r}, expected one "
                    f"of {_TELEMETRY_SCHEMAS}")
    if not isinstance(snap.get("enabled"), bool):
        errs.append("'enabled' missing or not a bool")
    for section, want in (("counters", numbers.Integral),
                          ("gauges", numbers.Real)):
        block = snap.get(section)
        if not isinstance(block, dict):
            errs.append(f"'{section}' missing or not an object")
            continue
        for name, v in block.items():
            if not isinstance(name, str) or not name:
                errs.append(f"{section}: non-string metric name {name!r}")
            if not isinstance(v, want) or isinstance(v, bool):
                errs.append(f"{section}.{name}: {v!r} is not "
                            f"{want.__name__}")
    hists = snap.get("histograms")
    if not isinstance(hists, dict):
        errs.append("'histograms' missing or not an object")
    else:
        for name, h in hists.items():
            if not isinstance(h, dict):
                errs.append(f"histograms.{name}: not an object")
                continue
            for k in _HIST_KEYS:
                v = h.get(k)
                if not isinstance(v, numbers.Real) or isinstance(v, bool):
                    errs.append(f"histograms.{name}.{k}: {v!r} is not "
                                "numeric")
            c = h.get("count")
            if isinstance(c, numbers.Real) and c < 0:
                errs.append(f"histograms.{name}.count: negative")
    ring = snap.get("ring")
    if not isinstance(ring, dict) or not isinstance(
            ring.get("len"), numbers.Integral) or not isinstance(
            ring.get("capacity"), numbers.Integral):
        errs.append("'ring' missing or malformed (needs int len/capacity)")
    # v2 sections (bound only when present/declared — v1 docs still parse)
    if "series" in snap:
        errs.extend(check_series(snap["series"]))
    elif snap.get("schema") in ("pmdfc-telemetry-v2",
                                "pmdfc-telemetry-v3") \
            and doc.get("workload") is not None:
        # a serving snapshot (workload present ⇒ a live NetServer built
        # it) must ship the windowed series alongside
        errs.append("v2 serving snapshot lacks the 'series' block")
    if doc.get("workload") is not None:
        errs.extend(check_workload(doc["workload"]))
    errs.extend(check_causes(doc))
    errs.extend(check_admission(doc))
    errs.extend(check_fastpath(snap))
    errs.extend(check_migration(snap))
    errs.extend(check_autotune(snap))
    errs.extend(check_qos(snap))
    errs.extend(check_containment(snap))
    errs.extend(check_durability(snap))
    errs.extend(check_replica(doc))
    errs.extend(check_profile(snap))
    return errs


_FLIGHT_SCHEMAS = ("pmdfc-flight-v1", "pmdfc-flight-v2")


def _check_span_v2(i: int, rec: dict) -> list[str]:
    errs = []
    for k in ("span", "parent"):
        v = rec.get(k)
        if not isinstance(v, numbers.Integral) or isinstance(v, bool) \
                or not (0 <= v <= 0xFFFFFFFF):
            errs.append(f"records[{i}].{k}: {v!r} is not a 32-bit id")
    if not isinstance(rec.get("ok"), bool):
        errs.append(f"records[{i}].ok: missing or not a bool")
    t0, t1 = rec.get("t0_ns"), rec.get("t1_ns")
    if t0 is not None or t1 is not None:
        for k, v in (("t0_ns", t0), ("t1_ns", t1)):
            if not isinstance(v, numbers.Integral) or isinstance(v, bool):
                errs.append(f"records[{i}].{k}: {v!r} is not an int")
        if isinstance(t0, numbers.Integral) \
                and isinstance(t1, numbers.Integral) and t1 < t0:
            errs.append(f"records[{i}]: t1_ns < t0_ns")
    return errs


def check_flight(doc: dict) -> list[str]:
    """Schema violations in a flight-recorder dump. v1 documents are
    held only to the v1 shape (rung/detail/telemetry/records); the span
    tree + clock record requirements bind documents declaring v2."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    schema = doc.get("schema")
    if schema not in _FLIGHT_SCHEMAS:
        errs.append(f"schema is {schema!r}, expected one of "
                    f"{_FLIGHT_SCHEMAS}")
    if not isinstance(doc.get("rung"), str) or not doc.get("rung"):
        errs.append("'rung' missing or not a string")
    if not isinstance(doc.get("detail"), dict):
        errs.append("'detail' missing or not an object")
    errs.extend(check({"telemetry": doc.get("telemetry")}))
    records = doc.get("records")
    if not isinstance(records, list):
        return errs + ["'records' missing or not a list"]
    v2 = schema == "pmdfc-flight-v2"
    for i, rec in enumerate(records):
        if not isinstance(rec, dict) or not isinstance(
                rec.get("kind"), str):
            errs.append(f"records[{i}]: not an object with a 'kind'")
            continue
        if not v2:
            continue
        if rec["kind"] == "span" and "span" in rec:
            errs.extend(_check_span_v2(i, rec))
        elif rec["kind"] == "clock":
            for k in ("offset_ns", "rtt_ns"):
                if not isinstance(rec.get(k), numbers.Integral):
                    errs.append(f"records[{i}].{k}: missing or non-int")
        elif rec["kind"] == "recompile":
            if not isinstance(rec.get("program"), str):
                errs.append(f"records[{i}].program: missing or non-str")
    if "series" in doc:
        errs.extend(check_series(doc["series"]))
    # the SLO watchdog's breach dumps must stay attributable
    if v2 and doc.get("rung") == "slo_breach":
        det = doc.get("detail") or {}
        for k in ("target", "stage", "metric", "threshold", "value"):
            if k not in det:
                errs.append(f"slo_breach detail lacks {k!r}")
    return errs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", nargs="?", help="teledump JSON file")
    p.add_argument("--live", nargs=2, metavar=("HOST", "PORT"),
                   help="pull from a live server instead of a file")
    p.add_argument("--page-words", type=int, default=1024)
    args = p.parse_args(argv)

    if args.live:
        from pmdfc_tpu.runtime.net import TcpBackend

        with TcpBackend(args.live[0], int(args.live[1]),
                        page_words=args.page_words,
                        keepalive_s=None) as be:
            doc = be.server_stats()
    elif args.path:
        with open(args.path) as f:
            doc = json.load(f)
    else:
        p.error("need a PATH or --live HOST PORT")

    is_flight = (isinstance(doc, dict) and "rung" in doc
                 and str(doc.get("schema", "")).startswith("pmdfc-flight"))
    errs = check_flight(doc) if is_flight else check(doc)
    if errs:
        for e in errs:
            print(f"[check_teledump] FAIL: {e}", file=sys.stderr)
        return 1
    snap = doc["telemetry"]
    kind = (f"flight dump ({doc['schema']}, rung {doc['rung']}, "
            f"{len(doc['records'])} records)" if is_flight
            else "telemetry snapshot")
    print(f"[check_teledump] OK: {kind} — {len(snap['counters'])} "
          f"counters, {len(snap['gauges'])} gauges, "
          f"{len(snap['histograms'])} histograms, "
          f"ring {snap['ring']['len']}/{snap['ring']['capacity']}")
    return 0


if __name__ == "__main__":
    import os

    # runnable as `python tools/check_teledump.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
