"""`python -m tools.analyze` — run the full rule suite over the tree.

Exit status: 0 when every finding is either absent or explicitly
allowlisted AND no allowlist entry is stale (an entry whose finding no
longer fires is a suppression nobody is auditing — it must be deleted);
1 otherwise. `--json` emits machine-readable findings for tooling.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.analyze import DEFAULT_ALLOWLIST, run_analysis


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="concurrency & JAX-discipline static analyzer")
    p.add_argument("roots", nargs="*", default=None,
                   help="files/dirs to analyze (default: pmdfc_tpu/)")
    p.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                   help="suppression file (one finding id per line)")
    p.add_argument("--no-allowlist", action="store_true",
                   help="ignore the allowlist (show every finding)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON")
    args = p.parse_args(argv)

    findings, unused = run_analysis(
        args.roots or None,
        None if args.no_allowlist else args.allowlist)

    if args.json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "stale_allowlist": unused,
        }, indent=2))
    else:
        for f in findings:
            print(f)
        for ident in unused:
            print(f"stale-allow   {ident}: allowlisted but no longer "
                  f"found — delete the entry")
        n = len(findings) + len(unused)
        print(f"tools.analyze: {len(findings)} finding(s), "
              f"{len(unused)} stale allowlist entr"
              f"{'y' if len(unused) == 1 else 'ies'} -> "
              f"{'FAIL' if n else 'OK'}")
    return 1 if (findings or unused) else 0


if __name__ == "__main__":
    sys.exit(main())
