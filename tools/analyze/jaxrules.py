"""JAX-discipline rules.

Three rule families, all pure-`ast` (fixtures and the tree are never
imported, so no rule ever initializes a jax backend):

- **jax-donation** — every jit call carrying `donate_argnums`/
  `donate_argnames` must live in a module that keys donation off the
  platform: the module must contain a platform-guard expression
  (`jax.default_backend()` or a `.platform` attribute read feeding a
  comparison/branch). This is the exact shape of the jax 0.4.37 CPU
  donation corruption we shipped a fix for (kv.py `_donate()`,
  shard.py `_wrap`): donated programs scribble on pass-through buffers
  on the CPU jaxlib, so unconditional donation is a latent
  wrong-bytes bug on every host run.

- **jit-purity** — functions that become jitted programs (decorated
  with `jax.jit`/`partial(jax.jit, ...)`, passed by name into
  `jax.jit`/`pjit`/`shard_map`, or passed into a local jit-wrapper —
  a function that itself jits one of its parameters) must not call
  host-side nondeterminism or Python side effects: `time.*`,
  `random.*`/`np.random.*`, `os.environ`/`getenv`, `print`, `open`,
  socket or threading operations. Tracing executes these ONCE at
  compile time and never again — a timestamp or RNG draw inside a
  jitted body is a constant burned into the program, which is almost
  never what the author meant.

- **pallas-platform-gate** — every `pl.pallas_call` site must be
  reachable only behind a platform key: either the call carries an
  `interpret=` fallback that is not the literal `False` (the repo
  idiom: `interpret=jax.default_backend() != "tpu"`), or the module
  contains a platform-guard expression gating the launch. Same bug
  class as unkeyed donation — a Mosaic kernel is TPU-only lowering,
  and making it the unconditional path breaks every CPU host run.

- **wire-drift** — `runtime/net.py` is the single source of truth for
  the wire vocabulary. Any other module that binds a `MSG_*`,
  `PIPE_FLAG`, `TRACE_FLAG`, `CHAN_*`, or `MAGIC` name to a literal
  must match net.py's value; within any module the MSG_* codes must be
  pairwise distinct and the HOLA flag bits must stay out of the
  channel byte and out of each other.

- **profiler-seam** — `runtime/profiler.py` owns the blocking-fetch
  seam: a `jax.block_until_ready(...)` / `.block_until_ready()` call
  anywhere else in the serving tree is device time the X-ray cannot
  attribute (and a sync point the dispatch pipeline cannot see).
  Serving modules time fetches through `profiler.fetch(...)` thunks
  and sync warmups through `profiler.block_ready(...)`. Benchmarks
  (`bench/`) measure the raw device boundary on purpose and are
  exempt, as is the profiler module itself.
"""

from __future__ import annotations

import ast

from tools.analyze.model import Allowlist, Finding, Model, ModuleInfo

_WIRE_PREFIXES = ("MSG_", "CHAN_")
_WIRE_NAMES = ("PIPE_FLAG", "TRACE_FLAG", "MAGIC")

# module-name -> banned attribute calls/reads inside jitted bodies
_BANNED_MODULES = {
    "time": "host clock (compile-time constant under trace)",
    "random": "host RNG (drawn once at trace time)",
    "os": "process state (environ/getenv at trace time)",
    "socket": "network IO inside a traced program",
    "threading": "thread machinery inside a traced program",
}
_BANNED_CALLS = {
    "print": "stdout side effect (fires at trace time only)",
    "open": "file IO inside a traced program",
    "input": "console IO inside a traced program",
}


# -- shared helpers ---------------------------------------------------------


def _is_jit_func(f: ast.expr) -> bool:
    """`jax.jit`, `jit`, `pjit`, `jax.pjit`."""
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    return name in ("jit", "pjit")


def _donation_kwargs(call: ast.Call) -> bool:
    return any(k.arg in ("donate_argnums", "donate_argnames")
               for k in call.keywords)


# -- jax-donation -----------------------------------------------------------


def _has_platform_guard(tree: ast.Module) -> bool:
    # the canonical keying helper counts as a guard — but ONLY when it
    # is imported from kv (a local def named `_donate` with who-knows-
    # what policy inside does not satisfy the rule)
    imports_canonical = any(
        isinstance(node, ast.ImportFrom)
        and node.module == "pmdfc_tpu.kv"
        and any(a.name == "_donate" for a in node.names)
        for node in ast.walk(tree))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "default_backend":
                return True
            if imports_canonical and isinstance(f, ast.Name) \
                    and f.id == "_donate":
                return True
        if isinstance(node, ast.Attribute) and node.attr == "platform":
            return True
    return False


def check_donation(model: Model, allow: Allowlist) -> list[Finding]:
    out = []
    for mi in model.modules.values():
        sites = []
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _donation_kwargs(node):
                continue
            f = node.func
            # direct jit(..., donate_*) or partial(jax.jit, ..., donate_*)
            is_partial = (isinstance(f, ast.Name) and f.id == "partial") \
                or (isinstance(f, ast.Attribute) and f.attr == "partial")
            if _is_jit_func(f) or (
                    is_partial and node.args
                    and _is_jit_func(node.args[0])):
                sites.append(node)
        if not sites:
            continue
        if _has_platform_guard(mi.tree):
            continue
        for node in sites:
            # id keyed by line is brittle; key on the enclosing def name
            qual = _enclosing_name(mi.tree, node)
            ident = f"jax-donation:{mi.path}:{qual}"
            if allow.allows(ident):
                continue
            out.append(Finding(
                "jax-donation", mi.path, node.lineno, ident,
                "donation (`donate_argnums`) is not keyed on the "
                "platform: no `jax.default_backend()`/`.platform` guard "
                "in this module — on the CPU jaxlib donated programs can "
                "scribble on pass-through buffers (the jax 0.4.37 "
                "corruption class)"))
    return out


def _enclosing_name(tree: ast.Module, target: ast.AST) -> str:
    """Name of the innermost def/class containing `target` (or
    '<module>') — a line-stable allowlist qualifier."""
    best = "<module>"
    stack = [(tree, "<module>")]
    while stack:
        node, name = stack.pop()
        for child in ast.iter_child_nodes(node):
            cname = name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                cname = child.name
            if child is target or _contains(child, target):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    best = child.name
                stack.append((child, cname))
                break
    return best


def _contains(node: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(node))


# -- pallas-platform-gate ---------------------------------------------------


def _interpret_fallback(call: ast.Call) -> bool:
    """True when the `pallas_call` carries an `interpret=` kwarg that can
    be anything but unconditionally-compiled: a computed expression (the
    platform key) or the literal True. `interpret=False` is the same as
    omitting it — Mosaic-only, flagged."""
    for k in call.keywords:
        if k.arg == "interpret":
            return not (isinstance(k.value, ast.Constant)
                        and k.value.value is False)
    return False


def check_pallas_gate(model: Model, allow: Allowlist) -> list[Finding]:
    out = []
    for mi in model.modules.values():
        sites = []
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else None
                if name == "pallas_call":
                    sites.append(node)
        if not sites:
            continue
        guarded = _has_platform_guard(mi.tree)
        for node in sites:
            if _interpret_fallback(node):
                continue
            if guarded:
                # launch gated by an explicit platform branch in this
                # module (e.g. `if jax.default_backend() == "tpu":`) —
                # the other accepted shape
                continue
            qual = _enclosing_name(mi.tree, node)
            ident = f"pallas-platform-gate:{mi.path}:{qual}"
            if allow.allows(ident):
                continue
            out.append(Finding(
                "pallas-platform-gate", mi.path, node.lineno, ident,
                "`pl.pallas_call` is unconditionally Mosaic-lowered: no "
                "`interpret=` platform fallback on the call and no "
                "`jax.default_backend()`/`.platform` guard in this "
                "module — TPU-only code must never be the unconditional "
                "path (same bug class as unkeyed donation)"))
    return out


# -- jit-purity -------------------------------------------------------------


def _jit_roots(mi: ModuleInfo) -> dict[str, ast.FunctionDef]:
    """Functions in `mi` that become jitted programs."""
    funcs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.FunctionDef):
            funcs[node.name] = node
    roots: dict[str, ast.FunctionDef] = {}

    # (a) decorated: @jax.jit / @partial(jax.jit, ...)
    for fn in funcs.values():
        for d in fn.decorator_list:
            if _is_jit_func(d):
                roots[fn.name] = fn
            elif isinstance(d, ast.Call):
                f = d.func
                is_partial = (isinstance(f, ast.Name) and f.id == "partial") \
                    or (isinstance(f, ast.Attribute) and f.attr == "partial")
                if _is_jit_func(f) or (is_partial and d.args
                                       and _is_jit_func(d.args[0])):
                    roots[fn.name] = fn

    # (b) local jit-wrappers: a function that passes one of its params
    # into jax.jit/shard_map — calls to it with a named function in a
    # matching position make that function a root
    wrapper_params: dict[str, set] = {}
    for fn in funcs.values():
        params = {a.arg for a in fn.args.args}
        jitted_params = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else None
                if name in ("jit", "pjit", "shard_map", "_shard_map"):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and sub.id in params:
                            jitted_params.add(sub.id)
        if jitted_params:
            # methods: positions are declared over fn.args.args (which
            # includes `self`/`cls`) but an attribute-style call site
            # (`self._wrap(name, body, ...)`) does not pass it — record
            # the shift so (c) can re-align positional indices
            is_method = bool(fn.args.args) and \
                fn.args.args[0].arg in ("self", "cls")
            wrapper_params[fn.name] = (
                {i for i, a in enumerate(fn.args.args)
                 if a.arg in jitted_params},
                is_method)

    # (c) call sites: f passed by name into jit/shard_map/wrappers
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            f.id if isinstance(f, ast.Name) else None
        positions = None
        if name in ("jit", "pjit", "shard_map", "_shard_map"):
            positions = range(len(node.args))
        elif name in wrapper_params:
            idxs, is_method = wrapper_params[name]
            if is_method and isinstance(f, ast.Attribute):
                # `self._wrap(...)`: the receiver is not in node.args
                positions = {i - 1 for i in idxs if i > 0}
            else:
                positions = idxs
        if positions is None:
            continue
        for i in positions:
            if i < len(node.args):
                a = node.args[i]
                if isinstance(a, ast.Name) and a.id in funcs:
                    roots[a.id] = funcs[a.id]
                elif isinstance(a, ast.Attribute) and \
                        a.attr == "__wrapped__" and \
                        isinstance(a.value, ast.Name) and \
                        a.value.id in funcs:
                    roots[a.value.id] = funcs[a.value.id]
    return roots


def check_jit_purity(model: Model, allow: Allowlist) -> list[Finding]:
    out = []
    for mi in model.modules.values():
        roots = _jit_roots(mi)
        module_funcs = {n: f for n, f in mi.functions.items()}
        for rname, root in sorted(roots.items()):
            # scan the root body plus same-module helper calls one level
            # deep (the repo's jitted kernels call local helpers freely)
            bodies = [(rname, root)]
            seen = {rname}
            for node in ast.walk(root):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name):
                    h = node.func.id
                    if h in module_funcs and h not in seen:
                        seen.add(h)
                        bodies.append((h, module_funcs[h]))
            for bname, body in bodies:
                for f2 in _banned_calls(body):
                    where, line, why = f2
                    ident = f"jit-purity:{mi.path}:{rname}:{where}"
                    if allow.allows(ident):
                        continue
                    out.append(Finding(
                        "jit-purity", mi.path, line, ident,
                        f"jitted program `{rname}` (via `{bname}`) calls "
                        f"`{where}` — {why}"))
    return out


def _banned_calls(fn: ast.FunctionDef):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in _BANNED_CALLS:
                yield f.id, node.lineno, _BANNED_CALLS[f.id]
            elif isinstance(f, ast.Attribute):
                base = f.value
                # time.monotonic(), random.random(), np.random.xxx()
                if isinstance(base, ast.Name) and \
                        base.id in _BANNED_MODULES:
                    yield (f"{base.id}.{f.attr}", node.lineno,
                           _BANNED_MODULES[base.id])
                elif isinstance(base, ast.Attribute) and \
                        base.attr == "random" and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id in ("np", "numpy"):
                    yield (f"np.random.{f.attr}", node.lineno,
                           "host RNG (drawn once at trace time)")
        elif isinstance(node, ast.Attribute) and node.attr == "environ":
            if isinstance(node.value, ast.Name) and node.value.id == "os":
                yield ("os.environ", node.lineno,
                       _BANNED_MODULES["os"])


# -- wire-drift -------------------------------------------------------------


def _wire_constants(mi: ModuleInfo) -> dict[str, tuple[int, int]]:
    """NAME -> (value, line) for literal wire-constant bindings."""
    out = {}
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if not (name.startswith(_WIRE_PREFIXES)
                    or name in _WIRE_NAMES):
                continue
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, int):
                out[name] = (node.value.value, node.lineno)
    return out


def check_wire_drift(model: Model, allow: Allowlist) -> list[Finding]:
    out = []
    canonical_mi = None
    for mi in model.modules.values():
        if mi.path.replace("\\", "/").endswith("runtime/net.py"):
            canonical_mi = mi
            break
    canon = _wire_constants(canonical_mi) if canonical_mi else {}

    for mi in model.modules.values():
        consts = _wire_constants(mi)
        if not consts:
            continue
        # intra-module: MSG codes must be pairwise distinct
        seen_vals: dict[int, str] = {}
        for name, (val, line) in sorted(consts.items(),
                                        key=lambda kv: kv[1][1]):
            if not name.startswith("MSG_"):
                continue
            if val in seen_vals:
                ident = f"wire-drift:{mi.path}:{name}"
                if not allow.allows(ident):
                    out.append(Finding(
                        "wire-drift", mi.path, line, ident,
                        f"`{name}` = {val} collides with "
                        f"`{seen_vals[val]}` — two wire verbs sharing a "
                        f"code deserialize into each other"))
                continue
            seen_vals[val] = name
        # flag bits must stay out of the channel byte and disjoint
        pf = consts.get("PIPE_FLAG")
        tf = consts.get("TRACE_FLAG")
        for fname, fv in (("PIPE_FLAG", pf), ("TRACE_FLAG", tf)):
            if fv is not None and fv[0] & 0xFF:
                ident = f"wire-drift:{mi.path}:{fname}"
                if not allow.allows(ident):
                    out.append(Finding(
                        "wire-drift", mi.path, fv[1], ident,
                        f"`{fname}` = {fv[0]:#x} overlaps the HOLA "
                        f"channel byte (low 8 bits must stay clear)"))
        if pf is not None and tf is not None and (pf[0] & tf[0]):
            ident = f"wire-drift:{mi.path}:PIPE_FLAG&TRACE_FLAG"
            if not allow.allows(ident):
                out.append(Finding(
                    "wire-drift", mi.path, tf[1], ident,
                    f"PIPE_FLAG ({pf[0]:#x}) and TRACE_FLAG ({tf[0]:#x}) "
                    f"share bits — capability acks become ambiguous"))
        # cross-module: every re-binding must match runtime/net.py
        if mi is canonical_mi or not canon:
            continue
        for name, (val, line) in sorted(consts.items()):
            want = canon.get(name)
            if want is not None and want[0] != val:
                ident = f"wire-drift:{mi.path}:{name}"
                if allow.allows(ident):
                    continue
                out.append(Finding(
                    "wire-drift", mi.path, line, ident,
                    f"`{name}` = {val} drifts from runtime/net.py's "
                    f"{want[0]} — client and server would disagree on "
                    f"the wire vocabulary"))
    return out


# -- profiler-seam ----------------------------------------------------------

# paths where a raw device sync is the point, not a leak: benchmarks
# time the boundary itself, and the profiler module IS the seam
_SEAM_EXEMPT_DIRS = ("/bench/",)
_SEAM_EXEMPT_FILES = ("runtime/profiler.py",)


def check_profiler_seam(model: Model, allow: Allowlist) -> list[Finding]:
    out = []
    for mi in model.modules.values():
        path = mi.path.replace("\\", "/")
        if any(d in path for d in _SEAM_EXEMPT_DIRS) \
                or path.endswith(_SEAM_EXEMPT_FILES):
            continue
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if name != "block_until_ready":
                continue
            qual = _enclosing_name(mi.tree, node)
            ident = f"profiler-seam:{mi.path}:{qual}"
            if allow.allows(ident):
                continue
            out.append(Finding(
                "profiler-seam", mi.path, node.lineno, ident,
                "`block_until_ready` outside the profiler's timed-fetch "
                "seam: device time spent here is invisible to the "
                "X-ray's attribution — route blocking fetches through "
                "`profiler.fetch(...)` and warmup syncs through "
                "`profiler.block_ready(...)` (runtime/profiler.py)"))
    return out


def run(model: Model, allow: Allowlist) -> list[Finding]:
    return (check_donation(model, allow)
            + check_pallas_gate(model, allow)
            + check_jit_purity(model, allow)
            + check_wire_drift(model, allow)
            + check_profiler_seam(model, allow))
