"""Guarded-by lint.

Two checks over the source model:

1. **Declaration coverage** — every lock attribute (and module-level
   lock) must carry a `# guarded-by:` comment: either the fields it
   protects, or `<none>` for a pure critical-section lock. An
   undeclared lock is a finding: the point of the suite is that the
   next refactor can read what every lock is FOR.

2. **Write discipline** — every write to a declared field (assignment,
   augmented assignment, `del`, subscript store, or a mutating method
   call like `.append`/`.pop`) must occur while the declared lock is
   held. "Held" means: lexically inside `with <lock>:`, inside a
   method whose name ends in `_locked` (the repo's callers-hold-it
   convention), inside a method annotated `# caller-holds: <lock>`,
   or inside a `@_locked`-decorated method. `__init__`/`__new__` are
   exempt — construction precedes publication.

   Writes through `self` check the owning class (MRO-aware); writes
   through any other base (`cs.alive = ...`) are matched by field name
   against every declaring class and the held lock must share the
   SAME base expression (`with cs.out_cv: cs.alive = ...`).

Finding ids: ``guarded-by:<path>:<Class.attr>`` for declarations,
``guarded-write:<path>:<func>:<field>`` for writes — line numbers are
shown but not part of the id, so allowlist entries survive edits.
"""

from __future__ import annotations

from tools.analyze.model import Allowlist, ClassInfo, Finding, Model
from tools.analyze.resolve import FunctionFacts, class_mro

_EXEMPT = {"__init__", "__new__", "__post_init__"}


def check_declarations(model: Model) -> list[Finding]:
    out = []
    for decl in model.all_locks():
        if decl.guards is None:
            qual = decl.lock_id
            out.append(Finding(
                "guarded-by", decl.module.path, decl.line,
                f"guarded-by:{decl.module.path}:{qual}",
                f"{decl.kind} `{qual}` has no `# guarded-by:` "
                f"declaration (name the fields it protects, or <none>)"))
    return out


def _guard_lock_for(model: Model, cls: ClassInfo, field: str):
    """The lock attr declared to guard `field` in `cls`'s MRO, if any."""
    for c in class_mro(model, cls):
        if field in c.guarded:
            return c, c.guarded[field]
    return None, None


def check_writes(model: Model,
                 facts: dict[str, FunctionFacts]) -> list[Finding]:
    out = []
    for fid, f in facts.items():
        func_name = fid.split(".")[-1]
        if func_name in _EXEMPT or func_name.endswith("_locked"):
            continue
        for w in f.writes:
            if w.base == "self":
                if f.owner is None:
                    continue
                owner, lock_attr = _guard_lock_for(model, f.owner, w.field)
                if lock_attr is None:
                    continue
                decl = model.find_lock(f.owner, lock_attr)
                want = decl.lock_id if decl else None
                held_ids = {h.lock_id for h in w.held}
                if want is None or want in held_ids:
                    continue
                out.append(Finding(
                    "guarded-write", f.module.path, w.line,
                    f"guarded-write:{f.module.path}:{fid}:{w.field}",
                    f"`self.{w.field}` ({w.kind}) is guarded by "
                    f"`{want}` but written without it "
                    f"(held: {sorted(h for h in held_ids if h) or '[]'})"))
            else:
                # cross-object write: match by field name against the
                # classes that declare it guarded; the held lock must
                # ride the same base expression
                declares = model.guarded_fields.get(w.field, [])
                if not declares:
                    continue
                if w.base_cls is not None:
                    # the base's class is known: only classes in its MRO
                    # can actually own the field (kills name-coincidence
                    # false positives like a bench Sim's `stats` matching
                    # FaultInjector's `stats`)
                    mro = {c.name for c in class_mro(
                        model, model.classes.get(w.base_cls))}
                    declares = [(c, la) for c, la in declares
                                if c.name in mro]
                    if not declares:
                        continue
                wants = set()
                for cls, lock_attr in declares:
                    decl = model.find_lock(cls, lock_attr)
                    if decl is not None:
                        wants.add(decl.lock_id)
                if not wants:
                    continue
                ok = any(h.lock_id in wants and h.base in (w.base, "self")
                         for h in w.held)
                if ok:
                    continue
                out.append(Finding(
                    "guarded-write", f.module.path, w.line,
                    f"guarded-write:{f.module.path}:{fid}:{w.field}",
                    f"`{w.base}.{w.field}` ({w.kind}) is guarded by "
                    f"{sorted(wants)} but written without holding it on "
                    f"`{w.base}`"))
    return out


def run(model: Model, facts: dict[str, FunctionFacts],
        allow: Allowlist) -> list[Finding]:
    found = check_declarations(model) + check_writes(model, facts)
    return [f for f in found if not allow.allows(f.ident)]
