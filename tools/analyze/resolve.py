"""Function-level facts: held-lock scopes, writes, resolved calls.

One walk per function produces everything the rules need:

- the with-stack of held locks at every point (lock identity resolved
  through the model: `self._lock` via the class MRO, `cs.out_cv` via
  the unique declaring class, `with lock:` through local aliases like
  ``lock = self.op_lock``),
- every write to an attribute (assign / augassign / del / subscript
  store / known mutating method call) with the held stack at that
  point — the guarded-by lint's raw material,
- every call with the held stack at that point plus its resolved
  target(s) — the lock-order graph's raw material. Resolution is
  deliberately conservative: `self.m()` through the MRO, typed
  attributes through the inferred `attr_types`, module-alias calls
  through import tracking and return annotations, and a unique-name
  fallback ONLY when the name is defined exactly once in the analyzed
  set. Ambiguous names (`close`, `get`, `put`, ...) resolve to nothing
  — a missing edge is recoverable by the runtime sanitizer; a wrong
  edge would fail the build on a phantom deadlock.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.analyze.model import (
    ClassInfo, Model, ModuleInfo, caller_holds, is_locked_decorated)

# deque/list/set/dict methods that mutate their receiver
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "clear", "remove", "discard", "add", "update",
    "setdefault", "sort", "reverse",
}


@dataclasses.dataclass
class Held:
    lock_id: str | None      # "Class.attr" / "module.NAME"; None=unresolved
    base: str                # unparsed base expr ("self", "cs", "o.cs", "")
    line: int


@dataclasses.dataclass
class Write:
    field: str
    base: str                # "" for module globals
    kind: str                # assign|augassign|del|store|mutcall
    line: int
    held: list[Held]
    base_cls: str | None = None  # inferred class of the base expression


@dataclasses.dataclass
class CallSite:
    targets: list[str]       # resolved function ids (possibly empty)
    attr: str                # the called name (diagnostics)
    line: int
    held: list[Held]


@dataclasses.dataclass
class FunctionFacts:
    fid: str                 # "Class.method" or "mod.py:func"
    owner: ClassInfo | None
    module: ModuleInfo
    node: ast.FunctionDef
    acquires: list[tuple[str, int]] = dataclasses.field(
        default_factory=list)                     # (lock_id, line)
    nested: list[tuple[str, str, int]] = dataclasses.field(
        default_factory=list)                     # (outer, inner, line)
    writes: list[Write] = dataclasses.field(default_factory=list)
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    assumed_held: list[str] = dataclasses.field(default_factory=list)
    unresolved_with: int = 0


def owner_class_of(owner) -> ClassInfo | None:
    return owner if isinstance(owner, ClassInfo) else None


def class_mro(model: Model, cls: ClassInfo | None):
    seen, order, stack = set(), [], [cls]
    while stack:
        c = stack.pop(0)
        if c is None or c.name in seen:
            continue
        seen.add(c.name)
        order.append(c)
        stack.extend(model.classes.get(b) for b in c.bases)
    return order


def _find_method(model: Model, cls: ClassInfo | None, name: str):
    for c in class_mro(model, cls):
        if name in c.methods:
            return c, c.methods[name]
    return None


def _return_class(fn: ast.FunctionDef) -> str | None:
    from tools.analyze.model import _ann_class
    return _ann_class(fn.returns)


class _Walker:
    def __init__(self, model: Model, owner, fn: ast.FunctionDef,
                 fid: str):
        self.model = model
        self.owner = owner
        self.cls = owner_class_of(owner)
        self.module: ModuleInfo = (owner.module if self.cls is not None
                                   else owner)
        self.fn = fn
        self.facts = FunctionFacts(fid, self.cls, self.module, fn)
        self.aliases: dict[str, ast.expr] = {}   # local = self.lock_attr
        self.local_defs: dict[str, ast.FunctionDef] = {}
        self.param_types: dict[str, str | None] = {}
        self.held: list[Held] = []

    # -- lock identity --

    def resolve_lock_expr(self, ctx: ast.expr):
        """(lock_id | None, base_text) for a with-context expression."""
        if isinstance(ctx, ast.Name):
            target = self.aliases.get(ctx.id)
            if target is not None:
                return self.resolve_lock_expr(target)
            decl = self.module.locks.get(ctx.id)
            if decl is not None:
                return decl.lock_id, ""
            return None, ctx.id
        if isinstance(ctx, ast.Attribute):
            base_txt = ast.unparse(ctx.value)
            if base_txt == "self":
                decl = self.model.find_lock(self.cls, ctx.attr)
            else:
                decl = self.model.find_lock(None, ctx.attr)
                if decl is None:
                    t = self._expr_class(ctx.value)
                    if t is not None:
                        decl = self.model.find_lock(t, ctx.attr)
            return (decl.lock_id if decl else None), base_txt
        return None, ast.unparse(ctx)

    # -- type inference on expressions --

    def _expr_class(self, expr: ast.expr) -> ClassInfo | None:
        if isinstance(expr, ast.Name):
            t = self.param_types.get(expr.id)
            return self.model.classes.get(t) if t else None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self.cls is not None:
            for c in class_mro(self.model, self.cls):
                t = c.attr_types.get(expr.attr)
                if t is None:
                    continue
                return self._type_to_class(t)
        if isinstance(expr, ast.Attribute):
            # one level of attribute typing through a typed base
            # (`o.cs` where o: _StagedOp and _StagedOp.cs: _ConnState)
            base_cls = self._expr_class(expr.value)
            if base_cls is not None:
                for c in class_mro(self.model, base_cls):
                    t = c.attr_types.get(expr.attr)
                    if t is not None:
                        return self._type_to_class(t)
        if isinstance(expr, ast.Subscript):
            base = expr.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and self.cls is not None:
                for c in class_mro(self.model, self.cls):
                    t = c.attr_types.get(base.attr)
                    if isinstance(t, tuple) and t[0] == "list":
                        return self._type_to_class(t[1])
        return None

    def _type_to_class(self, t) -> ClassInfo | None:
        if isinstance(t, str):
            return self.model.classes.get(t)
        if isinstance(t, tuple) and t[0] == "factory":
            # `self.x = alias.fn(...)`: resolve fn via aliases + return
            # annotation (e.g. `tele.scope(...) -> Scope`)
            fname = t[2]
            for cand in self.model.by_name.get(fname, []):
                own, fn = cand
                if isinstance(own, ModuleInfo):
                    rc = _return_class(fn)
                    if rc and rc in self.model.classes:
                        return self.model.classes[rc]
            return None
        return None

    # -- call target resolution --

    def resolve_call(self, node: ast.Call) -> tuple[list[str], str]:
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in self.local_defs:
                return [f"{self.facts.fid}.<local>.{name}"], name
            if name in self.module.functions:
                return [f"{self.module.path}:{name}"], name
            if name in self.model.classes and (
                    name in self.module.classes
                    or name in self.module.aliases):
                ci = self.model.classes[name]
                hit = _find_method(self.model, ci, "__init__")
                if hit:
                    return [f"{hit[0].name}.__init__"], name
                return [], name
            src = self.module.aliases.get(name)
            if src and ":" in src:
                # `from M import name` — find it in the analyzed set
                modpath, fname = src.split(":", 1)
                for cand in self.model.by_name.get(fname, []):
                    own, _fn = cand
                    if isinstance(own, ModuleInfo) and \
                            _mod_matches(own, modpath):
                        return [f"{own.path}:{fname}"], name
                if fname in self.model.classes:
                    hit = _find_method(self.model,
                                       self.model.classes[fname],
                                       "__init__")
                    if hit:
                        return [f"{hit[0].name}.__init__"], name
            return [], name
        if not isinstance(f, ast.Attribute):
            return [], "<expr>"
        name = f.attr
        base = f.value
        # self.method()
        if isinstance(base, ast.Name) and base.id == "self" \
                and self.cls is not None:
            hit = _find_method(self.model, self.cls, name)
            if hit:
                return [f"{hit[0].name}.{name}"], name
            return [], name
        # module_alias.func()
        if isinstance(base, ast.Name) and base.id in self.module.aliases \
                and base.id not in self.param_types:
            modpath = self.module.aliases[base.id]
            for cand in self.model.by_name.get(name, []):
                own, _fn = cand
                if isinstance(own, ModuleInfo) and _mod_matches(own, modpath):
                    return [f"{own.path}:{name}"], name
        # typed attribute / element
        t = self._expr_class(base)
        if t is not None:
            hit = _find_method(self.model, t, name)
            if hit:
                return [f"{hit[0].name}.{name}"], name
            return [], name
        # unique-name fallback: exactly one definition in the whole set
        cands = self.model.by_name.get(name, [])
        if len(cands) == 1:
            own, _fn = cands[0]
            if isinstance(own, ClassInfo):
                return [f"{own.name}.{name}"], name
            return [f"{own.path}:{name}"], name
        return [], name

    # -- the walk --

    def run(self) -> FunctionFacts:
        fn = self.fn
        from tools.analyze.model import _ann_class
        for a in fn.args.args + fn.args.kwonlyargs:
            self.param_types[a.arg] = _ann_class(a.annotation)
        held0: list[Held] = []
        for lock_attr in caller_holds(fn, self.module.lines):
            decl = self.model.find_lock(self.cls, lock_attr)
            held0.append(Held(decl.lock_id if decl else None, "self",
                              fn.lineno))
        if is_locked_decorated(fn):
            decl = self.model.find_lock(self.cls, "_lock")
            lid = decl.lock_id if decl else None
            held0.append(Held(lid, "self", fn.lineno))
            if lid:
                self.facts.acquires.append((lid, fn.lineno))
        if fn.name.endswith("_locked") and self.cls is not None:
            for c in class_mro(self.model, self.cls):
                for attr in c.locks:
                    held0.append(Held(c.locks[attr].lock_id, "self",
                                      fn.lineno))
        self.facts.assumed_held = [h.lock_id for h in held0 if h.lock_id]
        self.held = held0
        for stmt in fn.body:
            self._visit(stmt)
        return self.facts

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.local_defs[node.name] = node
            return                       # analyzed separately, empty held
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.With):
            self._visit_with(node)
            return
        if isinstance(node, ast.Assign):
            self._record_alias(node)
            for tgt in node.targets:
                self._record_write_target(tgt, "assign", node.lineno)
            self._visit_expr(node.value)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._record_write_target(node.target, "assign", node.lineno)
                self._visit_expr(node.value)
            return
        if isinstance(node, ast.AugAssign):
            self._record_write_target(node.target, "augassign", node.lineno)
            self._visit_expr(node.value)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._record_write_target(tgt, "del", node.lineno)
            return
        if isinstance(node, ast.Expr):
            self._visit_expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            else:
                self._visit(child)

    def _visit_with(self, node: ast.With) -> None:
        entered = 0
        for item in node.items:
            lid, base = self.resolve_lock_expr(item.context_expr)
            is_lockish = lid is not None or self._looks_lockish(
                item.context_expr)
            if lid is None and is_lockish:
                self.facts.unresolved_with += 1
            if is_lockish:
                h = Held(lid, base, node.lineno)
                if lid is not None:
                    self.facts.acquires.append((lid, node.lineno))
                    # same-lock pairs are kept: a lexical `with L: with
                    # L:` on a non-reentrant Lock is a certain deadlock
                    # (lockorder's self-edge check; RLock/Condition
                    # filtered there by kind)
                    for outer in self.held:
                        if outer.lock_id:
                            self.facts.nested.append(
                                (outer.lock_id, lid, node.lineno))
                self.held.append(h)
                entered += 1
            else:
                self._visit_expr(item.context_expr)
        for stmt in node.body:
            self._visit(stmt)
        for _ in range(entered):
            self.held.pop()

    def _looks_lockish(self, ctx: ast.expr) -> bool:
        """Is this with-context plausibly a lock? (attribute/name whose
        final component is a known lock attr somewhere, or matches the
        repo's lock naming: contains 'lock', '_l', or '_cv')."""
        name = None
        if isinstance(ctx, ast.Attribute):
            name = ctx.attr
        elif isinstance(ctx, ast.Name):
            tgt = self.aliases.get(ctx.id)
            if tgt is not None:
                return self._looks_lockish(tgt)
            name = ctx.id
        if name is None:
            return False
        if self.model.find_lock(self.cls, name) is not None:
            return True
        low = name.lower()
        return "lock" in low or low in ("_l",) or low.endswith("_cv")

    def _record_alias(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Attribute):
            self.aliases[node.targets[0].id] = node.value

    def _record_write_target(self, tgt: ast.expr, kind: str,
                             line: int) -> None:
        if isinstance(tgt, ast.Tuple):
            for elt in tgt.elts:
                self._record_write_target(elt, kind, line)
            return
        attr_node = None
        if isinstance(tgt, ast.Attribute):
            attr_node = tgt
        elif isinstance(tgt, ast.Subscript) and \
                isinstance(tgt.value, ast.Attribute):
            attr_node = tgt.value
            kind = "store"
        if attr_node is None:
            return
        bc = self._expr_class(attr_node.value)
        self.facts.writes.append(Write(
            attr_node.attr, ast.unparse(attr_node.value), kind, line,
            list(self.held), bc.name if bc is not None else None))

    def _visit_expr(self, node: ast.expr) -> None:
        # manual traversal, NOT ast.walk: walk yields a pruned node's
        # children anyway, so `continue` alone would still attribute
        # calls inside a merely-CONSTRUCTED lambda to the current held
        # set — a deferred body that never runs under these locks would
        # fabricate lock-order edges (phantom deadlocks)
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.Lambda, ast.FunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if isinstance(sub, ast.Call):
                targets, name = self.resolve_call(sub)
                self.facts.calls.append(
                    CallSite(targets, name, sub.lineno, list(self.held)))
                # mutating method call on an attribute
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr in MUTATORS \
                        and isinstance(f.value, ast.Attribute):
                    bc = self._expr_class(f.value.value)
                    self.facts.writes.append(Write(
                        f.value.attr, ast.unparse(f.value.value),
                        "mutcall", sub.lineno, list(self.held),
                        bc.name if bc is not None else None))


def _mod_matches(mi: ModuleInfo, dotted: str) -> bool:
    """Does module info `mi` correspond to dotted path `pkg.mod` (or the
    `pkg.mod:name` form's module part)?"""
    dotted = dotted.split(":", 1)[0]
    tail = dotted.split(".")[-1]
    base = mi.path.rsplit("/", 1)[-1]
    return base == f"{tail}.py" or base == tail


def analyze_functions(model: Model) -> dict[str, FunctionFacts]:
    """FunctionFacts for every function/method (plus locals) in the set."""
    out: dict[str, FunctionFacts] = {}

    def _run(owner, fn: ast.FunctionDef, fid: str):
        w = _Walker(model, owner, fn, fid)
        facts = w.run()
        out[fid] = facts
        for name, sub in w.local_defs.items():
            _run(owner, sub, f"{fid}.<local>.{name}")

    for mi in model.modules.values():
        for fname, fn in mi.functions.items():
            _run(mi, fn, f"{mi.path}:{fname}")
        for ci in mi.classes.values():
            for mname, fn in ci.methods.items():
                _run(ci, fn, f"{ci.name}.{mname}")
    return out
