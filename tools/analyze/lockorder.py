"""Lock-order graph: nested acquisitions + resolved call edges.

Edge ``A -> B`` means "somewhere, B is (or may be) acquired while A is
held" — either a lexically nested ``with``, or a call made under A to
a function whose transitive may-acquire summary contains B. Summaries
reach a fixpoint over the resolved call graph, so the net.py flush
loop's path into per-connection send locks, the breaker's path into
the telemetry registry, and the engine's slice/call gates all
contribute edges without any runtime execution.

Findings:

- **lock-order** — a strongly-connected component with more than one
  lock (a potential AB/BA deadlock), or a self-edge on a
  non-reentrant lock (Lock, not RLock). Each cycle lists one example
  site per edge. Allowlist id: ``lock-order:<A-->B-->...>`` over the
  cycle's sorted edge list.
- **lock-rank** — an edge that runs AGAINST the declared hierarchy
  (`pmdfc_tpu.runtime.sanitizer.HIERARCHY` — shared with the runtime
  sanitizer): ranked locks must be acquired outer-to-inner. Edges
  with an unranked endpoint only participate in the cycle check.
- **unranked-lock** — a lock declared in one of the SERVING-TIER
  modules (`RANKED_MODULES`) with no `HIERARCHY` rank. An unranked
  lock silently opts out of both the static rank rule and the runtime
  sanitizer's inversion check, so new serving/partitioning locks
  cannot ship unranked (the coverage gate the mesh-plane refactor
  rides on).
"""

from __future__ import annotations

import dataclasses

from tools.analyze.model import Allowlist, Finding, Model
from tools.analyze.resolve import FunctionFacts

# Modules whose locks MUST carry a HIERARCHY rank: the threaded serving
# tiers plus the mesh serving plane (parallel/). Leaf-only helper
# modules stay out — their locks participate in hold/re-acquire checks
# only, the documented sanitizer contract for unranked locks.
RANKED_MODULES = frozenset({
    "runtime/net.py", "runtime/failure.py", "runtime/engine.py",
    "runtime/server.py", "runtime/slo.py", "runtime/autotune.py",
    "runtime/qos.py",
    "client/replica.py", "client/directory.py",
    "parallel/shard.py", "parallel/partitioning.py", "parallel/plane.py",
    "cluster/ring.py", "cluster/migrate.py",
})


def _hierarchy() -> dict[str, int]:
    try:
        from pmdfc_tpu.runtime.sanitizer import HIERARCHY
        return dict(HIERARCHY)
    except Exception:  # noqa: BLE001 — standalone/fixture analysis runs
        return {}      # without the package importable: cycle check only


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str       # "nested" or the callee fid


def may_acquire(facts: dict[str, FunctionFacts]) -> dict[str, set]:
    """Transitive lock-acquisition summary per function (fixpoint)."""
    acq = {fid: {lid for lid, _ in f.acquires} for fid, f in facts.items()}
    calls = {fid: [t for c in f.calls for t in c.targets]
             for fid, f in facts.items()}
    changed = True
    while changed:
        changed = False
        for fid, tgts in calls.items():
            cur = acq[fid]
            before = len(cur)
            for t in tgts:
                cur |= acq.get(t, set())
            if len(cur) != before:
                changed = True
    return acq


def build_edges(facts: dict[str, FunctionFacts]) -> list[Edge]:
    summaries = may_acquire(facts)
    edges: list[Edge] = []
    for fid, f in facts.items():
        for outer, inner, line in f.nested:
            edges.append(Edge(outer, inner, f.module.path, line, "nested"))
        for c in f.calls:
            held = [h.lock_id for h in c.held if h.lock_id]
            if not held:
                continue
            for t in c.targets:
                for inner in summaries.get(t, ()):
                    for outer in held:
                        if outer != inner:
                            edges.append(Edge(outer, inner, f.module.path,
                                              c.line, t))
                # self-reacquire through a call: only meaningful for
                # non-reentrant locks, surfaced by the cycle check below
                for outer in held:
                    if outer in summaries.get(t, ()):
                        edges.append(Edge(outer, outer, f.module.path,
                                          c.line, t))
    return edges


def _sccs(nodes: set, adj: dict[str, set]) -> list[list[str]]:
    """Tarjan SCC (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strong(v0):
        work = [(v0, iter(sorted(adj.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        onstack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


def run(model: Model, facts: dict[str, FunctionFacts],
        allow: Allowlist) -> list[Finding]:
    edges = build_edges(facts)
    # drop allowlisted EDGES before any graph verdict (an allowlisted
    # edge documents "this nesting is intentional and ordered by other
    # means"); cycle ids then stay stable as the graph grows
    kept: list[Edge] = []
    for e in edges:
        if not allow.allows(f"lock-order:{e.src}->{e.dst}"):
            kept.append(e)
    adj: dict[str, set] = {}
    example: dict[tuple, Edge] = {}
    nodes: set = set()
    kinds = {d.lock_id: d.kind for d in model.all_locks()}
    for e in kept:
        nodes.add(e.src)
        nodes.add(e.dst)
        adj.setdefault(e.src, set()).add(e.dst)
        example.setdefault((e.src, e.dst), e)
    findings: list[Finding] = []

    # self-deadlock: L -> L on a non-reentrant primitive (RLock and
    # Condition — whose re-wait semantics the sanitizer owns — exempt)
    for (a, b), e in sorted(example.items()):
        if a == b and kinds.get(a) == "Lock":
            ident = f"lock-order:{a}->{a}"
            if not allow.allows(ident):
                findings.append(Finding(
                    "lock-order", e.path, e.line, ident,
                    f"`{a}` (non-reentrant Lock) may be re-acquired "
                    f"while held (via {e.via})"))

    for comp in _sccs(nodes, adj):
        if len(comp) < 2:
            continue
        comp = sorted(comp)
        cyc_edges = sorted(
            (a, b) for (a, b) in example
            if a in comp and b in comp and a != b)
        ident = "lock-order:" + "|".join(f"{a}->{b}" for a, b in cyc_edges)
        if allow.allows(ident):
            continue
        sites = "; ".join(
            f"{a}->{b} at {example[(a, b)].path}:{example[(a, b)].line}"
            f" (via {example[(a, b)].via})"
            for a, b in cyc_edges)
        e0 = example[cyc_edges[0]]
        findings.append(Finding(
            "lock-order", e0.path, e0.line, ident,
            f"lock-order cycle over {comp}: {sites}"))

    ranks = _hierarchy()
    # hierarchy coverage: serving-tier locks must be ranked (skipped in
    # standalone fixture runs where the package — and so the hierarchy
    # table — is not importable)
    if ranks:
        for decl in model.all_locks():
            mod = decl.module.path.replace("\\", "/").split(
                "pmdfc_tpu/", 1)[-1]
            if mod not in RANKED_MODULES or decl.lock_id in ranks:
                continue
            ident = f"unranked-lock:{decl.lock_id}"
            if allow.allows(ident):
                continue
            findings.append(Finding(
                "unranked-lock", decl.module.path, decl.line, ident,
                f"`{decl.lock_id}` is declared in serving-tier module "
                f"{mod} but has no rank in sanitizer.HIERARCHY — it "
                "opts out of the static rank rule AND the runtime "
                "inversion check; add it to the table"))
    seen_rank: set = set()
    for e in kept:
        if e.src == e.dst:
            continue
        ra, rb = ranks.get(e.src), ranks.get(e.dst)
        if ra is None or rb is None or rb > ra:
            continue
        key = (e.src, e.dst)
        if key in seen_rank:
            continue
        seen_rank.add(key)
        ident = f"lock-rank:{e.src}->{e.dst}"
        if allow.allows(ident):
            continue
        findings.append(Finding(
            "lock-rank", e.path, e.line, ident,
            f"`{e.dst}` (rank {rb}) acquired while holding `{e.src}` "
            f"(rank {ra}) — against the declared hierarchy (via {e.via})"))
    return findings
