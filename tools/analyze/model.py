"""Source model shared by every analyzer rule.

Pure `ast` + raw source lines — no imports of the analyzed code, so the
analyzer runs on any tree (including the seeded bad fixtures) without
executing it. The model extracts, per module:

- import aliases (``import x.y as z`` / ``from x import y``),
- classes, their base names, and their methods,
- lock declarations: ``self.X = threading.Lock()`` (also ``RLock``/
  ``Condition``, also the ``Lock() if cond else None`` form) plus
  module-level ``NAME = threading.Lock()``,
- the annotation grammar (comments are read from the raw source since
  `ast` drops them):

    # guarded-by: fieldA, fieldB     on a lock decl: the fields it guards
    # guarded-by: <none>             a pure critical-section lock
    # guarded-by: _lock              on a FIELD assignment: reverse form
    # caller-holds: _lock            on a def: callers hold _lock already

  Multiple contiguous ``guarded-by`` comment lines above a declaration
  union their field lists (long lists wrap).
- lightweight attribute type inference (``self.x = ClassName(...)``,
  constructor params with annotations, ``tele.scope(...)`` through
  return annotations, lists of constructed elements) — enough to
  resolve method calls like ``self.stats.inc`` or
  ``self.breakers[e].record_failure`` to their defining class.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

LOCK_CTORS = {"Lock", "RLock", "Condition"}

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(.+?)\s*$")
_HOLDS_RE = re.compile(r"#\s*caller-holds:\s*(.+?)\s*$")


@dataclasses.dataclass
class Finding:
    rule: str          # guarded-by | lock-order | lock-rank | jax-* | wire-*
    path: str          # module path relative to the analysis root
    line: int
    ident: str         # stable allowlist id: "rule:path:qualifier"
    message: str

    def __str__(self) -> str:
        return (f"{self.rule:<12} {self.path}:{self.line}: {self.message}"
                f"\n{'':<13}[id: {self.ident}]")


class Allowlist:
    """One suppression per line: ``<finding id>  # justification``.

    The justification is MANDATORY reviewing convention, not syntax —
    the file is the audit trail for every accepted exception (and for
    the regression notes of races fixed by this suite).
    """

    def __init__(self, ids: dict[str, str]):
        self.ids = ids          # id -> justification text
        self.used: set[str] = set()

    @classmethod
    def load(cls, path: str | None) -> "Allowlist":
        ids: dict[str, str] = {}
        if path and os.path.exists(path):
            with open(path) as f:
                for raw in f:
                    line = raw.strip()
                    if not line or line.startswith("#"):
                        continue
                    ident, _, note = line.partition("#")
                    ids[ident.strip()] = note.strip()
        return cls(ids)

    def allows(self, ident: str) -> bool:
        if ident in self.ids:
            self.used.add(ident)
            return True
        return False

    def unused(self) -> list[str]:
        return sorted(set(self.ids) - self.used)


@dataclasses.dataclass
class LockDecl:
    cls: str | None              # owning class, None = module level
    attr: str                    # attribute / module variable name
    kind: str                    # Lock | RLock | Condition
    module: "ModuleInfo"
    line: int
    guards: list[str] | None     # None = undeclared; [] = <none>

    @property
    def lock_id(self) -> str:
        if self.cls is not None:
            return f"{self.cls}.{self.attr}"
        base = os.path.basename(self.module.path)
        return f"{os.path.splitext(base)[0]}.{self.attr}"


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    bases: list[str]
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    locks: dict[str, LockDecl] = dataclasses.field(default_factory=dict)
    # field name -> lock attr guarding it (from either annotation form)
    guarded: dict[str, str] = dataclasses.field(default_factory=dict)
    # attr name -> inferred class name ("T" or ("list", "T"))
    attr_types: dict[str, object] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    path: str                    # analysis-relative path
    tree: ast.Module
    lines: list[str]
    aliases: dict[str, str] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    locks: dict[str, LockDecl] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Model:
    modules: dict[str, ModuleInfo]
    # class name -> ClassInfo (package-unique names asserted at build)
    classes: dict[str, ClassInfo]
    # method/function name -> list of (owner ClassInfo|ModuleInfo, node)
    by_name: dict[str, list]
    # field name -> list of (ClassInfo, lock attr) for cross-object checks
    guarded_fields: dict[str, list]

    def all_locks(self):
        for m in self.modules.values():
            yield from m.locks.values()
            for c in m.classes.values():
                yield from c.locks.values()

    def find_lock(self, cls: ClassInfo | None, attr: str):
        """Resolve a lock attribute to its declaration: the class's MRO
        first (within the analyzed set), then a package-unique name."""
        seen = set()
        stack = [cls] if cls is not None else []
        while stack:
            c = stack.pop()
            if c is None or c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.locks:
                return c.locks[attr]
            for b in c.bases:
                stack.append(self.classes.get(b))
        owners = [d for d in self.all_locks() if d.attr == attr]
        if len(owners) == 1:
            return owners[0]
        return None


def _comment_directives(lines: list[str], lineno: int, pattern: re.Pattern
                        ) -> list[str]:
    """Matches of `pattern` on the node's own line plus the contiguous
    comment-only block immediately above it."""
    out = []
    m = pattern.search(lines[lineno - 1]) if lineno - 1 < len(lines) else None
    if m:
        out.append(m.group(1))
    i = lineno - 2
    while i >= 0 and lines[i].lstrip().startswith("#"):
        m = pattern.search(lines[i])
        if m:
            out.append(m.group(1))
        i -= 1
    return out


def _parse_guard_fields(texts: list[str]) -> list[str]:
    fields: list[str] = []
    for t in texts:
        if t.strip().startswith("<none>"):
            # `<none>` usually carries a trailing justification on the
            # same line — `# guarded-by: <none>  (pure critical
            # section)` — which must not be split into phantom field
            # names (a phantom matching a real attribute elsewhere
            # would fabricate guarded-write findings)
            continue
        fields.extend(p.strip() for p in t.split(",") if p.strip())
    return fields


# runtime-sanitizer factory names (pmdfc_tpu.runtime.sanitizer): the
# injected form `san.lock("Class._lock")` declares the same primitive
# `threading.Lock()` does — the wrapper is behavior-transparent when off
_SAN_FACTORIES = {"lock": "Lock", "rlock": "RLock",
                  "condition": "Condition"}


def _lock_ctor_kind(node: ast.AST) -> str | None:
    """'Lock' for `threading.Lock()` / bare `Lock()` /
    `san.lock("...")`; handles the `... if cond else None` form."""
    if isinstance(node, ast.IfExp):
        return _lock_ctor_kind(node.body) or _lock_ctor_kind(node.orelse)
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "threading":
            name = f.attr
        elif f.value.id in ("san", "sanitizer") \
                and f.attr in _SAN_FACTORIES:
            return _SAN_FACTORIES[f.attr]
    elif isinstance(f, ast.Name):
        name = f.id
    return name if name in LOCK_CTORS else None


def _ann_class(ann: ast.AST | None) -> str | None:
    """Extract a usable class name from an annotation: `T`, `"T"`,
    `T | None`, `Optional[T]`, `pkg.T`."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return None if ann.id == "None" else ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _ann_class(ann.left) or _ann_class(ann.right)
    if isinstance(ann, ast.Subscript):
        base = _ann_class(ann.value)
        if base == "Optional":
            return _ann_class(ann.slice)
        return None
    return None


def _collect_aliases(tree: ast.Module) -> dict[str, str]:
    """name -> dotted module (for `import m as a`) or `from M import n`
    records the source as 'M:n' so functions resolve cross-module."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}:{a.name}"
    return out


class _ClassScanner(ast.NodeVisitor):
    """Fills a ClassInfo: methods, lock decls, guard annotations, types."""

    def __init__(self, ci: ClassInfo, lines: list[str]):
        self.ci = ci
        self.lines = lines

    def scan(self) -> None:
        for stmt in self.ci.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.ci.methods[stmt.name] = stmt
                self._scan_method(stmt)
            elif isinstance(stmt, ast.ClassDef):
                # nested class (e.g. ChaosProxy._FrameReader): registered
                # as its own top-level-like class by the module scanner
                pass

    def _scan_method(self, fn: ast.FunctionDef) -> None:
        ann = {a.arg: _ann_class(a.annotation)
               for a in (fn.args.args + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                # `self.x: T = ...` declares like a plain assignment
                tgt, value = node.target, node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            else:
                continue
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            kind = _lock_ctor_kind(value)
            texts = _comment_directives(self.lines, node.lineno, _GUARDED_RE)
            if kind is not None:
                guards = _parse_guard_fields(texts) if texts else None
                self.ci.locks.setdefault(attr, LockDecl(
                    self.ci.name, attr, kind, self.ci.module,
                    node.lineno, guards))
                if guards:
                    for f in guards:
                        self.ci.guarded[f] = attr
                continue
            if texts:
                # reverse form on a field: `self.X = ...  # guarded-by: _l`
                locks = _parse_guard_fields(texts)
                if len(locks) == 1:
                    self.ci.guarded[attr] = locks[0]
            self._infer_type(attr, value, ann)

    def _infer_type(self, attr: str, value: ast.AST, ann: dict) -> None:
        t = self._expr_type(value, ann)
        if t is not None and attr not in self.ci.attr_types:
            self.ci.attr_types[attr] = t

    def _expr_type(self, value: ast.AST, ann: dict):
        if isinstance(value, ast.Name):
            return ann.get(value.id)
        if isinstance(value, ast.ListComp):
            elt = self._expr_type(value.elt, ann)
            return ("list", elt) if elt else None
        if isinstance(value, ast.Call):
            f = value.func
            if isinstance(f, ast.Name):
                return f.id          # resolved against classes later
            if isinstance(f, ast.Attribute):
                # module-alias constructor / annotated factory: resolved
                # by the call resolver via aliases + return annotations
                return ("factory", ast.dump(f), f.attr)
        return None


def build_module(path: str, rel: str, src: str | None = None) -> ModuleInfo:
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    tree = ast.parse(src, filename=path)
    mi = ModuleInfo(rel, tree, src.splitlines())
    mi.aliases = _collect_aliases(tree)
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi.functions[stmt.name] = stmt
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            kind = _lock_ctor_kind(stmt.value)
            if kind is not None:
                texts = _comment_directives(mi.lines, stmt.lineno,
                                            _GUARDED_RE)
                guards = _parse_guard_fields(texts) if texts else None
                mi.locks[stmt.targets[0].id] = LockDecl(
                    None, stmt.targets[0].id, kind, mi, stmt.lineno, guards)
    # classes, including nested ones (registered flat by name)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            bases = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    bases.append(b.id)
                elif isinstance(b, ast.Attribute):
                    bases.append(b.attr)
            ci = ClassInfo(node.name, mi, bases, node)
            _ClassScanner(ci, mi.lines).scan()
            mi.classes[node.name] = ci
    return mi


def build_model(files: list[tuple[str, str]]) -> Model:
    """files: [(absolute path, analysis-relative path)]."""
    modules: dict[str, ModuleInfo] = {}
    for path, rel in files:
        modules[rel] = build_module(path, rel)
    classes: dict[str, ClassInfo] = {}
    by_name: dict[str, list] = {}
    guarded_fields: dict[str, list] = {}
    for mi in modules.values():
        for fname, fn in mi.functions.items():
            by_name.setdefault(fname, []).append((mi, fn))
        for ci in mi.classes.values():
            # duplicate class names across modules: keep the first, the
            # resolver then refuses ambiguous cross-object resolution
            classes.setdefault(ci.name, ci)
            for mname, fn in ci.methods.items():
                by_name.setdefault(mname, []).append((ci, fn))
            for field, lock in ci.guarded.items():
                guarded_fields.setdefault(field, []).append((ci, lock))
    return Model(modules, classes, by_name, guarded_fields)


def collect_files(roots: list[str]) -> list[tuple[str, str]]:
    out = []
    for root in roots:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            out.append((root, os.path.basename(root)))
            continue
        base = os.path.dirname(root)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    out.append((full, os.path.relpath(full, base)))
    return out


def caller_holds(fn: ast.FunctionDef, lines: list[str]) -> list[str]:
    """Locks the function's callers are annotated to hold
    (`# caller-holds: _lock` on/above the def line)."""
    return _parse_guard_fields(
        _comment_directives(lines, fn.lineno, _HOLDS_RE))


def is_locked_decorated(fn: ast.FunctionDef) -> bool:
    """`@_locked` — the KV/ShardedKV serialize-on-instance-lock
    decorator: the whole body runs under `self._lock`."""
    for d in fn.decorator_list:
        name = d.attr if isinstance(d, ast.Attribute) else \
            d.id if isinstance(d, ast.Name) else None
        if name == "_locked":
            return True
    return False
