"""Concurrency & JAX-discipline static analyzer (stdlib `ast` only).

The serving plane is deeply threaded — per-connection reader threads
feeding one flush loop, pipelined writer/reader pairs, hedged replica
GETs, breakers, a shared telemetry registry — and until this suite the
only thing enforcing its lock discipline was reviewer memory. Three
rule families, one CLI (`python -m tools.analyze`), one allowlist:

- **guarded-by lint** (`guarded.py`): every `threading.Lock/RLock/
  Condition` attribute in `pmdfc_tpu/` must carry a `# guarded-by:`
  declaration naming the fields it protects, and every write to a
  declared field must happen inside a `with <that lock>:` scope (or in
  a function annotated as running with the lock already held).
- **lock-order graph** (`lockorder.py`): a directed graph built from
  nested with-acquisitions plus resolved call edges (a call made while
  holding L edges L to every lock the callee may acquire). Cycles are
  potential deadlocks; edges must also respect the declared hierarchy
  (`pmdfc_tpu.runtime.sanitizer.HIERARCHY` — the SAME table the
  runtime sanitizer enforces). Hierarchy COVERAGE is a rule too
  (`unranked-lock`): a lock declared in a serving-tier module
  (`lockorder.RANKED_MODULES`, incl. the mesh plane's `parallel/`)
  without a HIERARCHY rank is a finding — new serving locks cannot
  ship opted out of both gates.
- **JAX discipline** (`jaxrules.py`): buffer donation must be keyed on
  the platform (the jax 0.4.37 CPU donation corruption class), jitted
  program bodies must be free of host-side nondeterminism and Python
  side effects, and wire-protocol constants (`MSG_*`, flag bits) must
  not drift from `runtime/net.py`'s canonical definitions.

Findings carry stable ids (`rule:path:qualifier`); the checked-in
`tools/analyze/allowlist.txt` is the only escape, one justified line
per suppression. The dynamic complement is
`pmdfc_tpu/runtime/sanitizer.py` (`PMDFC_SAN=on`).
"""

from __future__ import annotations

import os

from tools.analyze.model import (  # noqa: F401
    Allowlist, Finding, build_model, collect_files)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ROOTS = [os.path.join(_REPO, "pmdfc_tpu")]
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "allowlist.txt")


def run_analysis(roots: list[str] | None = None,
                 allowlist_path: str | None = DEFAULT_ALLOWLIST,
                 ) -> tuple[list[Finding], list[str]]:
    """Full rule suite -> (unallowlisted findings, stale allow entries)."""
    from tools.analyze import guarded, jaxrules, lockorder
    from tools.analyze.resolve import analyze_functions

    files = collect_files(roots or DEFAULT_ROOTS)
    model = build_model(files)
    facts = analyze_functions(model)
    allow = Allowlist.load(allowlist_path)
    findings = (guarded.run(model, facts, allow)
                + lockorder.run(model, facts, allow)
                + jaxrules.run(model, allow))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, allow.unused()
