"""TinyLFU admission-gate suite (`tier.py` + the ISSUE-15 vertical).

The contracts under test:
- sketch mechanics: the doorkeeper holds each key's first touch of an
  epoch, only doorkept touches count into the CM rows, aging halves
  every counter and clears the doorkeeper on the `reset_ops` cadence,
  INVALID lanes estimate zero;
- scan resistance: a cyclic scan's one-touch-per-pass keys are denied
  hot slots while a zipf working set's hot-tier residency holds a
  floor (and without the gate the same scan floods the hot tier);
- the ghost ring keeps its readmission override (the W-TinyLFU
  correction), counted in `admit_ghost_override` as a strict subset of
  `ghost_readmits`;
- `PMDFC_ADMIT=off` is BIT-IDENTICAL to an admission-less config on a
  seeded mixed workload (states, results, and stats);
- restore is refusal-free in every direction and the sketch restarts
  EMPTY (the `checkpoint.strip_admission` contract — snapshot bytes
  are identical with or without the gate);
- the stats lanes ride every surface (`KV.stats`, `shard_report`, the
  wire `MSG_STATS`) with `misses == Σ causes` bit-exact, pinned by
  `tools/check_teledump.check_admission`;
- the autotune `admit_thresh` knob walks DOWN on ghost-readmit
  pressure, UP on demotion churn, clamps to its envelope, and reverts
  with the governor.

Heavier end-to-end scenarios (paired scan-antagonist arms, pressure
pulses) ride the `paging_smoke` agenda step
(`bench/paging_sim.py --job scan_mix --smoke`), the PR-13 tier-budget
note; the sharded reshard drill carries `slow` for the same reason.
"""

import os
import sys

import numpy as np
import pytest

from pmdfc_tpu import checkpoint as ckpt
from pmdfc_tpu import tier as tier_mod
from pmdfc_tpu.config import (AdmitConfig, AutotuneConfig, IndexConfig,
                              KVConfig, NetConfig, TelemetryConfig,
                              TierConfig)
from pmdfc_tpu.kv import KV, MISS_CAUSE_NAMES
from pmdfc_tpu.utils.keys import INVALID_WORD

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pytestmark = pytest.mark.admit

W = 32  # small pages keep the suite inside the tier-1 budget

# Two gated configs shared across drills (each distinct config is a
# fresh jit-compile family; the suite reuses these everywhere the
# drill semantics allow): ADMIT ages slowly (epoch far beyond any
# drill's traffic), ADMIT_FAST ages every 64 touches so a cyclic
# scan's evidence decays between passes.
ADMIT = AdmitConfig(sketch_width=1 << 10, door_bits=1 << 11,
                    reset_ops=4096, threshold=2)
ADMIT_FAST = AdmitConfig(sketch_width=1 << 10, door_bits=1 << 11,
                         reset_ops=64, threshold=2)


def _cfg(capacity=1 << 8, admit=ADMIT, **tkw):
    tkw.setdefault("promote_touches", 1)
    return KVConfig(index=IndexConfig(capacity=capacity), bloom=None,
                    paged=True, page_words=W,
                    tier=TierConfig(admit=admit, **tkw))


def _keys(los):
    los = np.asarray(los, np.uint32)
    return np.stack([los >> 16, los], axis=-1).astype(np.uint32)


def _pages(keys):
    lo = np.asarray(keys, np.uint32)[:, 1]
    return (lo[:, None] * np.uint32(2654435761)
            + np.arange(W, dtype=np.uint32)[None, :])


def _assert_cause_sum(kv):
    s = kv.stats()
    assert s["misses"] == sum(s[k] for k in MISS_CAUSE_NAMES)


# -- sketch mechanics (unit drills on the tier module) -----------------


def test_sketch_doorkeeper_then_cm_and_invalid_lanes():
    import jax.numpy as jnp

    acfg = AdmitConfig(sketch_width=256, door_bits=512, reset_ops=1 << 20)
    ts = tier_mod.init(64, W, TierConfig(admit=acfg))
    keys = jnp.asarray(_keys([5, 9]))
    mask = jnp.ones(2, bool)
    # first touch: doorkeeper only -> estimate 1, CM untouched
    ts = tier_mod.admit_observe(ts, acfg, keys, mask)
    assert list(np.asarray(tier_mod.admit_estimate(ts, acfg, keys))) \
        == [1, 1]
    assert int(np.asarray(ts.admit_cm).sum()) == 0
    # second touch: doorkept -> CM increments, estimate 2
    ts = tier_mod.admit_observe(ts, acfg, keys, mask)
    assert list(np.asarray(tier_mod.admit_estimate(ts, acfg, keys))) \
        == [2, 2]
    assert int(np.asarray(ts.admit_cm).sum()) > 0
    # INVALID lanes estimate zero whatever the sketch holds
    inv = jnp.full((2, 2), INVALID_WORD, jnp.uint32)
    assert not np.asarray(tier_mod.admit_estimate(ts, acfg, inv)).any()
    # a masked-off batch folds nothing (the cond early-out)
    before = np.asarray(ts.admit_ops).copy()
    ts = tier_mod.admit_observe(ts, acfg, keys, jnp.zeros(2, bool))
    assert int(ts.admit_ops) == int(before)


def test_sketch_aging_halves_cm_and_clears_doorkeeper():
    import jax.numpy as jnp

    acfg = AdmitConfig(sketch_width=256, door_bits=512, reset_ops=8)
    ts = tier_mod.init(64, W, TierConfig(admit=acfg))
    keys = jnp.asarray(_keys([5, 9]))
    mask = jnp.ones(2, bool)
    for _ in range(3):  # 6 observed touches: under the epoch budget
        ts = tier_mod.admit_observe(ts, acfg, keys, mask)
    est_before = np.asarray(tier_mod.admit_estimate(ts, acfg, keys))
    assert list(est_before) == [3, 3]
    assert int(np.asarray(ts.admit_door).sum()) > 0
    # the 8th touch spends the epoch: CM halves, doorkeeper clears
    ts = tier_mod.admit_observe(ts, acfg, keys, mask)
    assert int(ts.admit_ops) == 0
    a = tier_mod.admit_counters_dict(ts.admit_stats)
    assert a["admit_age_epochs"] == 1
    assert not np.asarray(ts.admit_door).any()
    # CM counts halved: estimate drops (3 -> floor((3)/2) = 1, door bit
    # gone)
    est_after = np.asarray(tier_mod.admit_estimate(ts, acfg, keys))
    assert (est_after < est_before).all()
    # and the signal re-accumulates in the fresh epoch
    ts = tier_mod.admit_observe(ts, acfg, keys, mask)
    assert (np.asarray(tier_mod.admit_estimate(ts, acfg, keys))
            > est_after).all()


# -- env resolution + conformance --------------------------------------


def test_admit_env_resolution(monkeypatch):
    monkeypatch.setenv("PMDFC_ADMIT", "off")
    kv = KV(_cfg())
    assert kv.state.pool.admit_cm is None
    assert kv.admit_state() is None
    assert not kv.set_admit_threshold(3)
    monkeypatch.setenv("PMDFC_ADMIT", "on")
    kv = KV(_cfg(admit=None))
    assert kv.state.pool.admit_cm is not None  # defaults installed
    monkeypatch.setenv("PMDFC_ADMIT", "banana")
    with pytest.raises(ValueError, match="PMDFC_ADMIT"):
        KV(_cfg())


def test_admit_off_bit_identical_conformance(monkeypatch):
    """PMDFC_ADMIT=off on a gate-configured KV must be BIT-IDENTICAL
    to an admission-less config on a seeded mixed workload: same
    results, same stats, same final state leaves (the construction-time
    kill-switch contract — the TierState never grows the sketch
    leaves, so the compiled programs are the pre-gate programs)."""
    import jax

    monkeypatch.setenv("PMDFC_ADMIT", "off")
    a = KV(_cfg())
    b = KV(_cfg(admit=None))
    rng = np.random.default_rng(11)
    for _ in range(3):
        los = rng.integers(0, 1 << 11, 48).astype(np.uint32)
        keys = _keys(los)
        pages = _pages(keys)
        a.insert(keys, pages)
        b.insert(keys, pages)
        qa, fa = a.get(keys[:24])
        qb, fb = b.get(keys[:24])
        assert (fa == fb).all() and (qa == qb).all()
        da = a.delete(keys[40:])
        db = b.delete(keys[40:])
        assert (da == db).all()
    sa, sb = a.stats(), b.stats()
    sa.pop("uptime_s"), sb.pop("uptime_s")
    assert sa == sb
    assert "admit_denied" not in sa
    for la, lb in zip(jax.tree.leaves(a.state), jax.tree.leaves(b.state)):
        assert (np.asarray(la) == np.asarray(lb)).all()


# -- scan resistance ----------------------------------------------------


def _promote_zipf_set(kv, zipf_keys, zipf_pages):
    kv.insert(zipf_keys, zipf_pages)
    for _ in range(3):  # puts + repeat gets: sketch estimates high
        out, found = kv.get(zipf_keys)
        assert found.all() and (out == zipf_pages).all()


def _hot_resident(kv, keys):
    """How many of `keys` hold hot rows right now."""
    hk = np.asarray(kv.state.pool.hot_keys)
    occ = hk[~np.all(hk == INVALID_WORD, axis=-1)]
    have = {tuple(k) for k in occ}
    return sum(tuple(k) in have for k in keys)


def test_scan_flood_denied_and_zipf_residency_holds():
    """THE scan-flood drill: with the gate (fast aging — scan evidence
    decays between passes), a cyclic scan is denied hot slots and the
    zipf set's hot-tier residency holds a floor; without it the same
    scan floods the hot tier and evicts the zipf set."""
    zipf_keys = _keys(np.arange(1, 25))
    zipf_pages = _pages(zipf_keys)
    scan_keys = _keys(np.arange(1000, 1128))
    scan_pages = _pages(scan_keys)

    gated = KV(_cfg(admit=ADMIT_FAST))
    _promote_zipf_set(gated, zipf_keys, zipf_pages)
    assert _hot_resident(gated, zipf_keys) == len(zipf_keys)
    gated.insert(scan_keys, scan_pages)
    for pas in range(2):  # two cyclic passes, window at a time
        for lo in range(0, len(scan_keys), 32):
            out, found = gated.get(scan_keys[lo:lo + 32])
            assert found.all()
    a = gated.admit_state()
    assert a["admit_denied"] > 0
    # the floor: the zipf working set keeps its hot rows under the flood
    assert _hot_resident(gated, zipf_keys) >= len(zipf_keys) * 3 // 4
    ts = gated.tier_stats()
    assert ts["admit_ghost_override"] <= ts["ghost_readmits"]
    _assert_cause_sum(gated)

    naive = KV(_cfg(admit=None))
    _promote_zipf_set(naive, zipf_keys, zipf_pages)
    assert _hot_resident(naive, zipf_keys) == len(zipf_keys)
    naive.insert(scan_keys, scan_pages)
    for pas in range(2):
        for lo in range(0, len(scan_keys), 32):
            naive.get(scan_keys[lo:lo + 32])
    # the motivation: without admission the scan takes the hot tier
    assert _hot_resident(naive, zipf_keys) \
        < _hot_resident(gated, zipf_keys)
    assert naive.tier_stats()["demotions"] \
        > gated.tier_stats()["demotions"]
    _assert_cause_sum(naive)


def test_ghost_override_readmits_below_threshold():
    """The W-TinyLFU correction: a demoted key readmits via the ghost
    ring even when its sketch estimate alone would be refused, counted
    in `admit_ghost_override` (⊆ ghost_readmits). Demotion is staged
    through the LIVE threshold knob (`set_admit_threshold(0)` opens the
    gate so the flood can take A's slot, then 2 restores it before the
    readmit — exercising the knob end to end)."""
    kv = KV(_cfg(capacity=1 << 8, admit=ADMIT_FAST,
                 hot_fraction=64, ghost_rows=64))
    h = tier_mod.num_hot_rows(1 << 8, kv.config.tier)
    keys = _keys(np.arange(1, 3 * h + 2))
    kv.insert(keys, _pages(keys))
    a = keys[:1]
    for _ in range(3):
        kv.get(a)  # promote A (repeat touches beat the threshold)
    assert _hot_resident(kv, a) == 1
    # open the gate and flood: A's evidence ages away (reset_ops=64)
    # while the flood keys stay freshly touched, so the victim duel
    # eventually costs A its slot and the ghost ring remembers it
    assert kv.set_admit_threshold(0)
    rest = keys[1:2 * h + 1]
    for _ in range(6):
        kv.get(rest)
        kv.get(rest)
        if _hot_resident(kv, a) == 0:
            break
    assert _hot_resident(kv, a) == 0
    assert kv.tier_stats()["demotions"] >= 1
    # gate back up: A's estimate is aged below the threshold, so the
    # readmit can only be the ghost ring's say-so
    assert kv.set_admit_threshold(2)
    import jax.numpy as jnp

    est_a = int(np.asarray(tier_mod.admit_estimate(
        kv.state.pool, ADMIT_FAST, jnp.asarray(a)))[0])
    assert est_a < 2, est_a
    before = kv.tier_stats()
    out, found = kv.get(a)
    assert found.all() and (out == _pages(a)).all()
    after = kv.tier_stats()
    assert after["ghost_readmits"] > before["ghost_readmits"]
    assert after["admit_ghost_override"] \
        > before["admit_ghost_override"]
    assert after["admit_ghost_override"] <= after["ghost_readmits"]
    _assert_cause_sum(kv)


def test_put_is_a_touch():
    """The insert path feeds the sketch: a key the client keeps
    RE-WRITING earns admission the same way a re-read one does (the
    GET's own fold adds one more touch — threshold 3 splits four puts
    from one)."""
    kv = KV(_cfg(admit=AdmitConfig(sketch_width=1 << 10,
                                   door_bits=1 << 11,
                                   reset_ops=4096, threshold=3)))
    hot = _keys([7])
    cold = _keys([9])
    pages_h, pages_c = _pages(hot), _pages(cold)
    for _ in range(4):  # four puts: estimate 4 before any read
        kv.insert(hot, pages_h)
    kv.insert(cold, pages_c)  # one put: estimate 1 (doorkeeper only)
    out, found = kv.get(hot)  # +1 touch: 5 >= 3 -> admitted
    assert found.all()
    assert _hot_resident(kv, hot) == 1
    out, found = kv.get(cold)  # +1 touch: 2 < 3 -> denied
    assert found.all()
    assert _hot_resident(kv, cold) == 0
    assert kv.admit_state()["admit_denied"] >= 1


# -- restore / reshard (restart-empty, refusal-free) -------------------


def test_restore_restart_empty_matrix(tmp_path):
    """Snapshot bytes are identical with or without the gate
    (`checkpoint.strip_admission`), so every restore direction is
    refusal-free and the sketch restarts EMPTY — the evicted-filter
    discipline, with the walked threshold restarting at its config
    default (the autotune controller re-walks it)."""
    cfg_g, cfg_n = _cfg(), _cfg(admit=None)
    keys = _keys(np.arange(1, 33))
    pages = _pages(keys)
    kv = KV(cfg_g)
    kv.insert(keys, pages)
    kv.get(keys)
    kv.set_admit_threshold(9)
    assert kv.admit_state()["ops"] > 0
    p_g = str(tmp_path / "gate.ckpt")
    kv.snapshot(p_g)
    # gate -> gate: fresh sketch, threshold back at the config default
    kv2 = KV(cfg_g, state=ckpt.load(p_g, cfg_g))
    a = kv2.admit_state()
    assert a["threshold"] == ADMIT.threshold and a["epochs"] == 0
    assert a["ops"] == 0 and a["admit_denied"] == 0
    out, found = kv2.get(keys)
    assert found.all() and (out == pages).all()
    # gate -> no-gate: loads clean, no admission surface
    kv3 = KV(cfg_n, state=ckpt.load(p_g, cfg_n))
    assert kv3.admit_state() is None
    out, found = kv3.get(keys)
    assert found.all() and (out == pages).all()
    # no-gate (the pre-gate snapshot shape) -> gate: transplanted empty
    kvn = KV(cfg_n)
    kvn.insert(keys, pages)
    p_n = str(tmp_path / "plain.ckpt")
    kvn.snapshot(p_n)
    kv4 = KV(cfg_g, state=ckpt.load(p_n, cfg_g))
    assert kv4.admit_state() is not None
    assert kv4.admit_state()["epochs"] == 0
    out, found = kv4.get(keys)
    assert found.all() and (out == pages).all()


@pytest.mark.slow
def test_sharded_restore_and_reshard_restart_empty(tmp_path):
    """Same-count restore and a 2->3 reshard both land with a fresh
    stacked sketch (the reshard target's init supplies it; same-count
    transplants) — zero lost live pages either way."""
    import jax

    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh

    cfg = _cfg()
    keys = _keys(np.arange(1, 49))
    pages = _pages(keys)
    skv = ShardedKV(cfg, mesh=make_mesh(jax.devices("cpu")[:2]),
                    dispatch="broadcast")
    skv.insert(keys, pages)
    skv.get(keys)
    p = str(tmp_path / "s.ckpt")
    skv.save(p)
    s2 = ShardedKV(cfg, mesh=make_mesh(jax.devices("cpu")[:2]),
                   dispatch="broadcast")
    s2.restore(p)
    out, found = s2.get(keys)
    assert found.all() and (out == pages).all()
    a = s2.admit_state()
    assert a is not None and a["epochs"] == 0
    s3 = ShardedKV(cfg, mesh=make_mesh(jax.devices("cpu")[:3]),
                   dispatch="broadcast")
    s3.restore(p)
    out, found = s3.get(keys)
    assert found.all() and (out == pages).all()
    assert s3.admit_state() is not None
    rep = s3.shard_report()
    assert len(rep["tier"]["admit_denied"]) == 3


# -- stats surfaces + schema pins --------------------------------------


def test_stats_surfaces_and_wire_pins():
    """Admission lanes ride `KV.stats` and the wire MSG_STATS with the
    cause-sum invariant intact, and the pulled document passes
    `check_teledump.check` including the new `check_admission` pins."""
    from tools import check_teledump

    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.runtime import telemetry
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    telemetry.configure(TelemetryConfig(enabled=True))
    kv = KV(_cfg())
    keys = _keys(np.arange(1, 33))
    kv.insert(keys, _pages(keys))
    kv.get(keys)
    kv.get(_keys(np.arange(900, 916)))  # misses: causes must reconcile
    with NetServer(lambda: DirectBackend(kv),
                   net=NetConfig(flush_timeout_us=0, settle_us=0)) as srv:
        srv.start()
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            doc = be.server_stats()
    for k in tier_mod.ADMIT_STAT_NAMES + ["admit_threshold"]:
        assert k in doc, k
    assert doc["misses"] == sum(doc[k] for k in MISS_CAUSE_NAMES)
    assert check_teledump.check(doc) == []
    # the pins bite: drifted override > readmits, torn lanes, bad sums
    bad = dict(doc)
    bad["admit_ghost_override"] = bad["ghost_readmits"] + 1
    assert any("subset" in e for e in check_teledump.check_admission(bad))
    bad = dict(doc)
    del bad["admit_victim_kept"]
    assert check_teledump.check_admission(bad)
    bad = dict(doc)
    bad["shard_report"] = {"tier": {
        "admit_denied": [bad["admit_denied"] + 1]}}
    assert any("drift" in e for e in check_teledump.check_admission(bad))
    # teletop renders the admission block off the same document
    from tools import teletop

    row = teletop.summarize("x:0", doc)
    assert row["tier"]["admit"]["threshold"] == ADMIT.threshold


# -- autotune knob ------------------------------------------------------


class _FakeGatedKV:
    """Host-only stand-in: balloon + admission surfaces with scripted
    stats deltas (the controller only ever sees these surfaces)."""

    def __init__(self, ghost_per_k=0, churn_per_k=0):
        self.n = 0
        self.th = 8
        self.g, self.c = ghost_per_k, churn_per_k

    def balloon_state(self):
        return {"cold_rows": 1024, "circulating": 1024, "parked": 0,
                "free": 64, "step": 64}

    def balloon_grow(self, rows):
        return True

    def balloon_shrink(self, rows):
        return True

    def admit_state(self):
        return {"threshold": self.th}

    def set_admit_threshold(self, v):
        self.th = v
        return True

    def stats(self):
        self.n += 1
        return {"gets": 1000 * self.n, "ghost_readmits": self.g * self.n,
                "demotions": self.c * self.n, "miss_evicted": 0,
                "miss_parked": 0}


def _drive_ctl(fk, rounds, cfg=None):
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime import autotune
    from pmdfc_tpu.runtime import telemetry as tele
    from pmdfc_tpu.runtime import timeseries as ts
    from pmdfc_tpu.runtime.net import NetServer

    reg = tele.configure(TelemetryConfig())
    ring = ts.SeriesRing(capacity=256, interval_s=1.0)
    reg.series_sink = ring
    srv = NetServer(lambda: LocalBackend(page_words=8), net=NetConfig())
    ctl = autotune.AutotuneController(
        cfg or AutotuneConfig(balloon_every=1, hysteresis_windows=1))
    ctl.bind_server(srv)
    ctl.bind_balloon(fk)
    pfx = srv.stats.prefix + "."
    t = [0.0]

    def win():
        t[0] += 1.0
        return {"t": t[0], "dt_s": 1.0,
                "counters": {pfx + "coalesced_ops": 100},
                "gauges": {pfx + "staging_depth": 1},
                "hists": {pfx + "flush_ops_hist":
                          {"count": 100, "sum": 105, "p50": 1,
                           "p95": 2, "p99": 2}}}

    decs = []
    for _ in range(rounds):
        ring.push(win())
        decs += ctl.tick()
    return ctl, decs


def test_autotune_admit_knob_registration_and_walks():
    ctl, _ = _drive_ctl(_FakeGatedKV(), 1)
    assert "admit_thresh" in ctl.knob_values()
    assert ctl.knob_values()["admit_thresh"] == 8.0
    # ghost-readmit pressure: the gate is too strict, threshold DOWN
    fk = _FakeGatedKV(ghost_per_k=100)
    _, decs = _drive_ctl(fk, 6)
    assert fk.th < 8
    moves = [d for d in decs if d.get("knob") == "admit_thresh"]
    assert moves and all("ghost" in d["why"] for d in moves)
    # demotion churn with a quiet ghost lane: scan leak, threshold UP
    fk = _FakeGatedKV(churn_per_k=100)
    _drive_ctl(fk, 6)
    assert fk.th > 8
    # both quiet: hold
    fk = _FakeGatedKV()
    _drive_ctl(fk, 6)
    assert fk.th == 8
    # envelope clamp at admit_hi
    fk = _FakeGatedKV(churn_per_k=500)
    ctl, _ = _drive_ctl(fk, 60)
    assert fk.th == int(AutotuneConfig().admit_hi)
    assert ctl.knob_values()["admit_thresh"] == AutotuneConfig().admit_hi


def test_autotune_admit_knob_cadence_exemption():
    """A non-cadence round never resets the admit knob's hysteresis
    streak (the balloon_x discipline: a round that never looked cannot
    disagree) — with balloon_every=2 and hysteresis 2 the knob still
    moves once two cadence rounds have AGREED (the first cadence round
    only arms the stats delta)."""
    fk = _FakeGatedKV(ghost_per_k=100)
    _drive_ctl(fk, 8, AutotuneConfig(balloon_every=2,
                                     hysteresis_windows=2))
    assert fk.th < 8


def test_autotune_no_gate_no_knob():
    class _Flat(_FakeGatedKV):
        def admit_state(self):
            return None

    ctl, _ = _drive_ctl(_Flat(), 1)
    assert "admit_thresh" not in ctl.knob_values()
    assert "balloon_x" in ctl.knob_values()


# -- partitioning coverage ----------------------------------------------


def test_axis_rules_cover_admit_leaves():
    from pmdfc_tpu.parallel import partitioning as pt

    rows = pt.describe(_cfg())
    leaves = {r["leaf"] for r in rows}
    for name in ("admit_cm", "admit_door", "admit_ops", "admit_thresh",
                 "admit_stats"):
        assert f".pool.{name}" in leaves
    for r in rows:
        assert r["axes"][0] == pt.SHARD
        assert "kv" in r["spec"], r
