"""KV façade tests: insert/get/delete/extent/find_anyway/stats.

Behavior contract from the reference: every inserted key is gettable unless
evicted (`server/test_KV.cpp` failedSearch accounting); evictions propagate
into bloom deletes (`server/KV.cpp:107-121`); extents resolve any page inside
the run to `value + 4096 * (key - base)` (`server/KV.cpp:165-179`).
"""

import dataclasses

import numpy as np

from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.ops import bloom as bloom_ops
from pmdfc_tpu.utils.keys import pack_key


def small_cfg(paged=False, capacity=1 << 12):
    return KVConfig(
        index=IndexConfig(capacity=capacity),
        bloom=BloomConfig(num_bits=1 << 14),
        paged=paged,
        page_words=16,
    )


def u64vals(lo):
    lo = np.asarray(lo, np.uint32)
    return np.stack([np.zeros_like(lo), lo], axis=-1)


def keys_of(lo, hi=1):
    lo = np.asarray(lo, np.uint32)
    return np.asarray(pack_key(np.full_like(lo, hi), lo))


def test_insert_then_get_roundtrip():
    kv = KV(small_cfg())
    ks = keys_of(np.arange(500))
    kv.insert(ks, u64vals(np.arange(500) * 3))
    out, found = kv.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out[:, 1], np.arange(500) * 3)


def test_miss_is_legal():
    kv = KV(small_cfg())
    _, found = kv.get(keys_of([42]))
    assert not found.any()
    s = kv.stats()
    assert s["misses"] == 1 and s["gets"] >= 1


def test_paged_roundtrip():
    cfg = small_cfg(paged=True)
    kv = KV(cfg)
    rng = np.random.default_rng(0)
    ks = keys_of(np.arange(64))
    pages = rng.integers(0, 2**32, size=(64, cfg.page_words), dtype=np.uint32)
    kv.insert(ks, pages)
    out, found = kv.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out, pages)


def test_update_in_place():
    kv = KV(small_cfg())
    ks = keys_of([9])
    kv.insert(ks, u64vals([1]))
    kv.insert(ks, u64vals([2]))
    out, found = kv.get(ks)
    assert found.all() and out[0, 1] == 2


def test_eviction_propagates_to_bloom():
    # tiny index: 1 cluster of 16 slots -> inserting 32 keys evicts the
    # first 16; the bloom filter must then reject them (no false negatives
    # for live keys, and evicted keys were deleted).
    cfg = KVConfig(
        index=IndexConfig(capacity=16, cluster_slots=16),
        bloom=BloomConfig(num_bits=1 << 14),
        paged=False,
    )
    kv = KV(cfg)
    for start in range(0, 32, 8):
        ks = keys_of(np.arange(start, start + 8))
        kv.insert(ks, u64vals(np.arange(start, start + 8)))
    s = kv.stats()
    assert s["evictions"] == 16
    # live keys still pass the bloom filter
    live = keys_of(np.arange(16, 32))
    q = bloom_ops.query_batch(kv.state.bloom, live, num_hashes=4)
    assert bool(np.asarray(q).all())
    # counters returned to zero for fully-evicted-and-deleted set
    out, found = kv.get(keys_of(np.arange(16)))
    assert not found.any()


def test_delete():
    kv = KV(small_cfg())
    ks = keys_of(np.arange(10))
    kv.insert(ks, u64vals(np.arange(10)))
    hit = kv.delete(keys_of([3, 4, 99]))
    assert list(hit) == [True, True, False]
    _, found = kv.get(ks)
    assert found.sum() == 8


def test_extent_roundtrip():
    kv = KV(small_cfg())
    base = 100
    length = 13
    kv.insert_extent(keys_of([base])[0], np.array([0, 5000], np.uint32), length)
    probe = keys_of(np.arange(base, base + length))
    out, found = kv.get_extent(probe)
    assert found.all()
    np.testing.assert_array_equal(
        out[:, 1], 5000 + np.arange(length, dtype=np.uint32) * 4096
    )
    # outside the run: miss (stricter than the reference, which could return
    # a stale cover)
    out2, found2 = kv.get_extent(keys_of([base + length, base - 1]))
    assert not found2.any()


def test_extent_cover_count_is_logarithmic():
    kv = KV(small_cfg())
    kv.insert_extent(keys_of([0])[0], np.array([0, 0], np.uint32), 1024)
    # 1024 aligned at 0 -> exactly 1 cover entry
    assert kv.stats()["extent_puts"] == 1
    u = kv.utilization()
    assert u * kv.capacity() <= 2


def test_key_with_all_ones_hi_word_survives_padding():
    # regression: a valid key with hi == 0xFFFFFFFF must not collide with
    # INVALID padding rows in the batch dedupe sort
    kv = KV(small_cfg())
    ks = keys_of(np.arange(30), hi=0xFFFFFFFF)
    res = kv.insert(ks, u64vals(np.arange(30)))
    assert (res.slots >= 0).all() and not res.dropped.any()
    out, found = kv.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out[:, 1], np.arange(30))


def test_large_extent_reachable():
    # regression: covers bigger than 2**(max_height-1) were unreachable by
    # get_extent's height probes
    kv = KV(small_cfg())
    _, uncovered = kv.insert_extent(
        keys_of([0])[0], np.array([0, 0], np.uint32), 1 << 16
    )
    assert uncovered == 0
    probe = keys_of([40000, (1 << 16) - 1, 1 << 16])
    _, found = kv.get_extent(probe)
    assert list(found) == [True, True, False]


def test_extent_truncation_reported():
    cfg = dataclasses.replace(small_cfg(), extent_max_covers=4)
    kv = KV(cfg)
    # base 1 with a long run needs many covers; only 4 fit -> tail reported
    _, uncovered = kv.insert_extent(
        keys_of([1])[0], np.array([0, 0], np.uint32), 1000
    )
    assert uncovered > 0


def test_find_anyway_and_utilization():
    kv = KV(small_cfg())
    ks = keys_of(np.arange(100))
    kv.insert(ks, u64vals(np.arange(100)))
    vals, found, slots = kv.find_anyway(keys_of([50, 7777]))
    assert list(found) == [True, False]
    assert vals[0, 1] == 50
    assert 0 < kv.utilization() < 1
    assert kv.capacity() >= 4096
    assert kv.recovery()


def test_stats_counts():
    kv = KV(small_cfg())
    ks = keys_of(np.arange(20))
    kv.insert(ks, u64vals(np.arange(20)))
    kv.get(ks)
    kv.get(keys_of([999]))
    s = kv.stats()
    assert s["puts"] == 20 and s["hits"] == 20 and s["misses"] == 1
    assert "puts=" in kv.print_stats()


def test_paged_pool_rows_recycled_under_eviction():
    # Index much smaller than the insert stream: evictions must recycle
    # pool rows so live keys always read back their own page and the free
    # stack never leaks (top == rows - live entries).
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 8),
        bloom=None,
        paged=True,
        page_words=8,
    )
    kv = KV(cfg)
    rng = np.random.default_rng(1)
    n = 2048
    ks = keys_of(np.arange(n))
    pages = rng.integers(0, 2**32, size=(n, 8), dtype=np.uint32)
    for i in range(0, n, 128):
        kv.insert(ks[i : i + 128], pages[i : i + 128])
    out, found = kv.get(ks)
    assert found.sum() > 0 and (~found).sum() > 0  # churn really evicted
    np.testing.assert_array_equal(out[found], pages[found])
    # free-row accounting: live entries == allocated rows
    import jax.numpy as jnp
    from pmdfc_tpu.kv import utilization

    live = float(utilization(kv.state, cfg)) * kv.capacity()
    top = int(kv.state.pool.top)
    assert top == kv.capacity() - round(live)


def test_paged_delete_frees_rows():
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 8), bloom=None, paged=True,
        page_words=8,
    )
    kv = KV(cfg)
    ks = keys_of(np.arange(32))
    pages = np.arange(32 * 8, dtype=np.uint32).reshape(32, 8)
    kv.insert(ks, pages)
    top0 = int(kv.state.pool.top)
    assert kv.delete(ks[:10]).all()
    assert int(kv.state.pool.top) == top0 + 10
    # reinserting reuses freed rows and round-trips
    kv.insert(ks[:10], pages[:10] + 7)
    out, found = kv.get(ks[:10])
    assert found.all()
    np.testing.assert_array_equal(out, pages[:10] + 7)


def test_fill_sweep_point_conformance():
    """The fill-sweep harness's accounting must satisfy the test_KV rule
    (misses <= evictions + drops) at nominal capacity, where the
    eviction-substitute cost is nonzero for cuckoo."""
    from pmdfc_tpu.bench.fill_sweep import run_point

    r = run_point("cuckoo", capacity=1 << 12, fill=1.0, batch=1 << 10)
    assert r["conformance_ok"]
    assert r["misses"] <= r["evictions"] + r["drops"]
    # and the no-growth families really do lose entries at this fill
    r2 = run_point("linear", capacity=1 << 12, fill=1.2, batch=1 << 10)
    assert r2["conformance_ok"] and r2["miss_rate"] > 0


# --- integrity: per-page checksums (the tier-1 rung of the ladder) ------


def test_corrupt_page_degrades_to_miss_never_wrong_bytes():
    """Poisoned pool bytes must NEVER be returned: the insert-time digest
    mismatches at get, the page degrades to a first-class miss, and
    `corrupt_pages` counts it — the clean-cache contract (lose anything,
    serve nothing wrong) extended to bytes at rest."""
    import jax.numpy as jnp

    kv = KV(small_cfg(paged=True))
    ks = keys_of(np.arange(64))
    pages = (np.arange(64, dtype=np.uint32)[:, None]
             + np.arange(16, dtype=np.uint32) * 3)
    kv.insert(ks, pages)
    out, found = kv.get(ks)
    assert found.all() and np.array_equal(out, pages)

    # bit-rot every row in place (digest sidecar untouched)
    pool = kv.state.pool
    kv.state = dataclasses.replace(
        kv.state,
        pool=dataclasses.replace(
            pool, pages=pool.pages ^ jnp.uint32(1 << 7)),
    )
    out, found = kv.get(ks)
    assert not found.any(), "corrupt pages served as hits"
    assert (out == 0).all(), "corrupt bytes leaked to the caller"
    assert kv.stats()["corrupt_pages"] == 64
    # misses account the degraded gets — the ladder stays observable
    assert kv.stats()["misses"] >= 64


def test_corrupt_page_miss_on_compact_path():
    """The serving path (hit-compacted GET) takes the same integrity
    gate: a corrupt row is excluded from the compacted return."""
    import jax.numpy as jnp

    from pmdfc_tpu import kv as kv_mod

    cfg = small_cfg(paged=True)
    kv = KV(cfg)
    ks = keys_of(np.arange(32))
    pages = (np.arange(32, dtype=np.uint32)[:, None]
             + np.arange(16, dtype=np.uint32))
    kv.insert(ks, pages)
    # find key 0's pool row through the index and poison just that row
    vals, found, _ = kv.find_anyway(ks[:1])
    assert found[0]
    row = int(vals[0][1])
    pool = kv.state.pool
    kv.state = dataclasses.replace(
        kv.state,
        pool=dataclasses.replace(
            pool, pages=pool.pages.at[row, 3].add(jnp.uint32(1))),
    )
    state, out, order, fmask, nfound = kv_mod.get_compact(
        kv.state, cfg, jnp.asarray(np.vstack([ks, ks[:4]])[:32]))
    fmask = np.asarray(fmask)
    assert not fmask[0], "poisoned row survived the compact path"
    assert fmask[1:32].all()
    assert int(nfound) == 31
    # the compacted rows that DID return carry exact content
    order = np.asarray(order)[: int(nfound)]
    np.testing.assert_array_equal(np.asarray(out)[: int(nfound)],
                                  pages[order])


def test_update_refreshes_digest_and_delete_clears_row():
    """Digest follows the newest write: an update re-digests in place and
    a reinsert after delete re-digests the recycled row."""
    kv = KV(small_cfg(paged=True))
    ks = keys_of(np.arange(8))
    a = np.full((8, 16), 5, np.uint32)
    b = np.full((8, 16), 9, np.uint32)
    kv.insert(ks, a)
    kv.insert(ks, b)  # in-place update path
    out, found = kv.get(ks)
    assert found.all() and np.array_equal(out, b)
    kv.delete(ks[:4])
    kv.insert(ks[:4], a[:4])  # recycled-row path
    out, found = kv.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out[:4], a[:4])
    np.testing.assert_array_equal(out[4:], b[4:])
    assert kv.stats()["corrupt_pages"] == 0


def test_integrity_backend_stale_overwrite_degrades_to_miss():
    """Review-found crash regression: another writer overwrites a key this
    client also put; the client's end-to-end digest must degrade the now-
    unexpected page to a miss (stale data is not a legal hit) WITHOUT
    raising — KV-backed backends return read-only numpy views."""
    from pmdfc_tpu.client.backends import DirectBackend, IntegrityBackend

    kv = KV(small_cfg(paged=True))
    be = IntegrityBackend(DirectBackend(kv))
    ks = keys_of(np.arange(8))
    v1 = np.full((8, 16), 3, np.uint32)
    v2 = np.full((8, 16), 4, np.uint32)
    be.put(ks, v1)
    kv.insert(ks, v2)  # out-of-band overwrite (not through the wrapper)
    out, found = be.get(ks)  # must not raise on the read-only array
    assert not found.any()
    assert (out == 0).all()
    assert be.counters["corrupt_pages"] == 8
    # the wrapper's own put refreshes the digest and service resumes
    be.put(ks, v2)
    out, found = be.get(ks)
    assert found.all() and np.array_equal(out, v2)
