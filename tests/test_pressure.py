"""Macro pressure workloads: filebench personalities + training analog.

Ref: `client/filebench/*.f` personalities and the BERT fine-tuning
pressure app (`client/BERT/run.py`) — SURVEY §4.5. Personalities run here
over the hermetic LocalBackend (fast, no device); the training harness
runs as a subprocess exactly as a user would invoke it.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from pmdfc_tpu.bench.filebench import Fileset, run_personality
from pmdfc_tpu.bench.paging_sim import PagingSim
from pmdfc_tpu.client.backends import LocalBackend
from pmdfc_tpu.client.cleancache import CleanCacheClient

W = 32


def _sim(ram_pages=64, capacity=4096):
    client = CleanCacheClient(LocalBackend(W, capacity))
    return PagingSim(client, ram_pages, W), client


def test_fileserver_personality_verifies():
    sim, _ = _sim()
    out = run_personality(sim, "fileserver", loops=12, nfiles=16,
                          mean_pages=4)
    assert out["verify_failures"] == 0
    assert out["files_created"] == 12 and out["files_deleted"] == 12
    assert out["pages_read"] > 0 and out["pages_written"] > 0


def test_webserver_personality_verifies():
    sim, _ = _sim()
    out = run_personality(sim, "webserver", loops=10, nfiles=16,
                          mean_pages=4, reads_per_loop=5)
    assert out["verify_failures"] == 0
    # readonly fileset + log appends: reads dominate writes after prealloc
    assert out["pages_read"] > out["files_created"]


def test_dgwebserver_scales_fileset():
    sim, _ = _sim(ram_pages=32)
    out = run_personality(sim, "dgwebserver", loops=4, nfiles=8,
                          mean_pages=2, reads_per_loop=3)
    assert out["verify_failures"] == 0


def test_randomread_working_set():
    sim, _ = _sim(ram_pages=16)
    out = run_personality(sim, "randomread", loops=400, nfiles=8,
                          mean_pages=8, working_set=0.25)
    assert out["verify_failures"] == 0
    assert out["pages_read"] == 400
    # a 0.25 working set over 64 pages mostly exceeds 16 RAM pages, so the
    # clean cache must have served a real share of the faults
    assert out["cc_hits"] > 0


def test_trim_is_invalidate_inode():
    """After trim, old content must never serve: rewrite the file with new
    content and read it back through every cache layer."""
    sim, client = _sim(ram_pages=8)
    fid = 5
    for i in range(16):
        sim.write(fid, i)
    for i in range(16):
        sim.read(fid, i)  # cycles pages through RAM + clean cache
    sim.trim(fid, range(16))
    assert all((fid, i) not in sim.versions for i in range(16))
    # fresh generation: version counters restart; reads must verify
    for i in range(16):
        sim.write(fid, i)
    for i in range(16):
        sim.read(fid, i)
    assert sim.stats["verify_failures"] == 0


def test_fileset_gamma_sizes():
    rng = np.random.default_rng(0)
    fs = Fileset(rng, 200, mean_pages=8)
    sizes = np.array(list(fs.sizes.values()))
    assert sizes.min() >= 1
    assert 4 <= sizes.mean() <= 12  # gamma(1.5) around the mean
    assert sizes.max() > sizes.mean() * 2  # heavy tail exists


@pytest.mark.slow
def test_train_pressure_learns():
    proc = subprocess.run(
        [sys.executable, "-m", "pmdfc_tpu.bench.train_pressure",
         "--steps", "60", "--corpus-pages", "256", "--ram-pages", "64",
         "--page-words", "256", "--batch", "32", "--capacity", "4096",
         "--device", "cpu"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["verify_failures"] == 0
    assert out["learned"], (out["loss_first"], out["loss_last"])
    assert out["cc_hits"] > 0  # pressure actually flowed through the cache


def _swap_sim(ram_pages, capacity, page_words=32):
    from pmdfc_tpu.bench.swap_sim import SwapSim
    from pmdfc_tpu.client.cleancache import SwapClient

    client = SwapClient(LocalBackend(page_words, capacity))
    return SwapSim(client, ram_pages, page_words)


def test_swap_randread_all_remote():
    """Ample remote capacity: every fault after warm is a remote swap hit
    (the juleeswap fio-4K-randread fast path)."""
    from pmdfc_tpu.bench.swap_sim import run

    sim = _swap_sim(ram_pages=32, capacity=4096)
    out = run(sim, ops=800, working_pages=128, write_frac=0.0)
    assert out["verify_failures"] == 0
    assert out["disk_hits"] == 0
    assert out["swap_hit_frac"] == 1.0
    assert out["faults"] > 0


def test_swap_drops_recover_from_device():
    """A clean-cache KV may drop stored pages; writethrough means every
    drop is served by the swap device — never data loss."""
    from pmdfc_tpu.bench.swap_sim import run

    sim = _swap_sim(ram_pages=16, capacity=48)  # force remote eviction
    out = run(sim, ops=600, working_pages=128, write_frac=0.0)
    assert out["verify_failures"] == 0
    assert out["disk_hits"] > 0          # drops happened and were recovered
    assert out["swap_hits"] > 0          # the fast path still served some


def test_swap_writes_never_serve_stale():
    """Swap-in invalidates both copies; rewritten pages re-swap with their
    new version and always verify."""
    from pmdfc_tpu.bench.swap_sim import run

    sim = _swap_sim(ram_pages=16, capacity=4096)
    out = run(sim, ops=800, working_pages=64, write_frac=0.5)
    assert out["verify_failures"] == 0
    # pin the swap-slot-free semantics directly (frontswap
    # invalidate_page): after a fault is served, NEITHER copy remains
    sim2 = _swap_sim(ram_pages=2, capacity=4096)
    for off in (1, 2, 3):  # 3 > ram 2 ⇒ offset 1 swaps out
        sim2.touch(off, write=True)
    assert sim2.client.load(0, 1) is not None  # remotely stored
    assert 1 in sim2.disk                      # writethrough copy
    sim2.touch(1, write=False)                 # fault it back in
    assert sim2.client.load(0, 1) is None, "remote copy must be freed"
    assert 1 not in sim2.disk, "device copy must be freed"
    assert sim2.stats["verify_failures"] == 0


def test_swap_iodepth_batch_path_verifies():
    """The fio-iodepth batched fault path (touch_batch) must preserve the
    writethrough/no-stale invariants of the per-touch path: zero verify
    failures under mixed read/write with duplicates in a window, and the
    swap slot freed on swap-in."""
    from pmdfc_tpu.bench.swap_sim import run

    sim = _swap_sim(ram_pages=16, capacity=4096)
    out = run(sim, ops=800, working_pages=64, write_frac=0.3, iodepth=8)
    assert out["verify_failures"] == 0
    assert out["faults"] > 0 and out["swap_hits"] > 0
    assert out["touches"] == 800

    # duplicates within one window: first service faults, rest are hits
    sim2 = _swap_sim(ram_pages=4, capacity=4096)
    import numpy as np

    sim2.touch_batch(np.array([7, 7, 7, 8]), np.zeros(4, bool))
    assert sim2.stats["faults"] == 2          # 7 once, 8 once
    assert sim2.stats["ram_hits"] == 2        # the duplicate 7s
    assert sim2.stats["verify_failures"] == 0


def test_swap_parallel_jobs_aggregate():
    """run_jobs: disjoint swap areas over one shared backend, aggregated
    accounting, no data loss."""
    from pmdfc_tpu.bench.swap_sim import SwapSim, run_jobs
    from pmdfc_tpu.client.cleancache import SwapClient

    client = SwapClient(LocalBackend(32, 8192))
    out = run_jobs(
        lambda j: SwapSim(client, 16, 32, swap_type=j),
        n_jobs=4, ops=1600, working_pages=256, write_frac=0.2, iodepth=8,
    )
    assert out["verify_failures"] == 0
    assert out["jobs"] == 4 and out["touches"] == out["ops"]
    assert out["swap_hits"] > 0


def test_paging_read_batch_matches_per_op_semantics():
    """read_batch (iodepth window) must preserve read()'s accounting and
    verification: same hits/faults totals on the same access sequence, no
    verify failures, RAM never over cap."""
    import numpy as np

    from pmdfc_tpu.bench.paging_sim import PagingSim
    from pmdfc_tpu.client import CleanCacheClient

    def build():
        return PagingSim(CleanCacheClient(LocalBackend(16, 4096)),
                         ram_pages=32, page_words=16)

    rng = np.random.default_rng(5)
    seq = rng.integers(128, size=512)
    a, b = build(), build()
    for i in seq:
        a.read(1, int(i))
    for lo in range(0, 512, 8):
        b.read_batch(1, seq[lo:lo + 8])
    a.flush_evictions(); b.flush_evictions()
    assert a.stats["verify_failures"] == b.stats["verify_failures"] == 0
    assert a.stats["reads"] == b.stats["reads"] == 512
    # totals conserve: every read is a hit or a fault in both modes
    for s in (a.stats, b.stats):
        assert s["ram_hits"] + s["cc_hits"] + s["disk_reads"] == 512
    assert len(b.ram) <= 32
