"""Client library tests: cleancache/frontswap surface, bloom mirror, backends,
paging simulator, trace replay, dataset generators."""

import numpy as np

from pmdfc_tpu.bench.gen_input import (
    load,
    one_to_n,
    repeated,
    save,
    sequential,
    uniform,
    zipf,
)
from pmdfc_tpu.bench.paging_sim import PagingSim, page_content, run_job
from pmdfc_tpu.bench.replay import parse_trace, replay, synthetic_trace
from pmdfc_tpu.client import (
    CleanCacheClient,
    DirectBackend,
    LocalBackend,
    SwapClient,
    get_longkey,
)
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV


def direct_backend(capacity=1 << 10, page_words=16, bloom=True):
    cfg = KVConfig(
        index=IndexConfig(capacity=capacity),
        bloom=BloomConfig(num_bits=1 << 14) if bloom else None,
        paged=True,
        page_words=page_words,
    )
    return DirectBackend(KV(cfg))


def test_longkey_construction():
    hi, lo = get_longkey(0xABCD, 7)
    assert hi == 0xABCD and lo == 7
    # truncation matches the reference's 32-bit fields
    hi, _ = get_longkey(0x1_0000_0002, 7)
    assert hi == 2


def test_cleancache_roundtrip_local_backend():
    c = CleanCacheClient(LocalBackend(page_words=8, capacity=64))
    page = np.arange(8, dtype=np.uint32)
    c.put_page(3, 44, page)
    got = c.get_page(3, 44)
    np.testing.assert_array_equal(got, page)
    assert c.get_page(3, 45) is None  # miss is legal
    assert c.counters["hit_gets"] == 1 and c.counters["miss_gets"] == 1


def test_cleancache_bloom_short_circuits_misses():
    c = CleanCacheClient(direct_backend())
    pages = np.tile(np.arange(16, dtype=np.uint32), (4, 1))
    c.put_pages(np.full(4, 9), np.arange(4), pages)
    # keys never put: the mirror rejects them without touching the backend
    out, found = c.get_pages(np.full(8, 9), np.arange(100, 108))
    assert not found.any()
    assert c.counters["bf_short_circuits"] == 8
    assert c.counters["actual_gets"] == 0
    # put keys resolve through the local overlay even before a refresh
    out, found = c.get_pages(np.full(4, 9), np.arange(4))
    assert found.all()
    np.testing.assert_array_equal(out, pages)


def test_bloom_refresh_pulls_server_truth():
    be = direct_backend()
    c = CleanCacheClient(be)
    pages = np.tile(np.arange(16, dtype=np.uint32), (2, 1))
    c.put_pages(np.array([1, 1]), np.array([10, 11]), pages)
    # server-side delete; the stale mirror still says "maybe"
    be.kv.delete(np.array([[1, 10]], np.uint32))
    _, found = c.get_pages(np.array([1]), np.array([10]))
    assert not found[0] and c.counters["actual_gets"] == 1
    # one refresh still carries the put-overlay (in-flight-put protection);
    # the second reflects pure server truth and short-circuits
    c.refresh_bloom()
    c.refresh_bloom()
    before = c.counters["bf_short_circuits"]
    _, found = c.get_pages(np.array([1]), np.array([10]))
    assert not found[0]
    assert c.counters["bf_short_circuits"] == before + 1  # no backend trip


def test_swap_client():
    s = SwapClient(LocalBackend(page_words=8, capacity=32))
    page = np.full(8, 7, np.uint32)
    s.store(0, 123, page)
    np.testing.assert_array_equal(s.load(0, 123), page)
    s.invalidate(0, 123)
    assert s.load(0, 123) is None


def test_paging_sim_seq_read_uses_cleancache():
    c = CleanCacheClient(direct_backend(capacity=1 << 12, page_words=16))
    sim = PagingSim(c, ram_pages=64, page_words=16, put_batch=16)
    # two passes over a file 4x RAM: pass 2 faults should hit the clean cache
    out = run_job(sim, "seq_read", file_pages=256, ops=512)
    assert out["verify_failures"] == 0
    assert out["cc_hits"] > 0
    assert out["reads"] == 512


def test_paging_sim_writes_never_read_stale():
    c = CleanCacheClient(direct_backend(capacity=1 << 12, page_words=16))
    sim = PagingSim(c, ram_pages=32, page_words=16, put_batch=8)
    out = run_job(sim, "rand_rw", file_pages=128, ops=600, seed=5)
    assert out["verify_failures"] == 0
    assert out["writes"] > 0 and out["reads"] > 0


def test_page_content_versioning():
    a = page_content(1, 2, 8, version=0)
    b = page_content(1, 2, 8, version=1)
    assert not np.array_equal(a, b)


def test_replay_synthetic():
    ops, keys = synthetic_trace(5000, write_frac=0.5, seed=3)
    cfg = KVConfig(index=IndexConfig(capacity=1 << 12), bloom=None,
                   paged=False)
    out = replay(KV(cfg), ops, keys, batch=512)
    assert out["ops"] == 5000
    assert out["writes"] > 0
    # clean-cache accounting: a read-miss of a written key needs an
    # eviction/drop to explain it (first-touch reads legitimately miss)
    assert out["read_hits"] > 0


def test_bundled_fileserver_trace_replays():
    """Replay-parity artifact: the bundled reference-format trace parses and
    replays with clean-cache-legal accounting."""
    import os

    from pmdfc_tpu.bench.replay import parse_trace, replay

    path = os.path.join(os.path.dirname(__file__), "data",
                        "fileserver.trace")
    ops, keys = parse_trace(path)
    assert len(ops) > 5000  # events expand to per-4KB page ops
    assert 0 < ops.sum() < len(ops)  # mixed R/W
    cfg = KVConfig(index=IndexConfig(capacity=1 << 14), bloom=None,
                   paged=False)
    out = replay(KV(cfg), ops, keys, batch=2048)
    assert out["writes"] == int(ops.sum())
    # reads of never-written pages legally miss; hits must exist
    assert out["read_hits"] > 0
    assert out["read_misses"] + out["read_hits"] == int((ops == 0).sum())


def test_write_fileserver_trace_deterministic(tmp_path):
    from pmdfc_tpu.bench.replay import parse_trace, write_fileserver_trace

    a, b = str(tmp_path / "a.trace"), str(tmp_path / "b.trace")
    write_fileserver_trace(a, n_events=100, seed=3)
    write_fileserver_trace(b, n_events=100, seed=3)
    assert open(a).read() == open(b).read()
    ops, keys = parse_trace(a)
    assert len(ops) >= 100


def test_parse_trace(tmp_path):
    p = tmp_path / "trace.txt"
    p.write_text(
        "0 1.0 W 42 0 8192 8192\n"   # 2 pages at page index 2,3
        "1 2.0 R 42 0 8192 4096\n"   # 1 page read back
        "malformed line\n"
    )
    ops, keys = parse_trace(str(p))
    assert list(ops) == [1, 1, 0]
    np.testing.assert_array_equal(keys[:, 0], [42, 42, 42])
    np.testing.assert_array_equal(keys[:, 1], [2, 3, 2])


def test_gen_input_patterns(tmp_path):
    u = uniform(100)
    assert len(np.unique(u.view("u4,u4"))) == 100  # bijective: all distinct
    # reference input_1toN: hot key 1 between runs of N sequential keys
    o = one_to_n(100, run=4)
    flat = (o[:, 0].astype(np.uint64) << np.uint64(32)) | o[:, 1]
    assert list(flat[:10]) == [1, 1, 2, 3, 4, 1, 5, 6, 7, 8]
    # every 5th slot is the hot key, +1 for sequential key 1 itself (the
    # reference's i starts at 1, so key 1 duplicates — kept faithfully)
    assert (flat == 1).sum() == 21
    s = sequential(10, start=7)
    assert list(s[:, 1]) == list(range(7, 17))
    r = repeated(100, repeat=4)
    _, counts = np.unique(r.view("u4,u4"), return_counts=True)
    assert counts.max() == 4
    z = zipf(1000)
    assert len(z) == 1000
    f = tmp_path / "keys.txt"
    save(str(f), u)
    np.testing.assert_array_equal(load(str(f)), u)


def test_hash_families_lockstep_and_distribution():
    """All four parity families + murmur3: numpy mirrors are bit-exact
    against jax, seeds give independent members, distribution is sane."""
    import jax.numpy as jnp

    from pmdfc_tpu.utils import hashing
    from pmdfc_tpu.utils import hashing_np as hnp

    rng = np.random.default_rng(3)
    hi = rng.integers(0, 1 << 32, 4096, dtype=np.uint64).astype(np.uint32)
    lo = rng.integers(0, 1 << 32, 4096, dtype=np.uint64).astype(np.uint32)
    for fam in hashing.FAMILIES:
        j = np.asarray(hashing.h(jnp.asarray(hi), jnp.asarray(lo),
                                 seed=11, family=fam))
        n = hnp.h_np(hi, lo, seed=11, family=fam)
        np.testing.assert_array_equal(j, n, err_msg=fam)
        # distribution: low byte roughly uniform
        counts = np.bincount(n & 0xFF, minlength=256)
        assert counts.max() < 16 * 4096 / 256, fam
        # seed independence
        n2 = hnp.h_np(hi, lo, seed=12, family=fam)
        assert (n != n2).mean() > 0.99, fam
    import pytest as _pt

    with _pt.raises(ValueError, match="unknown hash family"):
        hashing.h(jnp.asarray(hi), jnp.asarray(lo), family="nope")


def test_hashing_np_matches_jax():
    import jax.numpy as jnp

    from pmdfc_tpu.ops import bloom as bloom_ops
    from pmdfc_tpu.utils.hashing import hash_u64
    from pmdfc_tpu.utils.hashing_np import hash_u64_np, query_packed_np

    rng = np.random.default_rng(0)
    hi = rng.integers(0, 2**32, 256, dtype=np.uint32)
    lo = rng.integers(0, 2**32, 256, dtype=np.uint32)
    for seed in (0, 7, 0xC0C0C0C0):
        a = np.asarray(hash_u64(jnp.asarray(hi), jnp.asarray(lo), seed=seed))
        b = hash_u64_np(hi, lo, seed=seed)
        np.testing.assert_array_equal(a, b)
    # packed query parity
    st = bloom_ops.init(BloomConfig(num_bits=1 << 12))
    keys = np.stack([hi[:32], lo[:32]], axis=-1)
    st = bloom_ops.insert_batch(
        st, jnp.asarray(keys), jnp.ones(32, bool), num_hashes=4
    )
    packed = np.asarray(bloom_ops.to_packed_bits(st))
    ours = query_packed_np(packed, keys, 4)
    theirs = np.asarray(
        bloom_ops.query_packed(jnp.asarray(packed), jnp.asarray(keys),
                               num_hashes=4)
    )
    np.testing.assert_array_equal(ours, theirs)
    assert ours.all()
