"""Failure tier: server kill/restart with checkpoint restore under load,
client reconnect, and fault injection (dropped completions, driver stalls).

Ref: the tcp_style reconnect state machine (`client/tcp_style/tcp.c:648-705`)
and the clean-cache fault model — a dead server degrades every page op to a
LEGAL result (put → dropped, get → miss), never an exception, never wrong
data (`client/rdpma.c:1050-1168` TX_READ_ABORTED ⇒ -1).
"""

import time

import numpy as np
import pytest

from pmdfc_tpu import checkpoint
from pmdfc_tpu.client.backends import EngineBackend
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.engine import Engine
from pmdfc_tpu.runtime.failure import FaultInjector, ReconnectingClient
from pmdfc_tpu.runtime.server import KVServer

W = 16
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),
    paged=True,
    page_words=W,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    # content derived from the key — any wrong-data bug is detectable
    return (keys[:, 1:2].astype(np.uint32)
            * np.arange(1, W + 1, dtype=np.uint32))


def _engine(**kw):
    d = dict(num_queues=2, queue_cap=1 << 8, batch=128, timeout_us=200,
             arena_pages=512, page_bytes=W * 4)
    d.update(kw)
    return Engine(**d)


def _registry_factory(registry, timeout_us=30_000_000, slice_pages=256):
    # generous default: the first op per batch shape pays an XLA compile
    # (seconds on CPU) which must not read as a transport failure.
    # Fault drills use small slices: every transport failure quarantines
    # the dead backend's slice until the engine drains.
    def factory():
        srv = registry.get("server")
        if srv is None:
            raise ConnectionError("server down")
        return EngineBackend(srv, slice_pages=slice_pages,
                             timeout_us=timeout_us)
    return factory


def _warm(registry, keys, pages):
    """Compile every batch shape the drill will use, outside fault windows."""
    warm = ReconnectingClient(_registry_factory(registry), page_words=W,
                              retry_delay_s=0.0)
    warm.put(keys, pages)
    warm.get(keys)
    assert warm.stats()["disconnects"] == 0
    warm.close()


def test_restart_with_checkpoint_restore_and_reconnect(tmp_path):
    """Kill → checkpoint restore → reconnect: pre-snapshot pages serve with
    verified content; downtime ops degrade to legal miss/drop; recovery
    time is measured."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(128, seed=1)
    pages = _pages(keys)
    for lo in range(0, 128, 32):
        client.put(keys[lo:lo+32], pages[lo:lo+32])
    out, found = client.get(keys)
    assert found.all()

    path = str(tmp_path / "kv.npz")
    checkpoint.save(registry["server"].kv.state, path)

    # crash: server gone, engine freed
    srv = registry.pop("server", None)
    registry["server"] = None
    srv.stop()

    # downtime: every op degrades legally, nothing raises
    out, found = client.get(keys[:16])
    assert not found.any() and (out == 0).all()
    client.put(keys[:8], pages[:8])
    assert client.stats()["dropped_puts"] >= 8
    assert client.stats()["disconnects"] >= 1

    # restart from the snapshot; client re-attaches on its next op
    t0 = time.perf_counter()
    state = checkpoint.load(path, CFG)
    registry["server"] = KVServer(
        CFG, engine=_engine(), kv=KV(CFG, state=state)
    ).start()
    out, found = client.get(keys)
    recovery_s = time.perf_counter() - t0
    try:
        assert found.all(), "pre-snapshot pages must survive restart"
        np.testing.assert_array_equal(out, pages)
        assert client.stats()["reconnects"] >= 2  # initial + re-attach
        print(f"[failure] restore+reconnect+first-get: {recovery_s:.3f}s")
    finally:
        registry["server"].stop()


def test_restart_under_load_never_serves_wrong_data(tmp_path):
    """Puts/gets stream while the server dies mid-stream and returns from a
    snapshot: every successful get must return the key's exact content —
    misses are legal, corruption is not."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(256, seed=2)
    pages = _pages(keys)
    path = str(tmp_path / "kv.npz")

    wrong = 0
    for step, lo in enumerate(range(0, 256, 32)):
        client.put(keys[lo:lo+32], pages[lo:lo+32])
        if step == 3:
            checkpoint.save(registry["server"].kv.state, path)
            srv = registry["server"]
            registry["server"] = None
            srv.stop()
        if step == 5:
            registry["server"] = KVServer(
                CFG, pad_to=128, engine=_engine(),
                kv=KV(CFG, state=checkpoint.load(path, CFG)),
            ).start()
        sel = np.arange(0, lo + 32)
        out, found = client.get(keys[sel])
        good = _pages(keys[sel])
        wrong += int((out[found] != good[found]).any(axis=1).sum())
    assert wrong == 0
    registry["server"].stop()


def test_dropped_completions_timeout_then_recover():
    """Completions dropped on the floor: clients time out (bounded), count
    the loss as legal drops/misses, and the next batch succeeds."""
    fi = FaultInjector()
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine(),
                                   fault_injector=fi).start()}
    client = ReconnectingClient(
        _registry_factory(registry, timeout_us=300_000, slice_pages=64),
        page_words=W, retry_delay_s=0.0,
    )
    try:
        keys = _keys(64, seed=3)
        pages = _pages(keys)
        _warm(registry, keys[:32], pages[:32])
        client.put(keys[:32], pages[:32])

        fi.drop_next(3)  # swallow everything for a while
        t0 = time.perf_counter()
        client.put(keys[32:], pages[32:])
        assert time.perf_counter() - t0 < 5.0, "timeout must be bounded"
        assert client.stats()["dropped_puts"] >= 32
        assert fi.stats["dropped_batches"] >= 1

        # drain the remaining armed drops with throwaway traffic
        deadline = time.time() + 10
        while fi._drop_left > 0 and time.time() < deadline:
            client.get(keys[:1])
            time.sleep(0.01)
        # recovered: full service, content intact for the first half
        out, found = client.get(keys[:32])
        assert found.all()
        np.testing.assert_array_equal(out, pages[:32])
    finally:
        registry["server"].stop()


def test_stalled_driver_backpressure_is_bounded_loss():
    """A stalled driver fills the tiny submission queues; clients see
    bounded TimeoutErrors surfaced as drops, then full recovery."""
    fi = FaultInjector()
    eng = _engine(queue_cap=1 << 6, batch=32, timeout_us=100)
    registry = {"server": KVServer(CFG, pad_to=128, engine=eng,
                                   fault_injector=fi).start()}
    client = ReconnectingClient(
        _registry_factory(registry, timeout_us=200_000, slice_pages=64),
        page_words=W, retry_delay_s=0.0,
    )
    try:
        keys = _keys(192, seed=4)
        pages = _pages(keys)
        _warm(registry, keys[:32], pages[:32])
        fi.stall_next(6, seconds=0.25)
        for lo in range(0, 192, 32):
            client.put(keys[lo:lo+32], pages[lo:lo+32])
        # some puts were dropped under pressure — bounded, counted, legal
        out, found = client.get(keys[:64])
        assert (out[found] == pages[:64][found]).all()
        dropped = client.stats()["dropped_puts"]
        # pressure off: service returns once the engine drains (late
        # completions release quarantined staging slices)
        deadline = time.time() + 10
        while time.time() < deadline:
            client.put(keys[:32], pages[:32])
            out, found = client.get(keys[:32])
            if found.all():
                break
            time.sleep(0.1)
        assert found.all()
        assert dropped <= 192  # every loss is accounted, none silent
        assert fi.stats["stalled_batches"] >= 1
        np.testing.assert_array_equal(out, pages[:32])
    finally:
        registry["server"].stop()


def test_put_first_after_kill_degrades_not_raises():
    """The FIRST op after a server death being a put (arena already freed)
    must degrade to a dropped put — no exception class may escape."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(8, seed=9)
    client.put(keys, _pages(keys))  # attach + warm
    srv = registry["server"]
    registry["server"] = None
    srv.stop()
    client.put(keys, _pages(keys))  # arena is gone: staging raises inside
    assert client.stats()["dropped_puts"] >= 8
    assert client.stats()["disconnects"] == 1


def test_invalidation_journal_blocks_stale_resurrection(tmp_path):
    """Snapshot → invalidate → crash → restore: the snapshot resurrects the
    invalidated entry server-side, but the client's journal replays the
    invalidation on reconnect — stale data must never serve."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(16, seed=6)
    pages = _pages(keys)
    client.put(keys, pages)
    path = str(tmp_path / "kv.npz")
    checkpoint.save(registry["server"].kv.state, path)  # contains keys[:8]
    hit = client.invalidate(keys[:8])                   # AFTER the snapshot
    assert hit.all()
    srv = registry["server"]
    registry["server"] = None
    srv.stop()
    registry["server"] = KVServer(
        CFG, pad_to=128, engine=_engine(),
        kv=KV(CFG, state=checkpoint.load(path, CFG)),
    ).start()
    try:
        client.get(keys[:1])  # trips dead-backend detection (legal miss)
        out, found = client.get(keys)
        assert not found[:8].any(), "invalidated pages must not resurrect"
        assert found[8:].all()
        np.testing.assert_array_equal(out[8:], pages[8:])
        assert client.stats()["replayed_invalidates"] >= 8
    finally:
        registry["server"].stop()


def test_paging_sim_survives_restart(tmp_path):
    """The cleancache paging workload rides ReconnectingClient across a
    kill/restore cycle: reads after recovery are hits-or-legal-misses with
    verified content, and the run completes without an exception."""
    from pmdfc_tpu.bench.paging_sim import PagingSim, run_job
    from pmdfc_tpu.client.cleancache import CleanCacheClient

    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    rb = ReconnectingClient(_registry_factory(registry), page_words=W,
                            retry_delay_s=0.0)
    cc = CleanCacheClient(rb)
    sim = PagingSim(cc, ram_pages=32, page_words=W)
    path = str(tmp_path / "kv.npz")
    try:
        run_job(sim, "rand_rw", file_pages=128, ops=400, seed=5)
        checkpoint.save(registry["server"].kv.state, path)
        srv = registry["server"]
        registry["server"] = None
        srv.stop()
        # downtime: cleancache misses fall back to "disk"; workload survives
        run_job(sim, "rand_read", file_pages=128, ops=100, seed=6)
        registry["server"] = KVServer(
            CFG, engine=_engine(), kv=KV(CFG, state=checkpoint.load(path, CFG)),
        ).start()
        out = run_job(sim, "rand_rw", file_pages=128, ops=400, seed=7)
        assert out["verify_failures"] == 0
        assert out["cc_hits"] > 0  # recovered cache actually serves again
    finally:
        if registry["server"]:
            registry["server"].stop()


# --- torn / corrupt checkpoints (rung 4 of the ladder) ------------------


def test_torn_checkpoint_detected_and_rejected(tmp_path):
    """A truncated or bit-flipped snapshot must raise the typed
    CheckpointCorruptError — restore must never hand back partial state
    as if it were durable."""
    from pmdfc_tpu.checkpoint import CheckpointCorruptError

    kv = KV(CFG)
    keys = _keys(64, seed=21)
    kv.insert(keys, _pages(keys))
    p = str(tmp_path / "snap.npz")
    checkpoint.save(kv.state, p)

    data = open(p, "rb").read()
    # torn write: everything after 60% is missing
    torn = str(tmp_path / "torn.npz")
    open(torn, "wb").write(data[: int(len(data) * 0.6)])
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(torn, CFG)
    # bit rot in the middle of the archive
    rot = str(tmp_path / "rot.npz")
    mut = bytearray(data)
    mut[len(mut) // 2] ^= 0x10
    open(rot, "wb").write(bytes(mut))
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(rot, CFG)
    # not a snapshot at all
    junk = str(tmp_path / "junk.npz")
    open(junk, "wb").write(b"\x00" * 512)
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(junk, CFG)
    # a snapshot without the integrity manifest is not trusted either
    import numpy as _np

    bare = str(tmp_path / "bare.npz")
    leaves = {f"leaf_{i}": _np.zeros(2) for i in range(3)}
    _np.savez(bare, **leaves)
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(bare, CFG)
    # and the pristine file still round-trips
    kv2 = KV(CFG, state=checkpoint.load(p, CFG))
    out, found = kv2.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, _pages(keys))


def test_kill_restore_falls_back_past_torn_snapshot(tmp_path):
    """The kill→restore drill with a torn NEWEST snapshot: restore
    detects the tear, falls back to the last durable snapshot, and serves
    exactly that state — no torn state is ever served."""
    from pmdfc_tpu.checkpoint import CheckpointCorruptError

    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(96, seed=22)
    pages = _pages(keys)
    client.put(keys[:64], pages[:64])
    durable = str(tmp_path / "durable.npz")
    # crash-safe snapshot through the server (serialized against the
    # driver's donating dispatches)
    registry["server"].checkpoint(durable)
    client.put(keys[64:], pages[64:])
    newest = str(tmp_path / "newest.npz")
    registry["server"].checkpoint(newest)
    # the newest snapshot is torn on disk (crash mid-write analog)
    data = open(newest, "rb").read()
    open(newest, "wb").write(data[: len(data) // 2])

    srv = registry["server"]
    registry["server"] = None
    srv.stop()

    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(newest, CFG)
    state = checkpoint.load(durable, CFG)  # fall back to durable
    registry["server"] = KVServer(
        CFG, pad_to=128, engine=_engine(), kv=KV(CFG, state=state)
    ).start()
    try:
        client.get(keys[:1])  # trip dead-backend detection, then re-attach
        deadline = time.time() + 10
        while time.time() < deadline:
            out, found = client.get(keys)
            if found[:64].all():
                break
            time.sleep(0.05)
        # exactly the durable state: first 64 verified, the rest legal miss
        assert found[:64].all()
        np.testing.assert_array_equal(out[:64], pages[:64])
        assert not found[64:].any(), "post-durable writes resurrected"
    finally:
        registry["server"].stop()


# --- reconnect backoff (rung 3) -----------------------------------------


def test_reconnect_backoff_widens_and_resets():
    """Failed reconnects space out exponentially (with seeded jitter) up
    to the cap; a successful reconnect resets the spacing."""
    alive = {"up": False}

    def factory():
        if not alive["up"]:
            raise ConnectionError("down")
        from pmdfc_tpu.client.backends import LocalBackend

        return LocalBackend(page_words=W)

    rc = ReconnectingClient(factory, page_words=W, retry_delay_s=0.01,
                            max_retry_delay_s=0.2, backoff=2.0,
                            jitter=0.25, seed=7)
    keys = _keys(4, seed=23)
    t0 = time.monotonic()
    # hammer ops while down: most must be gated by the widening delay,
    # so attempts (== backoffs) stay far below the op count
    ops = 0
    while time.monotonic() - t0 < 0.5:
        rc.get(keys)
        ops += 1
    backoffs = rc.stats()["reconnect_backoffs"]
    assert backoffs >= 2
    assert backoffs < ops / 2, "backoff did not gate reconnect attempts"
    assert rc._cur_delay > 0.01, "delay never widened"
    assert rc._cur_delay <= 0.2 * 1.25 + 1e-9, "cap not applied"
    assert rc.stats()["missed_gets"] == ops * 4

    alive["up"] = True
    deadline = time.time() + 5
    while not rc.connected and time.time() < deadline:
        rc.get(keys)
        time.sleep(0.02)
    assert rc.connected
    assert rc._cur_delay == 0.01, "successful reconnect must reset backoff"
    assert rc.stats()["reconnects"] >= 1
