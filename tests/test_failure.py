"""Failure tier: server kill/restart with checkpoint restore under load,
client reconnect, and fault injection (dropped completions, driver stalls).

Ref: the tcp_style reconnect state machine (`client/tcp_style/tcp.c:648-705`)
and the clean-cache fault model — a dead server degrades every page op to a
LEGAL result (put → dropped, get → miss), never an exception, never wrong
data (`client/rdpma.c:1050-1168` TX_READ_ABORTED ⇒ -1).
"""

import time

import numpy as np
import pytest

from pmdfc_tpu import checkpoint
from pmdfc_tpu.client.backends import EngineBackend
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.engine import Engine
from pmdfc_tpu.runtime.failure import FaultInjector, ReconnectingClient
from pmdfc_tpu.runtime.server import KVServer

W = 16
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),
    paged=True,
    page_words=W,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    # content derived from the key — any wrong-data bug is detectable
    return (keys[:, 1:2].astype(np.uint32)
            * np.arange(1, W + 1, dtype=np.uint32))


def _engine(**kw):
    d = dict(num_queues=2, queue_cap=1 << 8, batch=128, timeout_us=200,
             arena_pages=512, page_bytes=W * 4)
    d.update(kw)
    return Engine(**d)


def _registry_factory(registry, timeout_us=30_000_000, slice_pages=256):
    # generous default: the first op per batch shape pays an XLA compile
    # (seconds on CPU) which must not read as a transport failure.
    # Fault drills use small slices: every transport failure quarantines
    # the dead backend's slice until the engine drains.
    def factory():
        srv = registry.get("server")
        if srv is None:
            raise ConnectionError("server down")
        return EngineBackend(srv, slice_pages=slice_pages,
                             timeout_us=timeout_us)
    return factory


def _warm(registry, keys, pages):
    """Compile every batch shape the drill will use, outside fault windows."""
    warm = ReconnectingClient(_registry_factory(registry), page_words=W,
                              retry_delay_s=0.0)
    warm.put(keys, pages)
    warm.get(keys)
    assert warm.counters["disconnects"] == 0
    warm.close()


def test_restart_with_checkpoint_restore_and_reconnect(tmp_path):
    """Kill → checkpoint restore → reconnect: pre-snapshot pages serve with
    verified content; downtime ops degrade to legal miss/drop; recovery
    time is measured."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(128, seed=1)
    pages = _pages(keys)
    for lo in range(0, 128, 32):
        client.put(keys[lo:lo+32], pages[lo:lo+32])
    out, found = client.get(keys)
    assert found.all()

    path = str(tmp_path / "kv.npz")
    checkpoint.save(registry["server"].kv.state, path)

    # crash: server gone, engine freed
    srv = registry.pop("server", None)
    registry["server"] = None
    srv.stop()

    # downtime: every op degrades legally, nothing raises
    out, found = client.get(keys[:16])
    assert not found.any() and (out == 0).all()
    client.put(keys[:8], pages[:8])
    assert client.counters["dropped_puts"] >= 8
    assert client.counters["disconnects"] >= 1

    # restart from the snapshot; client re-attaches on its next op
    t0 = time.perf_counter()
    state = checkpoint.load(path, CFG)
    registry["server"] = KVServer(
        CFG, engine=_engine(), kv=KV(CFG, state=state)
    ).start()
    out, found = client.get(keys)
    recovery_s = time.perf_counter() - t0
    try:
        assert found.all(), "pre-snapshot pages must survive restart"
        np.testing.assert_array_equal(out, pages)
        assert client.counters["reconnects"] >= 2  # initial + re-attach
        print(f"[failure] restore+reconnect+first-get: {recovery_s:.3f}s")
    finally:
        registry["server"].stop()


def test_restart_under_load_never_serves_wrong_data(tmp_path):
    """Puts/gets stream while the server dies mid-stream and returns from a
    snapshot: every successful get must return the key's exact content —
    misses are legal, corruption is not."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(256, seed=2)
    pages = _pages(keys)
    path = str(tmp_path / "kv.npz")

    wrong = 0
    for step, lo in enumerate(range(0, 256, 32)):
        client.put(keys[lo:lo+32], pages[lo:lo+32])
        if step == 3:
            checkpoint.save(registry["server"].kv.state, path)
            srv = registry["server"]
            registry["server"] = None
            srv.stop()
        if step == 5:
            registry["server"] = KVServer(
                CFG, pad_to=128, engine=_engine(),
                kv=KV(CFG, state=checkpoint.load(path, CFG)),
            ).start()
        sel = np.arange(0, lo + 32)
        out, found = client.get(keys[sel])
        good = _pages(keys[sel])
        wrong += int((out[found] != good[found]).any(axis=1).sum())
    assert wrong == 0
    registry["server"].stop()


def test_dropped_completions_timeout_then_recover():
    """Completions dropped on the floor: clients time out (bounded), count
    the loss as legal drops/misses, and the next batch succeeds."""
    fi = FaultInjector()
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine(),
                                   fault_injector=fi).start()}
    client = ReconnectingClient(
        _registry_factory(registry, timeout_us=300_000, slice_pages=64),
        page_words=W, retry_delay_s=0.0,
    )
    try:
        keys = _keys(64, seed=3)
        pages = _pages(keys)
        _warm(registry, keys[:32], pages[:32])
        client.put(keys[:32], pages[:32])

        fi.drop_next(3)  # swallow everything for a while
        t0 = time.perf_counter()
        client.put(keys[32:], pages[32:])
        assert time.perf_counter() - t0 < 5.0, "timeout must be bounded"
        assert client.counters["dropped_puts"] >= 32
        assert fi.stats["dropped_batches"] >= 1

        # drain the remaining armed drops with throwaway traffic
        deadline = time.time() + 10
        while fi._drop_left > 0 and time.time() < deadline:
            client.get(keys[:1])
            time.sleep(0.01)
        # recovered: full service, content intact for the first half
        out, found = client.get(keys[:32])
        assert found.all()
        np.testing.assert_array_equal(out, pages[:32])
    finally:
        registry["server"].stop()


def test_stalled_driver_backpressure_is_bounded_loss():
    """A stalled driver fills the tiny submission queues; clients see
    bounded TimeoutErrors surfaced as drops, then full recovery."""
    fi = FaultInjector()
    eng = _engine(queue_cap=1 << 6, batch=32, timeout_us=100)
    registry = {"server": KVServer(CFG, pad_to=128, engine=eng,
                                   fault_injector=fi).start()}
    client = ReconnectingClient(
        _registry_factory(registry, timeout_us=200_000, slice_pages=64),
        page_words=W, retry_delay_s=0.0,
    )
    try:
        keys = _keys(192, seed=4)
        pages = _pages(keys)
        _warm(registry, keys[:32], pages[:32])
        fi.stall_next(6, seconds=0.25)
        for lo in range(0, 192, 32):
            client.put(keys[lo:lo+32], pages[lo:lo+32])
        # some puts were dropped under pressure — bounded, counted, legal
        out, found = client.get(keys[:64])
        assert (out[found] == pages[:64][found]).all()
        dropped = client.counters["dropped_puts"]
        # pressure off: service returns once the engine drains (late
        # completions release quarantined staging slices)
        deadline = time.time() + 10
        while time.time() < deadline:
            client.put(keys[:32], pages[:32])
            out, found = client.get(keys[:32])
            if found.all():
                break
            time.sleep(0.1)
        assert found.all()
        assert dropped <= 192  # every loss is accounted, none silent
        assert fi.stats["stalled_batches"] >= 1
        np.testing.assert_array_equal(out, pages[:32])
    finally:
        registry["server"].stop()


def test_put_first_after_kill_degrades_not_raises():
    """The FIRST op after a server death being a put (arena already freed)
    must degrade to a dropped put — no exception class may escape."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(8, seed=9)
    client.put(keys, _pages(keys))  # attach + warm
    srv = registry["server"]
    registry["server"] = None
    srv.stop()
    client.put(keys, _pages(keys))  # arena is gone: staging raises inside
    assert client.counters["dropped_puts"] >= 8
    assert client.counters["disconnects"] == 1


def test_invalidation_journal_blocks_stale_resurrection(tmp_path):
    """Snapshot → invalidate → crash → restore: the snapshot resurrects the
    invalidated entry server-side, but the client's journal replays the
    invalidation on reconnect — stale data must never serve."""
    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    client = ReconnectingClient(_registry_factory(registry), page_words=W,
                                retry_delay_s=0.0)
    keys = _keys(16, seed=6)
    pages = _pages(keys)
    client.put(keys, pages)
    path = str(tmp_path / "kv.npz")
    checkpoint.save(registry["server"].kv.state, path)  # contains keys[:8]
    hit = client.invalidate(keys[:8])                   # AFTER the snapshot
    assert hit.all()
    srv = registry["server"]
    registry["server"] = None
    srv.stop()
    registry["server"] = KVServer(
        CFG, pad_to=128, engine=_engine(),
        kv=KV(CFG, state=checkpoint.load(path, CFG)),
    ).start()
    try:
        client.get(keys[:1])  # trips dead-backend detection (legal miss)
        out, found = client.get(keys)
        assert not found[:8].any(), "invalidated pages must not resurrect"
        assert found[8:].all()
        np.testing.assert_array_equal(out[8:], pages[8:])
        assert client.counters["replayed_invalidates"] >= 8
    finally:
        registry["server"].stop()


def test_paging_sim_survives_restart(tmp_path):
    """The cleancache paging workload rides ReconnectingClient across a
    kill/restore cycle: reads after recovery are hits-or-legal-misses with
    verified content, and the run completes without an exception."""
    from pmdfc_tpu.bench.paging_sim import PagingSim, run_job
    from pmdfc_tpu.client.cleancache import CleanCacheClient

    registry = {"server": KVServer(CFG, pad_to=128, engine=_engine()).start()}
    rb = ReconnectingClient(_registry_factory(registry), page_words=W,
                            retry_delay_s=0.0)
    cc = CleanCacheClient(rb)
    sim = PagingSim(cc, ram_pages=32, page_words=W)
    path = str(tmp_path / "kv.npz")
    try:
        run_job(sim, "rand_rw", file_pages=128, ops=400, seed=5)
        checkpoint.save(registry["server"].kv.state, path)
        srv = registry["server"]
        registry["server"] = None
        srv.stop()
        # downtime: cleancache misses fall back to "disk"; workload survives
        run_job(sim, "rand_read", file_pages=128, ops=100, seed=6)
        registry["server"] = KVServer(
            CFG, engine=_engine(), kv=KV(CFG, state=checkpoint.load(path, CFG)),
        ).start()
        out = run_job(sim, "rand_rw", file_pages=128, ops=400, seed=7)
        assert out["verify_failures"] == 0
        assert out["cc_hits"] > 0  # recovered cache actually serves again
    finally:
        if registry["server"]:
            registry["server"].stop()
