"""Concurrency & JAX-discipline suite drills (marker: analyze).

Three layers, mirroring the tooling itself:

1. **The tree gate** — `run_analysis()` over `pmdfc_tpu/` with the
   checked-in allowlist must be empty (the same invariant
   `python -m tools.analyze` enforces in the agenda).
2. **Seeded fixtures** — known-bad modules (AB/BA inversion, unguarded
   write, platform-unkeyed donation) must each produce their expected
   finding; the clean twins must pass. This is the suite testing the
   SUITE: a rule that silently stopped firing would otherwise look like
   a clean tree.
3. **The runtime sanitizer** — instrumented locks must catch order
   inversions against the declared hierarchy, refuse self-deadlocks,
   and time long holds (condition waits excluded); and a chaos-proxied
   net soak under `PMDFC_SAN` semantics must finish with ZERO reports.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from tools.analyze import Allowlist, build_model, run_analysis
from tools.analyze import guarded, jaxrules, lockorder
from tools.analyze.resolve import analyze_functions

pytestmark = pytest.mark.analyze

_FIXTURES = os.path.join(os.path.dirname(__file__), "data",
                         "analyze_fixtures")


def _run_all(*names):
    files = [(os.path.join(_FIXTURES, n), n) for n in names]
    model = build_model(files)
    facts = analyze_functions(model)
    allow = Allowlist({})
    return (guarded.run(model, facts, allow)
            + lockorder.run(model, facts, allow)
            + jaxrules.run(model, allow))


# --- 1. the tree gate ------------------------------------------------------


def test_tree_is_clean_under_checked_in_allowlist():
    findings, stale = run_analysis()
    assert not findings, "\n".join(str(f) for f in findings)
    assert not stale, f"stale allowlist entries: {stale}"


def test_lock_hierarchy_covers_every_ranked_module_lock():
    # every lock the model finds in the instrumented serving modules must
    # have a rank — a new lock without one silently opts out of both the
    # static rank rule and the runtime order check. The module set is the
    # analyzer's own (lockorder.RANKED_MODULES drives the unranked-lock
    # rule inside `python -m tools.analyze`); this drill re-checks it
    # directly so the rule and the table can't drift apart, and pins that
    # the mesh serving plane's modules are covered.
    from pmdfc_tpu.runtime.sanitizer import HIERARCHY

    findings, _ = run_analysis()
    assert not findings  # precondition: tree parses + passes
    from tools.analyze import DEFAULT_ROOTS
    from tools.analyze.model import collect_files

    assert {"parallel/shard.py", "parallel/partitioning.py",
            "parallel/plane.py",
            "runtime/slo.py"} <= lockorder.RANKED_MODULES
    model = build_model(collect_files(DEFAULT_ROOTS))
    missing = []
    for decl in model.all_locks():
        mod = decl.module.path.split("pmdfc_tpu/", 1)[-1]
        if mod in lockorder.RANKED_MODULES \
                and decl.lock_id not in HIERARCHY:
            missing.append(decl.lock_id)
    assert not missing, f"locks without a declared rank: {missing}"


def test_unranked_serving_lock_is_a_finding(monkeypatch):
    # the coverage gate itself: strip a serving-plane lock's rank and the
    # unranked-lock rule must fire with a stable id
    from pmdfc_tpu.runtime import sanitizer

    stripped = {k: v for k, v in sanitizer.HIERARCHY.items()
                if k != "ShardedKV._lock"}
    monkeypatch.setattr(sanitizer, "HIERARCHY", stripped)
    from tools.analyze import DEFAULT_ROOTS
    from tools.analyze.model import collect_files

    model = build_model(collect_files(DEFAULT_ROOTS))
    facts = analyze_functions(model)
    found = lockorder.run(model, facts, Allowlist({}))
    unranked = [f for f in found if f.rule == "unranked-lock"]
    assert any(f.ident == "unranked-lock:ShardedKV._lock"
               for f in unranked), found


def test_unranked_slo_lock_is_a_finding(monkeypatch):
    # ISSUE 9 satellite: runtime/slo.py is a RANKED module — a lock the
    # SLO watchdog grows WITHOUT a HIERARCHY rank must be a finding in
    # `python -m tools.analyze`, not a silent opt-out (same drill shape
    # as the mesh-plane coverage gate above)
    from pmdfc_tpu.runtime import sanitizer

    stripped = {k: v for k, v in sanitizer.HIERARCHY.items()
                if k != "SloWatchdog._lock"}
    monkeypatch.setattr(sanitizer, "HIERARCHY", stripped)
    from tools.analyze import DEFAULT_ROOTS
    from tools.analyze.model import collect_files

    model = build_model(collect_files(DEFAULT_ROOTS))
    facts = analyze_functions(model)
    found = lockorder.run(model, facts, Allowlist({}))
    assert any(f.ident == "unranked-lock:SloWatchdog._lock"
               and f.rule == "unranked-lock" for f in found), found


# --- 2. seeded fixtures ----------------------------------------------------


def test_bad_inversion_fixture_yields_lock_order_cycle():
    found = _run_all("bad_inversion.py")
    cycles = [f for f in found if f.rule == "lock-order"]
    assert cycles, found
    assert any("Pair.lock_a" in f.message and "Pair.lock_b" in f.message
               for f in cycles)


def test_bad_unguarded_fixture_yields_guarded_write():
    found = _run_all("bad_unguarded.py")
    writes = [f for f in found if f.rule == "guarded-write"]
    assert len(writes) == 1, found
    assert "closed" in writes[0].message
    assert writes[0].ident == \
        "guarded-write:bad_unguarded.py:Box.drop:closed"


def test_bad_donation_fixture_yields_jax_donation():
    found = _run_all("bad_donation.py")
    dons = [f for f in found if f.rule == "jax-donation"]
    assert len(dons) == 1, found
    assert dons[0].ident == "jax-donation:bad_donation.py:scatter"


def test_bad_shardmap_donation_fixture_yields_jax_donation():
    # the mesh-plane shape of the donation class: a shard_map-wrapped
    # program donated without platform keying must fire the same rule
    found = _run_all("bad_donation_shardmap.py")
    dons = [f for f in found if f.rule == "jax-donation"]
    assert len(dons) == 1, found
    assert dons[0].ident == "jax-donation:bad_donation_shardmap.py:build"


def test_bad_pallas_gate_fixture_yields_finding():
    # an unconditional Mosaic lowering (no interpret= fallback, no
    # platform guard anywhere in the module) is the TPU-only-path bug
    found = _run_all("bad_pallas_gate.py")
    gates = [f for f in found if f.rule == "pallas-platform-gate"]
    assert len(gates) == 1, found
    assert gates[0].ident == "pallas-platform-gate:bad_pallas_gate.py:launch"


def test_interpret_false_literal_is_still_unconditional(tmp_path):
    # `interpret=False` is the same as omitting the kwarg — the call is
    # Mosaic-only on every backend, so it must NOT satisfy the gate
    src = tmp_path / "lit.py"
    src.write_text(
        "from jax.experimental import pallas as pl\n"
        "def go(x, k, s):\n"
        "    return pl.pallas_call(k, out_shape=s, interpret=False)(x)\n")
    model = build_model([(str(src), "lit.py")])
    found = jaxrules.run(model, Allowlist({}))
    assert [f.rule for f in found] == ["pallas-platform-gate"], found


def test_bad_profiler_seam_fixture_yields_findings():
    # a raw device sync outside runtime/profiler.py is unattributable
    # device time — both the `jax.block_until_ready(...)` form and the
    # `.block_until_ready()` method form must fire, with def-stable ids
    found = _run_all("bad_profiler_seam.py")
    seams = [f for f in found if f.rule == "profiler-seam"]
    assert len(seams) == 2, found
    assert {f.ident for f in seams} == {
        "profiler-seam:bad_profiler_seam.py:fetch_result",
        "profiler-seam:bad_profiler_seam.py:drain",
    }


def test_profiler_seam_exempts_bench_and_the_seam_itself(tmp_path):
    # the same leaky source under a bench/ path or as the profiler
    # module itself is the sanctioned raw boundary — no finding
    src = ("import jax\n"
           "def measure(x):\n"
           "    return jax.block_until_ready(x)\n")
    bench = tmp_path / "bench"
    bench.mkdir()
    (bench / "lat.py").write_text(src)
    prof = tmp_path / "profiler.py"
    prof.write_text(src)
    model = build_model([
        (str(bench / "lat.py"), "pmdfc_tpu/bench/lat.py"),
        (str(prof), "pmdfc_tpu/runtime/profiler.py"),
    ])
    found = jaxrules.run(model, Allowlist({}))
    assert [f for f in found if f.rule == "profiler-seam"] == [], found


def test_clean_fixtures_pass():
    assert _run_all("clean_locks.py") == []
    assert _run_all("clean_donation.py") == []
    # the canonical shared helper (`from pmdfc_tpu.kv import _donate`,
    # the onesided.py pattern) also counts as platform keying
    assert _run_all("clean_donation_shared.py") == []
    # platform-keyed shard_map donation (the parallel/shard._wrap shape)
    assert _run_all("clean_donation_shardmap.py") == []
    # platform-keyed pallas launches (interpret= fallback / backend
    # branch, the ops/fused.py idiom)
    assert _run_all("clean_pallas_gate.py") == []
    # device syncs routed through the profiler seam (fetch thunks +
    # block_ready warmups, the runtime/profiler.py discipline)
    assert _run_all("clean_profiler_seam.py") == []


def test_local_donate_spoof_does_not_count_as_guard():
    # a module-local `def _donate()` (arbitrary policy) must NOT satisfy
    # the rule — only the canonical kv import does
    found = _run_all("bad_donation_spoof.py")
    assert [f.rule for f in found] == ["jax-donation"], found


def test_allowlist_suppresses_and_reports_stale():
    files = [(os.path.join(_FIXTURES, "bad_unguarded.py"),
              "bad_unguarded.py")]
    model = build_model(files)
    facts = analyze_functions(model)
    allow = Allowlist({
        "guarded-write:bad_unguarded.py:Box.drop:closed": "drill",
        "guarded-write:bad_unguarded.py:Box.gone:items": "stale entry",
    })
    assert guarded.run(model, facts, allow) == []
    assert allow.unused() == \
        ["guarded-write:bad_unguarded.py:Box.gone:items"]


def test_lambda_body_does_not_fabricate_lock_order_edges(tmp_path):
    # a lambda CONSTRUCTED under a lock is deferred work: nothing in its
    # body runs under that lock, so no edge may come from it (a phantom
    # edge here could report a fake AB/BA cycle on correct code)
    src = '''
import threading

class A:
    def __init__(self):
        # guarded-by: <none>  (fixture)
        self.lock_a = threading.Lock()
        # guarded-by: <none>  (fixture)
        self.lock_b = threading.Lock()

    def inner(self):
        with self.lock_a:
            pass

    def defer(self):
        with self.lock_b:
            cb = lambda: self.inner()   # noqa: E731
        return cb

    def order(self):
        with self.lock_a:
            with self.lock_b:
                pass
'''
    p = tmp_path / "lam.py"
    p.write_text(src)
    model = build_model([(str(p), "lam.py")])
    facts = analyze_functions(model)
    found = lockorder.run(model, facts, Allowlist({}))
    assert found == [], found


def test_lexical_self_reacquire_is_flagged(tmp_path):
    # `with L: with L:` on a non-reentrant Lock is a certain deadlock —
    # the static side must see the lexical form, not just call summaries
    src = '''
import threading

class B:
    def __init__(self):
        # guarded-by: <none>  (fixture)
        self._lock = threading.Lock()
        # guarded-by: <none>  (fixture)
        self._rlock = threading.RLock()

    def bad(self):
        with self._lock:
            with self._lock:
                pass

    def fine(self):
        with self._rlock:
            with self._rlock:
                pass
'''
    p = tmp_path / "self.py"
    p.write_text(src)
    model = build_model([(str(p), "self.py")])
    facts = analyze_functions(model)
    found = lockorder.run(model, facts, Allowlist({}))
    assert [f.ident for f in found] == \
        ["lock-order:B._lock->B._lock"], found


def test_wire_drift_rule_catches_constant_divergence(tmp_path):
    twin = tmp_path / "runtime"
    twin.mkdir()
    (twin / "net.py").write_text("MSG_PUTPAGE = 3\nPIPE_FLAG = 0x100\n")
    drifted = tmp_path / "peer.py"
    drifted.write_text("MSG_PUTPAGE = 4\nTRACE_FLAG = 0x10\n")
    model = build_model([(str(twin / "net.py"), "runtime/net.py"),
                         (str(drifted), "peer.py")])
    found = jaxrules.run(model, Allowlist({}))
    idents = {f.ident for f in found}
    assert "wire-drift:peer.py:MSG_PUTPAGE" in idents   # value drift
    assert "wire-drift:peer.py:TRACE_FLAG" in idents    # flag in chan byte


# --- 3. the runtime sanitizer ---------------------------------------------


@pytest.fixture
def san_on():
    from pmdfc_tpu.runtime import sanitizer

    sanitizer.configure(on=True, strict=False, hold_ms=200.0)
    sanitizer.reset()
    yield sanitizer
    sanitizer.reset()
    sanitizer.configure(on=False)


def test_sanitizer_off_returns_plain_primitives():
    from pmdfc_tpu.runtime import sanitizer

    sanitizer.configure(on=False)
    assert type(sanitizer.lock("x")) is type(threading.Lock())
    assert isinstance(sanitizer.condition("y"),
                      type(threading.Condition()))


def test_sanitizer_detects_ab_ba_inversion(san_on):
    a = san_on.lock("NetServer.op_lock")       # rank 30
    b = san_on.lock("KV._lock")                # rank 65 (inner)
    with a:
        with b:
            pass
    assert san_on.violations() == []           # declared order: clean
    with b:
        with a:                                # against the hierarchy
            pass
    v = san_on.violations()
    assert len(v) == 1 and v[0]["kind"] == "inversion"
    assert v[0]["acquired"] == "NetServer.op_lock"
    assert v[0]["while_holding"] == "KV._lock"


def test_sanitizer_refuses_self_deadlock(san_on):
    lk = san_on.lock("NetServer.op_lock")
    with lk:
        with pytest.raises(RuntimeError, match="re-acquired"):
            lk.acquire()
    assert [v["kind"] for v in san_on.violations()] == ["reacquire"]
    # and the lock still works after the refusal
    with lk:
        pass


def test_sanitizer_rlock_reentry_is_legal(san_on):
    rl = san_on.rlock("KV._lock")
    with rl:
        with rl:
            pass
    assert san_on.violations() == []


def test_sanitizer_times_long_holds_on_watched_locks(san_on):
    san_on.configure(hold_ms=20.0)
    cv = san_on.condition("NetServer._flush_cv")   # in HOLD_WATCH
    with cv:
        time.sleep(0.06)
    v = san_on.violations()
    assert len(v) == 1 and v[0]["kind"] == "long_hold"
    assert v[0]["held_ms"] >= 20.0
    san_on.reset()
    # an UNwatched lock may hold long (device dispatch under KV._lock)
    lk = san_on.rlock("KV._lock")
    with lk:
        time.sleep(0.06)
    assert san_on.violations() == []


def test_sanitizer_condition_wait_does_not_count_as_holding(san_on):
    san_on.configure(hold_ms=20.0)
    cv = san_on.condition("NetServer._flush_cv")
    with cv:
        cv.wait(0.06)      # parked, not holding
    assert san_on.violations() == []


def test_sanitizer_condition_is_reentrant_like_the_primitive(san_on):
    # threading.Condition()'s default lock is an RLock: nested
    # `with cv:` is legal and must not be reported — and a wait from
    # the nested depth must fully release and restore it (Condition
    # releases ALL recursion levels via _release_save)
    cv = san_on.condition("NetServer._flush_cv")
    with cv:
        with cv:
            cv.wait(0.01)
        cv.notify_all()    # still held after the nested exit
    assert san_on.violations() == []
    # and the condition is actually free afterwards: another thread
    # can take it (a leaked recursion level would hang here)
    got = []
    t = threading.Thread(target=lambda: (cv.acquire(), got.append(1),
                                         cv.release()))
    t.start(); t.join(2.0)
    assert got == [1]


def test_none_guard_with_justification_declares_no_fields(tmp_path):
    # `# guarded-by: <none>  (reason...)` is the convention's dominant
    # form; the justification must not be comma-split into phantom
    # guarded fields (a phantom matching a real attribute elsewhere
    # would fabricate guarded-write findings on unrelated classes)
    p = tmp_path / "none_guard.py"
    p.write_text(
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        # guarded-by: <none>  (pure section, alive, stats)\n"
        "        self._lock = threading.Lock()\n")
    model = build_model([(str(p), "none_guard.py")])
    c = model.modules["none_guard.py"].classes["C"]
    assert model.find_lock(c, "_lock").guards == []
    assert dict(c.guarded) == {}


def test_sanitizer_nonblocking_self_probe_returns_false(san_on):
    # acquire(blocking=False) on a self-held lock cannot deadlock:
    # plain threading.Lock returns False there, so must the wrapper
    lk = san_on.lock("NetServer.op_lock")
    with lk:
        assert lk.acquire(blocking=False) is False
    assert san_on.violations() == []
    with lk:       # still usable, no leaked state
        pass


def test_sanitizer_flush_runs_after_the_physical_release(san_on):
    # the deferred telemetry/rung half (which can write a flight dump)
    # must run AFTER the wrapped primitive is dropped, not merely after
    # the held-set empties — otherwise the dump IO runs inside the very
    # critical section being timed and convoys its waiters
    from pmdfc_tpu.runtime import sanitizer as san_mod
    san_on.configure(hold_ms=5.0)
    lk = san_on.lock("NetServer._flush_cv")  # in HOLD_WATCH
    seen = []
    orig = san_mod._flush_pending

    def spy():
        seen.append(lk._inner.locked())
        orig()

    san_mod._flush_pending = spy
    try:
        with lk:
            time.sleep(0.02)               # trips the long-hold report
    finally:
        san_mod._flush_pending = orig
    assert [v["kind"] for v in san_on.violations()] == ["long_hold"]
    assert seen == [False]                 # inner lock already released


def test_sanitizer_violations_reach_telemetry(san_on):
    from pmdfc_tpu.runtime import telemetry as tele

    tele.configure()
    b = san_on.lock("KV._lock")
    a = san_on.lock("NetServer.op_lock")
    with b, a:
        # the violation is RECORDED immediately but its telemetry/rung
        # half (which can write a flight dump) must be deferred until
        # this thread has dropped every lock — dump IO inside the very
        # critical section being timed would convoy the serving path
        assert len(san_on.violations()) == 1
        mid = tele.snapshot()["counters"]
        assert not any(k == "rung.sanitizer_violation" and v
                       for k, v in mid.items())
    snap = tele.snapshot()
    assert snap["counters"].get("sanitizer0.inversions", 0) >= 1 or any(
        k.endswith(".inversions") and v >= 1
        for k, v in snap["counters"].items())
    assert snap["counters"].get("rung.sanitizer_violation", 0) >= 1 or any(
        k == "rung.sanitizer_violation" and v >= 1
        for k, v in snap["counters"].items())


# --- 3b. instrumented serving plane under chaos ---------------------------


W = 16


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
        W, dtype=np.uint32)


@pytest.mark.slow
def test_chaos_soak_under_sanitizer_reports_nothing(san_on):
    """The acceptance drill: coalesced server + pipelined clients +
    seeded net chaos, every lock instrumented — the soak must complete
    with zero wrong bytes AND zero sanitizer reports."""
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.config import NetConfig
    from pmdfc_tpu.runtime.failure import ChaosProxy, ReconnectingClient
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    shared = LocalBackend(page_words=W, capacity=1 << 12)
    srv = NetServer(lambda: shared, net=NetConfig(
        flush_ops=64, flush_timeout_us=500, settle_us=100)).start()
    proxy = ChaosProxy("127.0.0.1", srv.port, seed=7,
                       rates={"flip": 0.01, "duplicate": 0.005,
                              "delay": 0.01}, delay_s=0.002)
    stop = threading.Event()
    errors: list[BaseException] = []

    def worker(t):
        rc = ReconnectingClient(
            lambda: TcpBackend("127.0.0.1", proxy.port, page_words=W,
                               op_timeout_s=2.0, keepalive_s=None),
            page_words=W, retry_delay_s=0.01, seed=t)
        rng = np.random.default_rng(100 + t)
        try:
            while not stop.is_set():
                keys = _keys(int(rng.integers(1, 32)),
                             seed=int(rng.integers(1 << 16)))
                rc.put(keys, _pages(keys))
                out, found = rc.get(keys)
                # zero wrong bytes: served rows must match their content
                if found.any():
                    assert np.array_equal(out[found], _pages(keys)[found])
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
        finally:
            rc.close()

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(4)]
    with srv, proxy:
        for t in threads:
            t.start()
        time.sleep(3.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    assert san_on.violations() == [], san_on.violations()
