"""One-sided / passive-memory mode (ref `server/onesided/rdma_svr.cpp`,
`client/julee.c:103-120`, `client/onesided/pmdfc_rdma.c:708-790`).

The pool is passive (no index, no server logic); the client owns the
key→row map. Clean-cache semantics throughout: grant exhaustion drops the
oldest mapping, a lost client map turns every get into a legal miss.
"""

import numpy as np
import pytest

from pmdfc_tpu.client.cleancache import CleanCacheClient
from pmdfc_tpu.onesided import OneSidedBackend, PassivePool

W = 64


def _pages(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint64).astype(
        np.uint32
    )


def _keys(n, seed=0):
    rng = np.random.default_rng(seed + 1000)
    flat = rng.choice(1 << 24, size=n, replace=False)
    return np.stack([flat >> 12, flat & 0xFFF], -1).astype(np.uint32)


@pytest.fixture(params=["hbm", "host"])
def pool(request):
    return PassivePool(num_rows=256, page_words=W, mode=request.param)


def test_roundtrip_content(pool):
    be = OneSidedBackend(pool, slice_pages=128)
    keys, pages = _keys(100), _pages(100)
    be.put(keys, pages)
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    # absent keys: pure local miss, zero pool traffic
    reads_before = pool.reads
    out2, found2 = be.get(_keys(10, seed=9))
    assert not found2.any() and (out2 == 0).all()
    assert pool.reads == reads_before


def test_overwrite_reuses_row(pool):
    be = OneSidedBackend(pool, slice_pages=16)
    keys = _keys(8)
    be.put(keys, _pages(8, seed=1))
    free_before = len(be._free)
    newpages = _pages(8, seed=2)
    be.put(keys, newpages)  # re-put: same rows, no allocation
    assert len(be._free) == free_before
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, newpages)


def test_invalidate_frees_rows(pool):
    be = OneSidedBackend(pool, slice_pages=16)
    keys = _keys(16)
    be.put(keys, _pages(16))
    hit = be.invalidate(keys[:10])
    assert hit.all()
    assert len(be._free) == 10
    _, found = be.get(keys)
    assert list(found) == [False] * 10 + [True] * 6
    # freed rows are reusable
    more = _keys(10, seed=5)
    be.put(more, _pages(10, seed=5))
    assert be.drops == 0


def test_grant_exhaustion_drops_oldest(pool):
    be = OneSidedBackend(pool, slice_pages=32)
    keys, pages = _keys(48), _pages(48)
    be.put(keys, pages)  # 48 puts into 32 rows: 16 oldest dropped
    assert be.drops == 16
    out, found = be.get(keys)
    assert list(found) == [False] * 16 + [True] * 32
    np.testing.assert_array_equal(out[16:], pages[16:])
    s = be.stats()
    assert s["mapped"] == 32 and s["free_rows"] == 0


def test_duplicate_keys_in_batch_last_wins(pool):
    be = OneSidedBackend(pool, slice_pages=16)
    k = _keys(4)
    keys = np.concatenate([k, k[:2]])
    pages = _pages(6, seed=3)
    be.put(keys, pages)
    out, found = be.get(k)
    assert found.all()
    np.testing.assert_array_equal(out[0], pages[4])
    np.testing.assert_array_equal(out[1], pages[5])
    np.testing.assert_array_equal(out[2:], pages[2:4])


def test_client_map_loss_is_legal_miss(pool):
    """Crash analog: a fresh client over the same pool region misses
    legally everywhere and can repopulate; the pool needs no repair."""
    grant = pool.grant(64)
    be = OneSidedBackend(pool, grant=grant)
    keys, pages = _keys(32), _pages(32)
    be.put(keys, pages)
    # client restarts: same grant, empty map
    be2 = OneSidedBackend(pool, grant=grant)
    out, found = be2.get(keys)
    assert not found.any() and (out == 0).all()
    be2.put(keys[:8], pages[:8])
    out2, found2 = be2.get(keys[:8])
    assert found2.all()
    np.testing.assert_array_equal(out2, pages[:8])


def test_multi_client_isolation(pool):
    a = OneSidedBackend(pool, slice_pages=64)
    b = OneSidedBackend(pool, slice_pages=64)
    assert a.grant_hi <= b.grant_lo or b.grant_hi <= a.grant_lo
    ka, kb = _keys(40, seed=1), _keys(40, seed=2)
    pa, pb = _pages(40, seed=1), _pages(40, seed=2)
    a.put(ka, pa)
    b.put(kb, pb)
    out_a, f_a = a.get(ka)
    out_b, f_b = b.get(kb)
    assert f_a.all() and f_b.all()
    np.testing.assert_array_equal(out_a, pa)
    np.testing.assert_array_equal(out_b, pb)
    # grants are finite: exhausting the pool raises loudly
    with pytest.raises(ValueError, match="exhausted"):
        pool.grant(1 << 20)


def test_pool_persistence_across_restart(pool, tmp_path):
    grant = pool.grant(64)
    be = OneSidedBackend(pool, grant=grant)
    keys, pages = _keys(20), _pages(20)
    be.put(keys, pages)
    path = str(tmp_path / "pool.npz")
    pool.save(path)
    # server restart: new pool object, same region file (PMEM analog)
    pool2 = PassivePool(num_rows=256, page_words=W, mode=pool.mode)
    pool2.load(path)
    # client that KEPT its map (the persistent-hashtable variant) resolves
    be2 = OneSidedBackend(pool2, grant=grant)
    be2._map = dict(be._map)
    be2._free = list(be._free)
    out, found = be2.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    # wrong-shape restore fails loudly
    with pytest.raises(ValueError, match="shape"):
        PassivePool(num_rows=16, page_words=W).load(path)


def test_cleancache_client_rides_onesided(pool):
    cc = CleanCacheClient(OneSidedBackend(pool, slice_pages=64))
    pages = _pages(30, seed=7)
    oids = np.full(30, 5)
    idxs = np.arange(30)
    cc.put_pages(oids, idxs, pages)
    out, found = cc.get_pages(oids, idxs)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    assert cc.get_page(5, 1000) is None
    hit = cc.invalidate_pages(oids[:5], idxs[:5])
    assert hit.all()
    out2, found2 = cc.get_pages(oids[:5], idxs[:5])
    assert not found2.any()


def test_storm_content_verified():
    """Reference-style storm (`client/rdpma_page_test.c:116-180`): many
    batches, every byte verified, on the HBM pool."""
    pool = PassivePool(num_rows=1 << 12, page_words=W, mode="hbm")
    be = OneSidedBackend(pool, slice_pages=1 << 12)
    rng = np.random.default_rng(11)
    n = 1 << 12
    keys = _keys(n, seed=12)
    pages = (
        keys[:, 1:2].astype(np.uint32) * np.arange(1, W + 1, dtype=np.uint32)
    )
    for lo in range(0, n, 256):
        be.put(keys[lo : lo + 256], pages[lo : lo + 256])
    order = rng.permutation(n)
    for lo in range(0, n, 512):
        sel = order[lo : lo + 512]
        out, found = be.get(keys[sel])
        assert found.all()
        np.testing.assert_array_equal(out, pages[sel])


# -- one-sided over the network (PoolServer/RemotePool, runtime/net.py) --


def _net_pool():
    from pmdfc_tpu.runtime.net import PoolServer, RemotePool

    pool = PassivePool(num_rows=256, page_words=W, mode="host")
    srv = PoolServer(pool).start()
    proxy = RemotePool("127.0.0.1", srv.port, page_words=W)
    return srv, pool, proxy


def test_remote_pool_grant_and_verbs():
    """The MR-handshake + raw-verb analogs over a real socket
    (`server/onesided/rdma_svr.cpp:178`, `pmdfc_rdma.c:708-790`)."""
    srv, pool, proxy = _net_pool()
    with srv, proxy:
        assert proxy.num_rows == 256
        lo, hi = proxy.grant(32)
        assert hi - lo == 32
        rows = np.arange(lo, lo + 8, dtype=np.int32)
        pages = (rows[:, None] * 3 + np.arange(W)).astype(np.uint32)
        proxy.write_rows(rows, pages)
        out = proxy.read_rows(rows)
        assert np.array_equal(out, pages)
        # miss lanes (-1) come back zeroed, no protocol error
        mixed = np.array([lo, -1, lo + 1], np.int32)
        out2 = proxy.read_rows(mixed)
        assert np.array_equal(out2[0], pages[0])
        assert (out2[1] == 0).all()


def test_onesided_client_stack_over_network():
    """The full one-sided client stack (key→row map, FIFO drop, clean-cache
    semantics) unchanged over the TCP proxy."""
    from pmdfc_tpu.client.cleancache import CleanCacheClient
    from pmdfc_tpu.onesided import OneSidedBackend

    srv, pool, proxy = _net_pool()
    with srv, proxy:
        be = OneSidedBackend(proxy, slice_pages=64)
        cc = CleanCacheClient(be)
        oids = np.full(48, 3, np.uint32)
        idxs = np.arange(48, dtype=np.uint32)
        pages = (idxs[:, None] * 7 + np.arange(W)).astype(np.uint32)
        cc.put_pages(oids, idxs, pages)
        out, found = cc.get_pages(oids, idxs)
        assert found.all()
        assert np.array_equal(out, pages)
        # absence answered locally: zero wire traffic for a pure miss
        ops_before = srv.stats["ops"]
        assert cc.get_page(3, 9999) is None
        assert srv.stats["ops"] == ops_before
        # map loss (client restart) = legal misses, pool needs no repair
        be2 = OneSidedBackend(proxy, slice_pages=64)
        _, found2 = CleanCacheClient(be2).get_pages(oids[:4], idxs[:4])
        assert not found2.any()


def test_remote_pool_grant_exhaustion_refused():
    srv, pool, proxy = _net_pool()
    with srv, proxy:
        proxy.grant(200)
        try:
            proxy.grant(200)
            assert False, "expected exhaustion"
        except RuntimeError:
            pass
        # connection still healthy after the refusal
        lo, hi = proxy.grant(16)
        assert hi - lo == 16
