"""2-D serving mesh drills (marker: mesh2d) — replication fused into
the plane as device-side replica collectives.

Runs on the suite-wide forced 8-device CPU host mesh. Layers:

1. **Partitioning** — the grown `MESH2D_AXIS_RULES` table validates on
   a 2-D mesh and REFUSES on a 1-D one; every `KVState` leaf either
   shards over the replica axis via a rule or carries an explicit
   replicated-along marker (`partitioning._PATH_REPLICATED`).
2. **Plane semantics** — a `(kv, replica)` plane reproduces the
   single-device ground truth on a mixed workload; one launch per
   phase replicates every lane; the hedged replica-shard read returns
   the first digest-validated lane's row with per-lane attribution and
   the miss-cause sum invariant held bit-exact.
3. **Conformance** — `PMDFC_MESH2D=off`: the SAME factory call yields
   a 1-D mesh, zero 2-D programs launch, the wire transcript is
   bit-identical to a plain 1-D plane, and the replica wire capability
   is neither requested nor acked.
4. **The fault drill** — a seeded storm through the coalesced
   NetServer while one replica lane's rows are corrupted mid-soak:
   zero wrong bytes served, digest refusals attributed per lane,
   `misses == Σ causes` bit-exact across `stats()`, the shard-report
   sums, and the wire `MSG_STATS` snapshot; the device-side
   anti-entropy pass (`MSG_RREPAIR`) re-syncs the lane.
5. **Delegation** — a `ReplicaGroup` over fused endpoints collapses
   its rf-way fan-out to one wire put per key (`fused_delegated`),
   `fused_plane=False` keeps the host loops.
"""

from __future__ import annotations

import numpy as np
import pytest

from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              MeshConfig, NetConfig, ReplicaConfig)

pytestmark = pytest.mark.mesh2d

W = 16


def _cfg(capacity=1 << 10, bloom=True, paged=True):
    return KVConfig(
        index=IndexConfig(capacity=capacity),
        bloom=BloomConfig(num_bits=1 << 15) if bloom else None,
        paged=paged, page_words=W)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False)
    return np.stack([flat >> 10, flat & 0x3FF], -1).astype(np.uint32)


def _pages(keys):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, W + 1, dtype=np.uint32)[None, :])


def _plane(n_shards=2, lanes=2, cfg=None):
    from pmdfc_tpu.parallel.plane import make_serving_backend

    return make_serving_backend(
        cfg or _cfg(), MeshConfig(n_shards=n_shards, replica_axis=lanes))


def _cause_sum(stats: dict) -> int:
    from pmdfc_tpu.kv import MISS_CAUSE_NAMES

    return sum(int(stats[c]) for c in MISS_CAUSE_NAMES)


# --- 1. partitioning ------------------------------------------------------


def test_mesh2d_rules_and_replicated_markers():
    import jax

    from pmdfc_tpu.parallel import partitioning as pt
    from pmdfc_tpu.parallel.shard import make_mesh, make_mesh2d

    mesh1 = make_mesh(np.array(jax.devices()[:2]))
    mesh2 = make_mesh2d(2, 2)
    # the grown table validates on the 2-D mesh and REFUSES on 1-D —
    # a replica rule on a replica-less mesh is the silent-replicate bug
    pt.validate_rules(pt.MESH2D_AXIS_RULES, mesh2)
    with pytest.raises(ValueError, match="names a mesh axis"):
        pt.validate_rules(pt.MESH2D_AXIS_RULES, mesh1)
    # mesh-aware resolution picks the right table
    assert pt.rules_for_mesh(mesh2) == pt.MESH2D_AXIS_RULES
    assert pt.rules_for_mesh(mesh1) == pt.DEFAULT_AXIS_RULES
    # the replica_lane rule is the per-lane attribution outputs' spec
    spec = pt.spec_for((pt.SHARD, pt.REPLICA_LANE),
                       pt.MESH2D_AXIS_RULES)
    assert spec == jax.sharding.PartitionSpec("kv", "replica")
    # every leaf: a 2-D rule naming the replica axis OR an explicit
    # replicated-along marker (all state replicates along the lane)
    for cfg in (_cfg(), _cfg(bloom=False), _cfg(paged=False)):
        for row in pt.describe(cfg):
            named = pt.REPLICA_MESH_AXIS in row["spec"]
            marked = pt.REPLICA_MESH_AXIS in row["replicated_along"]
            assert named or marked, row
    # an unclassified leaf path is an error, not a silent replicate
    with pytest.raises(ValueError, match="replicated-along"):
        pt.replicated_along(".nonsense.leaf")


def test_mesh2d_construction_gates():
    from pmdfc_tpu.config import TierConfig
    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh2d

    with pytest.raises(ValueError, match="devices"):
        make_mesh2d(8, 2)  # 16 > the 8 forced host devices
    with pytest.raises(ValueError, match="tiered"):
        ShardedKV(KVConfig(index=IndexConfig(capacity=1 << 9),
                           page_words=W, tier=TierConfig()),
                  mesh=make_mesh2d(2, 2))


# --- 2. plane semantics ---------------------------------------------------


@pytest.mark.slow
def test_mesh2d_matches_single_device_results():
    # slow tier (tier-1 budget): the hedged-read drill below pins 2-D
    # byte/found correctness in tier-1; this is the full ref-KV
    # identity sweep (stats, deletes, extents) for full CI
    from pmdfc_tpu.kv import KV

    keys = _keys(300, seed=11)
    pages = _pages(keys)
    be = _plane(2, 2)
    assert be.replica_lanes == 2 and be.skv.n_replicas == 2
    ref = KV(_cfg())

    be.put(keys, pages)
    ref.insert(keys, pages)
    out, found = be.get(keys)
    rout, rfound = ref.get(keys)
    np.testing.assert_array_equal(found, np.asarray(rfound))
    np.testing.assert_array_equal(out, np.asarray(rout))
    hit = be.invalidate(keys[:64])
    rhit = ref.delete(keys[:64])
    np.testing.assert_array_equal(hit, np.asarray(rhit))
    assert be.insert_extent(np.array([3, 0], np.uint32),
                            np.array([0, 4096], np.uint32), 32) == 0
    ref.insert_extent(np.array([3, 0], np.uint32),
                      np.array([0, 4096], np.uint32), 32)
    ekeys = np.array([[3, 5], [3, 40]], np.uint32)
    _, ef = be.get_extent(ekeys)
    _, ref_ef = ref.get_extent(ekeys)
    assert ef[0] and not ef[1]
    np.testing.assert_array_equal(ef, np.asarray(ref_ef))
    # canonical stats agree with the 1-D ground truth, causes included
    s, r = be.skv.stats(), ref.stats()
    for k in ("puts", "gets", "hits", "misses", "deletes"):
        assert s[k] == r[k], (k, s, r)
    assert s["misses"] == _cause_sum(s)
    # one launch replicated every lane: a healthy plane serves entirely
    # from lane 0 (lowest validated lane wins), lane 1 idle but in sync
    # (page GETs only — extent resolution is the broadcast body and
    # carries no lane arbitration)
    rep = be.skv.replica_report()
    assert rep["n_replicas"] == 2
    assert rep["served"][0] == 300 and rep["served"][1] == 0
    assert rep["digest_refused"] == [0, 0]


@pytest.mark.slow
def test_mesh2d_unpaged_plane_serves_values():
    be = _plane(2, 2, cfg=_cfg(bloom=False, paged=False))
    keys = _keys(64, seed=13)
    vals = np.stack([keys[:, 0] ^ 7, keys[:, 1] + 1], -1).astype(np.uint32)
    be.put(keys, vals)
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, vals)
    assert be.replica_repair() == 0  # nothing to digest-compare


def test_mesh2d_hedged_read_routes_around_corrupt_lane():
    keys = _keys(256, seed=17)
    pages = _pages(keys)
    be = _plane(2, 2)
    be.put(keys, pages)
    skv = be.skv
    # lane 1 corrupted: lane 0 serves everything, lane 1's digest gate
    # refuses per-row, zero wrong bytes, invariant exact
    skv.corrupt_replica_lane(1)
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    rep = skv.replica_report()
    assert rep["served"][0] == 256 and rep["served"][1] == 0
    assert rep["digest_refused"][1] == 256
    # heal lane 1, then corrupt lane 0: the hedge rescues from lane 1
    assert skv.replica_repair() >= 256
    skv.corrupt_replica_lane(0)
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    rep = skv.replica_report()
    assert rep["served"][1] == 256
    assert rep["digest_refused"][0] >= 256
    s = skv.stats()
    assert s["misses"] == _cause_sum(s) == 0
    # both lanes corrupt: legal misses (cause = digest), never bytes
    skv.corrupt_replica_lane(1)
    out, found = be.get(keys)
    assert not found.any() and not out.any()
    s = skv.stats()
    assert s["misses"] == _cause_sum(s) == 256
    assert s["miss_digest"] == 256
    # per-shard report sums reconcile with the canonical totals
    repsh = skv.shard_report()
    assert sum(repsh["stats"]["misses"]) == s["misses"]
    assert repsh["replica"]["digest_refused"][0] >= 512


@pytest.mark.slow
def test_mesh2d_repair_is_attributed_per_lane():
    # slow tier: the wire soak's MSG_RREPAIR leg carries tier-1's
    # repair coverage; this is the per-lane attribution deep-dive
    keys = _keys(128, seed=19)
    be = _plane(2, 2)
    be.put(keys, _pages(keys))
    be.skv.corrupt_replica_lane(1)
    n = be.replica_repair()
    assert n >= 128
    rep = be.skv.replica_report()
    assert rep["repaired"][1] >= 128 and rep["repaired"][0] == 0
    out, found = be.get(keys)
    assert found.all()
    # the repaired lane validates again: no further refusals
    assert be.skv.replica_report()["digest_refused"][1] == 0


@pytest.mark.slow
def test_mesh2d_warmup_counts_nothing():
    be = _plane(2, 2)
    assert be.warmup(32) > 0
    s = be.skv.stats()
    assert s["gets"] == 0 and s["puts"] == 0, s
    names = {k[0] for k in be.skv._jits}
    assert {"plane_insert2", "plane_delete2", "plane_get_ro2"} <= names


# --- 3. conformance -------------------------------------------------------


def _verb_transcript(be, seed=77, steps=36):
    """Seeded mixed workload straight against the backend verbs — the
    conformance unit (the WIRE layer's own transcript conformance is
    covered by test_mesh's 1-D drill and the 2-D wire soak below)."""
    rng = np.random.default_rng(seed)
    universe = _keys(256, seed=seed)
    out = []
    for _ in range(steps):
        op = int(rng.integers(5))
        lo = int(rng.integers(0, 240))
        n = int(rng.integers(1, 16))
        sel = universe[lo:lo + n]
        if op == 0:
            be.put(sel, _pages(sel))
            out.append(("put", n))
        elif op in (1, 2):
            pages, found = be.get(sel)
            out.append(("get", found.tolist(), pages[found].tolist()))
        elif op == 3:
            out.append(("inval", be.invalidate(sel).tolist()))
        else:
            vals, ef = be.get_extent(sel)
            out.append(("gext", ef.tolist(), vals[ef].tolist()))
    be.insert_extent(np.array([3, 0], np.uint32),
                     np.array([0, 4096], np.uint32), 32)
    vals, ef = be.get_extent(np.array([[3, 5], [3, 40]], np.uint32))
    out.append(("ext", ef.tolist(), vals.tolist()))
    return out


@pytest.mark.slow
def test_mesh2d_off_kill_switch_is_conformant(monkeypatch):
    """`PMDFC_MESH2D=off` must collapse the SAME factory call to a 1-D
    mesh + host-replication topology: zero 2-D programs, bit-identical
    transcript vs a plain 1-D plane on a seeded mixed workload, and the
    wire capability neither requested (client) nor acked (server).

    Slow tier (the test_mesh 2x-serve precedent): tier-1's budget on
    the 870 s window is ~30 s after PR 12, so the double-transcript
    drills run in full CI and the `mesh2d_smoke` agenda step — tier-1
    keeps the cheap 2-D correctness pins (hedged read, rules,
    construction gates)."""
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    monkeypatch.setenv("PMDFC_MESH2D", "off")
    off = _plane(2, 2)
    assert off.replica_lanes == 1 and off.skv.n_replicas == 1
    assert off.skv.mesh.devices.ndim == 1
    got_off = _verb_transcript(off)
    assert not any(k[0].endswith("2") for k in off.skv._jits), \
        "2-D programs launched under the kill switch"
    # capability gate while the switch is off: the client never
    # REQUESTS the capability, so lanes stay 1 and replica_repair
    # never puts a verb on the wire
    srv = NetServer(lambda: off,
                    net=NetConfig(flush_timeout_us=2000,
                                  settle_us=200)).start()
    try:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as tb:
            assert tb.replica_lanes == 1
            assert tb.replica_repair() == 0
    finally:
        srv.stop()
    monkeypatch.delenv("PMDFC_MESH2D")
    plain = _plane(2, 1)
    got_plain = _verb_transcript(plain)
    assert got_off == got_plain, "kill switch is not conformant"


# --- 4. the wire fault drill ----------------------------------------------


@pytest.mark.slow
def test_mesh2d_wire_soak_corrupt_lane_mid_flight():
    """THE acceptance drill: a seeded mixed storm through the coalesced
    NetServer over a (kv=2, replica=2) plane; one replica lane's rows
    are corrupted MID-SOAK. Zero wrong bytes ever served, the lane's
    digest refusals attributed per lane, `misses == Σ causes` bit-exact
    across stats(), the per-shard report sums, and the wire MSG_STATS
    snapshot — then MSG_RREPAIR re-syncs the lane and it serves again.

    Slow tier + the `mesh2d_smoke` agenda step (which runs it
    explicitly): see the kill-switch drill's tier note — the in-plane
    fault semantics it soaks are pinned cheaply in tier-1 by
    `test_mesh2d_hedged_read_routes_around_corrupt_lane`."""
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    be = _plane(2, 2)
    be.warmup(64)
    keys = _keys(256, seed=23)
    pages = _pages(keys)
    srv = NetServer(lambda: be,
                    net=NetConfig(flush_timeout_us=2000,
                                  settle_us=200)).start()
    wrong = 0
    try:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, window=8) as tb:
            assert tb.replica_lanes == 2
            tb.put(keys, pages)
            rng = np.random.default_rng(29)
            for step in range(18):
                if step == 7:
                    be.skv.corrupt_replica_lane(0)  # mid-soak fault
                lo = int(rng.integers(0, len(keys) - 32))
                sel = slice(lo, lo + int(rng.integers(4, 32)))
                if rng.integers(4) == 0:
                    tb.put(keys[sel], pages[sel])
                else:
                    out, found = tb.get(keys[sel])
                    wrong += int((out[found]
                                  != pages[sel][found]).any(axis=1).sum())
            assert wrong == 0, f"{wrong} wrong pages served"
            rep = be.skv.replica_report()
            assert rep["digest_refused"][0] > 0   # the corrupt lane
            assert rep["served"][1] > 0           # lane 1 rescued
            # invariant across every stats surface, bit-exact
            s = be.skv.stats()
            assert s["misses"] == _cause_sum(s)
            repsh = be.skv.shard_report()
            assert sum(repsh["stats"]["misses"]) == s["misses"]
            for name in ("miss_cold", "miss_digest"):
                assert sum(repsh["stats"][name]) == s[name]
            wire = tb.server_stats()
            assert wire["misses"] == _cause_sum(wire) == s["misses"]
            assert wire["replica"]["digest_refused"] \
                == rep["digest_refused"]
            # the pulled document stays schema-clean with the replica
            # block aboard (the mesh2d_smoke agenda gate)
            from pmdfc_tpu.runtime import telemetry as tele
            if tele.enabled():
                from tools.check_teledump import check
                errs = check(wire)
                assert not errs, errs
            # device-side anti-entropy over the wire, then clean serving
            repaired = tb.replica_repair()
            assert repaired > 0
            out, found = tb.get(keys)
            assert found.all()
            np.testing.assert_array_equal(out, pages)
    finally:
        srv.stop()


# --- 5. ReplicaGroup delegation -------------------------------------------


def _fused_fleet(n_servers, lanes=2):
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    planes = [_plane(2, lanes) for _ in range(n_servers)]
    servers = [NetServer(lambda b=b: b,
                         net=NetConfig(flush_timeout_us=2000,
                                       settle_us=200)).start()
               for b in planes]
    eps = [TcpBackend("127.0.0.1", s.port, page_words=W,
                      keepalive_s=None) for s in servers]
    return planes, servers, eps


@pytest.mark.slow
def test_mesh2d_group_delegates_fanout_to_fused_plane():
    # slow tier: two fused fleets + a group per drill — the 2-D wire
    # soak above carries tier-1's fused-serving weight
    from pmdfc_tpu.client.replica import ReplicaGroup

    planes, servers, eps = _fused_fleet(2)
    g = ReplicaGroup(eps, page_words=W,
                     cfg=ReplicaConfig(n_replicas=2, rf=2,
                                       repair_interval_s=0))
    try:
        keys = _keys(96, seed=31)
        pages = _pages(keys)
        g.put(keys, pages)
        c = dict(g.counters)
        assert c["fused_delegated"] >= 96  # every key collapsed
        # each key physically landed on exactly ONE server (the device
        # lanes carry the rf, not a second TCP loop)
        per = [int(p.skv.stats()["puts"]) for p in planes]
        assert sum(per) == 96 and all(n > 0 for n in per), per
        out, found = g.get(keys)
        assert found.all()
        np.testing.assert_array_equal(out, pages)
        # no host hedges fired: the device lanes are the hedge
        assert dict(g.counters)["hedges_fired"] == 0
    finally:
        g.close()
        for s in servers:
            s.stop()


@pytest.mark.slow
def test_mesh2d_group_fused_plane_off_keeps_host_loops():
    # slow tier: the cfg-knob twin of the delegation drill (the env
    # kill-switch half below carries the tier-1 conformance weight)
    from pmdfc_tpu.client.replica import ReplicaGroup

    planes, servers, eps = _fused_fleet(2)
    g = ReplicaGroup(eps, page_words=W,
                     cfg=ReplicaConfig(n_replicas=2, rf=2,
                                       repair_interval_s=0,
                                       fused_plane=False))
    try:
        keys = _keys(64, seed=37)
        g.put(keys, _pages(keys))
        assert dict(g.counters)["fused_delegated"] == 0
        # host fan-out intact: every key reached BOTH servers
        per = [int(p.skv.stats()["puts"]) for p in planes]
        assert per == [64, 64], per
    finally:
        g.close()
        for s in servers:
            s.stop()


@pytest.mark.slow
def test_mesh2d_off_group_keeps_host_fanout(monkeypatch):
    """PMDFC_MESH2D=off, group half: the client never requests the
    replica capability, endpoints read lanes=1, and the ReplicaGroup
    keeps its host rf-way TCP fan-out — the host-replication
    conformance path (servers collapse to 1-D planes too)."""
    from pmdfc_tpu.client.replica import ReplicaGroup

    monkeypatch.setenv("PMDFC_MESH2D", "off")
    planes, servers, eps = _fused_fleet(2)
    g = ReplicaGroup(eps, page_words=W,
                     cfg=ReplicaConfig(n_replicas=2, rf=2,
                                       repair_interval_s=0))
    try:
        assert all(ep.replica_lanes == 1 for ep in eps)
        assert all(p.replica_lanes == 1 for p in planes)
        keys = _keys(48, seed=43)
        g.put(keys, _pages(keys))
        assert dict(g.counters)["fused_delegated"] == 0
        per = [int(p.skv.stats()["puts"]) for p in planes]
        assert per == [48, 48], per  # host loops intact
    finally:
        g.close()
        for s in servers:
            s.stop()


@pytest.mark.slow
def test_mesh2d_group_device_repair_rides_repair_cadence():
    from pmdfc_tpu.client.replica import ReplicaGroup

    planes, servers, eps = _fused_fleet(1)
    g = ReplicaGroup(eps, page_words=W,
                     cfg=ReplicaConfig(n_replicas=1, rf=1,
                                       repair_interval_s=0,
                                       device_repair_ticks=2))
    try:
        keys = _keys(48, seed=41)
        pages = _pages(keys)
        g.put(keys, pages)
        planes[0].skv.corrupt_replica_lane(1)
        g.repair_tick()            # tick 1: cadence not due
        assert dict(g.counters)["device_repair_rows"] == 0
        moved = g.repair_tick()    # tick 2: delegated MSG_RREPAIR fires
        assert moved >= 48
        assert dict(g.counters)["device_repair_rows"] >= 48
        assert planes[0].skv.replica_report()["repaired"][1] >= 48
    finally:
        g.close()
        for s in servers:
            s.stop()
