"""Blast-radius containment suite — NACK, bisection, quarantine, deadlines.

The four coupled pieces under one marker:

- `MSG_NACK` negotiation (the `CONTAIN_FLAG` HOLA bit): a failed op is
  answered as an explicit, cause-carrying legal miss/drop on a LIVE
  connection; an un-negotiated peer keeps the rung-3 conn-drop
  semantics bit-for-bit (mixed-fleet interop).
- Poison-op bisection: a phase failure retries the fused batch in
  halves (bounded by ceil(log2 b) extra failures), NACKs the isolated
  culprit, fingerprints it so a RESUBMIT is refused at staging, and
  completes every healthy op in the batch normally.
- Shard quarantine (`ShardQuarantine` + `PlaneBackend`): a shard
  tripping its breaker degrades to `miss_quarantined` host-side while
  healthy shards keep serving; `misses == sum of causes` stays
  bit-exact on every stats surface; a healed shard re-admits through
  the half-open probe.
- End-to-end deadlines: the client stamps a budget into the GET frame;
  the flush sweep sheds already-expired staged ops into `miss_deadline`
  WITHOUT launching device work; `ReplicaGroup` stops firing failover
  rounds at dead work.

Fault injection is the deterministic `FaultPlan` seam (raise-on-keys /
raise-on-shard / raise-on-op-N) — no sleeps-as-faults, every drill
replays. The long poison-storm/shard-kill soak lives in
`bench/containment_soak.py` (agenda hook `containment_smoke`).
"""

import math
import threading
import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, LocalBackend
from pmdfc_tpu.config import (BloomConfig, ContainmentConfig, IndexConfig,
                              KVConfig, NetConfig)
from pmdfc_tpu.kv import KV, MISS_CAUSE_NAMES
from pmdfc_tpu.runtime.failure import (FaultPlan, FaultyBackend,
                                       ShardFault, ShardQuarantine)
from pmdfc_tpu.runtime.net import NetServer, TcpBackend

# the end-to-end NetServer wire drills (each pays server spin-up plus
# coalescer flush dwell, ~5 s apiece on the 1-cpu harness host) and the
# two mesh plane drills also carry `slow` and ride the agenda's
# `tier1_overflow` step, per the PR 13/16 tier-1 budget notes — the
# seed suite already fills ~850 of the 870 s window, so only the
# sub-second unit/client drills stay tier-1
pytestmark = pytest.mark.containment

W = 16


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
        W, dtype=np.uint32
    )


def _faulty_server(**net_kw):
    plan = FaultPlan()
    shared = FaultyBackend(LocalBackend(page_words=W, capacity=1 << 12),
                           plan)
    kw = dict(flush_timeout_us=150_000, settle_us=40_000)
    kw.update(net_kw)
    return NetServer(lambda: shared, net=NetConfig(**kw)).start(), plan


# -- negotiation ------------------------------------------------------


@pytest.mark.slow
def test_nack_negotiation_and_kill_switch(monkeypatch):
    """The `CONTAIN_FLAG` bit is offered and acked by default; either
    side's `PMDFC_CONTAINMENT=off` withholds it (resolved at
    construction, the kill-switch convention of every capability)."""
    srv, _ = _faulty_server()
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            assert be.nack, "containment not negotiated by default"
        monkeypatch.setenv("PMDFC_CONTAINMENT", "off")
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            assert not be.nack, "client-side kill switch ignored"
        monkeypatch.delenv("PMDFC_CONTAINMENT")
    monkeypatch.setenv("PMDFC_CONTAINMENT", "off")
    srv2, _ = _faulty_server()
    monkeypatch.delenv("PMDFC_CONTAINMENT")
    with srv2:
        with TcpBackend("127.0.0.1", srv2.port, page_words=W,
                        keepalive_s=None) as be:
            assert not be.nack, "server-side kill switch ignored"


# -- bisection + fingerprint refusal ----------------------------------


@pytest.mark.slow
def test_poison_bisection_isolates_culprit():
    """b connections fuse one flush; exactly one op is poisoned. The
    bisection must (1) NACK only the culprit, within its
    ceil(log2 b) failure bound, (2) answer every healthy op normally
    with ZERO connection drops — including the victim's conn — and
    (3) refuse the fingerprinted resubmit at staging without re-running
    isolation."""
    srv, plan = _faulty_server()
    bad = _keys(8, seed=101)
    plan.poison_keys(bad)
    b = 4
    with srv:
        bes = [TcpBackend("127.0.0.1", srv.port, page_words=W,
                          keepalive_s=None) for _ in range(b)]
        pools = [_keys(8, seed=50 + i) for i in range(b)]
        barrier = threading.Barrier(b)
        errs: list = []

        def worker(i):
            try:
                barrier.wait()
                ks = bad if i == 0 else pools[i]
                bes[i].put(ks, _pages(ks))
            except Exception as e:  # noqa: BLE001
                errs.append((i, repr(e)))

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, f"an op raised through a NACK: {errs}"
        st = srv.stats.snapshot()
        assert st["poison_ops"] == 1, st
        assert st["nacks_sent"] >= 1
        assert st["bisect_failures"] <= math.ceil(math.log2(b)), st
        # zero non-involved drops: every healthy conn still serves its
        # own puts; the VICTIM's conn is alive too (NACK, not rung 3)
        for i in range(1, b):
            _, found = bes[i].get(pools[i])
            assert found.all(), f"conn{i} lost its batch"
        _, found = bes[0].get(pools[1])
        assert found.all(), "victim conn was dropped"
        # resubmit: refused at staging — no second isolation, no device
        bes[0].put(bad, _pages(bad))
        st = srv.stats.snapshot()
        assert st["poison_refused"] >= 1, st
        assert st["poison_ops"] == 1, "resubmit re-ran isolation"
        for be in bes:
            be.close()


@pytest.mark.slow
def test_poison_fingerprint_is_verb_seeded():
    """The fingerprint digest seeds with the VERB: a GET for the keys of
    a poisoned PUT is not refused at staging (it is its own op — here it
    fails too and earns its own isolation + NACK all-miss); the GET's
    resubmit then IS refused under the get-seeded fingerprint."""
    srv, plan = _faulty_server()
    bad = _keys(8, seed=7)
    plan.poison_keys(bad)
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            be.put(bad, _pages(bad))  # isolated + NACKed
            refused0 = srv.stats.snapshot()["poison_refused"]
            _, found = be.get(bad)    # NOT refused: distinct verb seed
            assert not found.any(), "poisoned GET must answer all-miss"
            st = srv.stats.snapshot()
            assert st["poison_refused"] == refused0, \
                "a GET was refused under a PUT's fingerprint"
            assert st["poison_ops"] == 2  # the GET earned its own
            _, found = be.get(bad)    # refused now, still legal miss
            assert not found.any()
            assert srv.stats.snapshot()["poison_refused"] > refused0


@pytest.mark.slow
def test_unnegotiated_peer_keeps_conn_drop_semantics(monkeypatch):
    """Mixed fleet: an old (un-negotiated) client hitting a poison op
    gets the pre-containment rung-3 contract — its connection drops,
    nothing masquerades as a NACK — and the server survives to serve a
    fresh channel."""
    srv, plan = _faulty_server()
    bad = _keys(8, seed=7)
    plan.poison_keys(bad)
    monkeypatch.setenv("PMDFC_CONTAINMENT", "off")
    with srv:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, op_timeout_s=5.0)
        assert not be.nack
        with pytest.raises((ConnectionError, OSError)):
            be.put(bad, _pages(bad))
            be.get(bad)  # the drop may land on the next roundtrip
        be.close()
        monkeypatch.delenv("PMDFC_CONTAINMENT")
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be2:
            ks = _keys(8, seed=8)
            be2.put(ks, _pages(ks))
            _, found = be2.get(ks)
            assert found.all(), "server did not survive the conn drop"


# -- deadlines --------------------------------------------------------


@pytest.mark.slow
def test_deadline_shed_lands_in_miss_deadline():
    """A 1 ms budget against a deliberately slow flush dwell: the sweep
    sheds the staged GET before dispatch (`NACK_DEADLINE` -> legal
    all-miss on a live conn), the backend books it under
    `miss_deadline`, and `misses == sum of causes` stays bit-exact."""
    cfg = KVConfig(index=IndexConfig(capacity=1 << 12),
                   bloom=BloomConfig(num_bits=1 << 13),
                   paged=True, page_words=W)
    kv = KV(cfg)
    srv = NetServer(lambda: DirectBackend(kv),
                    net=NetConfig(flush_timeout_us=200_000,
                                  settle_us=120_000)).start()
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, deadline_ms=1.0) as be:
            assert be.nack
            ks = _keys(32, seed=3)
            _, found = be.get(ks)
            assert not found.any(), "an expired GET reported hits"
            # the conn survived the shed: a later op still answers
            _, found = be.get(ks[:4])
            assert not found.any()
        st = srv.stats.snapshot()
        assert st["deadline_shed"] >= 1, st
        s = kv.stats()
        assert s["miss_deadline"] >= 32, s
        causes = {c: s[c] for c in MISS_CAUSE_NAMES}
        assert s["misses"] == sum(causes.values()), (s["misses"], causes)


@pytest.mark.slow
def test_deadline_zero_means_none():
    """`deadline_ms=0` (the default, and what an old peer's stamp reads
    as) never sheds — the slow-dwell server still answers."""
    srv, _ = _faulty_server(flush_timeout_us=100_000, settle_us=60_000)
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            ks = _keys(8, seed=4)
            be.put(ks, _pages(ks))
            _, found = be.get(ks)
            assert found.all()
        assert srv.stats.snapshot()["deadline_shed"] == 0


def test_replica_group_deadline_stops_failover():
    """`ReplicaConfig.deadline_ms`: once the op budget is spent, the
    group stops firing failover rounds at dead work — the remaining
    keys take the legal miss and `deadline_stops` counts the stop."""
    from pmdfc_tpu.client.replica import ReplicaGroup
    from pmdfc_tpu.config import ReplicaConfig

    eps = [LocalBackend(page_words=W, capacity=1 << 10)
           for _ in range(3)]
    g = ReplicaGroup(
        eps, page_words=W,
        cfg=ReplicaConfig(n_replicas=3, rf=2, hedge_ms=0.0,
                          repair_interval_s=0.0, deadline_ms=1e-6))
    try:
        _, found = g.get(_keys(16, seed=5))  # all-miss either way
        assert not found.any()
        assert g.counters["deadline_stops"] == 1
        assert g.counters["failover_gets"] == 0, \
            "an expired op still fired a failover round"
    finally:
        g.close()
    g2 = ReplicaGroup(
        [LocalBackend(page_words=W, capacity=1 << 10)
         for _ in range(3)], page_words=W,
        cfg=ReplicaConfig(n_replicas=3, rf=2, hedge_ms=0.0,
                          repair_interval_s=0.0))
    try:
        g2.get(_keys(16, seed=5))
        assert g2.counters["deadline_stops"] == 0
        assert g2.counters["failover_gets"] > 0
    finally:
        g2.close()


# -- fault seam + quarantine units ------------------------------------


def test_faultplan_seam():
    """The deterministic injection seam itself: poisoned keys raise on
    any phase touching them, a dead shard raises `ShardFault` carrying
    the shard id, raise-on-op-N counts down exactly once, and healing
    clears each fault independently."""
    plan = FaultPlan()
    ks = _keys(4, seed=1)
    plan.poison_keys(ks[:1])
    with pytest.raises(RuntimeError):
        plan.check("put", keys=ks)
    plan.check("put", keys=ks[1:])  # healthy subset passes
    plan.clear_poison()
    plan.check("put", keys=ks)

    plan.fail_shard(2)
    with pytest.raises(ShardFault) as ei:
        plan.check("get", shards=np.array([0, 2]))
    assert ei.value.shard == 2
    plan.check("get", shards=np.array([0, 1]))
    plan.heal_shard(2)
    plan.check("get", shards=np.array([2]))

    plan.raise_on_op(2)
    plan.check("get")
    with pytest.raises(RuntimeError):
        plan.check("get")
    plan.check("get")  # one-shot: the countdown does not re-arm


def test_faulty_backend_capability_mirror():
    """`FaultyBackend` forwards attribute PRESENCE exactly: capability
    probes (`getattr(be, "get_fused", None)`) must see what the inner
    backend exposes, no more — and wrapped phases consult the plan."""
    plan = FaultPlan()
    inner = LocalBackend(page_words=W, capacity=1 << 10)
    fb = FaultyBackend(inner, plan)
    assert fb.page_words == W
    assert hasattr(fb, "get") and hasattr(fb, "insert_extent")
    assert hasattr(fb, "get_fused") == hasattr(inner, "get_fused")
    ks = _keys(4, seed=2)
    fb.put(ks, _pages(ks))
    _, found = fb.get(ks)
    assert found.all()
    plan.poison_keys(ks[:1])
    with pytest.raises(RuntimeError):
        fb.get(ks)


def test_shard_quarantine_unit():
    """`ShardQuarantine` host-side: `quarantine_failures` strikes open a
    shard's breaker, `gate` masks its rows (granting half-open probes
    after cooldown), invalidations journal while blocked and drain at
    re-admission, and the report carries the lifecycle counters."""
    q = ShardQuarantine(4, failures_to_open=2, cooldown_s=0.05,
                        max_cooldown_s=0.2, backoff=2.0, seed=1)
    shards = np.array([0, 1, 2, 3, 2])
    blocked, probing = q.gate(shards)
    assert not blocked.any() and not probing
    assert not q.note_failure(2)
    assert q.note_failure(2)          # second strike trips
    assert q.quarantined() == [2]
    blocked, _ = q.gate(shards)
    assert blocked.tolist() == [False, False, True, False, True]
    q.journal_invalidations(2, _keys(8, seed=3))
    deadline = time.monotonic() + 5.0
    probed = []
    while not probed and time.monotonic() < deadline:
        time.sleep(0.02)              # ride out the jittered cooldown
        _, probed = q.gate(shards)
    assert probed == [2], "half-open probe never granted"
    assert q.note_success(2)          # probe succeeded -> re-admitted
    assert q.quarantined() == []
    ks, overflowed = q.drain_journal(2)
    assert len(ks) == 8 and not overflowed
    rep = q.report()
    assert rep["stats"]["trips"] == 1
    assert rep["stats"]["readmits"] == 1
    assert rep["stats"]["journaled_invals"] == 8


# -- shard quarantine through the serving plane -----------------------


@pytest.mark.slow
def test_plane_shard_quarantine_and_readmission():
    """End-to-end failure domain over a forced-host mesh: kill one
    shard via the fault seam; its breaker trips, its rows degrade to
    `miss_quarantined` while healthy shards keep serving, the invariant
    `misses == sum of causes` stays bit-exact on `stats()` AND
    `shard_report()`, and healing re-admits through the half-open
    probe with resident keys intact."""
    from pmdfc_tpu.config import MeshConfig, mesh_enabled
    from pmdfc_tpu.parallel.plane import make_serving_backend

    if not mesh_enabled():
        pytest.skip("PMDFC_MESH=off")
    plan = FaultPlan()
    cfg = KVConfig(index=IndexConfig(capacity=1 << 10),
                   bloom=BloomConfig(num_bits=1 << 12),
                   paged=True, page_words=W)
    be = make_serving_backend(
        cfg, MeshConfig(n_shards=4),
        containment=ContainmentConfig(quarantine_failures=2,
                                      quarantine_cooldown_s=0.05,
                                      quarantine_max_cooldown_s=0.2),
        fault_plan=plan)
    skv = be.skv
    pool = _keys(128, seed=7)
    be.put(pool, _pages(pool))
    _, res = be.get(pool)
    pool = pool[np.asarray(res, bool)]
    node = skv.node_of(pool)
    k = int(np.bincount(node, minlength=4).argmax())
    on_k, off_k = pool[node == k], pool[node != k]
    assert len(on_k) and len(off_k)

    plan.fail_shard(k)
    for _ in range(8):
        try:
            be.get(pool[:32])
        except ShardFault:
            pass
        if be.quarantine.quarantined():
            break
    assert be.quarantine.quarantined() == [k]
    # quarantined serving: sick rows masked to the attributed miss,
    # healthy shards untouched
    _, found = be.get(pool)
    f = np.asarray(found, bool)
    assert not f[node == k].any(), "a quarantined row claimed a hit"
    assert f[node != k].all(), "a healthy shard lost rows"
    st = skv.stats()
    assert st["miss_quarantined"] >= int((node == k).sum()), st
    causes = {c: st[c] for c in MISS_CAUSE_NAMES}
    assert st["misses"] == sum(causes.values()), (st["misses"], causes)
    rep = skv.shard_report()["stats"]
    assert sum(rep["misses"]) == sum(
        sum(rep[c]) for c in MISS_CAUSE_NAMES)
    # the sick shard's own report row carries the quarantined lane
    assert rep["miss_quarantined"][k] > 0

    plan.heal_shard(k)
    deadline = time.monotonic() + 10.0
    while be.quarantine.quarantined() and time.monotonic() < deadline:
        time.sleep(0.02)
        try:
            be.get(on_k[:16])
        except ShardFault:
            pass
    assert not be.quarantine.quarantined(), "shard never re-admitted"
    _, found = be.get(on_k)
    assert np.asarray(found, bool).all(), \
        "resident keys lost across quarantine"
    st = skv.stats()
    causes = {c: st[c] for c in MISS_CAUSE_NAMES}
    assert st["misses"] == sum(causes.values())
    assert be.quarantine.report()["stats"]["readmits"] >= 1


@pytest.mark.slow
def test_plane_containment_off_is_conformant(monkeypatch):
    """`PMDFC_CONTAINMENT=off`: the plane builds NO quarantine, serves
    verb-for-verb like before, and a device failure propagates raw (the
    pre-containment contract, bit-for-bit)."""
    from pmdfc_tpu.config import MeshConfig, mesh_enabled
    from pmdfc_tpu.parallel.plane import make_serving_backend

    if not mesh_enabled():
        pytest.skip("PMDFC_MESH=off")
    monkeypatch.setenv("PMDFC_CONTAINMENT", "off")
    plan = FaultPlan()
    cfg = KVConfig(index=IndexConfig(capacity=1 << 10),
                   bloom=BloomConfig(num_bits=1 << 12),
                   paged=True, page_words=W)
    be = make_serving_backend(cfg, MeshConfig(n_shards=4),
                              fault_plan=plan)
    assert be.quarantine is None
    pool = _keys(32, seed=9)
    be.put(pool, _pages(pool))
    _, found = be.get(pool)
    f = np.asarray(found, bool)
    out, _ = be.get(pool[f])
    assert (np.asarray(out) == _pages(pool[f])).all()
    plan.fail_shard(0)
    with pytest.raises(ShardFault):
        for _ in range(4):
            be.get(pool)
    st = be.skv.stats()
    assert st["miss_quarantined"] == 0 and st["miss_deadline"] == 0
