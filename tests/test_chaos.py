"""Seeded chaos soak — the whole integrity/degradation ladder at once.

A real-KV NetServer serves through a `ChaosProxy` running a seeded fault
schedule (bit-flips, truncations, duplications, delays, reorders), the
client stack is the full ladder (`IntegrityBackend` over
`ReconnectingClient` over `TcpBackend`), pool bytes are poisoned mid-soak,
and the server is killed and restored from a crash-safe checkpoint (with a
torn newest snapshot that must be rejected). Three invariants, asserted
continuously:

1. NO exception escapes a page op — every fault degrades to miss/drop.
2. NO wrong bytes are ever returned — every `found` page content-verifies
   against the key-derived ground truth (checksum rung + CRC rung + the
   client's own end-to-end digest).
3. Restart serves exactly the last DURABLE checkpoint: the torn newest
   snapshot raises `CheckpointCorruptError`; the restored server's state
   equals what the durable snapshot recorded (hit set and content).

The fast tier runs a short schedule; the `slow` variant soaks longer with
higher fault rates and a second kill/restore cycle.
"""

import time

import numpy as np
import pytest

from pmdfc_tpu import checkpoint
from pmdfc_tpu.checkpoint import CheckpointCorruptError
from pmdfc_tpu.client.backends import DirectBackend, IntegrityBackend
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.failure import ChaosProxy, ReconnectingClient
from pmdfc_tpu.runtime.net import NetServer, TcpBackend

W = 16
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),
    paged=True,
    page_words=W,
)
RATES = {"flip": 0.04, "truncate": 0.02, "duplicate": 0.04,
         "delay": 0.02, "reorder": 0.02}


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    # ground truth derives from the key: ANY wrong byte is detectable
    return (keys[:, 1:2].astype(np.uint32) * 3 + 1) * np.arange(
        1, W + 1, dtype=np.uint32
    )


def _start_server(kv):
    return NetServer(lambda: DirectBackend(kv)).start()


def _soak(steps: int, seed: int, rates: dict, kill_at: tuple,
          tmp_path, pipe: bool = False, window: int = 8) -> dict:
    rng = np.random.default_rng(seed)
    keys = _keys(256, seed=seed)
    pages = _pages(keys)

    kv = KV(CFG)
    srv = _start_server(kv)

    # Warm the serving-path device programs through a chaos-free direct
    # connection BEFORE the faulted window: a cold first compile takes
    # seconds, during which every verb times out (op_timeout_s=1.0) and
    # the client burns the whole soak reconnecting — a compile-timing
    # flake (seen only on cold per-process caches), not a chaos outcome.
    # The warm keys are invalidated again, so KV state stays empty.
    warm = TcpBackend("127.0.0.1", srv.port, page_words=W,
                      keepalive_s=None, op_timeout_s=120.0)
    warm.put(keys[224:240], pages[224:240])
    warm.get(keys[224:240])
    warm.invalidate(keys[224:240])
    warm.close()

    px = ChaosProxy("127.0.0.1", srv.port, seed=seed, rates=rates,
                    delay_s=0.02, reorder_wait_s=0.05)
    port = px.port

    def factory():
        return TcpBackend("127.0.0.1", port, page_words=W,
                          keepalive_s=None, op_timeout_s=1.0,
                          pipeline=pipe, window=window)

    rc = ReconnectingClient(factory, page_words=W, retry_delay_s=0.005,
                            max_retry_delay_s=0.1, seed=seed)
    be = IntegrityBackend(rc)

    durable = str(tmp_path / f"durable_{seed}.npz")
    durable_found: np.ndarray | None = None
    stats = {"wrong_bytes": 0, "found_gets": 0, "poisoned": 0,
             "restores": 0}
    kill_steps = set(kill_at)

    for step in range(steps):
        op = rng.integers(4)
        lo = int(rng.integers(0, 224))
        n = int(rng.integers(1, 16))
        sel = slice(lo, lo + n)
        # every op must degrade, never raise (invariant 1: the soak loop
        # itself finishing is the assertion)
        if op == 0:
            be.put(keys[sel], pages[sel])
        elif op in (1, 2):
            out, found = be.get(keys[sel])
            stats["found_gets"] += int(found.sum())
            good = pages[sel]
            stats["wrong_bytes"] += int(
                (out[found] != good[found]).any(axis=1).sum())
        else:
            be.invalidate(keys[sel])

        if not rc.stats()["connected"]:
            # Disconnected ops fail locally in microseconds, so an
            # unpaced loop burns every remaining step inside the
            # client's 5-100 ms retry backoff window and the soak ends
            # before a reconnect is ever attempted (nothing but drops —
            # a degenerate run that starves the trace/hit-rate
            # assertions). Connected ops are naturally paced by the
            # chaos delays; give disconnected phases the same wall-time
            # footing so recovery is part of every run.
            time.sleep(0.02)

        if step == steps // 4:
            # poison bytes at rest: rung 1 must convert these to misses.
            # The op schedule only touches keys[:239], so keys[240:] are a
            # reserved probe set: insert them DIRECTLY (chaos-free, always
            # lands), poison everything, probe immediately — detection is
            # deterministic regardless of how much chaos-path traffic
            # actually survived to this point.
            import dataclasses

            import jax.numpy as jnp

            kv.insert(keys[240:], pages[240:])
            out0, f0 = kv.get(keys[240:])
            assert f0.all() and (out0 == pages[240:]).all()
            before = kv.stats()["corrupt_pages"]
            with kv._lock:
                pool = kv.state.pool
                kv.state = dataclasses.replace(
                    kv.state,
                    pool=dataclasses.replace(
                        pool, pages=pool.pages ^ jnp.uint32(1 << 9)),
                )
            p_out, p_found = kv.get(keys)
            detected = kv.stats()["corrupt_pages"] - before
            assert detected >= 16, "poisoned probe rows were not detected"
            assert not p_found[240:].any(), \
                "a poisoned probe page was served as a hit"
            assert (p_out[p_found] == pages[p_found]).all(), \
                "a poisoned page was served"
            stats["corrupt_detected"] = stats.get("corrupt_detected", 0) \
                + detected
            stats["poisoned"] += 1

        if step in kill_steps:
            # crash-safe checkpoint, then kill; newest snapshot is torn
            kv.snapshot(durable)
            torn = str(tmp_path / f"torn_{seed}_{step}.npz")
            kv.snapshot(torn)
            data = open(torn, "rb").read()
            open(torn, "wb").write(data[: int(len(data) * 0.7)])
            srv.stop()
            px.close()
            # invariant 3a: the torn snapshot is detected and rejected
            with pytest.raises(CheckpointCorruptError):
                checkpoint.load(torn, CFG)
            kv = KV(CFG, state=checkpoint.load(durable, CFG))
            # record exactly what the durable snapshot serves
            d_out, d_found = kv.get(keys)
            durable_found = d_found.copy()
            assert (d_out[d_found] == pages[d_found]).all(), \
                "restored state serves wrong bytes"
            srv = _start_server(kv)
            px = ChaosProxy("127.0.0.1", srv.port, seed=seed + step,
                            rates=rates, delay_s=0.02, reorder_wait_s=0.05)
            port = px.port  # factory closes over `port` via nonlocal read
            rc._factory = lambda p=px.port: TcpBackend(
                "127.0.0.1", p, page_words=W, keepalive_s=None,
                op_timeout_s=1.0, pipeline=pipe, window=window)
            stats["restores"] += 1
            # invariant 3b: before any new put lands, the server's hit set
            # is the durable snapshot's hit set (direct, chaos-free probe)
            probe = KV(CFG, state=checkpoint.load(durable, CFG))
            p_out, p_found = probe.get(keys)
            assert (p_found == durable_found).all()

    px.close()
    srv.stop()
    be.close()
    stats["chaos"] = dict(px.stats)
    stats["client"] = rc.stats()
    stats["corrupt_detected"] = (
        stats.get("corrupt_detected", 0) + be.counters["corrupt_pages"])
    return stats


def test_chaos_soak_short(tmp_path):
    s = _soak(steps=120, seed=5, rates=RATES, kill_at=(60,),
              tmp_path=tmp_path)
    # invariant 2: nothing wrong was ever served
    assert s["wrong_bytes"] == 0
    assert s["restores"] == 1
    # the schedule really exercised the ladder: faults fired and the
    # poisoned pages were detected (not served)
    assert s["poisoned"] == 1
    assert s["corrupt_detected"] > 0, "poisoned rows were never probed"


@pytest.mark.slow
def test_chaos_soak_long(tmp_path):
    rates = {k: v * 2 for k, v in RATES.items()}
    s = _soak(steps=600, seed=9, rates=rates, kill_at=(200, 420),
              tmp_path=tmp_path)
    assert s["wrong_bytes"] == 0
    assert s["restores"] == 2
    assert s["corrupt_detected"] > 0
    # chaos actually landed: at least some faults of several kinds fired
    fired = sum(v for k, v in s["chaos"].items()
                if k.endswith("_frames") and k != "forwarded_frames")
    assert fired > 0


def test_chaos_extent_verbs_degrade_to_drop_conn():
    """The extent verbs (`MSG_INSEXT`/`MSG_GETEXT`) ride the same CRC
    rung as the page verbs: a bit-flipped frame is counted (`bad_frames`)
    and dropped — the server never parses a garbage registration, the
    client degrades to the legal result (uncovered / miss), and the
    connection recovers."""
    kv = KV(CFG)
    # warm the extent programs OFF the wire: a first-compile stall must
    # not masquerade as a chaos-induced timeout in the assertions below
    kv.insert_extent(np.array([1, 1], np.uint32),
                     np.array([0, 4096], np.uint32), 4)
    kv.get_extent(np.stack([np.full(8, 1, np.uint32),
                            np.arange(1, 9, dtype=np.uint32)], -1))
    srv = _start_server(kv)
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=21) as px:
        def factory():
            return TcpBackend("127.0.0.1", px.port, page_words=W,
                              keepalive_s=None, op_timeout_s=10.0)

        rc = ReconnectingClient(factory, page_words=W,
                                retry_delay_s=0.005,
                                max_retry_delay_s=0.1, seed=21)
        probe = np.stack([np.full(8, 7, np.uint32),
                          np.arange(512, 520, dtype=np.uint32)], -1)
        # connect + one clean op FIRST (ReconnectingClient dials lazily)
        # so the armed flip lands on the INSEXT frame itself, not the
        # handshake — this test exists to prove the server's INSEXT
        # path, specifically, never parses a corrupted registration
        vals, found = rc.get_extent(probe[:1])
        assert rc.connected and not found.any()
        # a corrupted INSEXT frame: the server must not register ANY
        # extent from it; the client reports the whole run uncovered
        px.flip_next(1)
        uncovered = rc.insert_extent([7, 512], [3, 1 << 20], 40)
        assert uncovered == 40  # legal degraded result, never raises
        assert srv.stats["bad_frames"] >= 1
        deadline = time.time() + 5
        while not rc.connected and time.time() < deadline:
            rc.get_extent(probe[:1])
            time.sleep(0.02)
        vals, found = rc.get_extent(probe)
        assert not found.any(), "a torn INSEXT frame registered an extent"
        # now a clean registration, then a flipped GETEXT: degrade to
        # miss (never garbage values), then recover and resolve
        assert rc.insert_extent([7, 512], [3, 1 << 20], 40) == 0
        px.flip_next(1)
        vals, found = rc.get_extent(probe)
        assert not found.any() and (vals == 0).all()
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            vals, found = rc.get_extent(probe)
            if found.all():
                ok = True
                break
            time.sleep(0.02)
        assert ok, "extent path never recovered after the flipped frame"
        want = (3 << 32 | 1 << 20) + (probe[:, 1].astype(np.int64)
                                      - 512) * 4096
        got = (vals[:, 0].astype(np.int64) << 32) | vals[:, 1]
        assert (got == want).all()
        assert px.stats["flipped_frames"] == 2
        rc.close()


def test_chaos_stats_verb_degrades_to_drop_conn():
    """`MSG_STATS` under chaos: a flipped frame (either direction) must
    surface as a dropped connection (`ConnectionError`/`ProtocolError`)
    — never a parse of a garbage JSON snapshot — and the counter rung
    records it; a fresh op channel then serves the real snapshot."""
    kv = KV(CFG)
    srv = _start_server(kv)
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=22) as px:
        be = TcpBackend("127.0.0.1", px.port, page_words=W,
                        keepalive_s=None, op_timeout_s=1.0)
        snap = be.stats()  # the unified stats() surface = server pull
        assert "puts" in snap and "corrupt_pages" in snap
        px.flip_next(1)  # lands on the STATS request frame
        with pytest.raises((ConnectionError, OSError)):
            be.stats()
        assert srv.stats["bad_frames"] >= 1
        be.close()
        # the server survived: a fresh channel pulls a clean snapshot
        be2 = TcpBackend("127.0.0.1", px.port, page_words=W,
                         keepalive_s=None, op_timeout_s=1.0)
        snap2 = be2.server_stats()  # the explicit-roundtrip alias
        assert "puts" in snap2 and "corrupt_pages" in snap2
        be2.close()


def test_chaos_soak_deterministic_schedule(tmp_path):
    """Same seed ⇒ same op schedule and same fault schedule: two runs
    agree on every deterministic counter (the soak is reproducible, so a
    failure in CI replays locally)."""
    a = _soak(steps=60, seed=13, rates={}, kill_at=(), tmp_path=tmp_path)
    b = _soak(steps=60, seed=13, rates={}, kill_at=(), tmp_path=tmp_path)
    assert a["found_gets"] == b["found_gets"]
    assert a["wrong_bytes"] == b["wrong_bytes"] == 0


# --- pipelined (windowed) connection under chaos (netpipe tier) ---------


@pytest.mark.netpipe
def test_chaos_soak_short_pipelined(tmp_path):
    """The acceptance soak on a WINDOWED connection: the full seeded
    fault schedule (flips, truncations, duplications, delays, reorders)
    plus a kill/restore cycle over a pipelined `TcpBackend` — zero
    wrong-bytes deliveries, zero protocol violations (every fault
    degrades to a legal miss/drop; the soak finishing IS the
    no-exception invariant)."""
    s = _soak(steps=120, seed=5, rates=RATES, kill_at=(60,),
              tmp_path=tmp_path, pipe=True, window=8)
    assert s["wrong_bytes"] == 0
    assert s["restores"] == 1
    assert s["poisoned"] == 1
    assert s["corrupt_detected"] > 0


@pytest.mark.netpipe
def test_chaos_pipelined_replies_match_seq_or_drop():
    """Reordered/duplicated/truncated frames on a windowed connection
    must either match by sequence id or degrade to drop-conn: with 4
    threads keeping the window full through a ChaosProxy, every served
    page content-verifies against its own key (no mis-delivered
    wrong-verb bytes) and every thread finishes (no stuck waiter)."""
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer

    shared = LocalBackend(page_words=W, capacity=1 << 13)
    srv = NetServer(lambda: shared).start()
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=31) as px:
        def factory():
            return TcpBackend("127.0.0.1", px.port, page_words=W,
                              keepalive_s=None, op_timeout_s=1.0,
                              pipeline=True, window=8)

        rc = ReconnectingClient(factory, page_words=W,
                                retry_delay_s=0.005,
                                max_retry_delay_s=0.1, seed=31)
        # connect BEFORE the storm: a worker that races the lazy
        # connect degrades its whole quota in microseconds (the same
        # unpaced-degraded-loop class the trace soak hit), and on a
        # fast host the one connected thread then finishes before the
        # first fault arms — fired=0, a host-speed flake
        deadline = time.time() + 5
        while not rc.connected and time.time() < deadline:
            rc.get(_keys(1, seed=999))
            time.sleep(0.01)
        assert rc.connected, "could not establish the windowed conn"
        wrong = []
        errs = []
        stop = [False]

        def worker(i):
            try:
                keys = _keys(32, seed=300 + i)
                pages = _pages(keys)
                r = 0
                # run until the barrage landed (stop flag), bounded so
                # a wedged proxy can't hang the drill
                while not stop[0] and r < 4000:
                    r += 1
                    rc.put(keys, pages)
                    out, found = rc.get(keys)
                    bad = (out[found] != pages[found]).any(axis=1)
                    if bad.any():
                        wrong.append((i, int(bad.sum())))
            except Exception as e:  # noqa: BLE001 — invariant 1: no
                errs.append((i, repr(e)))  # exception escapes a page op

        ts = [__import__("threading").Thread(target=worker, args=(i,))
              for i in range(4)]
        for t in ts:
            t.start()
        # seed a deterministic fault barrage while the window is full,
        # then keep the workers running until it actually LANDED
        def _fired():
            return sum(v for k, v in px.stats.items()
                       if k.endswith("_frames")
                       and k != "forwarded_frames")

        for fault in ("duplicate", "reorder", "flip", "duplicate",
                      "truncate", "reorder", "flip"):
            time.sleep(0.05)
            px.arm(fault, 1)
        deadline = time.time() + 20
        while _fired() == 0 and time.time() < deadline \
                and any(t.is_alive() for t in ts):
            time.sleep(0.02)
        stop[0] = True
        for t in ts:
            t.join(60)
        assert not any(t.is_alive() for t in ts), "stuck waiter"
        assert not errs, errs
        assert not wrong, f"mis-delivered pages: {wrong}"
        fired = sum(v for k, v in px.stats.items()
                    if k.endswith("_frames") and k != "forwarded_frames")
        assert fired > 0, "no fault actually landed"
        rc.close()


@pytest.mark.slow
@pytest.mark.netpipe
def test_chaos_soak_long_pipelined(tmp_path):
    """Long windowed soak at doubled fault rates with two kill/restore
    cycles — the slow-tier twin of the pipelined acceptance soak."""
    rates = {k: v * 2 for k, v in RATES.items()}
    s = _soak(steps=600, seed=9, rates=rates, kill_at=(200, 420),
              tmp_path=tmp_path, pipe=True, window=8)
    assert s["wrong_bytes"] == 0
    assert s["restores"] == 2
    assert s["corrupt_detected"] > 0
    fired = sum(v for k, v in s["chaos"].items()
                if k.endswith("_frames") and k != "forwarded_frames")
    assert fired > 0


@pytest.mark.telemetry
def test_soak_leaves_attributable_trace(tmp_path):
    """ISSUE 5 satellite: the seeded soak must leave an attributable
    trace behind — every verb the client COMPLETED through the chaos has
    a server span carrying the same 32-bit trace id, verbs that died
    with the connection are recorded as failed spans (with the error
    class), and the wire rung (`bad_frame`) counted the CRC/desync drops
    the server actually saw."""
    from pmdfc_tpu.config import TelemetryConfig
    from pmdfc_tpu.runtime import telemetry as tele

    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15))
    try:
        s = _soak(steps=120, seed=5, rates=RATES, kill_at=(),
                  tmp_path=tmp_path, pipe=True)
        assert s["wrong_bytes"] == 0
        spans = [r for r in reg.ring if r.get("kind") == "span"]
        client = [r for r in spans if r["src"] == "client"]
        server_traces = {r["trace"] for r in spans
                         if r["src"] == "server"}
        completed = [r for r in client if r["ok"]]
        failed = [r for r in client if not r["ok"]]
        assert len(completed) >= 10, "soak barely completed any verbs"
        missing = [r for r in completed
                   if r["trace"] not in server_traces]
        assert not missing, \
            f"{len(missing)} completed verbs lack a server span"
        # the seeded schedule really dropped connections: those verbs
        # are failed spans naming the failure, not silent gaps
        assert s["client"]["disconnects"] > 0
        assert failed and all(r.get("err") for r in failed)
        # server-side CRC/desync drops are rung-counted with the conn
        if s["chaos"]["flipped_frames"] > 0:
            assert reg._rungs["bad_frame"] > 0
    finally:
        tele.configure()


@pytest.mark.slow
@pytest.mark.containment
def test_reconnect_storm_after_phase_failures_is_backoff_bounded():
    """PR 18 satellite: a server whose EVERY phase fails against an
    un-negotiated client keeps the rung-3 conn-drop contract — and once
    the server goes away entirely, the client's reconnect attempts are
    spaced by exponential backoff, NOT a tight livelock loop: hundreds
    of degraded ops in the dead window cost only a handful of dial
    attempts. No exception ever escapes a page op."""
    import pytest as _pytest

    from pmdfc_tpu.config import NetConfig
    from pmdfc_tpu.runtime.failure import FaultPlan, FaultyBackend

    monkey = _pytest.MonkeyPatch()
    monkey.setenv("PMDFC_CONTAINMENT", "off")  # rung-3 semantics
    try:
        plan = FaultPlan()
        shared = FaultyBackend(
            DirectBackend(KV(CFG)), plan)
        srv = NetServer(lambda: shared,
                        net=NetConfig(flush_timeout_us=20_000,
                                      settle_us=2_000)).start()
        keys = _keys(8, seed=31)
        plan.poison_keys(keys)

        def factory():
            return TcpBackend("127.0.0.1", srv.port, page_words=W,
                              keepalive_s=None, op_timeout_s=5.0)

        rc = ReconnectingClient(factory, page_words=W,
                                retry_delay_s=0.02,
                                max_retry_delay_s=0.3, backoff=2.0,
                                seed=31)
        # phase-failure storm: every op kills the conn (old contract);
        # the client degrades each op to a legal miss/drop and redials
        for _ in range(6):
            _, found = rc.get(keys)
            assert not found.any()
            deadline = time.time() + 5
            while not rc.connected and time.time() < deadline:
                rc.get(keys[:1])
                time.sleep(0.01)
        s = rc.stats()
        assert s["disconnects"] >= 3, s
        # dead-server window: hammer ops far faster than the backoff
        # schedule permits dial attempts — bounded, not a livelock
        srv.stop()
        rc.get(keys)  # burn the attached (now dead) backend
        backoffs0 = rc.stats()["reconnect_backoffs"]
        t_end = time.monotonic() + 0.7
        ops = 0
        while time.monotonic() < t_end:
            _, found = rc.get(keys)
            assert not found.any()
            ops += 1
        attempts = rc.stats()["reconnect_backoffs"] - backoffs0
        assert ops > 50, f"degraded ops were not cheap ({ops})"
        # 0.02 + 0.04 + 0.08 + 0.16 + 0.3 + ... -> <= ~8 dials in 0.7 s
        # even before jitter; a livelock would dial once per op
        assert attempts <= 10, \
            f"{attempts} dial attempts in 0.7s ({ops} ops) — livelock"
        assert attempts >= 2, "backoff never even attempted a redial"
        rc.close()
    finally:
        monkey.undo()


@pytest.mark.slow
@pytest.mark.containment
def test_nacked_ops_close_spans_as_failed_v2_records():
    """PR 18 satellite: an op answered with `MSG_NACK` closes its spans
    as FAILED v2 records on BOTH sides — the server flush span and the
    client op span carry `ok=False` with the cause-bearing
    `err="nack:<cause>"` — so a NACKed op is attributable in the flight
    recorder, never a silent gap."""
    from pmdfc_tpu.config import NetConfig, TelemetryConfig
    from pmdfc_tpu.runtime import telemetry as tele
    from pmdfc_tpu.runtime.failure import FaultPlan, FaultyBackend

    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15))
    try:
        plan = FaultPlan()
        shared = FaultyBackend(DirectBackend(KV(CFG)), plan)
        srv = NetServer(lambda: shared,
                        net=NetConfig(flush_timeout_us=20_000,
                                      settle_us=2_000)).start()
        keys = _keys(8, seed=33)
        with srv, TcpBackend("127.0.0.1", srv.port, page_words=W,
                             keepalive_s=None) as be:
            assert be.nack
            # warm the GET program off the poison path (first-compile
            # stalls must not blur the assertion window)
            be.get(_keys(4, seed=34))
            plan.poison_keys(keys)
            _, found = be.get(keys)  # isolated -> NACK_POISON
            assert not found.any()
        nacked = [r for r in reg.ring
                  if r.get("kind") == "span" and not r.get("ok", True)
                  and str(r.get("err", "")).startswith("nack:")]
        assert nacked, "no FAILED span carries the nack cause"
        srcs = {r["src"] for r in nacked}
        assert "client" in srcs, f"client span missing ({srcs})"
        assert "server" in srcs, f"server span missing ({srcs})"
        # v2 shape: tree fields + flat fields on the same record
        full = [r for r in nacked if "span" in r and "trace" in r]
        assert full, "nack spans lack v2 span/trace fields"
    finally:
        tele.configure()
