"""Native engine + driver loop tests: the transport-storm tier.

Mirrors the reference's `rdma_testing.ko` storms (`client/rdpma_page_test.c`):
known-content single put/get smoke, then multi-threaded put/get storms with
content verification — against the in-process engine instead of a NIC (the
reference's own dram-backend move).
"""

import threading

import numpy as np
import pytest

from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.runtime import Engine, KVServer, OP_DEL, OP_GET, OP_PUT


def small_server(paged=True):
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 12),
        bloom=None,
        paged=paged,
        page_words=16,
    )
    eng = Engine(num_queues=4, queue_cap=1 << 12, batch=1 << 10,
                 timeout_us=200, arena_pages=1 << 10, page_bytes=64)
    return KVServer(cfg, engine=eng)


def test_engine_mpmc_roundtrip_no_server():
    eng = Engine(num_queues=2, queue_cap=1 << 8, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64)
    ids = [eng.submit(i % 2, OP_PUT, 1, i, i % 16) for i in range(100)]
    got = 0
    seen = set()
    while got < 100:
        reqs = eng.pop_batch(64, timeout_us=1000)
        got += len(reqs)
        seen.update(int(r) for r in reqs["req_id"])
        eng.complete(reqs["req_id"], np.zeros(len(reqs), np.int32))
    assert seen == set(ids)
    for rid in ids:
        assert eng.wait(rid) == 0
    s = eng.stats()
    assert s["submitted"] == 100 and s["completed"] == 100
    eng.close()


def test_single_put_get_known_content():
    # "hi, dicl" smoke (ref client/rdpma_page_test.c:65-87)
    with small_server() as srv:
        page = np.zeros(16, np.uint32)
        page[:3] = [0x68692C20, 0x6469636C, 0x21]  # "hi, dicl!"
        srv.engine.arena[3] = page
        rid = srv.engine.submit(0, OP_PUT, 7, 1234, 3)
        assert srv.engine.wait(rid) == 0
        rid = srv.engine.submit(1, OP_GET, 7, 1234, 5)
        assert srv.engine.wait(rid) == 0
        np.testing.assert_array_equal(srv.engine.arena[5], page)
        # miss is legal and reported
        rid = srv.engine.submit(0, OP_GET, 7, 9999, 6)
        assert srv.engine.wait(rid) == -1
        # delete then miss
        rid = srv.engine.submit(0, OP_DEL, 7, 1234, 0)
        assert srv.engine.wait(rid) == 0
        rid = srv.engine.submit(0, OP_GET, 7, 1234, 6)
        assert srv.engine.wait(rid) == -1


def test_threaded_storm_with_content_verification():
    # 4 writer/reader threads x 200 pages (ref rdpma_page_test.c kthread
    # storms, scaled to CI)
    with small_server() as srv:
        nthreads, per = 4, 200
        errors = []

        def worker(t):
            try:
                rng = np.random.default_rng(t)
                # each thread owns arena slots [t*2, t*2+1] for staging
                stage, dst = t * 2, t * 2 + 1
                for i in range(per):
                    key = (t << 16) | i
                    page = rng.integers(0, 2**32, 16, dtype=np.uint32)
                    srv.engine.arena[stage] = page
                    rid = srv.engine.submit(t, OP_PUT, 1, key, stage)
                    assert srv.engine.wait(rid) == 0
                    rid = srv.engine.submit(t, OP_GET, 1, key, dst)
                    st = srv.engine.wait(rid)
                    # miss only legal if evicted — capacity 4096 >> 800
                    assert st == 0, f"t{t} i{i} unexpected miss"
                    got = srv.engine.arena[dst].copy()
                    assert (got == page).all(), f"t{t} i{i} content mismatch"
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(nthreads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not errors, errors[:1]
        s = srv.engine.stats()
        assert s["submitted"] == nthreads * per * 2
        assert s["completed"] == s["submitted"]
        assert s["batches"] >= 1


def test_unpaged_u64_values_mode():
    with small_server(paged=False) as srv:
        rid = srv.engine.submit(0, OP_PUT, 2, 77, 4242)  # value rides page_off
        assert srv.engine.wait(rid) == 0
        # unpaged get returns status only (value check via kv directly)
        rid = srv.engine.submit(0, OP_GET, 2, 77, 0)
        assert srv.engine.wait(rid) == 0
        out, found = srv.kv.get(np.array([[2, 77]], np.uint32))
        assert found.all() and out[0, 1] == 4242
