"""Native engine + driver loop tests: the transport-storm tier.

Mirrors the reference's `rdma_testing.ko` storms (`client/rdpma_page_test.c`):
known-content single put/get smoke, then multi-threaded put/get storms with
content verification — against the in-process engine instead of a NIC (the
reference's own dram-backend move).
"""

import threading
import time

import numpy as np
import pytest

from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.runtime import Engine, KVServer, OP_DEL, OP_GET, OP_PUT


def small_server(paged=True):
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 12),
        bloom=None,
        paged=paged,
        page_words=16,
    )
    eng = Engine(num_queues=4, queue_cap=1 << 12, batch=1 << 10,
                 timeout_us=200, arena_pages=1 << 10, page_bytes=64)
    return KVServer(cfg, engine=eng)


def test_engine_mpmc_roundtrip_no_server():
    eng = Engine(num_queues=2, queue_cap=1 << 8, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64)
    ids = [eng.submit(i % 2, OP_PUT, 1, i, i % 16) for i in range(100)]
    got = 0
    seen = set()
    while got < 100:
        reqs = eng.pop_batch(64, timeout_us=1000)
        got += len(reqs)
        seen.update(int(r) for r in reqs["req_id"])
        eng.complete(reqs["req_id"], np.zeros(len(reqs), np.int32))
    assert seen == set(ids)
    for rid in ids:
        assert eng.wait(rid) == 0
    s = eng.stats()
    assert s["submitted"] == 100 and s["completed"] == 100
    eng.close()


def test_single_put_get_known_content():
    # "hi, dicl" smoke (ref client/rdpma_page_test.c:65-87)
    with small_server() as srv:
        page = np.zeros(16, np.uint32)
        page[:3] = [0x68692C20, 0x6469636C, 0x21]  # "hi, dicl!"
        srv.engine.arena[3] = page
        rid = srv.engine.submit(0, OP_PUT, 7, 1234, 3)
        assert srv.engine.wait(rid) == 0
        rid = srv.engine.submit(1, OP_GET, 7, 1234, 5)
        assert srv.engine.wait(rid) == 0
        np.testing.assert_array_equal(srv.engine.arena[5], page)
        # miss is legal and reported
        rid = srv.engine.submit(0, OP_GET, 7, 9999, 6)
        assert srv.engine.wait(rid) == -1
        # delete then miss
        rid = srv.engine.submit(0, OP_DEL, 7, 1234, 0)
        assert srv.engine.wait(rid) == 0
        rid = srv.engine.submit(0, OP_GET, 7, 1234, 6)
        assert srv.engine.wait(rid) == -1


def test_threaded_storm_with_content_verification():
    # 4 writer/reader threads x 200 pages (ref rdpma_page_test.c kthread
    # storms, scaled to CI)
    with small_server() as srv:
        nthreads, per = 4, 200
        errors = []

        def worker(t):
            try:
                rng = np.random.default_rng(t)
                # each thread owns arena slots [t*2, t*2+1] for staging
                stage, dst = t * 2, t * 2 + 1
                for i in range(per):
                    key = (t << 16) | i
                    page = rng.integers(0, 2**32, 16, dtype=np.uint32)
                    srv.engine.arena[stage] = page
                    rid = srv.engine.submit(t, OP_PUT, 1, key, stage)
                    assert srv.engine.wait(rid) == 0
                    rid = srv.engine.submit(t, OP_GET, 1, key, dst)
                    st = srv.engine.wait(rid)
                    # miss only legal if evicted — capacity 4096 >> 800
                    assert st == 0, f"t{t} i{i} unexpected miss"
                    got = srv.engine.arena[dst].copy()
                    assert (got == page).all(), f"t{t} i{i} content mismatch"
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(nthreads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not errors, errors[:1]
        s = srv.engine.stats()
        assert s["submitted"] == nthreads * per * 2
        assert s["completed"] == s["submitted"]
        assert s["batches"] >= 1


def test_submit_batch_wait_many_roundtrip():
    # the 4-pages-per-verb discipline (ref client/rdpma.c:307-320), deep
    with small_server() as srv:
        n = 256
        keys = np.stack(
            [np.full(n, 9, np.uint32), np.arange(n, dtype=np.uint32)], -1
        )
        slots = np.arange(n, dtype=np.uint32) % srv.engine.arena_pages
        pages = np.random.default_rng(0).integers(
            0, 2**32, (n, 16), dtype=np.uint32
        )
        srv.engine.arena[slots] = pages
        base = srv.engine.submit_batch(0, OP_PUT, keys, slots)
        st = srv.engine.wait_many(base, n)
        assert (st == 0).all()
        base = srv.engine.submit_batch(1, OP_GET, keys, slots)
        st = srv.engine.wait_many(base, n)
        assert (st == 0).all()
        np.testing.assert_array_equal(srv.engine.arena[slots], pages)


def test_queue_full_backpressure_without_driver():
    # No driver thread: the queue must fill, submit_batch must time out with
    # an exact partial count, and the submitted prefix must still complete
    # once a driver appears (ref: client send-queue block relies on the NIC
    # draining; an in-process driver cannot promise that, so timeout).
    eng = Engine(num_queues=1, queue_cap=1 << 8, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64)
    n = (1 << 8) + 50
    keys = np.stack(
        [np.zeros(n, np.uint32), np.arange(n, dtype=np.uint32)], -1
    )
    with pytest.raises(TimeoutError, match=r"256/306"):
        eng.submit_batch(0, OP_PUT, keys, timeout_us=50_000)
    # drain manually: exactly qcap requests are live
    got = 0
    while True:
        reqs = eng.pop_batch(64, timeout_us=10_000)
        if len(reqs) == 0:
            break
        eng.complete(reqs["req_id"], np.zeros(len(reqs), np.int32))
        got += len(reqs)
    assert got == 1 << 8
    eng.close()


def test_completion_slot_wraparound():
    # Push ids far past the completion-table capacity; every waiter must
    # still observe its own completion (slot reuse is keyed by req_id).
    eng = Engine(num_queues=1, queue_cap=1 << 8, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64)
    rounds = 40  # 40 * 256 ids >> comp_cap
    for r in range(rounds):
        n = 1 << 8
        keys = np.stack(
            [np.full(n, r, np.uint32), np.arange(n, dtype=np.uint32)], -1
        )
        base = eng.submit_batch(0, OP_PUT, keys)
        done = 0
        while done < n:
            reqs = eng.pop_batch(64, timeout_us=10_000)
            eng.complete(reqs["req_id"],
                         (reqs["klo"] % 7).astype(np.int32))
            done += len(reqs)
        st = eng.wait_many(base, n)
        np.testing.assert_array_equal(st, np.arange(n) % 7)
    s = eng.stats()
    assert s["submitted"] == s["completed"] == rounds * 256
    eng.close()


def test_deep_pipelined_client_needs_comp_slots():
    """Round-4 sweep regression: ids are live from allocation until the
    WAITER reads the slot, so a pipelined client (submit many verbs, wait
    later) keeps more ids outstanding than the queue/batch-derived legacy
    completion-table bound. comp_slots sized to the outstanding population
    must make every deferred wait succeed."""
    nverbs, vb = 16, 64
    eng = Engine(num_queues=1, queue_cap=1 << 10, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64,
                 comp_slots=4 * nverbs * vb)
    import threading

    stop = threading.Event()

    def driver():
        while not stop.is_set():
            reqs = eng.pop_batch(64, timeout_us=5_000)
            if len(reqs):
                eng.complete(reqs["req_id"],
                             (reqs["klo"] % 5).astype(np.int32))

    th = threading.Thread(target=driver, daemon=True)
    th.start()
    try:
        pending = []
        for v in range(nverbs):  # all submits BEFORE any wait
            keys = np.stack([np.full(vb, v, np.uint32),
                             np.arange(vb, dtype=np.uint32)], -1)
            pending.append(eng.submit_batch(0, OP_PUT, keys))
        for base in pending:
            st = eng.wait_many(base, vb, timeout_us=5_000_000)
            np.testing.assert_array_equal(st, np.arange(vb) % 5)
    finally:
        stop.set()
        th.join(timeout=5)
        eng.close()


def test_deep_pipelined_client_wedges_without_comp_slots():
    """The failure mode the fix closes, pinned: with the LEGACY table
    sizing, a deferred waiter whose slot a newer id overwrote never
    completes (this is what 'completed 0/32768 before timeout' was)."""
    nverbs, vb = 16, 64
    # legacy comp_cap = (qcap*nq + batch)*2 = (64 + 64)*2 = 256 << 1024 ids
    eng = Engine(num_queues=1, queue_cap=64, batch=64, timeout_us=100,
                 arena_pages=16, page_bytes=64)
    import threading

    stop = threading.Event()

    def driver():
        while not stop.is_set():
            reqs = eng.pop_batch(64, timeout_us=5_000)
            if len(reqs):
                eng.complete(reqs["req_id"], np.zeros(len(reqs), np.int32))

    th = threading.Thread(target=driver, daemon=True)
    th.start()
    try:
        pending = []
        for v in range(nverbs):
            keys = np.stack([np.full(vb, v, np.uint32),
                             np.arange(vb, dtype=np.uint32)], -1)
            pending.append(eng.submit_batch(0, OP_PUT, keys,
                                            timeout_us=2_000_000))
        # wait for the LAST verb first so the driver provably finished
        # everything, then check verb 0: its slots were overwritten
        eng.wait_many(pending[-1], vb, timeout_us=5_000_000)
        with pytest.raises(TimeoutError):
            eng.wait_many(pending[0], vb, timeout_us=50_000)
    finally:
        stop.set()
        th.join(timeout=5)
        eng.close()


def _storm_server(capacity_bits=21, page_words=16, arena_pages=1 << 14):
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << capacity_bits),
        bloom=None, paged=True, page_words=page_words,
    )
    eng = Engine(num_queues=8, queue_cap=1 << 14, batch=1 << 13,
                 timeout_us=300, arena_pages=arena_pages,
                 page_bytes=page_words * 4)
    return KVServer(cfg, engine=eng)


def _fill(khi: np.ndarray, klo: np.ndarray, words: int) -> np.ndarray:
    """Deterministic content so storms verify without storing pages."""
    base = (khi * np.uint32(2654435761) + klo * np.uint32(40503))
    return base[:, None] + np.arange(words, dtype=np.uint32)[None, :]


@pytest.mark.slow
def test_reference_grade_storm():
    """4 writer/reader threads x 250k pages, content-verified (ref
    client/rdpma_page_test.c:116-180 kthread storm, sized for CI; set
    PMDFC_STORM_PER for the full 4 x 1M)."""
    import os

    per = int(os.environ.get("PMDFC_STORM_PER", 250_000))
    nthreads, cb = 4, 2048  # client batch per verb burst
    with _storm_server() as srv:
        errors = []
        verified = np.zeros(nthreads, np.int64)
        misses = np.zeros(nthreads, np.int64)

        def worker(t):
            try:
                backend_slots = np.arange(t * cb, (t + 1) * cb,
                                          dtype=np.uint32)
                for lo in range(0, per, cb):
                    n = min(cb, per - lo)
                    slots = backend_slots[:n]
                    khi = np.full(n, t + 1, np.uint32)
                    klo = np.arange(lo, lo + n, dtype=np.uint32)
                    keys = np.stack([khi, klo], -1)
                    pages = _fill(khi, klo, srv.engine.page_words)
                    srv.engine.arena[slots] = pages
                    base = srv.engine.submit_batch(t, OP_PUT, keys, slots,
                                                   timeout_us=60_000_000)
                    srv.engine.wait_many(base, n, timeout_us=60_000_000)
                    # read back immediately (hot window: eviction unlikely
                    # but legal — verify content only on hits)
                    base = srv.engine.submit_batch(
                        (t + 4) % 8, OP_GET, keys, slots,
                        timeout_us=60_000_000)
                    st = srv.engine.wait_many(base, n,
                                              timeout_us=60_000_000)
                    hit = st == 0
                    got = srv.engine.arena[slots[hit]]
                    exp = pages[hit]
                    if not (got == exp).all():
                        raise AssertionError(
                            f"t{t} block@{lo}: content mismatch"
                        )
                    verified[t] += int(hit.sum())
                    misses[t] += int((~hit).sum())
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(nthreads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1200)
        assert not errors, errors[:1]
        total = nthreads * per
        s = srv.engine.stats()
        assert s["submitted"] == total * 2
        assert s["completed"] == s["submitted"]
        # clean-cache: every miss must be accounted for by an eviction/drop
        kvs = srv.kv.stats()
        assert misses.sum() <= kvs["evictions"] + kvs["drops"]
        assert verified.sum() >= total * 0.5  # capacity >> working set


def test_extent_verbs_through_transport_storm():
    """Extent verbs cross the engine transport (round 4, VERDICT-r3 item
    8): concurrent clients register page RANGES (insert_extent) and
    resolve keys through covers (get_extent) while page traffic flows,
    all through the coalescing engine into one KVServer. Verifies the
    reference's address arithmetic end to end: resolved value =
    record.value + (key - base) * 4096 (`KV.cpp:170-173`)."""
    import threading

    from pmdfc_tpu.client import EngineBackend
    from pmdfc_tpu.config import IndexConfig, KVConfig

    # 8 rounds x 4 threads keeps every interleaving the test pins
    # (same-flush ins_ext->get_ext, cross-thread disjoint runs, page
    # traffic between extent verbs) while fitting the fast-tier budget;
    # the 10-minute soak covers sustained-volume extent traffic.
    nthreads, rounds, elen = 4, 8, 48
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 14), bloom=None, paged=True,
        page_words=16, extent_capacity=256, extent_max_covers=16,
    )
    eng = Engine(num_queues=8, queue_cap=1 << 12, batch=1 << 11,
                 timeout_us=300, arena_pages=1 << 12, page_bytes=64)
    with KVServer(cfg, engine=eng) as srv:
        bes = [EngineBackend(srv, queue=t, timeout_us=60_000_000)
               for t in range(nthreads)]
        errors: list[BaseException] = []

        def worker(t):
            try:
                be = bes[t]
                khi = np.uint32(100 + t)
                for j in range(rounds):
                    base = np.uint32(j * 256)  # aligned, disjoint runs
                    vhi, vlo = np.uint32(t), np.uint32(j << 20)
                    uncovered = be.insert_extent(
                        [khi, base], [vhi, vlo], elen)
                    assert uncovered == 0, uncovered
                    # interleave page traffic on the same transport
                    pk = np.stack([np.full(32, 1000 + t, np.uint32),
                                   np.arange(j * 32, j * 32 + 32,
                                             dtype=np.uint32)], -1)
                    be.put(pk, _fill(pk[:, 0], pk[:, 1], 16))
                    # resolve: in-extent probes hit with exact arithmetic,
                    # the probe one past the end misses
                    ds = np.array([0, 1, elen // 2, elen - 1, elen],
                                  np.uint32)
                    probe = np.stack(
                        [np.full(len(ds), khi), base + ds], -1)
                    vals, found = be.get_extent(probe)
                    assert found.tolist() == [True] * 4 + [False]
                    exp_lo = vlo + ds[:4] * np.uint32(4096)
                    np.testing.assert_array_equal(vals[:4, 1], exp_lo)
                    np.testing.assert_array_equal(
                        vals[:4, 0], np.full(4, vhi))
                    out, pfound = be.get(pk)
                    assert pfound.all()
                    np.testing.assert_array_equal(
                        out, _fill(pk[:, 0], pk[:, 1], 16))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(nthreads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for be in bes:
            be.close()
        assert not errors, errors[0]
        s = srv.kv.stats()
        assert s["extent_puts"] == nthreads * rounds, s


def test_multi_client_arena_isolation():
    # Two default-constructed clients on one engine must get disjoint
    # staging slices and never clobber each other (ADVICE round-1 finding).
    from pmdfc_tpu.client import EngineBackend

    with small_server() as srv:
        b1 = EngineBackend(srv, queue=0)
        b2 = EngineBackend(srv, queue=1)
        assert b1.arena_hi <= b2.arena_lo or b2.arena_hi <= b1.arena_lo
        errors = []

        def client(b, tag):
            try:
                rng = np.random.default_rng(tag)
                for i in range(30):
                    n = 64
                    keys = np.stack(
                        [np.full(n, tag, np.uint32),
                         np.arange(i * n, (i + 1) * n, dtype=np.uint32)], -1
                    )
                    pages = rng.integers(0, 2**32, (n, 16), dtype=np.uint32)
                    b.put(keys, pages)
                    out, found = b.get(keys)
                    assert found.all(), f"client{tag} round {i} miss"
                    assert (out == pages).all(), f"client{tag} clobbered"
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=client, args=(b, t))
                   for t, b in ((100, b1), (200, b2))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
        assert not errors, errors[:1]


def test_unpaged_u64_values_mode():
    with small_server(paged=False) as srv:
        rid = srv.engine.submit(0, OP_PUT, 2, 77, 4242)  # value rides page_off
        assert srv.engine.wait(rid) == 0
        # unpaged get returns status only (value check via kv directly)
        rid = srv.engine.submit(0, OP_GET, 2, 77, 0)
        assert srv.engine.wait(rid) == 0
        out, found = srv.kv.get(np.array([[2, 77]], np.uint32))
        assert found.all() and out[0, 1] == 4242


def test_double_start_is_idempotent():
    """`with KVServer(...).start()` calls start() twice (__enter__ starts
    too). Two driver loops racing one KV silently LOSE inserts (the state
    read-modify-write has a lost-update window) and leak a stray thread
    onto a freed engine — one server must only ever have one driver."""
    import threading

    from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
    from pmdfc_tpu.runtime.server import KVServer

    cfg = KVConfig(index=IndexConfig(capacity=1 << 12),
                   bloom=BloomConfig(num_bits=1 << 13),
                   paged=True, page_words=16)
    eng = Engine(num_queues=2, queue_cap=1 << 10, batch=256, timeout_us=200,
                 arena_pages=512, page_bytes=64)
    # snapshot pre-existing drivers: another test may legitimately have
    # leaked a wedged one (stop() documents that), and suites run shared
    pre = {t for t in threading.enumerate() if t.name == "pmdfc-driver"}
    with KVServer(cfg, engine=eng).start() as srv:  # the double-start shape
        drivers = [t for t in threading.enumerate()
                   if t.name == "pmdfc-driver" and t not in pre]
        assert len(drivers) == 1, f"{len(drivers)} driver loops running"
        assert srv._thread in drivers
        # and the data path is sound under the eager pop split: singleton
        # first batches must not lose their inserts
        from pmdfc_tpu.client.backends import EngineBackend

        be = EngineBackend(srv)
        rng = np.random.default_rng(41)
        flat = rng.choice(1 << 22, size=32, replace=False)
        keys = np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)
        pages = (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
            16, dtype=np.uint32
        )
        results = []
        def work():
            be.put(keys, pages)
            results.append(be.get(keys)[1])
        t = threading.Thread(target=work)
        t.start()
        t.join()
        assert results and results[0].all(), "insert lost"
        be.close()
    assert not [t for t in threading.enumerate()
                if t.name == "pmdfc-driver" and t not in pre], \
        "stray driver survived stop()"


def test_engine_destroy_under_client_fire():
    """Tearing the engine down while client threads are mid-submit/wait must
    degrade to failure codes, never touch freed memory (the heap-corruption
    class behind the round-2 native segfaults: the failure drills kill
    servers under load by design)."""
    from pmdfc_tpu.runtime.engine import Engine, OP_GET

    for round_ in range(6):
        eng = Engine(num_queues=2, queue_cap=1 << 8, batch=64,
                     timeout_us=100, arena_pages=8, page_bytes=64)
        stop = threading.Event()
        errors = []

        def fire(t):
            rng = np.random.default_rng(t)
            keys = rng.integers(0, 2**32, (16, 2), dtype=np.uint64
                                ).astype(np.uint32)
            while not stop.is_set():
                try:
                    base = eng.submit_batch(t % 2, OP_GET, keys,
                                            timeout_us=1000)
                    eng.wait_many(base, len(keys), timeout_us=1000)
                except (TimeoutError, RuntimeError):
                    # engine closing/closed: failure is the legal outcome
                    if eng._h is None:
                        return
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=fire, args=(t,)) for t in range(4)]
        for th in threads:
            th.start()
        time.sleep(0.05)  # let the storm reach steady state
        eng.close()       # yank the engine out from under them
        stop.set()
        for th in threads:
            th.join(timeout=10)
        assert not errors, errors[:1]
        assert all(not th.is_alive() for th in threads)


def test_single_flush_put_delete_get_ordering():
    """Within ONE coalesced flush, puts land before deletes before gets —
    the guarantee that replaces the reference client's synchronous
    per-queue verbs. Submit all three op kinds for overlapping keys
    BEFORE the driver can flush (long timeout, deep batch) and check the
    serialized outcome."""
    cfg = KVConfig(index=IndexConfig(capacity=1 << 10), bloom=None,
                   paged=True, page_words=16)
    eng = Engine(num_queues=4, queue_cap=1 << 8, batch=256,
                 timeout_us=200_000, arena_pages=64, page_bytes=64)
    srv = KVServer(cfg, engine=eng)  # driver NOT started yet
    ka = (1, 10)   # put then deleted  -> miss
    kb = (1, 11)   # put only          -> hit
    pa = np.full(16, 0xAAAAAAAA, np.uint32)
    pb = np.full(16, 0xBBBBBBBB, np.uint32)
    eng.arena[0] = pa
    eng.arena[1] = pb
    ids = []
    ids.append(("put_a", eng.submit(0, OP_PUT, *ka, 0)))
    ids.append(("put_b", eng.submit(1, OP_PUT, *kb, 1)))
    ids.append(("del_a", eng.submit(2, OP_DEL, *ka, 0)))
    # gets into fresh slots; same flush as the puts and the delete
    ids.append(("get_a", eng.submit(3, OP_GET, *ka, 2)))
    ids.append(("get_b", eng.submit(0, OP_GET, *kb, 3)))
    srv.start()
    try:
        st = {name: eng.wait(rid, timeout_us=30_000_000)
              for name, rid in ids}
        assert st["put_a"] == 0 and st["put_b"] == 0
        assert st["del_a"] == 0, "delete must observe the same-flush put"
        assert st["get_a"] == -1, "get must observe the same-flush delete"
        assert st["get_b"] == 0
        np.testing.assert_array_equal(eng.arena[3], pb)
    finally:
        srv.stop()
