"""The jax pin lock (VERDICT r5 §7): `bench/common.enable_compile_cache`
monkeypatches `jax._src` internals, so bench runs must FAIL LOUDLY on a
jax/jaxlib version the hardening was never verified against — a bench
row produced with unverified (or silently disabled) cache hardening is
not evidence. Tests keep the non-strict degrade path (a version drift
must not zero out the collected suite)."""

import pytest

from pmdfc_tpu.bench import common


def test_strict_pin_rejects_unverified_version(monkeypatch):
    """strict=True + a (jax, jaxlib) pair outside the hand-verified set
    ⇒ RuntimeError naming the pin, BEFORE any config mutation."""
    monkeypatch.delenv("PMDFC_JAX_PIN", raising=False)
    monkeypatch.delenv("PMDFC_COMPILE_CACHE", raising=False)
    monkeypatch.setattr(common, "jax_versions",
                        lambda: ("99.0.0", "99.0.0"))
    with pytest.raises(RuntimeError, match="_VALIDATED_JAX"):
        common.enable_compile_cache(strict=True)


def test_strict_pin_escape_hatch_degrades(monkeypatch):
    """PMDFC_JAX_PIN=loose: the operator accepted the risk — the strict
    path degrades like the test path (no raise)."""
    monkeypatch.setenv("PMDFC_JAX_PIN", "loose")
    monkeypatch.setattr(common, "jax_versions",
                        lambda: ("99.0.0", "99.0.0"))
    common.enable_compile_cache(strict=True)  # must not raise


def test_container_versions_pass_strict():
    """The container this suite runs on is in the verified set (or the
    pin file needs updating alongside the image)."""
    if common.jax_versions() not in common._VALIDATED_JAX:
        pytest.skip("container jax not in the verified set — strict "
                    "bench runs here are expected to refuse")
    common.enable_compile_cache(strict=True)  # must not raise


def test_validated_pins_are_exact_versions():
    """The validated set records EXACT versions, not prefixes — the
    whole point of the lock (a prefix silently blesses future patch
    releases whose internals were never re-verified)."""
    for jv, jlv in common._VALIDATED_JAX:
        assert jv.count(".") >= 2 and jlv.count(".") >= 2, (jv, jlv)
