"""One-sided client fast path — directory mirror + direct validated row
reads (ISSUE 11).

The contract under test, at every layer:

- a fast read answers ONLY while the row's current at-rest digest still
  equals the directory entry's (and the directory epoch matches) — a
  recycled/re-written row, a ballooned pool, or a resharded mesh can
  degrade a fast read to the verb path (`fastpath_stale`) but can never
  serve wrong bytes;
- every fast lane is exactly one of hit/stale; server reads are
  DERIVED as `hits + stale` (never stored, so the sum cannot drift
  mid-pull) and the client cache's own counters agree lane for lane;
- `PMDFC_FASTPATH=off` is verb-for-verb the pre-fast-path protocol.
"""

import threading
import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, LocalBackend
from pmdfc_tpu.client.cleancache import CleanCacheClient
from pmdfc_tpu.config import (
    BloomConfig, IndexConfig, KVConfig, NetConfig, TierConfig)
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.net import NetServer, TcpBackend

pytestmark = pytest.mark.fastpath

W = 16  # tiny pages keep socket traffic fast


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
        W, dtype=np.uint32)


def _cfg(capacity=1 << 10, tier=None):
    return KVConfig(index=IndexConfig(capacity=capacity),
                    bloom=BloomConfig(num_bits=1 << 13),
                    paged=True, page_words=W, tier=tier)


def _server(kv=None, coalesce=True, **kw):
    kv = kv or KV(_cfg())
    shared = DirectBackend(kv)
    net = NetConfig(flush_timeout_us=500, settle_us=50) if coalesce \
        else None
    return NetServer(lambda: shared, net=net, **kw).start(), kv


def _dial(srv, **kw):
    kw.setdefault("keepalive_s", None)
    return TcpBackend("127.0.0.1", srv.port, page_words=W, **kw)


def _fp_counters(srv):
    # reads are DERIVED server-side (hits + stale are the only stored
    # lanes — a third counter raced them under live stats pulls)
    s = srv.stats
    h, st = int(s["fastpath_hits"]), int(s["fastpath_stale"])
    return (h + st, h, st)


# ---------------------------------------------------------------------------
# KV-level surface
# ---------------------------------------------------------------------------


def test_directory_snapshot_matches_live_state():
    kv = KV(_cfg())
    keys = _keys(128, seed=3)
    pages = _pages(keys)
    kv.insert(keys, pages)
    snap = kv.directory_snapshot()
    assert snap is not None and len(snap["keys"]) > 0
    fv = kv.fast_view()
    # every directory entry validates against the live mirror and
    # gathers exactly the bytes the verb path serves
    ok = fv.validate(snap["epoch"], snap["shards"], snap["rows"],
                     snap["digs"])
    assert ok.all()
    got = fv.gather(snap["shards"], snap["rows"])
    want, found = kv.get(snap["keys"])
    assert found.all()
    assert np.array_equal(got, want)


def test_epoch_bumps_on_structural_invalidation():
    kv = KV(_cfg())
    keys = _keys(32, seed=4)
    kv.insert(keys, _pages(keys))
    e0 = kv.dir_epoch
    kv.insert(keys[:4], _pages(keys[:4]))   # puts never bump the epoch
    assert kv.dir_epoch == e0
    kv.delete(keys[:2])                     # invalidation does
    assert kv.dir_epoch == e0 + 1
    # a stale-epoch read fails every lane even for untouched rows
    snap_epoch = e0
    fv = kv.fast_view()
    assert not fv.validate(snap_epoch, np.zeros(1, np.uint32),
                           np.zeros(1, np.uint32),
                           np.zeros(1, np.uint32)).any()


def test_unpaged_config_has_no_fast_surface():
    kv = KV(KVConfig(index=IndexConfig(capacity=256), paged=False,
                     bloom=None, page_words=W))
    assert kv.fast_view() is None
    assert kv.directory_snapshot() is None
    srv = NetServer(lambda: DirectBackend(kv),
                    net=NetConfig(flush_timeout_us=200)).start()
    with srv:
        be = _dial(srv, directory=True)
        # capability requested but the backend cannot serve it -> no ack
        assert not be.fastpath and be.directory is None
        be.close()


# ---------------------------------------------------------------------------
# wire fast path
# ---------------------------------------------------------------------------


def test_fastread_end_to_end_bit_identical():
    srv, kv = _server()
    with srv:
        keys = _keys(96, seed=7)
        pages = _pages(keys)
        plain = _dial(srv)
        plain.put(keys, pages)
        fast = _dial(srv, directory=True)
        assert fast.fastpath and fast.directory is not None
        assert fast.dir_refresh()
        out_f, found_f = fast.get(keys)
        out_v, found_v = plain.get(keys)
        assert np.array_equal(found_f, found_v)
        assert np.array_equal(out_f, out_v)
        reads, hits, stale = _fp_counters(srv)
        assert reads == hits == len(keys) and stale == 0
        assert int(srv.stats["dir_pulls"]) == 1
        # the exactness pin: client cache and server scope agree lane
        # for lane
        c = fast.directory.counters
        assert (c["fastpath_gets"], c["fastpath_hits"],
                c["fastpath_stale"]) == (reads, hits, stale)
        fast.close()
        plain.close()


def test_teledump_pins_fastpath_invariant():
    from tools.check_teledump import check, check_fastpath

    srv, _ = _server()
    with srv:
        keys = _keys(32, seed=8)
        fast = _dial(srv, directory=True)
        fast.put(keys, _pages(keys))
        fast.dir_refresh()
        fast.get(keys)
        doc = fast.server_stats()
        assert check(doc) == []
        # pin drills: a producer that stores a reads counter must agree
        # with the lanes; a hits lane travelling without its stale lane
        # is malformed
        snap = doc["telemetry"]
        hits_names = [n for n in snap["counters"]
                      if n.endswith(".fastpath_hits")]
        assert hits_names
        scope = hits_names[0][: -len("fastpath_hits")]
        forged = {**snap,
                  "counters": {**snap["counters"],
                               scope + "fastpath_reads":
                               snap["counters"][hits_names[0]] + 1}}
        assert any("fast-lane drift" in e for e in check_fastpath(forged))
        broken = {**snap, "counters": dict(snap["counters"])}
        broken["counters"].pop(scope + "fastpath_stale")
        assert any("without its stale lane" in e
                   for e in check_fastpath(broken))
        fast.close()


def test_reput_stales_entry_delete_bumps_epoch():
    srv, kv = _server()
    with srv:
        keys = _keys(64, seed=9)
        pages = _pages(keys)
        a = _dial(srv, directory=True)
        b = _dial(srv)
        a.put(keys, pages)
        a.dir_refresh()
        assert a.get(keys[:8])[1].all()
        r0, h0, s0 = _fp_counters(srv)
        # a re-put from ANOTHER connection changes the row digest: a's
        # cached entry must stale-fall-back and serve the NEW bytes
        new = pages[3:4] ^ np.uint32(0xABCD)
        b.put(keys[3:4], new)
        out, found = a.get(keys[3:4])
        assert found[0] and np.array_equal(out[0], new[0])
        r1, h1, s1 = _fp_counters(srv)
        assert (r1 - r0, s1 - s0) == (1, 1)
        assert a.directory.counters["fastpath_stale"] == 1
        # an invalidate from another connection bumps the epoch: the
        # next fast read fails validation, the verb path answers the
        # truth, and the client marks its mirror dirty
        e0 = kv.dir_epoch
        assert b.invalidate(keys[5:6])[0]
        assert kv.dir_epoch == e0 + 1
        out2, found2 = a.get(keys[5:7])
        assert not found2[0] and found2[1]
        assert np.array_equal(out2[1], pages[6])
        assert not a.directory.ready()
        # refresh re-arms the fast path under the new epoch
        assert a.dir_refresh() and a.directory.ready()
        out3, found3 = a.get(keys[6:7])
        assert found3[0] and np.array_equal(out3[0], pages[6])
        a.close()
        b.close()


def test_dir_delta_upserts_and_tombstones():
    srv, kv = _server()
    with srv:
        keys = _keys(48, seed=10)
        pages = _pages(keys)
        a = _dial(srv, directory=True)
        b = _dial(srv)
        a.put(keys, pages)
        a.dir_refresh()
        n0 = len(a.directory)
        assert n0 == 48
        b.invalidate(keys[:4])                 # -> tombstones
        b.put(keys[4:6], pages[4:6] ^ np.uint32(1))  # -> changed digests
        assert a.dir_refresh()                 # delta, not full
        c = a.directory.counters
        assert c["dir_refreshes"] == 2
        # the delta shipped only the moved entries (+ tombstones), not
        # the whole table again
        assert c["dir_upserts"] < n0 + 8
        assert c["dir_tombstones"] >= 4
        assert len(a.directory) == 44
        mask, *_ = a.directory.lookup(keys[:4])
        assert not mask.any()
        out, found = a.get(keys[4:6])
        assert found.all()
        assert np.array_equal(out, pages[4:6] ^ np.uint32(1))
        a.close()
        b.close()


def test_fastpath_off_is_verb_for_verb_identical(monkeypatch):
    """`PMDFC_FASTPATH=off`: a directory-requesting client against an
    off server produces the same wire transcript as a plain client —
    no capability ack, no directory, zero fast-path verbs, identical
    results and identical server op counts."""

    def run(directory: bool):
        srv, _ = _server()
        with srv:
            be = _dial(srv, directory=directory)
            keys = _keys(40, seed=11)
            pages = _pages(keys)
            be.put(keys, pages)
            if directory:
                assert not be.dir_refresh()  # no-op: no directory built
            out, found = be.get(keys)
            miss = be.get(_keys(8, seed=12))[1]
            ops = int(srv.stats["ops"])
            fp = _fp_counters(srv)
            pulls = int(srv.stats["dir_pulls"])
            neg = be.fastpath, be.directory
            be.close()
        return out, found, miss, ops, fp, pulls, neg

    monkeypatch.setenv("PMDFC_FASTPATH", "off")
    out1, found1, miss1, ops1, fp1, pulls1, neg = run(directory=True)
    assert neg == (False, None)
    assert fp1 == (0, 0, 0) and pulls1 == 0
    out2, found2, miss2, ops2, fp2, pulls2, _ = run(directory=False)
    assert ops1 == ops2
    assert np.array_equal(out1, out2) and np.array_equal(found1, found2)
    assert not miss1.any() and not miss2.any()


# ---------------------------------------------------------------------------
# structural-change drills (balloon / reshard) — the epoch ladder
# ---------------------------------------------------------------------------


def test_tier_promotion_vacates_directory_rows():
    """A free-row promotion moves a key's value to the hot tier but
    leaves the vacated cold row's pages/sums intact — after the key is
    re-put (hot row updated in place, acked), the OLD directory entry
    still carries a matching digest for the vacated row. The liveness
    lane of `FastView.validate` is the only thing standing between
    that address and a stale read; pin it."""
    from pmdfc_tpu.config import TierConfig

    kv = KV(_cfg(capacity=256, tier=TierConfig(ghost_rows=16)))
    keys = _keys(32, seed=30)
    pages = _pages(keys)
    kv.insert(keys, pages)
    snap = kv.directory_snapshot()
    assert len(snap["keys"]) == len(keys)
    # drive promotions (inserts land cold; promote_touches default 2)
    for _ in range(6):
        kv.get(keys)
    assert (kv.tier_stats() or {})["promotions"] > 0
    # overwrite EVERY key: promoted keys update their hot row in place,
    # cold keys re-digest their row — either way no old-snapshot lane
    # may validate, because any that did would gather superseded bytes
    kv.insert(keys, pages ^ np.uint32(0x5A5A))
    fv = kv.fast_view()
    ok = fv.validate(snap["epoch"], snap["shards"], snap["rows"],
                     snap["digs"])
    assert not ok.any()
    # a fresh pull serves the new bytes (hot rows are live, gen 0)
    snap2 = kv.directory_snapshot()
    fv2 = kv.fast_view()
    ok2 = fv2.validate(snap2["epoch"], snap2["shards"], snap2["rows"],
                       snap2["digs"])
    assert ok2.all()
    got = fv2.gather(snap2["shards"], snap2["rows"])
    want, found = kv.get(snap2["keys"])
    assert found.all() and np.array_equal(got, want)


def test_balloon_shrink_drill_zero_wrong_bytes():
    """Balloon shrink mid-serve: every fast lane in flight degrades to
    a legal miss or the verb path — zero wrong bytes, `fastpath_stale`
    exact on both sides of the wire."""
    kv = KV(_cfg(capacity=256,
                 tier=TierConfig(balloon_step=32, ghost_rows=16,
                                 cold_init_rows=256)))
    srv, _ = _server(kv=kv)
    with srv:
        keys = _keys(128, seed=13)
        pages = _pages(keys)
        a = _dial(srv, directory=True)
        a.put(keys, pages)
        _, landed = a.get(keys)
        keys, pages = keys[landed], pages[landed]
        a.dir_refresh()
        assert a.get(keys[:16])[1].all()
        e0 = kv.dir_epoch
        assert kv.balloon_shrink(64)
        assert kv.dir_epoch > e0
        wrong = 0
        served = misses = 0
        for lo in range(0, len(keys), 16):
            out, found = a.get(keys[lo:lo + 16])
            served += int(found.sum())
            misses += int((~found).sum())
            wrong += int((out[found] != pages[lo:lo + 16][found])
                         .any(axis=1).sum())
        assert wrong == 0       # stale lanes fell back, never lied
        assert served > 0       # the surviving rows still serve
        reads, hits, stale = _fp_counters(srv)
        c = a.directory.counters
        assert (c["fastpath_gets"], c["fastpath_hits"],
                c["fastpath_stale"]) == (reads, hits, stale)
        # post-shrink epoch is refreshable and the fast path re-arms
        assert a.dir_refresh()
        out, found = a.get(keys[:16])
        assert wrong == 0 and (out[found] == pages[:16][found]).all()
        a.close()


def test_reshard_4_to_2_drill_zero_wrong_bytes(tmp_path):
    """4→2 reshard mid-serve: the swapped-in plane carries a different
    epoch, every outstanding directory entry (4-shard owners, old rows)
    goes stale, the verb path serves the truth, and a refresh re-arms
    the fast path against the 2-shard mesh."""
    import jax

    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh

    cfg = _cfg(capacity=256)
    skv4 = ShardedKV(cfg, mesh=make_mesh(jax.devices()[:4]))
    db = DirectBackend(skv4)
    srv = NetServer(lambda: db,
                    net=NetConfig(flush_timeout_us=500, settle_us=50))
    srv.start()
    with srv:
        keys = _keys(96, seed=14)
        pages = _pages(keys)
        a = _dial(srv, directory=True)
        a.put(keys, pages)
        _, landed = a.get(keys)
        keys, pages = keys[landed], pages[landed]
        a.dir_refresh()
        assert a.get(keys[:16])[1].all()
        assert set(np.unique(
            [e[0] for e in a.directory._map.values()])) > {0}
        # snapshot the 4-shard plane, replay onto 2 shards, swap it in
        path = str(tmp_path / "skv4.ckpt")
        skv4.save(path)
        skv2 = ShardedKV(cfg, mesh=make_mesh(jax.devices()[:2]))
        skv2.restore(path)
        db.kv = skv2
        wrong = served = 0
        for lo in range(0, len(keys), 16):
            out, found = a.get(keys[lo:lo + 16])
            served += int(found.sum())
            wrong += int((out[found] != pages[lo:lo + 16][found])
                         .any(axis=1).sum())
        assert wrong == 0
        assert served == len(keys)  # loss-free replay: all still hit
        reads, hits, stale = _fp_counters(srv)
        assert stale > 0
        # refresh against the new plane: owners now live on 2 shards
        assert a.dir_refresh() and a.directory.ready()
        out, found = a.get(keys[:32])
        assert found.all() and np.array_equal(out, pages[:32])
        owners = {e[0] for e in a.directory._map.values()}
        assert owners <= {0, 1}
        a.close()


def test_chaos_fastpath_soak_no_wrong_bytes():
    """Seeded ChaosProxy between a directory client and the coalesced
    server: bitflips/kills degrade connections, never bytes. The
    CleanCacheClient miss invariant (`miss_gets == bloom_negative +
    remote`) must hold with the fast path active underneath."""
    from pmdfc_tpu.runtime.failure import ChaosProxy, ReconnectingClient

    srv, _ = _server()
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=17,
                         rates={"flip": 0.02, "truncate": 0.01},
                         delay_s=0.01, reorder_wait_s=0.02) as px:
        def factory():
            be = TcpBackend("127.0.0.1", px.port, page_words=W,
                            keepalive_s=None, op_timeout_s=1.0,
                            directory=True)
            be.dir_refresh()
            return be

        rc = ReconnectingClient(factory, page_words=W,
                                retry_delay_s=0.005, max_retry_delay_s=0.05)
        cc = CleanCacheClient(rc)
        keys = _keys(192, seed=18)
        pages = _pages(keys)
        rng = np.random.default_rng(19)
        put_ok = np.zeros(len(keys), bool)
        for step in range(30):
            lo = (step * 8) % len(keys)
            sel = slice(lo, lo + 8)
            cc.put_pages(keys[sel, 0], keys[sel, 1], pages[sel])
            put_ok[sel] = True
            idx = rng.integers(0, len(keys), 16)
            out, found = cc.get_pages(keys[idx, 0], keys[idx, 1])
            # zero wrong bytes: a found page is bit-exact, always
            assert (out[found] == pages[idx][found]).all()
            if step % 10 == 0:
                rc.dir_refresh()
        c = cc.counters
        assert c["miss_gets"] == (c["miss_bloom_negative"]
                                  + c["miss_remote"])
        cc.close()
        rc.close()


# ---------------------------------------------------------------------------
# lifecycle + stats-parity satellites
# ---------------------------------------------------------------------------


def test_cleancache_close_joins_refresher_and_dir_refresh():
    class SpyBackend(LocalBackend):
        def __init__(self):
            super().__init__(page_words=W)
            self.dir_refreshes = 0

        def dir_refresh(self):
            self.dir_refreshes += 1
            return True

    be = SpyBackend()
    with CleanCacheClient(be, bloom_refresh_s=0.01) as cc:
        t0 = time.monotonic()
        while be.dir_refreshes == 0 and time.monotonic() - t0 < 5:
            time.sleep(0.01)
        assert be.dir_refreshes > 0          # directory rides the loop
        refresher = cc._refresher
        assert refresher is not None and refresher.is_alive()
    assert not refresher.is_alive()          # close() JOINED the thread
    assert cc._refresher is None
    cc.close()                               # idempotent
    # threads that were never started: close() is a no-op
    with CleanCacheClient(SpyBackend()) as cc2:
        pass
    assert cc2._refresher is None


def test_pool_server_stats_parity():
    from pmdfc_tpu.onesided import PassivePool
    from pmdfc_tpu.runtime.net import PoolServer, RemotePool

    pool = PassivePool(num_rows=64, page_words=W, mode="host")
    srv = PoolServer(pool).start()
    with srv:
        rp = RemotePool("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None)
        lo, hi = rp.grant(8)
        rows = np.arange(lo, lo + 4, dtype=np.int32)
        rp.write_rows(rows, _pages(_keys(4, seed=20)))
        got = rp.read_rows(rows)
        assert got.shape == (4, W)
        snap = rp.server_stats()
        # the pool's own counters cross the wire...
        assert snap["writes"] == 4 and snap["reads"] == 4
        assert snap["granted_rows"] == 8
        # ...and the registry gauges mirror them (teletop/teledump see
        # the passive node like any serving surface)
        g = (snap.get("telemetry") or {}).get("gauges") or {}
        pw = {k: v for k, v in g.items() if k.endswith(".pool_writes")}
        assert pw and all(v == 4 for v in pw.values())
        gr = {k: v for k, v in g.items()
              if k.endswith(".pool_granted_rows")}
        assert gr and all(v == 8 for v in gr.values())
        rp.close()


def test_replica_group_prefers_fastpath_over_hedging():
    from pmdfc_tpu.client.replica import ReplicaGroup
    from pmdfc_tpu.config import ReplicaConfig

    srv1, _ = _server()
    srv2, _ = _server()
    with srv1, srv2:
        eps = [_dial(s, directory=True) for s in (srv1, srv2)]
        grp = ReplicaGroup(
            eps, page_words=W,
            cfg=ReplicaConfig(n_replicas=2, rf=2, hedge_ms=5000.0,
                              repair_interval_s=0.0))
        keys = _keys(64, seed=21)
        pages = _pages(keys)
        grp.put(keys, pages)
        assert grp.dir_refresh() == 2
        out, found = grp.get(keys)
        assert found.all() and np.array_equal(out, pages)
        fp = sum(_fp_counters(s)[1] for s in (srv1, srv2))
        assert fp > 0                        # primaries answered fast
        st = grp.stats()["group"]
        assert st["hedges_fired"] == 0       # nothing ever hedged
        grp.close()


def test_fastpath_under_concurrent_writers():
    """8 reader threads on the fast path while a writer re-puts and
    invalidates hot keys: every served page is bit-exact against the
    writer's journal (monotonic versions make torn serves detectable)."""
    srv, kv = _server()
    with srv:
        keys = _keys(64, seed=22)
        base = _pages(keys)
        wr = _dial(srv)
        wr.put(keys, base)
        version = np.zeros(len(keys), np.uint32)
        vlock = threading.Lock()
        stop = threading.Event()
        errs: list = []

        def writer():
            rng = np.random.default_rng(23)
            while not stop.is_set():
                i = int(rng.integers(0, len(keys)))
                with vlock:
                    v = int(version[i]) + 1  # claimed, not yet visible
                wr.put(keys[i:i + 1], base[i:i + 1] + np.uint32(v))
                with vlock:
                    version[i] = v           # completed-put journal
                time.sleep(0.001)

        def reader(seed):
            be = _dial(srv, directory=True)
            be.dir_refresh()
            rng = np.random.default_rng(seed)
            try:
                for _ in range(40):
                    idx = rng.integers(0, len(keys), 8)
                    with vlock:
                        vmin = version[idx].copy()
                    out, found = be.get(keys[idx])
                    with vlock:
                        vmax = version[idx].copy()
                    served_v = out[:, 0] - base[idx][:, 0]
                    # a put COMPLETED before the read must be visible
                    # (>= vmin); at most one claimed put can be in
                    # flight past the vmax snapshot (single writer)
                    okl = (~found) | ((served_v >= vmin)
                                      & (served_v <= vmax + 1))
                    if not okl.all():
                        raise AssertionError(
                            f"stale/wrong bytes: v={served_v[~okl]} "
                            f"window=[{vmin[~okl]},{vmax[~okl]}]")
                    if rng.random() < 0.2:
                        be.dir_refresh()
            except Exception as e:  # noqa: BLE001
                errs.append(e)
            finally:
                be.close()

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        rs = [threading.Thread(target=reader, args=(100 + i,))
              for i in range(8)]
        for t in rs:
            t.start()
        for t in rs:
            t.join()
        stop.set()
        wt.join(timeout=5)
        assert not errs, errs[0]
        assert _fp_counters(srv)[0] > 0
        wr.close()
