"""Straggler-compaction insert paths (round 5) + the eviction-skip
invariant (ADVICE r4 item 1).

Cuckoo and path now run their displacement/claim rounds at a compacted
narrow width once the full-width fill rounds drain a batch
(`models/cuckoo.py` round-1 + narrow kick loop, `models/path.py` staged
claim rounds). The conformance suite's shapes are too small to leave
the W == b degenerate case, so these tests drive batches big enough
that the narrow buffers (b/8, b/4, b/16) are real, plus the high-fill
regime that forces the lax.cond full-width fallback.
"""

import jax
import numpy as np
import pytest

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import get_index_ops
from pmdfc_tpu.utils.keys import INVALID_WORD, pack_key

pytestmark = pytest.mark.slow

B = 1 << 14  # > 1024*8 so cuckoo W=b/8 and path W1=b/4, W2=b/16 engage


def keys_of(lo):
    lo = np.asarray(lo, np.uint32)
    return np.asarray(pack_key(np.full_like(lo, 7), lo))


def vals_of(lo):
    lo = np.asarray(lo, np.uint32)
    return np.stack([lo ^ np.uint32(0xABCD), lo], axis=-1)


@pytest.mark.parametrize("kind", [IndexKind.CUCKOO, IndexKind.PATH])
def test_narrow_rounds_place_everything_at_fill(kind):
    """A fill batch (0.5x capacity) through the narrow rounds: every key
    that was not reported dropped/evicted must be found, bit-exact."""
    ops = get_index_ops(kind)
    cfg = IndexConfig(kind=kind, capacity=2 * B)
    st = ops.init(cfg)
    ks, vs = keys_of(np.arange(B)), vals_of(np.arange(B))
    st, res = ops.insert_batch(st, ks, vs)
    dropped = np.asarray(res.dropped)
    ev = np.asarray(res.evicted)
    ev_live = (ev[:, 0] != INVALID_WORD) | (ev[:, 1] != INVALID_WORD)
    # at fill 0.5 with fresh tables, losses must be essentially nil —
    # a narrow-buffer overflow bug would show up as mass drops here
    assert dropped.sum() + ev_live.sum() < B // 100
    got = ops.get_batch(st, ks)
    found = np.asarray(got.found)
    lost = set(map(tuple, ev[ev_live].tolist()))
    for i in np.nonzero(~found)[0]:
        assert dropped[i] or (tuple(ks[i].tolist()) in lost)
    vals = np.asarray(got.values)
    ok = found & ~dropped
    np.testing.assert_array_equal(vals[ok], vs[ok])


@pytest.mark.parametrize("kind", [IndexKind.CUCKOO, IndexKind.PATH])
def test_overflow_fallback_keeps_accounting(kind):
    """1.5x-capacity pressure in big batches forces the overflow cond
    (full-width fallback). Clean-cache invariant: every miss is
    explained by a reported eviction or drop."""
    ops = get_index_ops(kind)
    cap = B  # batches are half of capacity; 3 batches = 1.5x fill
    cfg = IndexConfig(kind=kind, capacity=cap)
    st = ops.init(cfg)
    rng = np.random.default_rng(5)
    all_ks = []
    evicted_or_dropped = 0
    for r in range(3):
        lo = rng.integers(0, 1 << 30, B // 2).astype(np.uint32)
        ks, vs = keys_of(lo), vals_of(lo)
        st, res = ops.insert_batch(st, ks, vs)
        ev = np.asarray(res.evicted)
        evicted_or_dropped += int(np.asarray(res.dropped).sum())
        evicted_or_dropped += int(
            ((ev[:, 0] != INVALID_WORD) | (ev[:, 1] != INVALID_WORD)).sum()
        )
        all_ks.append(ks)
    ks = np.concatenate(all_ks)
    got = ops.get_batch(st, ks)
    misses = int((~np.asarray(got.found)).sum())
    # duplicates across rounds can collapse to one slot; the invariant is
    # one-sided: misses cannot exceed reported losses
    assert misses <= evicted_or_dropped


def test_level_narrow_bottom_tail_exact():
    """Level's lean GET probes the bottom tier only for top misses, at a
    compacted b/8 width (cond full-width fallback). Fill past the top
    tier so real keys live in the bottom, then verify the lean path
    returns them bit-exact at a batch width where the narrow buffer is
    engaged — and that an absent-key storm (all misses overflow the
    buffer) takes the exact full-width branch."""
    from pmdfc_tpu.models.base import get_index_ops

    ops = get_index_ops(IndexKind.LEVEL)
    cfg = IndexConfig(kind=IndexKind.LEVEL, capacity=B)
    st = ops.init(cfg)
    rng = np.random.default_rng(9)
    lo = rng.choice(1 << 24, size=int(B * 0.8), replace=False).astype(
        np.uint32
    )
    ks, vs = keys_of(lo), vals_of(lo)
    st, res = ops.insert_batch(st, ks, vs)
    ok = ~np.asarray(res.dropped)
    ev = np.asarray(res.evicted)
    lost = set(map(tuple, ev[(ev[:, 0] != INVALID_WORD)
                             | (ev[:, 1] != INVALID_WORD)].tolist()))
    live = ok & np.array([tuple(k) not in lost for k in ks.tolist()])
    # 0.8x capacity overfills the top tier: some live keys MUST sit in
    # the bottom rows or this test isn't exercising the tail
    slots = np.asarray(ops.get_batch(st, ks).slots)
    top_slots = st.top_rows * (st.table.shape[1] // 4)
    bottom_live = int((live & (slots >= top_slots)).sum())
    assert bottom_live > 0
    # pin the NARROW branch: if bottom-resident keys ever exceeded W the
    # cond would silently take the full-width path and the narrow
    # scatter-back would go untested while this test still passed
    assert bottom_live <= max(1024, B // 8), bottom_live
    vals, found = jax.tree.map(np.asarray, ops.get_values(st, ks))
    assert found[live].all()
    np.testing.assert_array_equal(vals[live], vs[live])
    # absent-key storm: every probe misses the top tier -> overflow ->
    # full-width branch; all must come back not-found, none fabricated
    ab = keys_of(np.arange(1 << 25, (1 << 25) + B, dtype=np.uint32))
    _, f_ab = jax.tree.map(np.asarray, ops.get_values(st, ab))
    assert not f_ab.any()


def test_eviction_free_batches_keep_every_fresh_slot():
    """ADVICE r4: the KV facade skips its post-verify gather when a batch
    reports zero evictions (`kv.py:205`), so the cross-module invariant
    it rests on must be pinned per family: an insert reporting
    all-INVALID evicted and no drops leaves EVERY fresh slot's key
    gettable."""
    n = 512
    for kind in IndexKind:
        ops = get_index_ops(kind)
        kw = {}
        if kind in (IndexKind.CCEH, IndexKind.EXTENDIBLE):
            kw = dict(segment_slots=128, split_headroom=2)
        st = ops.init(IndexConfig(kind=kind, capacity=1 << 13, **kw))
        lo = np.arange(n, dtype=np.uint32)
        ks, vs = keys_of(lo), vals_of(lo)
        st, res = ops.insert_batch(st, ks, vs)
        ev = np.asarray(res.evicted)
        if ((ev[:, 0] != INVALID_WORD) | (ev[:, 1] != INVALID_WORD)).any():
            continue  # family reported displacement — facade verifies
        fresh = np.asarray(res.fresh) & ~np.asarray(res.dropped)
        got = ops.get_batch(st, ks)
        found = np.asarray(got.found)
        assert found[fresh].all(), (
            f"{kind.value}: eviction-free insert lost a fresh slot "
            "(silent same-batch displacement — the facade's skipped "
            "post-verify gather would have caught this)"
        )
