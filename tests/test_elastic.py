"""Elastic-membership drills — the placement ring + live migration.

Three layers of pins:

1. RING PROPERTIES (pure, no sockets): owner sets are deterministic and
   distinct, the scalar oracle matches the numpy batch resolver, epochs
   are monotonic and rings immutable, and a single join/leave moves
   only ~rf/N of the key space (MEASURED, with vnode-variance slack) —
   the consistent-hashing claim the whole subsystem rides on.
2. MIGRATION SEMANTICS (LocalBackend clusters, hermetic): a grow/shrink
   streams exactly the owed keys to their new owners, the dual-read
   window serves mid-move, an in-flight key missing from BOTH epochs'
   owners degrades to a legal `miss_routed` (cause invariant exact),
   the repair journal drops keys a transition moved off an endpoint,
   and `PMDFC_RING=off` is verb-for-verb the static murmur map.
3. THE CHAOS ACCEPTANCE DRILL (real NetServers): scale 3 → 5 → 2 mid
   zipf-storm — zero wrong bytes, bounded hit-rate dip vs the no-churn
   reference, moved key count within the ~1/N bound, a flight-recorder
   `membership_change` event with the series tail, and the miss-cause
   sum invariant holding bit-exactly throughout.
"""

import collections
import glob
import json
import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, LocalBackend
from pmdfc_tpu.client.replica import ReplicaGroup
from pmdfc_tpu.cluster.migrate import TokenBucket
from pmdfc_tpu.cluster.ring import HashRing, moved_mask
from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              ReplicaConfig, RingConfig, TelemetryConfig)
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime import telemetry as tele
from pmdfc_tpu.runtime.failure import CircuitBreaker, ReconnectingClient
from pmdfc_tpu.runtime.net import NetServer, TcpBackend
from pmdfc_tpu.utils.hashing_np import hash_u64_np

pytestmark = pytest.mark.elastic

W = 16
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),
    paged=True,
    page_words=W,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 1:2].astype(np.uint32) * 3 + 1) * np.arange(
        1, W + 1, dtype=np.uint32
    )


def _group(eps, rf=2, ring: RingConfig | None = None, **kw):
    cfg = ReplicaConfig(n_replicas=len(eps), rf=rf,
                        repair_interval_s=0, ring=ring, **kw)
    return ReplicaGroup(eps, page_words=W, cfg=cfg)


# --- 1. ring properties ---------------------------------------------------


def test_ring_owner_identity_batch_vs_scalar():
    """The numpy batch resolver and the scalar oracle agree on every
    key, owner sets are distinct, and a rebuilt ring (same members,
    vnodes, seed) resolves identically — placement is pure data."""
    r = HashRing(range(5), vnodes=32, seed=1234)
    keys = _keys(512, seed=3)
    own = r.owners_np(keys, 3)
    assert own.shape == (512, 3)
    assert (own[:, 0] != own[:, 1]).all()
    assert (own[:, 1] != own[:, 2]).all()
    assert (own[:, 0] != own[:, 2]).all()
    for i in range(128):
        assert r.owner_set(tuple(keys[i]), 3) == tuple(own[i])
    r2 = HashRing(range(5), vnodes=32, seed=1234)
    assert (r2.owners_np(keys, 3) == own).all()
    # every member takes a share of primaries (spread)
    prim = np.bincount(own[:, 0], minlength=5)
    assert (prim > 0).all(), prim


def test_ring_epoch_monotonic_and_immutable():
    r1 = HashRing(range(3), vnodes=16)
    r2 = r1.join(7)
    r3 = r2.leave(0)
    r4 = r3.replace(1, 9)
    assert (r1.epoch, r2.epoch, r3.epoch, r4.epoch) == (1, 2, 3, 4)
    assert r1.members == (0, 1, 2)          # originals untouched
    assert r2.members == (0, 1, 2, 7)
    assert r3.members == (1, 2, 7)
    assert r4.members == (2, 7, 9)
    with pytest.raises(ValueError):
        r1.join(2)        # already a member
    with pytest.raises(ValueError):
        r1.leave(9)       # not a member
    with pytest.raises(ValueError):
        HashRing([0]).leave(0)  # cannot empty the ring
    keys = _keys(256, seed=5)
    # a key's position never depends on membership: epochs of one ring
    # family place it identically
    assert (r1.positions(keys) == r4.positions(keys)).all()


def test_ring_stability_measured_join_and_leave():
    """The consistent-hashing claim, MEASURED: one join of an N-member
    ring moves ~1/N of primaries and ~rf/N of owner sets (vnode
    variance gives slack, never an order of magnitude)."""
    n, rf = 8, 2
    keys = _keys(20000, seed=11)
    r = HashRing(range(n), vnodes=64)
    r2 = r.join(n)
    prim_moved = (r.owners_np(keys, 1)[:, 0]
                  != r2.owners_np(keys, 1)[:, 0]).mean()
    exp = 1.0 / (n + 1)
    assert 0.3 * exp < prim_moved < 2.0 * exp, \
        f"primary move {prim_moved:.4f} vs expected {exp:.4f}"
    set_moved = moved_mask(r, r2, keys, rf).mean()
    exp_set = rf / (n + 1)
    assert 0.3 * exp_set < set_moved < 2.0 * exp_set, \
        f"owner-set move {set_moved:.4f} vs expected {exp_set:.4f}"
    # leave is symmetric: removing the joined member moves ITS share
    r3 = r2.leave(n)
    leave_moved = moved_mask(r2, r3, keys, rf).mean()
    assert 0.3 * exp_set < leave_moved < 2.0 * exp_set
    # untouched members' keys stay put: a key whose set avoids the
    # joiner in BOTH epochs resolves identically
    o1, o2 = r.owners_np(keys, rf), r2.owners_np(keys, rf)
    untouched = ~(o2 == n).any(axis=1)
    assert (o1[untouched] == o2[untouched]).all()


def test_token_bucket_rate_bound():
    tb = TokenBucket(rate=1000.0, burst=100)
    assert tb.take(50) == 50       # inside the burst
    assert tb.take(100) == 50      # burst exhausted beyond the level
    assert tb.take(100) == 0       # drained
    time.sleep(0.05)               # ~50 tokens refill
    got = tb.take(1000)
    assert 20 <= got <= 100, got
    assert TokenBucket(rate=0, burst=1).take(10**6) == 10**6  # unbounded


# --- 2. migration semantics (hermetic LocalBackend clusters) --------------


def test_grow_migrates_owed_keys_and_dual_read_serves():
    """Join mid-serve: the backlog equals the measured moved-key count,
    the dual-read window serves every key BEFORE migration drains, and
    after the drain every new owner physically holds its owed pages."""
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(384, seed=21)
        pages = _pages(keys)
        g.put(keys, pages)
        old_ring = g.ring
        eps.append(LocalBackend(W))
        slot = g.add_endpoint(eps[-1])
        assert slot == 3
        assert g.migrator.active()
        # owed accounting: the backlog is exactly the owner-set diff
        owed = int(moved_mask(old_ring, g.ring, keys, 2).sum())
        assert g.migrator.lag() == owed > 0
        # dual-read window: everything serves mid-move, right bytes
        out, found = g.get(keys)
        assert found.all() and (out == pages).all()
        assert g.drain_migration(20)
        assert dict(g.migrator.scope)["moved_pages"] >= owed
        # the new owners physically hold their keys now
        own = g.ring.owners_np(keys, 2)
        for e in range(4):
            mask = (own == e).any(axis=1)
            o, f = eps[e].get(keys[mask])
            assert f.all(), f"endpoint {e} missing owed keys"
            assert (o == pages[mask]).all()
    finally:
        g.close()


def test_shrink_retires_slot_after_drain():
    """Leave: the leaving member keeps serving dual-reads while its key
    ranges stream out; at settle the slot is dead (breaker force-open,
    endpoint closed) and the surviving fleet holds everything."""
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(256, seed=23)
        pages = _pages(keys)
        g.put(keys, pages)
        g.remove_endpoint(0)
        assert g.migrator.active()
        out, found = g.get(keys)       # mid-window
        assert found.all() and (out == pages).all()
        assert g.drain_migration(20)
        assert 0 in g._dead
        assert g.breakers[0].state == CircuitBreaker.OPEN
        assert g.breakers[0].stats["forced_opens"] >= 1
        assert g.ring.members == (1, 2)
        out, found = g.get(keys)       # settled: survivors own it all
        assert found.all() and (out == pages).all()
        # membership invariant: no traffic ever routes to the dead slot
        assert not (g._members(keys) == 0).any()
    finally:
        g.close()


def test_replace_endpoint_quarantines_and_migrates():
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(256, seed=29)
        pages = _pages(keys)
        g.put(keys, pages)
        eps.append(LocalBackend(W))
        new_slot = g.replace_endpoint(1, eps[-1])
        assert new_slot == 3
        # quarantine: the replaced member takes no more serving traffic
        assert g.breakers[1].state == CircuitBreaker.OPEN
        out, found = g.get(keys)
        assert found.all() and (out == pages).all()
        assert g.drain_migration(20)
        assert 1 in g._dead and g.ring.members == (0, 2, 3)
        own = g.ring.owners_np(keys, 2)
        mask = (own == 3).any(axis=1)
        o, f = eps[-1].get(keys[mask])
        assert f.all() and (o == pages[mask]).all()
        assert dict(g.migrator.scope)["moved_replace"] > 0
    finally:
        g.close()


def test_miss_routed_attribution_mid_move():
    """A key whose owner set is mid-move and which NEITHER epoch's
    owners can serve degrades to `miss_routed` — the migration dip's
    attributable lane — and `misses == Σ miss_*` stays bit-exact."""
    eps = [LocalBackend(W) for _ in range(2)]
    # rate ~0: the window stays open while we probe mid-move
    g = _group(eps, rf=1,
               ring=RingConfig(migrate_pages_per_s=1e-6, migrate_burst=1))
    try:
        keys = _keys(256, seed=31)
        pages = _pages(keys)
        g.put(keys, pages)
        eps.append(LocalBackend(W))
        g.add_endpoint(eps[-1])
        assert g.migrator.active()
        # simulate in-flight loss: the old owners' stores vanish (the
        # pages are mid-copy, nobody has them yet)
        for e in eps[:2]:
            e._store.clear()
        out, found = g.get(keys)
        assert not found.any()
        grp = g.stats()["group"]
        assert grp["misses"] == (grp["miss_replica_exhausted"]
                                 + grp["miss_digest"]
                                 + grp["miss_routed"]
                                 + grp["miss_remote"])
        moved = int(moved_mask(*g.migrator.rings(), keys, 1).sum())
        assert grp["miss_routed"] == moved > 0
        assert grp["miss_remote"] == len(keys) - moved
    finally:
        g.close()


def test_invalidate_survives_ownership_round_trip():
    """Tombstone durability under churn: a join moves a key's ownership
    away (the ex-owner keeps its copy — nothing deletes on ownership
    loss), the key is invalidated (which also pops the digest that
    would otherwise refuse stale bytes), then a shrink hands ownership
    BACK to the ex-owner. An owner-set-wide tombstone would let the
    ex-owner serve the invalidated page as a hit; the fleet-wide
    fan-out keeps it a miss forever. Proven to fail with the owner-set
    fan-out."""
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(300, seed=61)
        pages = _pages(keys)
        g.put(keys, pages)
        eps.append(LocalBackend(W))
        g.add_endpoint(eps[-1])
        assert g.drain_migration(20)
        g.invalidate(keys[:32])
        # shrink twice: plenty of keys' ownership lands back on slots
        # that held pre-join copies
        g.remove_endpoint(0)
        assert g.drain_migration(20)
        g.remove_endpoint(1)
        assert g.drain_migration(20)
        out, found = g.get(keys)
        assert not found[:32].any(), \
            f"{int(found[:32].sum())} tombstoned keys resurrected"
        assert found[32:].all() and (out[32:] == pages[32:]).all()
    finally:
        g.close()


def test_repair_journal_drops_moved_keys():
    """Satellite: repair entries for keys whose owner set no longer
    includes the queued endpoint (post-ring-change) are DROPPED at
    repair_tick, not retried forever — the journal-growth fix."""
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(256, seed=37)
        g.put(keys, _pages(keys))
        # seed endpoint 0's repair queue with EVERY key, as if it had
        # rejoined before a ring change re-owned most of them
        with g._repair_lock:
            g._repair_pending[0] = collections.deque(
                map(tuple, keys.tolist()))
        owned = int((g._members(keys) == 0).any(axis=1).sum())
        deadline = time.time() + 10
        while time.time() < deadline:
            g.repair_tick()
            with g._repair_lock:
                if not g._repair_pending.get(0):
                    break
        with g._repair_lock:
            assert not g._repair_pending.get(0), "backlog never drained"
        grp = g.stats()["group"]
        assert grp["repair_dropped"] == len(keys) - owned > 0
    finally:
        g.close()


def test_close_parity_joins_repair_thread():
    """Satellite: close() joins the repair/migration thread with
    `CleanCacheClient` parity — handle dropped only after a completed
    join, idempotent, context-manager exit covered."""
    eps = [LocalBackend(W) for _ in range(2)]
    cfg = ReplicaConfig(n_replicas=2, rf=1, repair_interval_s=0.01)
    g = ReplicaGroup(eps, page_words=W, cfg=cfg)
    t = g._repair_thread
    assert t is not None and t.is_alive()
    g.close()
    assert g._repair_thread is None and not t.is_alive()
    g.close()  # idempotent
    with ReplicaGroup([LocalBackend(W)], page_words=W,
                      cfg=ReplicaConfig(n_replicas=1, rf=1,
                                        repair_interval_s=0.01)) as g2:
        assert g2._repair_thread.is_alive()
    assert g2._repair_thread is None


def test_breaker_force_open_semantics():
    """Satellite (failure.py interplay): a permanent force-open never
    half-opens (retired slot); a finite quarantine rejoins through the
    normal half-open machinery."""
    br = CircuitBreaker(failures_to_open=3, cooldown_s=0.01, jitter=0.0)
    br.force_open()
    assert br.state == CircuitBreaker.OPEN and not br.ready()
    time.sleep(0.05)
    assert br.state == CircuitBreaker.OPEN and not br.allow()
    assert br.stats["forced_opens"] == 1
    br2 = CircuitBreaker(failures_to_open=3, cooldown_s=0.01, jitter=0.0)
    br2.force_open(0.03)
    assert not br2.ready()
    time.sleep(0.05)
    assert br2.ready()            # quarantine elapsed: probe available
    assert br2.allow()
    br2.record_success()
    assert br2.state == CircuitBreaker.CLOSED


def test_breaker_down_for_latch():
    """`down_for()` measures the whole outage: open -> half_open ->
    reopen cycles never reset it, only a recorded success does — the
    latch auto-replacement keys on."""
    br = CircuitBreaker(failures_to_open=1, cooldown_s=0.01, jitter=0.0)
    assert br.down_for() == 0.0
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    t0 = br.down_for()
    assert t0 > 0.0
    time.sleep(0.02)
    assert br.ready()                     # half-open probe available
    br.record_failure()                   # probe failed: reopen
    assert br.down_for() > t0             # the outage keeps counting
    br.record_success()
    assert br.down_for() == 0.0


def test_membership_lost_claim_retires_registered_spare():
    """A membership op that loses the Migrator.start claim race AFTER
    registering its new endpoint must retire that slot (dead set,
    breaker force-open, endpoint closed) — not leave a live-but-
    ringless zombie the auto-replace loop would re-build a spare
    beside on every later tick."""
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        def boom(*a, **k):
            raise RuntimeError("claim lost")

        g.migrator.start = boom
        n0, epoch0 = g.n, g.ring.epoch
        spare = LocalBackend(W)
        with pytest.raises(RuntimeError, match="claim lost"):
            g.replace_endpoint(1, spare)
        # the spare's slot exists but is fully retired; placement and
        # the live member are untouched
        assert g.n == n0 + 1 and n0 in g._dead
        assert not g.breakers[n0].ready()
        assert g.ring.epoch == epoch0 and g.ring.members == (0, 1, 2)
        assert 1 not in g._dead and g.breakers[1].state == "closed"
        with pytest.raises(RuntimeError, match="claim lost"):
            g.add_endpoint(LocalBackend(W))
        assert n0 + 1 in g._dead
    finally:
        g.close()


@pytest.mark.slow
def test_breaker_driven_auto_replacement():
    """ROADMAP item 2's leftover, shipped: a member whose breaker stays
    latched out of CLOSED past `cfg.auto_replace_after_s` is replaced
    with a freshly built spare on the repair cadence — the ring's
    replace() path under REAL failure (the earlier drills replaced
    healthy members). The swap rides the normal transition: quarantine,
    dual-read window, migration of the owed ranges, retire."""
    cl = _Cluster(3)
    spares: list = []

    def spare_factory(failed_slot):
        i = cl.spawn()
        spares.append((failed_slot, i))
        return cl.endpoint(i)

    eps = [cl.endpoint(i) for i in range(3)]
    cfg = ReplicaConfig(n_replicas=3, rf=2, repair_interval_s=0,
                        hedge_ms=0, breaker_failures=2,
                        breaker_cooldown_s=30.0, breaker_jitter=0.0,
                        auto_replace_after_s=0.05,
                        ring=RingConfig(migrate_pages_per_s=0))
    g = ReplicaGroup(eps, page_words=W, cfg=cfg,
                     spare_factory=spare_factory)
    try:
        keys = _keys(256, seed=53)
        pages = _pages(keys)
        g.put(keys, pages)
        g.repair_tick()
        assert dict(g.counters)["auto_replacements"] == 0  # all healthy
        # REAL failure: kill server 1; serving traffic latches its
        # breaker open (ReconnectingClient feeds from the degrade path)
        cl.stop(1)
        for i in range(0, 96, 8):
            g.get(keys[i:i + 8])
        assert g.breakers[1].state != CircuitBreaker.CLOSED
        assert g.breakers[1].down_for() > 0
        time.sleep(0.08)          # past the auto-replace latch
        g.repair_tick()           # the cadence that fires the swap
        assert dict(g.counters)["auto_replacements"] == 1
        assert spares == [(1, 3)]
        assert g.ring.members == (0, 2, 3)
        assert g.drain_migration(30)
        assert 1 in g._dead
        # one swap per outage: further ticks must not replace again
        g.repair_tick()
        assert dict(g.counters)["auto_replacements"] == 1
        # the fleet serves on — zero wrong bytes, hit-rate recovers
        out, found = g.get(keys)
        assert (out[found] == pages[found]).all()
        assert int(found.sum()) >= int(0.8 * len(keys)), int(found.sum())
    finally:
        g.close()
        cl.close()


def test_ring_off_conformance(monkeypatch):
    """`PMDFC_RING=off` is verb-for-verb the static murmur map: member
    resolution equals the pre-ring formula exactly (placement decides
    every fan-out, so this IS transcript identity), membership ops
    refuse, no elastic wire capability is requested or acked, and a
    seeded workload's per-endpoint op counts match the formula's
    prediction."""
    monkeypatch.setenv("PMDFC_RING", "off")
    eps = [LocalBackend(W) for _ in range(3)]
    g = _group(eps, rf=2)
    try:
        keys = _keys(512, seed=41)
        # the exact static formula the pre-ring tree shipped
        h = hash_u64_np(keys[:, 0], keys[:, 1], seed=0x5EC0_11D5)
        prim = (h % np.uint32(3)).astype(np.int64)
        want = (prim[:, None] + np.arange(2)) % 3
        assert (g._members(keys) == want).all()
        assert g.ring is None and g.migrator is None
        with pytest.raises(RuntimeError):
            g.add_endpoint(LocalBackend(W))
        with pytest.raises(RuntimeError):
            g.remove_endpoint(0)
        # fan-out transcript: each endpoint received exactly the puts
        # the static map assigns it
        pages = _pages(keys)
        g.put(keys, pages)
        for e in range(3):
            assert len(eps[e]._store) == int((want == e).any(axis=1).sum())
    finally:
        g.close()
    # wire half: the client never requests the elastic capability, so
    # the server (ring on or off) never acks and the transcript carries
    # zero elastic verbs
    kv = KV(CFG)
    srv = NetServer(lambda: DirectBackend(kv)).start()
    try:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None)
        assert not be.elastic
        assert be.ring_note(1, 3) is None      # refuses client-side
        be.handoff(keys[:4], pages[:4])        # degrades to a plain put
        out, found = be.get(keys[:4])
        assert found.all() and (out == pages[:4]).all()
        assert srv.stats["ring_notes"] == 0
        assert srv.stats["handoff_pages"] == 0
        be.close()
    finally:
        srv.stop()


# --- 3. wire + acceptance -------------------------------------------------


def test_ring_note_bumps_directory_epoch_and_handoff_counts():
    """`MSG_RINGNOTE` structurally invalidates the one-sided fast lane
    (PR 11): the server's directory epoch bumps, the client's cached
    mirror goes dirty and re-arms after a refresh, and `MSG_HANDOFF`
    pages land with their own server-side attribution."""
    kv = KV(CFG)
    srv = NetServer(lambda: DirectBackend(kv)).start()
    try:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, directory=True)
        assert be.elastic
        keys = _keys(64, seed=43)
        pages = _pages(keys)
        be.put(keys, pages)
        assert be.dir_refresh()
        out, found = be.get(keys)
        assert found.all() and (out == pages).all()
        e0 = kv.dir_epoch
        new_epoch = be.ring_note(epoch=7, members=4)
        assert new_epoch == e0 + 1
        assert not be.directory.ready()        # mirror dirtied NOW
        assert srv.stats["ring_notes"] == 1
        assert srv.stats["ring_epoch"] == 7
        # verb path keeps serving while dirty; refresh re-arms
        out, found = be.get(keys)
        assert found.all() and (out == pages).all()
        assert be.dir_refresh() and be.directory.ready()
        # handoff: same bytes as a put, separate attribution
        k2 = keys.copy()
        k2[:, 0] ^= 0x8000
        be.handoff(k2, pages)
        out, found = be.get(k2)
        assert found.all() and (out == pages).all()
        assert srv.stats["handoff_pages"] == len(k2)
        be.close()
    finally:
        srv.stop()


class _Cluster:
    """N real-KV NetServers with mid-soak spawn/stop (slots append-only,
    ports stable per slot)."""

    def __init__(self, n: int):
        self.kvs: list = []
        self.servers: list = []
        self.ports: list = []
        for _ in range(n):
            self.spawn()

    def spawn(self) -> int:
        kv = KV(CFG)
        srv = NetServer(lambda kv=kv: DirectBackend(kv)).start()
        self.kvs.append(kv)
        self.servers.append(srv)
        self.ports.append(srv.port)
        return len(self.servers) - 1

    def stop(self, i: int) -> None:
        if self.servers[i] is not None:
            self.servers[i].stop()
            self.servers[i] = None
            self.kvs[i] = None

    def endpoint(self, i: int) -> ReconnectingClient:
        def factory(i=i):
            return TcpBackend("127.0.0.1", self.ports[i], page_words=W,
                              keepalive_s=None, op_timeout_s=10.0)

        return ReconnectingClient(factory, page_words=W,
                                  retry_delay_s=0.005,
                                  max_retry_delay_s=0.05, seed=97 + i)

    def close(self) -> None:
        for i in range(len(self.servers)):
            self.stop(i)


def _storm(g, cl, keys, pages, steps, seed, on_step=None) -> dict:
    rng = np.random.default_rng(seed)
    stats = {"gets": 0, "hits": 0, "wrong_bytes": 0}
    for step in range(steps):
        if on_step is not None:
            on_step(step)
        op = rng.integers(4)
        lo = int(rng.integers(0, len(keys) - 16))
        n = int(rng.integers(1, 16))
        sel = slice(lo, lo + n)
        if op == 0:
            g.put(keys[sel], pages[sel])
        else:
            out, found = g.get(keys[sel])
            stats["gets"] += n
            stats["hits"] += int(found.sum())
            good = pages[sel]
            stats["wrong_bytes"] += int(
                (out[found] != good[found]).any(axis=1).sum())
        g.repair_tick()
    return stats


@pytest.mark.slow  # tier-1 budget: heavy drill rides the slow tier (PR 16)
def test_elastic_chaos_scale_3_5_2_mid_soak(tmp_path):
    """THE acceptance drill: a seeded storm over real NetServers while
    the fleet scales 3 → 5 → 2. Zero wrong bytes, hit-rate ≥ 80% of
    the identical no-churn run, migration moved only the owed ~rf/N key
    ranges (counted against `moved_mask`), the transition boundary
    fired flight-recorder events whose dump carries the series tail,
    and the group's miss-cause sum invariant holds bit-exactly."""
    reg = tele.configure(TelemetryConfig(enabled=True,
                                         dump_dir=str(tmp_path),
                                         dump_min_interval_s=0.0))
    assert reg is not None
    steps = 220
    keys = _keys(224, seed=55)
    pages = _pages(keys)
    try:
        # no-churn reference (same seed, same step schedule)
        cl0 = _Cluster(3)
        g0 = ReplicaGroup([cl0.endpoint(i) for i in range(3)],
                          page_words=W,
                          cfg=ReplicaConfig(n_replicas=3, rf=2,
                                            repair_interval_s=0))
        try:
            g0.put(keys, pages)
            base = _storm(g0, cl0, keys, pages, steps, seed=55)
        finally:
            g0.close()
            cl0.close()
        assert base["wrong_bytes"] == 0
        base_rate = base["hits"] / max(1, base["gets"])

        cl = _Cluster(3)
        g = ReplicaGroup([cl.endpoint(i) for i in range(3)],
                         page_words=W,
                         cfg=ReplicaConfig(n_replicas=3, rf=2,
                                           repair_interval_s=0))
        owed = [0]

        def change(kind, slot=None):
            g.drain_migration(20)
            old_ring = g.ring
            if kind == "grow":
                s = cl.spawn()
                g.add_endpoint(cl.endpoint(s))
            else:
                g.remove_endpoint(slot)
            owed[0] += int(moved_mask(old_ring, g.ring, keys, 2).sum())

        schedule = {40: lambda: change("grow"),
                    70: lambda: change("grow"),
                    120: lambda: change("shrink", 0),
                    150: lambda: change("shrink", 1),
                    180: lambda: change("shrink", 2)}

        def on_step(step):
            act = schedule.get(step)
            if act is not None:
                act()

        try:
            g.put(keys, pages)
            faulted = _storm(g, cl, keys, pages, steps, seed=55,
                             on_step=on_step)
            assert faulted["wrong_bytes"] == 0, "wrong bytes mid-scale"
            rate = faulted["hits"] / max(1, faulted["gets"])
            assert rate >= 0.8 * base_rate, \
                f"hit-rate dip unbounded: {rate:.3f} < 0.8*{base_rate:.3f}"
            assert g.drain_migration(30)
            # fleet is {3, 4}: retired servers can stop now
            assert g.ring.members == (3, 4)
            for s in (0, 1, 2):
                cl.stop(s)
            # post-scale: the 2-survivor fleet serves the whole set
            out, found = g.get(keys)
            assert (out[found] == pages[found]).all()
            assert found.mean() >= 0.95, \
                f"post-scale recovery broken ({found.mean():.3f})"
            # moved accounting: every transition's moves were owed
            # (journal ⊆ universe here, so moved ≤ owed x rf)
            mig = dict(g.migrator.scope)
            assert mig["moved_pages"] > 0
            assert mig["transitions"] == 5
            assert (mig["moved_join"] + mig["moved_leave"]
                    + mig["moved_replace"]) == mig["moved_pages"]
            assert mig["candidate_keys"] <= 2 * owed[0] + 1, \
                (mig["candidate_keys"], owed[0])
            # cause invariant, bit-exact
            grp = g.stats()["group"]
            assert grp["misses"] == (grp["miss_replica_exhausted"]
                                     + grp["miss_digest"]
                                     + grp["miss_routed"]
                                     + grp["miss_remote"])
        finally:
            g.close()
            cl.close()
        # the transition trajectory is attributable: membership events
        # fired, and the flight dump carries the windowed series tail
        dumps = glob.glob(str(tmp_path / "flight_membership_*.json"))
        assert dumps, "no membership flight dump written"
        doc = json.load(open(sorted(dumps)[-1]))
        assert doc["rung"].startswith("membership_")
        from tools.check_teledump import check_flight

        assert check_flight(doc) == [], check_flight(doc)
    finally:
        tele.configure()
