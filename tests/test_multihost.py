"""`connect_multihost` drill — 2 real processes over a localhost
coordinator (VERDICT r4 item 6: the DCN path had zero test coverage).

Analog of the reference's multi-node deployment: `script.sh:3-41` drives
3 VMs against one RDMA server; here one LOGICAL server (a ShardedKV)
spans 2 OS processes x 2 virtual CPU devices each, joined by
`jax.distributed.initialize` through `connect_multihost`. Each worker
(tests/multihost_worker.py) asserts the global mesh is 4 devices and
that insert/get/delete/stats match host-computed ground truth — the
multi-process analog of test_shard.py's a2a-vs-ground-truth gate.
"""

import os
import subprocess
import sys

import pytest

from pmdfc_tpu.bench.multihost_bench import _free_port  # one port grabber

pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def test_multihost_bench_smoke():
    """The DCN-path workload driver end-to-end: 2 processes, JSON record,
    every key served, balanced shards."""
    import json

    p = subprocess.run(
        [sys.executable, "-m", "pmdfc_tpu.bench.multihost_bench",
         "--procs", "2", "--n", str(1 << 15), "--batch", str(1 << 13),
         "--capacity", str(1 << 17), "--timeout", "400"],
        capture_output=True, text=True, timeout=470,
    )
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-1000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "multihost_get_mops"
    assert out["hits"] == out["n"]
    assert out["procs"] == 2 and out["devices"] == 4
    assert out["shard_occupancy_min"] > 0


def test_two_process_sharded_kv():
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own JAX env (2 CPU devices each); scrub the
    # suite's 8-device flag so it cannot leak through
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost drill timed out:\n" + "\n".join(
            o or "" for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{out[-4000:]}"
        )
        assert f"worker {pid}: OK" in out
