"""`connect_multihost` drill — 2 real processes over a localhost
coordinator (VERDICT r4 item 6: the DCN path had zero test coverage).

Analog of the reference's multi-node deployment: `script.sh:3-41` drives
3 VMs against one RDMA server; here one LOGICAL server (a ShardedKV)
spans 2 OS processes x 2 virtual CPU devices each, joined by
`jax.distributed.initialize` through `connect_multihost`. Each worker
(tests/multihost_worker.py) asserts the global mesh is 4 devices and
that insert/get/delete/stats match host-computed ground truth — the
multi-process analog of test_shard.py's a2a-vs-ground-truth gate.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sharded_kv():
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own JAX env (2 CPU devices each); scrub the
    # suite's 8-device flag so it cannot leak through
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost drill timed out:\n" + "\n".join(
            o or "" for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{out[-4000:]}"
        )
        assert f"worker {pid}: OK" in out
