"""Replica-group drills — the multi-endpoint analog of `test_chaos.py`.

A cluster of N real-KV NetServers, each behind its own (optional)
`ChaosProxy`, fronted by a `ReplicaGroup` over
`ReconnectingClient`-wrapped `TcpBackend` endpoints. The drills assert
the replicated extension of the PR-1 ladder invariants:

1. NO exception escapes a page op — kills, chaos, and full-set
   exhaustion all degrade to legal misses/drops.
2. NO wrong bytes are ever served — every `found` page content-verifies
   against key-derived ground truth, from whichever replica served it.
3. Availability: with one server down at any instant (rolling
   kill/restore), GET hit-rate stays ≥ 80% of the no-fault run; the
   dead endpoint's breaker opens within the configured threshold; a
   cold-rejoined replica is repaired (repair_pages > 0) and post-repair
   hit-rate recovers.
"""

import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, LocalBackend
from pmdfc_tpu.client.replica import ReplicaGroup
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig, ReplicaConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.failure import (
    ChaosProxy, CircuitBreaker, ReconnectingClient)
from pmdfc_tpu.runtime.net import NetServer, TcpBackend

pytestmark = pytest.mark.replica

W = 16
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),
    paged=True,
    page_words=W,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    # ground truth derives from the key: ANY wrong byte is detectable
    return (keys[:, 1:2].astype(np.uint32) * 3 + 1) * np.arange(
        1, W + 1, dtype=np.uint32
    )


class _Cluster:
    """N real-KV NetServers, optionally chaos-proxied, with kill /
    cold-restore per endpoint; endpoint factories track the live port."""

    def __init__(self, n: int, seed: int = 0, rates: dict | None = None):
        self.n = n
        self.seed = seed
        self.rates = rates
        self.kvs: list[KV | None] = [None] * n
        self.servers: list[NetServer | None] = [None] * n
        self.proxies: list[ChaosProxy | None] = [None] * n
        self.ports = [0] * n
        for i in range(n):
            self._bring_up(i)

    def _bring_up(self, i: int) -> None:
        kv = KV(CFG)
        srv = NetServer(lambda kv=kv: DirectBackend(kv)).start()
        self.kvs[i] = kv
        self.servers[i] = srv
        port = srv.port
        if self.rates is not None:
            px = ChaosProxy("127.0.0.1", srv.port,
                            seed=self.seed * 97 + i, rates=self.rates,
                            delay_s=0.02, reorder_wait_s=0.05)
            self.proxies[i] = px
            port = px.port
        self.ports[i] = port

    def kill(self, i: int) -> None:
        if self.servers[i] is not None:
            self.servers[i].stop()
            self.servers[i] = None
        if self.proxies[i] is not None:
            self.proxies[i].close()
            self.proxies[i] = None
        self.kvs[i] = None

    def restore(self, i: int) -> None:
        """COLD restore: a crashed clean-cache server lost everything."""
        self.kill(i)
        self._bring_up(i)

    def endpoint(self, i: int, **kw) -> ReconnectingClient:
        def factory(i=i):
            # op timeout generous enough for a first-compile of a new
            # batch width on a cold CPU cache (kills surface as refused
            # connections, not timeouts, so drills stay fast)
            return TcpBackend("127.0.0.1", self.ports[i], page_words=W,
                              keepalive_s=None, op_timeout_s=10.0, **kw)

        return ReconnectingClient(factory, page_words=W,
                                  retry_delay_s=0.005,
                                  max_retry_delay_s=0.05,
                                  seed=self.seed * 31 + i)

    def group(self, cfg: ReplicaConfig, seed: int = 0) -> ReplicaGroup:
        return ReplicaGroup([self.endpoint(i) for i in range(self.n)],
                            page_words=W, cfg=cfg, seed=seed)

    def close(self) -> None:
        for i in range(self.n):
            self.kill(i)


_FAST_CFG = ReplicaConfig(
    n_replicas=3, rf=2, hedge_ms=50.0,
    breaker_failures=3, breaker_cooldown_s=0.05,
    breaker_max_cooldown_s=0.4, repair_interval_s=0.0,  # manual ticks
    repair_batch=64,
)


def _drain_repair(g: ReplicaGroup, deadline_s: float = 5.0) -> None:
    """Drive manual repair ticks until the backlog drains (bounded)."""
    end = time.time() + deadline_s
    while time.time() < end:
        g.repair_tick()
        if not g._repair_pending:
            return
        time.sleep(0.01)


def test_replica_map_stable_spread_and_distinct():
    """The key→replica-set map is deterministic, spreads primaries
    across all endpoints, and each set has rf DISTINCT members."""
    g = ReplicaGroup([LocalBackend(W) for _ in range(5)], page_words=W,
                     cfg=ReplicaConfig(n_replicas=5, rf=3,
                                       repair_interval_s=0))
    try:
        keys = _keys(512, seed=7)
        m1 = g._members(keys)
        m2 = g._members(keys)
        assert (m1 == m2).all()
        assert m1.shape == (512, 3)
        for row in m1[:64]:
            assert len(set(row.tolist())) == 3
        primaries = np.bincount(m1[:, 0], minlength=5)
        assert (primaries > 0).all(), primaries
    finally:
        g.close()


def test_breaker_state_machine():
    """closed → open at the failure threshold (shedding while open) →
    half-open after the cooldown → one probe failure re-opens with a
    WIDENED cooldown → a probe success closes and resets."""
    br = CircuitBreaker(failures_to_open=3, cooldown_s=0.05,
                        max_cooldown_s=1.0, backoff=2.0, jitter=0.0,
                        half_open_probes=1, seed=0)
    assert br.state == "closed" and br.allow()
    br.record_failure("timeout")
    br.record_failure("bad_frame")
    assert br.state == "closed"
    br.record_success()  # a success resets the streak
    for _ in range(2):
        br.record_failure("timeout")
    assert br.state == "closed"
    br.record_failure("digest")
    assert br.state == "open"
    assert not br.allow() and br.stats["shed_ops"] >= 1
    time.sleep(0.06)
    assert br.ready()  # half-open, probe available (non-consuming)
    assert br.state == "half_open"
    assert br.allow()        # consumes the probe slot
    assert not br.allow()    # budget spent
    br.record_failure("timeout")  # failed probe: re-open, wider cooldown
    assert br.state == "open" and br.stats["reopens"] == 1
    time.sleep(0.06)
    assert br.state == "open", "cooldown did not widen on reopen"
    time.sleep(0.06)
    assert br.allow()
    br.record_success()
    assert br.state == "closed" and br.stats["closes"] == 1
    assert br.stats["timeouts"] == 4 and br.stats["bad_frames"] == 1
    assert br.stats["digest_mismatches"] == 1


def test_fanout_put_get_invalidate_local():
    """PUT lands on exactly rf members; GET serves; invalidate removes
    from every member (LocalBackend cluster — hermetic, no sockets)."""
    eps = [LocalBackend(W) for _ in range(3)]
    cfg = ReplicaConfig(n_replicas=3, rf=2, repair_interval_s=0)
    with ReplicaGroup(eps, page_words=W, cfg=cfg) as g:
        keys = _keys(128, seed=3)
        pages = _pages(keys)
        g.put(keys, pages)
        assert sum(len(e._store) for e in eps) == 2 * 128
        out, found = g.get(keys)
        assert found.all() and (out == pages).all()
        hit = g.invalidate(keys)
        assert hit.all()
        assert sum(len(e._store) for e in eps) == 0
        out, found = g.get(keys)
        assert not found.any()


def test_kill_one_server_failover_serves_and_breaker_opens():
    """One server dies mid-traffic: every GET still serves (rf=2 ⇒ a
    live member exists for every key), the dead endpoint's breaker
    opens within `breaker_failures` ops, and no op raises."""
    cl = _Cluster(3, seed=11)
    g = cl.group(_FAST_CFG, seed=11)
    try:
        keys = _keys(192, seed=11)
        pages = _pages(keys)
        g.put(keys, pages)
        out, found = g.get(keys)
        assert found.all() and (out == pages).all()

        cl.kill(0)
        for _ in range(_FAST_CFG.breaker_failures):
            out, found = g.get(keys)  # must not raise
            assert (out[found] == pages[found]).all()
        assert g.breakers[0].state == "open", \
            "breaker did not open within the configured threshold"
        # with the breaker open the dead endpoint is routed AROUND:
        # every key still serves from its surviving member
        out, found = g.get(keys)
        assert found.all(), f"{int((~found).sum())} keys lost with rf=2"
        assert (out == pages).all()
        assert g.counters["failover_gets"] > 0
    finally:
        g.close()
        cl.close()


@pytest.mark.slow  # tier-1 budget: heavy drill rides the slow tier (PR 16)
def test_hedged_get_fires_on_slow_primary():
    """A slow (not dead) primary: the hedge fires after `hedge_ms`, the
    secondary serves every key, and the slow primary's in-flight answer
    is ABANDONED — the tail is bounded by the hedge deadline plus the
    fast replica's round trip, not by the slow replica."""
    cl = _Cluster(3, seed=23, rates={})  # proxies, no random faults
    cfg = ReplicaConfig(n_replicas=3, rf=2, hedge_ms=40.0,
                        breaker_failures=10, repair_interval_s=0)
    g = cl.group(cfg, seed=23)
    try:
        keys = _keys(96, seed=23)
        pages = _pages(keys)
        g.put(keys, pages)
        # keys whose PRIMARY is endpoint 0 — only its proxy gets slowed
        sub = keys[np.asarray(g._members(keys))[:, 0] == 0]
        assert len(sub) >= 8
        good = _pages(sub)
        _ = g.get(sub)  # warm: connections up, widths compiled
        cl.proxies[0].delay_next(8, seconds=0.6)
        t0 = time.monotonic()
        out, found = g.get(sub)
        dt = time.monotonic() - t0
        assert found.all() and (out == good).all()
        assert g.counters["hedges_fired"] >= 1
        # one armed delay is 0.6 s; serving under it proves the hedge
        # answered and the slow primary was not waited out
        assert dt < 0.55, f"hedged GET took {dt:.2f}s"
    finally:
        g.close()
        cl.close()


def test_rejoin_triggers_bloom_guided_repair():
    """Kill a replica, keep writing, restore it COLD: once its breaker
    closes, anti-entropy repair re-replicates the keys it owns but lost
    (bloom-guided, digest-verified) — the rejoined server itself then
    holds byte-correct pages for its share of the journal."""
    cl = _Cluster(3, seed=31)
    g = cl.group(_FAST_CFG, seed=31)
    try:
        keys = _keys(192, seed=31)
        pages = _pages(keys)
        g.put(keys[:96], pages[:96])

        cl.kill(1)
        # writes continue while 1 is down (its copies are being missed)
        for _ in range(_FAST_CFG.breaker_failures):
            g.put(keys[96:], pages[96:])
        assert g.breakers[1].state == "open"

        cl.restore(1)  # cold: fresh KV, empty bloom
        # drive ops until the half-open probe closes the breaker
        deadline = time.time() + 5
        while g.breakers[1].state != "closed" and time.time() < deadline:
            g.get(keys[:16])
            time.sleep(0.01)
        assert g.breakers[1].state == "closed", "rejoin never probed in"

        _drain_repair(g)
        assert g.counters["repair_pages"] > 0
        assert g.counters["repair_rounds"] >= 1

        # the rejoined server ITSELF now holds its share: every journal
        # key owned by endpoint 1 serves from kv[1] with correct bytes
        owned = (g._members(keys) == 1).any(axis=1)
        out, found = cl.kvs[1].get(keys[owned])
        assert found.all(), \
            f"{int((~found).sum())}/{int(owned.sum())} owned keys not repaired"
        assert (out == pages[owned]).all()
    finally:
        g.close()
        cl.close()


def test_all_replicas_down_is_a_legal_miss():
    """Replica-set exhausted → the fifth ladder rung: GETs are misses,
    PUTs drop, invalidates report False — never an exception."""
    cl = _Cluster(2, seed=41)
    cfg = ReplicaConfig(n_replicas=2, rf=2, breaker_failures=2,
                        breaker_cooldown_s=0.05, repair_interval_s=0)
    g = cl.group(cfg, seed=41)
    try:
        keys = _keys(32, seed=41)
        pages = _pages(keys)
        g.put(keys, pages)
        cl.close()  # every server dies
        for _ in range(cfg.breaker_failures + 1):
            out, found = g.get(keys)
        assert not found.any() and (out == 0).all()
        g.put(keys, pages)          # legal drop
        hit = g.invalidate(keys)    # legal no-op
        assert not hit.any()
        assert g.counters["load_shed_gets"] > 0
    finally:
        g.close()


def _storm(g: ReplicaGroup, keys, pages, steps: int, seed: int,
           on_step=None) -> dict:
    """Seeded mixed put/get storm; returns hit-rate + wrong-byte stats.
    The loop finishing without an exception IS invariant 1."""
    rng = np.random.default_rng(seed)
    stats = {"gets": 0, "hits": 0, "wrong_bytes": 0}
    for step in range(steps):
        if on_step is not None:
            on_step(step)
        op = rng.integers(4)
        lo = int(rng.integers(0, len(keys) - 16))
        n = int(rng.integers(1, 16))
        sel = slice(lo, lo + n)
        if op == 0:
            g.put(keys[sel], pages[sel])
        else:
            out, found = g.get(keys[sel])
            stats["gets"] += n
            stats["hits"] += int(found.sum())
            good = pages[sel]
            stats["wrong_bytes"] += int(
                (out[found] != good[found]).any(axis=1).sum())
    return stats


@pytest.mark.slow  # tier-1 budget: heavy drill rides the slow tier (PR 16)
def test_rolling_kill_restore_drill():
    """THE acceptance drill (n_replicas=3, rf=2): a seeded storm with a
    rolling one-server-down schedule. Hit-rate ≥ 80% of the identical
    no-fault run, zero wrong bytes, zero exceptions, repair fires and
    the post-repair tail recovers."""
    steps = 240
    keys = _keys(224, seed=55)
    pages = _pages(keys)

    # no-fault reference run (same seed, same schedule)
    cl0 = _Cluster(3, seed=55)
    g0 = cl0.group(_FAST_CFG, seed=55)
    try:
        g0.put(keys, pages)
        base = _storm(g0, keys, pages, steps, seed=55)
    finally:
        g0.close()
        cl0.close()
    assert base["wrong_bytes"] == 0
    base_rate = base["hits"] / max(1, base["gets"])

    # fault run: one server down at any instant, rotating; each victim
    # cold-restores before the next kill, with repair ticks in between
    cl = _Cluster(3, seed=55)
    g = cl.group(_FAST_CFG, seed=55)
    try:
        g.put(keys, pages)
        schedule = {30: ("kill", 0), 90: ("restore", 0),
                    120: ("kill", 1), 180: ("restore", 1)}

        def on_step(step):
            act = schedule.get(step)
            if act is not None:
                getattr(cl, act[0])(act[1])
                if act[0] == "restore":
                    # healing barrier: the drill's premise is ONE server
                    # down at any instant — the storm steps are so fast
                    # that the next kill could otherwise land while this
                    # victim is still cold/breaker-open (two overlapping
                    # loss windows), which is a different (rf-exceeded)
                    # fault class. Probe until the breaker closes, then
                    # drain repair, so kill windows never overlap.
                    i = act[1]
                    deadline = time.time() + 5
                    while (g.breakers[i].state != "closed"
                           and time.time() < deadline):
                        g.get(keys[:8])
                        time.sleep(0.01)
                    _drain_repair(g)
            g.repair_tick()

        faulted = _storm(g, keys, pages, steps, seed=55, on_step=on_step)
        assert faulted["wrong_bytes"] == 0, "wrong bytes under faults"
        rate = faulted["hits"] / max(1, faulted["gets"])
        assert rate >= 0.8 * base_rate, \
            f"hit-rate floor broken: {rate:.3f} < 0.8*{base_rate:.3f}"
        assert g.breakers[0].stats["opens"] >= 1
        # rejoined replicas were repaired
        _drain_repair(g)
        assert g.counters["repair_pages"] > 0
        # post-repair recovery: the full key set serves again
        out, found = g.get(keys)
        assert (out[found] == pages[found]).all()
        assert found.mean() >= base_rate - 0.05, \
            f"post-repair hit-rate did not recover ({found.mean():.3f})"
    finally:
        g.close()
        cl.close()


@pytest.mark.slow
def test_multi_endpoint_chaos_soak():
    """Rolling kill/restore UNDER per-replica chaos (seeded net-level
    faults on every endpoint) — the long multi-endpoint analog of
    `test_chaos.test_chaos_soak_long`: no exception, zero wrong bytes,
    faults actually fired, repair still heals the rejoined replicas."""
    rates = {"flip": 0.02, "truncate": 0.01, "duplicate": 0.02,
             "delay": 0.01}
    keys = _keys(224, seed=77)
    pages = _pages(keys)
    cl = _Cluster(3, seed=77, rates=rates)
    cfg = ReplicaConfig(n_replicas=3, rf=2, hedge_ms=30.0,
                        breaker_failures=4, breaker_cooldown_s=0.05,
                        breaker_max_cooldown_s=0.4,
                        repair_interval_s=0.0, repair_batch=64)
    g = cl.group(cfg, seed=77)
    try:
        g.put(keys, pages)
        schedule = {60: ("kill", 2), 200: ("restore", 2),
                    280: ("kill", 0), 420: ("restore", 0)}

        def on_step(step):
            act = schedule.get(step)
            if act is not None:
                getattr(cl, act[0])(act[1])
            g.repair_tick()

        s = _storm(g, keys, pages, 520, seed=77, on_step=on_step)
        assert s["wrong_bytes"] == 0
        assert s["hits"] > 0
        fired = sum(
            sum(v for k, v in px.stats.items()
                if k.endswith("_frames") and k != "forwarded_frames")
            for px in cl.proxies if px is not None)
        assert fired > 0, "chaos never landed"
        _drain_repair(g)
        assert g.counters["repair_pages"] > 0
        out, found = g.get(keys)
        assert (out[found] == pages[found]).all()
    finally:
        g.close()
        cl.close()
