"""Test env: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; sharding tests run over
`--xla_force_host_platform_device_count=8` on CPU (same trick the driver's
`dryrun_multichip` uses). Must run before any jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
