"""Test env: force an 8-device virtual CPU mesh, never touch the TPU tunnel.

Multi-chip hardware is unavailable in CI; sharding tests run over
`--xla_force_host_platform_device_count=8` on CPU (same trick the driver's
`dryrun_multichip` uses).

Note: the environment's sitecustomize may register an experimental remote-TPU
("axon") PJRT plugin and force `jax_platforms=axon,cpu` via `jax.config`,
which overrides the JAX_PLATFORMS env var and makes the first `jax.devices()`
block on the remote tunnel. Backend init is lazy, so re-pinning the config to
"cpu" here — before any test triggers backend creation — keeps the whole
suite hermetic and offline.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache (disable with PMDFC_COMPILE_CACHE=0).
# Cuts the full suite 990s -> ~400s warm and composes with the per-module
# clear_caches fixture below: executables drop from MEMORY each module
# (bounding the map count) and reload from DISK in milliseconds. A day of
# wandering full-suite segfaults was initially pinned on this cache, but
# bisection exonerated it — the real cause was vm.max_map_count
# exhaustion (see the fixture); crashes occurred with the cache off too.
# The atomic-write and single-device-only patches below stay as hardening.
if os.environ.get("PMDFC_COMPILE_CACHE", "1") != "0":
    _cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# jax's LRUCache.put writes entries with a bare write_bytes: a process
# killed mid-write (CI timeouts, wedged-tunnel kills) leaves a TRUNCATED
# entry on disk, and the XLA executable deserializer SEGFAULTS reading it
# on a later run (observed twice in full-suite runs). Write-to-temp +
# atomic rename means readers only ever see whole entries; concurrent
# same-key writers both produce valid files and the last rename wins.
import jax._src.lru_cache as _lru  # noqa: E402

_orig_put = _lru.LRUCache.put


def _atomic_put(self, key, val):
    if self.eviction_enabled:  # locked path handles its own bookkeeping
        return _orig_put(self, key, val)
    if not key:
        raise ValueError("key cannot be empty")
    cache_path = self.path / f"{key}{_lru._CACHE_SUFFIX}"
    if cache_path.exists():
        return
    tmp = cache_path.with_name(cache_path.name + f".tmp{os.getpid()}")
    try:
        tmp.write_bytes(val)
        os.replace(tmp, cache_path)
    except OSError:
        try:
            tmp.unlink()
        except OSError:
            pass


_lru.LRUCache.put = _atomic_put

# jaxlib 0.9's executable (de)serializer SEGFAULTS on multi-device CPU
# executables (observed on both the write path — executable.serialize() —
# and the read path, always under the 8-device shard_map programs). Skip
# the persistent cache for anything spanning >1 device; single-device
# programs carry most of the suite's compile time anyway.
import jax._src.compilation_cache as _cc  # noqa: E402

_orig_put_exec = _cc.put_executable_and_time


def _single_device_put_exec(cache_key, module_name, executable, backend,
                            compile_time):
    try:
        ndev = len(executable.local_devices())
    except Exception:  # noqa: BLE001 — be conservative, skip caching
        return
    if ndev > 1:
        return
    return _orig_put_exec(cache_key, module_name, executable, backend,
                          compile_time)


_cc.put_executable_and_time = _single_device_put_exec

import pytest  # noqa: E402


def _ensure_map_headroom() -> bool:
    """Raise vm.max_map_count if this process may (root containers).

    jax's in-process executable cache grows monotonically; a full-suite run
    accumulates >65k memory mappings (JIT code pages + buffers), crosses
    the kernel's 65530 default, and the next mmap failure SEGFAULTS inside
    XLA's compiler — observed as wandering crashes at ~90% of every full
    run once the suite grew past the limit. Peak measured: 64 890 maps.
    """
    path = "/proc/sys/vm/max_map_count"
    try:
        if int(open(path).read()) < 262144:
            open(path, "w").write("262144")
        return int(open(path).read()) >= 200000
    except OSError:
        return False


_MAP_HEADROOM = _ensure_map_headroom()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Fallback when the kernel ceiling could not be raised: drop compiled
    executables after each module, keeping the map count sawtoothing near
    32k (far under 65530). Costs ~1-2 min of recompiles-from-disk per full
    run, so it only runs when actually needed."""
    yield
    if not _MAP_HEADROOM:
        jax.clear_caches()
