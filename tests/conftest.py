"""Test env: force an 8-device virtual CPU mesh, never touch the TPU tunnel.

Multi-chip hardware is unavailable in CI; sharding tests run over
`--xla_force_host_platform_device_count=8` on CPU (same trick the driver's
`dryrun_multichip` uses).

Note: the environment's sitecustomize may register an experimental remote-TPU
("axon") PJRT plugin and force `jax_platforms=axon,cpu` via `jax.config`,
which overrides the JAX_PLATFORMS env var and makes the first `jax.devices()`
block on the remote tunnel. Backend init is lazy, so re-pinning the config to
"cpu" here — before any test triggers backend creation — keeps the whole
suite hermetic and offline.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: one source of truth in bench/common —
# cuts the full suite 990s -> ~400s warm, shared with the bench harnesses
# so agenda runs amortize remote compiles. Includes atomic entry writes
# and single-device-only serialization (see the helper's docstring).
# Disable with PMDFC_COMPILE_CACHE=0. A day of wandering full-suite
# segfaults was initially pinned on this cache, but bisection exonerated
# it — the real cause was vm.max_map_count exhaustion (see below).
from pmdfc_tpu.bench.common import enable_compile_cache  # noqa: E402

enable_compile_cache()

import pytest  # noqa: E402


def _ensure_map_headroom() -> bool:
    """Raise vm.max_map_count if this process may (root containers).

    jax's in-process executable cache grows monotonically; a full-suite run
    accumulates >65k memory mappings (JIT code pages + buffers), crosses
    the kernel's 65530 default, and the next mmap failure SEGFAULTS inside
    XLA's compiler — observed as wandering crashes at ~90% of every full
    run once the suite grew past the limit. Peak measured: 64 890 maps.

    Host-wide kernel sysctl: opt out with PMDFC_RAISE_MAP_COUNT=0 (the
    per-module jax.clear_caches() fallback below then bounds the map count
    instead, at ~1-2 min of recompiles per full run); any mutation is
    logged to stderr (round-3 advisor finding: silent side effect).
    """
    import sys

    path = "/proc/sys/vm/max_map_count"
    try:
        before = int(open(path).read())
        if (before < 262144
                and os.environ.get("PMDFC_RAISE_MAP_COUNT", "1") != "0"):
            open(path, "w").write("262144")
            print(f"[conftest] raised vm.max_map_count {before} -> 262144 "
                  "(host-wide; PMDFC_RAISE_MAP_COUNT=0 to disable)",
                  file=sys.stderr)
        # opt-out guards only the WRITE: a host that already has headroom
        # (pre-raised by its operator) must not pay the clear_caches fallback
        return int(open(path).read()) >= 200000
    except OSError:
        return False


_MAP_HEADROOM = _ensure_map_headroom()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Fallback when the kernel ceiling could not be raised: drop compiled
    executables after each module, keeping the map count sawtoothing near
    32k (far under 65530). Costs ~1-2 min of recompiles-from-disk per full
    run, so it only runs when actually needed."""
    yield
    if not _MAP_HEADROOM:
        jax.clear_caches()
