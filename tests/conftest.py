"""Test env: force an 8-device virtual CPU mesh, never touch the TPU tunnel.

Multi-chip hardware is unavailable in CI; sharding tests run over
`--xla_force_host_platform_device_count=8` on CPU (same trick the driver's
`dryrun_multichip` uses).

Note: the environment's sitecustomize may register an experimental remote-TPU
("axon") PJRT plugin and force `jax_platforms=axon,cpu` via `jax.config`,
which overrides the JAX_PLATFORMS env var and makes the first `jax.devices()`
block on the remote tunnel. Backend init is lazy, so re-pinning the config to
"cpu" here — before any test triggers backend creation — keeps the whole
suite hermetic and offline.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite compiles hundreds of (program,
# shape) pairs; re-runs should pay milliseconds, not minutes. Keyed by
# everything that affects lowering, so it is safe across code edits; the
# directory is gitignored.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
