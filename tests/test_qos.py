"""Multi-tenant QoS control plane suite (`runtime/qos.py`).

Covers the plane layer by layer against deterministic fixtures — the
soak-scale antagonist drills ride the `qos_smoke` agenda step
(`bench/qos_soak.py --smoke`), the tier-budget discipline:

- namespace tagging: `tag_oids`/`tenant_of` roundtrip bit-exactly,
  preserve the oid payload, and agree with the client edge's inlined
  `CleanCacheClient._tag` (the two implementations must never fork).
- token-bucket edge admission: all-or-nothing takes, burst cap,
  rate 0 = unlimited (operator intent), live `set_rate`.
- DRR drain: service composition follows the declared weights
  deterministically; an emptied lane forfeits its residue.
- shed ladder: lowest-priority lane sheds first, newest ops first,
  non-sheddable ops (HANDOFF-class) survive, and depth lands exactly
  one below the threshold.
- `miss_shed` attribution: `KV.account_shed`/`ShardedKV.account_shed`
  keep `misses == sum of causes` bit-exact on every stats surface, and
  an end-to-end wire drill over a real NetServer sheds a rate-limited
  tenant deterministically with the live teledump passing
  `tools/check_teledump.py` including the `check_qos` lane pins.
- `PMDFC_QOS=off` conformance: a server built WITH a QosConfig carries
  no plane, no tenant scope, and serves verb-for-verb on the FIFO
  path; the client edge stops tagging.
- autotune: `qos_rate_t<tid>` knobs register only for rate-limited
  tenants, with the declared or derived envelope.
- concurrency discipline: the new lock is ranked in the sanitizer
  HIERARCHY between the flush cv and the TCP conn lock, and
  `runtime/qos.py` is a ranked module for `tools/analyze`.
"""

import numbers
import os
import sys
import types

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, LocalBackend
from pmdfc_tpu.client.cleancache import CleanCacheClient
from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              NetConfig, QosConfig, TelemetryConfig,
                              TenantConfig)
from pmdfc_tpu.kv import KV, MISS_CAUSE_NAMES
from pmdfc_tpu.runtime import qos
from pmdfc_tpu.runtime import telemetry as tele
from pmdfc_tpu.runtime.net import NetServer, TcpBackend

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pytestmark = pytest.mark.qos

W = 16  # page words — tiny pages keep socket traffic fast


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
        W, dtype=np.uint32)


def _op(tid=0, count=1, mt=5, shed_ok=True):
    return types.SimpleNamespace(tid=tid, count=count, mt=mt,
                                 shed_ok=shed_ok)


def _plane(cfg):
    tele.configure(TelemetryConfig())
    return qos.QosPlane(cfg, "t")


# -- namespace tagging -------------------------------------------------


def test_tag_roundtrip_and_payload_preserved():
    oids = np.array([0, 1, 0x0FFF_FFFF, 12345], np.uint32)
    for tid in (0, 1, 7, 15):
        tagged = qos.tag_oids(oids, tid, 4)
        assert (np.asarray(qos.tenant_of(tagged, 4)) == tid).all()
        # payload bits survive the tag
        assert ((tagged & np.uint32(0x0FFF_FFFF)) == oids).all()
    with pytest.raises(ValueError):
        qos.tag_oids(oids, 16, 4)  # tid does not fit the prefix


def test_client_tag_agrees_with_plane_tag():
    oids = _keys(64, seed=3)[:, 0] & np.uint32(0x0FFF_FFFF)
    cc = CleanCacheClient(LocalBackend(page_words=W, capacity=1 << 10),
                          tenant=5, tenant_bits=4)
    np.testing.assert_array_equal(
        cc._tag(oids), qos.tag_oids(oids, 5, 4))


def test_untagged_and_unregistered_resolve_to_default():
    plane = _plane(QosConfig(tenant_bits=4, tenants=(
        TenantConfig(tid=3),)))
    assert plane.resolve(None) == 0
    assert plane.resolve(np.zeros((0,), np.uint32)) == 0
    untagged = np.array([[123, 4]], np.uint32)
    assert plane.resolve(untagged) == 0
    tagged = untagged.copy()
    tagged[:, 0] = qos.tag_oids(tagged[:, 0], 3, 4)
    assert plane.resolve(tagged) == 3
    stranger = untagged.copy()  # tagged with an unregistered tid
    stranger[:, 0] = qos.tag_oids(stranger[:, 0], 9, 4)
    assert plane.resolve(stranger) == 0


# -- token bucket ------------------------------------------------------


def test_token_bucket_all_or_nothing_and_unlimited():
    b = qos.TokenBucket(rate=1.0, burst=4)
    assert b.take(4)           # burst drains whole
    assert not b.take(1)       # empty: refill is 1 token/s
    assert not b.take(8)       # larger than burst: can never succeed
    free = qos.TokenBucket(rate=0.0, burst=1)
    for _ in range(100):
        assert free.take(1 << 20)  # rate 0 = unlimited
    assert b.set_rate(25.0) == 25.0
    assert b.rate() == 25.0
    assert b.set_rate(-5.0) == 0.0  # clamps to the unlimited floor


# -- DRR drain ---------------------------------------------------------


def test_drr_composition_follows_weights():
    plane = _plane(QosConfig(tenant_bits=4, quantum_ops=4, tenants=(
        TenantConfig(tid=1, weight=3), TenantConfig(tid=2, weight=1))))
    for _ in range(50):
        plane.stage(_op(tid=1))
        plane.stage(_op(tid=2))
    out = plane.drain(16)
    got = np.bincount([o.tid for o in out], minlength=3)
    # one visit each: w3 lane credits 12 page-units, w1 lane credits 4
    assert (got[1], got[2]) == (12, 4)
    assert plane.depth() == 100 - 16
    rest = plane.drain(1 << 20)  # drains dry; depth reconciles
    assert plane.depth() == 0 and len(rest) == 84


def test_drr_serves_whole_ops_and_repays_debt():
    plane = _plane(QosConfig(tenant_bits=4, quantum_ops=2, tenants=(
        TenantConfig(tid=1, weight=1),)))
    plane.stage(_op(tid=1, count=64))  # one giant verb
    plane.stage(_op(tid=1, count=1))
    out = plane.drain(1)
    assert len(out) == 1 and out[0].count == 64  # served whole
    assert plane.drain(1)[0].count == 1  # debt repays, lane continues
    assert plane.depth() == 0


# -- shed ladder -------------------------------------------------------


def test_shed_ladder_lowest_priority_newest_first():
    plane = _plane(QosConfig(
        tenant_bits=4, shed_threshold=8, shed_batch=16, tenants=(
            TenantConfig(tid=1, priority=2),
            TenantConfig(tid=2, priority=1))))
    for i in range(6):
        plane.stage(_op(tid=1, count=1))
        plane.stage(_op(tid=2, count=10 + i))  # count marks arrival order
    victims = plane.shed_overflow(lambda op: op.shed_ok)
    # depth 12, threshold 8 -> shed 5, all from the priority-1 lane,
    # newest first; the compliant lane is untouched
    assert [v.tid for v in victims] == [2] * 5
    assert [v.count for v in victims] == [15, 14, 13, 12, 11]
    assert plane.depth() == 7
    survivors = plane.drain(1 << 20)
    assert sum(1 for o in survivors if o.tid == 1) == 6
    assert [o.count for o in survivors if o.tid == 2] == [10]


def test_shed_ladder_spares_nonsheddable_ops():
    plane = _plane(QosConfig(
        tenant_bits=4, shed_threshold=2, shed_batch=16, tenants=(
            TenantConfig(tid=2, priority=1),)))
    handoff = _op(tid=2, count=1, shed_ok=False)
    plane.stage(handoff)
    for _ in range(4):
        plane.stage(_op(tid=2, count=1))
    victims = plane.shed_overflow(lambda op: op.shed_ok)
    assert handoff not in victims  # HANDOFF-class ops never shed
    assert all(v.shed_ok for v in victims)
    assert handoff in plane.drain(1 << 20)


# -- miss_shed attribution --------------------------------------------


def _cause_sum(st):
    return sum(int(st[k]) for k in MISS_CAUSE_NAMES)


def test_kv_account_shed_keeps_causes_exact():
    kv = KV(KVConfig(index=IndexConfig(capacity=1 << 10),
                     bloom=BloomConfig(num_bits=1 << 13),
                     paged=True, page_words=W))
    keys = _keys(32)
    kv.insert(keys, _pages(keys))
    kv.get(_keys(16, seed=9))  # real cold misses ride along
    kv.account_shed(gets=5, puts=2)
    st = kv.stats()
    assert st["miss_shed"] == 5
    assert st["drops"] >= 2
    assert st["misses"] == _cause_sum(st)


def test_sharded_account_shed_keeps_causes_exact():
    from pmdfc_tpu.parallel import ShardedKV

    skv = ShardedKV(KVConfig(index=IndexConfig(capacity=1 << 12),
                             bloom=BloomConfig(num_bits=1 << 15),
                             paged=False))
    skv.account_shed(gets=3, puts=1)
    st = skv.stats()
    assert st["miss_shed"] == 3
    assert st["misses"] == _cause_sum(st)
    rep = skv.shard_report()
    assert sum(rep["stats"]["miss_shed"]) == 3
    assert sum(rep["stats"]["misses"]) == sum(
        sum(rep["stats"][k]) for k in MISS_CAUSE_NAMES)


@pytest.mark.slow  # ~6 s NetServer drill: rides agenda `tier1_overflow`
def test_wire_shed_drill_end_to_end():
    """A rate-limited tenant sheds DETERMINISTICALLY at the edge (its
    verbs exceed the bucket burst, so no refill timing can admit
    them); every shed is attributed to miss_shed on the KV stats AND
    the wire doc, the compliant (untagged) tenant is untouched, and
    the live teledump passes the full checker chain."""
    tele.configure(TelemetryConfig(enabled=True))
    kv = KV(KVConfig(index=IndexConfig(capacity=1 << 12),
                     bloom=BloomConfig(num_bits=1 << 13),
                     paged=True, page_words=W))
    qcfg = QosConfig(tenant_bits=4, tenants=(
        TenantConfig(tid=2, rate_ops_per_s=1.0, burst_ops=4),))
    srv = NetServer(lambda: DirectBackend(kv), net=NetConfig(),
                    qos=qcfg).start()
    try:
        assert srv.qos_plane() is not None
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            good = _keys(64, seed=1)
            be.put(good, _pages(good))
            _, found = be.get(good)
            assert found.all()  # compliant traffic fully served
            bad = _keys(24, seed=2)
            bad[:, 0] = qos.tag_oids(bad[:, 0], 2, 4)
            be.put(bad[:8], _pages(bad[:8]))  # 8 pages > burst 4: shed
            for i in range(3):
                _, found = be.get(bad[i * 8:(i + 1) * 8])
                assert not found.any()  # shed GETs answer NOTEXIST
            doc = be.server_stats()
        st = kv.stats()
        assert st["miss_shed"] == 24
        assert st["drops"] >= 8  # the shed PUT pages
        assert st["misses"] == _cause_sum(st)
        assert int(doc["miss_shed"]) == 24
        assert int(doc["misses"]) == sum(
            int(doc[k]) for k in MISS_CAUSE_NAMES)
        sc = dict(srv.qos_plane().scope(2))
        assert sc["ops"] == 4 and sc["shed_edge"] == 4
        assert sc["staged"] == 0 and sc["shed_ladder"] == 0
        assert sc["shed_gets"] == 3 and sc["shed_puts"] == 1
        assert dict(srv.qos_plane().scope(0))["shed_edge"] == 0
        from tools.check_teledump import check
        assert check(doc) == []
    finally:
        srv.stop()


# -- check_qos pins ----------------------------------------------------


def _snap(ops=10, staged=7, shed_edge=3, shed_ladder=2, shed_gets=4,
          shed_puts=1, weight=3, rate=100.0):
    pfx = "net.server.qos.t2."
    return {
        "counters": {pfx + "ops": ops, pfx + "staged": staged,
                     pfx + "shed_edge": shed_edge,
                     pfx + "shed_ladder": shed_ladder,
                     pfx + "shed_gets": shed_gets,
                     pfx + "shed_puts": shed_puts},
        "gauges": {pfx + "weight": weight, pfx + "rate": rate},
    }


def test_check_qos_accepts_consistent_lanes():
    from tools.check_teledump import check_qos

    assert check_qos(_snap()) == []
    assert check_qos({"counters": {}, "gauges": {}}) == []


@pytest.mark.parametrize("mutate, needle", [
    (dict(ops=11), "conservation"),
    (dict(shed_ladder=8), "shed"),
    (dict(shed_gets=1), "shed_gets"),
    (dict(weight=0), "weight"),
    (dict(rate=-1.0), "rate"),
])
def test_check_qos_rejects_drift(mutate, needle):
    from tools.check_teledump import check_qos

    errs = check_qos(_snap(**mutate))
    assert errs, f"drift {mutate} not caught"
    assert any(needle in e or "drift" in e for e in errs)


def test_check_qos_rejects_straggler_lanes():
    from tools.check_teledump import check_qos

    snap = _snap()
    del snap["counters"]["net.server.qos.t2.shed_ladder"]
    assert any("travel together" in e for e in check_qos(snap))


def test_miss_shed_in_cause_taxonomy():
    from tools.check_teledump import _MISS_CAUSES

    assert "miss_shed" in _MISS_CAUSES
    assert "miss_shed" in MISS_CAUSE_NAMES


# -- PMDFC_QOS=off conformance ----------------------------------------


@pytest.mark.slow  # ~5 s NetServer drill: rides agenda `tier1_overflow`
def test_qos_off_is_single_tenant_fifo(monkeypatch):
    monkeypatch.setenv("PMDFC_QOS", "off")
    tele.configure(TelemetryConfig(enabled=True))
    qcfg = QosConfig(tenant_bits=4, tenants=(
        TenantConfig(tid=2, rate_ops_per_s=1.0, burst_ops=1),))
    shared = LocalBackend(page_words=W, capacity=1 << 12)
    srv = NetServer(lambda: shared, net=NetConfig(), qos=qcfg).start()
    try:
        assert srv._qos is None  # resolved at construction: no plane
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            keys = _keys(32, seed=4)
            keys[:, 0] = qos.tag_oids(keys[:, 0], 2, 4)
            be.put(keys, _pages(keys))  # the throttle must NOT apply
            _, found = be.get(keys)
            assert found.all()
            doc = be.server_stats()
        snap = doc.get("telemetry") or {}
        assert not any(".qos.t" in k
                       for k in (snap.get("counters") or {}))
        assert not any(".qos.t" in k
                       for k in (snap.get("gauges") or {}))
    finally:
        srv.stop()
    # the client edge stops tagging too: untenanted wire bytes
    cc = CleanCacheClient(LocalBackend(page_words=W, capacity=1 << 10),
                          tenant=5, tenant_bits=4)
    oids = np.array([1, 2, 3], np.uint32)
    np.testing.assert_array_equal(cc._tag(oids), oids)


# -- autotune knob registration ---------------------------------------


def test_autotune_registers_rate_limited_tenants_only():
    from pmdfc_tpu.config import AutotuneConfig
    from pmdfc_tpu.runtime import autotune

    tele.configure(TelemetryConfig(enabled=True))
    qcfg = QosConfig(tenant_bits=4, tenants=(
        TenantConfig(tid=1, weight=3),                   # unlimited
        TenantConfig(tid=2, rate_ops_per_s=100.0),       # derived env
        TenantConfig(tid=3, rate_ops_per_s=50.0,
                     rate_lo=10.0, rate_hi=1000.0)))     # declared env
    shared = LocalBackend(page_words=W, capacity=1 << 12)
    srv = NetServer(lambda: shared, net=NetConfig(), qos=qcfg).start()
    try:
        ctl = autotune.attach(server=srv, cfg=AutotuneConfig())
        kvals = ctl.knob_values()
        assert "qos_rate_t2" in kvals and kvals["qos_rate_t2"] == 100.0
        assert "qos_rate_t3" in kvals
        # rate 0 = unlimited is operator intent: no knob
        assert "qos_rate_t0" not in kvals
        assert "qos_rate_t1" not in kvals
        k2 = ctl._knobs["qos_rate_t2"]
        assert (k2.lo, k2.hi) == (25.0, 400.0)  # rate x lo/hi fracs
        k3 = ctl._knobs["qos_rate_t3"]
        assert (k3.lo, k3.hi) == (10.0, 1000.0)  # declared envelope
        # the knob setter lands on the live bucket through the server
        assert srv.set_qos_rate(2, 60.0) == 60.0
        assert srv.qos_plane().rate(2) == 60.0
        assert kvals != ctl.knob_values()
    finally:
        srv.stop()


# -- concurrency discipline -------------------------------------------


def test_lock_rank_and_module_coverage_pins():
    from pmdfc_tpu.runtime.sanitizer import HIERARCHY
    from tools.analyze.lockorder import RANKED_MODULES

    assert "TokenBucket._lock" in HIERARCHY
    assert HIERARCHY["NetServer._flush_cv"] \
        < HIERARCHY["TokenBucket._lock"] \
        < HIERARCHY["TcpBackend._lock"]
    assert "runtime/qos.py" in RANKED_MODULES


def test_config_validation():
    with pytest.raises(ValueError):
        QosConfig(tenant_bits=0)
    with pytest.raises(ValueError):
        QosConfig(tenant_bits=2, tenants=(TenantConfig(tid=4),))
    with pytest.raises(ValueError):
        QosConfig(tenants=(TenantConfig(tid=1), TenantConfig(tid=1)))
    with pytest.raises(ValueError):
        TenantConfig(tid=1, weight=0)
    with pytest.raises(ValueError):
        TenantConfig(tid=1, rate_lo=5.0, rate_hi=2.0)
    assert isinstance(TenantConfig(tid=1).weight, numbers.Integral)
