"""Causal tracing + continuous profiling drills (marker: tracing).

ISSUE 9's acceptance surface:

1. **Span trees** — begin/end semantics, ambient parenting across call
   frames, detached cross-thread spans, kill-switch behavior.
2. **The nested-trace acceptance drill** — one pipelined GET through a
   ReplicaGroup → TcpBackend → coalesced NetServer → 4-shard mesh
   plane yields a tree ≥ 6 levels deep (client op → attempt/hedge →
   wire → queue wait → flush phase → shard program), verified through
   `tools/tracetool.py` on an actual flight dump; the Chrome-trace
   export and the `pmdfc-flight-v2` schema checker run on the same
   dump. A slow-primary drill pins the hedge=True attempt span.
3. **Recompile tracker** — a seeded shape outside the warmed pad
   ladder increments exactly one named `recompile.kv.*` counter, once.
4. **SLO watchdog** — burn-window/starvation semantics on synthetic
   metrics, and the end-to-end drill: an injected server-side latency
   fault breaches a configured p99 target and writes an attributable
   `slo_breach` flight dump naming the violating stage.
5. **Satellites** — flight dump-dir rotation cap, per-shard span
   attribution summing to the `mesh.shard{i}_ops` counters, and the
   `tools/check_bench.py` lane-regression gate semantics.
"""

from __future__ import annotations

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              MeshConfig, NetConfig, TelemetryConfig)
from pmdfc_tpu.runtime import telemetry as tele

pytestmark = pytest.mark.tracing

W = 16


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False)
    return np.stack([flat >> 10, flat & 0x3FF], -1).astype(np.uint32)


def _pages(keys):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, W + 1, dtype=np.uint32)[None, :])


def _cfg(capacity=1 << 10):
    return KVConfig(index=IndexConfig(capacity=capacity),
                    bloom=BloomConfig(num_bits=1 << 15),
                    paged=True, page_words=W)


def _load_tool(name):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def fresh_registry(tmp_path):
    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15,
                                         dump_dir=str(tmp_path),
                                         dump_min_interval_s=0.0))
    yield reg
    tele.configure()


# --- 1. span-tree semantics ------------------------------------------------


def test_span_begin_end_ambient_nesting(fresh_registry):
    a = tele.span_begin("client", "outer")
    b = tele.span_begin("client", "inner")     # parent from ambient
    c = tele.span_begin("server", "detached", parent=a.sid,
                        ambient=False)         # explicit, no push
    d = tele.span_begin("client", "inner2")    # parent = b (c not pushed)
    tele.span_end(d)
    tele.span_end(c)
    tele.span_end(b)
    tele.span_end(a, extra_attr=7)
    recs = {r["op"]: r for r in fresh_registry.ring
            if r.get("kind") == "span"}
    assert recs["outer"]["parent"] == 0
    assert recs["inner"]["parent"] == recs["outer"]["span"]
    assert recs["detached"]["parent"] == recs["outer"]["span"]
    assert recs["inner2"]["parent"] == recs["inner"]["span"]
    assert recs["outer"]["extra_attr"] == 7
    for r in recs.values():
        assert 0 < r["span"] <= 0xFFFFFFFF
        assert r["t1_ns"] >= r["t0_ns"]
        assert r["dur_us"] == pytest.approx(
            (r["t1_ns"] - r["t0_ns"]) / 1e3, abs=0.06)
    # the ambient stack fully unwound
    assert tele._SPAN_TLS.stack == []


def test_span_out_of_order_end_unwinds_stack(fresh_registry):
    a = tele.span_begin("client", "a")
    b = tele.span_begin("client", "b")
    tele.span_end(a)   # error-unwind order: a removed from mid-stack
    tele.span_end(b)
    assert tele._SPAN_TLS.stack == []
    assert len([r for r in fresh_registry.ring
                if r.get("kind") == "span"]) == 2


def test_span_kill_switch(fresh_registry):
    tele.set_enabled(False)
    try:
        sp = tele.span_begin("client", "x")
        assert sp is None
        tele.span_end(sp)          # no-op, no crash
        assert len(fresh_registry.ring) == 0
    finally:
        tele.set_enabled(True)
    # toggled OFF mid-span: the stack unwinds, nothing is recorded
    sp = tele.span_begin("client", "y")
    tele.set_enabled(False)
    try:
        tele.span_end(sp)
        assert tele._SPAN_TLS.stack == []
        assert not [r for r in fresh_registry.ring
                    if r.get("kind") == "span" and r.get("op") == "y"]
    finally:
        tele.set_enabled(True)


def test_record_span_parents_off_ambient(fresh_registry):
    a = tele.span_begin("client", "root")
    tele.record_span("client", "shot", 5, True, dur_us=1.0)
    tele.span_end(a)
    recs = {r["op"]: r for r in fresh_registry.ring
            if r.get("kind") == "span"}
    assert recs["shot"]["parent"] == recs["root"]["span"]
    assert recs["shot"]["span"] > 0


# --- 2. the nested-trace acceptance drill ----------------------------------


def _serving_stack(n_shards=4):
    """ReplicaGroup(1) -> ReconnectingClient -> TcpBackend -> coalesced
    NetServer -> PlaneBackend over an n-shard forced-host mesh."""
    from pmdfc_tpu.client.replica import ReplicaGroup
    from pmdfc_tpu.config import ReplicaConfig
    from pmdfc_tpu.parallel.plane import make_serving_backend
    from pmdfc_tpu.runtime.failure import ReconnectingClient
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    plane = make_serving_backend(_cfg(), MeshConfig(n_shards=n_shards))
    srv = NetServer(lambda: plane,
                    net=NetConfig(flush_timeout_us=0, settle_us=0)).start()

    def factory():
        return TcpBackend("127.0.0.1", srv.port, page_words=W,
                          keepalive_s=None, op_timeout_s=60.0)

    rc = ReconnectingClient(factory, page_words=W, seed=3)
    group = ReplicaGroup([rc], page_words=W,
                         cfg=ReplicaConfig(n_replicas=1, rf=1,
                                           repair_interval_s=0.0),
                         seed=3)
    return srv, group


def test_pipelined_get_yields_nested_trace_and_chrome_export(
        fresh_registry, tmp_path):
    """THE acceptance drill: one pipelined GET through the 4-shard
    coalesced plane -> >= 6 correctly nested spans in the exported
    trace (client op -> attempt -> wire -> queue wait -> flush phase ->
    shard program), verified on the actual flight dump via tracetool;
    the Chrome export and the v2 schema checker run on the same dump."""
    srv, group = _serving_stack(n_shards=4)
    try:
        keys = _keys(16, seed=11)
        group.put(keys, _pages(keys))
        out, found = group.get(keys)
        assert found.all()
        np.testing.assert_array_equal(out, _pages(keys))
    finally:
        group.close()
        srv.stop()
    # the GET's trace id: the group op span of the last completed get
    ggets = [r for r in fresh_registry.ring
             if r.get("kind") == "span" and r.get("src") == "group"
             and r.get("op") == "get" and r.get("ok")]
    assert ggets, "no group get span recorded"
    trace = ggets[-1]["trace"]
    assert trace != 0
    path = tele.dump_now("tracetest")
    assert path and os.path.exists(path)

    tracetool = _load_tool("tracetool")
    records = tracetool.load_dumps([path])
    nodes = tracetool.build_tree(records)
    roots = tracetool.trace_tree(nodes, trace)
    assert roots, "trace has no root span"
    depth = max(n.depth() for n in roots)
    assert depth >= 6, f"nesting depth {depth} < 6"

    # the specific chain exists: group get -> attempt -> client wire ->
    # server op -> phase -> flush -> shard_program
    def chain_ops(n, acc):
        acc = acc + [n.op]
        yield acc
        for k in n.all_children():
            yield from chain_ops(k, acc)

    chains = [c for root in roots for c in chain_ops(root, [])]
    shard_chains = [c for c in chains if c[-1] == "shard_program"]
    assert shard_chains, f"no chain reaches a shard program: {chains}"
    best = max(shard_chains, key=len)
    assert best[0] == "get" and "attempt" in best \
        and "phase" in best and any(op.startswith("flush:") for op in best)
    # queue wait is measured explicitly somewhere under the same trace
    assert any("queue_wait" in c[-1] for c in chains), chains

    # clock offset was captured from the HOLA exchange; in-process the
    # two "domains" are one clock, so the estimate must be ~rtt-sized
    offsets, _fb = tracetool.clock_offsets(records)
    assert offsets, "no clock record captured"
    assert all(abs(off) < 50_000_000 for off in offsets.values())

    # Chrome-trace export: valid complete events; the one-trace export
    # (the op shares its trace id across group/wire/server stages)
    # carries the >= 6 nested spans of the acceptance chain
    doc = tracetool.chrome_trace(records, trace=None)
    assert len(doc["traceEvents"]) >= 6
    for e in doc["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] > 0 and e["ts"] >= 0
    outp = tmp_path / "chrome.json"
    assert tracetool.main([path, "--out", str(outp),
                           "--trace", str(trace), "--table"]) == 0
    exported = json.loads(outp.read_text())
    assert len(exported["traceEvents"]) >= 6
    names = {e["name"] for e in exported["traceEvents"]}
    assert {"get", "attempt", "queue_wait", "phase"} <= names, names

    # per-stage breakdown table covers the serving stages
    stages = {r["stage"] for r in tracetool.breakdown(records)}
    assert {"flush:get", "shard:get"} <= stages, stages

    # the dump conforms to pmdfc-flight-v2 — and the checker bites
    checker = _load_tool("check_teledump")
    with open(path) as f:
        dumpdoc = json.load(f)
    assert checker.check_flight(dumpdoc) == []
    bad = json.loads(json.dumps(dumpdoc))
    for r in bad["records"]:
        if r.get("kind") == "span" and "span" in r:
            r["span"] = "not-an-id"
            break
    assert checker.check_flight(bad)
    # a v1-shaped dump (flat spans, no tree fields) still parses
    v1 = json.loads(json.dumps(dumpdoc))
    v1["schema"] = "pmdfc-flight-v1"
    for r in v1["records"]:
        for k in ("span", "parent", "t0_ns", "t1_ns"):
            r.pop(k, None)
    assert checker.check_flight(v1) == []


def test_hedge_fires_hedge_marked_attempt_span(fresh_registry):
    """A slow primary past hedge_ms yields an attempt span with
    hedge=True, nested under the group get span."""
    from pmdfc_tpu.client.replica import ReplicaGroup
    from pmdfc_tpu.config import ReplicaConfig

    class SlowMiss:
        def __init__(self, delay):
            self.delay = delay

        def put(self, keys, pages):
            return None

        def get(self, keys):
            time.sleep(self.delay)
            return (np.zeros((len(keys), W), np.uint32),
                    np.zeros(len(keys), bool))

        def invalidate(self, keys):
            return np.zeros(len(keys), bool)

        def packed_bloom(self):
            return None

        def close(self):
            pass

    eps = [SlowMiss(0.05), SlowMiss(0.05)]
    cfg = ReplicaConfig(n_replicas=2, rf=2, hedge_ms=2.0,
                        repair_interval_s=0.0)
    with ReplicaGroup(eps, page_words=W, cfg=cfg, seed=1) as g:
        g.get(_keys(4, seed=1))
    spans = [r for r in fresh_registry.ring if r.get("kind") == "span"]
    gget = [r for r in spans if r["src"] == "group" and r["op"] == "get"]
    hedges = [r for r in spans if r["op"] == "attempt" and r.get("hedge")]
    assert gget and hedges, (gget, hedges)
    assert all(h["parent"] == gget[-1]["span"] for h in hedges)
    assert all(h["trace"] == gget[-1]["trace"] for h in hedges)


# --- 3. recompile tracker --------------------------------------------------


def _recompile_counters(reg) -> dict:
    snap = reg.snapshot()["counters"]
    return {k: v for k, v in snap.items() if k.startswith("recompile.kv.")}


def test_cold_ladder_rung_increments_exactly_one_named_counter(
        fresh_registry):
    from pmdfc_tpu.kv import KV

    kv = KV(_cfg())
    keys = _keys(64, seed=7)
    kv.insert(keys[:16], _pages(keys[:16]))   # warms w=16 insert
    kv.get(keys[:16])                         # warms w=16 get
    kv.get(keys[:30])                         # warms w=32 get
    before = _recompile_counters(fresh_registry)
    kv.get(keys[:33])                         # w=64: OUTSIDE the ladder
    after = _recompile_counters(fresh_registry)
    bumped = {k: after[k] - before.get(k, 0) for k in after
              if after[k] != before.get(k, 0)}
    assert len(bumped) == 1, f"expected exactly one named bump: {bumped}"
    (name, delta), = bumped.items()
    assert delta == 1 and name.startswith("recompile.kv.get")
    # same shape again: the signature is known, no further counting
    kv.get(keys[:40])                         # pads to w=64 again
    assert _recompile_counters(fresh_registry) == after
    # the ring carries the named recompile event for the cold rung
    evs = [r for r in fresh_registry.ring if r.get("kind") == "recompile"]
    assert any(r["program"] == name[len("recompile."):] and "64" in r["sig"]
               for r in evs), evs


def test_plane_wrap_cache_miss_is_tracked(fresh_registry):
    from pmdfc_tpu.parallel.plane import make_serving_backend

    be = make_serving_backend(_cfg(), MeshConfig(n_shards=2))
    keys = _keys(8, seed=9)
    be.put(keys, _pages(keys))
    snap = fresh_registry.snapshot()["counters"]
    plane_counts = {k: v for k, v in snap.items()
                    if k.startswith("recompile.plane.")}
    assert plane_counts and all(v >= 1 for v in plane_counts.values())


# --- 4. SLO watchdog -------------------------------------------------------


def test_slo_burn_windows_and_starvation(fresh_registry):
    from pmdfc_tpu.runtime.slo import SloConfig, SloTarget, SloWatchdog

    sc = tele.scope("svc", unique=False)
    h = sc.hist("lat_us")
    num, den = sc.counter("errs"), sc.counter("ops")
    cfg = SloConfig(targets=(
        SloTarget("p99", "latency_p99", "svc.lat_us", 100.0),
        SloTarget("errs", "ratio_max", "svc.errs", 0.1,
                  denominator="svc.ops"),
    ), window_s=1.0, burn_windows=2, min_count=8)
    wd = SloWatchdog(cfg)
    assert wd.tick() == []            # priming tick: no window yet
    for _ in range(16):
        h.observe(10.0)
    den.inc(16)
    assert wd.tick() == []            # compliant window
    for _ in range(16):
        h.observe(5000.0)
    den.inc(16), num.inc(8)           # both targets violate: burn 1
    assert wd.tick() == []
    assert wd.stats["violations"] == 2
    for _ in range(16):
        h.observe(5000.0)
    den.inc(16), num.inc(8)           # burn 2 -> breach fires
    breached = wd.tick()
    assert {b["target"].name for b in breached} == {"p99", "errs"}
    assert wd.stats["breaches"] == 2
    # starved window: too few observations, burn state untouched
    h.observe(9999.0)
    den.inc(1)
    assert wd.tick() == []
    assert wd.stats["starved_windows"] >= 2
    # a healthy window re-arms cleanly
    for _ in range(16):
        h.observe(10.0)
    den.inc(16)
    assert wd.tick() == []


def test_attribute_stage_names_dominant_disjoint_stage():
    """A slow shard program must be nameable: per-op `phase` spans are
    one op's view of the SAME flush window (skipped), and the shared
    flush span is charged only its exclusive time — a containing span
    must never bury the child that actually grew."""
    from pmdfc_tpu.runtime.slo import attribute_stage

    def span(op, dur, **kw):
        return {"kind": "span", "op": op, "dur_us": dur, "src": "server",
                **kw}

    recs = [
        span("get", 1000.0),                       # whole-op: fallback only
        span("queue_wait", 50.0),
        span("flush:get", 900.0, phase="get"),     # shared flush window
        span("phase", 900.0, phase="get"),         # per-op views of it:
        span("phase", 900.0, phase="get"),         # must NOT multiply
        span("shard_program", 800.0, phase="get", shard=2),
        span("shard_program", 40.0, phase="get", shard=0),
    ]
    stage, table = attribute_stage(recs)
    assert stage == "shard2:get", (stage, table)
    # flush:get charged only its exclusive remainder (900 - 840)
    assert table["flush:get"] == pytest.approx(60.0)
    # and with no stage spans at all, whole-op spans are the fallback
    stage, _ = attribute_stage([span("get", 10.0)])
    assert stage == "server:get"


def test_slo_watchdog_restartable(fresh_registry):
    from pmdfc_tpu.runtime.slo import SloConfig, SloWatchdog

    wd = SloWatchdog(SloConfig(window_s=0.05))
    wd.start()
    time.sleep(0.12)
    wd.stop()
    ticks = wd.stats["ticks"]
    assert ticks >= 1
    wd.start()                      # must spawn a FRESH thread
    time.sleep(0.12)
    wd.stop()
    assert wd.stats["ticks"] > ticks, "watchdog did not restart"


def test_slo_config_from_dict_roundtrip_and_validation():
    from pmdfc_tpu.runtime.slo import SloConfig, SloTarget

    cfg = SloConfig.from_dict({
        "window_s": 2.5, "burn_windows": 3,
        "targets": [{"name": "g", "kind": "latency_p99",
                     "metric": "net.client.get_us", "threshold": 5e4},
                    {"name": "hr", "kind": "ratio_min", "threshold": 0.9,
                     "metric": "a.hits", "denominator": "a.gets"}]})
    assert cfg.window_s == 2.5 and len(cfg.targets) == 2
    with pytest.raises(ValueError, match="unknown SLO kind"):
        SloTarget("x", "p42", "m", 1.0)
    with pytest.raises(ValueError, match="denominator"):
        SloTarget("x", "ratio_min", "m", 1.0)


def test_injected_latency_breaches_p99_and_dumps_attributable_flight(
        fresh_registry, tmp_path):
    """The ISSUE acceptance drill: a server-side latency fault breaches
    a configured GET p99 target; the slo_breach flight dump names the
    target AND the violating stage (the slow flush phase)."""
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend
    from pmdfc_tpu.runtime.slo import SloConfig, SloTarget, SloWatchdog

    class Laggy(LocalBackend):
        def get(self, keys):
            time.sleep(0.02)          # the injected fault: 20 ms
            return super().get(keys)

    cfg = SloConfig(targets=(
        SloTarget("get_p99", "latency_p99", "net.client.get_us", 2000.0),
    ), window_s=0.5, burn_windows=2, min_count=4)
    wd = SloWatchdog(cfg)
    shared = Laggy(page_words=W, capacity=1 << 10)
    breaches = []
    with NetServer(lambda: shared, net=NetConfig()).start() as srv, \
            TcpBackend("127.0.0.1", srv.port, page_words=W,
                       keepalive_s=None, op_timeout_s=10.0) as be:
        keys = _keys(8, seed=5)
        be.put(keys, _pages(keys))
        be.get(keys)                  # the hist must exist to be primed
        wd.tick()                     # prime the window state
        for _round in range(2):
            for _ in range(6):
                be.get(keys)
            breaches += wd.tick()
    assert breaches, "p99 target never breached"
    b = breaches[0]
    assert b["target"].name == "get_p99" and b["value"] > 2000.0
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight_slo_breach_") and f.endswith(".json")]
    assert dumps, "no slo_breach flight dump written"
    with open(os.path.join(tmp_path, sorted(dumps)[-1])) as f:
        doc = json.load(f)
    assert doc["schema"] == "pmdfc-flight-v2"
    det = doc["detail"]
    assert det["target"] == "get_p99" and det["metric"] == "net.client.get_us"
    assert det["value"] > det["threshold"]
    # the violating stage comes from the trace data: the laggy backend
    # stalls the fused GET flush, so the flush:get stage dominates
    assert det["stage"] == "flush:get", det
    assert det["stages"]["flush:get"] > 0
    checker = _load_tool("check_teledump")
    assert checker.check_flight(doc) == []


# --- 5. satellites ---------------------------------------------------------


def test_dump_dir_rotation_caps_file_count(tmp_path):
    tele.configure(TelemetryConfig(dump_dir=str(tmp_path),
                                   dump_min_interval_s=0.0,
                                   dump_max_files=3))
    try:
        for i in range(8):
            tele.rung("bad_frame", n=i)
            time.sleep(0.01)   # distinct mtimes for the oldest-first sort
        files = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("flight_") and f.endswith(".json"))
        assert len(files) == 3, files
        # the NEWEST three survive (oldest-first deletion)
        seqs = [int(f.rsplit("_", 1)[1].split(".")[0]) for f in files]
        assert seqs == [5, 6, 7], seqs
    finally:
        tele.configure()


def test_shard_span_attribution_sums_to_mesh_counters(fresh_registry):
    """Satellite acceptance: a seeded mixed workload on the 4-shard
    plane produces shard_program spans whose per-shard op counts sum to
    the existing `mesh.shard{i}_ops` counters."""
    from pmdfc_tpu.parallel.plane import make_serving_backend

    be = make_serving_backend(_cfg(), MeshConfig(n_shards=4))
    rng = np.random.default_rng(21)
    universe = _keys(128, seed=21)
    for _ in range(30):
        lo = int(rng.integers(0, 112))
        n = int(rng.integers(1, 12))
        sel = universe[lo:lo + n]
        op = int(rng.integers(3))
        if op == 0:
            be.put(sel, _pages(sel))
        elif op == 1:
            be.get(sel)
        else:
            be.invalidate(sel)
    sums = {}
    for r in fresh_registry.ring:
        if r.get("kind") == "span" and r.get("op") == "shard_program":
            sums[r["shard"]] = sums.get(r["shard"], 0) + r["ops"]
    assert sums, "no shard_program spans recorded"
    for i in range(4):
        ctr = fresh_registry.metric(f"mesh.shard{i}_ops")
        want = ctr.value if ctr is not None else 0
        assert sums.get(i, 0) == want, \
            f"shard {i}: spans {sums.get(i, 0)} != counter {want}"


def test_check_bench_lane_regression_gate(tmp_path):
    cb = _load_tool("check_bench")

    def row(value, metric="m", unit="Mpages/s", **kw):
        return {"ts": "2026-08-04T00:00:00+00:00", "metric": metric,
                "unit": unit, "value": value, "transport": "tcp",
                "verb_keys": 32, **kw}

    # throughput lane: a 20% drop regresses at 15% tolerance
    regs = cb.check_history([row(10.0), row(8.0)], tolerance=0.15)
    assert len(regs) == 1 and regs[0]["direction"] == "higher-better"
    # within-band drift passes
    assert cb.check_history([row(10.0), row(9.0)], tolerance=0.15) == []
    # latency lanes invert the direction
    up = [row(100.0, metric="p99", unit="us"),
          row(130.0, metric="p99", unit="us")]
    down = [row(100.0, metric="p99", unit="us"),
            row(90.0, metric="p99", unit="us")]
    assert len(cb.check_history(up)) == 1
    assert cb.check_history(down) == []
    # differing shape keys = different lanes, never compared
    mixed = [row(10.0, verb_keys=16), row(5.0, verb_keys=64)]
    assert cb.check_history(mixed) == []
    # SECONDARY measured outputs (floats like best_wall_s, link rates;
    # None/list fields) are NOT lane identity: a rerun whose
    # measurements differ must still land in the same lane — this is
    # what keeps the gate non-vacuous on the real history's rows
    rerun = [row(10.0, best_wall_s=1.11, link_h2d_mbs=215.0,
                 gather_bytes_per_s=None),
             row(8.0, best_wall_s=2.22, link_h2d_mbs=301.0,
                 gather_bytes_per_s=12345)]
    assert cb.lane_key(rerun[0]) == cb.lane_key(rerun[1])
    assert len(cb.check_history(rerun)) == 1
    # ...while float KNOBS (zipf) and measured-int exceptions hold
    assert cb.lane_key(row(1.0, zipf=0.6)) != cb.lane_key(
        row(1.0, zipf=1.2))
    # improvements and single-row lanes never fire
    assert cb.check_history([row(8.0), row(10.0)]) == []
    assert cb.check_history([row(10.0)]) == []
    # CLI: regression exits 1, clean exits 0
    hist = tmp_path / "h.jsonl"
    hist.write_text("\n".join(json.dumps(r)
                              for r in [row(10.0), row(8.0)]) + "\n")
    assert cb.main([str(hist)]) == 1
    hist.write_text("\n".join(json.dumps(r)
                              for r in [row(10.0), row(9.9)]) + "\n")
    assert cb.main([str(hist), "--tolerance", "0.15"]) == 0


def test_check_bench_fused_kernel_lanes_never_collapse():
    """The fused_get sweep appends PAIRED rows per combo differing only
    in the `kernel` knob (pallas_fused vs xla_composed) — check_bench
    must hold them as separate lanes (else the slower kernel reads as a
    regression of the faster one), fork lanes on the `tile` knob (a new
    tile rung is a different program), and keep `hits` — a measured
    workload outcome — OUT of identity so reruns stay comparable."""
    cb = _load_tool("check_bench")

    def row(value, **kw):
        return {"ts": "2026-08-07T00:00:00+00:00", "metric": "fused_get",
                "unit": "Mops/s", "value": value, "device": "tpu",
                "family": "linear", "zipf": 0.99, "batch": 512,
                "tile": 128, "kernel": "pallas_fused", "hits": 31987,
                **kw}

    # paired kernels: distinct lanes, a 2x gap between them never fires
    paired = [row(40.0, kernel="xla_composed"), row(20.0)]
    assert cb.lane_key(paired[0]) != cb.lane_key(paired[1])
    assert cb.check_history(paired) == []
    # ...but within ONE kernel's lane the band still gates
    assert len(cb.check_history([row(40.0), row(20.0)])) == 1
    # tile is identity: a new rung opens a new lane
    assert cb.lane_key(row(1.0, tile=64)) != cb.lane_key(row(1.0))
    # hits is a measured outcome, not identity: a rerun whose hit count
    # drifted still lands in the same lane and gates
    rerun = [row(40.0, hits=31987), row(20.0, hits=29544)]
    assert cb.lane_key(rerun[0]) == cb.lane_key(rerun[1])
    assert len(cb.check_history(rerun)) == 1
