"""Closed-loop serving controller suite (`runtime/autotune.py`).

Covers the control loop end to end on deterministic synthetic series
windows (the controller reads the live registry's `SeriesRing`; tests
push crafted windows and drive `tick()` by hand — the Collector's
cadence is irrelevant to the loop's semantics):

- convergence: an over-wide dwell under light load walks DOWN to the
  envelope floor with hysteresis; deep staging under fan-in walks the
  dwell and the pipeline window UP; the hedge deadline tracks the wire
  GET p99 multiple.
- governor: an SLO breach freezes the controller and reverts every
  knob to the last-known-good vector with an attributable
  `autotune_revert` flight dump (schema-checked); sensor starvation
  retreats once, then holds.
- envelope: every walk clamps to the `AutotuneConfig` hard bounds —
  including the balloon's ±`balloon_max_extents` offset.
- live-knob hooks: the NetServer flush knobs, the `_WindowGate`
  admission semantics + `TcpBackend.set_window` mid-traffic, the
  degrade-safe `ReconnectingClient.set_window` forward, the
  `ReplicaGroup` hedge hook, and the Migrator's live rate bound with
  its static-config conformance point.
- `PMDFC_AUTOTUNE=off` conformance: a constructed controller is fully
  inert — no ctl scope, no decisions, knobs verb-for-verb at their
  hand-tuned config values.
- `tools/check_teledump.py` `check_autotune` pins.

Heavier end-to-end soaks ride the `autotune_smoke` agenda step
(`bench/autotune_sweep.py --smoke`), the tier-budget note of PR 13.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

from pmdfc_tpu.client.backends import LocalBackend
from pmdfc_tpu.config import (AutotuneConfig, NetConfig, ReplicaConfig,
                              RingConfig, TelemetryConfig)
from pmdfc_tpu.runtime import autotune
from pmdfc_tpu.runtime import telemetry as tele
from pmdfc_tpu.runtime import timeseries as ts
from pmdfc_tpu.runtime.net import NetServer, _WindowGate

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pytestmark = pytest.mark.autotune


# -- harness -----------------------------------------------------------


def _fresh_ring(dump_dir=None):
    """Fresh registry + series sink the controller will read."""
    cfg = TelemetryConfig(dump_dir=dump_dir) if dump_dir \
        else TelemetryConfig()
    reg = tele.configure(cfg)
    ring = ts.SeriesRing(capacity=256, interval_s=1.0)
    reg.series_sink = ring
    return reg, ring


class _Clock:
    def __init__(self):
        self.t = 0.0

    def win(self, counters=None, gauges=None, hists=None):
        self.t += 1.0
        return {"t": self.t, "dt_s": 1.0, "counters": counters or {},
                "gauges": gauges or {}, "hists": hists or {}}


def _light_window(clk, pfx):
    """One served window that looks like a lone client: batches of ~1,
    calm staging queue."""
    return clk.win(
        counters={pfx + "coalesced_ops": 100},
        gauges={pfx + "staging_depth": 1},
        hists={pfx + "flush_ops_hist":
               {"count": 100, "sum": 105, "p50": 1, "p95": 2, "p99": 2}})


def _fanin_window(clk, pfx, staging=200):
    """One served window under fan-in: deep staging, fat batches."""
    return clk.win(
        counters={pfx + "coalesced_ops": 4000},
        gauges={pfx + "staging_depth": staging},
        hists={pfx + "flush_ops_hist":
               {"count": 40, "sum": 4000, "p50": 90, "p95": 120,
                "p99": 140}})


def _srv():
    return NetServer(lambda: LocalBackend(page_words=8), net=NetConfig())


# -- config / kill switch ---------------------------------------------


def test_config_validation():
    with pytest.raises(ValueError):
        AutotuneConfig(dwell_us_lo=500, dwell_us_hi=100)
    with pytest.raises(ValueError):
        AutotuneConfig(up_frac=0.0)
    with pytest.raises(ValueError):
        AutotuneConfig(down_frac=1.0)
    with pytest.raises(ValueError):
        AutotuneConfig(hysteresis_windows=0)
    with pytest.raises(ValueError):
        AutotuneConfig(interval_s=0)
    AutotuneConfig()  # defaults valid


def test_kill_switch_off_is_inert(monkeypatch):
    monkeypatch.setenv("PMDFC_AUTOTUNE", "off")
    reg, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(AutotuneConfig())
    ctl.bind_server(srv)
    assert not ctl.enabled
    assert ctl.stats is None  # the scope-present-iff-enabled pin
    clk = _Clock()
    pfx = srv.stats.prefix + "."
    for _ in range(8):
        ring.push(_light_window(clk, pfx))
        assert ctl.tick() == []
    # knobs verb-for-verb at the hand-tuned config values
    assert srv.flush_knobs() == (float(NetConfig.flush_timeout_us),
                                 float(NetConfig.settle_us))
    # no ctl scope ever registered
    snap = reg.snapshot()
    assert not any(".knob_" in k for k in snap["gauges"])
    assert not any(k.startswith("ctl") for k in snap["counters"])


# -- convergence -------------------------------------------------------


def test_dwell_walks_down_under_light_load():
    _, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2))
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    trail = []
    for _ in range(16):
        ring.push(_light_window(clk, pfx))
        ctl.tick()
        trail.append(srv.flush_knobs())
    dwell = [d for d, _ in trail]
    cfg = ctl.cfg
    # monotone non-increasing walk, converged to the envelope floor,
    # never below it
    assert all(b <= a for a, b in zip(dwell, dwell[1:]))
    assert dwell[-1] == cfg.dwell_us_lo
    assert trail[-1][1] == cfg.settle_us_lo
    assert min(dwell) >= cfg.dwell_us_lo
    # hysteresis: the first window alone must not move anything
    assert trail[0] == (float(NetConfig.flush_timeout_us),
                        float(NetConfig.settle_us))
    assert ctl.stats["decisions"] > 0
    assert ctl.stats["reverts"] == 0


class _FakeClient:
    def __init__(self, window=32):
        self.window = window

    def set_window(self, n):
        self.window = max(1, int(n))
        return self.window


def test_window_and_dwell_walk_up_under_fan_in():
    _, ring = _fresh_ring()
    srv = _srv()
    cl = _FakeClient(window=32)
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2))
    ctl.bind_server(srv)
    ctl.bind_client(cl)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    d0 = srv.flush_knobs()[0]
    for _ in range(30):
        ring.push(_fanin_window(clk, pfx))
        ctl.tick()
    cfg = ctl.cfg
    assert srv.flush_knobs()[0] > d0
    assert srv.flush_knobs()[0] <= cfg.dwell_us_hi
    # deep staging walks the pipeline window up, clamped at the bound
    assert cl.window == cfg.window_hi
    vals = ctl.knob_values()
    assert vals["window"] == cfg.window_hi


def test_hedge_tracks_wire_p99():
    _, ring = _fresh_ring()
    group = _group()
    try:
        ctl = autotune.AutotuneController(
            AutotuneConfig(hysteresis_windows=1))
        ctl.bind_group(group)
        clk = _Clock()
        # wire GET p99 at 40 ms -> target = 3 * 40 = 120 ms: hedge
        # walks UP from the 50 ms default, never past the bound
        for _ in range(12):
            ring.push(clk.win(
                counters={group.counters.prefix + ".gets": 100},
                hists={"net.client.get_us":
                       {"count": 100, "sum": 2e6, "p50": 20000,
                        "p95": 35000, "p99": 40000}}))
            ctl.tick()
        up = group.hedge_ms_live()
        assert up > 50.0
        assert up <= ctl.cfg.hedge_ms_hi
        # p99 collapses to 1 ms -> target 3 ms: hedge walks back down
        for _ in range(16):
            ring.push(clk.win(
                counters={group.counters.prefix + ".gets": 100},
                hists={"net.client.get_us":
                       {"count": 100, "sum": 5e4, "p50": 300,
                        "p95": 800, "p99": 1000}}))
            ctl.tick()
        down = group.hedge_ms_live()
        assert down < up
        assert down >= ctl.cfg.hedge_ms_lo
        # the knob gauge mirrors the live hook
        assert ctl.knob_values()["hedge_ms"] == down
    finally:
        group.close()


def _group():
    from pmdfc_tpu.client.replica import ReplicaGroup

    eps = [LocalBackend(8, 256) for _ in range(2)]
    return ReplicaGroup(eps, page_words=8,
                        cfg=ReplicaConfig(n_replicas=2, rf=1,
                                          repair_interval_s=0,
                                          ring=RingConfig()))


# -- migration rate (the PR-12 leftover) ------------------------------


def test_migrate_rate_live_and_static_conformance():
    _, ring = _fresh_ring()
    group = _group()
    try:
        mig = group.migrator
        assert mig is not None
        static = mig.cfg.migrate_pages_per_s
        # conformance point: an untouched migrator IS the static config
        assert mig.rate() == static
        assert mig.set_rate(512.0) == 512.0
        assert mig.rate() == 512.0
        assert group.set_migrate_rate(1024.0) == 1024.0
        # None restores the static configured rate exactly
        assert mig.set_rate(None) == static
        assert mig.rate() == static
        # the controller walks it only while a transition is ACTIVE:
        # with migration idle, windows with lag gauges propose nothing
        ctl = autotune.AutotuneController(
            AutotuneConfig(hysteresis_windows=1))
        ctl.bind_group(group)
        clk = _Clock()
        mp = mig.scope.prefix + "."
        for _ in range(4):
            ring.push(clk.win(
                counters={group.counters.prefix + ".gets": 10},
                gauges={mp + "lag": 500, mp + "active": 0}))
            ctl.tick()
        assert mig.rate() == static
        # active transition + healthy queue-wait -> rate walks UP
        for _ in range(6):
            ring.push(clk.win(
                counters={group.counters.prefix + ".gets": 10},
                gauges={mp + "lag": 500, mp + "active": 1}))
            ctl.tick()
        assert mig.rate() > static
        assert mig.rate() <= ctl.cfg.migrate_pps_hi
    finally:
        group.close()


def test_unbounded_migrate_rate_gets_no_knob():
    """rate 0 = unbounded is operator intent (TokenBucket contract):
    no knob — registering would gauge 0 outside the envelope and a
    revert would throttle it to the floor (review finding)."""
    _fresh_ring()
    from pmdfc_tpu.client.replica import ReplicaGroup

    eps = [LocalBackend(8, 256) for _ in range(2)]
    group = ReplicaGroup(
        eps, page_words=8,
        cfg=ReplicaConfig(n_replicas=2, rf=1, repair_interval_s=0,
                          ring=RingConfig(migrate_pages_per_s=0)))
    try:
        ctl = autotune.AutotuneController(AutotuneConfig())
        ctl.bind_group(group)
        assert "migrate_pps" not in ctl.knob_values()
        assert "hedge_ms" in ctl.knob_values()
        assert group.migrator.rate() == 0.0  # still unbounded
    finally:
        group.close()


def test_envelope_widens_to_contain_static_point():
    """A config whose static value sits outside the declared bounds
    must neither fail the check_autotune envelope pin at bind time nor
    have the first walk yank the knob to a bound the operator never
    chose (review finding)."""
    from tools.check_teledump import check_autotune

    reg, _ = _fresh_ring()
    srv = NetServer(lambda: LocalBackend(page_words=8),
                    net=NetConfig(flush_timeout_us=50000))
    ctl = autotune.AutotuneController(AutotuneConfig())
    ctl.bind_server(srv)
    assert ctl.stats["knob_dwell_us_hi"] == 50000.0  # widened
    assert ctl.stats["knob_dwell_us"] == 50000.0
    assert check_autotune(reg.snapshot()) == []


def test_bind_unconnected_reconnecting_client_assumes_default():
    """Binding a lazily-connecting ReconnectingClient (window None)
    must record the transport DEFAULT as last-known-good, not the
    envelope floor — or a later governor revert would slam the live
    window 8x below a point the controller never moved (review
    finding)."""
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    _fresh_ring()
    rc = ReconnectingClient(lambda: _FakeWindowBackend(), page_words=8)
    ctl = autotune.AutotuneController(AutotuneConfig())
    ctl.bind_client(rc)
    assert ctl.knob_values()["window"] == float(NetConfig.window)
    assert ctl._lkg["window"] == float(NetConfig.window)


def test_disabled_hedging_gets_no_knob():
    """hedge_ms=0 is documented operator intent (hedging off): the
    controller must not register a knob that would re-enable duplicate
    GETs on the first p99 sighting (review finding)."""
    _fresh_ring()
    from pmdfc_tpu.client.replica import ReplicaGroup

    eps = [LocalBackend(8, 256) for _ in range(2)]
    group = ReplicaGroup(
        eps, page_words=8,
        cfg=ReplicaConfig(n_replicas=2, rf=1, hedge_ms=0.0,
                          repair_interval_s=0, ring=RingConfig()))
    try:
        ctl = autotune.AutotuneController(AutotuneConfig())
        ctl.bind_group(group)
        assert "hedge_ms" not in ctl.knob_values()
        assert group.hedge_ms_live() == 0.0  # hedging stays off
    finally:
        group.close()


def test_provisional_window_lkg_adopts_first_real_sighting():
    """A fallback lkg recorded at bind time (unconnected client) must
    be replaced by the first REAL window sighting — a custom-window
    factory (64) must not be reverted to the assumed default (32)
    (review finding)."""
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    _, ring = _fresh_ring()
    srv = _srv()

    def factory():
        be = _FakeWindowBackend()
        be.window = 64  # the operator's hand-tuned custom window
        return be

    rc = ReconnectingClient(factory, page_words=8)
    ctl = autotune.AutotuneController(AutotuneConfig())
    ctl.bind_server(srv)
    ctl.bind_client(rc)
    assert ctl._lkg["window"] == float(NetConfig.window)  # provisional
    rc._ensure(force=True)  # the client connects: window now real
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    ring.push(_light_window(clk, pfx))
    ctl.tick()
    assert ctl._lkg["window"] == 64.0  # adopted, not the fallback
    assert ctl.stats["knob_window"] == 64.0


def test_controller_move_never_adopted_as_lkg_sighting():
    """A knob the controller itself moved while the client was still
    DISCONNECTED must not be adopted as the "first real sighting":
    `ReconnectingClient.window` echoes the pending `_want_window`, so
    the adoption probe would record the controller's own move as the
    governor's revert target instead of the hand-tuned default (review
    finding)."""
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    _, ring = _fresh_ring()
    srv = _srv()
    rc = ReconnectingClient(lambda: _FakeWindowBackend(), page_words=8)
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=1))
    ctl.bind_server(srv)
    ctl.bind_client(rc)
    assert "window" in ctl._lkg_pending
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    # fan-in proposes window UP; hysteresis=1 lands the move this tick,
    # which reaches only the client's pending _want_window (no backend)
    ring.push(_fanin_window(clk, pfx))
    ctl.tick()
    assert ctl.knob_values()["window"] > float(NetConfig.window)
    assert rc.window is not None  # the echo the adoption probe would see
    # the write dropped the pending probe: a served no-proposal window
    # (nothing moves, so the legit `_lkg = pre` path stays out of the
    # picture) must keep the bind-time fallback as lkg — with the probe
    # still armed, this tick's adoption would have recorded the
    # controller's own 40 as the revert target
    ring.push(clk.win(counters={pfx + "coalesced_ops": 10}))
    ctl.tick()
    assert "window" not in ctl._lkg_pending
    assert ctl._lkg["window"] == float(NetConfig.window)


def test_clock_stepback_keeps_loop_alive():
    """Series windows stamp wall-clock `time.time()`; after an NTP
    step-back / VM resume a time-keyed ratchet would read every future
    window as already-seen and silently disable the loop (an armed
    freeze burn-down included) — the identity ratchet must keep
    evaluating (review finding)."""
    _, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(AutotuneConfig())
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    for _ in range(3):
        ring.push(_light_window(clk, pfx))
        ctl.tick()
    seen = ctl.stats["windows_seen"]
    clk.t = -1000.0  # wall clock steps far behind every consumed stamp
    ring.push(_light_window(clk, pfx))
    ctl.tick()
    assert ctl.stats["windows_seen"] == seen + 1  # still evaluating


def test_wedged_flush_window_keeps_up_streak_and_is_not_starvation():
    """A window with a DEEP staging queue but zero completed flushes
    (the flush loop wedged behind one long device dispatch) must still
    propose the fusion knobs UP per the documented rule table — not
    reset the streak for lack of batch evidence — and must not count
    toward a mid-peak "starved" revert (review finding)."""
    _, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2, starve_windows=2))
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    d0 = srv.flush_knobs()[0]
    for _ in range(3):
        # queue at depth, nothing completed: no ops counters, no hist
        ring.push(clk.win(gauges={pfx + "staging_depth": 200}))
        ctl.tick()
    assert srv.flush_knobs()[0] > d0  # the UP streak landed
    assert ctl.stats["governor_freezes"] == 0  # never read as starved
    assert ctl.stats["reverts"] == 0


def test_hysteresis_requires_consecutive_windows():
    """Two same-direction proposals separated by a no-evidence window
    are NOT consecutive: the gap breaks the streak, so isolated
    transients can never move a knob (review finding)."""
    _, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2))
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    d0 = srv.flush_knobs()
    for _ in range(6):
        ring.push(_light_window(clk, pfx))  # proposes dwell DOWN
        ctl.tick()
        # served window with no flush histogram: no proposal -> the
        # streak must reset, not survive the gap
        ring.push(clk.win(counters={pfx + "coalesced_ops": 10}))
        ctl.tick()
    assert srv.flush_knobs() == d0
    assert ctl.stats["decisions"] == 0


# -- governor ----------------------------------------------------------


def test_breach_freezes_reverts_and_dumps(tmp_path):
    from pmdfc_tpu.runtime import slo
    from tools.check_teledump import check_flight

    _, ring = _fresh_ring(dump_dir=str(tmp_path))
    srv = _srv()
    wd = slo.SloWatchdog(slo.SloConfig(targets=()))
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2, freeze_windows=3),
        watchdog=wd)
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    for _ in range(6):
        ring.push(_light_window(clk, pfx))
        ctl.tick()
    walked = srv.flush_knobs()
    lkg = dict(ctl._lkg)
    assert walked[0] < NetConfig.flush_timeout_us
    # induce the breach the watchdog would have counted
    wd.stats.inc("breaches")
    ring.push(_light_window(clk, pfx))
    out = ctl.tick()
    # reverted to last-known-good, frozen, attributable
    assert srv.flush_knobs() == (lkg["dwell_us"], lkg["settle_us"])
    assert ctl.frozen()
    assert ctl.stats["reverts"] == 1
    assert ctl.stats["decisions"] >= ctl.stats["reverts"]
    assert any(d.get("why") == "slo_breach" for d in out)
    dumps = glob.glob(str(tmp_path / "flight_autotune_revert_*.json"))
    assert dumps, "no autotune_revert flight dump written"
    with open(dumps[0]) as f:
        doc = json.load(f)
    assert check_flight(doc) == []
    assert doc["detail"]["reason"] == "slo_breach"
    assert "dwell_us" in doc["detail"]["knobs"]
    # frozen: further windows decide nothing until the freeze burns
    ring.push(_light_window(clk, pfx))
    assert ctl.tick() == []
    for _ in range(4):
        ring.push(_light_window(clk, pfx))
        ctl.tick()
    assert not ctl.frozen()


def test_starvation_reverts_once_then_holds():
    _, ring = _fresh_ring()
    srv = _srv()
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=2, starve_windows=3,
                       freeze_windows=2))
    ctl.bind_server(srv)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    for _ in range(6):
        ring.push(_light_window(clk, pfx))
        ctl.tick()
    assert srv.flush_knobs()[0] < NetConfig.flush_timeout_us
    lkg = dict(ctl._lkg)
    # the fleet goes dark: after starve_windows empty windows the
    # controller retreats to last-known-good exactly once
    for _ in range(12):
        ring.push(clk.win())
        ctl.tick()
    assert srv.flush_knobs() == (lkg["dwell_us"], lkg["settle_us"])
    assert ctl.stats["reverts"] == 1
    assert ctl.stats["governor_freezes"] == 1


# -- balloon stepping --------------------------------------------------


class _FakeBalloon:
    """Records grow/shrink calls, models a real circulating/parked
    pool, and serves a synthetic pressure signal."""

    def __init__(self, circulating=2048, parked=4096):
        self.grows = []
        self.shrinks = []
        self.circulating = circulating
        self.parked = parked
        self._gets = 0
        self._evicted = 0
        self.pressure = True

    def balloon_state(self):
        return {"cold_rows": self.circulating + self.parked,
                "circulating": self.circulating, "parked": self.parked,
                "free": 64, "step": 1024}

    def balloon_grow(self, rows):
        take = min(rows, self.parked)  # grow un-parks; no-op when bare
        self.parked -= take
        self.circulating += take
        self.grows.append(rows)
        return True

    def balloon_shrink(self, rows):
        take = min(rows, self.circulating)
        self.circulating -= take
        self.parked += take
        self.shrinks.append(rows)
        return True

    def stats(self):
        self._gets += 1000
        self._evicted += 100 if self.pressure else 0
        return {"gets": self._gets, "miss_evicted": self._evicted,
                "miss_parked": 0, "capacity": 4096}


def test_balloon_steps_are_clamped_to_envelope():
    _, ring = _fresh_ring()
    srv = _srv()
    bal = _FakeBalloon(parked=4096)
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=1, balloon_every=1,
                       balloon_max_extents=3))
    ctl.bind_server(srv)
    ctl.bind_balloon(bal)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    for _ in range(12):
        ring.push(clk.win(counters={pfx + "coalesced_ops": 50},
                          gauges={pfx + "staging_depth": 1}))
        ctl.tick()
    # grew one extent per decision, saturated at the envelope
    assert ctl.knob_values()["balloon_x"] == 3
    assert len(bal.grows) == 3
    assert all(r == 1024 for r in bal.grows)
    # pressure gone + tiny working set -> no shrink rule fires here
    # (no workload sketch window on the fake server path); the knob
    # holds inside the envelope
    bal.pressure = False
    for _ in range(6):
        ring.push(clk.win(counters={pfx + "coalesced_ops": 50},
                          gauges={pfx + "staging_depth": 1}))
        ctl.tick()
    assert -3 <= ctl.knob_values()["balloon_x"] <= 3


def test_balloon_offset_advances_only_on_observed_movement():
    """A saturated grow (nothing parked to return) must NOT advance the
    offset — a phantom offset would let later park decisions walk real
    capacity below the hand-tuned starting point while the gauge read
    'back at the default' (review finding)."""
    _, ring = _fresh_ring()
    srv = _srv()
    bal = _FakeBalloon(circulating=4096, parked=1024)  # ONE real extent
    ctl = autotune.AutotuneController(
        AutotuneConfig(hysteresis_windows=1, balloon_every=1,
                       balloon_max_extents=3))
    ctl.bind_server(srv)
    ctl.bind_balloon(bal)
    pfx = srv.stats.prefix + "."
    clk = _Clock()
    for _ in range(10):
        ring.push(clk.win(counters={pfx + "coalesced_ops": 50},
                          gauges={pfx + "staging_depth": 1}))
        ctl.tick()
    # only the one real extent counted, despite sustained pressure
    assert ctl.knob_values()["balloon_x"] == 1
    assert ctl.stats["knob_balloon_x"] == 1.0
    assert bal.parked == 0


def test_kv_balloon_state_surface():
    from pmdfc_tpu.config import IndexConfig, KVConfig, TierConfig
    from pmdfc_tpu.kv import KV

    flat = KV(KVConfig(index=IndexConfig(capacity=256), page_words=8,
                       bloom=None))
    assert flat.balloon_state() is None
    tiered = KV(KVConfig(index=IndexConfig(capacity=256), page_words=8,
                         bloom=None,
                         tier=TierConfig(balloon_step=64)))
    st = tiered.balloon_state()
    assert st is not None
    assert st["step"] == 64
    assert st["circulating"] + st["parked"] <= st["cold_rows"] \
        or st["parked"] >= 0
    assert st["free"] >= 0
    # the backend forward reaches the same surface
    from pmdfc_tpu.client.backends import DirectBackend

    assert DirectBackend(tiered).balloon_state() == st


# -- live-knob hooks ---------------------------------------------------


def test_window_gate_semantics():
    g = _WindowGate(2)
    assert g.acquire(timeout=0.1) and g.acquire(timeout=0.1)
    assert g.active == 2
    # full: a bounded acquire times out
    assert not g.acquire(timeout=0.05)
    # widen live: the next acquire admits
    assert g.set_limit(3) == 3
    assert g.acquire(timeout=0.1)
    # shrink below occupancy: grants stand, new acquires wait
    g.set_limit(1)
    assert not g.acquire(timeout=0.05)
    for _ in range(3):
        g.release()
    assert g.active == 0
    g.release()  # over-release tolerated (the semaphore contract)
    assert g.active == 0
    assert g.acquire(timeout=0.1)
    assert g.limit == 1


def test_tcp_set_window_live_mid_traffic():
    reg, _ = _fresh_ring()
    from pmdfc_tpu.runtime.net import TcpBackend

    srv = _srv().start()
    try:
        be = TcpBackend("127.0.0.1", srv.port, page_words=8,
                        keepalive_s=None)
        keys = np.array([[1, 2], [3, 4]], np.uint32)
        pages = np.arange(16, dtype=np.uint32).reshape(2, 8)
        be.put(keys, pages)
        assert be.set_window(4) == 4
        out, found = be.get(keys)
        assert found.all() and (out == pages).all()
        assert be._window_sem.limit == 4
        be.close()
    finally:
        srv.stop()


class _FakeWindowBackend:
    def __init__(self):
        self.window = 32

    def set_window(self, n):
        self.window = max(1, int(n))
        return self.window

    def close(self):
        pass


def test_reconnecting_client_window_survives_reconnect():
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    built = []

    def factory():
        be = _FakeWindowBackend()
        built.append(be)
        return be

    rc = ReconnectingClient(factory, page_words=8)
    # a live-set BEFORE the first connect applies to the fresh backend
    assert rc.set_window(64) == 64
    be = rc._ensure(force=True)
    assert be is built[0] and built[0].window == 64
    assert rc.window == 64
    # live-set while attached forwards immediately
    rc.set_window(16)
    assert built[0].window == 16
    # a reconnect's FRESH backend gets the live value re-applied
    with rc._lock:
        rc._be = None
    be2 = rc._ensure(force=True)
    assert be2 is built[1] and built[1].window == 16


# -- check_teledump pins ----------------------------------------------


def test_check_autotune_pins():
    from tools.check_teledump import check_autotune

    good = {
        "gauges": {"ctl0.knob_dwell_us": 150.0,
                   "ctl0.knob_dwell_us_lo": 100.0,
                   "ctl0.knob_dwell_us_hi": 20000.0,
                   "ctl0.frozen": 0},
        "counters": {"ctl0.decisions": 3, "ctl0.reverts": 1},
    }
    assert check_autotune(good) == []
    oob = json.loads(json.dumps(good))
    oob["gauges"]["ctl0.knob_dwell_us"] = 50.0  # under the lo bound
    assert any("outside its declared envelope" in e
               for e in check_autotune(oob))
    drift = json.loads(json.dumps(good))
    drift["counters"]["ctl0.reverts"] = 9
    assert any("decisions" in e for e in check_autotune(drift))
    missing = json.loads(json.dumps(good))
    del missing["gauges"]["ctl0.knob_dwell_us_lo"]
    assert check_autotune(missing)
    # a missing _hi must be an ERROR, not render the knob invisible to
    # every pin (discovery keys on the value gauge; review finding)
    nohi = json.loads(json.dumps(good))
    del nohi["gauges"]["ctl0.knob_dwell_us_hi"]
    assert any("envelope siblings" in e for e in check_autotune(nohi))
    # and the symmetric orphan: envelope gauges without a value gauge
    orphan = json.loads(json.dumps(good))
    del orphan["gauges"]["ctl0.knob_dwell_us"]
    assert any("without its knob value" in e
               for e in check_autotune(orphan))
    frozen = json.loads(json.dumps(good))
    frozen["gauges"]["ctl0.frozen"] = 7
    assert any("frozen" in e for e in check_autotune(frozen))
    # no knob gauges at all -> nothing bound (v1/ctl-less docs parse)
    assert check_autotune({"gauges": {}, "counters": {}}) == []
