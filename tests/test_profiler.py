"""Device-time X-ray suite — profiler seams, lanes, wire verb, tooling.

Covers `runtime/profiler.py` end to end:

- the timed-fetch seam: `profiler.fetch` splits dispatch vs device
  time at the blocking fetch, feeds per-program `device_us` /
  `dispatch_us` histograms and the phase x program x shard table.
- per-shard lane reconciliation: driving the 4-shard coalesced plane,
  the profiler's `shard_ops` lanes equal the mesh scope's
  `shard{i}_ops` counters EXACTLY (both split on the same routed-op
  counts vector, by construction).
- the windowed `shard_imbalance` gauge under seeded skew: max/mean in
  [1, n_shards].
- `MSG_PROFILE` negotiation: HOLASI-acked captures land under the
  flight recorder's dump dir with cooldown; an old peer (no ack)
  degrades `server_profile` to None without touching the wire.
- `tools/proftool.py`: breakdown table schema + reconciliation column,
  Perfetto export rehomes device spans onto per-program lanes.
- kill-switch conformance: with `PMDFC_PROF` off nothing attaches,
  snapshots stay `pmdfc-telemetry-v2` with no `profile` key, and every
  seam is a passthrough.
"""

import json
import time

import numpy as np
import pytest

from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              TelemetryConfig)
from pmdfc_tpu.runtime import profiler as prof_mod
from pmdfc_tpu.runtime import telemetry as tele

pytestmark = pytest.mark.prof

W = 16


def _cfg(capacity=1 << 10):
    return KVConfig(index=IndexConfig(capacity=capacity),
                    bloom=BloomConfig(num_bits=1 << 15),
                    paged=True, page_words=W)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False)
    return np.stack([flat >> 10, flat & 0x3FF], -1).astype(np.uint32)


def _pages(keys):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, W + 1, dtype=np.uint32)[None, :])


def _mesh(n):
    import jax

    from pmdfc_tpu.parallel.shard import make_mesh

    return make_mesh(np.array(jax.devices()[:n]))


@pytest.fixture()
def fresh_registry(tmp_path):
    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15,
                                         dump_dir=str(tmp_path),
                                         dump_min_interval_s=0.0))
    yield reg
    tele.configure()


# --- 1. the timed-fetch seam ----------------------------------------------


def test_fetch_splits_device_and_dispatch(fresh_registry):
    p = prof_mod.install()
    t_launch = time.monotonic_ns()

    def thunk():
        time.sleep(0.002)
        return 41

    assert prof_mod.fetch("kv.get", "get", thunk, n_ops=8,
                          t_launch_ns=t_launch, ring=True) == 41
    snap = p.snapshot()
    assert snap["schema"] == "pmdfc-prof-v1"
    assert snap["launches"] == 1
    (row,) = snap["rows"]
    assert (row["phase"], row["program"], row["shard"]) == ("get", "kv.get", -1)
    assert row["ops"] == 8
    assert row["device_us"] >= 2000  # the 2ms sleep is device time
    hists = tele.get().snapshot()["histograms"]
    assert hists["prof.kv.get.device_us"]["count"] == 1
    # the launch stamp preceded the fetch: a real dispatch gap recorded
    assert hists["prof.kv.get.dispatch_us"]["count"] == 1
    # device time is monotone with the blocked window
    def longer():
        time.sleep(0.004)
    prof_mod.fetch("kv.get", "get", longer, n_ops=8)
    h = tele.get().snapshot()["histograms"]["prof.kv.get.device_us"]
    assert h["max"] >= 4000 and h["count"] == 2
    # the registry snapshot carries the v3 profile block when attached
    doc = tele.get().snapshot()
    assert doc["schema"] == "pmdfc-telemetry-v3"
    assert doc["profile"]["launches"] == 2
    # the ring=True fetch also rang a device span for the timeline
    dev = [r for r in tele.get().ring_tail()
           if r.get("src") == "prof" and r.get("op") == "device"]
    assert len(dev) == 1 and dev[0]["program"] == "kv.get"


def test_kv_sync_verbs_attribute_through_the_seam(fresh_registry):
    from pmdfc_tpu.kv import KV

    prof_mod.install()
    kv = KV(_cfg())
    keys = _keys(64)
    kv.insert(keys, _pages(keys))
    out, found = kv.get(keys)
    assert found.all()
    snap = tele.get().snapshot()["profile"]
    by_prog = {(r["program"], r["phase"]) for r in snap["rows"]}
    assert ("kv.insert", "put") in by_prog
    assert ("kv.get", "get") in by_prog
    assert snap["launches"] >= 2


# --- 2. per-shard lanes reconcile with the mesh counters ------------------


def test_shard_lanes_reconcile_with_mesh_ops(fresh_registry):
    from pmdfc_tpu.parallel.plane import PlaneBackend
    from pmdfc_tpu.parallel.shard import ShardedKV

    p = prof_mod.install()
    skv = ShardedKV(_cfg(), mesh=_mesh(4))
    be = PlaneBackend(skv)
    keys = _keys(400, seed=7)
    be.put(keys, _pages(keys))
    out, found = be.get(keys)
    assert found.all()
    snap = p.snapshot()
    assert snap["n_shards"] == 4
    mesh_ops = [int(be._tele.get(f"shard{i}_ops", 0)) for i in range(4)]
    # EXACT: note_launch splits on the same routed-counts vector that
    # feeds the mesh counters — the acceptance reconciliation pin
    assert snap["shard_ops"] == mesh_ops, (snap["shard_ops"], mesh_ops)
    assert sum(mesh_ops) == 800  # 400 puts + 400 gets, fully routed
    assert all(us > 0 for us in snap["shard_device_us"])
    # the table's per-shard rows roll up to the same ops
    per_shard = [0] * 4
    for r in snap["rows"]:
        if r["shard"] >= 0:
            per_shard[r["shard"]] += r["ops"]
    assert per_shard == mesh_ops


# --- 3. shard-imbalance gauge under seeded skew ---------------------------


def test_imbalance_gauge_tracks_skew_within_range(fresh_registry):
    p = prof_mod.install()
    skew = np.array([30, 2, 2, 2])
    for _ in range(p.config.imbalance_window):
        p.note_launch("plane.get", "get", 100.0, counts=skew, n_shards=4)
    snap = p.snapshot()
    # max/mean of the window lanes: 30 / (36/4) = 3.333..
    assert snap["imbalance"] == pytest.approx(30 / 9, abs=1e-3)
    assert 1.0 <= snap["imbalance"] <= 4.0
    g = tele.get().snapshot()["gauges"]["prof.shard_imbalance"]
    assert g == pytest.approx(snap["imbalance"], abs=1e-3)
    # balanced traffic pulls the next window back toward 1
    for _ in range(p.config.imbalance_window):
        p.note_launch("plane.get", "get", 100.0,
                      counts=np.array([9, 9, 9, 9]), n_shards=4)
    assert p.snapshot()["imbalance"] == pytest.approx(1.0, abs=1e-3)


# --- 4. MSG_PROFILE negotiation + old-peer fallback -----------------------
# The two wire drills spin real NetServers (~20 s together on the 1-cpu
# harness host), so they also carry `slow` and ride the agenda's
# tier1_overflow step per the PR 13/16 tier-1 budget notes — tier-1
# keeps the sub-5 s attribution/reconciliation/conformance drills.


@pytest.mark.slow
def test_msg_profile_capture_cooldown_and_old_peer(
        fresh_registry, tmp_path, monkeypatch):
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.kv import KV
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    kv = KV(_cfg())
    shared = DirectBackend(kv)

    # old peer first: the server predates the verb (PMDFC_PROF unset ->
    # off), a prof-wanting client gets no HOLASI ack and degrades to
    # None without a wire exchange
    monkeypatch.delenv("PMDFC_PROF", raising=False)
    old_srv = NetServer(lambda: shared).start()
    with old_srv:
        monkeypatch.setenv("PMDFC_PROF", "on")
        with TcpBackend("127.0.0.1", old_srv.port, page_words=W) as be:
            assert be.prof is False
            assert be.server_profile(50) is None

    # profiler-speaking server: capture lands under the dump dir
    monkeypatch.setenv("PMDFC_PROF", "on")
    prof_mod.install()
    srv = NetServer(lambda: shared).start()
    with srv, TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
        assert be.prof is True
        res = be.server_profile(50)
        assert res is not None
        assert res["duration_ms"] == 50
        assert res["path"].startswith(str(tmp_path))
        # cooldown: an immediate second request is refused (NOTEXIST)
        assert be.server_profile(50) is None


@pytest.mark.slow
def test_msg_profile_refused_without_dump_dir(monkeypatch):
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.kv import KV
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    monkeypatch.setenv("PMDFC_PROF", "on")
    tele.configure(TelemetryConfig(ring_capacity=1 << 12))  # no dump_dir
    try:
        prof_mod.install()
        shared = DirectBackend(KV(_cfg()))
        srv = NetServer(lambda: shared).start()
        with srv, TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
            assert be.prof is True  # verb negotiated fine
            assert be.server_profile(50) is None  # but capture refused
    finally:
        tele.configure()


# --- 5. proftool: breakdown table + Perfetto lanes ------------------------


def test_proftool_breakdown_and_perfetto(fresh_registry, tmp_path):
    import tools.proftool as proftool
    from pmdfc_tpu.parallel.plane import PlaneBackend
    from pmdfc_tpu.parallel.shard import ShardedKV

    prof_mod.install()
    skv = ShardedKV(_cfg(), mesh=_mesh(4))
    be = PlaneBackend(skv)
    keys = _keys(256, seed=3)
    be.put(keys, _pages(keys))
    be.get(keys)
    # plane launches skip the ring (their shard_program spans cover the
    # window); a sync-verb fetch rings the device span the timeline sees
    prof_mod.fetch("kv.get", "get", lambda: time.sleep(0.001), n_ops=4,
                   ring=True)
    dump = {"schema": "pmdfc-flight-v2", "rung": "manual", "detail": {},
            "ts_unix": 0.0, "telemetry": tele.get().snapshot(),
            "records": tele.get().ring_tail()}
    path = tmp_path / "flight.json"
    path.write_text(json.dumps(dump))

    agg = proftool._merge(proftool.load_docs([str(path)]))
    table = proftool.breakdown(agg)
    assert table["schema"] == "pmdfc-proftable-v1"
    assert table["launches"] > 0 and table["rows"]
    # every shard lane reconciles against the dump's mesh counters
    assert len(table["shards"]) == 4
    assert all(s["match"] == "yes" for s in table["shards"]), table["shards"]
    assert abs(sum(r["share"] for r in table["rows"]) - 1.0) < 0.01
    # the Perfetto export rehomes device spans to per-program lanes
    trace = proftool.device_lane_trace([str(path)])
    dev = [e for e in trace["traceEvents"]
           if str(e.get("tid", "")).startswith("device:")]
    assert dev and all(e["ph"] == "X" for e in dev)
    assert {e["tid"] for e in dev} == {"device:kv.get"}
    # the CLI table path renders without error
    assert proftool.main([str(path), "--json"]) == 0


# --- 6. kill-switch conformance: PMDFC_PROF=off is byte-identical v2 ------


def test_prof_off_snapshots_stay_v2(monkeypatch):
    from pmdfc_tpu.kv import KV

    monkeypatch.delenv("PMDFC_PROF", raising=False)
    tele.configure(TelemetryConfig(ring_capacity=1 << 12))
    try:
        assert prof_mod.active() is None
        kv = KV(_cfg())
        keys = _keys(32)
        kv.insert(keys, _pages(keys))
        out, found = kv.get(keys)
        assert found.all()
        snap = tele.get().snapshot()
        assert snap["schema"] == "pmdfc-telemetry-v2"
        assert "profile" not in snap
        assert not any(k.startswith("prof.") for k in snap["histograms"])
        assert not any(k.startswith("prof.") for k in snap["gauges"])
        # the seams are passthroughs: no device spans, thunk value intact
        assert prof_mod.fetch("kv.get", "get", lambda: 7, n_ops=1,
                              ring=True) == 7
        assert not any(r.get("src") == "prof" for r in tele.get().ring_tail())
        # serializes exactly like a pre-profiler tree's snapshot
        assert json.loads(json.dumps(snap)) == snap
    finally:
        tele.configure()
