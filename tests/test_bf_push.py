"""Server→client bloom push loop with dirty-block delta sync.

Ref: the server pushes its packed filter into each client's registered
bitmap every 10 s (`send_bf`, `server/rdma_svr.cpp:157-251,1361-1363`);
8 KB dirty-block machinery (`counting_bloom_filter.h:101-107`). The key
safety property: NO sequence of pushes interleaved with in-flight puts may
ever produce a false negative in a client mirror (a false negative turns a
completed put into a lost page; false positives only cost an RTT).
"""

import threading
import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import DirectBackend, EngineBackend
from pmdfc_tpu.client.cleancache import CleanCacheClient
from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.runtime.engine import Engine
from pmdfc_tpu.runtime.server import KVServer
from pmdfc_tpu.utils.hashing_np import query_packed_np

BLOCK_BYTES = 64  # tiny blocks so deltas exercise multi-block paths
CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 13),  # 256 words = 16 blocks of 16 words
    paged=True,
    page_words=16,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _server(**kw):
    eng = Engine(num_queues=2, queue_cap=1 << 10, batch=256, timeout_us=200,
                 arena_pages=512, page_bytes=CFG.page_words * 4)
    return KVServer(CFG, engine=eng, bf_push_s=0.0, bf_block_bytes=BLOCK_BYTES,
                    **kw)


def test_first_push_is_full_then_deltas():
    srv = _server()
    cc = CleanCacheClient(DirectBackend(srv.kv))
    cc._bloom = None  # simulate a client that never pulled
    srv.register_bf_client(cc)

    srv.kv.insert(_keys(50, seed=1), np.zeros((50, 16), np.uint32))
    r1 = srv.push_bloom_now()
    assert srv.bf_push_stats["full_pushes"] == 1
    np.testing.assert_array_equal(cc._bloom, srv.kv.packed_bloom())

    # no change ⇒ zero blocks travel
    r2 = srv.push_bloom_now()
    assert r2["blocks"] == 0
    assert srv.bf_push_stats["delta_pushes"] == 1

    # small change ⇒ only dirty blocks travel, mirror converges exactly
    srv.kv.insert(_keys(3, seed=2), np.zeros((3, 16), np.uint32))
    r3 = srv.push_bloom_now()
    assert 0 < r3["blocks"] < (CFG.bloom.num_bits // 8) // BLOCK_BYTES
    np.testing.assert_array_equal(cc._bloom, srv.kv.packed_bloom())
    assert cc.counters["bf_blocks_received"] == r3["blocks"]


def test_delta_push_reflects_deletes():
    """Eviction/delete propagation: a key deleted server-side disappears
    from the mirror after the next delta push (no stale-positive forever),
    while remaining keys stay present."""
    srv = _server()
    cc = CleanCacheClient(DirectBackend(srv.kv))
    srv.register_bf_client(cc)
    keys = _keys(40, seed=3)
    srv.kv.insert(keys, np.zeros((40, 16), np.uint32))
    srv.push_bloom_now()
    srv.kv.delete(keys[:20])
    srv.push_bloom_now()
    maybe = query_packed_np(cc._bloom, keys, cc.num_hashes)
    assert maybe[20:].all()          # still-present keys: never negative
    assert not maybe[:20].all()      # most deleted keys cleared (fp legal)


def test_no_false_negative_when_push_races_put():
    """A push computed BEFORE a put's server-side insert landed must not
    erase the put from the mirror (the overlay + re-add discipline)."""
    srv = _server()
    cc = CleanCacheClient(DirectBackend(srv.kv))
    srv.register_bf_client(cc)
    stale = srv.kv.packed_bloom()          # snapshot without the put
    cc.put_pages(np.array([9]), np.array([77]),
                 np.arange(16, dtype=np.uint32)[None])
    # the racing push arrives with the stale snapshot
    cc.receive_bloom_full(stale)
    assert query_packed_np(cc._bloom, np.array([[9, 77]], np.uint32),
                           cc.num_hashes)[0]
    # and the page actually serves
    out, found = cc.get_pages(np.array([9]), np.array([77]))
    assert found[0]


def test_stale_snapshot_delivery_rejected():
    """A push computed before a put but DELIVERED after a newer snapshot
    retired the put's overlay entry must not clear the put's bits."""
    import time as _t

    srv = _server()
    cc = CleanCacheClient(DirectBackend(srv.kv))
    srv.register_bf_client(cc)
    stale = srv.kv.packed_bloom()
    t_stale = _t.monotonic()
    cc.put_pages(np.array([4]), np.array([44]),
                 np.arange(16, dtype=np.uint32)[None])
    # fresh snapshot retires the overlay entry...
    t_fresh = _t.monotonic()
    cc.receive_bloom_full(srv.kv.packed_bloom(), t_snap=t_fresh)
    assert not cc._overlay  # retired
    # ...then the stale one arrives out of order: must be ignored
    cc.receive_bloom_full(stale, t_snap=t_stale)
    assert query_packed_np(cc._bloom, np.array([[4, 44]], np.uint32),
                           cc.num_hashes)[0]
    _, found = cc.get_pages(np.array([4]), np.array([44]))
    assert found[0]


def test_push_error_does_not_kill_other_clients():
    class BadSink:
        def receive_bloom_full(self, *a, **k):
            raise RuntimeError("boom")

    srv = _server()
    good = CleanCacheClient(DirectBackend(srv.kv))
    srv.register_bf_client(BadSink())
    srv.register_bf_client(good)
    srv.kv.insert(_keys(10, seed=8), np.zeros((10, 16), np.uint32))
    srv.push_bloom_now()
    assert srv.bf_push_stats["errors"] == 1
    np.testing.assert_array_equal(good._bloom, srv.kv.packed_bloom())


def test_pushed_client_stops_pulling():
    """With the push loop running, the client's mirror tracks server truth
    without any refresh_bloom() pulls."""
    srv = _server().start()
    try:
        srv.bf_push_s = 0.01
        srv._bf_thread = threading.Thread(
            target=srv._bf_push_loop, daemon=True)
        srv._bf_thread.start()
        with EngineBackend(srv, slice_pages=64) as be:
            cc = CleanCacheClient(be)
            srv.register_bf_client(cc)
            pulls_before = cc.counters["bf_refreshes"]
            keys = _keys(64, seed=4)
            pages = np.tile(np.arange(16, dtype=np.uint32), (64, 1))
            for lo in range(0, 64, 16):
                cc.put_pages(keys[lo:lo+16, 0], keys[lo:lo+16, 1],
                             pages[lo:lo+16])
            deadline = time.time() + 5
            while time.time() < deadline:
                if srv.bf_push_stats["cycles"] >= 3:
                    break
                time.sleep(0.01)
            assert srv.bf_push_stats["cycles"] >= 3
            assert cc.counters["bf_pushes"] >= 1  # at least the full push
            srv.push_bloom_now()  # settle: mirror reflects every put
            assert cc.counters["bf_refreshes"] == pulls_before
            # no false negative for any completed put
            maybe = query_packed_np(cc._bloom, keys, cc.num_hashes)
            assert maybe.all()
            out, found = cc.get_pages(keys[:, 0], keys[:, 1])
            assert found.all()
    finally:
        srv.stop()


def test_concurrent_put_storm_under_push_never_false_negative():
    """Puts stream through the engine while the pusher fires every few ms;
    at every observation point a completed put's key answers 'maybe'."""
    srv = _server().start()
    try:
        srv.bf_push_s = 0.002
        srv._bf_thread = threading.Thread(
            target=srv._bf_push_loop, daemon=True)
        srv._bf_thread.start()
        with EngineBackend(srv, slice_pages=128) as be:
            cc = CleanCacheClient(be)
            srv.register_bf_client(cc)
            keys = _keys(512, seed=5)
            pages = np.tile(np.arange(16, dtype=np.uint32), (512, 1))
            violations = []

            def putter():
                for lo in range(0, 512, 32):
                    cc.put_pages(keys[lo:lo+32, 0], keys[lo:lo+32, 1],
                                 pages[lo:lo+32])
                    done = keys[: lo + 32]
                    maybe = query_packed_np(cc._bloom, done, cc.num_hashes)
                    if not maybe.all():
                        violations.append(lo)

            t = threading.Thread(target=putter)
            t.start()
            t.join(timeout=60)
            assert not t.is_alive()
            assert violations == []
            maybe = query_packed_np(cc._bloom, keys, cc.num_hashes)
            assert maybe.all()
    finally:
        srv.stop()
