"""Sharded KV over an 8-device virtual mesh (ref NUMA_KV, `server/NuMA_KV.cpp`).

Every behavior is checked against the single-chip `kv.KV` ground truth —
the sharded path must be semantically indistinguishable.
"""

import numpy as np
import pytest

from pmdfc_tpu.config import BloomConfig, IndexConfig, IndexKind, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.parallel import ShardedKV, make_mesh
from pmdfc_tpu.utils.hashing import shard_of
from pmdfc_tpu.utils.keys import pack_key

import jax
import jax.numpy as jnp


CFG = KVConfig(
    index=IndexConfig(capacity=1 << 12),
    bloom=BloomConfig(num_bits=1 << 15),
    paged=False,
)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False).astype(np.uint32)
    return np.stack([flat >> 10, flat & 0x3FF], axis=-1).astype(np.uint32)


@pytest.fixture(scope="module", params=[
    "a2a",
    pytest.param("broadcast", marks=pytest.mark.slow),
])
def skv(request):
    kv = ShardedKV(CFG, dispatch=request.param)
    assert kv.n_shards == 8, "conftest must provide 8 virtual devices"
    return kv


def test_shard_routing_balanced():
    keys = jnp.asarray(_keys(4096))
    owners = np.asarray(shard_of(keys, 8))
    counts = np.bincount(owners, minlength=8)
    assert counts.min() > 4096 // 8 * 0.7  # roughly uniform

def test_insert_get_roundtrip(skv):
    keys = _keys(500, seed=1)
    vals = np.stack([keys[:, 0] ^ 0xABCD, keys[:, 1] + 1], -1).astype(np.uint32)
    skv.insert(keys, vals)
    out, found = skv.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, vals)


def test_miss_is_legal(skv):
    out, found = skv.get(np.array([[0xDEAD, 0xBEEF]], np.uint32))
    assert not found.any()
    assert (out == 0).all()


def test_delete(skv):
    keys = _keys(64, seed=2)
    vals = np.ones((64, 2), np.uint32)
    skv.insert(keys, vals)
    hit = skv.delete(keys[:32])
    assert hit.all()
    _, found = skv.get(keys[:32])
    assert not found.any()
    _, found2 = skv.get(keys[32:])
    assert found2.all()


@pytest.mark.parametrize("dispatch", [
    "a2a",
    pytest.param("broadcast", marks=pytest.mark.slow),
])
def test_matches_single_chip_ground_truth(dispatch):
    """Same op sequence on ShardedKV and KV produces identical results."""
    skv, kv = ShardedKV(CFG, dispatch=dispatch), KV(CFG)
    keys = _keys(300, seed=3)
    vals = np.stack([keys[:, 1], keys[:, 0]], -1).astype(np.uint32)
    skv.insert(keys, vals)
    kv.insert(keys, vals)
    probe = np.concatenate([keys[:150], _keys(150, seed=4)])
    out_s, f_s = skv.get(probe)
    out_1, f_1 = kv.get(probe)
    np.testing.assert_array_equal(f_s, f_1)
    np.testing.assert_array_equal(out_s, out_1)
    assert skv.stats() == {
        k: v for k, v in kv.stats().items() if k != "uptime_s"
    }


@pytest.mark.parametrize("dispatch", [
    "a2a",
    pytest.param("broadcast", marks=pytest.mark.slow),
])
def test_dup_keys_last_wins_matches(dispatch):
    """Cross-shard batches preserve batch order for duplicate keys."""
    skv, kv = ShardedKV(CFG, dispatch=dispatch), KV(CFG)
    base = _keys(60, seed=21)
    keys = np.concatenate([base, base[::2], base[::3]])  # heavy duplication
    vals = np.stack(
        [np.arange(len(keys), dtype=np.uint32),
         np.arange(len(keys), dtype=np.uint32) * 7], -1
    )
    skv.insert(keys, vals)
    kv.insert(keys, vals)
    out_s, f_s = skv.get(base)
    out_1, f_1 = kv.get(base)
    np.testing.assert_array_equal(f_s, f_1)
    np.testing.assert_array_equal(out_s, out_1)


def test_a2a_find_anyway_utilization_recovery():
    skv = ShardedKV(CFG)
    keys = _keys(200, seed=30)
    vals = np.stack([keys[:, 1], keys[:, 0]], -1).astype(np.uint32)
    skv.insert(keys, vals)
    got_v, found, slot, shard = skv.find_anyway(keys[:50])
    assert found.all()
    np.testing.assert_array_equal(got_v, vals[:50])
    assert (slot >= 0).all()
    from pmdfc_tpu.utils.hashing import shard_of as shard_fn
    import jax.numpy as jnp
    np.testing.assert_array_equal(
        shard, np.asarray(shard_fn(jnp.asarray(keys[:50]), 8)).astype(np.int64)
    )
    # keys never inserted are not found by the scan
    _, nf, _, nsh = skv.find_anyway(_keys(20, seed=31))
    assert not nf.any() and (nsh == -1).all()
    u = skv.utilization()
    assert abs(u - 200 / skv.capacity()) < 1e-9
    assert skv.recovery()
    out, f = skv.get(keys)
    assert f.all()


@pytest.mark.parametrize("dispatch", [
    "a2a",
    pytest.param("broadcast", marks=pytest.mark.slow),
])
def test_packed_bloom_matches_single_chip(dispatch):
    """OR of per-shard packed filters == the single-chip filter, bit-for-bit
    (each key lives on exactly one shard; counters are non-negative)."""
    skv, kv = ShardedKV(CFG, dispatch=dispatch), KV(CFG)
    keys = _keys(400, seed=40)
    vals = np.ones((400, 2), np.uint32)
    skv.insert(keys, vals)
    kv.insert(keys, vals)
    skv.delete(keys[:100])
    kv.delete(keys[:100])
    np.testing.assert_array_equal(skv.packed_bloom(), kv.packed_bloom())
    per = skv.packed_bloom_per_shard()
    assert per.shape[0] == 8
    np.testing.assert_array_equal(
        np.bitwise_or.reduce(per, axis=0), kv.packed_bloom()
    )


@pytest.mark.slow  # fast-tier budget (README "Test tiers"): this invariant's cheap variant stays fast; the deep one runs in the full suite
def test_sharded_checkpoint_roundtrip(tmp_path):
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 10),
        bloom=BloomConfig(num_bits=1 << 12),
        paged=True,
        page_words=32,
    )
    skv = ShardedKV(cfg)
    keys = _keys(100, seed=50)
    rng = np.random.default_rng(51)
    pages = rng.integers(0, 1 << 32, size=(100, 32), dtype=np.uint64).astype(
        np.uint32
    )
    skv.insert(keys, pages)
    path = str(tmp_path / "sharded.npz")
    skv.save(path)
    skv2 = ShardedKV(cfg)
    skv2.restore(path)
    assert skv2.stats() == skv.stats()  # before the get bumps them
    out, found = skv2.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    # wrong-config restore fails loudly
    other = ShardedKV(KVConfig(index=IndexConfig(capacity=1 << 11),
                               bloom=None, paged=False))
    with pytest.raises(ValueError, match="mismatch"):
        other.restore(path)


@pytest.mark.slow  # fast-tier budget (README "Test tiers"): this invariant's cheap variant stays fast; the deep one runs in the full suite
def test_a2a_bucket_overflow_is_reported_not_silent():
    """Adversarial batch: every key routed to ONE shard; overflow rows come
    back as legal drops/misses and the stats account for them."""
    from pmdfc_tpu.utils.hashing import shard_of as shard_fn
    import jax.numpy as jnp

    skv = ShardedKV(CFG)
    pool = _keys(4096, seed=60)
    owner = np.asarray(shard_fn(jnp.asarray(pool), 8))
    mine = pool[owner == 3][:256]
    assert len(mine) == 256, "need 256 keys owned by shard 3"
    vals = np.ones((len(mine), 2), np.uint32)
    res = skv.insert(mine, vals)
    # pair capacity for w=256, n=8: bl=32 -> c_pair=16; each source shard
    # holds 32 rows all destined to shard 3 -> 16 dropped per source.
    dropped = res.dropped.sum()
    assert dropped == 8 * 16
    out, found = skv.get(mine)
    placed = ~res.dropped
    assert found[placed].all()
    assert not found[res.dropped].any()
    s = skv.stats()
    assert s["puts"] == 256
    assert s["drops"] == int(dropped)
    # deletes are loss-free even for the same adversarial routing: every
    # placed key must actually invalidate (a silently failed delete would
    # leave stale data behind)
    hit = skv.delete(mine)
    np.testing.assert_array_equal(hit, placed)
    _, refound = skv.get(mine)
    assert not refound.any()


def test_extent_cross_shard():
    """Covers land on different shards; every spanned page resolves."""
    skv = ShardedKV(CFG)
    skv.insert_extent([7, 1000], [0, 1 << 20], 300)
    offsets = np.arange(0, 310, 7, dtype=np.uint32)
    probe = np.stack(
        [np.full_like(offsets, 7), 1000 + offsets], -1
    ).astype(np.uint32)
    out, found = skv.get_extent(probe)
    spanned = offsets < 300
    np.testing.assert_array_equal(found, spanned)
    expect = (1 << 20) + offsets[spanned].astype(np.uint64) * 4096
    got = out[spanned, 0].astype(np.uint64) << 32 | out[spanned, 1]
    np.testing.assert_array_equal(got, expect)


def test_extent_matches_single_chip():
    skv, kv = ShardedKV(CFG), KV(CFG)
    for store in (skv, kv):
        store.insert_extent([1, 64], [0, 4096], 100)
        store.insert_extent([2, 0], [1, 0], 17)
    probe = np.array(
        [[1, 64], [1, 163], [1, 164], [2, 0], [2, 16], [2, 17], [3, 5]],
        np.uint32,
    )
    out_s, f_s = skv.get_extent(probe)
    out_1, f_1 = kv.get_extent(probe)
    np.testing.assert_array_equal(f_s, f_1)
    np.testing.assert_array_equal(out_s, out_1)


def test_paged_mode_sharded():
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 10),
        bloom=None,
        paged=True,
        page_words=64,
    )
    skv = ShardedKV(cfg)
    keys = _keys(40, seed=5)
    rng = np.random.default_rng(6)
    pages = rng.integers(0, 1 << 32, size=(40, 64), dtype=np.uint64).astype(
        np.uint32
    )
    skv.insert(keys, pages)
    out, found = skv.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, pages)


def test_eviction_propagates(skv_=None):
    """FIFO eviction still reports evicted keys through the combine."""
    cfg = KVConfig(
        index=IndexConfig(capacity=16, cluster_slots=16),
        bloom=BloomConfig(num_bits=1 << 10),
        paged=False,
    )
    skv = ShardedKV(cfg)
    keys = _keys(256, seed=7)
    vals = np.ones((256, 2), np.uint32)
    # capacity is 16 slots/shard × 8 shards = 128 < 256. Fill in a first
    # batch, then a second batch must FIFO-evict prior residents (a single
    # overfull batch would *drop* its own overflow instead — also legal).
    skv.insert(keys[:128], vals[:128])
    res = skv.insert(keys[128:], vals[128:])
    evicted = (res.evicted != 0xFFFFFFFF).any(axis=-1)
    assert evicted.sum() > 0
    assert skv.stats()["evictions"] == int(evicted.sum())


@pytest.mark.slow  # fast-tier budget (README "Test tiers"): this invariant's cheap variant stays fast; the deep one runs in the full suite
def test_sharded_cceh_roundtrip():
    from pmdfc_tpu.config import IndexKind

    cfg = KVConfig(
        index=IndexConfig(
            kind=IndexKind.CCEH, capacity=1 << 9, segment_slots=128,
            split_headroom=2,
        ),
        bloom=None,
        paged=False,
    )
    kv = ShardedKV(cfg, mesh=make_mesh())
    rng = np.random.default_rng(13)
    lo = rng.choice(1 << 20, size=700, replace=False).astype(np.uint32)
    ks = np.asarray(pack_key(np.ones(700, np.uint32), lo))
    vals = np.stack([np.zeros(700, np.uint32), lo], axis=-1)
    for i in range(0, 700, 128):
        kv.insert(ks[i : i + 128], vals[i : i + 128])
    out, found = kv.get(ks)
    s = kv.stats()
    assert (~found).sum() <= s["evictions"] + s["drops"]
    np.testing.assert_array_equal(out[found, 1], lo[found])


@pytest.mark.slow  # fast-tier budget (README "Test tiers"): this invariant's cheap variant stays fast; the deep one runs in the full suite
def test_cleancache_client_over_sharded_server():
    """The full client stack (cleancache + bloom mirror) rides the sharded
    server unchanged: DirectBackend speaks the same surface for KV and
    ShardedKV, and the OR-combined packed filter keeps mirror semantics."""
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.client.cleancache import CleanCacheClient

    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 10),
        bloom=BloomConfig(num_bits=1 << 13),
        paged=True,
        page_words=32,
    )
    skv = ShardedKV(cfg)
    cc = CleanCacheClient(DirectBackend(skv))
    rng = np.random.default_rng(70)
    pages = rng.integers(0, 1 << 32, size=(60, 32), dtype=np.uint64).astype(
        np.uint32
    )
    cc.put_pages(np.full(60, 11), np.arange(60), pages)
    out, found = cc.get_pages(np.full(60, 11), np.arange(60))
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    # absent keys short-circuit on the mirrored (OR-combined) filter:
    # most must never generate server traffic, not just bump a counter
    before = cc.counters["actual_gets"]
    out2, found2 = cc.get_pages(np.full(30, 11), np.arange(500, 530))
    assert not found2.any()
    assert cc.counters["bf_short_circuits"] >= 25
    assert cc.counters["actual_gets"] - before <= 5
    hit = cc.invalidate_pages(np.full(10, 11), np.arange(10))
    assert hit.all()
    _, refound = cc.get_pages(np.full(10, 11), np.arange(10))
    assert not refound.any()


def test_node_of_and_shard_report():
    """GetNodeID + per-node load stats analogs (`NuMA_KV.cpp:136-151`,
    `CCEH_hybrid.h:202-206`): routing is consistent with where keys land,
    and the per-shard report sums to the global truth."""
    skv = ShardedKV(CFG)
    keys = _keys(256, seed=21)
    vals = np.stack([keys[:, 0] ^ 0xABCD, keys[:, 1] + 1], -1).astype(
        np.uint32
    )
    skv.insert(keys, vals)
    nodes = skv.node_of(keys)
    assert nodes.shape == (256,)
    assert nodes.min() >= 0 and nodes.max() < skv.n_shards
    # find_anyway reports the shard each key actually lives on
    _, found, _, shard = skv.find_anyway(keys)
    assert found.all()
    assert np.array_equal(shard, nodes)
    rep = skv.shard_report()
    assert rep["n_shards"] == skv.n_shards
    assert sum(rep["occupancy"]) == 256
    assert sum(rep["stats"]["puts"]) == skv.stats()["puts"]
    # murmur3 routing spreads a random key set across every shard
    assert all(o > 0 for o in rep["occupancy"])
    assert "crf" not in rep  # LRFU plane is opt-in (the reference's -DLRFU)


def test_lrfu_stats_plane():
    """Per-shard LRFU load metrics (`CCEH_hybrid.h:202-206` Metric{atime,
    crf} + freq, the -DLRFU plane the reference stubs): freq counts every
    routed request, atime tracks the last touch tick, and a shard hammered
    repeatedly accumulates more crf than one touched once."""
    skv = ShardedKV(CFG, lrfu_stats=True)
    keys = _keys(256, seed=31)
    vals = np.stack([keys[:, 0], keys[:, 1]], -1).astype(np.uint32)
    skv.insert(keys, vals)
    nodes = skv.node_of(keys)
    # hammer one shard's keys with repeated gets
    hot = int(np.bincount(nodes, minlength=skv.n_shards).argmax())
    hot_keys = keys[nodes == hot]
    for _ in range(4):
        skv.get(hot_keys)
    rep = skv.shard_report()
    assert sum(rep["freq"]) == 256 + 4 * len(hot_keys)
    assert rep["atime"][hot] == 5  # last tick that routed to the hot shard
    cold = int(np.argmin(rep["crf"]))
    assert rep["crf"][hot] > rep["crf"][cold]
    # decayed-recency: a shard untouched since insert has crf <= its count
    counts = np.bincount(nodes, minlength=skv.n_shards)
    assert rep["crf"][cold] <= counts[cold]


@pytest.mark.slow  # fast-tier budget (README "Test tiers"): this invariant's cheap variant stays fast; the deep one runs in the full suite
def test_sampled_touch_sharded():
    """ShardedKV honors touch_sample_every: identical results, counters
    bumped one batch in N across shards (parity with kv.KV sampling)."""
    cfg = KVConfig(
        index=IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 12,
                          touch_sample_every=4, decay_every_gets=0),
        bloom=None, paged=False,
    )
    skv = ShardedKV(cfg, dispatch="a2a")
    keys = _keys(256, seed=9)
    skv.insert(keys, keys)
    for _ in range(8):
        out, found = skv.get(keys)
        assert found.all()
        np.testing.assert_array_equal(out, keys)
    total = int(np.asarray(skv.state.index.counters).sum())
    assert total == 2 * 256, total  # batches 4 and 8 only


def test_health_and_shard_report_tier_stats_agree():
    """ISSUE 5 satellite: `KVServer.health` and `shard_report` used to
    recompute the tier-counter block independently (the `migrated_bytes`
    derivation was forked between kv.py and shard.py and could drift).
    Both now read `tier.counters_dict` — assert the surfaces agree
    exactly, per counter, after real migration traffic."""
    from pmdfc_tpu import tier as tier_mod
    from pmdfc_tpu.config import TierConfig
    from pmdfc_tpu.runtime.engine import Engine
    from pmdfc_tpu.runtime.server import KVServer

    W = 16
    tcfg = KVConfig(
        index=IndexConfig(capacity=1 << 10), bloom=None,
        paged=True, page_words=W,
        tier=TierConfig(promote_touches=1, ghost_rows=64),
    )

    def touch(store):
        keys = _keys(192, seed=41)
        pages = np.repeat(keys[:, 1:2], W, axis=1).astype(np.uint32)
        store.insert(keys, pages)
        for _ in range(3):          # cold hits -> promotions
            _, found = store.get(keys[:64])
            assert found.all()

    # single chip: health's kv block vs the KV tier surface
    kv = KV(tcfg)
    touch(kv)
    srv = KVServer(tcfg, kv=kv, engine=Engine(
        num_queues=2, queue_cap=1 << 8, batch=128, timeout_us=200,
        arena_pages=256, page_bytes=W * 4))
    try:
        health = srv.health()
    finally:
        srv.engine.close()
    ts = kv.tier_stats()
    expect = tier_mod.counters_dict(
        np.asarray(kv.state.pool.tstats), W * 4)
    assert expect["promotions"] > 0
    for name in list(tier_mod.TIER_STAT_NAMES) + ["migrated_bytes"]:
        assert health["kv"][name] == ts[name] == expect[name], name

    # mesh: shard_report's per-shard tier block sums to tier_stats()/
    # stats(), under the same naming + derived-field rule
    skv = ShardedKV(tcfg)
    touch(skv)
    rep, ts, merged = skv.shard_report(), skv.tier_stats(), skv.stats()
    expect = tier_mod.counters_dict(
        np.asarray(skv.state.pool.tstats).sum(axis=0), W * 4)
    for name in tier_mod.TIER_STAT_NAMES:
        assert sum(rep["tier"][name]) == ts[name] == merged[name], name
    assert ts["migrated_bytes"] == merged["migrated_bytes"] \
        == expect["migrated_bytes"]
