"""Smoke coverage for the committed soak + insert-profile harnesses.

The soak (`bench/soak.py`) is the reproducible form of the round-3/4
serving-path soak claim in PERF.md; the profiler (`bench/insert_profile.py`)
is the decomposition the insert optimizations were driven by. Both are
agenda steps — a harness that only works on the day it was written is a
lost tunnel window, so CI pins their contracts at toy sizes.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(
        [sys.executable, "-m", *args], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=timeout,
    )


@pytest.mark.slow  # fast-tier 300 s contract (VERDICT r4 item 8): the
# subprocess soak costs ~13 s; fast-tier serving-path coverage lives in
# tests/test_runtime.py's engine storms, the full soak runs in slow + the
# on-chip agenda
def test_soak_smoke_clean_run():
    """A short soak must serve verified traffic, hold the clean-cache
    invariant, and exit 0 (no --history: CPU is a legal device)."""
    p = _run(["pmdfc_tpu.bench.soak", "--minutes", "0.08", "--threads", "2",
              "--verb", "64", "--capacity", "16384", "--keyspace", "512",
              "--page-words", "16", "--engine-batch", "1024"])
    assert p.returncode == 0, p.stderr.decode()[-2000:]
    out = json.loads(p.stdout.decode().strip().splitlines()[-1])
    assert out["metric"] == "soak_verified_pages_per_sec"
    assert out["verified_pages"] > 0
    assert out["mismatches"] == 0
    assert out["deleted_hits"] == 0
    assert out["clean_cache_invariant_ok"] is True
    # the headline counts deliveries, not requests
    assert out["value"] <= out["requests_per_sec"]


@pytest.mark.slow
def test_soak_history_offchip_exits_3(tmp_path):
    """--history off-chip must exit 3 and append nothing (the resumable
    agenda's done-marker discipline)."""
    hist = tmp_path / "h.jsonl"
    p = _run(["pmdfc_tpu.bench.soak", "--minutes", "0.03", "--threads", "1",
              "--verb", "32", "--capacity", "8192", "--keyspace", "256",
              "--page-words", "16", "--engine-batch", "256",
              "--history", str(hist)])
    assert p.returncode == 3, p.stderr.decode()[-2000:]
    assert not hist.exists() or not hist.read_text().strip()


@pytest.mark.slow
def test_insert_profile_smoke():
    """The profiler's pieces must sum near its fused ground truth and the
    JSON record must carry every phase."""
    p = _run(["pmdfc_tpu.bench.insert_profile", "--device", "cpu",
              "--n", "16384", "--capacity", "32768", "--reps", "1"])
    assert p.returncode == 0, p.stderr.decode()[-2000:]
    out = json.loads(p.stdout.decode().strip().splitlines()[-1])
    ns = out["ns_per_key"]
    assert set(ns) == {"hash", "plan", "rank", "gather", "scatter", "index"}
    assert all(v > 0 for v in ns.values())
    assert out["insert_mops_equiv"] > 0
