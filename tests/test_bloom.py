"""Counting bloom filter tests (ref behavior: `server/bftest.cpp` +
`server/util/counting_bloom_filter.h`)."""

import numpy as np
import jax.numpy as jnp

from pmdfc_tpu.config import BloomConfig
from pmdfc_tpu.ops import bloom
from pmdfc_tpu.utils.keys import pack_key


CFG = BloomConfig(num_bits=1 << 14, num_hashes=4)


def keys_of(lo):
    lo = np.asarray(lo, np.uint32)
    return pack_key(np.full_like(lo, 7), lo)


def test_insert_query_no_false_negatives():
    st = bloom.init(CFG)
    ks = keys_of(np.arange(256))
    st = bloom.insert_batch(st, ks, jnp.ones(256, bool), num_hashes=4)
    assert bool(bloom.query_batch(st, ks, num_hashes=4).all())


def test_absent_mostly_rejected():
    st = bloom.init(CFG)
    ks = keys_of(np.arange(256))
    st = bloom.insert_batch(st, ks, jnp.ones(256, bool), num_hashes=4)
    absent = keys_of(np.arange(100_000, 100_256))
    fp = np.asarray(bloom.query_batch(st, absent, num_hashes=4)).mean()
    assert fp < 0.1


def test_delete_removes():
    st = bloom.init(CFG)
    ks = keys_of(np.arange(64))
    ones = jnp.ones(64, bool)
    st = bloom.insert_batch(st, ks, ones, num_hashes=4)
    st = bloom.delete_batch(st, ks, ones, num_hashes=4)
    assert int(np.asarray(st.counters).sum()) == 0
    assert not bool(bloom.query_batch(st, ks, num_hashes=4).any())


def test_duplicate_inserts_accumulate():
    st = bloom.init(CFG)
    ks = keys_of([5, 5, 5, 9])
    st = bloom.insert_batch(st, ks, jnp.ones(4, bool), num_hashes=4)
    st = bloom.delete_batch(st, keys_of([5]), jnp.ones(1, bool), num_hashes=4)
    # two of three insertions of key 5 remain
    assert bool(bloom.query_batch(st, keys_of([5]), num_hashes=4).all())


def test_packed_matches_counters():
    st = bloom.init(CFG)
    ks = keys_of(np.arange(128))
    st = bloom.insert_batch(st, ks, jnp.ones(128, bool), num_hashes=4)
    packed = bloom.to_packed_bits(st)
    probe = keys_of(np.arange(0, 4096))
    a = np.asarray(bloom.query_batch(st, probe, num_hashes=4))
    b = np.asarray(bloom.query_packed(packed, probe, num_hashes=4))
    np.testing.assert_array_equal(a, b)


def test_dirty_blocks():
    st = bloom.init(CFG)
    p0 = bloom.to_packed_bits(st)
    st = bloom.insert_batch(st, keys_of([3]), jnp.ones(1, bool), num_hashes=4)
    p1 = bloom.to_packed_bits(st)
    dirty = np.asarray(bloom.dirty_blocks(p0, p1, block_bytes=64))
    assert dirty.any() and not dirty.all()
