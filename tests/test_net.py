"""TCP messenger tests — tcp_style transport parity over a real socket.

Ref: the tcp_style client's o2net-derived messenger (`client/tcp_style/
tcp.c`), message vocabulary (`tcp.h:36-44`), keepalive/idle-timeout
machinery (`tcp.h:30-34`), and the server's periodic BF push
(`server/rdma_svr.cpp:157-251`). These tests put an actual process/socket
boundary under the client stack — including a subprocess client, the
multi-node analog of the reference's VM-driven runs (SURVEY §4.6).
"""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pmdfc_tpu.client.backends import LocalBackend
from pmdfc_tpu.client.cleancache import CleanCacheClient
from pmdfc_tpu.runtime.net import NetServer, ProtocolError, TcpBackend
from pmdfc_tpu.utils.hashing_np import query_packed_np

W = 16  # page words — tiny pages keep socket traffic fast


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(
        W, dtype=np.uint32
    )


def _local_server(**kw):
    shared = LocalBackend(page_words=W, capacity=1 << 12)
    return NetServer(lambda: shared, **kw).start(), shared


def _kv_server(kv_cls=None, capacity=1 << 12, **kw):
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
    from pmdfc_tpu.kv import KV

    cfg = KVConfig(index=IndexConfig(capacity=capacity),
                   bloom=BloomConfig(num_bits=1 << 13),
                   paged=True, page_words=W)
    kv = (kv_cls or KV)(cfg)
    shared = DirectBackend(kv)
    return NetServer(lambda: shared, **kw).start(), kv


def test_roundtrip_put_get_invalidate():
    srv, _ = _local_server()
    with srv, TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
        keys = _keys(64)
        pages = _pages(keys)
        be.put(keys, pages)
        out, found = be.get(keys)
        assert found.all()
        assert np.array_equal(out, pages)
        # misses are legal, NOTEXIST path when nothing is found
        other = _keys(16, seed=9)
        out2, found2 = be.get(other)
        assert not found2.any()
        assert (out2 == 0).all()
        # mixed hit/miss compaction
        mix = np.concatenate([keys[:3], other[:3], keys[3:6]])
        out3, found3 = be.get(mix)
        assert found3.tolist() == [True] * 3 + [False] * 3 + [True] * 3
        assert np.array_equal(out3[found3], _pages(mix[found3]))
        hit = be.invalidate(keys[:8])
        assert hit.all()
        _, found4 = be.get(keys[:8])
        assert not found4.any()


@pytest.mark.slow  # fast-tier 300 s contract: extent verbs stay covered
# fast by tests/test_runtime.py::test_extent_verbs_through_transport_storm;
# the TCP-socket variant (~6.5 s of real-socket handshakes) rides slow
def test_extent_verbs_over_tcp():
    """Range registration + cover resolution ride the messenger (round 4):
    insert_extent/get_extent against a real-KV NetServer over a socket,
    verifying the reference's address arithmetic (value + diff*4096,
    `KV.cpp:170-173`) and the miss boundary."""
    srv, kv = _kv_server(capacity=1 << 13)
    with srv, TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
        uncovered = be.insert_extent([7, 512], [3, 1 << 20], 40)
        assert uncovered == 0
        ds = np.array([0, 13, 39, 40], np.uint32)
        probe = np.stack([np.full(4, 7, np.uint32), 512 + ds], -1)
        vals, found = be.get_extent(probe)
        assert found.tolist() == [True, True, True, False]
        np.testing.assert_array_equal(
            vals[:3, 1], (1 << 20) + ds[:3] * 4096)
        np.testing.assert_array_equal(vals[:3, 0], np.full(3, 3))
        # page ops keep working on the same channel afterwards
        keys = _keys(16)
        be.put(keys, _pages(keys))
        out, pfound = be.get(keys)
        assert pfound.all() and np.array_equal(out, _pages(keys))
        assert kv.stats()["extent_puts"] == 1


def test_client_bounds_oversized_server_frame():
    """The CLIENT side of the frame bound (VERDICT-r3 weak 5): a server
    announcing a payload beyond max_frame_bytes must fail the read before
    allocating it, not pre-allocate the 1 GiB default."""
    import socket as socket_mod

    from pmdfc_tpu.runtime.net import (
        MAGIC, MSG_HOLASI, MSG_SENDPAGE, _send_msg, _HDR)

    held = []

    def evil_server(port_box, ready):
        lsock = socket_mod.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(1)
        port_box.append(lsock.getsockname()[1])
        ready.set()
        conn, _ = lsock.accept()
        held.append(conn)  # keep alive past the test body
        conn.recv(1 << 16)  # swallow HOLA
        _send_msg(conn, MSG_HOLASI, words=W)  # legit handshake
        conn.recv(1 << 16)  # swallow the GET
        # reply header claims a 256 MiB payload (over the 1 MiB bound)
        conn.sendall(_HDR.pack(MAGIC, MSG_SENDPAGE, 0, 0, W, 0,
                               256 << 20, 0))
        lsock.close()

    port_box, ready = [], threading.Event()
    th = threading.Thread(target=evil_server, args=(port_box, ready),
                          daemon=True)
    th.start()
    ready.wait(5)
    be = TcpBackend("127.0.0.1", port_box[0], page_words=W,
                    keepalive_s=None, max_frame_bytes=1 << 20)
    with pytest.raises((ProtocolError, ConnectionError, ValueError)):
        be.get(_keys(4))
    th.join(timeout=5)


def test_handshake_word_mismatch_rejected():
    srv, _ = _local_server()
    with srv:
        with pytest.raises(ProtocolError):
            TcpBackend("127.0.0.1", srv.port, page_words=W * 2)


def test_cleancache_client_over_tcp():
    srv, kv = _kv_server()
    with srv:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W)
        cc = CleanCacheClient(be)
        oids = np.full(32, 7, np.uint32)
        idxs = np.arange(32, dtype=np.uint32)
        pages = np.arange(32, dtype=np.uint32)[:, None] + np.zeros(
            (32, W), np.uint32
        )
        cc.put_pages(oids, idxs, pages)
        out, found = cc.get_pages(oids, idxs)
        assert found.all()
        assert np.array_equal(out, pages)
        assert cc.get_page(7, 999) is None
        # client-initiated pull fetches the real packed filter over the wire
        cc.refresh_bloom()
        assert cc._bloom is not None
        assert np.array_equal(cc._bloom, np.asarray(kv.packed_bloom()))
        cc.close()
        be.close()


@pytest.mark.slow
def test_bf_push_full_then_delta():
    srv, kv = _kv_server(bf_block_bytes=64)
    with srv:
        received = []

        class Sink:
            def receive_bloom_full(self, packed, t_snap=None):
                received.append(("full", packed.copy(), t_snap))

            def receive_bloom_blocks(self, idx, blocks, wpb, t_snap=None):
                received.append(("delta", idx.copy(), blocks.copy(), wpb))

        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        bloom_sink=Sink())
        keys = _keys(32)
        be.put(keys, _pages(keys))
        deadline = time.time() + 5
        while not any(
            d["push"] for d in srv._clients.values()
        ) and time.time() < deadline:
            time.sleep(0.01)
        srv.push_bloom_now()
        deadline = time.time() + 5
        while not received and time.time() < deadline:
            time.sleep(0.01)
        assert received and received[0][0] == "full"
        assert np.array_equal(received[0][1], np.asarray(kv.packed_bloom()))
        # second cycle with no changes: nothing travels
        n0 = len(received)
        srv.push_bloom_now()
        time.sleep(0.2)
        assert len(received) == n0
        # new puts dirty a few blocks: only those travel
        more = _keys(8, seed=5)
        be.put(more, _pages(more))
        srv.push_bloom_now()
        deadline = time.time() + 5
        while len(received) == n0 and time.time() < deadline:
            time.sleep(0.01)
        kind, idx, blocks, wpb = received[-1]
        assert kind == "delta"
        full = np.asarray(kv.packed_bloom())
        assert np.array_equal(blocks, full.reshape(-1, wpb)[idx])
        assert len(idx) < len(full) // wpb  # strictly partial
        be.close()


@pytest.mark.slow
def test_push_race_no_false_negative():
    """Puts racing the push loop must never yield a mirror false negative —
    the stamp-echo discipline's contract across the process boundary."""
    srv, kv = _kv_server(bf_block_bytes=64)
    with srv:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W)
        cc = CleanCacheClient(be)
        # push channel shares the op channel's client id so the server's
        # stamp echo refers to THIS client's puts
        push_be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                             bloom_sink=cc, client_id=be.client_id)
        deadline = time.time() + 5
        while not any(
            d["push"] for d in srv._clients.values()
        ) and time.time() < deadline:
            time.sleep(0.01)
        all_keys = _keys(512, seed=3)
        stop = threading.Event()

        def pusher():
            while not stop.is_set():
                srv.push_bloom_now()
                time.sleep(0.002)

        t = threading.Thread(target=pusher)
        t.start()
        try:
            for lo in range(0, len(all_keys), 16):
                chunk = all_keys[lo : lo + 16]
                oids, idxs = chunk[:, 0], chunk[:, 1]
                pages = _pages(chunk)
                cc.put_pages(oids, idxs, pages)
        finally:
            stop.set()
            t.join()
        srv.push_bloom_now()
        time.sleep(0.1)
        # every completed put must still pass the client's bloom gate
        with cc._bloom_lock:
            bloom = cc._bloom
            overlay = dict(cc._overlay)
        assert bloom is not None
        in_bloom = query_packed_np(bloom, all_keys, cc.num_hashes)
        in_overlay = np.array(
            [(int(k[0]), int(k[1])) in overlay for k in all_keys]
        )
        assert (in_bloom | in_overlay).all(), "mirror false negative"
        cc.close()
        push_be.close()
        be.close()


@pytest.mark.slow
def test_idle_timeout_kills_and_keepalive_survives():
    srv, _ = _local_server(idle_timeout_s=0.3)
    with srv:
        # no keepalive: connection dies after idling past the timeout
        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None)
        keys = _keys(4)
        be.put(keys, _pages(keys))
        time.sleep(0.8)
        with pytest.raises(ConnectionError):
            be.put(keys, _pages(keys))
        assert srv.stats["idle_kills"] >= 1
        # keepalive faster than the timeout: connection survives the idle
        be2 = TcpBackend("127.0.0.1", srv.port, page_words=W,
                         keepalive_s=0.1)
        be2.put(keys, _pages(keys))
        time.sleep(0.8)
        be2.put(keys, _pages(keys))  # still alive
        be2.close()


@pytest.mark.slow
def test_reconnecting_client_over_tcp_restart():
    """Kill the server, degrade to legal results, restart on the same port,
    reconnect + invalidation-journal replay — the o2net reconnect drill
    across a real socket."""
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    srv, shared = _local_server()
    port = srv.port

    def factory():
        return TcpBackend("127.0.0.1", port, page_words=W,
                          keepalive_s=None)

    rc = ReconnectingClient(factory, page_words=W, retry_delay_s=0.01)
    keys = _keys(32, seed=11)
    pages = _pages(keys)
    rc.put(keys, pages)
    out, found = rc.get(keys)
    assert found.all() and np.array_equal(out, pages)

    srv.stop()
    # ops degrade, no exception escapes
    out, found = rc.get(keys)
    assert not found.any()
    rc.put(keys, pages)  # dropped put is legal
    rc.invalidate(keys[:4])  # journaled for replay
    assert rc.stats()["disconnects"] >= 1

    # restart on the same port with the SAME store (snapshot-restore analog:
    # the invalidated keys are resurrected until the journal replays)
    srv2 = NetServer(lambda: shared, port=port).start()
    try:
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            out, found = rc.get(keys[4:])
            if found.all():
                ok = True
                break
            time.sleep(0.05)
        assert ok, "client never reconnected"
        # journal replayed: the 4 invalidated keys are gone again
        _, found = rc.get(keys[:4])
        assert not found.any()
        assert rc.stats()["reconnects"] >= 1
        assert rc.stats()["replayed_invalidates"] >= 4
    finally:
        rc.close()
        srv2.stop()


_CHILD = r"""
import sys
import numpy as np
from pmdfc_tpu.runtime.net import TcpBackend

port, W, seed = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
rng = np.random.default_rng(seed)
flat = rng.choice(1 << 22, size=128, replace=False)
keys = np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)
pages = (keys[:, 0] * 7 + keys[:, 1])[:, None] + np.arange(W, dtype=np.uint32)
with TcpBackend("127.0.0.1", port, page_words=W) as be:
    be.put(keys, pages)
    out, found = be.get(keys)
    assert found.all(), found.sum()
    assert np.array_equal(out, pages)
print("CHILD_OK")
"""


@pytest.mark.slow
def test_multiprocess_clients():
    """Three concurrent client PROCESSES against one server — the 3-VM
    orchestration analog (`script.sh:3-41`) at test scale."""
    srv, _ = _local_server()
    with srv:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _CHILD, str(srv.port), str(W),
                 str(seed)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for seed in (1, 2, 3)
        ]
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err
            assert "CHILD_OK" in out
        assert srv.stats["connects"] >= 3


@pytest.mark.slow
def test_multinode_harness_small():
    """The orchestration driver end-to-end at test scale (2 processes)."""
    proc = subprocess.run(
        [sys.executable, "-m", "pmdfc_tpu.bench.multinode",
         "--clients", "2", "--ops", "400", "--file-pages", "128",
         "--ram-pages", "32", "--page-words", "32", "--capacity", "2048"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    agg = __import__("json").loads(proc.stdout.strip().splitlines()[-1])
    assert agg["ok"] == 2
    assert agg["verify_failures"] == 0


def test_server_survives_garbage_and_truncation():
    """Malformed frames must kill only the offending connection — the
    accept loop and other clients keep serving (TEST_Z / BUG_ON tier:
    `server/rdma_svr.h:41-42` dies, a userspace server must not)."""
    import socket as socklib

    from pmdfc_tpu.runtime import net as net_mod
    from pmdfc_tpu.runtime.net import _HDR, _send_msg

    srv, _ = _local_server()
    with srv:
        good = TcpBackend("127.0.0.1", srv.port, page_words=W)
        keys = _keys(8)
        good.put(keys, _pages(keys))

        socks = []
        try:
            # bad magic
            s1 = socklib.create_connection(("127.0.0.1", srv.port))
            socks.append(s1)
            s1.sendall(b"\xde\xad\xbe\xef" * 9)
            # truncated header then close
            s2 = socklib.create_connection(("127.0.0.1", srv.port))
            s2.sendall(b"\x13\xfc")
            s2.close()
            # oversized declared payload
            s3 = socklib.create_connection(("127.0.0.1", srv.port))
            socks.append(s3)
            s3.sendall(_HDR.pack(0xFC13, 0, 0, 0, 0, 0, 1 << 40, 0))
            # valid HOLA then garbage op (valid frame, unknown verb)
            s4 = socklib.create_connection(("127.0.0.1", srv.port))
            socks.append(s4)
            s4.settimeout(5)  # a silent server must FAIL, not hang CI
            _send_msg(s4, net_mod.MSG_HOLA, count=77, words=W)
            s4.recv(4096)  # HOLASI
            _send_msg(s4, 99)
            # valid HOLA then a frame whose payload was bit-flipped in
            # flight: the CRC must catch it (bad_frames), never parse it
            s5 = socklib.create_connection(("127.0.0.1", srv.port))
            socks.append(s5)
            s5.settimeout(5)
            _send_msg(s5, net_mod.MSG_HOLA, count=78, words=W)
            s5.recv(4096)  # HOLASI
            kk = _keys(4)
            body = (np.ascontiguousarray(kk, np.uint32).tobytes()
                    + _pages(kk).tobytes())
            hdr0 = _HDR.pack(0xFC13, net_mod.MSG_PUTPAGE, 0, 4, W, 0,
                             len(body), 0)
            import zlib

            crc = zlib.crc32(body, zlib.crc32(hdr0))
            frame = bytearray(hdr0[:-4] + crc.to_bytes(4, "little") + body)
            frame[_HDR.size + 10] ^= 0x40  # the in-flight bit flip
            s5.sendall(bytes(frame))

            time.sleep(0.2)
            # the healthy client still works
            out, found = good.get(keys)
            assert found.all()
            assert np.array_equal(out, _pages(keys))
            deadline = time.time() + 5
            while srv.stats["bad_frames"] < 2 and time.time() < deadline:
                time.sleep(0.02)
            # s4 (unknown op) and s5 (crc mismatch) both counted
            assert srv.stats["bad_frames"] >= 2
            # and the flipped put must NOT have landed
            _, f5 = good.get(kk)
            assert not f5.any(), "a corrupted frame's payload was applied"
        finally:
            for s in socks:
                s.close()
            good.close()


@pytest.mark.slow
def test_tcp_over_sharded_mesh_server():
    """The full stack at once: client process boundary (TCP messenger) →
    shared backend → 8-way mesh-sharded KV (`ShardedKV`, the NUMA_KV
    analog). The reference's closest shape is N kernel clients against the
    NUMA-dispatch server (`NuMA_KV.cpp` behind `rdma_svr.cpp`)."""
    from pmdfc_tpu.parallel import ShardedKV

    srv, skv = _kv_server(kv_cls=ShardedKV, capacity=1 << 10)
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
            cc = CleanCacheClient(be)
            keys = _keys(96, seed=31)
            oids, idxs = keys[:, 0], keys[:, 1]
            pages = _pages(keys)
            cc.put_pages(oids, idxs, pages)
            out, found = cc.get_pages(oids, idxs)
            assert found.all()
            assert np.array_equal(out, pages)
            # the keys really spread across the mesh
            rep = skv.shard_report()
            assert sum(1 for o in rep["occupancy"] if o > 0) >= 4
            # misses + invalidates flow through the same wire
            assert cc.get_page(12345, 67) is None
            hit = cc.invalidate_pages(oids[:5], idxs[:5])
            assert hit.all()
            _, found2 = cc.get_pages(oids[:5], idxs[:5])
            assert not found2.any()
            # mirror ⊇ server filter: the overlay re-adds bits of its own
            # (even invalidated) puts — false positives are legal, a
            # missing server bit never is
            cc.refresh_bloom()
            server_bits = skv.packed_bloom()
            assert np.array_equal(cc._bloom | server_bits, cc._bloom)
            cc.close()


def test_engine_backend_factory_over_tcp():
    """The production server shape: per-connection EngineBackend factories
    (disjoint arena slices per client) in front of a running KVServer —
    request coalescing and the TCP boundary composed."""
    from pmdfc_tpu.client.backends import EngineBackend
    from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
    from pmdfc_tpu.runtime.engine import Engine
    from pmdfc_tpu.runtime.server import KVServer

    cfg = KVConfig(index=IndexConfig(capacity=1 << 12),
                   bloom=BloomConfig(num_bits=1 << 13),
                   paged=True, page_words=W)
    eng = Engine(num_queues=2, queue_cap=1 << 10, batch=256, timeout_us=200,
                 arena_pages=512, page_bytes=W * 4)
    with KVServer(cfg, engine=eng).start() as ksrv:
        srv = NetServer(lambda: EngineBackend(ksrv)).start()
        with srv:
            b1 = TcpBackend("127.0.0.1", srv.port, page_words=W)
            b2 = TcpBackend("127.0.0.1", srv.port, page_words=W)
            k1, k2 = _keys(32, seed=41), _keys(32, seed=42)
            p1, p2 = _pages(k1), _pages(k2)
            # interleaved clients: distinct server-side arena slices must
            # never bleed into each other
            b1.put(k1, p1)
            b2.put(k2, p2)
            out1, f1 = b1.get(k1)
            out2, f2 = b2.get(k2)
            assert f1.all() and np.array_equal(out1, p1)
            assert f2.all() and np.array_equal(out2, p2)
            _, fx = b1.get(_keys(8, seed=43))
            assert not fx.any()
            b1.close()
            b2.close()


@pytest.mark.slow
def test_pull_then_push_stamp_domains_coherent():
    """ADVICE r2 (medium): a client-initiated BFPULL must not freeze the
    push path. The pull snapshot's stamp comes from the SERVER's applied-put
    stamp (one clock domain with push frames); stamping it with local 'now'
    made every later push look stale until a newer put out-stamped it."""
    srv, kv = _kv_server(bf_block_bytes=64)
    with srv:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W)
        cc = CleanCacheClient(be)  # __init__ pulls via refresh_bloom()
        push_be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                             bloom_sink=cc, client_id=be.client_id)
        deadline = time.time() + 5
        while not any(
            d["push"] for d in srv._clients.values()
        ) and time.time() < deadline:
            time.sleep(0.01)
        # put through THIS client, then pull again: the echoed stamp is the
        # put's send stamp, not local now
        ks = _keys(4, seed=11)
        cc.put_pages(ks[:, 0], ks[:, 1], _pages(ks))
        cc.refresh_bloom()
        # another client's put dirties the filter; the subsequent PUSH
        # must be APPLIED (not stale-rejected)
        other = TcpBackend("127.0.0.1", srv.port, page_words=W)
        more = _keys(8, seed=12)
        other.put(more, _pages(more))
        n0 = cc.counters["bf_pushes"]
        srv.push_bloom_now()
        deadline = time.time() + 5
        while cc.counters["bf_pushes"] == n0 and time.time() < deadline:
            time.sleep(0.01)
        assert cc.counters["bf_pushes"] > n0, (
            "push after pull was stale-rejected: stamp domains diverged"
        )
        # and the other client's keys are visible through the mirror gate
        with cc._bloom_lock:
            assert query_packed_np(cc._bloom, more, cc.num_hashes).all()
        other.close()
        push_be.close()
        be.close()


def test_stale_delta_or_merges_instead_of_dropping():
    """A delta frame that lost the race to a newer snapshot must still
    contribute its SET bits (the server's delta baseline already moved past
    it, so a dropped frame's adds would never be resent)."""
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.config import BloomConfig, IndexConfig, KVConfig
    from pmdfc_tpu.kv import KV

    cfg = KVConfig(index=IndexConfig(capacity=1 << 12),
                   bloom=BloomConfig(num_bits=1 << 13),
                   paged=True, page_words=W)
    kv = KV(cfg)
    cc = CleanCacheClient(DirectBackend(kv))
    full0 = kv.packed_bloom()
    cc.receive_bloom_full(full0, t_snap=time.monotonic())
    t_stale = time.monotonic()
    ks = _keys(6, seed=21)
    kv.insert(ks, _pages(ks))
    packed = kv.packed_bloom()
    wpb = 16
    diff = (full0 ^ packed).reshape(-1, wpb)
    idx = np.flatnonzero((diff != 0).any(axis=1))
    blocks = packed.reshape(-1, wpb)[idx]
    # a fresh snapshot arrives first...
    cc.receive_bloom_full(packed, t_snap=time.monotonic())
    # ...then the delta computed EARLIER lands (stale stamp): its set bits
    # must merge, not vanish
    before = cc._bloom.copy()
    cc.receive_bloom_blocks(idx, blocks, wpb, t_snap=t_stale)
    with cc._bloom_lock:
        assert (cc._bloom & before == before).all(), "stale delta cleared bits"
        assert query_packed_np(cc._bloom, ks, cc.num_hashes).all()


# --- pipelined wire protocol + cross-connection coalescer (netpipe) -----


def test_pipeline_negotiation_and_env_killswitch(monkeypatch):
    """Default clients negotiate the pipelined protocol (seq-echo ack in
    the HOLASI count field); `PMDFC_NET_PIPE=off` forces lockstep on both
    sides even when a NetConfig is supplied — and both modes serve the
    same verbs."""
    from pmdfc_tpu.config import NetConfig

    srv, _ = _local_server()
    with srv:
        with TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
            assert be.pipelined  # lockstep server still acks seq-echo
            keys = _keys(16)
            be.put(keys, _pages(keys))
            out, found = be.get(keys)
            assert found.all() and np.array_equal(out, _pages(keys))
        # explicit opt-out beats the default
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        pipeline=False) as be2:
            assert not be2.pipelined
            out, found = be2.get(_keys(16))
            assert found.all()
    monkeypatch.setenv("PMDFC_NET_PIPE", "off")
    srv2, _ = _local_server(net=NetConfig())
    with srv2:
        assert not srv2._coalesce  # env kills the coalescer too
        with TcpBackend("127.0.0.1", srv2.port, page_words=W) as be3:
            assert not be3.pipelined  # no ack ⇒ lockstep fallback
            keys = _keys(8, seed=2)
            be3.put(keys, _pages(keys))
            _, found = be3.get(keys)
            assert found.all()


@pytest.mark.netpipe
def test_coalesced_server_fuses_across_connections():
    """The tentpole invariant: N connections' verbs land in shared fused
    flushes (flush_max > 1), results route back per connection with no
    cross-connection bleed."""
    from pmdfc_tpu.config import NetConfig

    # long dwell + generous settle so the barrier-released ops coalesce
    # deterministically
    srv, _ = _local_server(net=NetConfig(flush_timeout_us=200_000,
                                         settle_us=30_000))
    with srv:
        n_conns = 6
        bes = [TcpBackend("127.0.0.1", srv.port, page_words=W,
                          keepalive_s=None) for _ in range(n_conns)]
        all_keys = [_keys(24, seed=60 + i) for i in range(n_conns)]
        barrier = threading.Barrier(n_conns)
        errs: list = []

        def worker(i):
            try:
                barrier.wait()
                bes[i].put(all_keys[i], _pages(all_keys[i]))
                out, found = bes[i].get(all_keys[i])
                assert found.all(), i
                assert np.array_equal(out, _pages(all_keys[i])), i
                # a miss probe stays a miss (padding rows match nothing)
                _, f2 = bes[i].get(_keys(8, seed=90 + i))
                assert not f2.any(), i
            except Exception as e:  # noqa: BLE001
                errs.append((i, repr(e)))

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_conns)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs, errs
        assert srv.stats["flushes"] >= 1
        assert srv.stats["flush_max"] > 1, (
            "no cross-connection coalescing happened")
        for b in bes:
            b.close()


@pytest.mark.netpipe
def test_pipelined_storm_shared_backend():
    """8 threads share ONE pipelined TcpBackend: replies must match by
    sequence id under full-window concurrency — every page content-
    verifies against its own key, no waiter ever wedges."""
    from pmdfc_tpu.config import NetConfig

    shared = LocalBackend(page_words=W, capacity=1 << 13)
    srv = NetServer(lambda: shared, net=NetConfig()).start()
    with srv:
        be = TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, window=16)
        assert be.pipelined
        errs: list = []

        def storm(i):
            try:
                keys = _keys(48, seed=200 + i)
                pages = _pages(keys)
                for _ in range(6):
                    be.put(keys, pages)
                    out, found = be.get(keys)
                    assert found.all(), i
                    assert np.array_equal(out, pages), i
                hit = be.invalidate(keys[:8])
                assert hit.all(), i
                _, f2 = be.get(keys[:8])
                assert not f2.any(), i
            except Exception as e:  # noqa: BLE001
                errs.append((i, repr(e)))

        ts = [threading.Thread(target=storm, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not any(t.is_alive() for t in ts), "stuck waiter"
        assert not errs, errs
        be.close()


@pytest.mark.netpipe
def test_coalesced_vs_lockstep_conformance():
    """The compatibility contract: a seeded mixed workload produces
    verb-for-verb IDENTICAL results on the legacy lockstep path
    (serialize_ops + non-pipelined client) and the coalesced+pipelined
    path, against real KVs."""
    from pmdfc_tpu.config import NetConfig

    def run(coalesced: bool):
        srv, _ = _kv_server(
            capacity=1 << 12,
            **({"net": NetConfig(flush_timeout_us=5000, settle_us=200)}
               if coalesced else {"serialize_ops": True}))
        results = []
        with srv, TcpBackend("127.0.0.1", srv.port, page_words=W,
                             keepalive_s=None,
                             pipeline=coalesced) as be:
            assert be.pipelined == coalesced
            rng = np.random.default_rng(77)
            universe = _keys(256, seed=77)
            for _ in range(120):
                op = int(rng.integers(4))
                lo = int(rng.integers(0, 240))
                n = int(rng.integers(1, 16))
                sel = universe[lo:lo + n]
                if op == 0:
                    be.put(sel, _pages(sel))
                    results.append(("put", n))
                elif op in (1, 2):
                    out, found = be.get(sel)
                    results.append(("get", found.tolist(),
                                    out[found].tolist()))
                else:
                    hit = be.invalidate(sel)
                    results.append(("inval", hit.tolist()))
        return results

    assert run(False) == run(True), (
        "coalesced path diverged from the lockstep reference")


# --- net-level chaos drills (ChaosProxy, deterministic armed faults) ----


def _proxied_client(srv, proxy, **kw):
    """ReconnectingClient whose factory dials the server THROUGH the
    chaos proxy — the full rung-2/rung-3 client stack."""
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    kw.setdefault("op_timeout_s", 2.0)

    def factory():
        return TcpBackend("127.0.0.1", proxy.port, page_words=W,
                          keepalive_s=None, **kw)

    return ReconnectingClient(factory, page_words=W, retry_delay_s=0.01,
                              max_retry_delay_s=0.2, seed=3)


def test_chaos_bitflip_is_dropped_frame_then_reconnect():
    """A bit-flipped frame must fail the CRC (bad_frames), kill only that
    connection, degrade the op legally, and the client must re-attach and
    verify content afterwards."""
    from pmdfc_tpu.runtime.failure import ChaosProxy

    srv, _ = _local_server()
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=11) as px:
        rc = _proxied_client(srv, px)
        keys = _keys(32, seed=51)
        pages = _pages(keys)
        rc.put(keys, pages)
        px.flip_next(1)
        out, found = rc.get(keys)  # flipped request: legal degraded result
        assert not found.any() and (out == 0).all()
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            out, found = rc.get(keys)
            if found.all():
                ok = True
                break
            time.sleep(0.02)
        assert ok, "client never recovered after the flipped frame"
        np.testing.assert_array_equal(out, pages)
        assert srv.stats["bad_frames"] >= 1
        assert px.stats["flipped_frames"] == 1
        assert rc.stats()["disconnects"] >= 1
        rc.close()


def test_chaos_duplicate_frame_desync_is_detected():
    """A duplicated request frame desynchronizes the reply stream; the
    client's reply validation must detect it (drop + reconnect), never
    return another op's payload."""
    from pmdfc_tpu.runtime.failure import ChaosProxy

    srv, _ = _local_server()
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=12) as px:
        rc = _proxied_client(srv, px)
        keys = _keys(16, seed=52)
        pages = _pages(keys)
        rc.put(keys, pages)
        px.dup_next(1)
        out, found = rc.get(keys[:8])  # duplicated GETPAGE: 2 replies queued
        # this op's own reply is fine; the NEXT op reads the stale
        # duplicate and must fail the stream, not misparse it
        assert np.array_equal(out[found], _pages(keys[:8])[found])
        rc.put(keys[:4], pages[:4])  # desync detected here (legal drop)
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            out, found = rc.get(keys)
            if found.all():
                ok = True
                break
            time.sleep(0.02)
        assert ok
        np.testing.assert_array_equal(out, pages)
        assert px.stats["duplicated_frames"] == 1
        rc.close()


def test_chaos_truncated_frame_and_half_open_are_bounded():
    """A truncated frame (torn write) kills the connection; a half-open
    proxy (peer vanished, socket alive) must cost at most the op timeout
    — both degrade to legal results in bounded time."""
    from pmdfc_tpu.runtime.failure import ChaosProxy

    srv, _ = _local_server()
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=13) as px:
        rc = _proxied_client(srv, px, op_timeout_s=1.0)
        keys = _keys(8, seed=53)
        pages = _pages(keys)
        rc.put(keys, pages)
        px.truncate_next(1)
        out, found = rc.get(keys)
        assert not found.any()
        deadline = time.time() + 5
        while not rc.connected and time.time() < deadline:
            rc.get(keys[:1])
            time.sleep(0.02)
        assert rc.connected
        px.half_open_next(1)
        t0 = time.monotonic()
        out, found = rc.get(keys)  # swallowed: recv times out
        dt = time.monotonic() - t0
        assert not found.any()
        assert dt < 4.0, f"half-open hang not bounded ({dt:.1f}s)"
        assert px.stats["truncated_frames"] == 1
        assert px.stats["half_open_drops"] >= 1
        rc.close()


def test_kill_op_conn_is_idempotent():
    """Two phases deciding to kill the SAME connection (a fused flush
    racing the reader's own teardown, or two phases sharing a sick
    conn's ops) must drop it exactly once: the second `_kill_op_conn`
    is a no-op — never a re-shutdown/re-notify against a possibly
    already-reused fd."""
    import socket as socket_mod

    from pmdfc_tpu.runtime.net import _ConnState, _StagedOp

    srv, _ = _local_server()
    with srv:
        a, b = socket_mod.socketpair()
        cs = _ConnState(a, {"addr": "drill"})
        op1 = _StagedOp(cs, 0, 1, 0, 0)
        op2 = _StagedOp(cs, 0, 2, 0, 0)  # second phase, same conn
        drops: list = []
        orig = srv._drop_conn
        srv._drop_conn = lambda conn: drops.append(conn)
        try:
            srv._kill_op_conn(op1)
            assert not cs.alive and len(drops) == 1
            srv._kill_op_conn(op2)
            assert len(drops) == 1, "second kill re-dropped the conn"
            assert not cs.alive
        finally:
            srv._drop_conn = orig
        a.close()
        b.close()
