"""The on-chip certification artifact machinery (bench.py supervisor).

Round-4 requirement (VERDICT r3 item 1): any bench.py invocation that
completes a real device=tpu run must persist the full record to
BENCH_TPU_CERT.json, and a later invocation that finds the tunnel down
must emit that certified record — labeled — instead of a CPU number.
These tests exercise the helpers hermetically (no JAX, no tunnel).
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_supervisor",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "CERT_PATH", str(tmp_path / "CERT.json"))
    monkeypatch.setattr(mod, "HISTORY_PATH", str(tmp_path / "HIST.jsonl"))
    return mod


TPU_RECORD = {
    "metric": "test_KV_get_throughput", "value": 53.5, "unit": "Mops/s",
    "vs_baseline": 10.92, "device": "tpu", "device_kind": "v5e",
}


def test_cert_roundtrip(bench_mod):
    assert bench_mod._load_cert() is None  # no file yet
    bench_mod._write_cert(TPU_RECORD)
    cert = bench_mod._load_cert()
    assert cert is not None
    assert cert["value"] == 53.5 and cert["device"] == "tpu"
    assert "cert_ts" in cert and "cert_writer" in cert
    # atomic write: no .tmp residue
    assert not os.path.exists(bench_mod.CERT_PATH + ".tmp")


def test_cert_rejects_non_tpu(bench_mod):
    """A CPU record must never certify (the fallback would lie)."""
    bench_mod._write_cert({**TPU_RECORD, "device": "cpu"})
    assert bench_mod._load_cert() is None


def test_cert_rejects_zero_value(bench_mod):
    bench_mod._write_cert({**TPU_RECORD, "value": 0.0})
    assert bench_mod._load_cert() is None


def test_cert_rejects_stale(bench_mod):
    """A cert inherited from a previous round (older than the freshness
    bound) must not be emitted as this round's primary artifact — it
    measured older code (review finding: regression masking)."""
    import datetime

    old = (datetime.datetime.now(datetime.timezone.utc)
           - datetime.timedelta(hours=17)).isoformat()
    with open(bench_mod.CERT_PATH, "w") as f:
        json.dump({**TPU_RECORD, "cert_ts": old}, f)
    assert bench_mod._load_cert() is None
    # ...and one missing its timestamp entirely is equally untrusted
    with open(bench_mod.CERT_PATH, "w") as f:
        json.dump(TPU_RECORD, f)
    assert bench_mod._load_cert() is None


def test_cert_rejects_corrupt_file(bench_mod):
    with open(bench_mod.CERT_PATH, "w") as f:
        f.write("{not json")
    assert bench_mod._load_cert() is None


def test_history_scan_skips_truncated_tail(bench_mod):
    with open(bench_mod.HISTORY_PATH, "w") as f:
        f.write(json.dumps({"ts": "t1", "value": 1.0}) + "\n")
        f.write('{"ts": "t2", "value": 2.0, "trunc')  # killed mid-append
    assert bench_mod._last_tpu_record()["value"] == 1.0
