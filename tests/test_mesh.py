"""Mesh-sharded serving plane drills (marker: mesh).

Runs on the forced multi-device CPU host mesh the suite-wide conftest
sets up (`--xla_force_host_platform_device_count=8`, the
`bench/multihost_bench.py` trick) — the CI stand-in for a real chip
mesh. Four layers:

1. **Partitioning subsystem** — the axis-rule tables cover every state
   leaf for every pool layout, rules validate against the live mesh,
   and the host router's binning is loss-free and order-stable
   (bit-identical owners to the device hash).
2. **Plane verbs** — routed phases produce single-device results, the
   read-only GET path accounts its stats host-side, and per-shard
   attribution (shard_report / mesh scope) adds up.
3. **The serving drill** — a seeded mixed workload through the
   coalesced NetServer on a 4-shard plane is verb-for-verb
   BIT-IDENTICAL to the single-device path, and `PMDFC_MESH=off`
   collapses the whole plane back to that path (the `PMDFC_NET_PIPE`
   conformance discipline applied to topology). KVServer's `mesh=`
   engine path rides the same drill.
4. **Reshard restore** — snapshot on N shards, restore on M≠N: zero
   lost live pages, deleted keys stay deleted (legal misses only),
   extents still resolve, counters carried.
"""

from __future__ import annotations

import numpy as np
import pytest

from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              MeshConfig, NetConfig, TierConfig)

pytestmark = pytest.mark.mesh

W = 16


def _cfg(capacity=1 << 10, tier=None, bloom=True, paged=True):
    return KVConfig(
        index=IndexConfig(capacity=capacity),
        bloom=BloomConfig(num_bits=1 << 15) if bloom else None,
        paged=paged, page_words=W, tier=tier)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False)
    return np.stack([flat >> 10, flat & 0x3FF], -1).astype(np.uint32)


def _pages(keys):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, W + 1, dtype=np.uint32)[None, :])


def _mesh(n):
    import jax

    from pmdfc_tpu.parallel.shard import make_mesh

    return make_mesh(np.array(jax.devices()[:n]))


# --- 1. partitioning subsystem --------------------------------------------


@pytest.mark.parametrize("cfg", [
    _cfg(), _cfg(tier=TierConfig(ghost_rows=32)), _cfg(bloom=False),
    _cfg(paged=False),
], ids=["flat", "tiered", "no-bloom", "unpaged"])
def test_axis_rules_cover_every_leaf(cfg):
    from pmdfc_tpu.parallel import partitioning as pt

    rows = pt.describe(cfg)
    assert rows, "empty state?"
    for r in rows:
        # every leaf resolves to a spec whose leading axis is the mesh
        # axis (the shard dimension is what partitions)
        assert r["axes"][0] == pt.SHARD
        assert "kv" in r["spec"], r


def test_rules_validate_against_mesh():
    from pmdfc_tpu.parallel import partitioning as pt

    mesh = _mesh(2)
    pt.validate_rules(pt.DEFAULT_AXIS_RULES, mesh)
    with pytest.raises(ValueError, match="names a mesh axis"):
        pt.validate_rules((("shard", "model"),), mesh)
    with pytest.raises(ValueError, match="no axis rule"):
        pt.leaf_axes(".nonsense.leaf", 1)


def test_sharded_kv_rejects_bad_rules():
    from pmdfc_tpu.parallel.shard import ShardedKV

    with pytest.raises(ValueError, match="names a mesh axis"):
        ShardedKV(_cfg(), mesh=_mesh(2),
                  axis_rules=(("page_word", "nope"),))


def test_router_binning_is_loss_free_and_stable():
    from pmdfc_tpu.parallel import partitioning as pt
    from pmdfc_tpu.parallel.shard import ShardedKV

    keys = _keys(500, seed=3)
    router = pt.ShardRouter(4, pad_floor=8)
    rb = router.build(keys, _pages(keys))
    # loss-free: every request owns a distinct routed lane
    assert rb.b == 500 and len(np.unique(rb.pos)) == 500
    assert rb.counts.sum() == 500
    # scatter round-trips both payloads
    np.testing.assert_array_equal(rb.scatter(rb.keys), keys)
    np.testing.assert_array_equal(rb.scatter(rb.values), _pages(keys))
    # owners bit-identical to the device hash (the GetNodeID contract)
    skv = ShardedKV(_cfg(), mesh=_mesh(4))
    np.testing.assert_array_equal(router.owners(keys), skv.node_of(keys))
    # stable order within a shard: lanes ascend in request order
    own = router.owners(keys)
    for s in range(4):
        lanes = rb.pos[own == s]
        assert (np.diff(lanes) > 0).all()


# --- 2. plane verbs --------------------------------------------------------


def test_plane_matches_single_device_results():
    from pmdfc_tpu import kv as kv_mod
    from pmdfc_tpu.parallel.shard import ShardedKV

    keys = _keys(300, seed=11)
    pages = _pages(keys)
    skv = ShardedKV(_cfg(), mesh=_mesh(4))
    ref = kv_mod.KV(_cfg())

    res = skv.plane_insert(keys, pages).fetch()
    rres = ref.insert(keys, pages)
    np.testing.assert_array_equal(np.asarray(res.dropped),
                                  np.asarray(rres.dropped))
    g = skv.plane_get(keys).fetch()
    rout, rfound = ref.get(keys)
    np.testing.assert_array_equal(g.found, np.asarray(rfound))
    np.testing.assert_array_equal(g.dense()[g.found],
                                  np.asarray(rout)[rfound])
    # hit_rows slices agree with the dense request-order form
    np.testing.assert_array_equal(g.hit_rows(50, 200),
                                  g.dense()[50:200][g.found[50:200]])
    hit = skv.plane_delete(keys[:64]).fetch()
    rhit = ref.delete(keys[:64])
    np.testing.assert_array_equal(hit, np.asarray(rhit))
    # stats agree though the plane accounted its lean gets host-side
    s, r = skv.stats(), ref.stats()
    for k in ("puts", "gets", "hits", "misses", "deletes"):
        assert s[k] == r[k], (k, s, r)


def test_plane_per_shard_attribution_sums_to_truth():
    from pmdfc_tpu.parallel.shard import ShardedKV

    keys = _keys(400, seed=7)
    skv = ShardedKV(_cfg(), mesh=_mesh(4))
    skv.plane_insert(keys, _pages(keys)).fetch()
    h = skv.plane_get(keys)
    assert h.counts.sum() == 400  # routed-op attribution per shard
    assert (h.counts > 0).all()   # murmur3 spreads a 400-key batch
    h.fetch()
    rep = skv.shard_report()
    assert sum(rep["stats"]["gets"]) == 400
    assert sum(rep["stats"]["hits"]) == 400
    assert sum(rep["stats"]["puts"]) == 400


def test_plane_backend_telemetry_and_warmup_are_stat_clean():
    from pmdfc_tpu.parallel.plane import PlaneBackend
    from pmdfc_tpu.parallel.shard import ShardedKV

    skv = ShardedKV(_cfg(), mesh=_mesh(2))
    be = PlaneBackend(skv)
    assert be.warmup(32) > 0
    # warmup's all-INVALID batches must not count as traffic
    s = skv.stats()
    assert s["gets"] == 0 and s["puts"] == 0, s
    keys = _keys(100, seed=9)
    be.put(keys, _pages(keys))
    out, found = be.get(keys)
    assert found.all()
    np.testing.assert_array_equal(out, _pages(keys))
    st = be.stats()
    assert st["shard_report"]["n_shards"] == 2
    # per-shard routed-op counters landed on the shared mesh scope
    ops = sum(be._tele.get(f"shard{i}_ops", 0) for i in range(2))
    assert ops > 0


def test_plane_counting_path_still_migrates_tier():
    # tiered pool: the GET phase's counting (non-lean) path must still
    # run under the plane so promotions happen at the sampled cadence
    from pmdfc_tpu.parallel.shard import ShardedKV

    cfg = _cfg(capacity=1 << 9, tier=TierConfig(
        ghost_rows=32, promote_touches=1, max_promotes_per_batch=32))
    skv = ShardedKV(cfg, mesh=_mesh(2))
    keys = _keys(64, seed=13)
    skv.plane_insert(keys, _pages(keys)).fetch()
    for _ in range(4):
        g = skv.plane_get(keys).fetch()
        assert g.found.all()
    t = skv.tier_stats()
    assert t is not None and t["promotions"] > 0, t


# --- 3. the serving drill --------------------------------------------------


def _serve_workload(backend_factory, coalesced=True):
    """Seeded mixed workload through a NetServer; returns the result
    transcript (the conformance unit of test_net.py, on the plane)."""
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    srv = NetServer(backend_factory,
                    net=NetConfig(flush_timeout_us=5000, settle_us=200)
                    if coalesced else None,
                    serialize_ops=not coalesced).start()
    results = []
    try:
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None, pipeline=coalesced) as be:
            rng = np.random.default_rng(77)
            universe = _keys(256, seed=77)
            for _ in range(100):
                op = int(rng.integers(5))
                lo = int(rng.integers(0, 240))
                n = int(rng.integers(1, 16))
                sel = universe[lo:lo + n]
                if op == 0:
                    be.put(sel, _pages(sel))
                    results.append(("put", n))
                elif op in (1, 2):
                    out, found = be.get(sel)
                    results.append(("get", found.tolist(),
                                    out[found].tolist()))
                elif op == 3:
                    hit = be.invalidate(sel)
                    results.append(("inval", hit.tolist()))
                else:
                    vals, ef = be.get_extent(sel)
                    results.append(("gext", ef.tolist(),
                                    vals[ef].tolist()))
            be.insert_extent(np.array([3, 0], np.uint32),
                             np.array([0, 4096], np.uint32), 32)
            vals, ef = be.get_extent(
                np.array([[3, 5], [3, 40]], np.uint32))
            results.append(("ext", ef.tolist(), vals.tolist()))
    finally:
        srv.stop()
    return results


@pytest.mark.slow
def test_mesh_plane_bit_identical_to_single_device_serving():
    """THE CI drill: the 4-shard serving plane behind the coalesced
    NetServer reproduces the single-device path verb-for-verb on a
    seeded mixed workload.

    Slow tier (runs in full CI): the kill-switch conformance drill
    below makes the same transcript comparison — `PMDFC_MESH=off` IS
    the single-device path — so tier-1 keeps one copy of the 2×-serve
    cost, not two."""
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.kv import KV
    from pmdfc_tpu.parallel.plane import make_serving_backend

    plane = make_serving_backend(_cfg(), MeshConfig(n_shards=4))
    single = DirectBackend(KV(_cfg()))
    got = _serve_workload(lambda: plane)
    want = _serve_workload(lambda: single)
    assert got == want, "mesh plane diverged from the single-device path"


def test_mesh_off_kill_switch_is_conformant(monkeypatch):
    """`PMDFC_MESH=off` must collapse the WHOLE plane to the current
    single-device path — same factory call, bit-identical transcript."""
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.parallel.plane import make_serving_backend

    monkeypatch.setenv("PMDFC_MESH", "off")
    off = make_serving_backend(_cfg(), MeshConfig(n_shards=4))
    assert isinstance(off, DirectBackend)
    got_off = _serve_workload(lambda: off)
    monkeypatch.delenv("PMDFC_MESH")
    on = make_serving_backend(_cfg(), MeshConfig(n_shards=4))
    got_on = _serve_workload(lambda: on)
    assert got_off == got_on, "kill switch is not conformant"


def test_kvserver_mesh_mode_serves_engine_verbs():
    from pmdfc_tpu.client import EngineBackend
    from pmdfc_tpu.runtime import Engine, KVServer

    cfg = _cfg()
    keys = _keys(128, seed=21)
    pages = _pages(keys)
    srv = KVServer(cfg, engine=Engine(page_bytes=W * 4),
                   mesh=MeshConfig(n_shards=4, pad_floor=16))
    assert srv._plane is not None and srv.kv.n_shards == 4
    assert srv.kv._router.pad_floor == 16
    # warm the plane ladder BEFORE admitting a synchronous client: an
    # unwarmed driver compiling mid-flush can outlast the client's
    # wait (the build_backend("engine") discipline)
    srv.warmup(256)
    with srv.start():
        eb = EngineBackend(srv, timeout_us=60_000_000)
        eb.put(keys, pages)
        out, found = eb.get(keys)
        assert found.all()
        np.testing.assert_array_equal(out, pages)
        assert eb.invalidate(keys[:16]).all()
        _, f2 = eb.get(keys[:16])
        assert not f2.any()
        assert eb.insert_extent(np.array([9, 0], np.uint32),
                                np.array([0, 4096], np.uint32), 8) == 0
        _, fe = eb.get_extent(np.array([[9, 2]], np.uint32))
        assert fe[0]
        assert srv.health()["kv"]["hits"] >= 128
        eb.close()


def test_kvserver_mesh_respects_kill_switch(monkeypatch):
    from pmdfc_tpu.runtime import KVServer

    monkeypatch.setenv("PMDFC_MESH", "off")
    srv = KVServer(_cfg(), mesh=4)
    assert srv._plane is None
    srv.engine.close()


# --- 4. reshard restore ----------------------------------------------------


@pytest.mark.parametrize(
    "n_from,n_to",
    [(4, 2),
     pytest.param(2, 3, marks=pytest.mark.slow),
     pytest.param(8, 4, marks=pytest.mark.slow)])
def test_reshard_restore_loses_nothing(tmp_path, n_from, n_to):
    # (8, 4): M divides N, so every old shard's key set concentrates on
    # ONE new shard — the replay shape that overflowed the a2a per-pair
    # buckets before the replay moved to the loss-free plane router
    from pmdfc_tpu.parallel.shard import ShardedKV

    cfg = _cfg()
    keys = _keys(400, seed=31)
    pages = _pages(keys)
    src = ShardedKV(cfg, mesh=_mesh(n_from))
    src.plane_insert(keys, pages).fetch()
    assert src.plane_delete(keys[:50]).fetch().all()
    src.insert_extent(np.array([5, 0], np.uint32),
                      np.array([0, 8192], np.uint32), 16)
    stats_before = src.stats()
    path = str(tmp_path / "snap.ckpt")
    src.save(path)

    dst = ShardedKV(cfg, mesh=_mesh(n_to))
    dst.restore(path)
    # zero lost live pages, right bytes
    g = dst.plane_get(keys[50:]).fetch()
    assert g.found.all(), f"{int((~g.found).sum())} live pages lost"
    np.testing.assert_array_equal(g.dense(), pages[50:])
    # legal misses only: deleted keys STAY deleted
    gdel = dst.plane_get(keys[:50]).fetch()
    assert not gdel.found.any(), "deleted keys resurrected"
    # extents replayed
    _, ef = dst.get_extent(np.array([[5, 7]], np.uint32))
    assert ef[0]
    # counters carried (the replay's own bumps must not inflate them)
    after = dst.stats()
    for k in ("puts", "deletes", "extent_puts"):
        assert after[k] == stats_before[k], (k, after, stats_before)


def test_reshard_restore_rejects_mismatched_config(tmp_path):
    from pmdfc_tpu.parallel.shard import ShardedKV

    src = ShardedKV(_cfg(capacity=1 << 10), mesh=_mesh(2))
    keys = _keys(32, seed=41)
    src.plane_insert(keys, _pages(keys)).fetch()
    path = str(tmp_path / "snap.ckpt")
    src.save(path)
    dst = ShardedKV(_cfg(capacity=1 << 11), mesh=_mesh(4))
    # a failed restore must not wipe the live read-only-GET accounting
    dst.plane_insert(keys, _pages(keys)).fetch()
    assert dst.plane_get(keys).fetch().found.all()
    before = dst.stats()
    with pytest.raises(ValueError, match="per-shard KVConfig"):
        dst.restore(path)
    assert dst.stats() == before


@pytest.mark.slow
def test_unpaged_reshard_keeps_values_and_extents(tmp_path):
    # unpaged mode: user values replay verbatim; extent-cover REFS are
    # excluded from the value replay (they'd resurrect pointing into
    # the rebuilt ring) — covers resolve via the replayed ring instead
    from pmdfc_tpu.parallel.shard import ShardedKV

    cfg = _cfg(paged=False)
    src = ShardedKV(cfg, mesh=_mesh(4))
    keys = _keys(128, seed=47)
    vals = np.stack([keys[:, 0] ^ 7, keys[:, 1] + 1], -1).astype(np.uint32)
    src.plane_insert(keys, vals).fetch()
    src.insert_extent(np.array([11, 0], np.uint32),
                      np.array([0, 4096], np.uint32), 16)
    path = str(tmp_path / "snap.ckpt")
    src.save(path)
    dst = ShardedKV(cfg, mesh=_mesh(2))
    dst.restore(path)
    g = dst.plane_get(keys).fetch()
    assert g.found.all()
    np.testing.assert_array_equal(g.dense(), vals)
    _, ef = dst.get_extent(np.array([[11, 9]], np.uint32))
    assert ef[0]


@pytest.mark.slow
def test_tiered_reshard_drops_only_stale(tmp_path):
    # tiered pool: live hot+cold pages replay; balloon-shrunk (stale
    # generation) entries become legal misses, never wrong bytes
    from pmdfc_tpu.parallel.shard import ShardedKV

    cfg = _cfg(capacity=1 << 9, tier=TierConfig(ghost_rows=32))
    src = ShardedKV(cfg, mesh=_mesh(2))
    keys = _keys(96, seed=43)
    pages = _pages(keys)
    src.plane_insert(keys, pages).fetch()
    path = str(tmp_path / "snap.ckpt")
    src.save(path)
    dst = ShardedKV(cfg, mesh=_mesh(4))
    dst.restore(path)
    g = dst.plane_get(keys).fetch()
    assert g.found.all()
    np.testing.assert_array_equal(g.dense(), pages)
