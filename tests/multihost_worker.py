"""Worker process for the `connect_multihost` drill (test_multihost.py).

Run as: python tests/multihost_worker.py <process_id> <coordinator_port>

Each of the 2 workers forces a 2-device CPU backend, joins the
distributed runtime (global mesh = 4 devices across 2 processes), drives
a ShardedKV through insert/get/delete, and checks the results against
the host-computed ground truth. Exit code 0 = all assertions held.
The drill is the DCN analog of the reference's multi-node deployment
(`script.sh:3-41`): one logical server spanning processes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=2 "
    + os.environ.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", ""
    )
)


def main() -> int:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    import jax

    # the host sitecustomize force-registers the remote-TPU plugin and
    # overrides JAX_PLATFORMS via jax.config; re-pin BEFORE any backend
    # init or the drill blocks on the tunnel (bench/common.pin_cpu)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from pmdfc_tpu.config import IndexConfig, IndexKind, KVConfig
    from pmdfc_tpu.parallel.shard import (
        ShardedKV,
        connect_multihost,
        make_mesh,
    )
    from pmdfc_tpu.utils.keys import pack_key

    ndev = connect_multihost(f"localhost:{port}", 2, pid)
    assert ndev == 4, f"global device count {ndev} != 4"

    cfg = KVConfig(
        index=IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 14),
        bloom=None,
        paged=False,
    )
    kv = ShardedKV(cfg, mesh=make_mesh(), dispatch="a2a")

    n = 4096
    lo = np.arange(n, dtype=np.uint32)
    keys = np.asarray(pack_key(np.full_like(lo, 3), lo))
    vals = np.stack([lo ^ np.uint32(0x5A5A), lo], axis=-1)

    res = kv.insert(keys, vals)
    assert not res.dropped.any(), "fill-phase insert dropped keys"

    got, found = kv.get(keys)
    assert found.all(), f"{(~found).sum()} inserted keys not found"
    np.testing.assert_array_equal(got, vals)

    hit = kv.delete(keys[: n // 4])
    assert hit.all(), "delete missed inserted keys"
    got2, found2 = kv.get(keys)
    assert not found2[: n // 4].any(), "deleted keys still served"
    assert found2[n // 4 :].all(), "delete clobbered live keys"

    s = kv.stats()
    assert s["puts"] == n and s["gets"] == 2 * n, s
    util = kv.utilization()
    assert 0.0 < util < 1.0, util

    rep = kv.shard_report()
    assert rep["n_shards"] == 4
    assert sum(rep["occupancy"]) == n - n // 4, rep["occupancy"]

    # extent verbs through the replicated body (the one op that needs
    # uncommitted host inputs on a multi-process mesh)
    ek = np.asarray(pack_key(np.uint32(9), np.uint32(1 << 20)))
    _, uncovered = kv.insert_extent(ek, np.asarray([7, 7], np.uint32), 5)
    assert uncovered == 0, uncovered
    eks = np.stack([ek + np.asarray([0, i], np.uint32) for i in range(5)])
    evals, efound = kv.get_extent(eks)
    assert efound.all(), efound

    print(f"worker {pid}: OK (devices={ndev}, util={util:.3f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
