"""IHash conformance suite — every index family must honor the contract.

The contract (ref `server/IHash.h:10-24` + clean-cache semantics the KV
façade relies on, `server/KV.cpp:100-127`):
- every inserted key is gettable with its value unless reported
  evicted/dropped (`misses <= evictions + drops`, `server/test_KV.cpp`);
- Insert of an existing key updates in place (fresh=False);
- duplicate keys within one batch resolve to the LAST occurrence;
- Delete removes and reports the old value;
- evicted keys are reported WITH their values (bloom/pool bookkeeping);
- padding (INVALID) keys are no-ops everywhere;
- paged KV integration: pages ride along index mutations losslessly.
"""

import numpy as np
import pytest

from pmdfc_tpu.config import IndexConfig, IndexKind, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.models.base import get_index_ops
from pmdfc_tpu.utils.keys import pack_key

ALL_KINDS = list(IndexKind)


def make_cfg(kind: IndexKind, capacity: int = 1 << 12) -> IndexConfig:
    kw = {}
    if kind in (IndexKind.CCEH, IndexKind.EXTENDIBLE):
        kw = dict(segment_slots=128, split_headroom=2)
    return IndexConfig(kind=kind, capacity=capacity, **kw)


def keys_of(lo, hi=1):
    lo = np.asarray(lo, np.uint32)
    return np.asarray(pack_key(np.full_like(lo, hi), lo))


def vals_of(lo):
    lo = np.asarray(lo, np.uint32)
    return np.stack([np.zeros_like(lo), lo], axis=-1)


@pytest.fixture(params=ALL_KINDS, ids=[k.value for k in ALL_KINDS])
def kind(request):
    return request.param


def test_roundtrip_and_update(kind):
    ops = get_index_ops(kind)
    st = ops.init(make_cfg(kind))
    ks = keys_of(np.arange(100))
    st, res = ops.insert_batch(st, ks, vals_of(np.arange(100) * 2))
    assert not bool(np.asarray(res.dropped).any())
    got = ops.get_batch(st, ks)
    assert bool(np.asarray(got.found).all())
    np.testing.assert_array_equal(np.asarray(got.values)[:, 1],
                                  np.arange(100) * 2)
    # update in place
    st, res2 = ops.insert_batch(st, ks[:10], vals_of(np.arange(10) + 500))
    assert not bool(np.asarray(res2.fresh).any())
    got2 = ops.get_batch(st, ks[:10])
    np.testing.assert_array_equal(np.asarray(got2.values)[:, 1],
                                  np.arange(10) + 500)


def test_delete_returns_old_value(kind):
    ops = get_index_ops(kind)
    st = ops.init(make_cfg(kind))
    ks = keys_of([11, 22, 33])
    st, _ = ops.insert_batch(st, ks, vals_of([1, 2, 3]))
    st, hit, old = ops.delete_batch(st, ks[:2])
    np.testing.assert_array_equal(np.asarray(hit), [True, True])
    np.testing.assert_array_equal(np.asarray(old)[:, 1], [1, 2])
    got = ops.get_batch(st, ks)
    np.testing.assert_array_equal(np.asarray(got.found),
                                  [False, False, True])
    st, hit2, _ = ops.delete_batch(st, keys_of([99]))
    assert not bool(np.asarray(hit2).any())


def test_duplicates_last_wins(kind):
    ops = get_index_ops(kind)
    st = ops.init(make_cfg(kind))
    ks = keys_of([5, 5, 5])
    st, res = ops.insert_batch(st, ks, vals_of([1, 2, 3]))
    got = ops.get_batch(st, ks[:1])
    assert int(np.asarray(got.values)[0, 1]) == 3
    assert int((np.asarray(res.slots) >= 0).sum()) == 1


def test_clean_cache_accounting_under_pressure(kind):
    # insert far beyond capacity; every miss must be explained by a
    # reported eviction or drop, and evictions must carry their values
    ops = get_index_ops(kind)
    cfg = make_cfg(kind, capacity=1 << 8)
    st = ops.init(cfg)
    n = ops.num_slots(cfg) * 3
    rng = np.random.default_rng(17)
    lo = rng.choice(1 << 24, size=n, replace=False)
    ks = keys_of(lo)
    ev = drop = 0
    for i in range(0, n, 256):
        st, res = ops.insert_batch(st, ks[i : i + 256],
                                   vals_of(lo[i : i + 256]))
        evm = (np.asarray(res.evicted) != 0xFFFFFFFF).all(-1)
        ev += int(evm.sum())
        drop += int(np.asarray(res.dropped).sum())
        # evicted entries report their values
        evv = np.asarray(res.evicted_vals)[evm]
        if len(evv):
            assert (evv != 0xFFFFFFFF).all()
    got = ops.get_batch(st, ks)
    found = np.asarray(got.found)
    misses = int((~found).sum())
    assert misses <= ev + drop, (misses, ev, drop)
    ok = found
    np.testing.assert_array_equal(np.asarray(got.values)[ok, 1], lo[ok])


def test_padding_keys_are_noops(kind):
    ops = get_index_ops(kind)
    st = ops.init(make_cfg(kind))
    pad = np.full((8, 2), 0xFFFFFFFF, np.uint32)
    st, res = ops.insert_batch(st, pad, np.zeros((8, 2), np.uint32))
    assert (np.asarray(res.slots) == -1).all()
    got = ops.get_batch(st, pad)
    assert not bool(np.asarray(got.found).any())
    st, hit, _ = ops.delete_batch(st, pad)
    assert not bool(np.asarray(hit).any())


def test_scan_powers_find_anyway(kind):
    ops = get_index_ops(kind)
    st = ops.init(make_cfg(kind))
    ks = keys_of([7])
    st, _ = ops.insert_batch(st, ks, vals_of([42]))
    flat_keys, flat_vals = ops.scan(st)
    fk = np.asarray(flat_keys)
    where = (fk[:, 0] == ks[0, 0]) & (fk[:, 1] == ks[0, 1])
    assert where.sum() == 1
    assert int(np.asarray(flat_vals)[where][0, 1]) == 42


def test_paged_kv_integration(kind):
    cfg = KVConfig(
        index=make_cfg(kind, capacity=1 << 9),
        bloom=None,
        paged=True,
        page_words=8,
    )
    kv = KV(cfg)
    rng = np.random.default_rng(23)
    n = 1024
    lo = rng.choice(1 << 20, size=n, replace=False)
    ks = keys_of(lo)
    pages = rng.integers(0, 2**32, size=(n, 8), dtype=np.uint32)
    for i in range(0, n, 128):
        kv.insert(ks[i : i + 128], pages[i : i + 128])
    out, found = kv.get(ks)
    s = kv.stats()
    assert (~found).sum() <= s["evictions"] + s["drops"]
    np.testing.assert_array_equal(out[found], pages[found])
    # free-row conservation
    from pmdfc_tpu.kv import utilization

    live = float(utilization(kv.state, cfg)) * kv.capacity()
    assert int(kv.state.pool.top) == kv.capacity() - round(live)


def test_hotring_prefers_evicting_cold_entries():
    # hot keys (touched often) must survive overflow; cold ones go first
    cfg = KVConfig(
        index=IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 6,
                          cluster_slots=32),
        bloom=None,
        paged=False,
    )
    kv = KV(cfg)
    lo = np.arange(256)
    ks = keys_of(lo)
    kv.insert(ks[:64], vals_of(lo[:64]))
    hot = ks[:16]
    for _ in range(5):
        kv.get(hot)  # heat up
    # steady eviction pressure: each small batch displaces the coldest
    for i in range(64, 256, 16):
        kv.insert(ks[i : i + 16], vals_of(lo[i : i + 16]))
    _, found_hot = kv.get(hot)
    _, found_all = kv.get(ks[:64])
    # hot keys survive at a higher rate than the cold residue
    hot_rate = found_hot.mean()
    cold_rate = found_all[16:].mean()
    assert hot_rate >= cold_rate
    assert hot_rate > 0.5


def test_hotring_decay_halves_counters():
    from pmdfc_tpu.models.base import get_index_ops

    ops = get_index_ops(IndexKind.HOTRING)
    cfg = IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 6,
                      decay_every_gets=32)
    kvcfg = KVConfig(index=cfg, bloom=None, paged=False)
    kv = KV(kvcfg)
    ks = keys_of([1, 2, 3])
    kv.insert(ks, vals_of([1, 2, 3]))
    for _ in range(4):
        kv.get(ks)
    peak = int(np.asarray(kv.state.index.counters).max())
    assert peak >= 4
    for _ in range(20):
        kv.get(ks)  # crosses decay_every_gets repeatedly
    after = int(np.asarray(kv.state.index.counters).max())
    # with halving every 32 keys the counter stays bounded well below the
    # un-decayed total (3 + 24 gets each)
    assert after < 24
    assert ops.decay is not None


def test_get_values_matches_get_batch(kind):
    """Families exposing the lean GET (`get_values`, the benched hot path)
    must agree with `get_batch`: same found mask, same values on hits,
    ZERO values on misses (the masked-sum contract `kv.py` relies on)."""
    ops = get_index_ops(kind)
    if ops.get_values is None:
        pytest.skip(f"{kind.value} has no lean GET")
    st = ops.init(make_cfg(kind))
    ks = keys_of(np.arange(64))
    st, _ = ops.insert_batch(st, ks, vals_of(np.arange(64) + 9))
    # drive the table toward full so displacement machinery actually runs
    # (cuckoo kicks, CCP second-chance relocation, level bottom movement) —
    # the lean path's one-location invariant must hold in THOSE states too
    cap = ops.num_slots(make_cfg(kind))
    rng = np.random.default_rng(5)
    fill = keys_of(rng.choice(1 << 20, size=min(2 * cap, 1 << 13),
                              replace=False) + 1000)
    for lo in range(0, len(fill), 1 << 11):
        st, _ = ops.insert_batch(st, fill[lo : lo + (1 << 11)],
                                 vals_of(fill[lo : lo + (1 << 11), 1]))
    probe = keys_of(np.arange(0, 128, 2))  # some hits, some misses
    ref = ops.get_batch(st, probe)
    vals, found = ops.get_values(st, probe)
    np.testing.assert_array_equal(np.asarray(found), np.asarray(ref.found))
    f = np.asarray(ref.found)
    np.testing.assert_array_equal(np.asarray(vals)[f],
                                  np.asarray(ref.values)[f])
    assert (np.asarray(vals)[~f] == 0).all(), "miss rows must be zero"
    # padding keys are no-ops on the lean path too
    pad = np.full((4, 2), 0xFFFFFFFF, np.uint32)
    vals2, found2 = ops.get_values(st, pad)
    assert not np.asarray(found2).any()
    assert (np.asarray(vals2) == 0).all()
