"""HotRing mechanics: hot-point shift (hot-mirror resolution) and tag-half
rehash (ref `server/hotring/hotring.c:560-600`, `:493+`).

Conformance (get/insert/delete/evict semantics) lives in
`test_index_conformance.py`; this file checks the HOTSPOT behaviors: under a
skewed workload, hot keys resolve from the narrow first-phase probe, and a
rehash splits every bucket by the next hash bit without losing an entry.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pmdfc_tpu.config import BloomConfig, IndexConfig, IndexKind, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.models import hotring
from pmdfc_tpu.utils.keys import INVALID_WORD

CFG = IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 10,
                  cluster_slots=16, hot_lanes=4)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 20, size=n, replace=False).astype(np.uint32)
    return np.stack([flat >> 10, flat & 0x3FF], axis=-1).astype(np.uint32)


def _vals(keys):
    return np.stack([keys[:, 1], keys[:, 0]], -1).astype(np.uint32)


def test_shift_promotes_hot_keys_to_mirror():
    """Zipf-style access: the heavily-touched keys resolve from the hot
    mirror (phase 1); cold keys don't — fewer probes/bytes for hot keys."""
    state = hotring.init(CFG)
    keys = _keys(512, seed=1)
    kj = jnp.asarray(keys)
    state, ires = hotring.insert_batch(state, kj, jnp.asarray(_vals(keys)))
    placed = ~np.asarray(ires.dropped)  # clean-cache drops are legal
    assert placed[:32].all(), "test needs all hot keys placed"

    hot_keys = kj[:32]
    # touch hot keys many times, cold keys once
    for _ in range(8):
        res = hotring.get_batch(state, hot_keys)
        state = hotring.touch(state, res.slots)
    res = hotring.get_batch(state, kj)
    state = hotring.touch(state, res.slots)

    state = hotring.hotspot_shift(state)
    hot_hit = np.asarray(hotring.probe_hot(state, kj))
    assert hot_hit[:32].all(), "every hot key must resolve from the mirror"
    # buckets hold ~8 entries over 4 hot lanes: cold keys mostly miss phase 1
    assert hot_hit[32:].mean() < 0.8
    # and mirror answers are correct end-to-end (drops legally miss)
    out = hotring.get_batch(state, kj)
    found = np.asarray(out.found)
    np.testing.assert_array_equal(found, placed)
    np.testing.assert_array_equal(
        np.asarray(out.values)[placed], _vals(keys)[placed]
    )


def test_mirror_never_serves_stale_values():
    """Update/delete invalidate the mirror row; a shifted mirror must never
    answer with a pre-update value."""
    state = hotring.init(CFG)
    keys = _keys(64, seed=2)
    kj = jnp.asarray(keys)
    state, _ = hotring.insert_batch(state, kj, jnp.asarray(_vals(keys)))
    res = hotring.get_batch(state, kj)
    state = hotring.touch(state, res.slots)
    state = hotring.hotspot_shift(state)
    assert np.asarray(hotring.probe_hot(state, kj)).all()

    # overwrite half with new values — mirror rows drop, truth serves
    newv = _vals(keys) ^ np.uint32(0xABCD)
    state, _ = hotring.insert_batch(
        state, kj[:32], jnp.asarray(newv[:32])
    )
    out = hotring.get_batch(state, kj)
    assert np.asarray(out.found).all()
    np.testing.assert_array_equal(np.asarray(out.values)[:32], newv[:32])
    np.testing.assert_array_equal(
        np.asarray(out.values)[32:], _vals(keys)[32:]
    )

    # delete: neither mirror nor table may still answer
    state, hit, _ = hotring.delete_batch(state, kj[:8])
    assert np.asarray(hit).all()
    out2 = hotring.get_batch(state, kj[:8])
    assert not np.asarray(out2.found).any()
    assert not np.asarray(hotring.probe_hot(state, kj[:8])).any()


def test_decay_runs_shift():
    state = hotring.init(CFG)
    keys = _keys(32, seed=3)
    kj = jnp.asarray(keys)
    state, _ = hotring.insert_batch(state, kj, jnp.asarray(_vals(keys)))
    res = hotring.get_batch(state, kj)
    state = hotring.touch(state, res.slots)
    state = hotring.decay(state)  # halves counters AND rebuilds the mirror
    assert np.asarray(hotring.probe_hot(state, kj)).sum() > 0


@pytest.mark.slow
def test_rehash_splits_by_tag_half_losslessly():
    state = hotring.init(CFG)
    keys = _keys(700, seed=4)
    kj = jnp.asarray(keys)
    state, res = hotring.insert_batch(state, kj, jnp.asarray(_vals(keys)))
    placed = np.asarray(res.slots) >= 0
    c_before = state.table.shape[0]

    state2 = hotring.rehash(state)
    assert state2.table.shape[0] == 2 * c_before
    # every placed entry still resolves with the correct value
    out = hotring.get_batch(state2, kj)
    found = np.asarray(out.found)
    assert found[placed].all()
    np.testing.assert_array_equal(
        np.asarray(out.values)[placed], _vals(keys)[placed]
    )
    # occupancy really split: old row r's entries now live in r or r + C
    t = np.asarray(state2.table)
    s = CFG.cluster_slots
    occ = (t[:, 0:s] != 0xFFFFFFFF) | (t[:, s:2*s] != 0xFFFFFFFF)
    assert occ[:c_before].sum() > 0 and occ[c_before:].sum() > 0
    assert occ.sum() == placed.sum()
    # rehash doubles headroom: previously-overflowing inserts now fit
    if (~placed).any():
        state3, res3 = hotring.insert_batch(
            state2, kj, jnp.asarray(_vals(keys))
        )
        assert np.asarray(res3.slots)[~placed].min() >= 0


def test_facade_skew_workload_end_to_end():
    """Through the KV façade: zipf gets drive touch/decay; after the drain
    interval the hot mirror serves the popular keys."""
    cfg = KVConfig(
        index=IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 10,
                          cluster_slots=16, hot_lanes=4,
                          decay_every_gets=2048),
        bloom=BloomConfig(num_bits=1 << 14),
        paged=False,
    )
    kv = KV(cfg)
    keys = _keys(256, seed=5)
    kv.insert(keys, _vals(keys))
    rng = np.random.default_rng(6)
    hot = keys[:16]
    for _ in range(20):
        sel = rng.integers(0, 16, size=128)
        out, found = kv.get(hot[sel])
        assert found.all()
    hot_hit = np.asarray(hotring.probe_hot(kv.state.index, jnp.asarray(hot)))
    assert hot_hit.all()
    s = kv.stats()
    assert s["hits"] == s["gets"]


def test_sampled_touch_counts_one_in_n():
    """touch_sample_every=N: lean batches return identical results but only
    every Nth batch bumps access counters (the HotRing paper's sampled
    statistics; N=1 keeps the reference's count-every-access behavior)."""
    def build(n):
        cfg = KVConfig(
            index=IndexConfig(kind=IndexKind.HOTRING, capacity=1 << 10,
                              touch_sample_every=n, decay_every_gets=0),
            bloom=None, paged=False,
        )
        return KV(cfg)

    keys = np.stack([np.arange(64, dtype=np.uint32)] * 2, -1)
    ref, sampled = build(1), build(4)
    ref.insert(keys, keys)
    sampled.insert(keys, keys)
    for i in range(8):
        o1, f1 = ref.get(keys)
        o2, f2 = sampled.get(keys)
        assert f1.all() and f2.all()
        np.testing.assert_array_equal(o1, o2)
    c_ref = int(np.asarray(ref.state.index.counters).sum())
    c_smp = int(np.asarray(sampled.state.index.counters).sum())
    assert c_ref == 8 * 64            # every access counted
    assert c_smp == 2 * 64, c_smp     # batches 4 and 8 only
