"""Clean fixture: donation keyed through the CANONICAL shared helper
(`pmdfc_tpu.kv._donate` — the onesided.py pattern). The jax-donation
rule must accept this form: one copy of the platform policy, imported
from kv, instead of a re-implemented in-module guard."""

from functools import partial

import jax
import jax.numpy as jnp

from pmdfc_tpu.kv import _donate

_scatter_don = partial(jax.jit, donate_argnums=(0,))(
    lambda pool, rows, batch: pool.at[rows].set(batch))
_scatter_plain = jax.jit(
    lambda pool, rows, batch: pool.at[rows].set(batch))


def write(pool, rows, batch):
    fn = _scatter_don if _donate() else _scatter_plain
    return fn(pool, jnp.asarray(rows), jnp.asarray(batch))
