"""Seeded-bad fixture: a `pl.pallas_call` with no `interpret=` fallback
in a module with no platform guard — the pallas-platform-gate rule MUST
flag `launch()` (TPU-only Mosaic lowering as the unconditional path)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def launch(x):
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
    )(x)
