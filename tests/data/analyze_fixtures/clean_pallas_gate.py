"""Clean twin: the `pl.pallas_call` carries the platform-keyed
`interpret=` fallback (the ops/fused.py idiom), and a second launch
shape gates by an explicit backend branch — neither may be flagged."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def launch(x):
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(x)


def launch_branched(x):
    # module-level platform guard (the `jax.default_backend()` call
    # above) also covers explicitly-branched launches
    if jax.default_backend() == "tpu":
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
            interpret=True,
        )(x)
    return x * 2
