"""Clean twin of bad_donation_shardmap: the same shard_map-wrapped
donation, keyed off the platform (the `parallel/shard._wrap` pattern) —
the jax-donation rule must pass it."""

from functools import partial

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _body(state, keys):
    return state, keys


def build(mesh, spec_state):
    donate = jax.devices()[0].platform != "cpu"
    return jax.jit(
        shard_map(partial(_body), mesh=mesh,
                  in_specs=(spec_state, P("kv")),
                  out_specs=(spec_state, P("kv"))),
        donate_argnums=(0,) if donate else (),
    )
