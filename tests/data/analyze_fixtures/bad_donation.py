"""Seeded-bad fixture: platform-unkeyed donation — the jax-donation rule
MUST flag it (no `jax.default_backend()` / `.platform` guard anywhere in
the module, so the donated program also runs on the CPU jaxlib where it
can scribble on pass-through buffers)."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def scatter(pool, rows, batch):
    return pool.at[rows].set(batch)


def write(pool, rows, batch):
    return scatter(pool, jnp.asarray(rows), jnp.asarray(batch))
