"""Seeded-bad fixture: raw device syncs outside the profiler's
timed-fetch seam — the profiler-seam rule MUST flag both shapes
(`jax.block_until_ready(...)` and the method form) as unattributable
device time."""

import jax


def fetch_result(out):
    # blocking fetch without profiler.fetch: device time vanishes
    return jax.block_until_ready(out)


def drain(handle):
    # the method form leaks the same way
    return handle.block_until_ready()
