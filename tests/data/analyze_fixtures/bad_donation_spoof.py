"""Bad fixture: a LOCAL `_donate()` with an unconditional policy must
NOT satisfy the jax-donation rule — only the canonical helper imported
from `pmdfc_tpu.kv` counts as platform keying."""

from functools import partial

import jax
import jax.numpy as jnp

_scatter_don = partial(jax.jit, donate_argnums=(0,))(
    lambda pool, rows, batch: pool.at[rows].set(batch))


def _donate():
    return True  # not keyed on anything


def write(pool, rows, batch):
    if _donate():
        return _scatter_don(pool, jnp.asarray(rows), jnp.asarray(batch))
