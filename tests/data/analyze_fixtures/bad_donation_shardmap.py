"""Seeded-bad fixture: platform-unkeyed donation of a SHARD_MAP-wrapped
program — the mesh-plane shape of the jax 0.4.37 donation class. The
jax-donation rule MUST flag it: the donated state is the whole sharded
table, and on the CPU jaxlib a donated shard_map program can scribble on
pass-through buffers exactly like a plain jit one (no
`jax.default_backend()` / `.platform` guard anywhere in this module)."""

from functools import partial

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _body(state, keys):
    return state, keys


def build(mesh, spec_state):
    return jax.jit(
        shard_map(partial(_body), mesh=mesh,
                  in_specs=(spec_state, P("kv")),
                  out_specs=(spec_state, P("kv"))),
        donate_argnums=(0,),
    )
