"""Clean fixture: donation keyed off the platform (the kv.py `_donate()`
pattern) — the jax-donation rule must pass it."""

from functools import partial

import jax
import jax.numpy as jnp

_scatter_don = partial(jax.jit, donate_argnums=(0,))(
    lambda pool, rows, batch: pool.at[rows].set(batch))
_scatter_plain = jax.jit(
    lambda pool, rows, batch: pool.at[rows].set(batch))

_DONATE = None


def write(pool, rows, batch):
    global _DONATE
    if _DONATE is None:
        _DONATE = jax.default_backend() != "cpu"
    fn = _scatter_don if _DONATE else _scatter_plain
    return fn(pool, jnp.asarray(rows), jnp.asarray(batch))
