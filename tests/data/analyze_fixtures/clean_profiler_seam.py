"""Clean twin: serving-module device syncs routed through the
profiler's seam — `profiler.fetch` thunks for attributable fetches,
`profiler.block_ready` for warmup syncs. Neither may be flagged."""

from pmdfc_tpu.runtime import profiler


def fetch_result(out, b):
    return profiler.fetch("kv.get", "get", lambda: out[:b], n_ops=b)


def warm(x):
    # warmup sync: sanctioned, unattributed
    return profiler.block_ready(x)
