"""Seeded-bad fixture: an AB/BA lock-order cycle the analyzer MUST flag.

`ping` nests a -> b while `pong` nests b -> a; with both orders present
the lock graph has a 2-cycle — the classic latent deadlock.
"""

import threading


class Pair:
    def __init__(self):
        # guarded-by: x
        self.lock_a = threading.Lock()
        # guarded-by: y
        self.lock_b = threading.Lock()
        self.x = 0
        self.y = 0

    def ping(self):
        with self.lock_a:
            self.x += 1
            with self.lock_b:
                self.y += 1

    def pong(self):
        with self.lock_b:
            self.y += 1
            with self.lock_a:
                self.x += 1
