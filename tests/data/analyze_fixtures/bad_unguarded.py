"""Seeded-bad fixture: a write to a declared-guarded field outside its
lock — the guarded-by lint MUST flag `drop()`."""

import threading


class Box:
    def __init__(self):
        # guarded-by: items, closed
        self._lock = threading.Lock()
        self.items = []
        self.closed = False

    def add(self, v):
        with self._lock:
            self.items.append(v)

    def drop(self):
        self.closed = True  # BUG: declared guarded, written lock-free
