"""Clean fixture: consistent a -> b nesting, every guarded field written
under its lock, every lock declared — the suite must report NOTHING."""

import threading


class Pair:
    def __init__(self):
        # guarded-by: x
        self.lock_a = threading.Lock()
        # guarded-by: y
        self.lock_b = threading.Lock()
        self.x = 0
        self.y = 0

    def ping(self):
        with self.lock_a:
            self.x += 1
            with self.lock_b:
                self.y += 1

    def poke(self):
        with self.lock_b:
            self.y += 1


class Box:
    def __init__(self):
        # guarded-by: items, closed
        self._lock = threading.Lock()
        self.items = []
        self.closed = False

    def add(self, v):
        with self._lock:
            self.items.append(v)

    def drop(self):
        with self._lock:
            self.closed = True

    # caller-holds: _lock
    def _drain(self):
        self.items.clear()

    def reset_locked(self):
        self.items = []
