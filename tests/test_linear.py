import jax.numpy as jnp
import numpy as np
import pytest

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models import get_index_ops
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid, pack_key

OPS = get_index_ops(IndexKind.LINEAR)


def _keys(his, los):
    return pack_key(jnp.asarray(his, jnp.uint32), jnp.asarray(los, jnp.uint32))


def _vals(xs):
    a = jnp.asarray(xs, jnp.uint32)
    return jnp.stack([jnp.zeros_like(a), a], axis=-1)


def test_insert_then_get_roundtrip():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 12)
    st = OPS.init(cfg)
    n = 512
    keys = _keys(np.arange(n) // 7, np.arange(n))
    vals = _vals(np.arange(n) * 3)
    st, res = OPS.insert_batch(st, keys, vals)
    assert not bool(res.dropped.any())
    got = OPS.get_batch(st, keys)
    assert bool(got.found.all())
    np.testing.assert_array_equal(np.asarray(got.values[:, 1]), np.arange(n) * 3)
    np.testing.assert_array_equal(np.asarray(got.slots), np.asarray(res.slots))


def test_miss_is_legal_answer():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 10)
    st = OPS.init(cfg)
    got = OPS.get_batch(st, _keys([1, 2], [3, 4]))
    assert not bool(got.found.any())
    assert bool((got.slots == -1).all())


def test_padding_keys_are_noops():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 10)
    st = OPS.init(cfg)
    keys = _keys([1, INVALID_WORD, 2], [1, INVALID_WORD, 2])
    st, res = OPS.insert_batch(st, keys, _vals([10, 11, 12]))
    assert np.asarray(res.slots)[1] == -1
    got = OPS.get_batch(st, keys)
    np.testing.assert_array_equal(np.asarray(got.found), [True, False, True])


def test_update_in_place_overwrites_value():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 10)
    st = OPS.init(cfg)
    k = _keys([5], [9])
    st, _ = OPS.insert_batch(st, k, _vals([100]))
    st, res = OPS.insert_batch(st, k, _vals([200]))
    assert bool(is_invalid(res.evicted).all())  # update, not eviction
    got = OPS.get_batch(st, k)
    assert int(got.values[0, 1]) == 200
    # still exactly one copy: occupancy == 1
    flat_keys, _ = OPS.scan(st)
    assert int((~is_invalid(flat_keys)).sum()) == 1


def test_duplicate_keys_in_batch_last_wins():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 10)
    st = OPS.init(cfg)
    keys = _keys([7, 7, 7], [1, 1, 1])
    st, _ = OPS.insert_batch(st, keys, _vals([1, 2, 3]))
    got = OPS.get_batch(st, keys[:1])
    assert int(got.values[0, 1]) == 3
    flat_keys, _ = OPS.scan(st)
    assert int((~is_invalid(flat_keys)).sum()) == 1


def test_fifo_eviction_on_full_cluster():
    # one cluster total => every key collides; capacity 16
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=16, cluster_slots=16)
    st = OPS.init(cfg)
    k1 = _keys(np.zeros(16, np.uint32), np.arange(16))
    st, res1 = OPS.insert_batch(st, k1, _vals(np.arange(16)))
    assert bool(is_invalid(res1.evicted).all())
    # 4 more keys evict the 4 oldest (FIFO)
    k2 = _keys(np.zeros(4, np.uint32), 100 + np.arange(4))
    st, res2 = OPS.insert_batch(st, k2, _vals([1, 2, 3, 4]))
    ev = np.asarray(res2.evicted)
    assert set(map(tuple, ev.tolist())) == {(0, 0), (0, 1), (0, 2), (0, 3)}
    got_old = OPS.get_batch(st, k1)
    np.testing.assert_array_equal(
        np.asarray(got_old.found), [False] * 4 + [True] * 12
    )
    got_new = OPS.get_batch(st, k2)
    assert bool(got_new.found.all())


def test_overflow_within_one_batch_drops_excess():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=16, cluster_slots=16)
    st = OPS.init(cfg)
    keys = _keys(np.zeros(20, np.uint32), np.arange(20))
    st, res = OPS.insert_batch(st, keys, _vals(np.arange(20)))
    assert int(res.dropped.sum()) == 4
    got = OPS.get_batch(st, keys)
    assert int(got.found.sum()) == 16
    # dropped keys report themselves, not phantom slots
    np.testing.assert_array_equal(
        np.asarray(res.slots)[np.asarray(res.dropped)], [-1] * 4
    )


def test_delete_then_miss():
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 10)
    st = OPS.init(cfg)
    keys = _keys([1, 2, 3], [1, 2, 3])
    st, _ = OPS.insert_batch(st, keys, _vals([1, 2, 3]))
    st, deleted, _ = OPS.delete_batch(st, keys[:2])
    np.testing.assert_array_equal(np.asarray(deleted), [True, True])
    got = OPS.get_batch(st, keys)
    np.testing.assert_array_equal(np.asarray(got.found), [False, False, True])
    # deleting a missing key reports False
    st, deleted2, _ = OPS.delete_batch(st, _keys([99], [99]))
    assert not bool(deleted2.any())


def test_large_random_workload_no_false_hits():
    rng = np.random.default_rng(0)
    cfg = IndexConfig(kind=IndexKind.LINEAR, capacity=1 << 14)
    st = OPS.init(cfg)
    n = 4096
    los = rng.choice(1 << 20, size=n, replace=False).astype(np.uint32)
    keys = _keys(np.full(n, 3, np.uint32), los)
    vals = _vals(los)
    st, res = OPS.insert_batch(st, keys, vals)
    got = OPS.get_batch(st, keys)
    evicted_or_dropped = int((~is_invalid(res.evicted)).sum()) + int(res.dropped.sum())
    # every key must be found unless evicted/dropped (test_KV failedSearch rule)
    assert int((~got.found).sum()) <= evicted_or_dropped
    ok = np.asarray(got.found)
    np.testing.assert_array_equal(
        np.asarray(got.values[:, 1])[ok], np.asarray(vals[:, 1])[ok]
    )
    # absent keys never produce false hits
    other = _keys(np.full(n, 4, np.uint32), los)
    got2 = OPS.get_batch(st, other)
    assert not bool(got2.found.any())


@pytest.mark.slow
def test_plan_insert_matches_legacy_helpers():
    """plan_insert/plan_rank (one fused sort) must agree with the two
    separately-trusted helpers they replace: winners identical to
    dedupe_last_wins, ranks a dense 0..k-1 per segment over the mask."""
    import jax.numpy as jnp

    from pmdfc_tpu.models.base import (
        dedupe_last_wins,
        plan_insert,
        plan_rank,
    )

    rng = np.random.default_rng(17)
    for trial in range(25):
        b = int(rng.integers(4, 200))
        # duplicate-heavy keys incl. INVALID padding rows
        pool = rng.integers(0, 40, size=(b, 2)).astype(np.uint32)
        pad = rng.random(b) < 0.2
        pool[pad] = 0xFFFFFFFF
        keys = jnp.asarray(pool)
        valid = ~np.all(pool == 0xFFFFFFFF, axis=1)
        # segment must be a pure function of the key (same key -> same seg)
        seg = jnp.asarray(
            ((pool[:, 0] * 31 + pool[:, 1]) % 7).astype(np.uint32))
        plan = plan_insert(keys, seg, jnp.asarray(valid))
        legacy = np.asarray(dedupe_last_wins(keys, jnp.asarray(valid)))
        np.testing.assert_array_equal(np.asarray(plan.winner), legacy,
                                      err_msg=f"trial {trial}")
        mask = np.asarray(plan.winner) & (rng.random(b) < 0.7)
        rank = np.asarray(plan_rank(plan, jnp.asarray(mask)))
        assert (rank[~mask] >= 0x7FFFFFFF - 1).all()  # inert huge ranks
        segs = np.asarray(seg)
        for sgi in np.unique(segs[mask]):
            got = np.sort(rank[mask & (segs == sgi)])
            np.testing.assert_array_equal(got, np.arange(len(got)),
                                          err_msg=f"trial {trial} seg {sgi}")


@pytest.mark.slow
def test_rowscatter_insert_equivalence():
    """The whole-row-rebuild insert prototype (bench/insert_rowscatter.py)
    must stay bit-identical to insert_batch — randomized batches with
    duplicates, padding, updates, evictions, and update-vs-evicting-insert
    lane collisions."""
    from pmdfc_tpu.bench.insert_rowscatter import check_equivalence

    assert check_equivalence(seed=7, trials=25) == 25


def test_insert_path_env_switch():
    """PMDFC_INSERT_PATH=row must route the registered insert through the
    row-rebuild implementation (the on-chip A/B lever)."""
    import os
    import subprocess
    import sys

    env = {**os.environ, "PMDFC_INSERT_PATH": "row", "JAX_PLATFORMS": "cpu"}
    code = (
        "from pmdfc_tpu.models import linear; "
        "assert linear.insert_batch is linear.insert_batch_row; "
        "from pmdfc_tpu.models.base import get_index_ops; "
        "from pmdfc_tpu.config import IndexKind; "
        "assert get_index_ops(IndexKind.LINEAR).insert_batch "
        "is linear.insert_batch_row; print('switch-ok')"
    )
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, timeout=120)
    assert b"switch-ok" in out.stdout, out.stderr[-500:]
