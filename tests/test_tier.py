"""Tier invariants suite — hot/cold pools, migration, ghost, ballooning.

The contracts under test (ISSUE 2):
- promotion/demotion preserves page bytes AND digest sidecars (migration
  can never launder corruption);
- hot and cold never both claim a key (every index row id is unique and
  the hot ownership plane matches the index exactly);
- ghost-list readmission: a recently demoted key re-promotes on ONE touch;
- balloon grow covers fill bursts without drops; balloon shrink under load
  degrades to legal misses — never wrong bytes;
- `PMDFC_TIER=off` is bit-identical to the flat pool on the conformance
  families.
"""

import numpy as np
import pytest

from pmdfc_tpu import tier as tier_mod
from pmdfc_tpu.config import IndexConfig, IndexKind, KVConfig, TierConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.models.base import get_index_ops
from pmdfc_tpu.ops.pagepool import PoolState, page_digest_np
from pmdfc_tpu.utils.keys import INVALID_WORD

pytestmark = pytest.mark.tier

W = 64  # small pages keep the suite inside the tier-1 budget


def _cfg(capacity=1 << 10, kind=IndexKind.LINEAR, tier=None, **tkw):
    t = tier if tier is not None else TierConfig(**tkw)
    return KVConfig(index=IndexConfig(kind=kind, capacity=capacity),
                    bloom=None, paged=True, page_words=W, tier=t)


def _flat_cfg(capacity=1 << 10, kind=IndexKind.LINEAR):
    return KVConfig(index=IndexConfig(kind=kind, capacity=capacity),
                    bloom=None, paged=True, page_words=W)


def _keys(los):
    los = np.asarray(los, np.uint32)
    return np.stack([los >> 16, los], axis=-1).astype(np.uint32)


def _pages(keys):
    lo = np.asarray(keys, np.uint32)[:, 1]
    return (lo[:, None] * np.uint32(2654435761)
            + np.arange(W, dtype=np.uint32)[None, :])


def _check_invariants(kv: KV):
    """Row-uniqueness + hot-ownership coherence + live-bit sanity."""
    pool = kv.state.pool
    assert isinstance(pool, tier_mod.TierState)
    h = pool.hfree.shape[0]
    ops = get_index_ops(kv.config.index.kind)
    fk_j, fv_j = ops.scan(kv.state.index)
    fk, fv = np.asarray(fk_j), np.asarray(fv_j)
    valid = ~np.all(fk == INVALID_WORD, axis=-1)
    # page-row entries: top-2 hi-word bits clear (lower bits = generation)
    paged = valid & ((fv[:, 0] >> 30) == 0)
    # only CURRENT-generation entries claim their row (stale ones are
    # legal misses and claim nothing)
    h_rows = pool.hfree.shape[0]
    cgen = np.asarray(pool.cgen)
    rws = fv[:, 1].astype(np.int64)
    is_cold = paged & (rws >= h_rows)
    cur = paged & np.where(
        is_cold, fv[:, 0] == cgen[np.clip(rws - h_rows, 0,
                                          len(cgen) - 1)],
        fv[:, 0] == 0)
    rows = fv[cur, 1].astype(np.int64)
    # no row claimed by two keys (hot+cold never both claim a key)
    assert len(np.unique(rows)) == len(rows)
    hk = np.asarray(pool.hot_keys)
    occ = ~np.all(hk == INVALID_WORD, axis=-1)
    # every index-claimed hot row is marked owned, and by the same key
    claimed_hot = rows[rows < h]
    keys_of_hot = fk[cur][rows < h]
    for r, k in zip(claimed_hot, keys_of_hot):
        assert occ[r], f"hot row {r} claimed by index but unowned"
        assert (hk[r] == k).all(), f"hot row {r} ownership mismatch"
    # every owned hot row resolves in the index to exactly that row
    assert occ.sum() == len(claimed_hot)


def test_tier_off_env_is_flat(monkeypatch):
    monkeypatch.setenv("PMDFC_TIER", "off")
    kv = KV(_cfg())
    assert isinstance(kv.state.pool, PoolState)


def test_tier_on_env_default(monkeypatch):
    monkeypatch.setenv("PMDFC_TIER", "on")
    kv = KV(_flat_cfg())
    assert isinstance(kv.state.pool, tier_mod.TierState)


@pytest.mark.parametrize("kind", [IndexKind.LINEAR, IndexKind.CCEH])
def test_tier_off_bit_identical_conformance(monkeypatch, kind):
    """With PMDFC_TIER=off a tier-configured KV must behave exactly like
    the flat pool on the conformance families."""
    monkeypatch.setenv("PMDFC_TIER", "off")
    a = KV(_cfg(kind=kind))
    b = KV(_flat_cfg(kind=kind))
    rng = np.random.default_rng(7)
    for _ in range(4):
        los = rng.integers(0, 1 << 12, 48).astype(np.uint32)
        keys = _keys(los)
        pages = _pages(keys)
        a.insert(keys, pages)
        b.insert(keys, pages)
        qa, fa = a.get(keys[:17])
        qb, fb = b.get(keys[:17])
        assert (fa == fb).all() and (qa == qb).all()
        da = a.delete(keys[40:])
        db = b.delete(keys[40:])
        assert (da == db).all()
    sa, sb = a.stats(), b.stats()
    sa.pop("uptime_s"), sb.pop("uptime_s")
    assert sa == sb


def test_promotion_preserves_bytes_and_digests():
    kv = KV(_cfg(capacity=1 << 9, promote_touches=2))
    keys = _keys(np.arange(1, 129))
    pages = _pages(keys)
    kv.insert(keys, pages)
    hot_set = keys[:24]
    for _ in range(3):
        out, found = kv.get(hot_set)
        assert found.all()
        assert (out == _pages(hot_set)).all()
    ts = kv.tier_stats()
    assert ts["promotions"] > 0
    assert ts["hot_hits"] > 0
    assert ts["migrated_bytes"] == ts["migrated_pages"] * W * 4
    # promoted rows' sidecar digests must equal the pages' true digests
    pool = kv.state.pool
    hk = np.asarray(pool.hot_keys)
    occ = ~np.all(hk == INVALID_WORD, axis=-1)
    assert occ.any()
    nh = pool.hfree.shape[0]
    hp = np.asarray(pool.pages)[:nh][occ]
    hs = np.asarray(pool.sums)[:nh][occ]
    assert (page_digest_np(hp) == hs).all()
    # and the bytes in hot rows are the originally inserted bytes
    assert (hp == _pages(hk[occ])).all()
    _check_invariants(kv)
    # everything (hot or cold) still serves the right bytes
    out, found = kv.get(keys)
    assert found.all()
    assert (out == pages).all()
    assert kv.stats()["corrupt_pages"] == 0
    _check_invariants(kv)


def test_demotion_and_ghost_readmission():
    # tiny hot tier so promotions force demotions quickly
    kv = KV(_cfg(capacity=1 << 8, tier=TierConfig(
        hot_fraction=16, promote_touches=2, ghost_rows=64)))
    h = tier_mod.num_hot_rows(1 << 8, kv.config.tier)
    keys = _keys(np.arange(1, 3 * h + 2))
    pages = _pages(keys)
    kv.insert(keys, pages)
    a = keys[:1]
    for _ in range(3):
        kv.get(a)  # promote A
    assert kv.tier_stats()["promotions"] >= 1
    # promote enough others to evict A from the hot tier
    rest = keys[1: 2 * h + 1]
    for _ in range(3):
        out, found = kv.get(rest)
        assert found.all() and (out == _pages(rest)).all()
    ts = kv.tier_stats()
    assert ts["demotions"] >= 1
    _check_invariants(kv)
    before = kv.tier_stats()["ghost_readmits"]
    out, found = kv.get(a)  # ONE touch readmits via the ghost ring
    assert found.all() and (out == _pages(a)).all()
    # A's bytes survived the demote/readmit round trips
    assert kv.stats()["corrupt_pages"] == 0
    _check_invariants(kv)
    assert kv.tier_stats()["ghost_readmits"] >= before


def test_balloon_grow_covers_fill_burst():
    kv = KV(_cfg(capacity=1 << 10, tier=TierConfig(
        cold_init_rows=64, balloon_step=64, grow_free_rows=16)))
    keys = _keys(np.arange(1, 400))
    pages = _pages(keys)
    for i in range(0, len(keys), 64):
        kv.insert(keys[i:i + 64], pages[i:i + 64])
    ts = kv.tier_stats()
    assert ts["balloon_grows"] >= 1
    s = kv.stats()
    assert s["drops"] == 0
    out, found = kv.get(keys)
    assert (out[found] == pages[found]).all()
    assert found.sum() + s["evictions"] >= len(keys) - s["drops"]
    _check_invariants(kv)


def test_balloon_shrink_under_load_degrades_to_misses():
    kv = KV(_cfg(capacity=1 << 9, tier=TierConfig(balloon_step=32)))
    keys = _keys(np.arange(1, 257))
    pages = _pages(keys)
    kv.insert(keys, pages)
    free_before = tier_mod.stats_arrays(kv.state.pool)["cold_free"]
    shrunk = kv.balloon_shrink(free_before + 64)  # must bite into LIVE rows
    assert shrunk
    ts = kv.tier_stats()
    assert ts["balloon_shrinks"] >= 1
    assert ts["shrink_evictions"] >= 1
    out, found = kv.get(keys)
    # some keys are legally gone; every served page is byte-exact
    assert not found.all()
    assert (out[found] == pages[found]).all()
    assert kv.stats()["corrupt_pages"] == 0
    # a later grow legally returns parked capacity; new puts land fine
    assert kv.balloon_grow(64)
    more = _keys(np.arange(1000, 1032))
    kv.insert(more, _pages(more))
    out2, found2 = kv.get(more)
    assert (out2[found2] == _pages(more)[found2]).all()
    _check_invariants(kv)


def test_stale_entries_never_alias_recirculated_rows():
    """The generation guard: after a forced shrink evicts live rows, a
    grow recirculates them to NEW keys — the old keys' stale index
    entries must miss (never serve the new owner's bytes), a stale
    re-put must take a fresh row, and a stale delete must not free the
    row under its new owner."""
    kv = KV(_cfg(capacity=1 << 8, tier=TierConfig(balloon_step=16)))
    keys = _keys(np.arange(1, 129))
    pages = _pages(keys)
    kv.insert(keys, pages)
    free0 = tier_mod.stats_arrays(kv.state.pool)["cold_free"]
    assert kv.balloon_shrink(free0 + 96)  # evict 96 live rows
    assert kv.balloon_grow(96)            # recirculate them
    new = _keys(np.arange(1000, 1096))
    new_pages = _pages(new)
    kv.insert(new, new_pages)             # reuses the evicted rows
    out, found = kv.get(keys)
    # stale entries: miss or (still-live rows) the ORIGINAL bytes
    assert (out[found] == pages[found]).all()
    # stale delete must not free rows under their new owners
    kv.delete(keys)
    out2, found2 = kv.get(new)
    assert found2.all()
    assert (out2 == new_pages).all()
    assert kv.stats()["corrupt_pages"] == 0
    _check_invariants(kv)


def test_delete_frees_hot_row():
    kv = KV(_cfg(capacity=1 << 8, promote_touches=1))
    keys = _keys(np.arange(1, 33))
    kv.insert(keys, _pages(keys))
    kv.get(keys[:4])  # promote_touches=1: first touch promotes
    assert kv.tier_stats()["promotions"] >= 4
    occ0 = tier_mod.stats_arrays(kv.state.pool)["hot_occupied"]
    assert occ0 >= 4
    hit = kv.delete(keys[:4])
    assert hit.all()
    assert tier_mod.stats_arrays(kv.state.pool)["hot_occupied"] <= occ0 - 4
    _, found = kv.get(keys[:4])
    assert not found.any()
    _check_invariants(kv)


def test_get_compact_tiered_serves_hits_front():
    kv = KV(_cfg(capacity=1 << 8, promote_touches=1))
    keys = _keys(np.arange(1, 17))
    pages = _pages(keys)
    kv.insert(keys, pages)
    kv.get(keys)  # everything promoted
    probe = np.concatenate([keys[:8], _keys(np.arange(500, 508))])
    out, order, found, nfound, b = kv.get_compact_async(probe)
    nf = int(nfound)
    assert nf == 8
    got = np.asarray(out)[:nf]
    src = np.asarray(order)[:nf]
    assert (got == pages[src]).all()


def test_update_in_place_of_hot_resident_key():
    kv = KV(_cfg(capacity=1 << 8, promote_touches=1))
    keys = _keys(np.arange(1, 9))
    kv.insert(keys, _pages(keys))
    kv.get(keys)  # promote
    new_pages = _pages(keys) ^ np.uint32(0xABCD)
    kv.insert(keys, new_pages)  # overwrite while hot-resident
    out, found = kv.get(keys)
    assert found.all()
    assert (out == new_pages).all()
    assert kv.stats()["corrupt_pages"] == 0
    _check_invariants(kv)


def test_tier_sampled_touch_cadence():
    """`touch_sample_every` governs tier bookkeeping like hotring
    counters: lean batches are pure reads (no touches, no migration);
    the sampled batch pays the counting path and drives promotion."""
    cfg = KVConfig(
        index=IndexConfig(capacity=1 << 8, touch_sample_every=4),
        bloom=None, paged=True, page_words=W,
        tier=TierConfig(promote_touches=1),
    )
    kv = KV(cfg)
    keys = _keys(np.arange(1, 9))
    pages = _pages(keys)
    kv.insert(keys, pages)
    for _ in range(3):  # batches 1-3: lean — no tier bookkeeping at all
        out, found = kv.get(keys)
        assert found.all() and (out == pages).all()
    ts = kv.tier_stats()
    assert ts["hot_hits"] + ts["cold_hits"] == 0
    assert ts["promotions"] == 0
    out, found = kv.get(keys)  # batch 4: the sampled counting batch
    assert found.all() and (out == pages).all()
    ts = kv.tier_stats()
    assert ts["cold_hits"] == 8
    assert ts["promotions"] == 8  # promote_touches=1


def test_tier_stats_surface_in_print_stats():
    kv = KV(_cfg(capacity=1 << 8))
    line = kv.print_stats()
    assert "hot_hits=" in line and "promotions=" in line
    assert "balloon_grows" in line


def test_sharded_tier_counters_in_shard_report():
    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh
    import jax

    mesh = make_mesh(jax.devices("cpu")[:2])
    kv = ShardedKV(_cfg(capacity=1 << 8, promote_touches=1),
                   mesh=mesh, dispatch="broadcast")
    keys = _keys(np.arange(1, 49))
    pages = _pages(keys)
    kv.insert(keys, pages)
    out, found = kv.get(keys)
    assert found.all() and (out == pages).all()
    out, found = kv.get(keys)  # drives promotions on both shards
    assert found.all() and (out == pages).all()
    rep = kv.shard_report()
    assert "tier" in rep
    t = rep["tier"]
    assert len(t["hot_hits"]) == 2
    total = kv.tier_stats()
    assert total["promotions"] == sum(t["promotions"])
    assert total["promotions"] > 0
    # hot_heat is decayed to the report tick: bounded by occupancy
    assert len(rep["hot_heat"]) == 2
    for heat, occ in zip(rep["hot_heat"], t["hot_occupied"]):
        assert 0.0 <= heat <= occ + 1e-6


def test_passive_pool_tiered_mode():
    """One-sided adoption: rows are client-addressed (they cannot move),
    so the hot tier is a write-through device mirror over the host cold
    region — promoted rows serve from the mirror, writes never go stale."""
    from pmdfc_tpu.onesided import PassivePool

    pool = PassivePool(128, page_words=32, mode="tiered", hot_rows=8,
                       promote_touches=2)
    rows = np.arange(16, dtype=np.int32)
    pages = (np.arange(16, dtype=np.uint32)[:, None] * 977
             + np.arange(32, dtype=np.uint32)[None, :])
    pool.write_rows(rows, pages)
    for _ in range(3):
        out = pool.read_rows(rows)
        assert (out == pages).all()
    s = pool.stats()
    assert s["promotions"] > 0 and s["hot_hits"] > 0
    # 16 hot-worthy rows vs 8 mirror slots: LRU slots demote
    assert s["demotions"] > 0
    assert s["hot_mirrored"] <= 8
    # write-through: an overwrite of a mirrored row serves the new bytes
    pages2 = pages ^ np.uint32(7)
    pool.write_rows(rows, pages2)
    assert (pool.read_rows(rows) == pages2).all()


def test_tier_stats_over_the_wire():
    """MSG_STATS: tier counters reach a monitoring client through the
    TCP messenger."""
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    kv = KV(_cfg(capacity=1 << 8, promote_touches=1))
    with NetServer(lambda: DirectBackend(kv)) as srv:
        srv.start()
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as be:
            keys = _keys(np.arange(1, 9))
            be.put(keys, _pages(keys))
            out, found = be.get(keys)
            assert found.all()
            s = be.server_stats()
            assert s["puts"] == 8
            assert "promotions" in s and "balloon_grows" in s
            assert s["promotions"] >= 1  # promote_touches=1: get promoted


def test_checkpoint_roundtrip_tiered(tmp_path):
    from pmdfc_tpu import checkpoint as ckpt

    cfg = _cfg(capacity=1 << 8, promote_touches=1)
    kv = KV(cfg)
    keys = _keys(np.arange(1, 33))
    pages = _pages(keys)
    kv.insert(keys, pages)
    kv.get(keys)  # promote some
    path = str(tmp_path / "tier.ckpt")
    kv.snapshot(path)
    st = ckpt.load(path, cfg)
    kv2 = KV(cfg, state=st)
    out, found = kv2.get(keys)
    assert found.all() and (out == pages).all()
