import jax.numpy as jnp
import numpy as np

from pmdfc_tpu.utils.hashing import hash_u64, hash_u64_multi
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid, make_longkey, pack_key, split_longkey


def test_hash_deterministic_and_seed_sensitive():
    hi = jnp.arange(1000, dtype=jnp.uint32)
    lo = jnp.arange(1000, dtype=jnp.uint32) * 7
    h0 = hash_u64(hi, lo, seed=0)
    h0b = hash_u64(hi, lo, seed=0)
    h1 = hash_u64(hi, lo, seed=1)
    np.testing.assert_array_equal(np.asarray(h0), np.asarray(h0b))
    assert np.mean(np.asarray(h0) != np.asarray(h1)) > 0.99


def test_hash_distribution_uniform():
    hi = jnp.zeros(1 << 14, dtype=jnp.uint32)
    lo = jnp.arange(1 << 14, dtype=jnp.uint32)  # sequential page indexes
    buckets = np.asarray(hash_u64(hi, lo)) % 256
    counts = np.bincount(buckets, minlength=256)
    # sequential keys must spread: no bucket over 3x the mean
    assert counts.max() < 3 * counts.mean()
    assert counts.min() > 0


def test_hash_multi_independent():
    hi = jnp.arange(4096, dtype=jnp.uint32)
    lo = jnp.arange(4096, dtype=jnp.uint32)
    hs = np.asarray(hash_u64_multi(hi, lo, num_hashes=4))
    assert hs.shape == (4, 4096)
    for i in range(4):
        for j in range(i + 1, 4):
            assert np.mean(hs[i] == hs[j]) < 0.01


def test_key_pack_roundtrip_and_invalid():
    hi, lo = make_longkey([1, 2, 3], [10, 20, 30])
    keys = pack_key(hi, lo)
    assert keys.shape == (3, 2)
    rhi, rlo = split_longkey(keys)
    np.testing.assert_array_equal(np.asarray(rhi), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(rlo), [10, 20, 30])
    assert not bool(is_invalid(keys).any())
    inv = pack_key([INVALID_WORD], [INVALID_WORD])
    assert bool(is_invalid(inv).all())
