"""Unified telemetry layer drills (`runtime/telemetry.py`).

Four tiers of coverage:
1. Registry semantics — counters/gauges/histograms, scope Mapping reads,
   the no-collision assertion, the Prometheus render, the kill switch.
2. Trace-id propagation — negotiated via TRACE_FLAG, minted per verb,
   recovered server-side: under a seeded `ChaosProxy` soak every verb
   the CLIENT completed has a matching SERVER span (same 32-bit id),
   and verbs that died with a dropped connection are recorded as
   failed spans.
3. Flight recorder — rung 3 (phase failure / breaker open) and rung 5
   (replica-set exhausted) fire dumps that attribute the degradation to
   a concrete conn/phase/endpoint (the ISSUE 5 acceptance drill).
4. Wire export — `MSG_STATS` ships the registry snapshot; the teledump
   schema checker (`tools/check_teledump.py`) pins its shape.
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from pmdfc_tpu.config import NetConfig, TelemetryConfig, telemetry_enabled
from pmdfc_tpu.runtime import telemetry as tele

pytestmark = pytest.mark.telemetry

W = 16


@pytest.fixture()
def fresh_registry():
    """Isolated registry per test; restore a default one afterwards so
    other suites keep a clean namespace."""
    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15))
    yield reg
    tele.configure()


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(1 << 22, size=n, replace=False)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 1:2].astype(np.uint32) * 3 + 1) * np.arange(
        1, W + 1, dtype=np.uint32
    )


# --- 1. registry semantics ----------------------------------------------


def test_scope_counters_and_mapping_reads(fresh_registry):
    s = tele.scope("t", {"a": 0, "b": 0})
    s.inc("a", 3)
    s.inc("c")          # lazy creation
    s.max("hwm", 7)
    s.max("hwm", 4)     # high-water: no regression
    assert s["a"] == 3 and s["b"] == 0 and s["c"] == 1 and s["hwm"] == 7
    assert dict(s) == {"a": 3, "b": 0, "c": 1, "hwm": 7}
    assert "a" in s and len(s) == 4
    with pytest.raises(KeyError):
        s["nope"]


def test_scope_instances_never_share_counters(fresh_registry):
    a = tele.scope("srv", {"ops": 0})
    b = tele.scope("srv", {"ops": 0})
    a.inc("ops", 5)
    assert a["ops"] == 5 and b["ops"] == 0
    assert a.prefix != b.prefix


def test_shared_scope_with_seed_counters(fresh_registry):
    """`unique=False` + pre-seeded counters must not self-deadlock (the
    seeding re-enters registration, which must happen OUTSIDE the
    registry lock); the first caller's seed wins, later callers get the
    existing scope unmodified."""
    s = tele.scope("sh", {"a": 2}, unique=False)
    assert s["a"] == 2
    s2 = tele.scope("sh", {"a": 5}, unique=False)
    assert s2 is s and s["a"] == 2


def test_registry_collision_asserts(fresh_registry):
    reg = fresh_registry
    reg._register("x.ops", tele.Counter)
    with pytest.raises(ValueError, match="already registered"):
        reg._register("x.ops", tele.Gauge)


def test_histogram_log2_quantiles(fresh_registry):
    h = tele.scope("h").hist("lat")
    for v in [1] * 50 + [100] * 45 + [5000] * 5:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["sum"] == pytest.approx(50 + 4500 + 25000)
    # p50 falls in the bucket holding 1 (upper bound 1), p95 in 100's
    # bucket (upper bound 128), p99 clipped to the observed max
    assert snap["p50"] <= 2
    assert 100 <= snap["p95"] <= 128
    assert snap["p99"] <= 5000
    assert snap["max"] == 5000


def test_render_prometheus_style(fresh_registry):
    s = tele.scope("net", {"bad_frames": 2})
    s.hist("lat").observe(10)
    text = tele.render()
    assert "# TYPE pmdfc_net0_bad_frames counter" in text
    assert "pmdfc_net0_bad_frames 2" in text
    assert 'pmdfc_net0_lat{quantile="p95"}' in text
    # round-trips through the snapshot renderer (teledump --format prom)
    assert tele.render_snapshot(tele.snapshot()) == text


def test_kill_switch_noops_tracing_keeps_counters():
    tele.configure(TelemetryConfig(enabled=False))
    try:
        s = tele.scope("k", {"ops": 0})
        s.inc("ops")
        assert s["ops"] == 1          # correctness counters always count
        s.hist("lat").observe(5)
        assert s.hist("lat").snapshot()["count"] == 0
        tele.record_span("client", "get", 1, True)
        tele.record_event("x")
        assert len(tele.get().ring) == 0
        tele.rung("bad_frame")        # counted, never ring-recorded
        assert tele.get()._rungs["bad_frame"] == 1
        assert tele.enabled() is False
    finally:
        tele.configure()


def test_env_kill_switch_resolution(monkeypatch):
    monkeypatch.setenv("PMDFC_TELEMETRY", "off")
    assert telemetry_enabled() is False
    assert telemetry_enabled(default=True) is False
    # env wins over a code-side enabled=True config
    reg = tele.configure(TelemetryConfig(enabled=True))
    try:
        assert tele.enabled() is False
        monkeypatch.setenv("PMDFC_TELEMETRY", "on")
        assert telemetry_enabled(default=False) is True
    finally:
        monkeypatch.delenv("PMDFC_TELEMETRY", raising=False)
        tele.configure()
    assert reg is not tele.get()


def test_set_enabled_runtime_toggle(fresh_registry):
    tele.record_span("client", "get", 1, True)
    tele.set_enabled(False)
    tele.record_span("client", "get", 2, True)
    tele.set_enabled(True)
    tele.record_span("client", "get", 3, True)
    traces = [r["trace"] for r in tele.get().ring]
    assert traces == [1, 3]


def test_mint_trace_32bit_nonzero(fresh_registry):
    seen = {tele.mint_trace() for _ in range(1000)}
    assert all(0 < t <= 0xFFFFFFFF for t in seen)
    assert len(seen) == 1000


# --- 2. trace-id propagation (wire + chaos) -----------------------------


def _span_index(reg):
    spans = [r for r in reg.ring if r.get("kind") == "span"]
    return (
        [s for s in spans if s["src"] == "client"],
        {s["trace"] for s in spans if s["src"] == "server"},
    )


def test_trace_negotiation_and_server_spans(fresh_registry):
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    shared = LocalBackend(page_words=W, capacity=1 << 12)
    with NetServer(lambda: shared, net=NetConfig()).start() as srv:
        for pipe in (True, False):
            with TcpBackend("127.0.0.1", srv.port, page_words=W,
                            keepalive_s=None, pipeline=pipe) as be:
                assert be.traced and be.pipelined == pipe
                keys = _keys(8, seed=3)
                be.put(keys, _pages(keys))
                _, found = be.get(keys)
                assert found.all()
    client, server_traces = _span_index(fresh_registry)
    ok = [s for s in client if s["ok"] and s["op"] in ("put", "get")]
    assert len(ok) >= 4
    for s in ok:
        assert s["trace"] != 0
        assert s["trace"] in server_traces, s
        assert s["dur_us"] > 0


def test_trace_off_when_telemetry_disabled():
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    tele.configure(TelemetryConfig(enabled=False))
    try:
        shared = LocalBackend(page_words=W, capacity=1 << 12)
        with NetServer(lambda: shared).start() as srv, \
                TcpBackend("127.0.0.1", srv.port, page_words=W,
                           keepalive_s=None) as be:
            assert not be.traced          # no TRACE_FLAG requested
            _, found = be.get(_keys(4))
            assert not found.any()
        assert len(tele.get().ring) == 0
    finally:
        tele.configure()


def test_trace_ids_match_under_chaos(fresh_registry):
    """The satellite acceptance: seeded ChaosProxy soak over a windowed
    connection — every verb the client COMPLETED has a server span with
    the same trace id, and dropped-conn verbs show as failed spans."""
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.failure import ChaosProxy, ReconnectingClient
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    shared = LocalBackend(page_words=W, capacity=1 << 13)
    srv = NetServer(lambda: shared, net=NetConfig()).start()
    # gentle per-frame rates: a fault still fails the whole 8-verb
    # window, so even 1% yields a steady failed-span stream while most
    # verbs complete and give the propagation check a real sample
    rates = {"flip": 0.01, "truncate": 0.005, "duplicate": 0.01}
    with srv, ChaosProxy("127.0.0.1", srv.port, seed=17,
                         rates=rates) as px:
        def factory():
            return TcpBackend("127.0.0.1", px.port, page_words=W,
                              keepalive_s=None, op_timeout_s=1.0,
                              pipeline=True, window=8)

        rc = ReconnectingClient(factory, page_words=W,
                                retry_delay_s=0.002,
                                max_retry_delay_s=0.02, seed=17)
        keys = _keys(128, seed=17)
        pages = _pages(keys)
        rng = np.random.default_rng(17)
        for step in range(300):
            lo = int(rng.integers(0, 96))
            n = int(rng.integers(1, 16))
            if rng.integers(2):
                rc.put(keys[lo:lo + n], pages[lo:lo + n])
            else:
                rc.get(keys[lo:lo + n])
            if not rc.connected:
                time.sleep(0.003)   # let reconnect land; keep spans flowing
        rc.close()
    client, server_traces = _span_index(fresh_registry)
    verbs = [s for s in client if s["op"] in ("put", "get", "invalidate")]
    completed = [s for s in verbs if s["ok"]]
    failed = [s for s in verbs if not s["ok"]]
    assert len(completed) > 50, "soak barely ran"
    # chaos actually dropped connections -> failed spans recorded
    fired = sum(v for k, v in px.stats.items()
                if k.endswith("_frames") and k != "forwarded_frames")
    assert fired > 0 and len(failed) > 0, (fired, len(failed))
    missing = [s for s in completed if s["trace"] not in server_traces]
    assert not missing, f"{len(missing)} completed verbs lack server spans"
    for s in failed:
        assert s["err"], s
        # a chaos-killed connection must CLOSE its open spans as failed
        # tree nodes — full v2 record, not a dangling begin (ISSUE 9)
        assert s["span"] and 0 < s["span"] <= 0xFFFFFFFF
        assert s["t1_ns"] >= s["t0_ns"] and s["dur_us"] >= 0


# --- 3. flight recorder: rung dumps with attribution --------------------


def _dumps(dump_dir, rung_name):
    out = []
    for f in sorted(os.listdir(dump_dir)):
        # the recorder writes atomically (".json.tmp" then rename):
        # skip in-flight temp files — matching one here raced the
        # rename and crashed the poll loop with FileNotFoundError
        if f.startswith(f"flight_{rung_name}_") and f.endswith(".json"):
            with open(os.path.join(dump_dir, f)) as fh:
                out.append(json.load(fh))
    return out


def test_rung3_phase_failure_dump_attributes_conn_and_phase(
        tmp_path, monkeypatch):
    """Rung 3: a fused serve phase raising server-side drops the
    involved connections; the flight dump must name the phase and the
    concrete conns it took down. Containment is forced OFF so the drill
    keeps pinning the legacy conn-drop path — with PR 18's
    `PMDFC_CONTAINMENT` on (the default), a negotiated connection gets
    a rung-7 `MSG_NACK` legal miss instead (drilled in
    tests/test_containment.py)."""
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    monkeypatch.setenv("PMDFC_CONTAINMENT", "off")
    tele.configure(TelemetryConfig(ring_capacity=1 << 14,
                                   dump_dir=str(tmp_path),
                                   dump_min_interval_s=0.0))
    try:
        class Poisoned(LocalBackend):
            def get(self, keys):
                raise RuntimeError("injected phase failure")

        shared = Poisoned(page_words=W, capacity=1 << 10)
        with NetServer(lambda: shared, net=NetConfig()).start() as srv, \
                TcpBackend("127.0.0.1", srv.port, page_words=W,
                           keepalive_s=None, op_timeout_s=5.0) as be:
            keys = _keys(4, seed=9)
            be.put(keys, _pages(keys))      # put phase still works
            with pytest.raises((ConnectionError, OSError)):
                be.get(keys)                # get phase raises -> rung 3
            deadline = time.time() + 5
            while not _dumps(tmp_path, "phase_failure") \
                    and time.time() < deadline:
                time.sleep(0.02)
        docs = _dumps(tmp_path, "phase_failure")
        assert docs, "no phase_failure dump written"
        d = docs[0]
        assert d["schema"] == "pmdfc-flight-v2"
        assert d["detail"]["phase"] == "get"
        assert d["detail"]["conns"], "no conn attribution"
        assert d["detail"]["ops"] >= 1
        # the ring tail holds the failed server span for the same conn
        fails = [r for r in d["records"]
                 if r.get("kind") == "span" and r.get("src") == "server"
                 and not r.get("ok")]
        assert any(r.get("conn") in d["detail"]["conns"] for r in fails)
        assert d["telemetry"]["counters"]["rung.phase_failure"] >= 1
    finally:
        tele.configure()


def test_rung5_replica_exhausted_dump_attributes_endpoints(tmp_path):
    """Rung 5: every endpoint behind an open breaker ⇒ the GET load-
    sheds to a legal miss; breaker_open and replica_exhausted dumps
    name the concrete endpoints."""
    from pmdfc_tpu.client.replica import ReplicaGroup
    from pmdfc_tpu.config import ReplicaConfig
    from pmdfc_tpu.runtime.failure import ReconnectingClient

    tele.configure(TelemetryConfig(ring_capacity=1 << 14,
                                   dump_dir=str(tmp_path),
                                   dump_min_interval_s=0.0))
    try:
        def dead_factory():
            raise ConnectionError("server down")

        eps = [ReconnectingClient(dead_factory, page_words=W,
                                  retry_delay_s=0.001,
                                  max_retry_delay_s=0.01, seed=i)
               for i in range(2)]
        cfg = ReplicaConfig(n_replicas=2, rf=2, hedge_ms=1.0,
                            breaker_failures=2, breaker_cooldown_s=30.0,
                            repair_interval_s=0.0)
        with ReplicaGroup(eps, page_words=W, cfg=cfg, seed=5) as g:
            keys = _keys(8, seed=5)
            for _ in range(4):           # open both breakers
                out, found = g.get(keys)
                assert not found.any()
            assert all(br.state == "open" for br in g.breakers)
            out, found = g.get(keys)     # rung 5: all sets exhausted
            assert not found.any()
            assert g.counters["load_shed_gets"] > 0
        opens = _dumps(tmp_path, "breaker_open")
        assert opens and opens[0]["detail"]["endpoint"].startswith(
            "replica")
        sheds = _dumps(tmp_path, "replica_exhausted")
        assert sheds, "no replica_exhausted dump written"
        d = sheds[-1]
        assert d["detail"]["op"] == "get"
        assert sorted(d["detail"]["open_endpoints"]) == [0, 1]
        assert d["detail"]["keys"] > 0
    finally:
        tele.configure()


def test_dump_cooldown_limits_writes(tmp_path):
    tele.configure(TelemetryConfig(dump_dir=str(tmp_path),
                                   dump_min_interval_s=60.0))
    try:
        for _ in range(5):
            tele.rung("bad_frame", conn=1)
        assert len(_dumps(tmp_path, "bad_frame")) == 1
        assert tele.get()._rungs["bad_frame"] == 5  # counted regardless
    finally:
        tele.configure()


# --- 4. wire export + schema --------------------------------------------


def _load_check_teledump():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_teledump", os.path.join(root, "tools", "check_teledump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_msg_stats_ships_registry_and_schema_conforms(fresh_registry):
    from pmdfc_tpu.client.backends import LocalBackend
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    shared = LocalBackend(page_words=W, capacity=1 << 10)
    with NetServer(lambda: shared, net=NetConfig()).start() as srv, \
            TcpBackend("127.0.0.1", srv.port, page_words=W,
                       keepalive_s=None) as be:
        keys = _keys(8, seed=1)
        be.put(keys, _pages(keys))
        be.get(keys)
        doc = be.server_stats()
    assert "stored" in doc                  # backend stats untouched
    snap = doc["telemetry"]
    # v2 = v1 + optional series/workload blocks (PR 10); every v1 field
    # keeps its exact shape, so v1 consumers parse v2 unchanged
    assert snap["schema"] == "pmdfc-telemetry-v2"
    assert "workload" in doc                # the X-ray sketch block
    assert any(k.endswith(".ops") for k in snap["counters"])
    assert any(k.endswith("get_us") for k in snap["histograms"])
    checker = _load_check_teledump()
    assert checker.check(doc) == []
    # and the checker actually catches breakage
    bad = json.loads(json.dumps(doc))
    bad["telemetry"]["counters"]["net0.ops"] = "three"
    assert checker.check(bad)
    assert checker.check({}) != []


# --- 5. migrated stats surfaces -----------------------------------------


def test_reconnecting_client_counters_shim_removed(fresh_registry):
    # the one-release deprecation shim (PR 5) is gone: `stats()` is the
    # only counter surface, and the old attribute must not quietly
    # reappear as something mapping-shaped
    from pmdfc_tpu.runtime import failure

    rc = failure.ReconnectingClient(
        lambda: (_ for _ in ()).throw(ConnectionError()), page_words=W)
    rc.get(_keys(3))
    assert rc.stats()["missed_gets"] == 3
    assert not hasattr(rc, "counters")


def test_integrity_backend_namespaces_wrapper_counters(fresh_registry):
    from pmdfc_tpu.client.backends import IntegrityBackend, LocalBackend

    be = IntegrityBackend(LocalBackend(page_words=W))
    keys = _keys(4, seed=2)
    be.put(keys, _pages(keys))
    _, found = be.get(keys)
    assert found.all()
    s = be.stats()
    assert s["integrity.verified_gets"] == 4
    assert s["integrity.corrupt_pages"] == 0
    assert "client_corrupt_pages" not in s   # the old shadow-prone keys
    # corrupt the inner store: the gate degrades to a miss, bumps the
    # namespaced counter, and fires the digest rung
    inner = be._be._store
    kk = (int(keys[0][0]), int(keys[0][1]))
    inner[kk] = inner[kk] + 1
    out, found = be.get(keys)
    assert not found[0] and be.counters["corrupt_pages"] == 1
    assert tele.get()._rungs["digest_mismatch"] >= 1