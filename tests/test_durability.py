"""Bounded-RPO durability suite (ISSUE 16).

The three legs of the warm-restart story, pinned:

1. **Snapshot chains** — `KV.snapshot(delta=True)` writes only rows
   dirtied since the previous link; `materialize_chain` folds a
   full+deltas chain byte-exactly and REFUSES torn members
   (`CheckpointCorruptError`), gaps / cross-chain mixes / second fulls
   (`SnapshotChainError`), and names the offending leaf on shape drift.
2. **Write-ahead journal** — CRC-framed records over rotating segments;
   a torn tail is legal ONLY in the final segment (truncated + counted),
   earlier corruption is `JournalCorruptError`; replay is idempotent
   (twice ≡ once) and applies put/delete in journal order, so deleted
   keys stay dead — no stale resurrection.
3. **Warm restart** — `journal.warm_restart` = chain + tail replay +
   the `recovering` serving state: not-yet-caught-up misses land in the
   `miss_recovering` cause lane with `misses == Σ causes` bit-exact,
   `mark_recovered` flips the attribution back (idempotently), and the
   state travels the wire via MSG_RECOVERY (degrading to not-recovering
   when the endpoint is down).

The child-process SIGKILL drill (`tools/crashbox.py`) and the
reshard-after-restore chain drill carry `slow`; everything else is
tier-1 sized.
"""

import os

import numpy as np
import pytest

from pmdfc_tpu import checkpoint
from pmdfc_tpu.checkpoint import CheckpointCorruptError, SnapshotChainError
from pmdfc_tpu.config import IndexConfig, JournalConfig, KVConfig
from pmdfc_tpu.kv import KV, MISS_CAUSE_NAMES
from pmdfc_tpu.runtime.journal import (
    REC_DELETE, REC_PUT, Journal, JournalCorruptError, KeyJournal,
    read_records, replay, segment_paths, warm_restart)

pytestmark = pytest.mark.durability

W = 16
CFG = KVConfig(index=IndexConfig(capacity=1 << 10), paged=True,
               page_words=W)
# rpo_ms=0: no flusher thread — syncs happen deterministically at the
# rpo_ops bound, so tests see exact counter values
JCFG = JournalConfig(rpo_ops=8, rpo_ms=0.0)


def _keys(lo, n):
    flat = np.arange(lo, lo + n, dtype=np.uint32)
    return np.stack([flat >> 11, flat & 0x7FF], -1).astype(np.uint32)


def _pages(keys):
    return (keys[:, 1:2].astype(np.uint32) * 3 + 1) * np.arange(
        1, W + 1, dtype=np.uint32)


def _causes(stats):
    return {k: int(stats[k]) for k in MISS_CAUSE_NAMES}


def _assert_ledger(stats):
    assert int(stats["misses"]) == sum(_causes(stats).values()), \
        _causes(stats)


# ---------------------------------------------------------------- journal


def test_keyjournal_bounded_set():
    kj = KeyJournal(4)
    for i in range(6):
        kj.note((i, i))
    assert len(kj) == 4
    assert (0, 0) not in kj and (5, 5) in kj  # oldest trimmed first
    kj.note((2, 2))          # re-note refreshes recency
    kj.note((9, 9))
    assert (2, 2) in kj and (3, 3) not in kj
    kj.discard((9, 9))
    kj.discard((9, 9))       # idempotent
    assert (9, 9) not in kj
    arr = kj.keys_array()
    assert arr.dtype == np.uint32 and arr.shape == (len(kj), 2)


def test_journal_seq_resumes_in_fresh_segment(tmp_path):
    d = str(tmp_path)
    j = Journal(d, JCFG)
    j.append_put(_keys(0, 4), _pages(_keys(0, 4)))
    j.append_delete(_keys(0, 2))
    j.close()
    # a reopened journal NEVER extends the old tail: new segment file,
    # seq continues after the last valid record
    j2 = Journal(d, JCFG)
    j2.append_put(_keys(8, 2), _pages(_keys(8, 2)))
    j2.close()
    assert len(segment_paths(d)) == 2
    recs, torn = read_records(d)
    assert torn == 0
    assert [r[0] for r in recs] == [REC_PUT, REC_DELETE, REC_PUT]
    assert [r[2] for r in recs] == [0, 1, 2]  # seq gapless across reopen


def test_journal_replay_idempotent_no_resurrection(tmp_path):
    d = str(tmp_path)
    j = Journal(d, JCFG)
    ka, kb = _keys(0, 16), _keys(16, 8)
    j.append_put(ka, _pages(ka))
    j.append_put(kb, _pages(kb))
    j.append_delete(ka[:4])       # deleted AFTER the put: must stay dead
    j.close()

    def state_of(kv):
        got, found = kv.get(_keys(0, 24))
        return np.asarray(found).copy(), np.asarray(got).copy()

    kv = KV(CFG)
    rep1 = replay(d, kv, after_mark=False)
    assert rep1["puts"] == 2 and rep1["deletes"] == 1
    f1, g1 = state_of(kv)
    assert not f1[:4].any() and f1[4:].all()
    rep2 = replay(d, kv, after_mark=False)  # twice ≡ once
    assert rep2["records"] == rep1["records"]
    f2, g2 = state_of(kv)
    np.testing.assert_array_equal(f1, f2)
    np.testing.assert_array_equal(g1[f1], g2[f2])


def test_torn_tail_truncated_and_counted(tmp_path):
    d = str(tmp_path)
    j = Journal(d, JCFG)
    for lo in range(0, 12, 4):
        j.append_put(_keys(lo, 4), _pages(_keys(lo, 4)))
    j.close()
    seg = segment_paths(d)[-1]
    with open(seg, "r+b") as f:       # tear mid-record: crash shape
        f.truncate(os.path.getsize(seg) - 3)
    recs, torn = read_records(d)
    assert torn > 0 and len(recs) == 2  # only the torn record dropped
    kv = KV(CFG)
    rep = replay(d, kv, after_mark=False)
    assert rep["truncated_bytes"] > 0 and rep["puts"] == 2
    _, found = kv.get(_keys(0, 8))
    assert found.all()


def test_corrupt_history_refused(tmp_path):
    d = str(tmp_path)
    # tiny segments force rotation: corruption then lands mid-history
    j = Journal(d, JournalConfig(rpo_ops=8, rpo_ms=0.0,
                                 segment_bytes=4096))
    for lo in range(0, 120, 8):
        j.append_put(_keys(lo, 8), _pages(_keys(lo, 8)))
    j.close()
    segs = segment_paths(d)
    assert len(segs) > 1
    with open(segs[0], "r+b") as f:   # torn tail is legal ONLY in the
        f.truncate(os.path.getsize(segs[0]) - 3)  # FINAL segment
    with pytest.raises(JournalCorruptError):
        read_records(d)


# --------------------------------------------------------- snapshot chain


def test_delta_chain_roundtrip_and_refusals(tmp_path):
    kv = KV(CFG)
    ka, kb = _keys(0, 48), _keys(48, 16)
    kv.insert(ka, _pages(ka))
    full = str(tmp_path / "full.npz")
    d1 = str(tmp_path / "d1.npz")
    d2 = str(tmp_path / "d2.npz")
    r0 = kv.snapshot(full)
    assert r0["kind"] == "full" and r0["seq"] == 0
    kv.insert(kb, _pages(kb))
    r1 = kv.snapshot(d1, delta=True)
    assert r1["kind"] == "delta" and r1["seq"] == 1
    assert 0 < r1["dirty_rows"] < r0["total_rows"]
    kv.delete(ka[:8])
    r2 = kv.snapshot(d2, delta=True)
    assert r2["seq"] == 2

    # byte-exact roundtrip, order-insensitive path list
    state = checkpoint.load_chain([d2, full, d1], CFG, run_recovery=False)
    kv2 = KV(CFG)
    kv2.state = state
    got, found = kv2.get(_keys(0, 64))
    assert not found[:8].any() and found[8:].all()
    np.testing.assert_array_equal(
        got[8:], _pages(_keys(0, 64))[8:])

    # gap in the chain (full + d2 without d1) is refused
    with pytest.raises(SnapshotChainError):
        checkpoint.materialize_chain([full, d2])
    # a delta standalone is refused
    with pytest.raises(SnapshotChainError):
        checkpoint.materialize_chain([d1])
    with pytest.raises(ValueError):
        checkpoint.load_leaves(d1, None)
    # cross-chain mix is refused: a second full starts a NEW chain id
    kvx = KV(CFG)
    kvx.insert(ka, _pages(ka))
    fullx = str(tmp_path / "fullx.npz")
    dx = str(tmp_path / "dx.npz")
    kvx.snapshot(fullx)
    kvx.insert(kb, _pages(kb))
    kvx.snapshot(dx, delta=True)
    with pytest.raises(SnapshotChainError):
        checkpoint.materialize_chain([full, dx])
    # torn delta member is refused as corruption, not as a chain error
    blob = bytearray(open(d1, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    torn = str(tmp_path / "torn.npz")
    open(torn, "wb").write(bytes(blob))
    with pytest.raises((CheckpointCorruptError, SnapshotChainError,
                        ValueError)):
        checkpoint.materialize_chain([full, torn])


def test_restore_refusal_names_the_leaf(tmp_path):
    kv = KV(CFG)
    ka = _keys(0, 8)
    kv.insert(ka, _pages(ka))
    path = str(tmp_path / "full.npz")
    kv.snapshot(path)
    small = KVConfig(index=IndexConfig(capacity=1 << 9), paged=True,
                     page_words=W)
    with pytest.raises(ValueError, match="mismatch") as ei:
        checkpoint.load(path, small)
    # the refusal names WHICH leaf disagreed, not just that one did
    assert "'" in str(ei.value) and "shape" in str(ei.value)


# ----------------------------------------------------------- warm restart


def test_miss_recovering_attribution_and_ledger():
    kv = KV(CFG)
    ka = _keys(0, 16)
    kv.insert(ka, _pages(ka))
    kv.begin_recovering()
    assert kv.recovery_info()["recovering"] is True
    _, found = kv.get(_keys(1024, 16))     # absent: would-be miss_cold
    assert not found.any()
    st = kv.stats()
    _assert_ledger(st)
    assert st["miss_recovering"] == 16 and st["miss_cold"] == 0
    _, found = kv.get(ka)                  # hits still serve while
    assert found.all()                     # recovering
    assert kv.mark_recovered() is True
    assert kv.mark_recovered() is False    # idempotent
    _, found = kv.get(_keys(2048, 8))
    st = kv.stats()
    _assert_ledger(st)
    assert st["miss_cold"] == 8            # attribution flipped back
    assert st["miss_recovering"] == 16


def test_warm_restart_end_to_end(tmp_path):
    snap = tmp_path / "snap"
    snap.mkdir()
    jdir = str(tmp_path / "wal")
    kv = KV(CFG, journal=Journal(jdir, JCFG))
    ka, kb, kc = _keys(0, 64), _keys(64, 16), _keys(80, 8)
    kv.insert(ka, _pages(ka))
    full = str(snap / "full.npz")
    delta = str(snap / "d1.npz")
    kv.snapshot(full)
    kv.insert(kb, _pages(kb))
    kv.snapshot(delta, delta=True)
    kv.insert(kc, _pages(kc))              # journal tail only
    kv.delete(ka[:4])
    kv._journal.close()

    kv2, report = warm_restart(CFG, [full, delta], jdir,
                               journal_config=JCFG)
    assert report["puts"] >= 1 and report["deletes"] >= 1
    got, found = kv2.get(_keys(0, 88))
    assert not found[:4].any(), "deleted keys resurrected by replay"
    assert found[4:].all(), "journal tail lost"
    np.testing.assert_array_equal(got[4:], _pages(_keys(0, 88))[4:])
    info = kv2.recovery_info()
    assert info["recovering"] is True
    assert info["chain"]["seq"] == 1       # cursor re-armed on the chain
    st = kv2.stats()
    _assert_ledger(st)
    # the rejoined journal accepts new mutations immediately
    kd = _keys(96, 4)
    kv2.insert(kd, _pages(kd))
    assert kv2.mark_recovered() is True
    kv2._journal.close()
    recs, torn = read_records(jdir)
    assert torn == 0 and any(r[0] == REC_PUT for r in recs)


def test_warm_restart_empty_chain_replays_from_start(tmp_path):
    jdir = str(tmp_path / "wal")
    kv = KV(CFG, journal=Journal(jdir, JCFG))
    ka = _keys(0, 12)
    kv.insert(ka, _pages(ka))
    kv._journal.close()
    kv2, report = warm_restart(CFG, [], jdir, journal_config=JCFG)
    assert report["puts"] == 1
    _, found = kv2.get(ka)
    assert found.all()
    kv2._journal.close()


# ------------------------------------------------------------ ring + wire


def test_ring_rejoin_bumps_epoch_same_members():
    from pmdfc_tpu.cluster.ring import HashRing

    r = HashRing([3, 5, 9])
    r2 = r.rejoin(5)
    assert r2.epoch == r.epoch + 1
    assert r2.members == r.members
    keys = _keys(0, 64)
    np.testing.assert_array_equal(r.owners_np(keys, 2),
                                  r2.owners_np(keys, 2))
    with pytest.raises(ValueError):
        r.rejoin(4)


def test_recovery_state_travels_the_wire():
    from pmdfc_tpu.client.backends import DirectBackend
    from pmdfc_tpu.runtime.failure import ReconnectingClient
    from pmdfc_tpu.runtime.net import NetServer, TcpBackend

    kv = KV(CFG)
    kv.begin_recovering()
    srv = NetServer(lambda: DirectBackend(kv)).start()
    try:
        with TcpBackend("127.0.0.1", srv.port, page_words=W) as be:
            assert be.recovery_info()["recovering"] is True
            assert be.mark_recovered() is True
            assert be.recovery_info()["recovering"] is False
            assert be.mark_recovered() is False
        port = srv.port
    finally:
        srv.stop()
    # degraded endpoint: the queries degrade to not-recovering / no-op
    # instead of raising (rung-5 behavior — recovery state is advisory)
    rc = ReconnectingClient(
        lambda: TcpBackend("127.0.0.1", port, page_words=W,
                           op_timeout_s=0.2),
        page_words=W, retry_delay_s=0.005, max_retry_delay_s=0.01)
    try:
        assert rc.recovery_info() == {"recovering": False}
        assert rc.mark_recovered() is False
    finally:
        rc.close()


def test_server_checkpoint_delta_and_health(tmp_path):
    from pmdfc_tpu.runtime.server import KVServer

    srv = KVServer(CFG)
    ka = _keys(0, 24)
    srv.kv.insert(ka, _pages(ka))
    r0 = srv.checkpoint(str(tmp_path / "full.npz"))
    assert r0["kind"] == "full"
    srv.kv.insert(_keys(24, 8), _pages(_keys(24, 8)))
    r1 = srv.checkpoint(str(tmp_path / "d1.npz"), delta=True)
    assert r1["kind"] == "delta" and r1["seq"] == 1
    h = srv.health()
    assert h["recovery"]["recovering"] is False
    srv.kv.begin_recovering()
    assert srv.health()["recovery"]["recovering"] is True


# ------------------------------------------------------- slow heavy drills


@pytest.mark.slow
def test_crashbox_sigkill_torn_tail_drill(tmp_path):
    """Real child process, real SIGKILL between two acked RPCs: zero
    wrong bytes, acked-pages lost within the RPO bound, journal-tail
    replay visible in the warm restart report."""
    from pmdfc_tpu.runtime.net import TcpBackend
    from tools.crashbox import Crashbox

    jdir = str(tmp_path / "wal")
    full = str(tmp_path / "full.npz")
    delta = str(tmp_path / "d1.npz")
    jcfg = JournalConfig(rpo_ops=64, rpo_ms=0.0)
    box = Crashbox(CFG, jdir, jcfg)
    box.start()
    be = TcpBackend("127.0.0.1", box.port, page_words=W)
    ka, kb, kc = _keys(0, 128), _keys(128, 32), _keys(160, 32)
    be.put(ka, _pages(ka))
    box.snapshot(full)
    be.put(kb, _pages(kb))
    box.snapshot(delta, delta=True)
    be.put(kc, _pages(kc))                 # acked, journal tail only
    be.close()
    box.kill()                             # no flush, no atexit
    assert not box.alive()

    box2 = Crashbox(CFG, jdir, jcfg, chain_paths=[full, delta])
    hello = box2.start()
    try:
        assert hello["replay"]["pages"] >= 1
        be2 = TcpBackend("127.0.0.1", box2.port, page_words=W)
        allk = _keys(0, 192)
        got, found = be2.get(allk)
        lost = int((~found).sum())
        assert lost <= (jcfg.rpo_ops + 1) * 192, lost
        good = _pages(allk)
        assert int((got[found] != good[found]).any(axis=1).sum()) == 0
        st = be2.server_stats()
        _assert_ledger(st)
        assert box2.recovery_info()["recovering"] is True
        assert be2.mark_recovered() is True
        be2.close()
    finally:
        box2.stop()


@pytest.mark.slow
@pytest.mark.mesh
def test_reshard_after_restore_chain(tmp_path):
    """A 4-shard full+delta chain restored onto a 2-shard mesh rides
    the plane-router replay — every key lands on its new owner with
    bytes intact."""
    import jax

    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 forced host devices")
    cfg = KVConfig(index=IndexConfig(capacity=1 << 10), paged=True,
                   page_words=W)
    s4 = ShardedKV(cfg, mesh=make_mesh(jax.devices()[:4]))
    ka, kb = _keys(0, 96), _keys(96, 32)
    s4.insert(ka, _pages(ka))
    full = str(tmp_path / "full.npz")
    d1 = str(tmp_path / "d1.npz")
    s4.save(full)
    s4.insert(kb, _pages(kb))
    r1 = s4.snapshot(d1, delta=True)
    assert r1["kind"] == "delta"

    s2 = ShardedKV(cfg, mesh=make_mesh(jax.devices()[:2]))
    s2.restore_chain([full, d1])
    got, found = s2.get(_keys(0, 128))
    assert found.all()
    np.testing.assert_array_equal(got, _pages(_keys(0, 128)))
