"""Workload X-ray suite — windowed series, miss-cause taxonomy, console.

Covers the PR-10 observability layer end to end:

- `runtime/timeseries.py`: DeltaTracker window semantics, ring
  wrap-around at capacity, concurrent-writer sampling, window-quantile
  agreement with live snapshots, and the SLO watchdog's behavior on the
  shared windowing (its PR-8 breach drills re-run in test_tracing).
- miss-cause taxonomy: every recorded miss carries exactly one cause
  and `misses == Σ miss_*` reconciles bit-exactly across `KV.stats`,
  `shard_report` per-shard sums, `KVServer.health`, and the wire
  `MSG_STATS` snapshot — including the seeded zipf soak through the
  4-shard coalesced plane with balloon shrink and ChaosProxy faults
  active (the acceptance drill).
- `runtime/workload.py` sketches: KMV exactness/bounds, heat heavy-
  hitter detection, window rolling.
- `tools/teletop.py`: `--once --json` against two live servers reports
  per-shard rates/p99/hit-rate/working-set from the wire snapshot.
- `pmdfc-telemetry-v2` schema + labeled Prometheus families +
  `tools/check_teledump.py` pins (v1 still parses; drift is caught).
- a forced `slo_breach` flight dump carries the windowed series tail
  covering the breach.
"""

import dataclasses
import glob
import io
import json
import os
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from pmdfc_tpu import kv as kv_mod
from pmdfc_tpu.config import (BloomConfig, IndexConfig, KVConfig,
                              NetConfig, TelemetryConfig, TierConfig)
from pmdfc_tpu.runtime import telemetry as tele
from pmdfc_tpu.runtime import timeseries as ts
from pmdfc_tpu.runtime import workload as wl

pytestmark = pytest.mark.xray

W = 16


def _cfg(capacity=1 << 10, tier=None, bloom=True):
    return KVConfig(
        index=IndexConfig(capacity=capacity),
        bloom=BloomConfig(num_bits=1 << 15) if bloom else None,
        page_words=W, tier=tier)


def _keys(n, seed=0, space=1 << 20):
    rng = np.random.default_rng(seed)
    flat = rng.choice(space, size=n, replace=False)
    return np.stack([flat >> 10, flat & 0x3FF], -1).astype(np.uint32)


def _pages(keys):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, W + 1, dtype=np.uint32)[None, :])


def _causes(d):
    return {k: int(d[k]) for k in kv_mod.MISS_CAUSE_NAMES}


def _assert_reconciled(stats: dict, where: str):
    total = sum(_causes(stats).values())
    assert int(stats["misses"]) == total, (
        f"{where}: misses={stats['misses']} != Σ causes={total} "
        f"({_causes(stats)})")


def _assert_shards_reconciled(rep: dict):
    st = rep["stats"]
    for i in range(rep["n_shards"]):
        total = sum(int(st[k][i]) for k in kv_mod.MISS_CAUSE_NAMES)
        assert int(st["misses"][i]) == total, (i, st)


@pytest.fixture()
def fresh_registry():
    reg = tele.configure(TelemetryConfig(enabled=True))
    yield reg
    tele.configure()


# --- 1. windowed time-series ----------------------------------------------


def test_delta_tracker_windows(fresh_registry):
    sc = tele.scope("xr")
    c = sc.counter("ops")
    h = sc.hist("lat_us")
    tr = ts.DeltaTracker()
    assert tr.counter_window("c", c) is None  # first sight: no window
    c.inc(5)
    assert tr.counter_window("c", c) == 5
    assert tr.counter_window("c", c) == 0
    # histogram window quantiles agree with the live snapshot over the
    # same observations (the ONE quantile_from convention)
    assert tr.hist_window("h", h) is None
    for v in (100.0, 200.0, 400.0, 100000.0):
        h.observe(v)
    q = tr.window_quantiles("h", h)
    live = h.snapshot()
    assert q["count"] == 4 == live["count"]
    assert q["p99"] == live["p99"]
    assert q["p50"] == live["p50"]
    # the NEXT window sees only new observations
    h.observe(7.0)
    q2 = tr.window_quantiles("h", h)
    assert q2["count"] == 1
    assert q2["p50"] <= 8.0
    # replaced metric object re-arms (no garbage delta)
    c2 = tele.Counter()
    c2.inc(100)
    assert tr.counter_window("c", c2) is None


def test_series_ring_wraparound_and_sparse_windows(fresh_registry):
    sc = tele.scope("xr")
    c = sc.counter("ops")
    idle = sc.counter("idle")
    col = ts.Collector(interval_s=0.01, capacity=4)
    col.tick()  # arms the tracker
    for i in range(6):
        c.inc(i + 1)
        col.tick()
    tail = col.ring.tail()
    assert len(tail) == 4  # wrapped at capacity
    assert [w["counters"]["xr0.ops"] for w in tail] == [3, 4, 5, 6]
    # idle metrics cost no window slots (the fixed-memory-bound claim)
    assert all("xr0.idle" not in w["counters"] for w in tail)
    assert idle.value == 0
    snap = col.ring.snapshot(2)
    assert snap["capacity"] == 4 and len(snap["windows"]) == 2


def test_series_concurrent_writers(fresh_registry):
    """Sampling races writers by design: no exception, no lost counts —
    window deltas plus the unsampled remainder equal the total."""
    sc = tele.scope("xr")
    c = sc.counter("ops")
    col = ts.Collector(interval_s=0.001, capacity=256)
    col.tick()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            c.inc(1)

    ths = [threading.Thread(target=writer) for _ in range(4)]
    for t in ths:
        t.start()
    for _ in range(50):
        col.tick()
    stop.set()
    for t in ths:
        t.join()
    final = col.tick()  # close the last window after writers stopped
    windows = col.ring.tail()
    sampled = sum(w["counters"].get("xr0.ops", 0) for w in windows)
    assert final is not None
    assert sampled == c.value  # deltas telescope: nothing lost


def test_collector_daemon_dies_with_registry_swap(fresh_registry):
    col = ts.ensure_collector(interval_s=0.01)
    assert ts.ensure_collector() is col  # idempotent per registry
    th = col._thread
    assert th is not None and th.is_alive()
    tele.configure(TelemetryConfig(enabled=True))  # swap
    th.join(timeout=2)
    assert not th.is_alive()  # orphaned sampler exited on its own


def test_snapshot_v2_carries_series_and_v1_fields(fresh_registry):
    col = ts.ensure_collector(interval_s=0.01)
    sc = tele.scope("xr")
    sc.inc("ops", 3)
    col.tick()
    col.tick()
    snap = tele.snapshot()
    assert snap["schema"] == "pmdfc-telemetry-v2"
    # every v1 field keeps its exact shape
    for k in ("enabled", "counters", "gauges", "histograms", "ring"):
        assert k in snap
    assert snap["series"]["windows"], snap["series"]
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import check_teledump as chk

    assert chk.check({"telemetry": snap}) == []
    # a v1 document (no series, v1 schema) still parses
    v1 = json.loads(json.dumps(snap))
    v1["schema"] = "pmdfc-telemetry-v1"
    del v1["series"]
    assert chk.check({"telemetry": v1}) == []


def test_slo_watchdog_breaches_on_shared_windows(fresh_registry):
    """The watchdog's burn behavior on the shared DeltaTracker: same
    window semantics as before the migration (the PR-8 restart/breach
    drills re-run unchanged in test_tracing)."""
    from pmdfc_tpu.runtime import slo

    sc = tele.scope("slo_xr")
    h = sc.hist("get_us")
    full = f"{sc.prefix}.get_us"
    wd = slo.SloWatchdog(slo.SloConfig(
        targets=(slo.SloTarget(name="p99", kind="latency_p99",
                               metric=full, threshold=1000.0),),
        burn_windows=2, min_count=4))
    assert wd.tick() == []  # first sight: no window
    for _ in range(8):
        h.observe(50000.0)
    assert wd.tick() == []  # burn 1 of 2
    for _ in range(8):
        h.observe(50000.0)
    breaches = wd.tick()
    assert len(breaches) == 1 and breaches[0]["value"] > 1000.0
    assert wd.stats["breaches"] == 1
    # healthy window resets the burn
    for _ in range(8):
        h.observe(10.0)
    assert wd.tick() == []
    # starvation leaves burn untouched
    h.observe(90000.0)
    assert wd.tick() == []
    assert wd.stats["starved_windows"] >= 1


# --- 2. miss-cause taxonomy (unit drills) ---------------------------------


def test_causes_cold_vs_evicted_flat():
    kv = kv_mod.KV(_cfg(capacity=256))
    keys = _keys(600, seed=2)
    pages = _pages(keys)
    for lo in range(0, 600, 64):  # cross-batch inserts -> FIFO evictions
        kv.insert(keys[lo:lo + 64], pages[lo:lo + 64])
    s0 = kv.stats()
    assert s0["evictions"] > 0
    kv.get(keys)
    s = kv.stats()
    _assert_reconciled(s, "flat")
    assert s["miss_evicted"] > 0
    assert s["miss_cold"] == 0  # every missed key was once resident
    # never-inserted keys are cold, not evicted
    kv2 = kv_mod.KV(_cfg())
    kv2.get(keys[:32])
    s2 = kv2.stats()
    _assert_reconciled(s2, "cold")
    assert s2["miss_cold"] == 32 and s2["miss_evicted"] == 0


def test_causes_stale_and_digest_tiered():
    cfg = _cfg(capacity=256, tier=TierConfig(balloon_step=32,
                                             ghost_rows=16))
    kv = kv_mod.KV(cfg)
    keys = _keys(128, seed=3)
    kv.insert(keys, _pages(keys))
    # balloon-shrink the whole cold pool: survivors' entries go stale
    kv.balloon_shrink(512)
    _, found = kv.get(keys)
    s = kv.stats()
    _assert_reconciled(s, "tiered shrink")
    assert s["miss_stale"] > 0
    # digest cause: corrupt one resident row's bytes at rest
    kv3 = kv_mod.KV(_cfg(capacity=256))
    k3 = _keys(8, seed=4)
    kv3.insert(k3, _pages(k3))
    pool = kv3.state.pool
    kv3.state = dataclasses.replace(
        kv3.state,
        pool=dataclasses.replace(pool,
                                 pages=pool.pages ^ jnp.uint32(1 << 7)))
    _, found = kv3.get(k3)
    assert not found.any()
    s3 = kv3.stats()
    _assert_reconciled(s3, "digest")
    assert s3["miss_digest"] == 8 == s3["corrupt_pages"]


def test_causes_parked_nopage():
    """A NOPAGE placement (balloon exhaustion left the entry row-less)
    reads as `miss_parked` — white-box: plant the sentinel the insert
    path writes on shortfall."""
    from pmdfc_tpu.models.base import get_index_ops

    cfg = _cfg(capacity=256, tier=TierConfig(ghost_rows=16))
    kv = kv_mod.KV(cfg)
    keys = _keys(4, seed=5)
    kv.insert(keys, _pages(keys))
    ops = get_index_ops(cfg.index.kind)
    res = ops.get_batch(kv.state.index, jnp.asarray(keys))
    nopage = jnp.broadcast_to(
        jnp.asarray([kv_mod.NOPAGE_TAG, 0], jnp.uint32), (4, 2))
    kv.state = dataclasses.replace(
        kv.state, index=ops.set_values(kv.state.index, res.slots, nopage))
    _, found = kv.get(keys)
    assert not found.any()
    s = kv.stats()
    _assert_reconciled(s, "nopage")
    assert s["miss_parked"] == 4


def test_causes_get_extent_and_sharded_arbitration():
    import jax

    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh

    cfg = _cfg(capacity=1 << 9)
    skv = ShardedKV(cfg, mesh=make_mesh(np.array(jax.devices()[:4])))
    skv.insert_extent(np.array([9, 0], np.uint32),
                      np.array([0, 8192], np.uint32), 16)
    probe = np.stack([np.full(64, 9, np.uint32),
                      np.arange(64, dtype=np.uint32)], -1)
    _, ef = skv.get_extent(probe)
    assert ef[:16].all() and not ef[16:].any()
    s = skv.stats()
    _assert_reconciled(s, "sharded get_extent")
    assert s["miss_cold"] == 48
    _assert_shards_reconciled(skv.shard_report())


# --- 3. workload sketches -------------------------------------------------


def test_kmv_exact_below_k_and_bounded_error_above():
    sk = wl.KmvSketch(k=256)
    h = wl._key_hashes(_keys(100, seed=6))
    sk.add_hashes(h)
    assert sk.estimate() == 100.0  # exact below k
    big = wl._key_hashes(_keys(20000, seed=7, space=1 << 19))
    sk.add_hashes(big)
    est = sk.estimate()
    assert 20100 * 0.7 < est < 20100 * 1.3  # ~1/sqrt(k) relative error


def test_heat_sketch_finds_the_hot_region():
    sketch = wl.WorkloadSketch(window_s=3600.0, fold_keys=512)
    hot = np.tile(np.array([[3, 7]], np.uint32), (3000, 1))
    cold = _keys(3000, seed=8)
    # interleaved like a real workload: a hot region keeps reappearing,
    # which is what keeps it resident in the bounded candidate set
    for lo in range(0, 3000, 300):
        sketch.observe(hot[lo:lo + 300])
        sketch.observe(cold[lo:lo + 300])
    snap = sketch.snapshot()
    assert snap["ops"] == 6000
    heat = snap["heat"]
    assert heat["skew"] >= 0.4  # one key is half the traffic
    hot_prefix = int(wl._key_hashes(hot[:1])[0] >> np.uint64(48))
    assert heat["top"][0][0] == hot_prefix
    # INVALID sentinel rows count nothing
    inv = np.full((10, 2), 0xFFFFFFFF, np.uint32)
    sketch.observe(inv)
    assert sketch.snapshot()["ops"] == 6000


def test_workload_window_rolls():
    sketch = wl.WorkloadSketch(window_s=0.01)
    sketch.observe(_keys(50, seed=9))
    time.sleep(0.02)
    sketch.observe(_keys(60, seed=10))  # rolls the first window
    snap = sketch.snapshot()
    assert snap["window"]["ops"] in (50, 60)
    assert snap["ops"] == 110
    assert snap["working_set"] > 80


# --- 4. export schemas ----------------------------------------------------


def test_prometheus_render_labels_shard_families(fresh_registry):
    sc = tele.scope("mesh", unique=False)
    hists = sc.hist_family("phase_get_us", 2)
    hists[1].observe(100.0)
    sc.counter("shard1_ops").inc(7)
    sc.counter("plain_total").inc(1)
    txt = tele.render()
    # labeled family forms for a stock scraper
    assert 'pmdfc_mesh_shard_ops{shard="1"} 7' in txt
    assert 'pmdfc_mesh_phase_get_us{shard="1",quantile="p99"}' in txt
    assert 'pmdfc_mesh_phase_get_us_count{shard="1"} 1' in txt
    # deprecated suffixed aliases stay for one release
    assert "pmdfc_mesh_shard1_ops 7" in txt
    assert 'pmdfc_mesh_phase_get_us_s1{quantile="p99"}' in txt
    # non-family metrics are untouched
    assert "pmdfc_mesh_plain_total 1" in txt
    assert txt.count("# TYPE pmdfc_mesh_shard_ops counter") == 1


def test_check_teledump_pins_v2(fresh_registry):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import check_teledump as chk

    col = ts.ensure_collector(interval_s=0.01)
    tele.scope("xr").inc("ops", 2)
    col.tick()
    col.tick()
    doc = {
        "telemetry": tele.snapshot(),
        "workload": wl.WorkloadSketch().snapshot(),
        "gets": 10, "misses": 4,
        "miss_cold": 3, "miss_evicted": 1, "miss_parked": 0,
        "miss_stale": 0, "miss_digest": 0, "miss_routed": 0,
        "miss_recovering": 0, "miss_shed": 0,
        "miss_quarantined": 0, "miss_deadline": 0,
    }
    doc = json.loads(json.dumps(doc))
    assert chk.check(doc) == []
    # cause-sum drift is a violation
    bad = json.loads(json.dumps(doc))
    bad["miss_cold"] = 99
    assert any("drift" in e for e in chk.check(bad))
    # per-shard drift too
    bad2 = json.loads(json.dumps(doc))
    bad2["shard_report"] = {"n_shards": 2, "stats": {
        "misses": [2, 2], "miss_cold": [2, 1], "miss_evicted": [0, 0],
        "miss_parked": [0, 0], "miss_stale": [0, 0],
        "miss_digest": [0, 0], "miss_routed": [0, 0],
        "miss_recovering": [0, 0], "miss_shed": [0, 0],
        "miss_quarantined": [0, 0], "miss_deadline": [0, 0]}}
    assert any("shard 1" in e for e in chk.check(bad2))
    # sketch bounds gate
    bad3 = json.loads(json.dumps(doc))
    bad3["workload"]["heat"]["skew"] = 7.0
    assert any("skew" in e for e in chk.check(bad3))
    # series shape gate
    bad4 = json.loads(json.dumps(doc))
    bad4["telemetry"]["series"]["windows"][0]["dt_s"] = "fast"
    assert any("dt_s" in e for e in chk.check(bad4))
    # a v2 serving snapshot (workload present) must ship series
    bad5 = json.loads(json.dumps(doc))
    del bad5["telemetry"]["series"]
    assert any("series" in e for e in chk.check(bad5))


def test_slo_breach_dump_carries_series_tail(fresh_registry, tmp_path):
    from pmdfc_tpu.runtime import slo

    reg = tele.configure(TelemetryConfig(enabled=True,
                                         dump_dir=str(tmp_path),
                                         dump_min_interval_s=0.0))
    col = ts.Collector(interval_s=0.01, registry=reg)
    sc = tele.scope("slo_xr2")
    h = sc.hist("get_us")
    wd = slo.SloWatchdog(slo.SloConfig(
        targets=(slo.SloTarget(name="p99", kind="latency_p99",
                               metric=f"{sc.prefix}.get_us",
                               threshold=100.0),),
        burn_windows=2, min_count=4))
    wd.tick()
    for burn in range(2):
        for _ in range(8):
            h.observe(50000.0)
        col.tick()  # the trajectory INTO the breach
        wd.tick()
    dumps = glob.glob(str(tmp_path / "flight_slo_breach_*.json"))
    assert dumps, os.listdir(tmp_path)
    doc = json.load(open(sorted(dumps)[-1]))
    assert doc["schema"] == "pmdfc-flight-v2"
    series = doc["series"]["windows"]
    assert len(series) >= 2  # the windowed tail covering the breach
    breach_w = [w for w in series
                if f"{sc.prefix}.get_us" in w["hists"]]
    assert breach_w and breach_w[-1]["hists"][
        f"{sc.prefix}.get_us"]["p99"] > 100.0
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import check_teledump as chk

    assert chk.check_flight(doc) == []


# --- 5. the acceptance soak + console -------------------------------------


def _start_plane_server(cfg, n_shards):
    """A 4-shard plane behind the coalesced NetServer (forced host
    devices, the test_mesh discipline)."""
    import jax

    from pmdfc_tpu.parallel.plane import PlaneBackend
    from pmdfc_tpu.parallel.shard import ShardedKV, make_mesh
    from pmdfc_tpu.runtime.net import NetServer

    skv = ShardedKV(cfg, mesh=make_mesh(
        np.array(jax.devices()[:n_shards])))
    be = PlaneBackend(skv)
    srv = NetServer(lambda: be,
                    net=NetConfig(flush_timeout_us=200,
                                  settle_us=50)).start()
    return skv, be, srv


@pytest.mark.slow  # tier-1 budget: heavy drill rides the slow tier (PR 16)
def test_xray_acceptance_soak_and_teletop(fresh_registry):
    """The ISSUE-10 acceptance drill: seeded zipf soak through the
    4-shard coalesced plane with balloon shrink + ChaosProxy faults —
    every miss carries one cause, sums reconcile bit-exactly on every
    surface (per-shard included), and teletop's `--once --json` against
    two live servers reports per-shard rates/p99/hit-rate/working-set
    from the wire snapshot."""
    from pmdfc_tpu.runtime.failure import ChaosProxy, ReconnectingClient
    from pmdfc_tpu.runtime.net import TcpBackend

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import teletop

    cfg = _cfg(capacity=1 << 9,
               tier=TierConfig(balloon_step=64, ghost_rows=32))
    skv, be, srv = _start_plane_server(cfg, 4)
    skv2, be2, srv2 = _start_plane_server(_cfg(capacity=1 << 9), 2)
    proxy = ChaosProxy("127.0.0.1", srv.port, seed=11,
                       rates={"flip": 0.01, "duplicate": 0.005})
    cli = ReconnectingClient(
        lambda: TcpBackend("127.0.0.1", proxy.port, page_words=W,
                           keepalive_s=None, op_timeout_s=5.0),
        page_words=W, retry_delay_s=0.01)
    try:
        rng = np.random.default_rng(23)
        space = _keys(1 << 10, seed=21)
        zipf = np.minimum(rng.zipf(1.3, size=4096) - 1, (1 << 10) - 1)
        for step in range(16):
            idx = zipf[step * 256:(step + 1) * 256]
            keys = space[idx]
            if step % 3 == 0:
                cli.put(keys, _pages(keys))
            out, found = cli.get(keys)
            # served bytes are right bytes, chaos or not
            if found.any():
                np.testing.assert_array_equal(out[found],
                                              _pages(keys)[found])
            if step == 8:
                # mid-soak balloon shrink (per shard, under the plane's
                # dispatch lock), deep enough to exhaust the free stack
                # and evict LIVE rows — stale/parked causes go live
                assert skv.balloon_shrink(512)
            if step % 5 == 0:
                cli.invalidate(keys[:16])
        # light traffic on the second server so teletop has two live rows
        with TcpBackend("127.0.0.1", srv2.port, page_words=W,
                        keepalive_s=None) as b2:
            k2 = space[:128]
            b2.put(k2, _pages(k2))
            b2.get(space[:256])

        # -- every surface reconciles, bit-exactly --
        s = skv.stats()
        assert s["gets"] > 0 and s["misses"] > 0
        _assert_reconciled(s, "ShardedKV.stats")
        rep = skv.shard_report()
        _assert_shards_reconciled(rep)
        for k in ("misses", *kv_mod.MISS_CAUSE_NAMES):
            assert sum(rep["stats"][k]) == s[k], k
        # the shrink actually manufactured taxonomy-specific causes
        assert s["miss_stale"] + s["miss_parked"] > 0, s
        # KVServer.health is the same truth (ONE source: kv.stats)
        from pmdfc_tpu.runtime.server import KVServer

        ksrv = KVServer(cfg, kv=skv)
        _assert_reconciled(ksrv.health()["kv"], "KVServer.health")
        ksrv.engine.close()
        # the wire snapshot agrees with the host surface
        with TcpBackend("127.0.0.1", srv.port, page_words=W,
                        keepalive_s=None) as mon:
            doc = mon.server_stats()
        _assert_reconciled(doc, "MSG_STATS")
        for k in ("misses", *kv_mod.MISS_CAUSE_NAMES):
            assert int(doc[k]) == skv.stats()[k], k
        _assert_shards_reconciled(doc["shard_report"])
        from tools import check_teledump as chk

        assert chk.check(doc) == []

        # -- teletop --once --json against TWO live servers --
        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            rc = teletop.main([f"127.0.0.1:{srv.port}",
                               f"127.0.0.1:{srv2.port}",
                               "--once", "--json", "--page-words",
                               str(W)])
        finally:
            sys.stdout = stdout
        assert rc == 0
        out = json.loads(buf.getvalue())
        rows = out["servers"]
        assert len(rows) == 2 and all(r["ok"] for r in rows)
        r0 = rows[0]
        assert r0["ops_rate"] is not None      # windowed rate, one poll
        assert r0["p99_us"] is not None
        assert 0.0 <= r0["hit_rate"] <= 1.0
        assert 0 < r0["working_set"] <= 4 * r0["capacity"]
        assert len(r0["shards"]) == 4 and len(rows[1]["shards"]) == 2
        for srow in r0["shards"]:
            assert srow["misses"] == sum(srow["miss_causes"].values())
        assert r0["misses"] == sum(r0["miss_causes"].values())
        # the human frame renders without blowing up
        assert "teletop" in teletop.render(rows)
    finally:
        cli.close()
        proxy.close()
        srv.stop()
        srv2.stop()
