"""CCEH index tests: split, doubling, eviction fallback, recovery, paging.

Correctness contract from the reference (`server/CCEH_hybrid.cpp`,
`server/src/cceh.cpp`): every inserted key is gettable unless evicted/dropped
(clean-cache accounting `misses <= evictions + drops`); splits deepen local
depth and redistribute by the next MSB hash bit; Recovery repairs directory
entries; the directory is internally consistent (every stored entry is
reachable through the directory).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from pmdfc_tpu.config import IndexConfig, IndexKind, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.models import cceh
from pmdfc_tpu.models.base import get_index_ops
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import pack_key

OPS = get_index_ops(IndexKind.CCEH)


def cfg(capacity=1 << 9, segment_slots=128, headroom=2):
    return IndexConfig(
        kind=IndexKind.CCEH,
        capacity=capacity,
        segment_slots=segment_slots,
        split_headroom=headroom,
    )


def _keys(lo, hi=1):
    lo = np.asarray(lo, np.uint32)
    return jnp.asarray(np.asarray(pack_key(np.full_like(lo, hi), lo)))


def _vals(lo):
    lo = np.asarray(lo, np.uint32)
    return jnp.asarray(np.stack([np.zeros_like(lo), lo], axis=-1))


def _check_directory_invariants(st):
    """Every valid entry is reachable via the directory; replication blocks
    agree; local depths bound prefix ownership."""
    g = cceh._geom(st)
    keys, _ = OPS.scan(st)
    keys = np.asarray(keys)
    dirr = np.asarray(st.dirr)
    ld = np.asarray(st.ld)
    valid = ~((keys[:, 0] == 0xFFFFFFFF) & (keys[:, 1] == 0xFFFFFFFF))
    slots = np.nonzero(valid)[0]
    h = np.asarray(hash_u64(jnp.asarray(keys[slots, 0]),
                            jnp.asarray(keys[slots, 1])))
    hw = np.asarray(
        hash_u64(jnp.asarray(keys[slots, 0]), jnp.asarray(keys[slots, 1]),
                 seed=cceh.WINDOW_SEED)
    ) & (g.W - 1)
    seg_expect = dirr[h >> (32 - g.Gmax)]
    row_expect = seg_expect * g.W + hw
    row_actual = slots // g.P
    np.testing.assert_array_equal(row_actual, row_expect)
    # replication blocks agree
    for i in range(g.Smax):
        s = dirr[i]
        block = 1 << (g.Gmax - ld[s])
        start = i & ~(block - 1)
        assert dirr[start] == s, f"dir[{i}]={s} but block start disagrees"


def test_roundtrip_no_split():
    st = OPS.init(cfg())
    ks = _keys(np.arange(64))
    st, res = OPS.insert_batch(st, ks, _vals(np.arange(64) * 2))
    assert not bool(res.dropped.any())
    got = OPS.get_batch(st, ks)
    assert bool(got.found.all())
    np.testing.assert_array_equal(np.asarray(got.values)[:, 1],
                                  np.arange(64) * 2)
    _check_directory_invariants(st)


def test_split_grows_segments_and_keeps_entries():
    # tiny segments: capacity 512, segment 128 -> 4 initial segments,
    # headroom 2 -> up to 16. 900 keys force splits.
    c = cfg()
    st = OPS.init(c)
    nseg0 = int(st.nseg)
    rng = np.random.default_rng(3)
    lo = rng.choice(1 << 20, size=900, replace=False)
    ks = _keys(lo)
    evicted = 0
    dropped = 0
    for i in range(0, 900, 128):
        st, res = OPS.insert_batch(st, ks[i : i + 128],
                                   _vals(lo[i : i + 128]))
        evicted += int((np.asarray(res.evicted) != 0xFFFFFFFF).all(-1).sum())
        dropped += int(np.asarray(res.dropped).sum())
    assert int(st.nseg) > nseg0, "no split happened"
    got = OPS.get_batch(st, ks)
    misses = int((~np.asarray(got.found)).sum())
    assert misses <= evicted + dropped
    # the vast majority fit in 2048 slots
    assert misses < 50
    ok = np.asarray(got.found)
    np.testing.assert_array_equal(np.asarray(got.values)[ok, 1], lo[ok])
    _check_directory_invariants(st)


def test_eviction_fallback_when_headroom_exhausted():
    c = cfg(capacity=1 << 8, segment_slots=64, headroom=1)
    st = OPS.init(c)
    total = get_index_ops(IndexKind.CCEH).num_slots(c)
    n = total * 3
    rng = np.random.default_rng(5)
    lo = rng.choice(1 << 22, size=n, replace=False)
    ks = _keys(lo)
    ev = drop = 0
    for i in range(0, n, 256):
        st, res = OPS.insert_batch(st, ks[i : i + 256], _vals(lo[i : i + 256]))
        ev += int((np.asarray(res.evicted) != 0xFFFFFFFF).all(-1).sum())
        drop += int(np.asarray(res.dropped).sum())
    assert ev > 0, "expected eviction fallback to kick in"
    got = OPS.get_batch(st, ks)
    misses = int((~np.asarray(got.found)).sum())
    assert misses == ev + drop  # exact clean-cache accounting (unique keys)
    _check_directory_invariants(st)


def test_update_in_place_and_delete():
    st = OPS.init(cfg())
    ks = _keys([7, 8])
    st, _ = OPS.insert_batch(st, ks, _vals([1, 2]))
    st, res = OPS.insert_batch(st, ks[:1], _vals([9]))
    assert not bool(res.fresh[0])
    got = OPS.get_batch(st, ks)
    np.testing.assert_array_equal(np.asarray(got.values)[:, 1], [9, 2])
    st, hit, old = OPS.delete_batch(st, ks[:1])
    assert bool(hit[0]) and int(old[0, 1]) == 9
    got = OPS.get_batch(st, ks)
    np.testing.assert_array_equal(np.asarray(got.found), [False, True])


def test_duplicate_keys_in_batch_last_wins():
    st = OPS.init(cfg())
    ks = _keys([5, 5, 5])
    st, res = OPS.insert_batch(st, ks, _vals([1, 2, 3]))
    got = OPS.get_batch(st, ks[:1])
    assert int(np.asarray(got.values)[0, 1]) == 3
    # exactly one placement
    assert int((np.asarray(res.slots) >= 0).sum()) == 1


def test_recovery_repairs_corrupt_directory():
    c = cfg()
    st = OPS.init(c)
    rng = np.random.default_rng(11)
    lo = rng.choice(1 << 20, size=600, replace=False)
    ks = _keys(lo)
    st, _ = OPS.insert_batch(st, ks, _vals(lo))
    g = cceh._geom(st)
    dirr = np.asarray(st.dirr).copy()
    ld = np.asarray(st.ld)
    # corrupt a NON-canonical replicated entry (not a block start)
    corrupted = None
    for i in range(g.Smax):
        s = dirr[i]
        block = 1 << (g.Gmax - ld[s])
        if i & (block - 1):  # not the canonical start
            dirr[i] = (s + 1) % g.Smax
            corrupted = i
            break
    assert corrupted is not None
    bad = dataclasses.replace(st, dirr=jnp.asarray(dirr))
    fixed = OPS.recovery(bad)
    np.testing.assert_array_equal(np.asarray(fixed.dirr), np.asarray(st.dirr))
    got = OPS.get_batch(fixed, ks)
    assert bool(np.asarray(got.found).all())


def test_paged_kv_pages_survive_splits():
    # the pool-row indirection must keep pages attached to keys across
    # segment splits triggered by later batches
    kvcfg = KVConfig(
        index=cfg(capacity=1 << 9, segment_slots=128, headroom=2),
        bloom=None,
        paged=True,
        page_words=8,
    )
    kv = KV(kvcfg)
    rng = np.random.default_rng(7)
    n = 1200
    lo = rng.choice(1 << 20, size=n, replace=False)
    ks = np.asarray(pack_key(np.ones(n, np.uint32), lo.astype(np.uint32)))
    pages = rng.integers(0, 2**32, size=(n, 8), dtype=np.uint32)
    for i in range(0, n, 128):
        kv.insert(ks[i : i + 128], pages[i : i + 128])
    out, found = kv.get(ks)
    s = kv.stats()
    assert (~found).sum() <= s["evictions"] + s["drops"]
    np.testing.assert_array_equal(out[found], pages[found])
    # free-row accounting holds
    from pmdfc_tpu.kv import utilization

    live = float(utilization(kv.state, kvcfg)) * kv.capacity()
    assert int(kv.state.pool.top) == kv.capacity() - round(live)


def test_kv_facade_end_to_end_with_cceh():
    kvcfg = KVConfig(index=cfg(), bloom=None, paged=False)
    kv = KV(kvcfg)
    lo = np.arange(400)
    ks = np.asarray(pack_key(np.ones(400, np.uint32), lo.astype(np.uint32)))
    vals = np.stack([np.zeros(400, np.uint32), lo.astype(np.uint32) * 5],
                    axis=-1)
    kv.insert(ks, vals)
    out, found = kv.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out[:, 1], lo * 5)
    vals2, found2, _ = kv.find_anyway(ks[:4])
    assert found2.all()
