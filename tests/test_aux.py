"""Aux subsystem tests: policy cache, timers/reporter, logger, checkpoint."""

import time

import pytest

import numpy as np

from pmdfc_tpu import checkpoint
from pmdfc_tpu.config import BloomConfig, IndexConfig, IndexKind, KVConfig
from pmdfc_tpu.kv import KV
from pmdfc_tpu.ops.policy_cache import Policy, PolicyCache
from pmdfc_tpu.utils.logger import make_logger
from pmdfc_tpu.utils.timers import Reporter, Timers


def k2(lo):
    lo = np.asarray(lo, np.uint32)
    return np.stack([np.ones_like(lo), lo], axis=-1)


def _fill(c, lo_range, batch=8):
    """Insert in small batches so overflow evicts rather than drops (a
    single huge batch protects every placement and must drop the excess)."""
    lo = np.arange(*lo_range)
    for i in range(0, len(lo), batch):
        c.put(k2(lo[i : i + batch]), k2(lo[i : i + batch]))


def test_policy_cache_lru():
    evicted = []
    c = PolicyCache(128, Policy.LRU, on_evict=lambda k, v: evicted.append(k))
    _fill(c, (0, 64))  # half load: no evictions yet
    # touch the first 16 so they are MRU
    c.get(k2(np.arange(16)))
    _fill(c, (100, 228))  # sustained pressure forces evictions
    assert len(evicted) > 0
    _, found_hot = c.get(k2(np.arange(16)))
    _, found_cold = c.get(k2(np.arange(16, 64)))
    # recently-used survive at a strictly higher rate than untouched
    assert found_hot.mean() > found_cold.mean()


def test_policy_cache_lfu():
    c = PolicyCache(128, Policy.LFU)
    _fill(c, (0, 64))  # half load: no evictions yet
    for _ in range(3):
        c.get(k2(np.arange(8)))  # 8 frequent keys
    _fill(c, (200, 328))
    _, found_freq = c.get(k2(np.arange(8)))
    _, found_rest = c.get(k2(np.arange(8, 64)))
    assert found_freq.mean() > found_rest.mean()
    assert found_freq.all(), "frequent entries evicted under LFU"


def test_policy_cache_fifo():
    evicted = []
    c = PolicyCache(128, Policy.FIFO, on_evict=lambda k, v: evicted.append(k))
    _fill(c, (0, 64))  # half load: no evictions yet
    c.get(k2(np.arange(32)))  # FIFO ignores accesses
    _fill(c, (300, 428))
    assert len(evicted) > 0
    # the earliest evictions are from the first-inserted generation,
    # regardless of recent access (later ones may be gen-2 as it ages)
    assert evicted[0][1] < 64


def test_policy_cache_update_not_evict():
    c = PolicyCache(64, Policy.LRU)
    c.put(k2([1]), k2([10]))
    c.put(k2([1]), k2([20]))
    vals, found = c.get(k2([1]))
    assert found.all() and vals[0, 1] == 20


def test_timers_and_reporter(capsys):
    t = Timers()
    with t.phase("insert"):
        time.sleep(0.01)
    t.add("poll", 0.002)
    avg = t.averages_us()
    assert avg["insert"] >= 10_000 and avg["poll"] == 2000
    assert "insert=" in t.report()
    r = Reporter(interval_s=0.05, sinks=[t.report]).start()
    time.sleep(0.18)
    r.stop()
    out = capsys.readouterr().out
    assert "[indicator]" in out and "insert=" in out


def test_logger_levels(tmp_path):
    log = make_logger("t1", "trace", logfile=str(tmp_path / "log.txt"))
    log.info("hello %d", 42)
    log.trace("fine detail")
    text = (tmp_path / "log.txt").read_text()
    assert "hello 42" in text and "fine detail" in text


@pytest.mark.parametrize("kind", [IndexKind.LINEAR, IndexKind.PATH])
def test_checkpoint_roundtrip(tmp_path, kind):
    # PATH rides along since round 5's fused-row state rewrite: the
    # snapshot schema is the pytree, so a layout change must stay
    # round-trippable (and its dense base-15 slot ids must survive into
    # the restored paged pool)
    cfg = KVConfig(
        index=IndexConfig(kind=kind, capacity=1 << 10),
        bloom=BloomConfig(num_bits=1 << 12),
        paged=True, page_words=8,
    )
    kv = KV(cfg)
    rng = np.random.default_rng(0)
    ks = k2(np.arange(200))
    pages = rng.integers(0, 2**32, (200, 8), dtype=np.uint32)
    kv.insert(ks, pages)
    p = str(tmp_path / "snap.npz")
    checkpoint.save(kv.state, p)
    # restore into a new KV: all pages and bloom state intact
    kv2 = KV(cfg, state=checkpoint.load(p, cfg))
    out, found = kv2.get(ks)
    assert found.all()
    np.testing.assert_array_equal(out, pages)
    np.testing.assert_array_equal(kv2.packed_bloom(), kv.packed_bloom())


def test_checkpoint_recovery_repairs_cceh(tmp_path):
    import dataclasses

    import jax.numpy as jnp

    cfg = KVConfig(
        index=IndexConfig(kind=IndexKind.CCEH, capacity=1 << 9,
                          segment_slots=128, split_headroom=2),
        bloom=None, paged=False,
    )
    kv = KV(cfg)
    rng = np.random.default_rng(1)
    lo = rng.choice(1 << 20, 600, replace=False)
    kv.insert(k2(lo), k2(lo))
    # corrupt a replicated (non-canonical) directory entry, then snapshot
    from pmdfc_tpu.models import cceh as cceh_mod

    st = kv.state.index
    g = cceh_mod._geom(st)
    dirr = np.asarray(st.dirr).copy()
    ld = np.asarray(st.ld)
    for i in range(g.Smax):
        block = 1 << (g.Gmax - ld[dirr[i]])
        if i & (block - 1):
            dirr[i] = (dirr[i] + 1) % g.Smax
            break
    bad = dataclasses.replace(kv.state, index=dataclasses.replace(
        st, dirr=jnp.asarray(dirr)))
    p = str(tmp_path / "snap.npz")
    checkpoint.save(bad, p)
    restored = checkpoint.load(p, cfg)  # recovery runs by default
    kv2 = KV(cfg, state=restored)
    _, found = kv2.get(k2(lo))
    assert found.all(), "recovery failed to repair the directory"


def test_checkpoint_rejects_wrong_config(tmp_path):
    cfg = KVConfig(index=IndexConfig(capacity=1 << 10), bloom=None,
                   paged=False)
    kv = KV(cfg)
    p = str(tmp_path / "snap.npz")
    checkpoint.save(kv.state, p)
    other = KVConfig(index=IndexConfig(capacity=1 << 12), bloom=None,
                     paged=False)
    try:
        checkpoint.load(p, other)
        raise AssertionError("expected mismatch error")
    except ValueError as e:
        assert "mismatch" in str(e)
