"""Device-fused GET kernel suite (`pmdfc_tpu/ops/fused.py`).

What it pins:

1. **Parity** — `fused.get_core` is a bit-exact drop-in twin of
   `kv._get_core` on seeded mixed workloads (present / absent / deleted
   probes, so the evicted sketch and every miss plane carry weight):
   pages, found mask, the folded stats vector, and the whole state tree.
   Tier-1 keeps three representative (family × pool × shape) combos;
   the full linear+cceh × flat/tiered × lean/counting grid and the
   recovering reattribution drill also carry `slow`.
2. **Cause taxonomy** — `misses == Σ causes` stays bit-exact under the
   fused classifier, and the at-rest corruption drill pins that every
   digest refusal the composed verify attributes, the fused verify
   attributes identically (zero wrong bytes served either way).
3. **Mode plumbing** — `fused_mode` strictness (a typo'd `PMDFC_FUSED`
   raises rather than silently running the other kernel), `supports()`
   gates (unpaged pools / non-pow2 geometry silently ride composed even
   when forced), `KVConfig.fused_get` validation.
4. **Kill switch** — `PMDFC_FUSED=off` pins the composed program at the
   KV seam (tier-1) and collapses the 4-shard serving plane to a verb
   transcript bit-identical to the forced-fused plane with zero fused
   programs tracked (`slow`, the PMDFC_MESH2D=off drill pattern).
5. **Recompile signatures** — a cold (family, w, tile, value-width)
   rung bumps exactly two named counters once each — the jitted program
   (`recompile.kv.get_fused*`) and the Pallas kernel build
   (`recompile.kv.get_fused.kernel`) — and a repeated shape bumps
   nothing (the PR-8 tracker discipline, fused edition).

Off-chip (the CI posture) the fused side runs in Pallas interpret mode:
a conformance vehicle with the SAME trace, so parity here is parity of
the program the chip runs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pmdfc_tpu import kv as kv_mod
from pmdfc_tpu.config import (IndexConfig, IndexKind, KVConfig, MeshConfig,
                              TelemetryConfig, TierConfig, fused_mode)
from pmdfc_tpu.ops import fused
from pmdfc_tpu.runtime import telemetry as tele

pytestmark = pytest.mark.fused

W = 64  # pow2 page words: inside the fused support set


def _cfg(kind=IndexKind.LINEAR, tiered=False, capacity=2048,
         page_words=W, fused_get="auto", paged=True):
    return KVConfig(index=IndexConfig(kind=kind, capacity=capacity),
                    paged=paged, page_words=page_words,
                    tier=TierConfig() if tiered else None,
                    fused_get=fused_get)


def _keys(n, rng):
    return np.stack([rng.integers(0, 1 << 30, n, dtype=np.uint32),
                     rng.integers(0, 1 << 30, n, dtype=np.uint32)], -1)


def _pages_of(keys, w=W):
    return ((keys[:, 0] * np.uint32(31) + keys[:, 1])[:, None]
            + np.arange(1, w + 1, dtype=np.uint32)[None, :])


def _seeded_kv(cfg, seed=7, n=192, deleted=10):
    """Insert `n` rows, delete a tail slice (evicted-sketch mass), and
    return (kv, probe) where probe mixes present, deleted, and absent
    keys — every miss cause the classifier knows gets lanes."""
    rng = np.random.default_rng(seed)
    kv = kv_mod.KV(cfg)
    keys = _keys(n, rng)
    pages = rng.integers(0, 1 << 32, (n, cfg.page_words), dtype=np.uint32)
    kv.insert(keys, pages)
    kv.delete(keys[n - deleted:])
    probe = np.concatenate([keys[:n // 2], keys[n - deleted:],
                            _keys(48, rng)])
    return kv, probe


def _stat(stats_vec, name):
    return int(np.asarray(stats_vec)[list(kv_mod.STAT_NAMES).index(name)])


def _assert_core_parity(kind, tiered, lean, recovering=False, damage=None):
    """The conformance unit: drive the SAME padded probe through the
    composed `_get_core` and `fused.get_core` (eager — interpret mode
    off-chip) and require bit-identical pages, found mask, stats vector,
    and state tree. Returns the fused-side state for cause checks."""
    cfg = _cfg(kind, tiered)
    assert fused.supports(cfg)
    kv, probe = _seeded_kv(cfg)
    state = kv.state
    if damage is not None:
        state = damage(state)
    pk = kv._pad_keys(jnp.asarray(probe), 256)
    s1, o1, f1 = kv_mod._get_core(state, cfg, pk, lean=lean,
                                  recovering=recovering)
    s2, o2, f2 = fused.get_core(state, cfg, pk, lean=lean,
                                recovering=recovering)
    assert jnp.array_equal(o1, o2), "page bytes drift"
    assert jnp.array_equal(f1, f2), "found mask drift"
    assert jnp.array_equal(s1.stats, s2.stats), (
        "stats delta (fused - composed): "
        f"{np.asarray(s2.stats) - np.asarray(s1.stats)}")
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        assert jnp.array_equal(a, b), "state tree drift"
    # the disjoint cause partition reconciles on the folded vector
    total = sum(_stat(s2.stats, c) for c in kv_mod.MISS_CAUSE_NAMES)
    assert _stat(s2.stats, "misses") == total
    if damage is None:  # the all-corrupt drill legitimately serves 0 hits
        assert _stat(s2.stats, "hits") > 0
    assert _stat(s2.stats, "misses") > 0
    return s2


# --- 1. parity -------------------------------------------------------------


@pytest.mark.parametrize("kind,tiered,lean", [
    (IndexKind.LINEAR, False, True),
    (IndexKind.LINEAR, True, False),
    (IndexKind.CCEH, False, True),
], ids=["linear-flat-lean", "linear-tiered-counting", "cceh-flat-lean"])
def test_fused_core_parity_representative(kind, tiered, lean):
    _assert_core_parity(kind, tiered, lean)


@pytest.mark.slow
@pytest.mark.parametrize("kind", [IndexKind.LINEAR, IndexKind.CCEH])
@pytest.mark.parametrize("tiered", [False, True])
@pytest.mark.parametrize("lean", [False, True])
def test_fused_core_parity_full_grid(kind, tiered, lean):
    _assert_core_parity(kind, tiered, lean)


@pytest.mark.slow
@pytest.mark.parametrize("kind", [IndexKind.LINEAR, IndexKind.CCEH])
def test_fused_core_parity_recovering(kind):
    """Warm-restart reattribution (cold → miss_recovering) is a static
    branch AROUND the kernel — the fused program must fold it the same."""
    _assert_core_parity(kind, True, False, recovering=True)


@pytest.mark.slow
@pytest.mark.parametrize("kind", [IndexKind.LINEAR, IndexKind.CCEH])
def test_fused_digest_cause_matches_composed(kind):
    """At-rest corruption: flip one bit in every resident page. The
    fused in-VMEM digest recompute must refuse the SAME rows the
    composed verify refuses and attribute them to the SAME cause lane
    (miss_digest == corrupt_pages, zero corrupt bytes served)."""
    def damage(state):
        pool = state.pool
        return dataclasses.replace(
            state, pool=dataclasses.replace(
                pool, pages=pool.pages ^ jnp.uint32(1 << 7)))

    st = _assert_core_parity(kind, False, False, damage=damage)
    assert _stat(st.stats, "miss_digest") > 0
    assert _stat(st.stats, "miss_digest") == _stat(st.stats,
                                                   "corrupt_pages")


# --- 2. the KV seam: stats surface + tier-1 kill-switch pin ---------------


def test_fused_kv_stats_parity_and_reconcile(monkeypatch):
    """`PMDFC_FUSED=on` vs `off` over the same mixed workload through
    the public KV API: identical serving, identical stats surface
    (uptime is host wall clock), `misses == Σ causes` bit-exact. `off`
    IS today's composed path, so this doubles as the tier-1 kill-switch
    pin — the 4-shard plane transcript drill below is `slow`."""
    outs = {}
    for mode in ("on", "off"):
        monkeypatch.setenv("PMDFC_FUSED", mode)
        kv, probe = _seeded_kv(_cfg())
        assert kv._fused_on() is (mode == "on")
        pages, found = kv.get(probe)
        outs[mode] = (np.asarray(pages), np.asarray(found), kv.stats())
    (po, fo, so), (pc, fc, sc) = outs["on"], outs["off"]
    assert np.array_equal(fo, fc), "found mask drift"
    assert np.array_equal(po, pc), "page bytes drift"
    drift = {k: (so.get(k), sc.get(k)) for k in set(so) | set(sc)
             if k != "uptime_s" and so.get(k) != sc.get(k)}
    assert not drift, f"stats lanes drifted: {drift}"
    assert int(so["misses"]) == sum(int(so[c])
                                    for c in kv_mod.MISS_CAUSE_NAMES)
    assert int(so["hits"]) > 0 and int(so["misses"]) > 0


# --- 3. mode plumbing ------------------------------------------------------


def test_fused_mode_env_parsing_is_strict(monkeypatch):
    for v, want in (("off", "off"), ("0", "off"), ("false", "off"),
                    ("no", "off"), ("on", "on"), ("1", "on"),
                    ("true", "on"), ("yes", "on"), ("auto", "auto")):
        monkeypatch.setenv("PMDFC_FUSED", v)
        assert fused_mode() == want
    monkeypatch.delenv("PMDFC_FUSED")
    assert fused_mode() == "auto"
    assert fused_mode("off") == "off"   # config default flows through
    # a typo'd flag must raise, never silently run the other kernel
    monkeypatch.setenv("PMDFC_FUSED", "fused")
    with pytest.raises(ValueError, match="PMDFC_FUSED"):
        fused_mode()


def test_fused_config_field_validated():
    with pytest.raises(ValueError, match="fused_get"):
        _cfg(fused_get="yes")


def test_unsupported_configs_ride_composed(monkeypatch):
    """The fallback matrix: outside `supports()` the composed program
    serves even under a forced `on` — silently, by design."""
    monkeypatch.setenv("PMDFC_FUSED", "on")
    # unpaged (u64 values) pools: no fused program, even forced
    assert not fused.supports(_cfg(paged=False))
    assert not fused.resolve(_cfg(paged=False))
    assert kv_mod.KV(_cfg(paged=False))._fused_on() is False
    # non-pow2 page geometry: the xor tree-fold digest requires pow2
    assert not fused.supports(_cfg(page_words=48))
    # supported + forced: fused anywhere (interpret mode off-chip)
    assert fused.resolve(_cfg())
    monkeypatch.delenv("PMDFC_FUSED")
    if jax.default_backend() != "tpu":
        # auto off-chip resolves composed: interpret mode is a parity
        # vehicle, never the serving kernel
        assert not fused.resolve(_cfg())


# --- 4/5. recompile signatures + the plane kill switch --------------------


@pytest.fixture()
def fresh_registry(tmp_path):
    reg = tele.configure(TelemetryConfig(ring_capacity=1 << 15,
                                         dump_dir=str(tmp_path)))
    yield reg
    tele.configure()


def _fused_recompiles(reg) -> dict:
    snap = reg.snapshot()["counters"]
    return {k: v for k, v in snap.items()
            if k.startswith("recompile.kv.get_fused")}


def test_fused_cold_rung_bumps_program_and_kernel_once(
        fresh_registry, monkeypatch):
    """A batch outside the warmed pad ladder is exactly two named
    builds — the jitted GET program (signature: w, value width, family,
    tile) and the Pallas kernel behind it — each counted once; the same
    shape again is a known signature and counts nothing."""
    monkeypatch.setenv("PMDFC_FUSED", "on")
    kv, probe = _seeded_kv(_cfg())
    kv.get(probe[:16])                 # warms the w=16 fused rung
    before = _fused_recompiles(fresh_registry)
    kv.get(probe[:33])                 # w=64: OUTSIDE the ladder
    after = _fused_recompiles(fresh_registry)
    bumped = {k: after[k] - before.get(k, 0) for k in after
              if after[k] != before.get(k, 0)}
    assert sorted(bumped.values()) == [1, 1], bumped
    assert "recompile.kv.get_fused.kernel" in bumped
    prog = next(k for k in bumped
                if k != "recompile.kv.get_fused.kernel")
    assert prog.startswith("recompile.kv.get_fused")
    # the rung's ring event carries the (family, tile) signature knobs
    evs = [r for r in fresh_registry.ring if r.get("kind") == "recompile"
           and r["program"] == prog[len("recompile."):]]
    assert any("family=linear" in r["sig"] and "tile=64" in r["sig"]
               for r in evs), evs
    # same shape again: the signature is known, no further counting
    kv.get(probe[:40])                 # pads to w=64 again
    assert _fused_recompiles(fresh_registry) == after


def _plane(cfg):
    from pmdfc_tpu.parallel.plane import make_serving_backend

    return make_serving_backend(cfg, MeshConfig(n_shards=4))


def _verb_transcript(be, seed=11, steps=20):
    """Seeded mixed workload straight against the plane verbs, folded
    into a comparable transcript (the test_mesh conformance idiom)."""
    rng = np.random.default_rng(seed)
    universe = _keys(192, np.random.default_rng(3))
    out = []
    for _ in range(steps):
        op = int(rng.integers(4))
        lo = int(rng.integers(0, 176))
        n = int(rng.integers(1, 16))
        sel = universe[lo:lo + n]
        if op == 0:
            be.put(sel, _pages_of(sel))
            out.append(("put", n))
        elif op in (1, 2):
            pages, found = be.get(sel)
            out.append(("get", found.tolist(), pages[found].tolist()))
        else:
            out.append(("inval", be.invalidate(sel).tolist()))
    st = be.stats()
    out.append(("stats", {k: int(v) for k, v in st.items()
                          if isinstance(v, (int, np.integer))},
                st["shard_report"]["stats"]))  # per-shard attribution too
    return out


@pytest.mark.slow
def test_fused_off_kill_switch_plane_is_conformant(
        fresh_registry, monkeypatch):
    """`PMDFC_FUSED=off` must pin the 4-shard serving plane to the
    composed program: the SAME factory call yields a bit-identical verb
    transcript vs the forced-fused plane, and zero fused programs are
    ever tracked under `off` (the PMDFC_MESH2D=off drill pattern).

    Slow tier per the PR 13/16 budget notes — tier-1 keeps the KV-seam
    kill-switch pin (`test_fused_kv_stats_parity_and_reconcile`)."""
    monkeypatch.setenv("PMDFC_FUSED", "off")
    off = _plane(_cfg(capacity=1 << 10))
    assert off.skv._fused_on() is False
    got_off = _verb_transcript(off)
    snap = fresh_registry.snapshot()["counters"]
    assert not any("get_fused" in k for k in snap), \
        "fused programs tracked under the kill switch"
    monkeypatch.setenv("PMDFC_FUSED", "on")
    on = _plane(_cfg(capacity=1 << 10))
    assert on.skv._fused_on() is True
    got_on = _verb_transcript(on)
    assert got_off == got_on, "kill switch is not conformant"
    snap = fresh_registry.snapshot()["counters"]
    assert "recompile.kv.get_fused.kernel" in snap, \
        "forced-fused plane never built the Pallas kernel"
