// pmdfc_tpu native runtime: request coalescing engine.
//
// Native component parity with the reference server's data-plane machinery:
// - lock-free bounded MPMC queues (capability of server/circular_queue.cpp's
//   FAA+CAS Valois queue, implemented as Vyukov sequence-stamped rings —
//   cache-friendlier and ABA-free without cmpxchg16b);
// - request batching with adaptive timeout flush (the coalescer role of
//   server/rdma_svr.cpp's per-queue poller threads + BATCH_SIZE fused verbs,
//   rdma_svr.h:16-19 — TPU batches are three orders deeper);
// - a page staging arena addressed by page index (the registered-MR staging
//   regions of rdma_svr.cpp:873-886, minus the NIC);
// - per-request completion slots the submitting thread spins/yields on (the
//   client's CQ spin-poll, client/rdpma.c:395-435, turned inward).
//
// The Python/JAX driver is the "device side": it pops coalesced batches,
// runs the fused index program, and completes the requests. C ABI only —
// consumed via ctypes (no pybind11 in this image).
//
// Build: make -C native   -> libpmdfc_runtime.so

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <thread>

namespace {

using u32 = uint32_t;
using u64 = uint64_t;

struct alignas(8) Req {
  u32 op;        // 0=put 1=get 2=del
  u32 khi, klo;
  u32 page_off;  // arena page index (put: source; get: destination)
  u64 req_id;
};

// Vyukov bounded MPMC queue.
class Mpmc {
 public:
  void init(u32 cap) {  // cap must be a power of two
    cap_ = cap;
    mask_ = cap - 1;
    cells_ = static_cast<Cell*>(std::calloc(cap, sizeof(Cell)));
    for (u32 i = 0; i < cap; ++i)
      cells_[i].seq.store(i, std::memory_order_relaxed);
    head_.store(0, std::memory_order_relaxed);
    tail_.store(0, std::memory_order_relaxed);
  }
  void destroy() { std::free(cells_); }

  bool push(const Req& r) {
    u64 pos = tail_.load(std::memory_order_relaxed);
    for (;;) {
      Cell& c = cells_[pos & mask_];
      u64 seq = c.seq.load(std::memory_order_acquire);
      intptr_t dif = (intptr_t)seq - (intptr_t)pos;
      if (dif == 0) {
        if (tail_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed))
          {
            c.req = r;
            c.seq.store(pos + 1, std::memory_order_release);
            return true;
          }
      } else if (dif < 0) {
        return false;  // full
      } else {
        pos = tail_.load(std::memory_order_relaxed);
      }
    }
  }

  bool pop(Req* out) {
    u64 pos = head_.load(std::memory_order_relaxed);
    for (;;) {
      Cell& c = cells_[pos & mask_];
      u64 seq = c.seq.load(std::memory_order_acquire);
      intptr_t dif = (intptr_t)seq - (intptr_t)(pos + 1);
      if (dif == 0) {
        if (head_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed))
          {
            *out = c.req;
            c.seq.store(pos + cap_, std::memory_order_release);
            return true;
          }
      } else if (dif < 0) {
        return false;  // empty
      } else {
        pos = head_.load(std::memory_order_relaxed);
      }
    }
  }

 private:
  struct Cell {
    std::atomic<u64> seq;
    Req req;
  };
  alignas(64) std::atomic<u64> head_{0};
  alignas(64) std::atomic<u64> tail_{0};
  Cell* cells_ = nullptr;
  u32 cap_ = 0, mask_ = 0;
};

// Completion table: req_id-tagged slots; waiters spin then yield.
struct CompSlot {
  std::atomic<u64> req_id{0};   // id whose completion is stored (0 = none)
  std::atomic<int32_t> status{0};
};

struct Engine {
  u32 nq = 0;
  u32 batch = 0;
  u32 timeout_us = 0;
  u32 arena_pages = 0;
  u32 page_bytes = 0;
  Mpmc* queues = nullptr;
  uint8_t* arena = nullptr;   // caller-owned (numpy) — never freed here
  bool owns_arena = false;    // legacy path: allocated by pm_create
  CompSlot* comp = nullptr;
  u64 comp_mask = 0;
  std::atomic<u64> next_id{1};
  std::atomic<u64> submitted{0}, completed{0}, batches{0}, flushes{0};
  u32 rr = 0;  // round-robin cursor (driver thread only)
  // Lifecycle guard: pm_destroy must never free queues/slots under a live
  // call. Every API entry increments `inflight` and bails if `closing`;
  // destroy flips `closing` then drains `inflight` before freeing. The
  // failure-drill tier tears servers down UNDER client load on purpose —
  // without this, a freed-queue write from a racing submit corrupts the
  // process heap and detonates arbitrarily later (observed as segfaults
  // inside XLA long after the engine died).
  std::atomic<u32> inflight{0};
  std::atomic<bool> closing{false};
};

struct Gate {
  Engine* e;
  bool ok;
  explicit Gate(Engine* eng) : e(eng) {
    e->inflight.fetch_add(1, std::memory_order_acq_rel);
    ok = !e->closing.load(std::memory_order_acquire);
  }
  ~Gate() { e->inflight.fetch_sub(1, std::memory_order_release); }
};

inline u64 now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

Engine* pm_create2(u32 nq, u32 qcap, u32 batch, u32 timeout_us,
                   u32 arena_pages, u32 page_bytes, u64 comp_slots);

Engine* pm_create(u32 nq, u32 qcap, u32 batch, u32 timeout_us,
                  u32 arena_pages, u32 page_bytes) {
  return pm_create2(nq, qcap, batch, timeout_us, arena_pages, page_bytes, 0);
}

// comp_slots: completion-table capacity (rounded up to a power of two;
// 0 = legacy sizing). The table is addressed by req_id & mask, so two LIVE
// ids comp_cap apart collide — and "live" spans from id allocation (at
// submit) until the WAITER READS the slot, not until the driver completes
// it. Deep pipelined clients (T threads x V-key verbs x D inflight) keep
// T*V*D ids allocated-but-unread; the legacy qcap/batch-derived bound does
// not see that term, and an overwritten unread slot wedges its waiter
// forever (found by the round-4 deep-client sweep: 8x32768x8 = 2M live ids
// vs a 1M-slot table -> "completed 0/32768 before timeout"). Callers with
// pipelined clients must pass comp_slots >= total outstanding ids.
Engine* pm_create2(u32 nq, u32 qcap, u32 batch, u32 timeout_us,
                   u32 arena_pages, u32 page_bytes, u64 comp_slots) {
  auto* e = new (std::nothrow) Engine();
  if (!e) return nullptr;
  e->nq = nq;
  e->batch = batch;
  e->timeout_us = timeout_us;
  e->arena_pages = arena_pages;
  e->page_bytes = page_bytes;
  e->queues = new Mpmc[nq];
  for (u32 i = 0; i < nq; ++i) e->queues[i].init(qcap);
  // arena is adopted from the caller via pm_set_arena (numpy-owned memory,
  // refcounted by the views that touch it); nothing to allocate here
  e->arena = nullptr;
  e->owns_arena = false;
  // Legacy floor = queued (qcap*nq) + popped-but-uncompleted (≤ batch) with
  // 2x headroom — sufficient only for synchronous (inflight≤1) clients.
  u64 want = (u64)(qcap * nq + batch) * 2;
  if (comp_slots > want) want = comp_slots;
  u64 comp_cap = 1;
  while (comp_cap < want) comp_cap <<= 1;
  e->comp = new (std::nothrow) CompSlot[comp_cap];
  if (!e->comp) { delete[] e->queues; delete e; return nullptr; }
  e->comp_mask = (u64)comp_cap - 1;
  return e;
}

// Stop sign WITHOUT freeing: makes every native spin loop (submit retry,
// waits, pop) bail promptly so the host-side call drain can finish. Call
// this, drain host-side callers, THEN pm_destroy — the Gate inside each
// API is defense-in-depth, not the primary lifetime mechanism (a caller
// could otherwise enter between destroy's drain and its frees).
void pm_close(Engine* e) {
  e->closing.store(true, std::memory_order_release);
}

// EMBEDDER CONTRACT: pm_destroy is only safe once the embedder has
// quiesced its own callers — call pm_close, wait until no thread of yours
// can still be about to enter a pm_* function with this handle, THEN
// pm_destroy. The Gate/inflight drain below is defense-in-depth, not the
// primary lifetime mechanism: a caller that read the handle before
// `closing` was set can still enter between the drain hitting zero and the
// frees (check-then-free). The Python binding enforces this with its own
// host-side call gate (engine.py close()); a non-Python embedder must
// provide the equivalent.
void pm_destroy(Engine* e) {
  // Quiesce: no new calls get past their Gate once `closing` is set; wait
  // for the ones already inside (their loops all poll `closing` and exit
  // promptly) before freeing anything.
  e->closing.store(true, std::memory_order_release);
  while (e->inflight.load(std::memory_order_acquire) != 0)
    std::this_thread::yield();
  for (u32 i = 0; i < e->nq; ++i) e->queues[i].destroy();
  delete[] e->queues;
  delete[] e->comp;
  if (e->owns_arena) std::free(e->arena);
  delete e;
}

// Adopt a caller-owned arena buffer (numpy-allocated): teardown then never
// frees page memory under an in-flight client view — the buffer's lifetime
// is refcounted by the views that touch it.
void pm_set_arena(Engine* e, uint8_t* buf) {
  if (e->owns_arena) std::free(e->arena);
  e->arena = buf;
  e->owns_arena = false;
}

uint8_t* pm_arena(Engine* e) { return e->arena; }

// Client side: enqueue one request; returns req_id, or 0 if the queue stayed
// full for timeout_us (driver gone/stalled — backpressure must not become a
// hang; the reference client's send-queue block relies on the NIC always
// draining, which an in-process driver cannot promise).
u64 pm_submit(Engine* e, u32 q, u32 op, u32 khi, u32 klo, u32 page_off,
              u32 timeout_us) {
  Gate g(e);
  if (!g.ok) return 0;
  u64 id = e->next_id.fetch_add(1, std::memory_order_relaxed);
  Req r{op, khi, klo, page_off, id};
  Mpmc& queue = e->queues[q % e->nq];
  if (!queue.push(r)) {
    u64 deadline = now_us() + timeout_us;
    for (;;) {
      std::this_thread::yield();
      if (e->closing.load(std::memory_order_acquire)) return 0;
      if (queue.push(r)) break;
      if (now_us() >= deadline) return 0;
    }
  }
  e->submitted.fetch_add(1, std::memory_order_relaxed);
  return id;
}

// Driver side: coalesce up to `max` requests across all queues; returns
// early count on timeout with whatever accumulated (adaptive flush).
u32 pm_pop_batch(Engine* e, Req* out, u32 max, u32 timeout_us) {
  Gate g(e);
  if (!g.ok) return 0;
  u32 n = 0;
  u64 deadline = now_us() + timeout_us;
  // Settle cutoff: once a partial batch has seen NO new arrivals for a
  // fraction of the flush budget, every client is almost certainly blocked
  // waiting on THIS batch — dwelling out the rest of the deadline would
  // serialize the convoy (clients wait on driver, driver waits on deadline).
  u32 settle = timeout_us / 8;
  if (settle > 500) settle = 500;
  if (settle < 50) settle = 50;
  u64 empty_since = 0;
  u32 idle_spins = 0;
  while (n < max) {
    bool got = false;
    for (u32 i = 0; i < e->nq && n < max; ++i) {
      if (e->queues[(e->rr + i) % e->nq].pop(&out[n])) {
        ++n;
        got = true;
      }
    }
    e->rr = (e->rr + 1) % e->nq;
    if (got) {
      empty_since = 0;
      // the deadline binds even while requests keep arriving: the FIRST
      // request of the batch must not wait for the cap to fill under a
      // sustained stream. Exception: a non-blocking pop (timeout 0) means
      // "drain what is queued right now" — it is bounded by an empty
      // sweep below, not by the (already-passed) deadline, so the
      // pipelined driver still empties the backlog in one call.
      if (timeout_us > 0 && now_us() >= deadline) {
        if (n < max) e->flushes.fetch_add(1, std::memory_order_relaxed);
        break;
      }
    } else {
      u64 t = now_us();
      if (empty_since == 0) empty_since = t;
      // settle cutoff: a partial batch that has seen no arrivals for a
      // fraction of the budget flushes early — every client is almost
      // certainly blocked on THIS batch (convoy), dwelling is pure loss
      if (t >= deadline || (n > 0 && t - empty_since >= settle)) {
        if (n > 0 && n < max)
          e->flushes.fetch_add(1, std::memory_order_relaxed);
        break;
      }
      if (++idle_spins > 64) {
        std::this_thread::yield();
        idle_spins = 0;
      }
      if (e->closing.load(std::memory_order_acquire)) break;
    }
  }
  if (n) e->batches.fetch_add(1, std::memory_order_relaxed);
  return n;
}

// Driver side: publish completions (status >= 0 ok / hit, < 0 miss or error).
void pm_complete(Engine* e, const u64* req_ids, const int32_t* status,
                 u32 n) {
  Gate g(e);
  if (!g.ok) return;
  for (u32 i = 0; i < n; ++i) {
    CompSlot& s = e->comp[req_ids[i] & e->comp_mask];
    s.status.store(status[i], std::memory_order_relaxed);
    s.req_id.store(req_ids[i], std::memory_order_release);
  }
  e->completed.fetch_add(n, std::memory_order_relaxed);
}

// Client side: enqueue a whole batch under ONE call (the reference ships 4
// pages per verb, client/rdpma.c:307-320; a ctypes call per page would be
// the Python-tax equivalent of one verb per page). Request ids are allocated
// contiguously: returns the count submitted (requests [*base_id, *base_id+
// count) are live). count < n means the queue stayed full past timeout_us
// for the tail — the unsubmitted ids are dead and never complete.
u32 pm_submit_batch(Engine* e, u32 q, u32 op, const u32* khi, const u32* klo,
                    const u32* page_off, u32 n, u32 timeout_us,
                    u64* base_id) {
  Gate g(e);
  if (!g.ok) { *base_id = 0; return 0; }
  u64 base = e->next_id.fetch_add(n, std::memory_order_relaxed);
  *base_id = base;
  Mpmc& queue = e->queues[q % e->nq];
  u64 deadline = 0;  // lazily armed on first full queue
  u32 i = 0;
  while (i < n) {
    Req r{op, khi[i], klo[i], page_off ? page_off[i] : 0, base + i};
    if (queue.push(r)) {
      ++i;
      continue;
    }
    if (deadline == 0) deadline = now_us() + timeout_us;
    std::this_thread::yield();
    if (e->closing.load(std::memory_order_acquire)) break;
    if (now_us() >= deadline) break;
  }
  if (i < n) {
    // Partial submit: try to hand back the unused ids so burned ids cannot
    // erode the comp-table spacing invariant (two live ids must never be
    // comp_cap apart). The CAS only succeeds if no one allocated since;
    // a failed CAS leaves a rare bounded gap, covered by comp_cap's 2x
    // headroom.
    u64 expect = base + n;
    e->next_id.compare_exchange_strong(expect, base + i,
                                       std::memory_order_relaxed);
  }
  e->submitted.fetch_add(i, std::memory_order_relaxed);
  return i;
}

// Client side: wait for n contiguous-id completions, filling status[n].
// Returns the number completed before timeout (n on success); slots not
// completed in time hold INT32_MIN.
u32 pm_wait_many(Engine* e, u64 base_id, u32 n, int32_t* status,
                 u32 timeout_us) {
  Gate g(e);
  if (!g.ok) { for (u32 i = 0; i < n; ++i) status[i] = INT32_MIN; return 0; }
  u64 deadline = now_us() + timeout_us;
  u32 done = 0;
  u32 spins = 0;
  for (u32 i = 0; i < n; ++i) status[i] = INT32_MIN;
  // Scan round-robin so one slow request does not starve observation of the
  // rest (completions land in driver order, not submit order).
  bool progress = true;
  while (done < n) {
    progress = false;
    for (u32 i = 0; i < n; ++i) {
      if (status[i] != INT32_MIN) continue;
      CompSlot& s = e->comp[(base_id + i) & e->comp_mask];
      if (s.req_id.load(std::memory_order_acquire) == base_id + i) {
        status[i] = s.status.load(std::memory_order_relaxed);
        ++done;
        progress = true;
      }
    }
    if (done == n) break;
    if (now_us() >= deadline) break;
    if (e->closing.load(std::memory_order_acquire)) break;
    if (!progress && ++spins > 64) {
      std::this_thread::yield();
      spins = 0;
    }
  }
  return done;
}

// Client side: wait for a request's completion. Returns status, or
// INT32_MIN on timeout.
int32_t pm_wait(Engine* e, u64 req_id, u32 timeout_us) {
  Gate g(e);
  if (!g.ok) return INT32_MIN;
  CompSlot& s = e->comp[req_id & e->comp_mask];
  u64 deadline = now_us() + timeout_us;
  u32 spins = 0;
  for (;;) {
    if (s.req_id.load(std::memory_order_acquire) == req_id)
      return s.status.load(std::memory_order_relaxed);
    if (now_us() >= deadline) return INT32_MIN;
    if (e->closing.load(std::memory_order_acquire)) return INT32_MIN;
    if (++spins > 256) {
      std::this_thread::yield();
      spins = 0;
    }
  }
}

void pm_stats(Engine* e, u64* out4) {
  Gate g(e);
  if (!g.ok) { out4[0] = out4[1] = out4[2] = out4[3] = 0; return; }
  out4[0] = e->submitted.load(std::memory_order_relaxed);
  out4[1] = e->completed.load(std::memory_order_relaxed);
  out4[2] = e->batches.load(std::memory_order_relaxed);
  out4[3] = e->flushes.load(std::memory_order_relaxed);
}

}  // extern "C"
