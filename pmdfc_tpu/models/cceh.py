"""CCEH — cacheline-conscious extendible hashing, TPU-native.

Reference: `server/CCEH_hybrid.{h,cpp}` and the DRAM variant
`server/src/cceh.{h,cpp}`: 16 KB segments probed 4 pairs × 8 cachelines
(32-slot window, `server/CCEH_hybrid.h:14-19`), MSB directory indexing,
segment split (`Segment::Split` `CCEH_hybrid.cpp:30-67`), directory doubling
+ stride updates (`:198-295`), and `Recovery` walking the directory to repair
buddy pointers (`:391-410`). The DRAM CCEH evicts on unsplittable overflow
and returns the victim (`server/src/cceh.h:169`) — the clean-cache contract.

TPU-native redesign (not a translation):
- **Fused-row probe window**: a segment is `W = segment_slots/32` rows of the
  shared `[khi|klo|vhi|vlo]` 128-lane layout (`models/rowops.py`); the hashed
  window IS the reference's 8-cacheline probe region, and a batched GET is
  directory-gather → row-gather → VPU lane compare. Two gathers total.
- **Replicated preallocated directory**: `dir[Smax]` always holds the entry
  for every top-`Gmax`-bit prefix, where `Gmax = log2(initial segments) +
  split_headroom`. A logical directory of depth g < Gmax is stored with each
  entry replicated `2**(Gmax-g)` times, so lookups never depend on the
  current depth and *doubling is a no-op on the array* (a scalar depth bump):
  the reference's stop-the-world directory realloc + stride pointer fix-up
  (`CCEH_hybrid.cpp:198-295`) disappears.
- **In-jit vectorized multi-split**: inserts run a `lax.while_loop` of
  (attempt placement → split every overflowing segment, up to
  `max_splits_per_round` at once). A split gathers the segment's `[W, 4*32]`
  block, moves entries whose next MSB hash bit is 1 to the buddy segment
  (same window, same lane — lanes are preserved, which keeps result slots
  recomputable), and rewrites the directory range with one vector `where`.
  The reference suspends the segment and rehashes pair-by-pair
  (`CCEH_hybrid.cpp:143-233`); here the whole thing is three scatters.
- **Eviction fallback**: when headroom is exhausted (local depth == Gmax) a
  full window evicts an occupant not touched by this batch and reports it,
  so the store keeps absorbing puts — the DRAM CCEH's behavior, and what the
  KV façade needs to propagate bloom deletes.

Mutation is eager (split rehashes entries now), so the reference's
lazy-deletion pattern-mismatch reuse (`CCEH_hybrid.cpp:143-168`) is
unnecessary: a slot is free iff its key is INVALID.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import (
    free_lanes,
    lane_pick,
    match_mask,
    match_rows,
    no_evict_stub,
    nth_lane,
    pick_kv,
    scatter_entry,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

WINDOW_SEED = 0x77AA55EE  # window hash family, independent of directory bits


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CCEHState:
    table: jnp.ndarray   # uint32[R, 4*P] fused rows; R = Smax * W
    ld: jnp.ndarray      # uint32[Smax] local depth per segment
    dirr: jnp.ndarray    # int32[Smax] replicated directory (MSB prefix -> seg)
    gdepth: jnp.ndarray  # uint32[] global depth (stats/recovery)
    nseg: jnp.ndarray    # int32[] allocated segment count
    # static knobs (part of the treedef, not traced)
    k_splits: int = dataclasses.field(metadata=dict(static=True), default=64)
    rounds: int = dataclasses.field(metadata=dict(static=True), default=3)
    # MSB directory indexing (CCEH, `CCEH_hybrid.cpp` uses high bits) vs LSB
    # (classic extendible hashing, `server/src/extendible_hash.h:27-33`).
    # Same machinery; only the prefix/bit arithmetic differs.
    msb: bool = dataclasses.field(metadata=dict(static=True), default=True)


@dataclasses.dataclass(frozen=True)
class _Geom:
    P: int      # probe window lanes per row
    W: int      # rows (windows) per segment
    Gmax: int   # max depth
    Smax: int   # max segments = 2**Gmax
    R: int      # total rows
    K: int      # max splits per round
    rounds: int
    msb: bool


def _geom(state: CCEHState) -> _Geom:
    r, lanes = state.table.shape
    smax = state.ld.shape[0]
    return _Geom(
        P=lanes // 4, W=r // smax, Gmax=smax.bit_length() - 1, Smax=smax,
        R=r, K=state.k_splits, rounds=state.rounds, msb=state.msb,
    )


def _init_geom(config: IndexConfig):
    p = config.probe_window
    w = max(1, config.segment_slots // p)
    s0 = max(1, config.capacity // (w * p))
    if s0 & (s0 - 1):
        s0 = 1 << (s0 - 1).bit_length()
    g0 = s0.bit_length() - 1
    gmax = max(1, g0 + config.split_headroom)
    return p, w, s0, g0, gmax, 1 << gmax


def num_slots(config: IndexConfig) -> int:
    p, w, _, _, _, smax = _init_geom(config)
    return smax * w * p


def init(config: IndexConfig, msb: bool = True) -> CCEHState:
    p, w, s0, g0, gmax, smax = _init_geom(config)
    r = smax * w
    table = jnp.concatenate(
        [
            jnp.full((r, 2 * p), INVALID_WORD, jnp.uint32),
            jnp.zeros((r, 2 * p), jnp.uint32),
        ],
        axis=1,
    )
    ld = jnp.where(jnp.arange(smax) < s0, jnp.uint32(g0), jnp.uint32(0))
    i = jnp.arange(smax, dtype=jnp.int32)
    # prefix i's g0 directory bits (top for MSB, low for LSB) name its segment
    dirr = (i >> (gmax - g0)) if msb else (i & (s0 - 1))
    return CCEHState(
        table=table, ld=ld, dirr=dirr.astype(jnp.int32),
        gdepth=jnp.asarray(g0, jnp.uint32),
        nseg=jnp.asarray(s0, jnp.int32),
        k_splits=min(config.max_splits_per_round, smax),
        rounds=config.split_headroom + 2,
        msb=msb,
    )


def _locate(g: _Geom, dirr: jnp.ndarray, hdir: jnp.ndarray,
            hwin: jnp.ndarray) -> jnp.ndarray:
    if g.msb:
        idx = (hdir >> (32 - g.Gmax)).astype(jnp.int32)
    else:
        idx = (hdir & jnp.uint32(g.Smax - 1)).astype(jnp.int32)
    seg = dirr[idx]
    return seg * g.W + hwin


def _hashes(g: _Geom, keys: jnp.ndarray):
    hdir = hash_u64(keys[..., 0], keys[..., 1])
    hwin = (
        hash_u64(keys[..., 0], keys[..., 1], seed=WINDOW_SEED)
        & jnp.uint32(g.W - 1)
    ).astype(jnp.int32)
    return hdir, hwin


@jax.jit
def get_batch(state: CCEHState, keys: jnp.ndarray) -> GetResult:
    g = _geom(state)
    hdir, hwin = _hashes(g, keys)
    row = _locate(g, state.dirr, hdir, hwin)
    rows = state.table[row]
    eq, lane = match_rows(rows, keys, g.P)
    found = lane >= 0
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * g.P, g.P), lane_pick(rows, eq, 3 * g.P, g.P)],
        axis=-1,
    )
    gslot = jnp.where(found, row * g.P + jnp.maximum(lane, 0), jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: CCEHState, keys: jnp.ndarray):
    """Lean GET (see `linear.get_values`): (values zero-on-miss, found),
    no slot/argmax bookkeeping — the probe gather runs at a fixed rows/s,
    so every non-gather op on this path costs headline throughput."""
    g = _geom(state)
    hdir, hwin = _hashes(g, keys)
    row = _locate(g, state.dirr, hdir, hwin)
    rows = state.table[row]
    eq = match_mask(rows, keys, g.P)
    found = eq.any(axis=1)
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * g.P, g.P), lane_pick(rows, eq, 3 * g.P, g.P)],
        axis=-1,
    )
    return values, found


def _split_round(g: _Geom, table, ld, dirr, gdepth, nseg, want):
    """Split every flagged segment (≤K, capacity permitting) at once.

    `want: bool[Smax]`. Returns updated (table, ld, dirr, gdepth, nseg).
    """
    can = want & (ld < jnp.uint32(g.Gmax))
    srank = jnp.cumsum(can.astype(jnp.int32)) - 1
    avail = jnp.minimum(jnp.int32(g.K), jnp.int32(g.Smax) - nseg)
    doit = can & (srank < avail)
    ndo = doit.sum(dtype=jnp.int32)

    # compact the ≤K splitting segment ids
    seg_ids = jnp.arange(g.Smax, dtype=jnp.int32)
    seg_list = jnp.full((g.K,), -1, jnp.int32).at[
        jnp.where(doit, srank, jnp.int32(g.K))
    ].set(seg_ids, mode="drop")
    ok = seg_list >= 0
    ld_old = ld  # pre-split depths (directory math needs these)
    ld_old_k = ld_old[jnp.maximum(seg_list, 0)]

    # move entries whose next MSB bit is 1 into the buddy segment
    warange = jnp.arange(g.W, dtype=jnp.int32)
    src_rows = jnp.maximum(seg_list, 0)[:, None] * g.W + warange[None, :]
    blocks = table[src_rows]                                  # [K, W, 4P]
    khi, klo = blocks[..., 0 : g.P], blocks[..., g.P : 2 * g.P]
    occupied = ~((khi == jnp.uint32(INVALID_WORD))
                 & (klo == jnp.uint32(INVALID_WORD)))
    hb = hash_u64(khi, klo)
    if g.msb:
        shift_e = jnp.uint32(31) - ld_old_k[:, None, None]
    else:
        shift_e = ld_old_k[:, None, None]
    bit = (hb >> shift_e) & jnp.uint32(1)
    move = occupied & (bit == 1) & ok[:, None, None]

    inv = jnp.uint32(INVALID_WORD)
    move4 = jnp.concatenate([move, move, move, move], axis=-1)
    keymask4 = jnp.concatenate(
        [jnp.ones_like(move), jnp.ones_like(move),
         jnp.zeros_like(move), jnp.zeros_like(move)], axis=-1
    )
    # buddy gets moved entries, INVALID keys elsewhere (values don't matter)
    tgt_blocks = jnp.where(move4, blocks, jnp.where(keymask4, inv, blocks))
    # source keeps non-moved entries, moved keys cleared
    src_after = jnp.where(move4 & keymask4, inv, blocks)

    new_ids = nseg + jnp.arange(g.K, dtype=jnp.int32)          # [K]
    tgt_rows = jnp.where(
        ok[:, None], new_ids[:, None] * g.W + warange[None, :], jnp.int32(g.R)
    )
    table = table.at[tgt_rows].set(tgt_blocks, mode="drop")
    table = table.at[jnp.where(ok[:, None], src_rows, jnp.int32(g.R))].set(
        src_after, mode="drop"
    )

    # depths: split seg and buddy both deepen to ld_old+1
    ld = jnp.where(doit, ld_old + 1, ld_old)
    ld = ld.at[jnp.where(ok, new_ids, jnp.int32(g.Smax))].set(
        ld_old_k + 1, mode="drop"
    )
    gdepth = jnp.maximum(gdepth, jnp.where(doit, ld, 0).max())
    new_of_seg = jnp.zeros((g.Smax,), jnp.int32).at[
        jnp.where(ok, seg_list, jnp.int32(g.Smax))
    ].set(new_ids, mode="drop")

    # directory: prefixes owned by s whose bit at ld_old[s] is 1 -> buddy
    i = jnp.arange(g.Smax, dtype=jnp.int32)
    s_i = dirr[i]
    # clamp: shift is only meaningful where doit (ld_old < Gmax); elsewhere
    # ld_old may equal Gmax and the raw MSB shift would be negative
    if g.msb:
        shift = jnp.maximum(
            jnp.int32(g.Gmax - 1) - ld_old[s_i].astype(jnp.int32), 0
        )
    else:
        shift = ld_old[s_i].astype(jnp.int32)
    bit_i = (i >> shift) & 1
    dirr = jnp.where(doit[s_i] & (bit_i == 1), new_of_seg[s_i], dirr)
    return table, ld, dirr, gdepth, nseg + ndo


@jax.jit
def insert_batch(state: CCEHState, keys: jnp.ndarray, values: jnp.ndarray):
    g = _geom(state)
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    hdir, hwin = _hashes(g, keys)
    vhi, vlo = values[:, 0], values[:, 1]

    def attempt(table, dirr, slots, fresh, pending):
        """Place pending keys into free lanes; returns overflow mask too."""
        row = _locate(g, dirr, hdir, hwin)
        rows = table[row]
        mk = jnp.where(pending[:, None], keys, jnp.uint32(INVALID_WORD))
        eq, lane = match_rows(rows, mk, g.P)
        upd = pending & (lane >= 0)
        r_u = jnp.where(upd, row, jnp.int32(g.R))
        l_u = jnp.maximum(lane, 0)
        table = table.at[r_u, 2 * g.P + l_u].set(vhi, mode="drop")
        table = table.at[r_u, 3 * g.P + l_u].set(vlo, mode="drop")
        slots = jnp.where(upd, row * g.P + l_u, slots)

        new = pending & ~upd
        rank = batch_rank_by_segment(row.astype(jnp.uint32), new)
        free = free_lanes(rows, g.P)
        can = new & (rank < free.sum(axis=1))
        hot = nth_lane(free, rank)
        lane_t = jnp.argmax(hot, axis=1).astype(jnp.int32)
        table = scatter_entry(table, row, lane_t, keys, values, g.P, can)
        slots = jnp.where(can, row * g.P + lane_t, slots)
        fresh = fresh | can
        return table, slots, fresh, new & ~can, row

    def cond(carry):
        table, ld, dirr, gdepth, nseg, slots, fresh, rnd = carry
        return (rnd < g.rounds) & (winner & (slots < 0)).any()

    def body(carry):
        table, ld, dirr, gdepth, nseg, slots, fresh, rnd = carry
        pending = winner & (slots < 0)
        table, slots, fresh, overflow, row = attempt(
            table, dirr, slots, fresh, pending
        )

        # split + relocation only when something actually overflowed: a
        # round whose attempt placed every pending key would otherwise
        # still pay _split_round's fixed K-segment gathers and a full
        # directory relocate for an empty `want` (the common last round).
        def do_split(op):
            table, ld, dirr, gdepth, nseg, slots = op
            seg = row // g.W
            want = jnp.zeros((g.Smax,), bool).at[
                jnp.where(overflow, seg, jnp.int32(g.Smax))
            ].set(True, mode="drop")
            table, ld, dirr, gdepth, nseg = _split_round(
                g, table, ld, dirr, gdepth, nseg, want
            )
            # placed entries may have moved (lane is split-invariant;
            # row is not)
            row2 = _locate(g, dirr, hdir, hwin)
            slots = jnp.where(slots >= 0, row2 * g.P + slots % g.P, slots)
            return table, ld, dirr, gdepth, nseg, slots

        table, ld, dirr, gdepth, nseg, slots = jax.lax.cond(
            overflow.any(), do_split, lambda op: op,
            (table, ld, dirr, gdepth, nseg, slots),
        )
        return table, ld, dirr, gdepth, nseg, slots, fresh, rnd + 1

    slots0 = jnp.full((b,), -1, jnp.int32)
    fresh0 = jnp.zeros((b,), bool)
    table, ld, dirr, gdepth, nseg, slots, fresh, _ = jax.lax.while_loop(
        cond, body,
        (state.table, state.ld, state.dirr, state.gdepth, state.nseg,
         slots0, fresh0, jnp.int32(0)),
    )

    # final pass: fill any space the last split opened, then evict — but
    # only when the loop left keys unplaced. In the common fill batch the
    # while_loop exits with nothing pending, and the whole tail (another
    # attempt gather+rank+scatters, the protection scatter, the eviction
    # gather+rank+extraction) is a no-op not worth its passes.
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)

    def tail_evict(op):
        table, slots, fresh = op
        pending = winner & (slots < 0)
        table, slots, fresh, still, row = attempt(
            table, dirr, slots, fresh, pending
        )
        # eviction fallback — never evict a lane placed/updated in THIS
        # batch
        prot_bits = jnp.zeros((g.R,), jnp.uint32).at[
            jnp.where(slots >= 0, slots // g.P, jnp.int32(g.R))
        ].add(
            jnp.uint32(1)
            << (jnp.maximum(slots, 0) % g.P).astype(jnp.uint32),
            mode="drop",
        )
        rows2 = table[row]
        lanes = jnp.arange(g.P, dtype=jnp.uint32)[None, :]
        prot = ((prot_bits[row][:, None] >> lanes) & 1).astype(bool)
        cand = ~free_lanes(rows2, g.P) & ~prot
        erank = batch_rank_by_segment(row.astype(jnp.uint32), still)
        place = still & (erank < cand.sum(axis=1))
        hot = nth_lane(cand, erank) & place[:, None]
        lane_e = jnp.argmax(hot, axis=1).astype(jnp.int32)
        ek, ev = pick_kv(rows2, hot, g.P)
        evicted_ = jnp.where(place[:, None], ek, inv2)
        evicted_vals_ = jnp.where(place[:, None], ev, inv2)
        table = scatter_entry(table, row, lane_e, keys, values, g.P, place)
        slots = jnp.where(place, row * g.P + lane_e, slots)
        fresh = fresh | place
        dropped_ = still & ~place
        return table, slots, fresh, evicted_, evicted_vals_, dropped_

    def tail_skip(op):
        table, slots, fresh = op
        # no-evict payload single-sourced from rowops (lane_e unused here:
        # cceh's tail computes its own placement lanes in the true branch)
        tb, no_ek, no_ev, no_drop, _ = no_evict_stub(b)(table)
        return tb, slots, fresh, no_ek, no_ev, no_drop

    table, slots, fresh, evicted, evicted_vals, dropped = jax.lax.cond(
        (winner & (slots < 0)).any(), tail_evict, tail_skip,
        (table, slots, fresh),
    )

    new_state = dataclasses.replace(
        state, table=table, ld=ld, dirr=dirr, gdepth=gdepth, nseg=nseg
    )
    res = InsertResult(
        slots=slots, evicted=evicted, dropped=dropped, fresh=fresh,
        evicted_vals=evicted_vals,
    )
    return new_state, res


@jax.jit
def delete_batch(state: CCEHState, keys: jnp.ndarray):
    g = _geom(state)
    hdir, hwin = _hashes(g, keys)
    row = _locate(g, state.dirr, hdir, hwin)
    rows = state.table[row]
    eq, lane = match_rows(rows, keys, g.P)
    hit = lane >= 0
    _, old_vals = pick_kv(rows, eq, g.P)
    old_vals = jnp.where(hit[:, None], old_vals, jnp.uint32(INVALID_WORD))
    r_d = jnp.where(hit, row, jnp.int32(g.R))
    l_d = jnp.maximum(lane, 0)
    inv = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_d, l_d].set(inv, mode="drop")
    table = table.at[r_d, g.P + l_d].set(inv, mode="drop")
    return dataclasses.replace(state, table=table), hit, old_vals


@jax.jit
def set_values(state: CCEHState, slots: jnp.ndarray, values: jnp.ndarray):
    g = _geom(state)
    okr = jnp.where(slots >= 0, slots // g.P, jnp.int32(g.R))
    lane = jnp.maximum(slots, 0) % g.P
    table = state.table.at[okr, 2 * g.P + lane].set(values[:, 0], mode="drop")
    table = table.at[okr, 3 * g.P + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: CCEHState):
    p = state.table.shape[1] // 4
    t = state.table
    keys = jnp.stack(
        [t[:, 0:p].reshape(-1), t[:, p : 2 * p].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * p : 3 * p].reshape(-1), t[:, 3 * p : 4 * p].reshape(-1)],
        axis=-1,
    )
    return keys, vals


@jax.jit
def recovery(state: CCEHState) -> CCEHState:
    """Directory repair after restore (ref `CCEH::Recovery`
    `server/CCEH_hybrid.cpp:391-410`).

    In the replicated representation every segment's 2**(Gmax-ld) directory
    entries must agree; the canonical entry is the block start (the buddy
    walk of the reference collapses to one vectorized re-read).
    """
    g = _geom(state)
    i = jnp.arange(g.Smax, dtype=jnp.int32)
    s = state.dirr[i]
    if g.msb:
        # MSB replication blocks are contiguous; canonical = block start
        block = jnp.int32(1) << (
            jnp.int32(g.Gmax) - state.ld[s].astype(jnp.int32)
        )
        start = i & ~(block - 1)
    else:
        # LSB replication classes are strided (i ≡ canonical mod 2**ld)
        start = i & ((jnp.int32(1) << state.ld[s].astype(jnp.int32)) - 1)
    dirr = state.dirr[start]
    gdepth = state.ld[dirr].max()
    return dataclasses.replace(state, dirr=dirr, gdepth=gdepth)


register_index(
    IndexKind.CCEH,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        recovery=recovery,
        get_values=get_values,
    ),
)
