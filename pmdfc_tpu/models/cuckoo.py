"""Cuckoo hash — two-choice buckets with batched kick rounds.

Reference: `server/src/cuckoo_hash.{h,cpp}` — 2-hash cuckoo with BFS path
search, path validation/execution, and ×2 resize up to `kMaxGrows`
(`cuckoo_hash.h:12-16,94-99`).

TPU-native redesign (not a translation):
- **Bucketized**: each hash picks a 32-lane fused row, so one key has 64
  candidate slots before any displacement — at these association widths the
  displacement path BFS collapses to almost never running, and a batched GET
  is two gathers + lane compares.
- **Batched kicks instead of path search**: unplaced keys displace one
  victim per row per round inside a `lax.while_loop` (≤ `max_cuckoo_kicks`
  rounds); the victim entry (key+value) is carried in the batch lane and
  retried against BOTH its buckets next round. Per-round scatters are
  conflict-free by segment ranking; a protection bitmask guarantees a kick
  never displaces an entry placed by THIS batch (which would corrupt the
  reported slots).
- **Clean-cache instead of resize**: where the reference grows the table, a
  victim that cannot re-home after the kick budget is EVICTED and reported
  (the KV façade then deletes it from the bloom filter); an original key
  that cannot place is dropped. Both are legal outcomes in the clean-cache
  contract the KV layer exposes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    compact_mask,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import (
    free_lanes,
    lane_pick,
    lean_two_window,
    match_rows,
    nth_lane,
    pick_kv,
    place_free_phase,
    scatter_entry,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

ALT_SEED = 0xC0C0C0C0  # second hash family


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CuckooState:
    table: jnp.ndarray  # uint32[C, 4*P] fused rows
    max_kicks: int = dataclasses.field(metadata=dict(static=True), default=8)


def _num_rows(config: IndexConfig) -> int:
    c = max(2, config.capacity // config.cluster_slots)
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    return _num_rows(config) * config.cluster_slots


def init(config: IndexConfig) -> CuckooState:
    c, s = _num_rows(config), config.cluster_slots
    table = jnp.concatenate(
        [
            jnp.full((c, 2 * s), INVALID_WORD, jnp.uint32),
            jnp.zeros((c, 2 * s), jnp.uint32),
        ],
        axis=1,
    )
    return CuckooState(table=table, max_kicks=config.max_cuckoo_kicks)


def _rows_of(c: int, keys: jnp.ndarray):
    r1 = hash_u64(keys[..., 0], keys[..., 1]) & jnp.uint32(c - 1)
    r2 = hash_u64(keys[..., 0], keys[..., 1], seed=ALT_SEED) & jnp.uint32(c - 1)
    return r1.astype(jnp.int32), r2.astype(jnp.int32)


@jax.jit
def get_batch(state: CuckooState, keys: jnp.ndarray) -> GetResult:
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r1, r2 = _rows_of(c, keys)
    rows1, rows2 = state.table[r1], state.table[r2]
    eq1, l1 = match_rows(rows1, keys, s)
    eq2, l2 = match_rows(rows2, keys, s)
    in1 = l1 >= 0
    found = in1 | (l2 >= 0)
    eq = jnp.where(in1[:, None], eq1, eq2)
    rows = jnp.where(in1[:, None], rows1, rows2)
    row = jnp.where(in1, r1, r2)
    lane = jnp.where(in1, l1, l2)
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * s, s), lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    gslot = jnp.where(found, row * s + jnp.maximum(lane, 0), jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: CuckooState, keys: jnp.ndarray):
    """Lean GET. A key lives in exactly ONE of its two windows (insert
    updates in place before any displacement), so the two masked sums add
    disjoint one-hots — no per-window selection pass."""
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r1, r2 = _rows_of(c, keys)
    return lean_two_window(state.table, r1, r2, keys, s)


@jax.jit
def insert_batch(state: CuckooState, keys: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)

    # update-in-place resolves before any displacement
    r1, r2 = _rows_of(c, keys)
    rows1, rows2 = state.table[r1], state.table[r2]
    mk = jnp.where(winner[:, None], keys, jnp.uint32(INVALID_WORD))
    eq1, l1 = match_rows(rows1, mk, s)
    eq2, l2 = match_rows(rows2, mk, s)
    in1 = l1 >= 0
    upd = winner & (in1 | (l2 >= 0))
    u_row = jnp.where(in1, r1, r2)
    u_lane = jnp.maximum(jnp.where(in1, l1, l2), 0)
    table = state.table
    r_u = jnp.where(upd, u_row, jnp.int32(c))
    table = table.at[r_u, 2 * s + u_lane].set(values[:, 0], mode="drop")
    table = table.at[r_u, 3 * s + u_lane].set(values[:, 1], mode="drop")
    upd_slots = jnp.where(upd, u_row * s + u_lane, jnp.int32(-1))
    # protect updated entries from same-batch kicks
    prot0 = jnp.zeros((c,), jnp.uint32).at[r_u].add(
        jnp.uint32(1) << u_lane.astype(jnp.uint32), mode="drop"
    )

    def body(carry):
        (table, prot, ckeys, cvals, active, is_orig, slots, fresh,
         evicted, evicted_vals, rnd) = carry
        w = ckeys.shape[0]
        cr1, cr2 = _rows_of(c, ckeys)
        # phase A: bucket 1 free lanes; phase B: bucket 2 (re-gathered)
        table, prot, pl1, sl1 = place_free_phase(
            table, prot, cr1, ckeys, cvals, active, s
        )
        active = active & ~pl1
        table, prot, pl2, sl2 = place_free_phase(
            table, prot, cr2, ckeys, cvals, active, s
        )
        active = active & ~pl2
        placed = pl1 | pl2
        slot_now = jnp.where(pl1, sl1, sl2)
        slots = jnp.where(placed & is_orig, slot_now, slots)
        fresh = fresh | (placed & is_orig)

        # kick phase: rank-0 key per bucket-2 row displaces one unprotected
        # occupant and carries it forward. In the common fill round the
        # two free phases just drained `active`, so the whole block — a
        # row gather, a segment-rank sort, occupant extraction and
        # scatters — runs under lax.cond and the final (usually only)
        # round pays one predicate instead.
        def do_kick(op):
            table, prot, ckeys, cvals, is_orig, slots, fresh = op
            rows2k = table[cr2]
            lanes = jnp.arange(s, dtype=jnp.uint32)[None, :]
            protected = ((prot[cr2][:, None] >> lanes) & 1).astype(bool)
            cand = ~free_lanes(rows2k, s) & ~protected
            krank = batch_rank_by_segment(cr2.astype(jnp.uint32), active)
            kick = active & (krank == 0) & cand.any(axis=1)
            hot = nth_lane(cand, jnp.zeros((w,), jnp.int32)) & kick[:, None]
            klane = jnp.argmax(hot, axis=1).astype(jnp.int32)
            vk, vv = pick_kv(rows2k, hot, s)
            table = scatter_entry(table, cr2, klane, ckeys, cvals, s, kick)
            bit = jnp.uint32(1) << klane.astype(jnp.uint32)
            prot = prot.at[jnp.where(kick, cr2, jnp.int32(c))].add(
                bit, mode="drop"
            )
            slots = jnp.where(kick & is_orig, cr2 * s + klane, slots)
            fresh = fresh | (kick & is_orig)
            # the victim becomes the carried key at this position
            ckeys = jnp.where(kick[:, None], vk, ckeys)
            cvals = jnp.where(kick[:, None], vv, cvals)
            is_orig = is_orig & ~kick
            return (table, prot, ckeys, cvals, is_orig, slots, fresh)

        (table, prot, ckeys, cvals, is_orig, slots, fresh) = jax.lax.cond(
            active.any(), do_kick, lambda op: op,
            (table, prot, ckeys, cvals, is_orig, slots, fresh),
        )
        # `kick` positions stay active carrying the victim
        return (table, prot, ckeys, cvals, active, is_orig, slots, fresh,
                evicted, evicted_vals, rnd + 1)

    def cond(carry):
        active, rnd = carry[4], carry[10]
        return active.any() & (rnd < state.max_kicks)

    def run_rounds(table, prot, ckeys, cvals, start_mask, slots0, rnd0):
        """Displacement rounds at the width of `ckeys` (full batch or the
        compacted straggler buffer)."""
        w = ckeys.shape[0]
        inv_w = jnp.full((w, 2), INVALID_WORD, jnp.uint32)
        carry = (
            table, prot, ckeys, cvals, start_mask, jnp.ones((w,), bool),
            slots0, jnp.zeros((w,), bool), inv_w, inv_w, rnd0,
        )
        (table, prot, ckeys, cvals, active, is_orig, slots, fresh,
         evicted, evicted_vals, _) = jax.lax.while_loop(cond, body, carry)
        # budget exhausted: carried victims are evicted; originals dropped
        lost_victim = active & ~is_orig
        evicted = jnp.where(lost_victim[:, None], ckeys, evicted)
        evicted_vals = jnp.where(lost_victim[:, None], cvals, evicted_vals)
        dropped = active & is_orig
        return table, slots, fresh, evicted, evicted_vals, dropped

    start = winner & ~upd

    # Round 1 at full width: one free-place pass per bucket. This drains
    # all but the multi-collision stragglers of a fill batch (the
    # clean-cache common case), so the kick loop below never needs to run
    # full-batch-wide sorts/gathers for a ~0.1% active set (VERDICT r4:
    # cuckoo insert was 0.34x baseline on-chip because every round paid
    # full batch width).
    cr1, cr2 = _rows_of(c, keys)
    table, prot, pl1, sl1 = place_free_phase(
        table, prot0, cr1, keys, values, start, s
    )
    act = start & ~pl1
    table, prot, pl2, sl2 = place_free_phase(
        table, prot, cr2, keys, values, act, s
    )
    act = act & ~pl2
    placed1 = (pl1 | pl2) & start
    slots = jnp.where(placed1, jnp.where(pl1, sl1, sl2), upd_slots)
    fresh1 = placed1

    # Compact survivors to a narrow buffer; displacement rounds run there.
    W = min(b, max(1024, b // 8))
    idx, in_w, safe, overflow = compact_mask(act, W)

    def narrow(op):
        table, prot = op
        ckeys_w = jnp.where(in_w[:, None], keys[safe], jnp.uint32(INVALID_WORD))
        cvals_w = jnp.where(in_w[:, None], values[safe], jnp.uint32(0))
        # rnd0=1: the hoisted full-width free-place pass above already
        # consumed one placement round, so the while_loop gets max_kicks-1
        # more — max_kicks keeps its documented total-budget meaning
        table, slots_w, fresh_w, ev_w, evv_w, drop_w = run_rounds(
            table, prot, ckeys_w, cvals_w, in_w,
            jnp.full((W,), -1, jnp.int32), jnp.int32(1),
        )
        # scatter narrow results back to batch positions (idx==b drops)
        s_pos = jnp.where(fresh_w, idx, jnp.int32(b))
        slots_b = jnp.full((b,), -1, jnp.int32).at[s_pos].set(
            slots_w, mode="drop")
        fresh_b = jnp.zeros((b,), bool).at[s_pos].set(True, mode="drop")
        e_pos = jnp.where(
            (ev_w[:, 0] != jnp.uint32(INVALID_WORD))
            | (ev_w[:, 1] != jnp.uint32(INVALID_WORD)), idx, jnp.int32(b))
        evicted = inv2.at[e_pos].set(ev_w, mode="drop")
        evicted_vals = inv2.at[e_pos].set(evv_w, mode="drop")
        d_pos = jnp.where(drop_w, idx, jnp.int32(b))
        dropped = jnp.zeros((b,), bool).at[d_pos].set(True, mode="drop")
        return table, slots_b, fresh_b, evicted, evicted_vals, dropped

    def full(op):
        # overflow (> W stragglers, extreme-fill batches): the narrow
        # buffer cannot hold the active set — run the rounds at full
        # width on the ROUND-1 survivors, exactly the old semantics.
        table, prot = op
        return run_rounds(
            table, prot, keys, values, act,
            jnp.full((b,), -1, jnp.int32), jnp.int32(1),  # see narrow()
        )

    table, slots2, fresh2, evicted, evicted_vals, dropped = (
        jax.lax.cond(overflow.any(), full, narrow, (table, prot))
        if W < b
        else full((table, prot))
    )
    slots = jnp.where(fresh2, slots2, slots)
    fresh = fresh1 | fresh2

    res = InsertResult(
        slots=slots, evicted=evicted, dropped=dropped, fresh=fresh,
        evicted_vals=evicted_vals,
    )
    return dataclasses.replace(state, table=table), res


@jax.jit
def delete_batch(state: CuckooState, keys: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r1, r2 = _rows_of(c, keys)
    rows1, rows2 = state.table[r1], state.table[r2]
    eq1, l1 = match_rows(rows1, keys, s)
    eq2, l2 = match_rows(rows2, keys, s)
    in1 = l1 >= 0
    hit = in1 | (l2 >= 0)
    eq = jnp.where(in1[:, None], eq1, eq2)
    rows = jnp.where(in1[:, None], rows1, rows2)
    _, old_vals = pick_kv(rows, eq, s)
    old_vals = jnp.where(hit[:, None], old_vals, jnp.uint32(INVALID_WORD))
    row = jnp.where(in1, r1, r2)
    lane = jnp.maximum(jnp.where(in1, l1, l2), 0)
    r_d = jnp.where(hit, row, jnp.int32(c))
    inv = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_d, lane].set(inv, mode="drop")
    table = table.at[r_d, s + lane].set(inv, mode="drop")
    return dataclasses.replace(state, table=table), hit, old_vals


@jax.jit
def set_values(state: CuckooState, slots: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r = jnp.where(slots >= 0, slots // s, jnp.int32(c))
    lane = jnp.maximum(slots, 0) % s
    table = state.table.at[r, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[r, 3 * s + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: CuckooState):
    s = state.table.shape[1] // 4
    t = state.table
    keys = jnp.stack(
        [t[:, 0:s].reshape(-1), t[:, s : 2 * s].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * s : 3 * s].reshape(-1), t[:, 3 * s : 4 * s].reshape(-1)],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.CUCKOO,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
        rows_per_get=2,  # two candidate buckets per probe
    ),
)
