"""Static hash — single fixed table, no splits, no eviction.

Reference: `server/src/static_hash.{h,cpp}` — one fixed `Pair*` array behind a
global semaphore lock (`static_hash.h:14-82`); inserts into a full region
fail. The TPU-native form is the shared fused-row layout probed at a single
hashed 32-lane window; a full window DROPS the insert (reported, legal under
clean-cache) rather than evicting — the distinguishing behavior vs. the
linear-probing index's FIFO eviction.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import (
    lane_pick,
    match_mask,
    match_rows,
    pick_kv,
    place_free_phase,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StaticState:
    table: jnp.ndarray  # uint32[C, 4*S] fused rows


def _num_rows(config: IndexConfig) -> int:
    c = max(1, config.capacity // config.cluster_slots)
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    return _num_rows(config) * config.cluster_slots


def init(config: IndexConfig) -> StaticState:
    c, s = _num_rows(config), config.cluster_slots
    table = jnp.concatenate(
        [
            jnp.full((c, 2 * s), INVALID_WORD, jnp.uint32),
            jnp.zeros((c, 2 * s), jnp.uint32),
        ],
        axis=1,
    )
    return StaticState(table=table)


def _row_of(state: StaticState, keys: jnp.ndarray) -> jnp.ndarray:
    c = state.table.shape[0]
    h = hash_u64(keys[..., 0], keys[..., 1])
    return (h & jnp.uint32(c - 1)).astype(jnp.int32)


@jax.jit
def get_batch(state: StaticState, keys: jnp.ndarray) -> GetResult:
    s = state.table.shape[1] // 4
    row = _row_of(state, keys)
    rows = state.table[row]
    eq, lane = match_rows(rows, keys, s)
    found = lane >= 0
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * s, s), lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    gslot = jnp.where(found, row * s + jnp.maximum(lane, 0), jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: StaticState, keys: jnp.ndarray):
    """Lean GET: (values zero-on-miss, found) — no slot math (the
    `linear.get_values` contract)."""
    s = state.table.shape[1] // 4
    rows = state.table[_row_of(state, keys)]
    eq = match_mask(rows, keys, s)
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * s, s), lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    return values, eq.any(axis=1)


@jax.jit
def insert_batch(state: StaticState, keys: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    row = _row_of(state, keys)
    rows = state.table[row]
    mk = jnp.where(winner[:, None], keys, jnp.uint32(INVALID_WORD))
    eq, lane = match_rows(rows, mk, s)
    upd = winner & (lane >= 0)
    table = state.table
    r_u = jnp.where(upd, row, jnp.int32(c))
    l_u = jnp.maximum(lane, 0)
    table = table.at[r_u, 2 * s + l_u].set(values[:, 0], mode="drop")
    table = table.at[r_u, 3 * s + l_u].set(values[:, 1], mode="drop")

    new = winner & (lane < 0)
    prot = jnp.zeros((c,), jnp.uint32)
    table, _, can, free_slots = place_free_phase(
        table, prot, row, keys, values, new, s
    )
    dropped = new & ~can

    slots = jnp.where(
        upd, row * s + l_u, jnp.where(can, free_slots, jnp.int32(-1))
    )
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)
    res = InsertResult(
        slots=slots, evicted=inv2, dropped=dropped, fresh=can,
        evicted_vals=inv2,
    )
    return StaticState(table=table), res


@jax.jit
def delete_batch(state: StaticState, keys: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    row = _row_of(state, keys)
    rows = state.table[row]
    eq, lane = match_rows(rows, keys, s)
    hit = lane >= 0
    _, old_vals = pick_kv(rows, eq, s)
    old_vals = jnp.where(hit[:, None], old_vals, jnp.uint32(INVALID_WORD))
    r_d = jnp.where(hit, row, jnp.int32(c))
    l_d = jnp.maximum(lane, 0)
    inv = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_d, l_d].set(inv, mode="drop")
    table = table.at[r_d, s + l_d].set(inv, mode="drop")
    return StaticState(table=table), hit, old_vals


@jax.jit
def set_values(state: StaticState, slots: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r = jnp.where(slots >= 0, slots // s, jnp.int32(c))
    lane = jnp.maximum(slots, 0) % s
    table = state.table.at[r, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[r, 3 * s + lane].set(values[:, 1], mode="drop")
    return StaticState(table=table)


def scan(state: StaticState):
    s = state.table.shape[1] // 4
    t = state.table
    keys = jnp.stack(
        [t[:, 0:s].reshape(-1), t[:, s : 2 * s].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * s : 3 * s].reshape(-1), t[:, 3 * s : 4 * s].reshape(-1)],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.STATIC,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
    ),
)
