"""Level hashing — two-level buckets with 4 candidate positions per key.

Reference: `server/src/Level_hashing.{h,cpp}` — top level of N buckets plus a
bottom level of N/2, two hash functions, `ASSOC_NUM 3` slots per bucket with
token occupancy bytes, bottom-to-top movement and in-place resize
(`Level_hashing.h:9-46,60-64`).

TPU-native redesign:
- Buckets are 32-lane fused rows (association 32, not 3 — lane compares are
  free on the VPU, so the token-byte bookkeeping disappears).
- A key's four candidates are top[h1], top[h2], bottom[h1>>1], bottom[h2>>1]
  (each bottom bucket backs two top buckets, the level-hashing shape).
  Insert runs four sequential rank-deconflicted free-lane phases with
  re-gathers; GET is four gathers + lane compares.
- Clean-cache instead of in-place resize: when all four buckets are full the
  insert evicts an unprotected occupant of bottom[h1>>1] and reports it —
  bottom entries are the demoted/cold class in level hashing, so the bottom
  level is the eviction pool.
- Global slot ids place the bottom table after the top (top rows first).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import (
    free_lanes,
    lane_pick,
    lean_miss_tail,
    lean_two_window,
    match_mask,
    match_rows,
    no_evict_stub,
    nth_lane,
    pick_kv,
    place_free_phase,
    scatter_entry,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

ALT_SEED = 0x1E7E11E7


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LevelState:
    # one table: rows [0, Ct) are the top level, [Ct, Ct + Ct//2) the bottom
    table: jnp.ndarray  # uint32[Ct + Ct//2, 4*S]
    top_rows: int = dataclasses.field(metadata=dict(static=True), default=2)


def _top_rows(config: IndexConfig) -> int:
    # capacity = (Ct + Ct/2) * S  =>  Ct = ceil(2/3 * capacity / S), pow2 >= 2
    c = max(2, (2 * config.capacity) // (3 * config.cluster_slots))
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    ct = _top_rows(config)
    return (ct + ct // 2) * config.cluster_slots


def init(config: IndexConfig) -> LevelState:
    ct, s = _top_rows(config), config.cluster_slots
    n = ct + ct // 2
    table = jnp.concatenate(
        [
            jnp.full((n, 2 * s), INVALID_WORD, jnp.uint32),
            jnp.zeros((n, 2 * s), jnp.uint32),
        ],
        axis=1,
    )
    return LevelState(table=table, top_rows=ct)


def _candidates(state: LevelState, keys: jnp.ndarray):
    """The four candidate rows (global row ids) in probe order."""
    ct = state.top_rows
    h1 = hash_u64(keys[..., 0], keys[..., 1]) & jnp.uint32(ct - 1)
    h2 = hash_u64(keys[..., 0], keys[..., 1], seed=ALT_SEED) & jnp.uint32(
        ct - 1
    )
    t1 = h1.astype(jnp.int32)
    t2 = h2.astype(jnp.int32)
    b1 = ct + (t1 >> 1)
    b2 = ct + (t2 >> 1)
    return t1, t2, b1, b2


def _match4(state: LevelState, keys: jnp.ndarray):
    """Probe all four candidates; first hit wins. Returns
    (row, lane, hit, rows_at_hit, eq_at_hit)."""
    s = state.table.shape[1] // 4
    cands = _candidates(state, keys)
    row = jnp.full(keys.shape[:1], -1, jnp.int32)
    lane = jnp.full(keys.shape[:1], -1, jnp.int32)
    hit = jnp.zeros(keys.shape[:1], bool)
    rows_sel = jnp.zeros((keys.shape[0], 4 * s), jnp.uint32)
    eq_sel = jnp.zeros((keys.shape[0], s), bool)
    for r in cands:
        rows = state.table[r]
        eq, ln = match_rows(rows, keys, s)
        here = ~hit & (ln >= 0)
        row = jnp.where(here, r, row)
        lane = jnp.where(here, ln, lane)
        rows_sel = jnp.where(here[:, None], rows, rows_sel)
        eq_sel = jnp.where(here[:, None], eq, eq_sel)
        hit = hit | here
    return row, lane, hit, rows_sel, eq_sel


@jax.jit
def get_batch(state: LevelState, keys: jnp.ndarray) -> GetResult:
    s = state.table.shape[1] // 4
    row, lane, found, rows, eq = _match4(state, keys)
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * s, s), lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    gslot = jnp.where(found, row * s + jnp.maximum(lane, 0), jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: LevelState, keys: jnp.ndarray):
    """Lean GET: the two TOP windows first (insert places top-tier-first,
    so at clean-cache fills nearly every key resolves there — round-4
    on-chip level GET ran at the 4-row gather wall, 11.2 Mops/s, with two
    of the four gathers spent on the rarely-populated bottom tier), then
    ONLY the top misses probe the bottom windows at a compacted narrow
    width, with a full-width `lax.cond` fallback so deep-bottom
    populations and absent-key storms stay exact.

    Candidate windows can COLLIDE (two hash functions landing on one
    row), so later windows are masked once a key has been found — a raw
    sum would double the value when the same window matches twice."""
    s = state.table.shape[1] // 4
    t1, t2, _, _ = _candidates(state, keys)
    values, found = lean_two_window(state.table, t1, t2, keys, s)
    missed = ~found & ~is_invalid(keys)

    def probe_bottom(ks):
        _, _, nb1, nb2 = _candidates(state, ks)
        return lean_two_window(state.table, nb1, nb2, ks, s)

    return lean_miss_tail(keys, missed, values, found, probe_bottom)


@jax.jit
def insert_batch(state: LevelState, keys: jnp.ndarray, values: jnp.ndarray):
    n = state.table.shape[0]
    s = state.table.shape[1] // 4
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)

    # update in place
    mk = jnp.where(winner[:, None], keys, jnp.uint32(INVALID_WORD))
    u_row, u_lane_raw, u_hit, _, _ = _match4(state, mk)
    upd = winner & u_hit
    u_lane = jnp.maximum(u_lane_raw, 0)
    table = state.table
    r_u = jnp.where(upd, u_row, jnp.int32(n))
    table = table.at[r_u, 2 * s + u_lane].set(values[:, 0], mode="drop")
    table = table.at[r_u, 3 * s + u_lane].set(values[:, 1], mode="drop")
    prot = jnp.zeros((n,), jnp.uint32).at[r_u].add(
        jnp.uint32(1) << u_lane.astype(jnp.uint32), mode="drop"
    )

    # four free-lane phases in probe order
    active = winner & ~upd
    slots = jnp.where(upd, u_row * s + u_lane, jnp.int32(-1))
    fresh = jnp.zeros((b,), bool)
    for r in _candidates(state, keys):
        table, prot, placed, sl = place_free_phase(
            table, prot, r, keys, values, active, s
        )
        slots = jnp.where(placed, sl, slots)
        fresh = fresh | placed
        active = active & ~placed

    # eviction in bottom[h1>>1]: displace an unprotected occupant. Only
    # keys that found no free lane in all FOUR windows reach here, so the
    # block's gather + rank + extraction runs under lax.cond — a batch
    # whose keys all placed free (fill phase below capacity) pays one
    # predicate (same skip discipline as hotring's overflow block and
    # the façade's eviction-free bloom-delete).
    t1, _, b1, _ = _candidates(state, keys)

    def with_evict(tb):
        rows_b = tb[b1]
        lanes = jnp.arange(s, dtype=jnp.uint32)[None, :]
        protected = ((prot[b1][:, None] >> lanes) & 1).astype(bool)
        cand = ~free_lanes(rows_b, s) & ~protected
        erank = batch_rank_by_segment(b1.astype(jnp.uint32), active)
        place_ = active & (erank < cand.sum(axis=1))
        hot = nth_lane(cand, erank) & place_[:, None]
        lane_e_ = jnp.argmax(hot, axis=1).astype(jnp.int32)
        ek, ev = pick_kv(rows_b, hot, s)
        tb = scatter_entry(tb, b1, lane_e_, keys, values, s, place_)
        return (tb, jnp.where(place_[:, None], ek, inv2),
                jnp.where(place_[:, None], ev, inv2), place_, lane_e_)

    table, evicted, evicted_vals, place, lane_e = jax.lax.cond(
        active.any(), with_evict, no_evict_stub(b), table
    )
    slots = jnp.where(place, b1 * s + lane_e, slots)
    fresh = fresh | place
    dropped = active & ~place

    res = InsertResult(
        slots=slots, evicted=evicted, dropped=dropped, fresh=fresh,
        evicted_vals=evicted_vals,
    )
    return dataclasses.replace(state, table=table), res


@jax.jit
def delete_batch(state: LevelState, keys: jnp.ndarray):
    n = state.table.shape[0]
    s = state.table.shape[1] // 4
    row, lane_raw, hit, rows, eq = _match4(state, keys)
    lane = jnp.maximum(lane_raw, 0)
    _, old_vals = pick_kv(rows, eq, s)
    old_vals = jnp.where(hit[:, None], old_vals, jnp.uint32(INVALID_WORD))
    r_d = jnp.where(hit, row, jnp.int32(n))
    inv = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_d, lane].set(inv, mode="drop")
    table = table.at[r_d, s + lane].set(inv, mode="drop")
    return dataclasses.replace(state, table=table), hit, old_vals


@jax.jit
def set_values(state: LevelState, slots: jnp.ndarray, values: jnp.ndarray):
    n = state.table.shape[0]
    s = state.table.shape[1] // 4
    r = jnp.where(slots >= 0, slots // s, jnp.int32(n))
    lane = jnp.maximum(slots, 0) % s
    table = state.table.at[r, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[r, 3 * s + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: LevelState):
    s = state.table.shape[1] // 4
    t = state.table
    keys = jnp.stack(
        [t[:, 0:s].reshape(-1), t[:, s : 2 * s].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * s : 3 * s].reshape(-1), t[:, 3 * s : 4 * s].reshape(-1)],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.LEVEL,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
        rows_per_get=2,  # top windows; bottom tier only on miss
        # (narrow compacted tail — the 2-hashes-x-2-tiers probe
        # set is unchanged, only the common-case traffic is)
    ),
)
