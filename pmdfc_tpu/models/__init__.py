"""Index structures ("model families") — TPU struct-of-arrays hash indexes.

Each module provides a registered-pytree state dataclass plus pure, jittable,
fixed-shape batched ops:

    init(config)                                  -> state
    get_batch(state, keys[B,2])                   -> GetResult
    insert_batch(state, keys[B,2], values[B,2])   -> (state, InsertResult)
    delete_batch(state, keys[B,2])                -> (state, deleted[B],
                                                      old_vals[B,2])
    set_values(state, slots[B], values[B,2])      -> state

mirroring the reference's `IHash` interface (`server/IHash.h:10-24`): Insert
returns evicted keys (clean-cache eviction), Get may legally miss.
"""

from pmdfc_tpu.models.base import GetResult, InsertResult, get_index_ops  # noqa: F401
