"""Path hashing — binary-tree fallback levels packed into fused rows.

Reference: `server/src/path_hashing.{hpp,cpp}` — a binary tree of cells:
level 0 has N single-slot cells, each lower level halves, and a key that
collides at level i falls back to its parent cell at level i+1; two seeds
give two independent fallback paths (`path_hashing.hpp:10-17,41-57`).

TPU-native v2 (round 5). The position at level i+1 is exactly the level-i
position halved (`p_{i+1} = p_i >> 1` — the reference's per-level hash
shift), so a key's fallback chain IS the ancestor chain of its level-0
cell. v1 stored levels as separate single-slot arrays, making a probe
16 gathers of 8-byte cells — the sub-128 B-row regime where the measured
gather wall collapses (PERF.md: 25-44 Mrows/s vs 79 for >=256 B rows);
on-chip GET ran at 6.4 Mops/s = 1.3x baseline. v2 packs each depth-4
subtree into ONE 256 B fused row:

- bank 0 rows hold levels 0-3: row r = L0 cells [8r, 8r+8) in lanes 0-7,
  their L1 parents in lanes 8-11, L2 in 12-13, L3 in lane 14 (lane 15 is
  permanently empty pow2 padding).
- bank 1 rows hold levels 4-7 of the same geometry over the L4 positions
  (`p4 = p0 >> 4`).

A probe path therefore touches exactly TWO rows per seed (bank 0 + bank
1), and the common-case GET touches two rows TOTAL: keys living in
levels 0-3 (everything, at clean-cache fills) resolve from the bank-0
rows of both seeds; only bank-0 misses pay the bank-1 gather, at a
compacted narrow width (full-width fallback under `lax.cond` keeps
absent-key probes exact).

Inserts claim cells in reference probe order (level-major, seed A before
B) with per-cell batch ranking; the two L0 rounds run at full batch
width, then survivors compact to b/4 (the L1 rounds) and b/16 (the
rest) — the VERDICT-r4 fix: straggler rounds must not pay full-batch
sorts. Exhausting both paths DROPS the insert (the reference fails it;
clean-cache reports it); a compaction overflow beyond the narrow-buffer
safety margin is likewise a reported drop, and the first compaction
falls back to full width under `lax.cond` so high-fill batches keep the
exact claim semantics.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    compact_mask,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import lean_miss_tail
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

SEED_A = 0x0A7B57ED
SEED_B = 0xB17C0DE5
LEVELS = 8
ROW = 16  # lanes per fused row (CELLS cells + 1 pad)
CELLS = 15  # addressable cells per row — slot ids are dense
            # base-15 (row*CELLS+lane), so num_slots (and the
            # paged pool it sizes) carries no pad-lane waste


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PathState:
    table: jnp.ndarray  # uint32[R, 4*ROW]: k0 | k1 | v0 | v1 lane blocks
    top: int = dataclasses.field(metadata=dict(static=True), default=128)


def _top_cells(config: IndexConfig) -> int:
    # sum_{i<L} top/2^i ~= 2*top  =>  top ~= capacity/2; floor keeps a
    # full depth-8 tree (and bank 1 rows) well-defined.
    c = max(1 << (LEVELS - 1), config.capacity // 2)
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def _bank_rows(top: int) -> tuple[int, int]:
    return top >> 3, max(1, top >> 7)


def num_slots(config: IndexConfig) -> int:
    r0, r1 = _bank_rows(_top_cells(config))
    return (r0 + r1) * CELLS


def init(config: IndexConfig) -> PathState:
    top = _top_cells(config)
    r0, r1 = _bank_rows(top)
    n = r0 + r1
    table = jnp.concatenate(
        [
            jnp.full((n, 2 * ROW), INVALID_WORD, jnp.uint32),
            jnp.zeros((n, 2 * ROW), jnp.uint32),
        ],
        axis=1,
    )
    return PathState(table=table, top=top)


def _locate(p: jnp.ndarray, base_row: int):
    """Fused-row coordinates of the 4-level ancestor chain rooted at
    position `p` of the bank's top level: (row, [lane_L0..lane_L3])."""
    row = (p >> 3) + base_row
    l0 = p & 7
    l1 = 8 + ((p >> 1) & 3)
    l2 = 12 + ((p >> 2) & 1)
    l3 = jnp.full_like(p, 14)
    return row, (l0, l1, l2, l3)


def _paths(top: int, keys: jnp.ndarray):
    """Per-seed probe geometry: ((row_b0, lanes4), (row_b1, lanes4)) x 2.

    Levels 0-3 live in the bank-0 row of p0; levels 4-7 in the bank-1 row
    of p4 = p0 >> 4 (the ancestor-chain identity above)."""
    r0, _ = _bank_rows(top)
    out = []
    for seed in (SEED_A, SEED_B):
        h = hash_u64(keys[..., 0], keys[..., 1], seed=seed)
        p0 = (h & jnp.uint32(top - 1)).astype(jnp.int32)
        out.append((_locate(p0, 0), _locate(p0 >> 4, r0)))
    return out


def _lane_mask(lanes) -> jnp.ndarray:
    """bool[B, ROW] one-hot union of the 4 chain lanes."""
    ar = jnp.arange(ROW, dtype=jnp.int32)[None, :]
    m = ar == lanes[0][:, None]
    for l in lanes[1:]:
        m = m | (ar == l[:, None])
    return m


def _row_eq(rowdata: jnp.ndarray, keys: jnp.ndarray, lanes) -> jnp.ndarray:
    """bool[B, ROW]: key match within the chain lanes of a gathered row."""
    return (
        (rowdata[:, 0:ROW] == keys[:, None, 0])
        & (rowdata[:, ROW : 2 * ROW] == keys[:, None, 1])
        & _lane_mask(lanes)
        & ~is_invalid(keys)[:, None]
    )


def _masked_vals(rowdata: jnp.ndarray, eq: jnp.ndarray):
    """One-hot masked value extraction (keys are unique in the table)."""
    m = eq.astype(jnp.uint32)
    v0 = (rowdata[:, 2 * ROW : 3 * ROW] * m).sum(axis=1)
    v1 = (rowdata[:, 3 * ROW : 4 * ROW] * m).sum(axis=1)
    return v0, v1


@jax.jit
def get_batch(state: PathState, keys: jnp.ndarray) -> GetResult:
    """Full GET (values + found + flat slot ids): all 4 rows gathered."""
    b = keys.shape[0]
    (A0, A1), (B0, B1) = _paths(state.top, keys)
    found = jnp.zeros((b,), bool)
    v0 = jnp.zeros((b,), jnp.uint32)
    v1 = jnp.zeros((b,), jnp.uint32)
    slot = jnp.full((b,), -1, jnp.int32)
    for row, lanes in (A0, B0, A1, B1):
        rd = state.table[row]
        eq = _row_eq(rd, keys, lanes)
        hit = eq.any(axis=1)
        w0, w1 = _masked_vals(rd, eq)
        v0, v1 = v0 | w0, v1 | w1  # disjoint one-hots across rows
        lane = jnp.argmax(eq, axis=1).astype(jnp.int32)
        slot = jnp.where(hit, row * CELLS + lane, slot)
        found = found | hit
    values = jnp.where(
        found[:, None], jnp.stack([v0, v1], axis=-1), jnp.uint32(0)
    )
    return GetResult(values=values, found=found, slots=slot)


@jax.jit
def get_values(state: PathState, keys: jnp.ndarray):
    """Lean GET: bank-0 rows of both seeds (2 gathers), then ONLY the
    bank-0 misses probe bank 1 — compacted narrow, with a full-width
    `lax.cond` fallback so overflowing miss sets (absent-key storms)
    stay exact."""
    b = keys.shape[0]
    (A0, A1), (B0, B1) = _paths(state.top, keys)
    rdA = state.table[A0[0]]
    rdB = state.table[B0[0]]
    eqA = _row_eq(rdA, keys, A0[1])
    eqB = _row_eq(rdB, keys, B0[1])
    a0, a1 = _masked_vals(rdA, eqA)
    b0, b1 = _masked_vals(rdB, eqB)
    v0, v1 = a0 | b0, a1 | b1
    found = eqA.any(axis=1) | eqB.any(axis=1)
    base = jnp.where(
        found[:, None], jnp.stack([v0, v1], axis=-1), jnp.uint32(0)
    )
    missed = ~found & ~is_invalid(keys)

    def probe_bank1(ks):
        (_, nA1), (_, nB1) = _paths(state.top, ks)
        f = jnp.zeros((ks.shape[0],), bool)
        w0 = jnp.zeros((ks.shape[0],), jnp.uint32)
        w1 = jnp.zeros((ks.shape[0],), jnp.uint32)
        for row, lanes in (nA1, nB1):
            rd = state.table[row]
            eq = _row_eq(rd, ks, lanes)
            u0, u1 = _masked_vals(rd, eq)
            w0, w1 = w0 | u0, w1 | u1
            f = f | eq.any(axis=1)
        return jnp.stack([w0, w1], axis=-1), f

    return lean_miss_tail(keys, missed, base, found, probe_bank1)


def _cand(top: int, keys: jnp.ndarray):
    """The 16 candidate (row, lane) pairs in reference probe order:
    level-major, seed A before seed B (`path_hashing.cpp` probe loop)."""
    (A0, A1), (B0, B1) = _paths(top, keys)
    cands = []
    for lvl in range(4):
        cands.append((A0[0], A0[1][lvl]))
        cands.append((B0[0], B0[1][lvl]))
    for lvl in range(4):
        cands.append((A1[0], A1[1][lvl]))
        cands.append((B1[0], B1[1][lvl]))
    return cands


def _claim_rounds(top, table, keys, values, active, slots, j0, j1):
    """Claim rounds [j0, j1) at the width of `keys`. Rank-0 claimant per
    free cell wins; losers fall to the next candidate. Live-table
    occupancy check makes same-batch claims visible without a separate
    protection plane."""
    n = table.shape[0]
    cands = _cand(top, keys)
    for j in range(j0, j1):
        row, lane = cands[j]
        cell = row * CELLS + lane
        occ_k0 = table[row, lane]
        occ_k1 = table[row, ROW + lane]
        free = (occ_k0 == jnp.uint32(INVALID_WORD)) & (
            occ_k1 == jnp.uint32(INVALID_WORD)
        )
        rank = batch_rank_by_segment(cell.astype(jnp.uint32), active)
        can = active & free & (rank == 0)
        r_t = jnp.where(can, row, jnp.int32(n))
        table = table.at[r_t, lane].set(keys[:, 0], mode="drop")
        table = table.at[r_t, ROW + lane].set(keys[:, 1], mode="drop")
        table = table.at[r_t, 2 * ROW + lane].set(values[:, 0], mode="drop")
        table = table.at[r_t, 3 * ROW + lane].set(values[:, 1], mode="drop")
        slots = jnp.where(can, cell, slots)
        active = active & ~can
    return table, active, slots


@jax.jit
def insert_batch(state: PathState, keys: jnp.ndarray, values: jnp.ndarray):
    b = keys.shape[0]
    top = state.top
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)
    table = state.table
    n = table.shape[0]

    # update in place (the 4 chain rows, gathered once)
    (A0, A1), (B0, B1) = _paths(top, keys)
    u_hit = jnp.zeros((b,), bool)
    u_cell = jnp.full((b,), -1, jnp.int32)
    for row, lanes in (A0, B0, A1, B1):
        rd = table[row]
        eq = _row_eq(rd, jnp.where(winner[:, None], keys,
                                   jnp.uint32(INVALID_WORD)), lanes)
        hit = eq.any(axis=1)
        lane = jnp.argmax(eq, axis=1).astype(jnp.int32)
        u_cell = jnp.where(hit, row * CELLS + lane, u_cell)
        u_hit = u_hit | hit
    u_row = jnp.where(u_hit, u_cell // CELLS, jnp.int32(n))
    u_lane = jnp.maximum(u_cell, 0) % CELLS
    table = table.at[u_row, 2 * ROW + u_lane].set(values[:, 0], mode="drop")
    table = table.at[u_row, 3 * ROW + u_lane].set(values[:, 1], mode="drop")

    active = winner & ~u_hit
    slots = jnp.where(u_hit, u_cell, jnp.int32(-1))

    # L0 rounds (seed A, then B) at full width — the fill-batch bulk.
    table, active, slots = _claim_rounds(
        top, table, keys, values, active, slots, 0, 2
    )

    # Survivors compact to b/4 for the L1 rounds, then to b/16 for the
    # rest; a first-stage overflow falls back to full width and a
    # second-stage overflow falls back to stage-1 width (exact high-fill
    # semantics on both rungs — a key is only dropped after all 16
    # candidate cells were actually probed, as in the reference).
    W1 = min(b, max(1024, b // 4))
    idx, in_w, safe, overflow = compact_mask(active, W1)

    def full(tb):
        tb, act, sl = _claim_rounds(top, tb, keys, values, active, slots, 2, 16)
        return tb, act, sl

    def narrow(tb):
        ck = jnp.where(in_w[:, None], keys[safe], jnp.uint32(INVALID_WORD))
        cv = jnp.where(in_w[:, None], values[safe], jnp.uint32(0))
        sl_w = jnp.full((W1,), -1, jnp.int32)
        tb, act_w, sl_w = _claim_rounds(top, tb, ck, cv, in_w, sl_w, 2, 4)

        W2 = min(W1, max(1024, b // 16))
        if W2 < W1:
            idx2, in2, safe2, over2 = compact_mask(act_w, W2)

            def stage2_narrow(tb):
                # survivors fit W2: run rounds 4-16 at the narrow width
                ck2 = jnp.where(in2[:, None], ck[safe2],
                                jnp.uint32(INVALID_WORD))
                cv2 = jnp.where(in2[:, None], cv[safe2], jnp.uint32(0))
                sl2 = jnp.full((W2,), -1, jnp.int32)
                tb, act2, sl2 = _claim_rounds(
                    top, tb, ck2, cv2, in2, sl2, 4, 16)
                # fold stage-2 results back into stage-1 width
                placed2 = in2 & ~act2
                pos2 = jnp.where(placed2, idx2, jnp.int32(W1))
                sl = sl_w.at[pos2].set(sl2, mode="drop")
                act = act_w & ~(
                    jnp.zeros((W1,), bool).at[pos2].set(True, mode="drop")
                )
                return tb, act, sl

            def stage2_full(tb):
                # > W2 survivors (skewed batches at moderate fill): probing
                # only the first W2 would early-drop keys the remaining 12
                # candidate cells could still place — the reference only
                # fails an insert after exhausting BOTH paths, so re-run
                # rounds 4-16 at stage-1 width instead (exact semantics,
                # paid only on the overflow batches that need it).
                return _claim_rounds(top, tb, ck, cv, act_w, sl_w, 4, 16)

            tb, act_w, sl_w = jax.lax.cond(
                over2.any(), stage2_full, stage2_narrow, tb
            )
        else:
            tb, act_w, sl_w = _claim_rounds(top, tb, ck, cv, act_w, sl_w, 4, 16)

        # scatter narrow results back to batch positions
        placed_w = in_w & (sl_w >= 0)
        pos = jnp.where(placed_w, idx, jnp.int32(b))
        sl_b = slots.at[pos].set(sl_w, mode="drop")
        plc = jnp.zeros((b,), bool).at[pos].set(True, mode="drop")
        act_b = (active & ~plc) | overflow
        return tb, act_b, sl_b

    if W1 == b:
        table, active, slots = full(table)
    else:
        table, active, slots = jax.lax.cond(
            overflow.any(), full, narrow, table
        )

    res = InsertResult(
        slots=slots, evicted=inv2, dropped=active,
        fresh=(slots >= 0) & ~u_hit, evicted_vals=inv2,
    )
    return PathState(table=table, top=top), res


@jax.jit
def delete_batch(state: PathState, keys: jnp.ndarray):
    b = keys.shape[0]
    n = state.table.shape[0]
    (A0, A1), (B0, B1) = _paths(state.top, keys)
    hit = jnp.zeros((b,), bool)
    cell = jnp.full((b,), -1, jnp.int32)
    v0 = jnp.zeros((b,), jnp.uint32)
    v1 = jnp.zeros((b,), jnp.uint32)
    for row, lanes in (A0, B0, A1, B1):
        rd = state.table[row]
        eq = _row_eq(rd, keys, lanes)
        h = eq.any(axis=1)
        w0, w1 = _masked_vals(rd, eq)
        v0, v1 = v0 | w0, v1 | w1
        lane = jnp.argmax(eq, axis=1).astype(jnp.int32)
        cell = jnp.where(h, row * CELLS + lane, cell)
        hit = hit | h
    old_vals = jnp.where(
        hit[:, None], jnp.stack([v0, v1], axis=-1),
        jnp.uint32(INVALID_WORD),
    )
    r_t = jnp.where(hit, cell // CELLS, jnp.int32(n))
    lane = jnp.maximum(cell, 0) % CELLS
    inv = jnp.full((b,), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_t, lane].set(inv, mode="drop")
    table = table.at[r_t, ROW + lane].set(inv, mode="drop")
    return dataclasses.replace(state, table=table), hit, old_vals


@jax.jit
def set_values(state: PathState, slots: jnp.ndarray, values: jnp.ndarray):
    n = state.table.shape[0]
    r_t = jnp.where(slots >= 0, slots // CELLS, jnp.int32(n))
    lane = jnp.maximum(slots, 0) % CELLS
    table = state.table.at[r_t, 2 * ROW + lane].set(values[:, 0], mode="drop")
    table = table.at[r_t, 3 * ROW + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: PathState):
    """Slot-id-aligned flatten: only the CELLS real lanes per row, so
    scan position == dense slot id (kv.find_anyway pairs them)."""
    t = state.table
    keys = jnp.stack(
        [t[:, 0:CELLS].reshape(-1),
         t[:, ROW : ROW + CELLS].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * ROW : 2 * ROW + CELLS].reshape(-1),
         t[:, 3 * ROW : 3 * ROW + CELLS].reshape(-1)],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.PATH,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
        rows_per_get=2,  # bank-0 rows of both seeds (bank 1 only on miss)
        gather_row_slots=ROW,
    ),
)
