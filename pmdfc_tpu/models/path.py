"""Path hashing — binary-tree fallback levels of single-slot cells.

Reference: `server/src/path_hashing.{hpp,cpp}` — a binary tree of cells:
level 0 has N single-slot cells, each lower level halves, and a key that
collides at level i falls back to its parent cell at level i+1; two seeds
give two independent fallback paths (`path_hashing.hpp:10-17,41-57`).

TPU-native: the whole tree is one SoA pair of arrays (`keys[N_total, 2]`,
`vals[N_total, 2]`) with per-level offsets baked in at trace time. A batched
GET gathers all `2 * levels` candidate cells at once and first-hit-selects —
the reference's pointer walk becomes one gather. Inserts claim cells in probe
order with per-cell batch ranking (rank-0 claims, everyone else falls to the
next level). Exhausting both paths DROPS the insert (the reference fails it;
clean-cache reports it).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

SEED_A = 0x0A7B57ED
SEED_B = 0xB17C0DE5
LEVELS = 8


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PathState:
    keys: jnp.ndarray  # uint32[N, 2]
    vals: jnp.ndarray  # uint32[N, 2]
    top: int = dataclasses.field(metadata=dict(static=True), default=2)


def _top_cells(config: IndexConfig) -> int:
    # sum_{i<L} top/2^i = top * (2 - 2^(1-L)) ≈ 2*top  =>  top ≈ capacity/2
    c = max(1 << (LEVELS - 1), config.capacity // 2)
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def _total_cells(top: int) -> int:
    return sum(top >> i for i in range(LEVELS))


def num_slots(config: IndexConfig) -> int:
    return _total_cells(_top_cells(config))


def init(config: IndexConfig) -> PathState:
    top = _top_cells(config)
    n = _total_cells(top)
    return PathState(
        keys=jnp.full((n, 2), INVALID_WORD, jnp.uint32),
        vals=jnp.zeros((n, 2), jnp.uint32),
        top=top,
    )


def _probe_cells(state: PathState, keys: jnp.ndarray) -> jnp.ndarray:
    """int32[B, 2*LEVELS] candidate cell ids in probe order (level-major,
    path A before path B within each level)."""
    top = state.top
    ha = hash_u64(keys[..., 0], keys[..., 1], seed=SEED_A)
    hb = hash_u64(keys[..., 0], keys[..., 1], seed=SEED_B)
    out = []
    off = 0
    for i in range(LEVELS):
        width = top >> i
        pa = (ha & jnp.uint32(width - 1)).astype(jnp.int32) + off
        pb = (hb & jnp.uint32(width - 1)).astype(jnp.int32) + off
        out.extend([pa, pb])
        off += width
        ha = ha >> 1  # parent chain: halving the position per level
        hb = hb >> 1
    return jnp.stack(out, axis=-1)


@jax.jit
def get_batch(state: PathState, keys: jnp.ndarray) -> GetResult:
    cells = _probe_cells(state, keys)               # [B, 2L]
    ck = state.keys[cells]                          # [B, 2L, 2]
    eq = (
        (ck[..., 0] == keys[:, None, 0])
        & (ck[..., 1] == keys[:, None, 1])
        & ~is_invalid(keys)[:, None]
    )
    found = eq.any(axis=1)
    first = jnp.argmax(eq, axis=1)
    cell = jnp.take_along_axis(cells, first[:, None], axis=1)[:, 0]
    values = state.vals[cell]
    values = jnp.where(found[:, None], values, jnp.uint32(0))
    gslot = jnp.where(found, cell, jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: PathState, keys: jnp.ndarray):
    """Lean GET. Path's probe is already minimal (the slot id IS the
    matched cell), so this delegates — XLA dead-code-eliminates the
    unused gslot computation under jit."""
    r = get_batch(state, keys)
    return r.values, r.found


@jax.jit
def insert_batch(state: PathState, keys: jnp.ndarray, values: jnp.ndarray):
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    cells = _probe_cells(state, keys)
    inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)

    # update in place
    ck = state.keys[cells]
    eq = (
        (ck[..., 0] == keys[:, None, 0]) & (ck[..., 1] == keys[:, None, 1])
        & winner[:, None]
    )
    u_hit = eq.any(axis=1)
    u_cell = jnp.take_along_axis(
        cells, jnp.argmax(eq, axis=1)[:, None], axis=1
    )[:, 0]
    n = state.keys.shape[0]
    kk, vv = state.keys, state.vals
    vv = vv.at[jnp.where(u_hit, u_cell, jnp.int32(n))].set(
        values, mode="drop"
    )

    # claim cells in probe order; rank-0 claimant per free cell wins
    active = winner & ~u_hit
    slots = jnp.where(u_hit, u_cell, jnp.int32(-1))
    for j in range(2 * LEVELS):
        cell_j = cells[:, j]
        occupied = ~(
            (kk[cell_j][:, 0] == jnp.uint32(INVALID_WORD))
            & (kk[cell_j][:, 1] == jnp.uint32(INVALID_WORD))
        )
        rank = batch_rank_by_segment(cell_j.astype(jnp.uint32), active)
        can = active & ~occupied & (rank == 0)
        tgt = jnp.where(can, cell_j, jnp.int32(n))
        kk = kk.at[tgt].set(keys, mode="drop")
        vv = vv.at[tgt].set(values, mode="drop")
        slots = jnp.where(can, cell_j, slots)
        active = active & ~can

    res = InsertResult(
        slots=slots, evicted=inv2, dropped=active, fresh=(slots >= 0) & ~u_hit,
        evicted_vals=inv2,
    )
    return PathState(keys=kk, vals=vv, top=state.top), res


@jax.jit
def delete_batch(state: PathState, keys: jnp.ndarray):
    cells = _probe_cells(state, keys)
    ck = state.keys[cells]
    eq = (
        (ck[..., 0] == keys[:, None, 0]) & (ck[..., 1] == keys[:, None, 1])
        & ~is_invalid(keys)[:, None]
    )
    hit = eq.any(axis=1)
    cell = jnp.take_along_axis(cells, jnp.argmax(eq, axis=1)[:, None],
                               axis=1)[:, 0]
    old_vals = jnp.where(
        hit[:, None], state.vals[cell], jnp.uint32(INVALID_WORD)
    )
    n = state.keys.shape[0]
    tgt = jnp.where(hit, cell, jnp.int32(n))
    inv2 = jnp.full((keys.shape[0], 2), INVALID_WORD, jnp.uint32)
    kk = state.keys.at[tgt].set(inv2, mode="drop")
    return dataclasses.replace(state, keys=kk), hit, old_vals


@jax.jit
def set_values(state: PathState, slots: jnp.ndarray, values: jnp.ndarray):
    n = state.keys.shape[0]
    tgt = jnp.where(slots >= 0, slots, jnp.int32(n))
    return dataclasses.replace(
        state, vals=state.vals.at[tgt].set(values, mode="drop")
    )


def scan(state: PathState):
    return state.keys, state.vals


register_index(
    IndexKind.PATH,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
        rows_per_get=2 * LEVELS,  # every tree cell on both paths
        gather_row_slots=1,  # single-slot cells, not cluster rows
    ),
)
