"""Shared index-op result types and the index registry.

Reference interface being mirrored: `IHash` (`server/IHash.h:10-24`) —
`Insert(key, value) -> evicted_key_or_-1`, `Get(key) -> value_or_NONE`,
`Delete(key)`, `Capacity()`, `Utilization()` — lifted to fixed-shape batches.
Batches may contain INVALID (padding) keys, which are no-ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind


class GetResult(NamedTuple):
    values: jnp.ndarray  # uint32[B, 2]; undefined where not found
    found: jnp.ndarray   # bool[B]
    slots: jnp.ndarray   # int32[B] global slot id (for the page pool); -1 if miss


class InsertResult(NamedTuple):
    slots: jnp.ndarray    # int32[B] global slot the key landed in; -1 if not placed
    evicted: jnp.ndarray  # uint32[B, 2] keys evicted to make room (INVALID if none)
    dropped: jnp.ndarray  # bool[B] True when the key itself was dropped
                          # (clean-cache overflow: a legal outcome)
    fresh: jnp.ndarray    # bool[B] True when the key landed in a NEW slot
                          # (False for in-place updates and drops). Lets the
                          # page pool scatter updates before fresh inserts so
                          # a same-slot (update, evicting-insert) pair within
                          # one batch resolves the same way the index did.
    evicted_vals: jnp.ndarray  # uint32[B, 2] values of evicted entries
                          # (INVALID where none). The KV façade reclaims pool
                          # rows from these — the analog of the reference
                          # reusing the evicted entry's page slot.


@dataclasses.dataclass(frozen=True)
class IndexOps:
    """Vtable for one index family."""

    init: Callable[[IndexConfig], Any]
    get_batch: Callable[..., GetResult]
    insert_batch: Callable[..., tuple]
    delete_batch: Callable[..., tuple]  # -> (state, hit[B], old_vals[B, 2])
    num_slots: Callable[[IndexConfig], int]  # static global-slot-space size
    # (state, slots[B], values[B, 2]) -> state: overwrite value lanes at the
    # given global slots (slot -1 = no-op). Lets the KV façade patch pool row
    # ids into freshly placed entries after batched allocation.
    set_values: Callable[..., Any] | None = None
    # (flat_keys[N, 2], flat_vals[N, 2]) view of every slot, N == num_slots.
    # Powers FindAnyway (`server/IKV.h:18`) and Utilization as full scans.
    scan: Callable[[Any], tuple] | None = None
    # Post-restart repair (ref `CCEH::Recovery` `server/CCEH_hybrid.cpp:391`).
    # state -> state; indexes without recovery needs leave it None.
    recovery: Callable[[Any], Any] | None = None
    # (state, hit_slots[B]) -> state: access-heat bookkeeping on GET
    # (hotring's per-access counter bump). The KV façade calls it when set.
    touch: Callable[..., Any] | None = None
    # state -> state: periodic heat drain (hotring counter halving). The KV
    # host wrapper applies it every `IndexConfig.decay_every_gets` keys.
    decay: Callable[[Any], Any] | None = None
    # Roofline shape of the lean GET: gathered units per probed key, and
    # the unit's width in slots (None = the index's cluster_slots). The
    # bench divides GET ops/s by these against a measured gather wall;
    # keeping them here means a family changing its probe pattern (e.g.
    # level's window count) cannot silently desynchronize the artifact's
    # gather_bytes_per_s / gather_wall_frac from the code.
    rows_per_get: int = 1
    gather_row_slots: int | None = None
    # Lean probe: (state, keys) -> (values[B, 2], found[B]) with values
    # already zeroed on miss. Skips slot/argmax bookkeeping — the KV façade
    # uses it on the GET hot path when no pool row or touch hook needs the
    # slot (the probe gather runs at the chip's fixed ~79 Mrows/s issue rate,
    # so every non-gather op directly costs headline throughput).
    get_values: Callable[..., tuple] | None = None


_REGISTRY: dict[IndexKind, IndexOps] = {}


def register_index(kind: IndexKind, ops: IndexOps) -> None:
    _REGISTRY[kind] = ops


def get_index_ops(kind: IndexKind) -> IndexOps:
    # Import lazily so each family registers on first use.
    if kind not in _REGISTRY:
        import importlib

        mod = {
            IndexKind.LINEAR: "pmdfc_tpu.models.linear",
            IndexKind.CCEH: "pmdfc_tpu.models.cceh",
            IndexKind.CUCKOO: "pmdfc_tpu.models.cuckoo",
            IndexKind.CUCKOO_PROBING: "pmdfc_tpu.models.cuckoo_probing",
            IndexKind.LEVEL: "pmdfc_tpu.models.level",
            IndexKind.PATH: "pmdfc_tpu.models.path",
            IndexKind.EXTENDIBLE: "pmdfc_tpu.models.extendible",
            IndexKind.STATIC: "pmdfc_tpu.models.static",
            IndexKind.HOTRING: "pmdfc_tpu.models.hotring",
        }[kind]
        importlib.import_module(mod)
    return _REGISTRY[kind]


def compact_mask(mask: jnp.ndarray, width: int):
    """Gather plan for compacting the True lanes of `mask[B]` into a
    width-W buffer (the straggler-round idiom shared by cuckoo's kick
    loop and path's claim stages — one definition, or the fill-value/
    drop-mode details drift per family).

    Returns `(idx, in_w, safe, overflow)`:
    - `idx[W]` — original positions of the first W True lanes (B pads);
    - `in_w[W]` — which buffer lanes are real;
    - `safe[W]` — `idx` clamped for gathering (`x[safe]` then mask);
    - `overflow[B]` — True lanes that did not fit (callers either report
      them as drops or `lax.cond` to a full-width fallback).
    """
    b = mask.shape[0]
    idx = jnp.nonzero(mask, size=width, fill_value=b)[0]
    in_w = idx < b
    safe = jnp.minimum(idx, b - 1)
    sel = jnp.zeros((b,), bool).at[idx].set(True, mode="drop")
    return idx, in_w, safe, mask & ~sel


def batch_rank_by_segment(segment_ids: jnp.ndarray, mask: jnp.ndarray):
    """Rank of each masked element among batch elements with the same segment id.

    The core primitive for conflict-free batched inserts: where the reference
    serializes same-bucket inserts behind a cluster lock
    (`server/src/linear_probing.cpp:26-65`), we sort the batch by bucket and
    assign each key its offset within its bucket's run — every (bucket, rank)
    pair is then a unique target slot and the whole batch scatters at once.

    Returns int32 ranks (0-based within segment; masked-off elements get
    arbitrary large ranks).
    """
    b = segment_ids.shape[0]
    sort_key = jnp.where(mask, segment_ids.astype(jnp.uint32), jnp.uint32(0xFFFFFFFF))
    order = jnp.argsort(sort_key, stable=True)
    sorted_ids = sort_key[order]
    idx = jnp.arange(b, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]]
    )
    start_idx = jax_cummax(jnp.where(is_start, idx, jnp.int32(0)))
    rank_sorted = idx - start_idx
    return jnp.zeros((b,), jnp.int32).at[order].set(rank_sorted)


def jax_cummax(x: jnp.ndarray) -> jnp.ndarray:
    import jax

    return jax.lax.cummax(x)


class InsertPlan(NamedTuple):
    """Products of ONE fused sort serving both dedupe and segment ranking.

    Sorting by (segment-with-invalid-top-bit, khi, klo) makes duplicate
    keys adjacent (same key ⇒ same segment) AND groups segments
    contiguously, so `dedupe_last_wins` and `batch_rank_by_segment` — two
    separate sorts on the insert hot path — collapse into one lexsort
    plus segmented scans. There is NO explicit original-index operand:
    the sort MUST stay stable (jnp.lexsort is), because ties keeping
    batch order is what makes "last occurrence wins" and plan-order
    ranks deterministic. Invalids ride bit 31 of the segment word
    (row counts never reach 2^31), so validity is not a separate operand
    either. Three operands, not five — sort cost grows with operand
    count and the sort is the insert path's biggest single piece
    (bench/insert_profile.py).
    """

    order: jnp.ndarray      # int32[B]: sorted positions (original indices)
    seg_start: jnp.ndarray  # bool[B] in SORTED space: first row of a run
    winner: jnp.ndarray     # bool[B] in ORIGINAL space: last dup occurrence


def plan_insert(keys: jnp.ndarray, seg: jnp.ndarray,
                valid: jnp.ndarray,
                num_segments: int | None = None) -> InsertPlan:
    # The invalid flag rides bit 31 of the segment word below; a segment
    # id at or above 2^31 would silently corrupt the valid/invalid sort
    # order and the dedupe winners. Row counts are trace-time constants,
    # so callers pass theirs and the bound is enforced statically
    # (ADVICE r4 item 2 — a comment-level invariant is not a check).
    if num_segments is not None and num_segments >= (1 << 31):
        # raise, not assert: python -O strips asserts, which would revert
        # this to the comment-level invariant the check exists to replace
        raise ValueError(
            f"plan_insert: {num_segments} segments >= 2^31 would collide "
            "with the packed invalid bit"
        )
    b = keys.shape[0]
    inv = (~valid).astype(jnp.uint32)
    hi, lo = keys[..., 0], keys[..., 1]
    # THREE sort operands, not five: invalids ride the top bit of the
    # segment word (cluster/bucket ids are table-row counts and can never
    # reach 2^31), and jnp.lexsort's stability replaces the explicit
    # original-index tiebreaker — ties keep batch order, so "last
    # occurrence wins" and plan_rank's plan-order ranks are unchanged.
    # The sort is the insert hot path's biggest single piece
    # (bench/insert_profile.py), and sort cost grows with operand count.
    segp = seg.astype(jnp.uint32) | (inv << jnp.uint32(31))
    order = jnp.lexsort((lo, hi, segp))
    s_hi, s_lo = hi[order], lo[order]
    s_segp = segp[order]
    s_inv = s_segp >> jnp.uint32(31)
    same_next = jnp.concatenate(
        [
            (s_hi[:-1] == s_hi[1:]) & (s_lo[:-1] == s_lo[1:])
            & (s_segp[:-1] == s_segp[1:]),
            jnp.zeros((1,), bool),
        ]
    )
    winner_sorted = ~same_next & (s_inv == 0)
    winner = jnp.zeros((b,), bool).at[order].set(winner_sorted)
    seg_start = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            s_segp[1:] != s_segp[:-1],
        ]
    )
    return InsertPlan(order=order.astype(jnp.int32), seg_start=seg_start,
                      winner=winner)


def plan_rank(plan: InsertPlan, mask: jnp.ndarray) -> jnp.ndarray:
    """int32[B]: 0-based rank of each masked row among masked rows of its
    segment (ordered by the plan's sort); unmasked rows get a huge rank
    (same contract as `batch_rank_by_segment`)."""
    import jax

    m = mask[plan.order].astype(jnp.int32)
    c = jnp.cumsum(m)
    base = jax.lax.cummax(jnp.where(plan.seg_start, c - m, jnp.int32(0)))
    rank_sorted = c - m - base
    rank = jnp.zeros_like(rank_sorted).at[plan.order].set(rank_sorted)
    # same contract as batch_rank_by_segment: unmasked rows get a huge rank,
    # so a consumer's `rank < capacity` test stays inert without re-gating
    return jnp.where(mask, rank, jnp.int32(0x7FFFFFFF))


def dedupe_last_wins(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Mask selecting, for each distinct key in the batch, its LAST occurrence.

    Batched puts must be deterministic: two puts of the same key in one batch
    resolve to the later one (matching the serialized order the reference's
    per-queue lock would impose, `client/rdpma.c:307-320`).
    """
    b = keys.shape[0]
    idx = jnp.arange(b, dtype=jnp.uint32)
    # Leading invalid flag keeps padding rows strictly after — and never
    # equal to — any valid key (a valid key may legitimately have
    # hi == 0xFFFFFFFF, so hi/lo alone cannot disambiguate).
    inv = (~valid).astype(jnp.uint32)
    hi, lo = keys[..., 0], keys[..., 1]
    order = jnp.lexsort((idx, lo, hi, inv))  # (inv, hi, lo), stable by position
    s_hi, s_lo, s_inv = hi[order], lo[order], inv[order]
    same_as_next = jnp.concatenate(
        [
            (s_hi[:-1] == s_hi[1:])
            & (s_lo[:-1] == s_lo[1:])
            & (s_inv[:-1] == s_inv[1:]),
            jnp.zeros((1,), bool),
        ]
    )
    winner_sorted = ~same_as_next
    winner = jnp.zeros((b,), bool).at[order].set(winner_sorted)
    return winner & valid
