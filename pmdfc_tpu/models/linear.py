"""Linear-probing index with FIFO cluster eviction — the default index.

Reference: `server/src/linear_probing.{h,cpp}` — fixed 16-slot lock-striped
clusters; when a cluster is full the oldest entry is FIFO-evicted and returned
so the KV façade can delete it from the bloom filter
(`server/src/linear_probing.cpp:26-65`). That eviction-on-overflow behavior IS
the clean-cache semantics: the store may drop entries, a miss is legal.

TPU-native redesign (not a translation):
- **Fused-row layout**: one cluster = ONE `uint32[4*S]` row holding four
  S-lane groups `[khi | klo | vhi | vlo]` (S = 32 slots by default → a
  128-lane row, exactly one TPU vreg row and exactly the reference CCEH's
  32-slot probe window, `server/CCEH_hybrid.h:18-19`). A batched GET is a
  single row gather `table[c] -> [B, 128]` followed by pure VPU lane
  compares — measured ~40× faster on TPU than the naive `[C, S, 2]`
  struct-of-pairs layout, whose 2-wide minor axis tile-pads 64× and whose
  value fetch needs extra element gathers.
- Values are extracted from the matched lane with a one-hot masked sum (keys
  are unique within a cluster), not a second gather.
- Per-cluster monotone FIFO cursor `head[C]`: eviction is a pure overwrite
  at `(head + rank) % S`, so a batched insert is a handful of elementwise
  scatters — no shift-left, no locks.
- Same-cluster conflicts inside a batch are resolved by ONE fused sort
  (`plan_insert`/`plan_rank`: dedupe-last-wins + per-cluster ranks from a
  single lexsort) rather than locks: every (cluster, rank) pair is a unique
  target lane. If a single batch carries more than S new keys for one
  cluster the overflow keys are dropped and reported
  (`InsertResult.dropped`) — legal under clean-cache, and it keeps the op
  deterministic.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    plan_insert,
    plan_rank,
    register_index,
)
from pmdfc_tpu.models.rowops import lane_pick as _lane_pick
from pmdfc_tpu.models.rowops import match_mask, match_rows as _match
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LinearState:
    table: jnp.ndarray  # uint32[C, 4*S]: lane groups [khi | klo | vhi | vlo]
    head: jnp.ndarray   # uint32[C] monotone FIFO cursor


def _num_clusters(config: IndexConfig) -> int:
    c = max(1, config.capacity // config.cluster_slots)
    # power of two so bucket selection is a mask, not a modulo
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    return _num_clusters(config) * config.cluster_slots


def init(config: IndexConfig) -> LinearState:
    c, s = _num_clusters(config), config.cluster_slots
    table = jnp.concatenate(
        [
            jnp.full((c, 2 * s), INVALID_WORD, jnp.uint32),  # khi | klo
            jnp.zeros((c, 2 * s), jnp.uint32),               # vhi | vlo
        ],
        axis=1,
    )
    return LinearState(table=table, head=jnp.zeros((c,), jnp.uint32))


def _cluster_of(keys: jnp.ndarray, num_clusters: int) -> jnp.ndarray:
    h = hash_u64(keys[..., 0], keys[..., 1])
    return h & jnp.uint32(num_clusters - 1)


@jax.jit
def get_batch(state: LinearState, keys: jnp.ndarray) -> GetResult:
    c_count = state.table.shape[0]
    s = state.table.shape[1] // 4
    c = _cluster_of(keys, c_count)
    rows = state.table[c]  # [B, 4S] — the one gather
    eq, slot = _match(rows, keys, s)
    found = slot >= 0
    values = jnp.stack(
        [_lane_pick(rows, eq, 2 * s, s), _lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    gslot = jnp.where(
        found, c.astype(jnp.int32) * s + jnp.maximum(slot, 0), jnp.int32(-1)
    )
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: LinearState, keys: jnp.ndarray):
    """Lean GET: (values[B, 2] zero-on-miss, found[B]) — no slot math.

    The masked sums already yield 0 for miss rows (all-false one-hot), so no
    extra `where` pass is needed downstream. This is the benched hot path:
    gather + 2 lane-group compares + 3 reductions, nothing else.
    """
    c_count = state.table.shape[0]
    s = state.table.shape[1] // 4
    c = _cluster_of(keys, c_count)
    rows = state.table[c]
    eq = match_mask(rows, keys, s)
    found = eq.any(axis=1)
    values = jnp.stack(
        [_lane_pick(rows, eq, 2 * s, s), _lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    return values, found


def _insert_plan(state: LinearState, keys: jnp.ndarray):
    """Shared insert prologue — classification both insert paths must agree
    on bit-for-bit: batch plan (dedupe/ranks), update-vs-fresh split, FIFO
    target lanes, drops, and the evicted pair pulled from the ORIGINAL row
    (BF-delete needs the pre-overwrite occupant). Only the scatter strategy
    may differ between the element and row paths.

    Returns (c, s, rows, plan, upd, ins, drop, mslot, pos, pos_hot,
    evicted, evicted_vals).
    """
    c_count = state.table.shape[0]
    s = state.table.shape[1] // 4
    valid = ~is_invalid(keys)
    c = _cluster_of(keys, c_count)
    plan = plan_insert(keys, c, valid, num_segments=c_count)  # one sort
    winner = plan.winner

    rows = state.table[c]
    _, mslot = _match(rows, keys, s)
    upd = winner & (mslot >= 0)
    new = winner & (mslot < 0)

    # fresh inserts: unique (cluster, rank) targets via segment ranking
    rank = plan_rank(plan, new)
    drop = new & (rank >= s)
    ins = new & ~drop
    pos = (state.head[c] + rank.astype(jnp.uint32)) & jnp.uint32(s - 1)
    pos_hot = (
        jnp.arange(s, dtype=jnp.uint32)[None, :] == pos[:, None]
    ) & ins[:, None]
    old = jnp.stack(
        [_lane_pick(rows, pos_hot, 0, s), _lane_pick(rows, pos_hot, s, s)],
        axis=-1,
    )
    old_v = jnp.stack(
        [_lane_pick(rows, pos_hot, 2 * s, s),
         _lane_pick(rows, pos_hot, 3 * s, s)],
        axis=-1,
    )
    # non-ins rows sum to (0, 0) which is not INVALID, but `ins` masks them
    evicted_mask = ins & ~is_invalid(old)
    evicted = jnp.where(
        evicted_mask[:, None], old, jnp.full_like(old, INVALID_WORD)
    )
    evicted_vals = jnp.where(
        evicted_mask[:, None], old_v, jnp.full_like(old_v, INVALID_WORD)
    )
    return (c, s, rows, plan, upd, ins, drop, mslot, pos, pos_hot,
            evicted, evicted_vals)


def _insert_result(c, s, upd, ins, drop, mslot, pos, evicted, evicted_vals):
    """Shared insert epilogue: global slot ids + InsertResult."""
    su = jnp.maximum(mslot, 0)
    gslot = jnp.where(
        upd,
        c.astype(jnp.int32) * s + su,
        jnp.where(ins, c.astype(jnp.int32) * s + pos.astype(jnp.int32),
                  jnp.int32(-1)),
    )
    return InsertResult(
        slots=gslot, evicted=evicted, dropped=drop, fresh=ins,
        evicted_vals=evicted_vals,
    )


@jax.jit
def insert_batch_element(state: LinearState, keys: jnp.ndarray,
                         values: jnp.ndarray):
    c_count = state.table.shape[0]
    (c, s, rows, plan, upd, ins, drop, mslot, pos, pos_hot,
     evicted, evicted_vals) = _insert_plan(state, keys)

    # --- elementwise lane scatters; rows can repeat but (row, lane) targets
    # are unique within each phase. Updates land first so a same-slot
    # (update, evicting-insert) pair resolves in the insert's favor —
    # matching the serialized order a lock would impose.
    table = state.table
    pos_i = pos.astype(jnp.int32)
    su = jnp.maximum(mslot, 0)
    cu = jnp.where(upd, c, jnp.uint32(c_count))  # OOB => dropped by scatter
    ci = jnp.where(ins, c, jnp.uint32(c_count))
    vhi, vlo = values[:, 0], values[:, 1]

    # scatter cost scales with ELEMENTS PROCESSED, not scatter count
    # (~8-11 ns/elem on the target chip even for fully-masked rows), so the
    # update phase is skipped at runtime when the batch carries no updates —
    # the common case for a cleancache fill, worth ~2 passes per batch.
    def with_updates(t):
        t = t.at[cu, 2 * s + su].set(vhi, mode="drop")
        return t.at[cu, 3 * s + su].set(vlo, mode="drop")

    table = jax.lax.cond(upd.any(), with_updates, lambda t: t, table)
    table = table.at[ci, pos_i].set(keys[:, 0], mode="drop")
    table = table.at[ci, s + pos_i].set(keys[:, 1], mode="drop")
    table = table.at[ci, 2 * s + pos_i].set(vhi, mode="drop")
    table = table.at[ci, 3 * s + pos_i].set(vlo, mode="drop")
    head2 = state.head.at[ci].add(jnp.uint32(1), mode="drop")

    res = _insert_result(c, s, upd, ins, drop, mslot, pos,
                         evicted, evicted_vals)
    return LinearState(table=table, head=head2), res


@jax.jit
def insert_batch_row(state: LinearState, keys: jnp.ndarray,
                     values: jnp.ndarray):
    """Whole-row-rebuild insert — the alternative to the element-scatter
    path (`insert_batch_element`): gather each touched cluster row once,
    merge every batch write as lane-masked overlays combined per cluster
    (segment sums in plan order), then ONE full-row scatter.

    Exactly equivalent to the element path (shared `_insert_plan`
    classification; randomized-equivalence proven in
    `tests/test_linear.py`); which one is faster is device-dependent —
    PERF.md's cost model says elements cost ~8-11 ns each (4-5/key) while
    full 256 B rows scatter at ~18.5 ns/row, so the row path should win
    on-chip once a batch writes >2-3 elements/key. Select with
    PMDFC_INSERT_PATH=row until the on-chip decision flips the default.
    """
    c_count = state.table.shape[0]
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    (c, s, rows, plan, upd, ins, drop, mslot, pos, ins_hot,
     evicted, evicted_vals) = _insert_plan(state, keys)
    lane = jnp.arange(s, dtype=jnp.uint32)[None, :]
    upd_hot = (lane == jnp.maximum(mslot, 0).astype(jnp.uint32)[:, None]
               ) & upd[:, None]

    khi, klo = keys[:, 0], keys[:, 1]
    vhi, vlo = values[:, 0], values[:, 1]
    zero = jnp.uint32(0)
    # two write planes: inserts and updates can legally target the SAME
    # lane (a fresh insert evicting the very slot another batch element
    # is updating); the element path's scatter order makes the insert
    # win, so the planes combine separately and insert takes priority
    ins4 = jnp.concatenate(
        [
            jnp.where(ins_hot, khi[:, None], zero),
            jnp.where(ins_hot, klo[:, None], zero),
            jnp.where(ins_hot, vhi[:, None], zero),
            jnp.where(ins_hot, vlo[:, None], zero),
        ],
        axis=1,
    )
    ins_m4 = jnp.tile(ins_hot, (1, 4))
    upd4 = jnp.concatenate(
        [
            jnp.zeros_like(upd_hot, jnp.uint32),
            jnp.zeros_like(upd_hot, jnp.uint32),
            jnp.where(upd_hot, vhi[:, None], zero),
            jnp.where(upd_hot, vlo[:, None], zero),
        ],
        axis=1,
    )
    upd_m4 = jnp.concatenate(
        [jnp.zeros_like(upd_hot), jnp.zeros_like(upd_hot),
         upd_hot, upd_hot], axis=1,
    )

    # combine all writes of one cluster: within a plane the
    # (cluster, lane) targets are unique, so a per-segment SUM in plan
    # order is an exact merge
    order = plan.order
    seg_id = jnp.cumsum(plan.seg_start.astype(jnp.int32)) - 1
    ci_m = jax.ops.segment_sum(ins_m4[order].astype(jnp.uint32), seg_id,
                               num_segments=b)
    ci_v = jax.ops.segment_sum(ins4[order], seg_id, num_segments=b)
    cu_m = jax.ops.segment_sum(upd_m4[order].astype(jnp.uint32), seg_id,
                               num_segments=b)
    cu_v = jax.ops.segment_sum(upd4[order], seg_id, num_segments=b)

    rows_s = rows[order]
    merged = jnp.where(
        ci_m[seg_id] > 0,
        ci_v[seg_id],
        jnp.where(cu_m[seg_id] > 0, cu_v[seg_id], rows_s),
    )
    c_s = c[order]
    valid_s = valid[order]
    first = plan.seg_start & valid_s  # invalid runs never scatter
    target = jnp.where(first, c_s, jnp.uint32(c_count))
    table = state.table.at[target].set(merged, mode="drop")
    head2 = state.head.at[
        jnp.where(ins, c, jnp.uint32(c_count))
    ].add(jnp.uint32(1), mode="drop")

    res = _insert_result(c, s, upd, ins, drop, mslot, pos,
                         evicted, evicted_vals)
    return LinearState(table=table, head=head2), res


# Insert-path selection: the element path is the measured default; set
# PMDFC_INSERT_PATH=row to run the whole stack (KV facade, engine, bench)
# through the row-rebuild path — the on-chip comparison that decides the
# permanent default (PERF.md "Pending on-chip experiments").
insert_batch = (
    insert_batch_row
    if os.environ.get("PMDFC_INSERT_PATH") == "row"
    else insert_batch_element
)


@jax.jit
def delete_batch(state: LinearState, keys: jnp.ndarray):
    c_count = state.table.shape[0]
    s = state.table.shape[1] // 4
    c = _cluster_of(keys, c_count)
    rows = state.table[c]
    eq, slot = _match(rows, keys, s)
    hit = slot >= 0
    old_vals = jnp.stack(
        [_lane_pick(rows, eq, 2 * s, s), _lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    old_vals = jnp.where(
        hit[:, None], old_vals, jnp.full_like(old_vals, INVALID_WORD)
    )
    cd = jnp.where(hit, c, jnp.uint32(c_count))
    sd = jnp.maximum(slot, 0)
    inval = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[cd, sd].set(inval, mode="drop")
    table = table.at[cd, s + sd].set(inval, mode="drop")
    return dataclasses.replace(state, table=table), hit, old_vals


@jax.jit
def set_values(state: LinearState, slots: jnp.ndarray, values: jnp.ndarray):
    """Overwrite value lanes at global slots (slot -1 ⇒ no-op)."""
    c_count = state.table.shape[0]
    s = state.table.shape[1] // 4
    ok = slots >= 0
    c = jnp.where(ok, slots // s, jnp.int32(c_count)).astype(jnp.uint32)
    lane = jnp.maximum(slots, 0) % s
    table = state.table.at[c, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[c, 3 * s + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: LinearState):
    s = state.table.shape[1] // 4
    keys = jnp.stack(
        [state.table[:, 0:s].reshape(-1), state.table[:, s : 2 * s].reshape(-1)],
        axis=-1,
    )
    vals = jnp.stack(
        [
            state.table[:, 2 * s : 3 * s].reshape(-1),
            state.table[:, 3 * s : 4 * s].reshape(-1),
        ],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.LINEAR,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
    ),
)
