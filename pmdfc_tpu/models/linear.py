"""Linear-probing index with FIFO cluster eviction — the default index.

Reference: `server/src/linear_probing.{h,cpp}` — fixed 16-slot lock-striped
clusters; when a cluster is full the oldest entry is FIFO-evicted and returned
so the KV façade can delete it from the bloom filter
(`server/src/linear_probing.cpp:26-65`). That eviction-on-overflow behavior IS
the clean-cache semantics: the store may drop entries, a miss is legal.

TPU-native redesign (not a translation):
- Struct-of-arrays state in HBM: `keys[C, S, 2]`, `vals[C, S, 2]` uint32 and a
  per-cluster monotone FIFO cursor `head[C]` — instead of the reference's
  shift-left-on-evict, the cursor makes eviction a pure overwrite at
  `head % S`, so a batched insert is one scatter.
- All ops are fixed-shape batches. Same-cluster conflicts inside a batch are
  resolved by `batch_rank_by_segment` (sort + segment rank) rather than locks:
  key i gets slot `(head[c] + rank_i) % S`, every target is unique, and the
  whole batch lands in one scatter. head advances by a scatter-add.
- If a single batch carries more than S new keys for one cluster, the
  overflow keys are dropped and reported (`InsertResult.dropped`) — legal
  under clean-cache, and it keeps the op deterministic.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LinearState:
    keys: jnp.ndarray  # uint32[C, S, 2]
    vals: jnp.ndarray  # uint32[C, S, 2]
    head: jnp.ndarray  # uint32[C] monotone FIFO cursor


def _num_clusters(config: IndexConfig) -> int:
    c = max(1, config.capacity // config.cluster_slots)
    # power of two so bucket selection is a mask, not a modulo
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    return _num_clusters(config) * config.cluster_slots


def init(config: IndexConfig) -> LinearState:
    c, s = _num_clusters(config), config.cluster_slots
    return LinearState(
        keys=jnp.full((c, s, 2), INVALID_WORD, dtype=jnp.uint32),
        vals=jnp.zeros((c, s, 2), dtype=jnp.uint32),
        head=jnp.zeros((c,), dtype=jnp.uint32),
    )


def _cluster_of(keys: jnp.ndarray, num_clusters: int) -> jnp.ndarray:
    h = hash_u64(keys[..., 0], keys[..., 1])
    return h & jnp.uint32(num_clusters - 1)


def _match_slot(cluster_keys: jnp.ndarray, keys: jnp.ndarray) -> jnp.ndarray:
    """[B, S, 2] window vs [B, 2] keys -> int32[B] slot or -1."""
    eq = (cluster_keys[..., 0] == keys[:, None, 0]) & (
        cluster_keys[..., 1] == keys[:, None, 1]
    )
    eq &= ~is_invalid(keys)[:, None]
    slot = jnp.argmax(eq, axis=1).astype(jnp.int32)
    return jnp.where(eq.any(axis=1), slot, jnp.int32(-1))


@jax.jit
def get_batch(state: LinearState, keys: jnp.ndarray) -> GetResult:
    c_count, s = state.keys.shape[0], state.keys.shape[1]
    c = _cluster_of(keys, c_count)
    window = state.keys[c]  # [B, S, 2]
    slot = _match_slot(window, keys)
    found = slot >= 0
    safe_slot = jnp.maximum(slot, 0)
    values = state.vals[c, safe_slot]
    gslot = jnp.where(found, c.astype(jnp.int32) * s + safe_slot, jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def insert_batch(state: LinearState, keys: jnp.ndarray, values: jnp.ndarray):
    c_count, s = state.keys.shape[0], state.keys.shape[1]
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    c = _cluster_of(keys, c_count)

    window = state.keys[c]
    mslot = _match_slot(window, keys)
    upd = winner & (mslot >= 0)
    new = winner & (mslot < 0)

    # --- in-place updates for keys already present (two ordered scatters so a
    # later insert landing on the same slot deterministically wins) ---
    cu = jnp.where(upd, c, jnp.uint32(c_count))  # OOB => dropped by scatter
    su = jnp.maximum(mslot, 0)
    vals1 = state.vals.at[cu, su].set(values, mode="drop")

    # --- fresh inserts: unique (cluster, rank) targets via segment ranking ---
    rank = batch_rank_by_segment(c, new)
    drop = new & (rank >= s)
    ins = new & ~drop
    pos = (state.head[c] + rank.astype(jnp.uint32)) & jnp.uint32(s - 1)
    old = state.keys[c, pos]  # pre-batch occupant
    evicted_mask = ins & ~is_invalid(old)
    evicted = jnp.where(
        evicted_mask[:, None], old, jnp.full_like(old, INVALID_WORD)
    )

    ci = jnp.where(ins, c, jnp.uint32(c_count))
    keys2 = state.keys.at[ci, pos].set(keys, mode="drop")
    vals2 = vals1.at[ci, pos].set(values, mode="drop")
    head2 = state.head.at[ci].add(jnp.uint32(1), mode="drop")

    gslot = jnp.where(
        upd,
        c.astype(jnp.int32) * s + su,
        jnp.where(ins, c.astype(jnp.int32) * s + pos.astype(jnp.int32), jnp.int32(-1)),
    )
    res = InsertResult(slots=gslot, evicted=evicted, dropped=drop, fresh=ins)
    return LinearState(keys=keys2, vals=vals2, head=head2), res


@jax.jit
def delete_batch(state: LinearState, keys: jnp.ndarray):
    c_count = state.keys.shape[0]
    c = _cluster_of(keys, c_count)
    slot = _match_slot(state.keys[c], keys)
    hit = slot >= 0
    cd = jnp.where(hit, c, jnp.uint32(c_count))
    inval = jnp.full_like(keys, INVALID_WORD)
    keys2 = state.keys.at[cd, jnp.maximum(slot, 0)].set(inval, mode="drop")
    return dataclasses.replace(state, keys=keys2), hit


def scan(state: LinearState):
    return state.keys.reshape(-1, 2), state.vals.reshape(-1, 2)


register_index(
    IndexKind.LINEAR,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
    ),
)
