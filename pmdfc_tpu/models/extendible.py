"""Classic extendible hashing — LSB directory variant of the CCEH machinery.

Reference: `server/src/extendible_hash.{h,cpp}` — LSB-indexed directory over
256 KB blocks (`extendible_hash.h:27-33`), block split + directory doubling.

TPU-native: identical fused-row/replicated-directory design as
`models/cceh.py` with LSB prefix arithmetic (`msb=False`): directory index is
`h & (Smax-1)`, a split redistributes by bit `ld` counted from the bottom,
and replication classes are strided rather than contiguous. Blocks are
segments of `segment_slots` lanes probed through the hashed window row.
"""

from __future__ import annotations

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import IndexOps, register_index
from pmdfc_tpu.models import cceh


def init(config: IndexConfig):
    return cceh.init(config, msb=False)


register_index(
    IndexKind.EXTENDIBLE,
    IndexOps(
        init=init,
        get_batch=cceh.get_batch,
        insert_batch=cceh.insert_batch,
        delete_batch=cceh.delete_batch,
        num_slots=cceh.num_slots,
        scan=cceh.scan,
        set_values=cceh.set_values,
        recovery=cceh.recovery,
        get_values=cceh.get_values,
    ),
)
