"""Cuckoo-probing (CCP) — linear-probing clusters + second-chance cuckoo.

Reference: `server/src/cuckoo_probing.{h,cpp}` — linear-probing clusters
whose FIFO victim is re-homed once to its second hash cluster, tagged with
`cuckooBit` (bit 63 of the value, `cuckoo_probing.h:13`); a victim that is
ALREADY cuckooed is evicted for real (`Insert` `cuckoo_probing.cpp:34-110`).

TPU-native redesign:
- Same fused-row FIFO clusters as `models/linear.py`.
- The cuckoo tag lives in a separate per-cluster uint32 bitmask plane (one
  bit per lane) instead of stealing a value bit — value words stay full-width
  (the KV façade already uses the value hi-bit for extent tagging).
- Batched: the insert scatter produces per-lane victims exactly like linear;
  a single relocation phase then re-homes the not-yet-cuckooed victims into
  free lanes of their second cluster (rank-deconflicted, re-gathered), sets
  their tag bits, and reports the rest as true evictions. One hop, no
  cascade — precisely the reference's second-chance rule.
- GET/DELETE probe both clusters (two gathers): an entry lives in cluster 1
  untagged or cluster 2 tagged.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from pmdfc_tpu.config import IndexConfig, IndexKind
from pmdfc_tpu.models.base import (
    GetResult,
    IndexOps,
    InsertResult,
    batch_rank_by_segment,
    dedupe_last_wins,
    register_index,
)
from pmdfc_tpu.models.rowops import (
    free_lanes,
    lane_pick,
    lean_two_window,
    match_rows,
    nth_lane,
    pick_kv,
    scatter_entry,
)
from pmdfc_tpu.utils.hashing import hash_u64
from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid

ALT_SEED = 0xCC9CC9CC


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CCPState:
    table: jnp.ndarray   # uint32[C, 4*S]
    head: jnp.ndarray    # uint32[C] FIFO cursor (cluster-1 placements)
    cuckooed: jnp.ndarray  # uint32[C] per-lane tag bits (lives-in-2nd-cluster)


def _num_rows(config: IndexConfig) -> int:
    c = max(2, config.capacity // config.cluster_slots)
    return 1 << (c - 1).bit_length() if c & (c - 1) else c


def num_slots(config: IndexConfig) -> int:
    return _num_rows(config) * config.cluster_slots


def init(config: IndexConfig) -> CCPState:
    c, s = _num_rows(config), config.cluster_slots
    table = jnp.concatenate(
        [
            jnp.full((c, 2 * s), INVALID_WORD, jnp.uint32),
            jnp.zeros((c, 2 * s), jnp.uint32),
        ],
        axis=1,
    )
    return CCPState(
        table=table,
        head=jnp.zeros((c,), jnp.uint32),
        cuckooed=jnp.zeros((c,), jnp.uint32),
    )


def _rows_of(c: int, keys: jnp.ndarray):
    r1 = hash_u64(keys[..., 0], keys[..., 1]) & jnp.uint32(c - 1)
    r2 = hash_u64(keys[..., 0], keys[..., 1], seed=ALT_SEED) & jnp.uint32(c - 1)
    return r1.astype(jnp.int32), r2.astype(jnp.int32)


def _match2(state: CCPState, keys: jnp.ndarray):
    """Probe both clusters; prefer cluster 1. Returns (row, lane, hit,
    rows_at_hit, eq)."""
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r1, r2 = _rows_of(c, keys)
    rows1, rows2 = state.table[r1], state.table[r2]
    eq1, l1 = match_rows(rows1, keys, s)
    eq2, l2 = match_rows(rows2, keys, s)
    in1 = l1 >= 0
    hit = in1 | (l2 >= 0)
    row = jnp.where(in1, r1, r2)
    lane = jnp.where(in1, l1, l2)
    rows = jnp.where(in1[:, None], rows1, rows2)
    eq = jnp.where(in1[:, None], eq1, eq2)
    return row, lane, hit, rows, eq


@jax.jit
def get_batch(state: CCPState, keys: jnp.ndarray) -> GetResult:
    s = state.table.shape[1] // 4
    row, lane, found, rows, eq = _match2(state, keys)
    values = jnp.stack(
        [lane_pick(rows, eq, 2 * s, s), lane_pick(rows, eq, 3 * s, s)],
        axis=-1,
    )
    gslot = jnp.where(found, row * s + jnp.maximum(lane, 0), jnp.int32(-1))
    return GetResult(values=values, found=found, slots=gslot)


@jax.jit
def get_values(state: CCPState, keys: jnp.ndarray):
    """Lean GET over both clusters; a key occupies exactly one lane across
    the two (update-in-place precedes rehoming), so masked sums add."""
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r1, r2 = _rows_of(c, keys)
    return lean_two_window(state.table, r1, r2, keys, s)


@jax.jit
def insert_batch(state: CCPState, keys: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    b = keys.shape[0]
    valid = ~is_invalid(keys)
    winner = dedupe_last_wins(keys, valid)
    r1, _ = _rows_of(c, keys)

    # update in place (either cluster)
    mk = jnp.where(winner[:, None], keys, jnp.uint32(INVALID_WORD))
    u_row, u_lane_raw, u_hit, _, _ = _match2(state, mk)
    upd = winner & u_hit
    u_lane = jnp.maximum(u_lane_raw, 0)
    table = state.table
    r_u = jnp.where(upd, u_row, jnp.int32(c))
    table = table.at[r_u, 2 * s + u_lane].set(values[:, 0], mode="drop")
    table = table.at[r_u, 3 * s + u_lane].set(values[:, 1], mode="drop")

    # fresh: FIFO lane in cluster 1 (exactly linear's scheme)
    new = winner & ~upd
    rank = batch_rank_by_segment(r1.astype(jnp.uint32), new)
    drop = new & (rank >= s)
    ins = new & ~drop
    rows1 = table[r1]
    pos = (
        (state.head[jnp.maximum(r1, 0)] + rank.astype(jnp.uint32))
        & jnp.uint32(s - 1)
    ).astype(jnp.int32)
    pos_hot = (
        jnp.arange(s, dtype=jnp.int32)[None, :] == pos[:, None]
    ) & ins[:, None]
    vk, vv = pick_kv(rows1, pos_hot, s)
    victim_mask = ins & ~is_invalid(vk)
    # victim tag: was it already living its second life?
    vbit = ((state.cuckooed[r1] >> pos.astype(jnp.uint32)) & 1).astype(bool)
    victim_tagged = victim_mask & vbit

    table = scatter_entry(table, r1, pos, keys, values, s, ins)
    head2 = state.head.at[jnp.where(ins, r1, jnp.int32(c))].add(
        jnp.uint32(1), mode="drop"
    )
    # fresh cluster-1 entries are untagged: accumulate the bits to clear
    # (scatter-add == scatter-or here — lanes are unique per row within the
    # batch) and mask them off in one vector op.
    clear_acc = jnp.zeros((c,), jnp.uint32).at[
        jnp.where(ins, r1, jnp.int32(c))
    ].add(jnp.uint32(1) << pos.astype(jnp.uint32), mode="drop")
    cuckooed = state.cuckooed & ~clear_acc

    # second chance: relocate untagged victims to THEIR second cluster.
    # The relocation — a re-gather of the victims' second clusters, a
    # full-batch segment-rank sort, the placement scatters and the tag
    # bits — only matters when some displaced victim is untagged; a
    # fill-phase batch whose FIFO lanes were free (no victims at all)
    # pays one predicate instead (same skip discipline as the other
    # families' guarded eviction blocks).
    reloc = victim_mask & ~victim_tagged

    def do_reloc(op):
        tb, ck = op
        _, vr2 = _rows_of(c, jnp.where(reloc[:, None], vk, jnp.uint32(0)))
        rows_v = tb[vr2]  # re-gathered: sees this batch's placements
        vrank = batch_rank_by_segment(vr2.astype(jnp.uint32), reloc)
        freev = free_lanes(rows_v, s)
        vcan_ = reloc & (vrank < freev.sum(axis=1))
        vhot = nth_lane(freev, vrank)
        vlane = jnp.argmax(vhot, axis=1).astype(jnp.int32)
        tb = scatter_entry(tb, vr2, vlane, vk, vv, s, vcan_)
        set_acc = jnp.zeros((c,), jnp.uint32).at[
            jnp.where(vcan_, vr2, jnp.int32(c))
        ].add(jnp.uint32(1) << vlane.astype(jnp.uint32), mode="drop")
        return tb, ck | set_acc, vcan_

    table, cuckooed, vcan = jax.lax.cond(
        reloc.any(), do_reloc,
        lambda op: (op[0], op[1], jnp.zeros((b,), bool)),
        (table, cuckooed),
    )

    # true evictions: tagged victims + victims whose 2nd cluster is full
    ev = victim_tagged | (reloc & ~vcan)
    evicted = jnp.where(ev[:, None], vk, jnp.uint32(INVALID_WORD))
    evicted_vals = jnp.where(ev[:, None], vv, jnp.uint32(INVALID_WORD))

    slots = jnp.where(
        upd, u_row * s + u_lane,
        jnp.where(ins, r1 * s + pos, jnp.int32(-1)),
    )
    res = InsertResult(
        slots=slots, evicted=evicted, dropped=drop, fresh=ins,
        evicted_vals=evicted_vals,
    )
    return CCPState(table=table, head=head2, cuckooed=cuckooed), res


@jax.jit
def delete_batch(state: CCPState, keys: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    row, lane_raw, hit, rows, eq = _match2(state, keys)
    lane = jnp.maximum(lane_raw, 0)
    _, old_vals = pick_kv(rows, eq, s)
    old_vals = jnp.where(hit[:, None], old_vals, jnp.uint32(INVALID_WORD))
    r_d = jnp.where(hit, row, jnp.int32(c))
    inv = jnp.full((keys.shape[0],), INVALID_WORD, jnp.uint32)
    table = state.table.at[r_d, lane].set(inv, mode="drop")
    table = table.at[r_d, s + lane].set(inv, mode="drop")
    # dedupe so a repeated key clears its tag bit once, not additively
    once = hit & dedupe_last_wins(keys, hit)
    clear_acc = jnp.zeros((c,), jnp.uint32).at[
        jnp.where(once, row, jnp.int32(c))
    ].add(jnp.uint32(1) << lane.astype(jnp.uint32), mode="drop")
    cuckooed = state.cuckooed & ~clear_acc
    return CCPState(table=table, head=state.head, cuckooed=cuckooed), hit, \
        old_vals


@jax.jit
def set_values(state: CCPState, slots: jnp.ndarray, values: jnp.ndarray):
    c = state.table.shape[0]
    s = state.table.shape[1] // 4
    r = jnp.where(slots >= 0, slots // s, jnp.int32(c))
    lane = jnp.maximum(slots, 0) % s
    table = state.table.at[r, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[r, 3 * s + lane].set(values[:, 1], mode="drop")
    return dataclasses.replace(state, table=table)


def scan(state: CCPState):
    s = state.table.shape[1] // 4
    t = state.table
    keys = jnp.stack(
        [t[:, 0:s].reshape(-1), t[:, s : 2 * s].reshape(-1)], axis=-1
    )
    vals = jnp.stack(
        [t[:, 2 * s : 3 * s].reshape(-1), t[:, 3 * s : 4 * s].reshape(-1)],
        axis=-1,
    )
    return keys, vals


register_index(
    IndexKind.CUCKOO_PROBING,
    IndexOps(
        init=init,
        get_batch=get_batch,
        insert_batch=insert_batch,
        delete_batch=delete_batch,
        num_slots=num_slots,
        scan=scan,
        set_values=set_values,
        get_values=get_values,
        rows_per_get=2,  # home + second-chance window
    ),
)
