"""Shared fused-row primitives for all hash-index families.

A "row" is one probe window stored as `uint32[4*S]`: four S-lane groups
`[khi | klo | vhi | vlo]` (S = 32 by default, so a row is exactly one 128-lane
TPU vreg row). Every index gathers rows with a single `table[row_ids]` and then
works purely on VPU lanes — this layout measured ~40× faster than the naive
`[C, S, 2]` struct-of-pairs form, whose 2-wide minor axis tile-pads 64×.

Reference probe geometry being mirrored: 4 pairs/cacheline × 8 cachelines =
32-slot window (`server/CCEH_hybrid.h:14-19`).
"""

from __future__ import annotations

import jax.numpy as jnp

from pmdfc_tpu.utils.keys import INVALID_WORD, is_invalid


def match_mask(rows: jnp.ndarray, keys: jnp.ndarray, s: int) -> jnp.ndarray:
    """eq[B, S]: key-equality one-hot with INVALID queries masked off —
    the single definition of "this lane holds this key"."""
    eq = (rows[:, 0:s] == keys[:, None, 0]) & (
        rows[:, s : 2 * s] == keys[:, None, 1]
    )
    return eq & ~is_invalid(keys)[:, None]


def match_rows(rows: jnp.ndarray, keys: jnp.ndarray, s: int):
    """rows[B, 4S] vs keys[B, 2] -> (eq[B, S] one-hot, slot[B] or -1)."""
    eq = match_mask(rows, keys, s)
    slot = jnp.argmax(eq, axis=1).astype(jnp.int32)
    return eq, jnp.where(eq.any(axis=1), slot, jnp.int32(-1))


def lane_pick(rows: jnp.ndarray, onehot: jnp.ndarray, lo: int, s: int):
    """Masked-sum extraction of ONE lane per row (≤1 hot lane per row)."""
    grp = rows[:, lo : lo + s]
    return jnp.where(onehot, grp, jnp.uint32(0)).sum(axis=1, dtype=jnp.uint32)


def pick_kv(rows: jnp.ndarray, onehot: jnp.ndarray, s: int):
    """(keys[B, 2], vals[B, 2]) at the hot lane of each row."""
    k = jnp.stack(
        [lane_pick(rows, onehot, 0, s), lane_pick(rows, onehot, s, s)], axis=-1
    )
    v = jnp.stack(
        [lane_pick(rows, onehot, 2 * s, s), lane_pick(rows, onehot, 3 * s, s)],
        axis=-1,
    )
    return k, v


def free_lanes(rows: jnp.ndarray, s: int) -> jnp.ndarray:
    """bool[B, S]: lanes whose key is INVALID (empty slots)."""
    return (rows[:, 0:s] == jnp.uint32(0xFFFFFFFF)) & (
        rows[:, s : 2 * s] == jnp.uint32(0xFFFFFFFF)
    )


def nth_lane(mask: jnp.ndarray, rank: jnp.ndarray) -> jnp.ndarray:
    """One-hot[B, S] of the rank-th True lane per row (all-False if rank
    exceeds the population count)."""
    pos = jnp.cumsum(mask, axis=1) - 1
    return mask & (pos == rank[:, None])


def place_free_phase(table: jnp.ndarray, prot: jnp.ndarray, r: jnp.ndarray,
                     keys: jnp.ndarray, vals: jnp.ndarray,
                     active: jnp.ndarray, s: int,
                     rank: jnp.ndarray | None = None):
    """Place active keys into free lanes of row r, rank-deconflicted.

    `prot` is a per-row uint32 lane bitmask of same-batch placements (kept so
    later displacement phases never touch them). Returns
    (table, prot, placed[B], slot[B] or -1). Callers sequence phases and
    re-gather between them, so cross-phase conflicts resolve by occupancy.

    `rank` lets callers that already built an insert sort plan
    (`base.plan_insert`) pass per-row ranks of `active` instead of paying
    this helper's own sort (sorts are the second-largest insert cost after
    scatters on the target chip).
    """
    c = table.shape[0]
    rows = table[r]
    if rank is None:
        from pmdfc_tpu.models.base import batch_rank_by_segment

        rank = batch_rank_by_segment(r.astype(jnp.uint32), active)
    free = free_lanes(rows, s)
    can = active & (rank < free.sum(axis=1))
    hot = nth_lane(free, rank)
    lane = jnp.argmax(hot, axis=1).astype(jnp.int32)
    table = scatter_entry(table, r, lane, keys, vals, s, can)
    bit = jnp.uint32(1) << lane.astype(jnp.uint32)
    prot = prot.at[jnp.where(can, r, jnp.int32(c))].add(bit, mode="drop")
    return table, prot, can, jnp.where(can, r * s + lane, jnp.int32(-1))


def scatter_entry(table: jnp.ndarray, rows: jnp.ndarray, lanes: jnp.ndarray,
                  keys: jnp.ndarray, values: jnp.ndarray, s: int,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """Write (key, value) at (row, lane) where mask; masked-off rows drop.

    (row, lane) pairs must be unique among masked elements.
    """
    n = table.shape[0]
    r = jnp.where(mask, rows, jnp.int32(n))
    lane = jnp.maximum(lanes, 0)
    table = table.at[r, lane].set(keys[:, 0], mode="drop")
    table = table.at[r, s + lane].set(keys[:, 1], mode="drop")
    table = table.at[r, 2 * s + lane].set(values[:, 0], mode="drop")
    table = table.at[r, 3 * s + lane].set(values[:, 1], mode="drop")
    return table


def no_evict_stub(b: int):
    """False branch for the guarded-eviction lax.cond shared by the
    families that skip eviction work on non-overflowing batches (hotring
    overflow, level bottom-tier displacement): table unchanged, no
    evicted pair, no placements. Kept HERE so the cond's output pytree
    has one definition — the true branches differ per policy, the no-op
    must not drift."""

    def stub(tb):
        inv2 = jnp.full((b, 2), INVALID_WORD, jnp.uint32)
        return (tb, inv2, inv2, jnp.zeros((b,), bool),
                jnp.zeros((b,), jnp.int32))

    return stub


def lean_miss_tail(keys: jnp.ndarray, missed: jnp.ndarray,
                   base_values: jnp.ndarray, base_found: jnp.ndarray,
                   probe, width: int | None = None):
    """Shared lean-GET miss tail: probe ONLY the `missed` lanes at a
    compacted narrow width, falling back to a full-width probe under
    `lax.cond` when the miss set overflows the buffer (absent-key
    storms stay exact). One definition for level's bottom tier and
    path's bank 1 — the compaction/scatter-back/fallback machinery must
    not drift per family (code-review r5).

    `probe(ks) -> (values[B', 2], found[B'])` must treat INVALID keys as
    guaranteed misses (every match helper here does). Returns the merged
    `(values[B, 2], found[B])`.
    """
    import jax

    b = keys.shape[0]
    W = width if width is not None else min(b, max(1024, b // 8))

    def full(_):
        v, f = probe(keys)
        m = missed & f
        return jnp.where(m[:, None], v, base_values), base_found | m

    if W >= b:
        return full(None)

    def narrow(_):
        from pmdfc_tpu.models.base import compact_mask

        idx, in_w, safe, _over = compact_mask(missed, W)
        ks = jnp.where(in_w[:, None], keys[safe], jnp.uint32(INVALID_WORD))
        v, f = probe(ks)
        pos = jnp.where(f, idx, jnp.int32(b))
        fb = jnp.zeros((b,), bool).at[pos].set(True, mode="drop")
        out = jnp.zeros((b, 2), jnp.uint32).at[pos].set(v, mode="drop")
        return jnp.where(fb[:, None], out, base_values), base_found | fb

    ms = missed.sum()  # one reduction feeds both branch decisions

    def tail(_):
        return jax.lax.cond(ms > W, full, narrow, None)

    # zero-miss batches (every key resolved in the primary windows — the
    # fill-phase GET common case) pay one predicate, not a padded narrow
    # probe over W INVALID keys
    return jax.lax.cond(
        ms > 0, tail, lambda _: (base_values, base_found), None
    )


def lean_two_window(table: jnp.ndarray, r1: jnp.ndarray, r2: jnp.ndarray,
                    keys: jnp.ndarray, s: int):
    """Lean GET over two hashed windows: (values[B,2] zero-on-miss,
    found[B]). Requires the one-location invariant (a key occupies exactly
    one lane across both windows). The two hashes can collide (r1 == r2):
    the windows are then the SAME row and a raw sum would double the
    value — window 2 is masked out in that case."""
    rows1, rows2 = table[r1], table[r2]
    eq1 = match_mask(rows1, keys, s)
    eq2 = match_mask(rows2, keys, s) & (r1 != r2)[:, None]
    values = jnp.stack(
        [
            lane_pick(rows1, eq1, 2 * s, s) + lane_pick(rows2, eq2, 2 * s, s),
            lane_pick(rows1, eq1, 3 * s, s) + lane_pick(rows2, eq2, 3 * s, s),
        ],
        axis=-1,
    )
    return values, eq1.any(axis=1) | eq2.any(axis=1)
